"""Runnable demo: a secure conference bridge on one TPU chip.

Three participants connect over loopback UDP, each with its own SDES-
keyed SRTP session. Every 20 ms tick the bridge:

  1. drains the socket into a PacketBatch (recvmmsg path),
  2. runs the batched SRTP reverse transform on device,
  3. decodes G.711 and deposits PCM into the conference mixer,
  4. mixes everyone (mix-minus + RFC 6465 levels, one device launch),
  5. re-encodes and SRTP-protects each participant's personalized mix,
  6. sends it back over UDP.

Run:  PYTHONPATH=. python examples/conference_bridge.py
(first JAX compile takes ~20-40 s; the demo then runs 50 ticks and
prints per-participant stats.)
"""

import os
import time

import jax
import numpy as np

# Demo platform policy: default to the CPU backend (tests/conftest.py's
# recipe — config-update BEFORE any backend init; env vars alone are
# clobbered where sitecustomize pins an accelerator plugin).  A tunneled
# accelerator "works" here but compiles the demo over the wire; set
# LIBJITSI_TPU_DEMO_DEVICE=accel to opt in to the real device.
if os.environ.get("LIBJITSI_TPU_DEMO_DEVICE", "cpu") != "accel":
    jax.config.update("jax_platforms", "cpu")
else:
    try:
        jax.devices()
    except RuntimeError:    # accelerator plugin unavailable after all
        jax.config.update("jax_platforms", "cpu")

import libjitsi_tpu
from libjitsi_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()      # re-runs start warm

from libjitsi_tpu.conference import AudioMixer
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.device import ToneSource
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.service.pump import g711_codec
from libjitsi_tpu.transform.srtp import SrtpStreamTable

N, FRAME = 3, 160              # participants; 20 ms @ 8 kHz (G.711)
TICKS = 50


def main():
    libjitsi_tpu.init()
    codec = g711_codec(ulaw=True)

    # --- bridge side: one rx/tx SRTP row + mixer row per participant
    rx = SrtpStreamTable(capacity=N)
    tx = SrtpStreamTable(capacity=N)
    mixer = AudioMixer(capacity=N, frame_samples=FRAME)
    bridge = UdpEngine(port=0, max_batch=64)
    keys = [(bytes([i + 1] * 16), bytes([i + 101] * 14)) for i in range(N)]
    for sid, (mk, ms) in enumerate(keys):
        rx.add_stream(sid, mk, ms)
        tx.add_stream(sid, mk, ms)
        mixer.add_participant(sid)
    ssrc_to_sid = {0xD000 + i: i for i in range(N)}

    # --- participant side: a tone source + its own SRTP view
    class Peer:
        def __init__(self, sid):
            self.sid = sid
            self.sock = UdpEngine(port=0, max_batch=16)
            self.tone = ToneSource(300.0 + 200 * sid, sample_rate=8000)
            self.tab = SrtpStreamTable(capacity=1)
            self.tab.add_stream(0, *keys[sid])
            self.seq = 100
            self.heard = 0

        def send_frame(self):
            payload = codec.encode(self.tone.read(FRAME))
            batch = rtp_header.build(
                [payload], [self.seq], [self.seq * FRAME],
                [0xD000 + self.sid], [0], stream=[0])
            self.seq += 1
            self.sock.send_batch(self.tab.protect_rtp(batch),
                                 "127.0.0.1", bridge.port)

        def drain(self):
            batch, _, _ = self.sock.recv_batch(timeout_ms=1)
            if batch.batch_size:
                # the socket doesn't know stream rows; this peer has one
                sub = PacketBatch(batch.data, np.asarray(batch.length),
                                  np.zeros(batch.batch_size, np.int32))
                dec, ok = self.tab.unprotect_rtp(sub)
                self.heard += int(ok.sum())

    peers = [Peer(i) for i in range(N)]
    addr = {}                   # sid -> (ip, port) learned from traffic

    t0 = time.time()
    for tick in range(TICKS):
        for p in peers:
            p.send_frame()
        # bridge tick: drain -> unprotect -> decode -> mix
        batch, sip, sport = bridge.recv_batch(timeout_ms=5)
        if batch.batch_size:
            hdr = rtp_header.parse(batch)
            sids = np.array([ssrc_to_sid.get(int(s), -1)
                             for s in hdr.ssrc])
            keep = sids >= 0
            sub = PacketBatch(batch.data[keep],
                              np.asarray(batch.length)[keep], sids[keep])
            dec, ok = rx.unprotect_rtp(sub)
            hdr2 = rtp_header.parse(dec)
            for j in np.nonzero(ok)[0]:
                sid = int(dec.stream[j])
                addr[sid] = (int(sip[keep][j]), int(sport[keep][j]))
                payload = dec.to_bytes(int(j))[int(hdr2.payload_off[j]):]
                mixer.push(sid, codec.decode(payload))
        out, levels = mixer.mix()
        # personalized mixes: ONE batched protect for all participants
        # (per-row key gather), then per-destination send
        if addr:
            sids = sorted(addr)
            b = rtp_header.build(
                [codec.encode(out[s]) for s in sids],
                [tick] * len(sids), [tick * FRAME] * len(sids),
                [0xB00] * len(sids), [0] * len(sids), stream=sids)
            wire = tx.protect_rtp(b)
            for j, s in enumerate(sids):
                ip, port = addr[s]
                one = PacketBatch(wire.data[j:j + 1],
                                  np.asarray(wire.length)[j:j + 1],
                                  wire.stream[j:j + 1])
                bridge.send_batch(one, ip, port)
        for p in peers:
            p.drain()
        time.sleep(0.002)

    dt = time.time() - t0
    print(f"{TICKS} ticks in {dt:.2f}s "
          f"({TICKS * N} frames mixed, levels now {levels.tolist()})")
    for p in peers:
        print(f"  participant {p.sid}: sent {TICKS}, "
              f"heard {p.heard} personalized mix frames")
    assert all(p.heard > TICKS // 2 for p in peers), "media did not flow"
    print("OK: every participant heard their mix-minus over SRTP/UDP")


if __name__ == "__main__":
    main()
