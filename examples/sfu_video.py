"""Runnable demo: a secure video SFU with simulcast on one chip.

One sender publishes a 3-layer VP8 simulcast track (real libvpx
encoders at 160x96 / 320x192 / 640x384); two receivers join with their
own SRTP leg keys.  Each tick the bridge:

  1. drains loopback UDP, demuxes the layer SSRCs to their rows,
  2. runs one batched SRTP unprotect for every layer's packets,
  3. projects ONE layer per receiver through its SimulcastForwarder
     (SSRC/seq/ts/picture-id rewritten into a single coherent stream),
  4. re-protects all receivers' projections in one launch and sends.

Receiver B advertises a small REMB, receiver A a large one — so A is
upswitched to the top layer on its next keyframe while B stays on the
base layer.  (The NACK->RTX path is exercised by the slow-tier e2e in
tests/test_sfu_bridge.py.)

Run:  PYTHONPATH=. python examples/sfu_video.py
(first JAX compile takes ~20-40 s; the demo runs ~30 ticks and prints
the per-receiver layer/forwarding stats.)
"""

import os

import jax
import numpy as np

if os.environ.get("LIBJITSI_TPU_DEMO_DEVICE", "cpu") != "accel":
    jax.config.update("jax_platforms", "cpu")
else:
    try:
        jax.devices()
    except RuntimeError:
        jax.config.update("jax_platforms", "cpu")

import libjitsi_tpu
from libjitsi_tpu.codecs import vp8
from libjitsi_tpu.codecs.vpx import VpxEncoder, vpx_available
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.service.sfu_bridge import SfuBridge
from libjitsi_tpu.transform.srtp import SrtpStreamTable
from libjitsi_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

LAYER_SSRCS = [0xB00 + k for k in range(3)]
DIMS = [(160, 96), (320, 192), (640, 384)]


def main() -> None:
    if not vpx_available():
        raise SystemExit("libvpx not present; this demo needs it")
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=32, recv_window_ms=0)
    print(f"SFU listening on 127.0.0.1:{sfu.port}")

    def keys(seed):
        return (bytes([seed]) * 16, bytes([seed + 1]) * 14)

    # sender + two receivers, SDES-style static leg keys
    send_rx, send_tx = keys(0x10), keys(0x20)
    sid_s = sfu.add_endpoint(0xA0, send_rx, send_tx)
    recvs = {}
    for name, ssrc, seed in (("A", 0xA1, 0x30), ("B", 0xA2, 0x40)):
        rx, tx = keys(seed), keys(seed + 0x10)
        sid = sfu.add_endpoint(ssrc, rx, tx)
        eng = UdpEngine(port=0, max_batch=64)
        # latch the receiver's address with one (any) packet
        hello = rtp_header.build([b"hello"], [1], [0], [ssrc], [96],
                                 stream=[0])
        prot = SrtpStreamTable(capacity=1)
        prot.add_stream(0, *rx)
        eng.send_batch(prot.protect_rtp(hello), "127.0.0.1", sfu.port)
        open_tab = SrtpStreamTable(capacity=1)
        open_tab.add_stream(0, *tx)          # projected video stream
        recvs[name] = dict(sid=sid, ssrc=ssrc, eng=eng, prot=prot,
                           open=open_tab, got=0, frames=0)

    track = sfu.add_video_track(sid_s, LAYER_SSRCS,
                                layer_bps=[100e3, 500e3, 2e6])

    # sender: one SRTP row + encoder per layer
    tx_tab = SrtpStreamTable(capacity=4)
    for k in range(3):
        tx_tab.add_stream(k, *send_rx)
    encs = [VpxEncoder(w, h) for w, h in DIMS]
    send_eng = UdpEngine(port=0, max_batch=64)
    seqs, pids = [100, 200, 300], [1, 2, 3]

    def planes(k, t):
        w, h = DIMS[k]
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
        y = (128 + 60 * np.sin(xx / 17 + t * 0.7)
             + 40 * np.cos(yy / 11 + t)).clip(0, 255).astype(np.uint8)
        c = np.full(((h + 1) // 2, (w + 1) // 2), 128, np.uint8)
        return y, c, c

    def send_frame(t):
        for k in range(3):
            for data, _key in encs[k].encode(*planes(k, t)):
                pls = vp8.packetize(data, picture_id=pids[k],
                                    max_payload=1100)
                pids[k] = (pids[k] + 1) & 0x7FFF
                n = len(pls)
                b = rtp_header.build(
                    pls, [(seqs[k] + i) & 0xFFFF for i in range(n)],
                    [t * 3000] * n, [LAYER_SSRCS[k]] * n, [96] * n,
                    marker=[0] * (n - 1) + [1], stream=[k] * n)
                seqs[k] = (seqs[k] + n) & 0xFFFF
                send_eng.send_batch(tx_tab.protect_rtp(b), "127.0.0.1",
                                    sfu.port)

    def send_remb(r, bps):
        blob = rtcp.build_compound([rtcp.build_remb(
            rtcp.Remb(r["ssrc"], int(bps), [0xA0]))])
        b = PacketBatch.from_payloads([blob], stream=[0])
        r["eng"].send_batch(r["prot"].protect_rtcp(b), "127.0.0.1",
                            sfu.port)

    fbs = {"A": 3_000_000, "B": 150_000}     # A rich, B starved
    fa = {n: vp8.FrameAssembler() for n in recvs}
    fb_tab = SrtpStreamTable(capacity=1)     # bridge SRTCP toward the
    fb_tab.add_stream(0, *send_tx)           # sender (PLI drain)
    now = 10.0
    for t in range(30):
        send_frame(t)
        for name, r in recvs.items():
            send_remb(r, fbs[name])
        for _ in range(10):
            sfu.tick(now=now)
        sfu.emit_feedback(now=now)
        # the sender answers PLIs with a keyframe (fresh encoder)
        back, _, _ = send_eng.recv_batch(timeout_ms=2)
        if back.batch_size:
            back.stream[:] = 0
            dec, ok = fb_tab.unprotect_rtcp(back)
            for i in np.nonzero(np.asarray(ok))[0]:
                try:
                    pkts = rtcp.parse_compound(dec.to_bytes(int(i)))
                except ValueError:
                    continue
                for p in pkts:
                    if isinstance(p, rtcp.Pli) and \
                            p.media_ssrc in LAYER_SSRCS:
                        k = LAYER_SSRCS.index(p.media_ssrc)
                        encs[k].close()
                        encs[k] = VpxEncoder(*DIMS[k])
        for name, r in recvs.items():
            back, _, _ = r["eng"].recv_batch(timeout_ms=2)
            if not back.batch_size:
                continue
            hdr0 = rtp_header.parse(back)
            keep = np.nonzero(hdr0.ssrc == 0xA0)[0]
            if len(keep) == 0:
                continue
            sub = PacketBatch(back.data[keep],
                              np.asarray(back.length)[keep],
                              np.zeros(len(keep), np.int64))
            dec, ok = r["open"].unprotect_rtp(sub)
            rows = np.nonzero(ok)[0]
            r["got"] += len(rows)
            if len(rows):
                fa[name].push_batch(PacketBatch(
                    dec.data[rows], np.asarray(dec.length)[rows],
                    dec.stream[rows]))
        now += 0.1                            # see PLI limiter note

    for name, r in recvs.items():
        fwd = track.fwd[r["sid"]]
        frames = fa[name].pop_frames()
        print(f"receiver {name}: layer={fwd.current_layer} "
              f"switches={fwd.switches} packets={r['got']} "
              f"frames={len(frames)} (REMB {fbs[name]/1e3:.0f} kbps)")
    a, b = track.fwd[recvs["A"]["sid"]], track.fwd[recvs["B"]["sid"]]
    assert a.current_layer > b.current_layer, "A should outrank B"
    print("demo ok: REMB-driven per-receiver simulcast projection")
    sfu.close()
    send_eng.close()
    for r in recvs.values():
        r["eng"].close()


if __name__ == "__main__":
    main()
