"""Demo: the ASSEMBLED ConferenceBridge running sharded over a mesh.

Three SRTP participants join a mesh-mode bridge whose SRTP tables are
row-partitioned over an 8-device mesh and whose mix-minus psums over
the participant axis — the whole tick runs sharded, byte-identical to
the single-chip bridge (the parity harness proves it here, live).

Run:  PYTHONPATH=. python examples/mesh_bridge.py
(uses a virtual 8-device CPU mesh; on a real v5e-8 the same code runs
over ICI unchanged)
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import libjitsi_tpu  # noqa: E402
from libjitsi_tpu.mesh import make_media_mesh  # noqa: E402
from libjitsi_tpu.mesh.parity import (assert_bridge_parity,  # noqa: E402
                                      run_bridge_once)


def main() -> None:
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    mesh = make_media_mesh()
    print(f"mesh: {mesh.devices.size} devices, axes {mesh.axis_names}")

    wire = run_bridge_once(cfg, mesh, capacity=16)
    print(f"mesh bridge forwarded {len(wire)} SRTP mix packets over "
          f"loopback UDP")

    assert_bridge_parity(cfg, mesh, capacity=16)
    print("parity: mesh-mode egress byte-identical to single-chip")
    print("demo ok: assembled conference tick sharded over the mesh")


if __name__ == "__main__":
    main()
