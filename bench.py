"""Headline benchmark: SRTP protect throughput at 10k streams on one chip.

Mirrors BASELINE.json's metric ("SRTP packets/sec/chip @ 10k streams") and
config #1's CPU reference: the vs_baseline denominator is a single-thread
OpenSSL SRTP protect (AES-128-CTR + HMAC-SHA1-80 via the `cryptography`
package — the same libcrypto the reference's fastest JNI provider binds).

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


from libjitsi_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

N_STREAMS = 10_240
# Launch size: throughput scales with batch because the round trip is
# dispatch-dominated, not compute-bound (recorded runs: 2048 -> 39M,
# 16384 -> 345M, 65536 -> ~1.1B pps pipelined ~= 0.26 TB/s of packet
# payload, ~2x that in HBM read+write traffic) while sync p99 latency
# stays flat (~0.2-0.3 ms across 2048..65536), so the big launch still
# meets the 2 ms p99 budget with >8x headroom — p99 is measured at THIS
# batch size.  131072+ was rejected: compile time blows up.
BATCH = 65536
# GCM also scales with launch (observed 62-92M pps @4096 -> 140-270M
# @16384 across tunnel conditions; matches BASELINE.md) but each row
# carries a 16 KiB GHASH matrix, so 16384 rows = 268 MB of tables —
# a deliberate HBM/throughput trade, not pushed to the CM batch size.
GCM_BATCH = 16384
WIDTH = 192          # capacity; 20 ms Opus packet ≈ 12B header + 160B payload
PKT_LEN = 172
TAG_LEN = 10
ITERS = 20


def tpu_pps() -> tuple[float, float, float, dict]:
    import jax
    import jax.numpy as jnp

    from libjitsi_tpu.transform.srtp import kernel

    rng = np.random.default_rng(3)
    tab_rk = rng.integers(0, 256, (N_STREAMS, 11, 16), dtype=np.uint8)
    tab_mid = rng.integers(0, 2**32, (N_STREAMS, 2, 5), dtype=np.uint64
                           ).astype(np.uint32)
    stream = rng.integers(0, N_STREAMS, BATCH).astype(np.int32)
    data = rng.integers(0, 256, (BATCH, WIDTH), dtype=np.uint8)
    length = np.full(BATCH, PKT_LEN, dtype=np.int32)
    payload_off = np.full(BATCH, 12, dtype=np.int32)
    iv = rng.integers(0, 256, (BATCH, 16), dtype=np.uint8)
    roc = np.zeros(BATCH, dtype=np.uint32)

    import functools

    @functools.partial(jax.jit, donate_argnums=())
    def step(tab_rk, tab_mid, stream, data, length, payload_off, iv, roc):
        return kernel.srtp_protect(
            data, length, payload_off, tab_rk[stream], iv, tab_mid[stream],
            roc, TAG_LEN, True, payload_off_const=12)

    args = [jnp.asarray(a) for a in
            (tab_rk, tab_mid, stream, data, length, payload_off, iv, roc)]
    out = step(*args)
    jax.block_until_ready(out)          # compile
    # The remote-TPU tunnel injects multi-x transport stalls (observed:
    # a single 47 ms RPC stall in an otherwise 0.1 ms/iter pass) that are
    # not chip throughput.  Three estimators, all reported:
    #   sync best pass   — classic wall-clock over 20 blocking iters;
    #   min-latency      — BATCH / fastest single iteration (one clean
    #                      round trip; still *includes* one tunnel RTT,
    #                      so it underestimates the chip);
    #   pipelined        — enqueue 50 independent steps, block once at
    #                      the end: async dispatch overlaps transport
    #                      with execution the way a real deployment runs.
    # The headline value is the pipelined estimator (the one sustained
    # measurement; the others are printed for methodology); p99 is
    # reported for the best sync pass (chip tail) and pooled over every
    # sample (stalls included) so the filtering is visible, not hidden.
    best_sync, best_p99 = 0.0, float("inf")
    min_lat = float("inf")
    all_lat = []
    for _ in range(5):
        lat = []
        t0 = time.perf_counter()
        for _ in range(ITERS):
            t1 = time.perf_counter()
            out = step(*args)
            jax.block_until_ready(out)
            lat.append(time.perf_counter() - t1)
        dt = time.perf_counter() - t0
        all_lat.extend(lat)
        min_lat = min(min_lat, min(lat))
        pps = BATCH * ITERS / dt
        p99_ms = float(np.percentile(np.asarray(lat), 99) * 1e3)
        if pps > best_sync:
            best_sync, best_p99 = pps, p99_ms
    best_pipelined = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(50):
            out = step(*args)
        jax.block_until_ready(out)
        best_pipelined = max(best_pipelined,
                             BATCH * 50 / (time.perf_counter() - t0))
    pooled_p99 = float(np.percentile(np.asarray(all_lat), 99) * 1e3)
    estimators = {"sync_best_pass": best_sync,
                  "min_latency": BATCH / min_lat,
                  "pipelined": best_pipelined}
    # Headline the pipelined estimator: it is a genuinely sustained
    # measurement (50 launches in flight), where min_latency extrapolates
    # one best-case round trip and sync pays a full drain per launch.
    return estimators["pipelined"], best_p99, pooled_p99, estimators


def cpu_pps() -> float:
    """Single-thread OpenSSL SRTP protect (keystream XOR + HMAC-SHA1-80)."""
    import hmac as pyhmac
    import hashlib

    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    rng = np.random.default_rng(4)
    n = 2000
    pkts = [rng.integers(0, 256, PKT_LEN, dtype=np.uint8).tobytes()
            for _ in range(n)]
    keys = [rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
            for _ in range(64)]
    akeys = [rng.integers(0, 256, 20, dtype=np.uint8).tobytes()
             for _ in range(64)]
    iv = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
    t0 = time.perf_counter()
    for i, p in enumerate(pkts):
        enc = Cipher(algorithms.AES(keys[i % 64]), modes.CTR(iv)).encryptor()
        ct = p[:12] + enc.update(p[12:]) + enc.finalize()
        tag = pyhmac.new(akeys[i % 64], ct + b"\x00\x00\x00\x00",
                         hashlib.sha1).digest()[:TAG_LEN]
        _ = ct + tag
    return n / (time.perf_counter() - t0)


def _time_fn(fn, args, iters=10):
    """Best per-iteration time across sync passes, single iterations and
    a pipelined pass (see tpu_pps: tunnel stalls are not chip
    throughput)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            t1 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t1)
        best = min(best, (time.perf_counter() - t0) / iters)
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(3 * iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / (3 * iters))
    return best


def gcm_pps() -> float:
    """BASELINE config #2's AEAD_AES_128_GCM leg of the cipher sweep."""
    import jax.numpy as jnp

    from libjitsi_tpu.kernels import gcm as G

    rng = np.random.default_rng(5)
    b = GCM_BATCH
    rks = rng.integers(0, 256, (b, 11, 16), dtype=np.uint8)
    gms = rng.integers(0, 2, (b, 128, 128), dtype=np.int8)
    data = rng.integers(0, 256, (b, WIDTH), dtype=np.uint8)
    length = np.full(b, PKT_LEN, np.int32)
    aad = np.full(b, 12, np.int32)
    iv = rng.integers(0, 256, (b, 12), dtype=np.uint8)
    args = [jnp.asarray(x) for x in (data, length, aad, rks, gms, iv)]
    dt = _time_fn(G.gcm_protect, args)
    return b / dt


def mixer_mix_per_sec(n_participants: int = 256) -> float:
    """BASELINE config #3: N-participant 48 kHz mono 20 ms mix-minus."""
    import jax.numpy as jnp

    from libjitsi_tpu.conference.mixer import _mix_jit

    rng = np.random.default_rng(6)
    pcm = jnp.asarray(rng.integers(-8000, 8000, (n_participants, 960))
                      .astype(np.int16))
    active = jnp.ones(n_participants, dtype=bool)
    dt = _time_fn(_mix_jit, (pcm, active))
    return 1.0 / dt


def bridge_mixes_per_sec(conferences: int = 64,
                         participants: int = 64) -> float:
    """Whole-bridge mixing: C conferences of N participants per launch
    (a single conference launch is dispatch-bound; see MixerBridge)."""
    import jax.numpy as jnp

    from libjitsi_tpu.conference.mixer import _mix_many_jit

    rng = np.random.default_rng(8)
    pcm = jnp.asarray(rng.integers(
        -8000, 8000, (conferences, participants, 960)).astype(np.int16))
    active = jnp.ones((conferences, participants), dtype=bool)
    dt = _time_fn(_mix_many_jit, (pcm, active))
    return conferences / dt


def fanout_rows_per_sec(packets: int = 128, receivers: int = 512) -> float:
    """BASELINE config #5 core: per-receiver re-encrypt of a fan-out
    matrix (rows = packets x receivers) in one launch."""
    import functools

    import jax
    import jax.numpy as jnp

    from libjitsi_tpu.transform.srtp import kernel

    rng = np.random.default_rng(7)
    rows = packets * receivers
    tab_rk = rng.integers(0, 256, (receivers, 11, 16), dtype=np.uint8)
    tab_mid = rng.integers(0, 2**32, (receivers, 2, 5), dtype=np.uint64
                           ).astype(np.uint32)
    recv = np.repeat(np.arange(receivers, dtype=np.int32), packets)
    data = rng.integers(0, 256, (rows, WIDTH), dtype=np.uint8)
    length = np.full(rows, PKT_LEN, np.int32)
    off = np.full(rows, 12, np.int32)
    iv = rng.integers(0, 256, (rows, 16), dtype=np.uint8)
    roc = np.zeros(rows, np.uint32)

    # same math as translator._fanout_protect, without buffer donation
    # (donation would invalidate the timed args between iterations)
    @jax.jit
    def step(tab_rk, tab_mid, recv, data, length, off, iv, roc):
        return kernel.srtp_protect(data, length, off, tab_rk[recv], iv,
                                   tab_mid[recv], roc, TAG_LEN, True,
                                   payload_off_const=12)

    args = [jnp.asarray(x) for x in
            (tab_rk, tab_mid, recv, data, length, off, iv, roc)]
    dt = _time_fn(step, args)
    return rows / dt


def main():
    pps, p99_ms, p99_pooled, estimators = tpu_pps()
    base = cpu_pps()
    print(json.dumps({
        "metric": "srtp_protect_pps_at_10k_streams",
        "value": round(pps, 1),
        "unit": "packets/sec/chip",
        "vs_baseline": round(pps / base, 3),
        "extra": {"batch": BATCH, "pkt_len": PKT_LEN, "p99_batch_ms":
                  round(p99_ms, 3),
                  "p99_ms_pooled_all_passes": round(p99_pooled, 3),
                  "estimators_pps": {k: round(v, 1)
                                     for k, v in estimators.items()},
                  "cpu_openssl_pps": round(base, 1),
                  "gcm_pps": round(gcm_pps(), 1),
                  "mix_256p_per_sec": round(mixer_mix_per_sec(), 1),
                  "bridge_64conf_64p_mixes_per_sec":
                      round(bridge_mixes_per_sec(), 1),
                  "sfu_fanout_rows_per_sec":
                      round(fanout_rows_per_sec(), 1)},
    }))


if __name__ == "__main__":
    main()
