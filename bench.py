"""Headline benchmark: SRTP protect throughput at 10k streams on one chip.

Mirrors BASELINE.json's metric ("SRTP packets/sec/chip @ 10k streams") and
config #1's CPU reference: the vs_baseline denominator is a single-thread
OpenSSL SRTP protect (AES-128-CTR + HMAC-SHA1-80 via the `cryptography`
package — the same libcrypto the reference's fastest JNI provider binds).

Survivability contract (round-3 postmortem: the driver's timeout killed the
whole run and recorded nothing):

- a WALL-CLOCK BUDGET (``LIBJITSI_TPU_BENCH_BUDGET_S``, default 440 s) is
  enforced by per-section time boxes; sections that would not fit are
  skipped and *recorded* as skipped;
- the result dict is built incrementally — the headline section runs
  first, every completed section lands in the dict immediately;
- the one JSON line is emitted from a ``finally`` block, from the SIGTERM
  handler (the driver's ``timeout`` sends TERM first) and from a daemon
  watchdog thread that fires even if the main thread is stuck in a native
  call — whichever comes first, exactly once;
- there are NO fatal asserts: integrity failures (auth miss, lost echo
  packets) are recorded as degradation fields, not raised.

Section order is headline-first (the tunnel link degrades over process
lifetime — see BASELINE.md): device microbenches, then crypto sweeps,
then the tunnel-floored production/loop paths.

Output protocol (round-5 rework — VERDICT r4 #1):
- FULL results: BENCH_DETAIL.json on disk + one big stdout line;
- FINAL stdout line: a COMPACT headline (value, vs_baseline, p99,
  roofline verdict, section tally) sized for the driver's tail window;
- every throughput figure carries an HBM-roofline annotation and is
  CAPPED at the physically possible rate (median-of-passes banking; a
  cross-check against the standalone AES core rate bounds the headline
  too) — see _roofline/_aes_consistency_check.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time

import numpy as np

from libjitsi_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

N_STREAMS = 10_240
# Launch size: 65536 amortizes per-launch dispatch overhead.  The batch
# comments of rounds 2-4 cited 0.5-1.1B pps here; those numbers were
# tunnel-acknowledgment fiction (block_until_ready does not wait on
# this link — round-5 finding, see BASELINE.md).  Fetch-verified
# execution on the real v5e is ~ms-scale per launch and measured
# honestly below.
BATCH = 65536
WIDTH = 192          # capacity; 20 ms Opus packet ≈ 12B header + 160B payload
PKT_LEN = 172
TAG_LEN = 10

BUDGET_S = float(os.environ.get("LIBJITSI_TPU_BENCH_BUDGET_S", "440"))
_T0 = time.monotonic()

# Physics self-check (VERDICT r4 #1/weak-1: the r04 headline exceeded
# the chip's HBM roofline 2.8x — tunnel-acknowledged launches harvested
# by max() banking).  Every pps figure is recorded next to the implied
# HBM traffic, and any estimator above the roofline is CAPPED to it and
# flagged: a number the bench itself marks impossible must not become
# the headline.  ~819 GB/s is TPU v5e; override for other chips.
HBM_GBPS = float(os.environ.get("LIBJITSI_TPU_HBM_GBPS", "819"))


_FLOOR = [None, None]           # [median, jitter (max - min)]


def _checksum(fn):
    """Wrap `fn` into a jitted twin returning ONE uint32 checksum scalar.

    Round-5 finding (BASELINE.md): on this tunnel `block_until_ready`
    does NOT wait for fresh launches — it returns in ~0.1 ms while the
    execution queues remotely, which is how rounds 2-4 recorded
    multi-billion-pps fiction.  Only fetching bytes forces completion;
    reducing the outputs to a scalar keeps that forced transfer at 4
    bytes, so timing `np.asarray(g(*args))` measures dispatch + real
    execution + one scalar round trip (subtract `_fetch_floor()`).
    """
    import jax
    import jax.numpy as jnp

    def _sum_tree(out):
        tot = jnp.uint32(0)
        for leaf in jax.tree_util.tree_leaves(out):
            if hasattr(leaf, "dtype"):
                tot = tot + jnp.sum(leaf.astype(jnp.uint32))
        return tot

    return jax.jit(lambda *a: _sum_tree(fn(*a)))


def _fetch_floor() -> float:
    """Per-iteration cost of the 4-byte verification fetch itself
    (dispatch RTT + scalar transfer), measured once on a trivial
    program and subtracted from every fetch-verified timing."""
    if _FLOOR[0] is None:
        import jax
        import jax.numpy as jnp

        g = jax.jit(lambda x: jnp.sum(x))
        x = jnp.arange(8, dtype=jnp.uint32)
        _ = np.asarray(g(x))
        samples = []
        for _i in range(7):
            t0 = time.perf_counter()
            _ = np.asarray(g(x))
            samples.append(time.perf_counter() - t0)
        arr = np.asarray(samples)
        _FLOOR[0] = float(np.median(arr))
        _FLOOR[1] = float(arr.max() - arr.min())
        EXTRA["scalar_fetch_floor_ms"] = round(_FLOOR[0] * 1e3, 2)
        EXTRA["scalar_fetch_floor_jitter_ms"] = round(_FLOOR[1] * 1e3, 3)
    return _FLOOR[0]


def _floor_jitter() -> float:
    """Spread of the fetch-floor samples — the bar any net measurement
    must clear (r5 verdict Weak #1: a net span inside this jitter is
    noise, not a rate)."""
    _fetch_floor()
    return _FLOOR[1]


def _roofline(key: str, pps: float, bytes_per_item: float,
              traffic: str) -> float:
    """Record `pps` under EXTRA[key] with its implied GB/s and the HBM
    ceiling for this traffic model; return the roofline-capped value.
    `traffic` documents the per-item byte model (auditable in the
    detail record)."""
    ceiling = HBM_GBPS * 1e9 / bytes_per_item
    implied = pps * bytes_per_item / 1e9
    rec = {"pps": round(pps, 1), "implied_gbps": round(implied, 1),
           "bytes_per_item": round(bytes_per_item, 1),
           "ceiling_pps": round(ceiling, 1), "traffic": traffic}
    if pps > ceiling:
        rec["roofline_capped"] = True
    EXTRA.setdefault("roofline", {})[key] = rec
    return min(pps, ceiling)


def _elapsed() -> float:
    return time.monotonic() - _T0


def _remaining() -> float:
    return BUDGET_S - _elapsed()


# ---------------------------------------------------------------- result --

RESULT: dict = {
    "metric": "srtp_protect_pps_at_10k_streams",
    "value": 0.0,
    "unit": "packets/sec/chip",
    "vs_baseline": 0.0,
    "extra": {"batch": BATCH, "pkt_len": PKT_LEN, "budget_s": BUDGET_S,
              "sections": {}},
}
EXTRA = RESULT["extra"]
SECTIONS = EXTRA["sections"]

_emit_lock = threading.Lock()
_emitted = False


def emit() -> None:
    """Emit results exactly once (thread/signal safe).

    Protocol (VERDICT r4 #1: BENCH_r04 had rc=0 and numbers, but the
    full dict overflowed the driver's tail window mid-line, so nothing
    machine-parsed it):
    - the FULL result dict is written to BENCH_DETAIL.json on disk and
      printed as a non-final stdout line (best effort);
    - the LAST stdout line is a COMPACT headline — value, vs_baseline,
      p99, roofline verdict, section tally, detail pointer — small
      enough that any sane tail window holds it whole.

    The emitted flag latches only after a successful serialization: the
    watchdog thread can race the main thread mutating EXTRA/SECTIONS
    (json.dumps then raises "dictionary changed size"), and a latched
    flag with no output would defeat the whole survivability contract —
    so serialization retries, then degrades to the compact line alone.
    """
    global _emitted
    import copy

    with _emit_lock:
        if _emitted:
            return
        base = EXTRA.get("cpu_openssl_pps")
        if base and RESULT["value"]:
            RESULT["vs_baseline"] = round(RESULT["value"] / base, 3)
        EXTRA["elapsed_s"] = round(_elapsed(), 1)
        full = None
        for _ in range(3):
            try:
                full = json.dumps(copy.deepcopy(RESULT))
                break
            except Exception:
                time.sleep(0.05)
        if full is not None:
            try:
                detail_path = os.environ.get(
                    "LIBJITSI_TPU_BENCH_DETAIL") or os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_DETAIL.json")
                with open(detail_path, "w") as f:
                    f.write(full)
            except Exception:
                pass
            print(full, flush=True)     # non-final: tail may clip it
        try:
            # build the compact line from the SERIALIZED snapshot (an
            # immutable copy) — referencing the live EXTRA dicts here
            # would reopen the mutation race the retry loop handles
            ex = json.loads(full)["extra"] if full is not None else {}
            sect = list(ex.get("sections", {}).values())
            ok_n = sum(1 for v in sect if isinstance(v, dict)
                       and v.get("status") == "ok")
            compact = json.dumps({
                "metric": RESULT["metric"], "value": RESULT["value"],
                "unit": RESULT["unit"],
                "vs_baseline": RESULT["vs_baseline"],
                "extra": {
                    "p99_batch_ms": ex.get("p99_batch_ms"),
                    "estimators_pps": ex.get("estimators_pps"),
                    "hbm_gbps_assumed": HBM_GBPS,
                    "headline_roofline": ex.get("roofline", {}).get(
                        "headline", {}),
                    "consistency_vs_aes_core": ex.get(
                        "consistency_vs_aes_core"),
                    "sections_ok": ok_n, "sections_total": len(sect),
                    "elapsed_s": ex.get("elapsed_s"),
                    "detail": ("BENCH_DETAIL.json + penultimate stdout "
                               "line"),
                }})
        except Exception:   # scalar-only degrade: ONE line out, always
            compact = json.dumps({
                "metric": RESULT["metric"],
                "value": float(RESULT["value"]),
                "unit": RESULT["unit"],
                "vs_baseline": float(RESULT["vs_baseline"]),
                "extra": {"degraded": "emit serialization raced"}})
        print(compact, flush=True)   # the FINAL line
        _emitted = True


def _kill_child() -> None:
    """os._exit only kills THIS process: a live section subprocess
    would otherwise keep holding the TPU tunnel as an orphan."""
    p = _CHILD
    if p is not None:
        try:
            p.kill()
        except Exception:
            pass


def _on_term(signum, frame):
    _kill_child()
    SECTIONS["_terminated"] = f"signal {signum} at {_elapsed():.1f}s"
    # Signal handlers run ON the main thread: if the signal lands while
    # this very thread is inside emit() holding the (non-reentrant)
    # lock, a blocking acquire would self-deadlock and nothing would
    # print.  Try-acquire instead — on failure the interrupted emit()
    # completes its own print when the handler returns.
    if not _emit_lock.acquire(blocking=False):
        return
    _emit_lock.release()
    emit()
    os._exit(0)


def _watchdog():
    _kill_child()
    SECTIONS["_terminated"] = f"watchdog at {_elapsed():.1f}s"
    emit()
    os._exit(0)


class SkipSection(Exception):
    """Raised by a section to record a clean skip (e.g. an optional
    dependency is absent) instead of an error entry — str(exc) is the
    reason recorded as ``status: "skipped: <reason>"``."""


def section(name: str, min_cost_s: float, box_s: float, fn):
    """Run one bench section inside a time box.

    Skips (and records the skip) when the remaining budget cannot cover
    ``min_cost_s``; passes the section a hard deadline of
    ``now + min(box_s, remaining)``; converts exceptions into recorded
    degradation entries instead of killing the run.
    """
    if _remaining() < min_cost_s:
        SECTIONS[name] = {"status": "skipped: budget",
                          "at_s": round(_elapsed(), 1)}
        return None
    t0 = time.monotonic()
    deadline = t0 + min(box_s, _remaining())
    # visible in the terminated record if this section never returns
    SECTIONS[name] = {"status": "running", "at_s": round(_elapsed(), 1)}
    try:
        out = fn(deadline)
        SECTIONS[name] = {"status": "ok",
                          "elapsed_s": round(time.monotonic() - t0, 1)}
        return out
    except SkipSection as e:  # clean refusal, not a degradation
        SECTIONS[name] = {"status": f"skipped: {e}",
                          "elapsed_s": round(time.monotonic() - t0, 1)}
        return None
    except Exception as e:  # recorded, never fatal
        SECTIONS[name] = {
            "status": f"error: {type(e).__name__}: {e}"[:300],
            "elapsed_s": round(time.monotonic() - t0, 1)}
        return None


# -------------------------------------------------------------- sections --

def _aes_core_name() -> str:
    from libjitsi_tpu.kernels.aes import get_core

    return get_core()


def tpu_pps(deadline: float) -> None:
    import jax
    import jax.numpy as jnp

    from libjitsi_tpu.transform.srtp import kernel

    rng = np.random.default_rng(3)
    tab_rk = rng.integers(0, 256, (N_STREAMS, 11, 16), dtype=np.uint8)
    tab_mid = rng.integers(0, 2**32, (N_STREAMS, 2, 5), dtype=np.uint64
                           ).astype(np.uint32)
    stream = rng.integers(0, N_STREAMS, BATCH).astype(np.int32)
    data = rng.integers(0, 256, (BATCH, WIDTH), dtype=np.uint8)
    length = np.full(BATCH, PKT_LEN, dtype=np.int32)
    payload_off = np.full(BATCH, 12, dtype=np.int32)
    iv = rng.integers(0, 256, (BATCH, 16), dtype=np.uint8)
    roc = np.zeros(BATCH, dtype=np.uint32)

    import functools

    @functools.partial(jax.jit, donate_argnums=())
    def step(tab_rk, tab_mid, stream, data, length, payload_off, iv, roc):
        return kernel.srtp_protect(
            data, length, payload_off, tab_rk[stream], iv, tab_mid[stream],
            roc, TAG_LEN, True, payload_off_const=12)

    args = [jnp.asarray(a) for a in
            (tab_rk, tab_mid, stream, data, length, payload_off, iv, roc)]
    # FETCH-VERIFIED timing (round-5 methodology — see _checksum): the
    # r2-r4 "sync/pipelined" loops measured dispatch acknowledgment,
    # not execution, because block_until_ready does not wait on this
    # tunnel.  Every sample below includes a forced 4-byte result
    # fetch; the scalar-fetch floor is measured and subtracted.
    g = _checksum(step)
    _ = np.asarray(g(*args))            # compile + prime
    floor = _fetch_floor()
    lat = []
    for _ in range(6):
        t0 = time.perf_counter()
        _ = np.asarray(g(*args))
        lat.append(time.perf_counter() - t0)
        if time.monotonic() > deadline and len(lat) >= 3:
            break
    per_launch = max(float(np.median(lat)) - floor, 1e-9)
    # sustained: enqueue k launches, fetch only the LAST checksum —
    # the device executes in order, so the final scalar proves all k
    # completed; this is the deployment overlap shape, now honest
    k = 3 if per_launch > 0.3 else 25
    sustained = []
    for _ in range(2):
        t0 = time.perf_counter()
        s = None
        for _i in range(k):
            s = g(*args)
        _ = np.asarray(s)
        sustained.append(k * BATCH / max(
            time.perf_counter() - t0 - floor, 1e-9))
        if time.monotonic() > deadline:
            break
    # Per-packet HBM traffic model for one protect launch: data in+out
    # (2W) + round-key gather (11*16) + midstates (2*5*4) + iv (16) +
    # roc/len/off/stream (4 each).  With honest timing the measured
    # rate sits far BELOW this ceiling; the cap is a sanity backstop.
    bytes_per_pkt = 2 * WIDTH + 11 * 16 + 2 * 5 * 4 + 16 + 4 * 4
    traffic = (f"2*{WIDTH} data + 176 rk + 40 mid + 16 iv + 16 scalars"
               f" per packet")
    med_sustained = float(np.median(sustained)) if sustained else \
        BATCH / per_launch
    RESULT["value"] = round(
        _roofline("headline", med_sustained, bytes_per_pkt, traffic), 1)
    _roofline("sync_per_launch", BATCH / per_launch, bytes_per_pkt,
              traffic)
    EXTRA["p99_batch_ms"] = round(
        (float(np.percentile(np.asarray(lat), 99)) - floor) * 1e3, 3)
    EXTRA["on_device_launch_ms"] = round(per_launch * 1e3, 3)
    EXTRA["estimators_pps"] = {
        "sync_fetch_verified": round(BATCH / per_launch, 1),
        "sustained_median": round(med_sustained, 1),
        "sustained_passes": [round(v, 1) for v in sustained],
        "aes_core_in_use": _aes_core_name()}


def cpu_pps(deadline: float) -> None:
    """Single-thread OpenSSL SRTP protect (keystream XOR + HMAC-SHA1-80)."""
    import hmac as pyhmac
    import hashlib

    # lazy + gated like control/dtls.py's _openssl(): the container may
    # not ship `cryptography`, and an absent optional baseline is a
    # skip, not a degradation record
    try:
        from cryptography.hazmat.primitives.ciphers import (
            Cipher, algorithms, modes)
    except ImportError:
        raise SkipSection("missing-dep")

    rng = np.random.default_rng(4)
    n = 2000
    pkts = [rng.integers(0, 256, PKT_LEN, dtype=np.uint8).tobytes()
            for _ in range(n)]
    keys = [rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
            for _ in range(64)]
    akeys = [rng.integers(0, 256, 20, dtype=np.uint8).tobytes()
             for _ in range(64)]
    iv = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
    t0 = time.perf_counter()
    done = 0
    for i, p in enumerate(pkts):
        enc = Cipher(algorithms.AES(keys[i % 64]), modes.CTR(iv)).encryptor()
        ct = p[:12] + enc.update(p[12:]) + enc.finalize()
        tag = pyhmac.new(akeys[i % 64], ct + b"\x00\x00\x00\x00",
                         hashlib.sha1).digest()[:TAG_LEN]
        _ = ct + tag
        done += 1
        if done % 500 == 0 and time.monotonic() > deadline:
            break
    EXTRA["cpu_openssl_pps"] = round(done / (time.perf_counter() - t0), 1)


def _time_fn(fn, args, deadline: float, iters: int = 4) -> float:
    """Median FETCH-VERIFIED per-launch time, scalar-fetch floor
    subtracted (round-5 methodology — block_until_ready does not wait
    on this tunnel; see _checksum).  Deadline-aware: stops sampling
    once the box is spent (the first sample already yields a number)."""
    g = _checksum(fn)
    _ = np.asarray(g(*args))            # compile + prime
    floor = _fetch_floor()
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _ = np.asarray(g(*args))
        samples.append(time.perf_counter() - t0)
        if time.monotonic() > deadline and samples:
            break
    return max(float(np.median(samples)) - floor, 1e-9)


def _chained_aes(fn, rks, k: int):
    """jit( blocks -> checksum of fn applied k times, CHAINED ): round
    i's ciphertext is round i+1's plaintext, so XLA cannot elide any
    round and the program span scales with k.  This is what makes the
    per-core numbers floor-proof (r5 verdict Weak #1: single-launch
    timings under the fetch-floor jitter are noise — xla_bitsliced32's
    231.6M blocks/s in the r05 record was exactly that artifact)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def prog(blk):
        out = lax.fori_loop(0, k, lambda _i, v: fn(rks, v), blk)
        return jnp.sum(out.astype(jnp.uint32))

    return jax.jit(prog)


def aes_core_blocks_per_sec(deadline: float, b: int = 65536) -> None:
    """Provider sweep for the AES core (SURVEY §7 'hard parts'): the
    table/S-box-gather core vs the gather-free bitsliced Boolean circuit
    (kernels/aes_bitsliced.py), plus the Pallas bitsliced kernel (lane-
    native; lowers since round 3).  Standalone block-encrypt rate via
    CHAINED launches: k data-dependent encrypts per program, k doubled
    until the net span clears 10x the fetch-floor jitter; a core that
    cannot clear the bar inside the budget records "below_floor", never
    a number.  The quick XLA providers run first so their numbers are
    banked before the Pallas compile (the one potentially slow step —
    its box is whatever remains of this section's)."""
    import jax.numpy as jnp

    from libjitsi_tpu.kernels.aes import aes_encrypt_table, \
        expand_keys_batch
    from libjitsi_tpu.kernels.aes_bitsliced import (
        aes_encrypt_bitsliced, aes_encrypt_bitsliced32,
        aes_encrypt_bitsliced_tower, aes_encrypt_pallas_bitsliced)

    rng = np.random.default_rng(21)
    rks = expand_keys_batch(rng.integers(0, 256, (b, 16), dtype=np.uint8))
    blocks = rng.integers(0, 256, (b, 16), dtype=np.uint8)
    rksd, blkd = jnp.asarray(rks), jnp.asarray(blocks)
    floor, jitter = _fetch_floor(), _floor_jitter()
    out: dict = {}
    EXTRA["aes_core_blocks_per_sec"] = out
    for name, fn in (("xla_table", aes_encrypt_table),
                     ("xla_bitsliced", aes_encrypt_bitsliced),
                     ("xla_bitsliced_tower", aes_encrypt_bitsliced_tower),
                     ("xla_bitsliced32", aes_encrypt_bitsliced32),
                     ("pallas_bitsliced", aes_encrypt_pallas_bitsliced)):
        if time.monotonic() > deadline:
            out[name] = "skipped: budget"
            continue
        try:
            k = 4
            while True:
                g = _chained_aes(fn, rksd, k)
                _ = np.asarray(g(blkd))          # compile + prime
                spans = []
                for _ in range(4):
                    t0 = time.perf_counter()
                    _ = np.asarray(g(blkd))
                    spans.append(time.perf_counter() - t0)
                    if time.monotonic() > deadline and spans:
                        break
                net = float(np.median(spans)) - floor
                if net >= 10.0 * max(jitter, 1e-9):
                    # 176B round keys + 16B in + 16B out per block
                    out[name] = round(_roofline(
                        f"aes_{name}", b * k / net, 208,
                        "176 rk + 16 in + 16 out"), 1)
                    break
                if k >= 1 << 16 or time.monotonic() > deadline:
                    out[name] = f"below_floor: k={k} net={net * 1e3:.3f}ms"
                    break
                k *= 2
        except Exception as e:   # Mosaic lowering refusal, recorded
            out[name] = f"error: {type(e).__name__}"
    _aes_consistency_check(out)


def _aes_consistency_check(core: dict) -> None:
    """Cross-estimator sanity (VERDICT r4 #1c): a 172B packet needs ~10
    AES keystream blocks, so headline_pps * 10 cannot exceed the
    (roofline-capped) standalone core rate by more than measurement
    slack.  The r04 record failed exactly this check (implied 40B
    blocks/s vs a 4.3B core); now it caps the headline instead of
    shipping an impossible number."""
    rates = [v for v in core.values() if isinstance(v, (int, float))]
    if not rates or not RESULT["value"]:
        return
    blocks_per_pkt = -(-(PKT_LEN - 12) // 16)
    allowed = max(rates) / blocks_per_pkt * 1.5
    rec = {"blocks_per_pkt": blocks_per_pkt,
           "core_rate_capped": round(max(rates), 1),
           "allowed_headline_pps": round(allowed, 1), "ok": True}
    if RESULT["value"] > allowed:
        rec["ok"] = False
        rec["headline_before_cap"] = RESULT["value"]
        RESULT["value"] = round(allowed, 1)
    EXTRA["consistency_vs_aes_core"] = rec


def gcm_sweep(deadline: float) -> None:
    """BASELINE config #2's AEAD_AES_128_GCM leg, both table paths at
    three batch sizes (VERDICT r3 #6: pin the grouped/per-row crossover
    from data, not a constant).

    `grouped` is the production table path: rows grouped by stream, one
    GHASH matrix read per stream per launch.  `per_row` gathers a 16 KiB
    matrix per row (capped at 32768 rows by HBM).  The crossover batch
    recorded here is what `transform/srtp/context.py` consumes via
    `kernels.registry` measurement at table setup.
    """
    import functools as _ft

    import jax.numpy as jnp

    from libjitsi_tpu.kernels import gcm as G
    from libjitsi_tpu.transform.srtp.context import _gcm_grid

    rng = np.random.default_rng(5)
    grouped: dict = {}
    per_row: dict = {}
    EXTRA["gcm_pps_grouped_by_batch"] = grouped
    EXTRA["gcm_pps_per_row_by_batch"] = per_row

    for b in (16384, 65536):
        if time.monotonic() > deadline:
            grouped[str(b)] = "skipped: budget"
            continue
        n_streams = max(b // 64, 64)
        rks = rng.integers(0, 256, (b, 11, 16), dtype=np.uint8)
        data = rng.integers(0, 256, (b, WIDTH), dtype=np.uint8)
        length = np.full(b, PKT_LEN, np.int32)
        aad = np.full(b, 12, np.int32)
        iv = rng.integers(0, 256, (b, 12), dtype=np.uint8)
        stream = np.repeat(np.arange(n_streams), b // n_streams)
        rng.shuffle(stream)
        grid, _us, inv = _gcm_grid(stream)
        gms_g = rng.integers(0, 2, (grid.shape[0], 128, 128), dtype=np.int8)
        args = [jnp.asarray(x) for x in (data, length, aad, rks, gms_g, iv,
                                         grid, inv)]
        dt = _time_fn(_ft.partial(G.gcm_protect_grouped, aad_const=12),
                      args, deadline, iters=2)
        # per pkt: 2W data + 176 rk + 12 iv + one 16KiB GHASH matrix
        # per GROUP amortized over its rows
        bpp = 2 * WIDTH + 176 + 12 + 16384 * grid.shape[0] / b
        grouped[str(b)] = round(
            _roofline(f"gcm_grouped_{b}", b / dt, bpp,
                      "2W+rk+iv+gmat/group"), 1)

    for b in (16384,):
        # 32768 dropped round 5: at honest timing its ~10 s/sample cost
        # one later section per run; the 16384 point plus the grouped
        # sweep still pins the crossover shape
        if time.monotonic() > deadline:
            per_row[str(b)] = "skipped: budget"
            continue
        rks = rng.integers(0, 256, (b, 11, 16), dtype=np.uint8)
        gms = rng.integers(0, 2, (b, 128, 128), dtype=np.int8)
        data = rng.integers(0, 256, (b, WIDTH), dtype=np.uint8)
        length = np.full(b, PKT_LEN, np.int32)
        aad = np.full(b, 12, np.int32)
        iv = rng.integers(0, 256, (b, 12), dtype=np.uint8)
        args = [jnp.asarray(x) for x in (data, length, aad, rks, gms, iv)]
        dt = _time_fn(G.gcm_protect, args, deadline, iters=2)
        per_row[str(b)] = round(
            _roofline(f"gcm_per_row_{b}", b / dt,
                      2 * WIDTH + 176 + 12 + 16384,
                      "2W+rk+iv+16KiB gmat/row"), 1)

    # continuity keys (same configs as BENCH_r02/r03)
    if isinstance(grouped.get("65536"), (int, float)):
        EXTRA["gcm_pps"] = grouped["65536"]
    if isinstance(per_row.get("32768"), (int, float)):
        EXTRA["gcm_pps_per_row"] = per_row["32768"]
    elif isinstance(per_row.get("16384"), (int, float)):
        EXTRA["gcm_pps_per_row"] = per_row["16384"]


def gcm_fanout(deadline: float, packets: int = 128, receivers: int = 512
               ) -> None:
    """AEAD leg of BASELINE config #5: full-mesh GCM fan-out via the
    grouped kernel (per-LEG GHASH matrices — 16 KiB x receivers, not
    x rows, of key-material traffic)."""
    import jax.numpy as jnp

    from libjitsi_tpu.kernels import gcm as G

    rng = np.random.default_rng(12)
    rks = rng.integers(0, 256, (receivers, 11, 16), dtype=np.uint8)
    gms = rng.integers(0, 2, (receivers, 128, 128), dtype=np.int8)
    data = rng.integers(0, 256, (packets, WIDTH), dtype=np.uint8)
    length = np.full(packets, PKT_LEN, np.int32)
    iv = rng.integers(0, 256, (receivers, packets, 12), dtype=np.uint8)
    args = [jnp.asarray(x) for x in (data, length, rks, gms, iv)]
    dt = _time_fn(G.gcm_protect_fanout, args, deadline, iters=2)
    rows = packets * receivers
    # per out row: W write + W/G read + gmat/packets + rk/packets + iv
    bpp = WIDTH + WIDTH / receivers + (16384 + 176) / packets + 12
    EXTRA["gcm_fanout_rows_per_sec"] = round(
        _roofline("gcm_fanout", rows / dt, bpp,
                  "W out + amortized in/gmat/rk + iv"), 1)


def mixer(deadline: float, n_participants: int = 256) -> None:
    """BASELINE config #3: N-participant 48 kHz mono 20 ms mix-minus."""
    import jax.numpy as jnp

    from libjitsi_tpu.conference.mixer import _mix_jit

    rng = np.random.default_rng(6)
    pcm = jnp.asarray(rng.integers(-8000, 8000, (n_participants, 960))
                      .astype(np.int16))
    active = jnp.ones(n_participants, dtype=bool)
    dt = _time_fn(_mix_jit, (pcm, active), deadline)
    EXTRA["mix_256p_per_sec"] = round(1.0 / dt, 1)


def bridge_mixes(deadline: float, conferences: int = 64,
                 participants: int = 64) -> None:
    """Whole-bridge mixing: C conferences of N participants per launch
    (a single conference launch is dispatch-bound; see MixerBridge)."""
    import jax.numpy as jnp

    from libjitsi_tpu.conference.mixer import _mix_many_jit

    rng = np.random.default_rng(8)
    pcm = jnp.asarray(rng.integers(
        -8000, 8000, (conferences, participants, 960)).astype(np.int16))
    active = jnp.ones((conferences, participants), dtype=bool)
    dt = _time_fn(_mix_many_jit, (pcm, active), deadline)
    EXTRA["bridge_64conf_64p_mixes_per_sec"] = round(conferences / dt, 1)


def fanout(deadline: float, packets: int = 128, receivers: int = 512
           ) -> None:
    """BASELINE config #5 core: per-receiver re-encrypt of a fan-out
    matrix (rows = packets x receivers) in one launch."""
    import jax
    import jax.numpy as jnp

    from libjitsi_tpu.transform.srtp import kernel

    rng = np.random.default_rng(7)
    rows = packets * receivers
    tab_rk = rng.integers(0, 256, (receivers, 11, 16), dtype=np.uint8)
    tab_mid = rng.integers(0, 2**32, (receivers, 2, 5), dtype=np.uint64
                           ).astype(np.uint32)
    recv = np.repeat(np.arange(receivers, dtype=np.int32), packets)
    data = rng.integers(0, 256, (rows, WIDTH), dtype=np.uint8)
    length = np.full(rows, PKT_LEN, np.int32)
    off = np.full(rows, 12, np.int32)
    iv = rng.integers(0, 256, (rows, 16), dtype=np.uint8)
    roc = np.zeros(rows, np.uint32)

    # same math as translator._fanout_protect (which since round 5
    # takes the uniform-offset fast path for fan-outs), without buffer
    # donation (donation would invalidate the timed args)
    @jax.jit
    def step(tab_rk, tab_mid, recv, data, length, off, iv, roc):
        return kernel.srtp_protect(data, length, off, tab_rk[recv], iv,
                                   tab_mid[recv], roc, TAG_LEN, True,
                                   payload_off_const=12)

    args = [jnp.asarray(x) for x in
            (tab_rk, tab_mid, recv, data, length, off, iv, roc)]
    dt = _time_fn(step, args, deadline, iters=2)
    EXTRA["sfu_fanout_rows_per_sec"] = round(
        _roofline("sfu_fanout", rows / dt,
                  2 * WIDTH + 176 + 40 + 16 + 16,
                  "2W data + rk + mid + iv + scalars"), 1)


_TABLES: dict = {}


def _production_tables(n_streams: int):
    """Build (and cache, keyed by stream count) the tx/rx tables +
    batch maker used by the probe and bulk production-path section
    CHILDREN (each child process builds its own; the cache only serves
    direct in-process drives).  The measured bulk-install rate lands in
    _TABLES["install_rate"] for the caller to report."""
    if _TABLES.get("n_streams") == n_streams:
        return _TABLES["tx"], _TABLES["rx"], _TABLES["make_batches"]
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    rng = np.random.default_rng(9)
    mks = rng.integers(0, 256, (n_streams, 16), dtype=np.uint8)
    mss = rng.integers(0, 256, (n_streams, 14), dtype=np.uint8)
    t0 = time.perf_counter()
    tx = SrtpStreamTable(capacity=n_streams)
    tx.add_streams(np.arange(n_streams), mks, mss)
    _TABLES["install_rate"] = round(
        n_streams / (time.perf_counter() - t0), 1)
    rx = SrtpStreamTable(capacity=n_streams)
    rx.add_streams(np.arange(n_streams), mks, mss)

    # distinct batches (distinct seqs: replay must accept all), mixed
    # sizes hitting all three width classes: 60% small voice, 30% mid
    # video, 10% near-MTU
    sizes = np.array([100, 400, 950])

    def make_batches(count: int, seq_base: int, bsz: int):
        out = []
        for k in range(count):
            streams = rng.permutation(n_streams)[:bsz]
            ln = sizes[rng.choice(3, bsz, p=[0.6, 0.3, 0.1])]
            payloads = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
                        for n in ln]
            out.append(rtp_header.build(
                payloads, [seq_base + k] * bsz, [k * 960] * bsz,
                (0x10000 + streams).tolist(), [96] * bsz,
                stream=streams.tolist()))
        return out

    _TABLES.update(tx=tx, rx=rx, make_batches=make_batches,
                   n_streams=n_streams)
    return tx, rx, make_batches


def _probe_child(n_streams: int = N_STREAMS) -> None:
    """Subprocess body of table_roundtrip_probe: builds its tables via
    the shared helper and prints ONE json line of results on stdout."""
    from libjitsi_tpu.rtp import header as rtp_header

    # self-bound under the parent's kill cap: past it, stop
    # measuring and print what exists (a killed child prints nothing)
    deadline = time.monotonic() + 70
    tx, rx, _ = _production_tables(n_streams)
    # single packet size on purpose: ONE size class = one compile pair
    rng = np.random.default_rng(77)
    rt = []
    auth_fail = 0
    for k in range(12):
        streams = rng.permutation(n_streams)[:512]
        payloads = [rng.integers(0, 256, 160, dtype=np.uint8).tobytes()
                    for _ in range(512)]
        b = rtp_header.build(
            payloads, [1000 + k] * 512, [k * 960] * 512,
            (0x10000 + streams).tolist(), [96] * 512,
            stream=streams.tolist())
        t1 = time.perf_counter()
        w = tx.protect_rtp(b)
        _, ok = rx.unprotect_rtp(w)
        rt.append(time.perf_counter() - t1)
        auth_fail += int(len(ok) - int(np.sum(ok)))
        if len(rt) in (4, 8, 12):
            # cumulative partial print: the parent parses the LAST
            # line, so even a hard kill mid-stall keeps these samples
            tail = rt[max(len(rt) // 4, 1):] or rt
            out = {"table_roundtrip_512_p99_ms": round(
                       float(np.percentile(tail, 99) * 1e3), 3),
                   "table_roundtrip_512_p50_ms": round(
                       float(np.percentile(tail, 50) * 1e3), 3),
                   "table_roundtrip_samples": len(rt),
                   "install_streams_per_sec": _TABLES["install_rate"]}
            if auth_fail:
                out["table_roundtrip_auth_failures"] = auth_fail
            print(json.dumps(out), flush=True)
        if time.monotonic() > deadline and len(rt) >= 4:
            break


_CHILD = None     # live section subprocess; killed by _on_term/_watchdog


def _run_in_child(fn_name: str, deadline: float, cap_s: float,
                  env: "dict | None" = None) -> None:
    """Run a bench section in a SUBPROCESS with its own timeout and
    merge its one-line JSON stdout into EXTRA.

    Why: three full runs showed a fresh XLA compile can sit on the
    degraded tunnel for the entire remaining budget; in-process that
    starves every later section (only the watchdog saves the record),
    while a killed child loses just its own numbers — and a fresh
    process gets a fresh tunnel connection besides.

    Salvage rule: whatever valid JSON the child managed to print is
    kept even if it then hung in teardown or died non-zero — losing
    already-measured numbers would re-create the round-3 failure this
    file exists to prevent.
    """
    global _CHILD
    import subprocess
    import sys

    budget = max(min(deadline - time.monotonic(), cap_s), 30)
    child_env = None
    if env:
        child_env = dict(os.environ)
        child_env.update(env)
    p = subprocess.Popen(
        [sys.executable, "-c", f"import bench; bench.{fn_name}()"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)), env=child_env)
    _CHILD = p
    timed_out = False
    try:
        out, err = p.communicate(timeout=budget)
    except subprocess.TimeoutExpired:
        timed_out = True
        p.kill()
        out, err = p.communicate()
    finally:
        _CHILD = None
    lines = [l for l in (out or "").splitlines() if l.strip()]
    payload = None
    # newest parseable line wins: a timeout-kill can clip the child's
    # FINAL print mid-line, and discarding the earlier complete partial
    # would lose already-measured numbers
    for line in reversed(lines):
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict):   # stray scalar/list prints are not
            payload = cand           # results; keep scanning upward
            break
    if payload is not None:
        EXTRA.update(payload)
        if timed_out or p.returncode != 0:
            SECTIONS[f"_{fn_name}_note"] = (
                f"results salvaged (timed_out={timed_out}, "
                f"rc={p.returncode})")
        return
    raise RuntimeError(
        f"{fn_name} child {'timed out' if timed_out else ''} "
        f"rc={p.returncode}: {(err or '')[-200:]}")


def table_roundtrip_probe(deadline: float) -> None:
    """VERDICT-r3 #3: the ASSEMBLED production path's latency on the
    real device — `SrtpStreamTable.protect_rtp` → `unprotect_rtp` round
    trip p99 at batch 512 over 10k installed streams, full host control
    plane per call; tunnel-caveated but measured.  Subprocess-isolated
    (see _run_in_child)."""
    _run_in_child("_probe_child", deadline, 85)


def table_path(deadline: float) -> None:
    """PRODUCTION-path SRTP: `SrtpStreamTable.protect_rtp/unprotect_rtp`
    with the full host control plane — header parse, chain-index /
    index-estimation, replay window update, size-class bucketing — at
    10k installed streams and mixed packet sizes (the kernel-only bench
    above deliberately excludes all of that).  Subprocess-isolated
    (see _run_in_child): its three size-class compile pairs are the
    bench's heaviest fresh compiles and have stalled past the whole
    budget on the degraded tunnel.

    On this box every call crosses the axon TPU tunnel (~120 ms+ fixed
    cost per synchronous transfer, measured by the h2d probe); the wall
    numbers are tunnel-floored, so the host-plane ceiling and the probe
    are reported alongside to keep the decomposition visible.  On local
    PCIe the same transfers are <1 ms.
    """
    _run_in_child("_table_child", deadline, 70)


def _table_child(n_streams: int = N_STREAMS, batch: int = 4096,
                 n_batches: int = 6) -> None:
    """Subprocess body of table_path; prints ONE json line.  Self-
    bounded under the parent's 180s kill cap with early breaks, so a
    mid-section stall still prints everything measured so far."""
    from libjitsi_tpu.core.packet import bucket_by_size
    from libjitsi_tpu.core.rtp_math import chain_packet_indices
    from libjitsi_tpu.rtp import header as rtp_header

    deadline = time.monotonic() + 55
    out: dict = {}
    tx, rx, make_batches = _production_tables(n_streams)
    batches = make_batches(n_batches, 2000, batch)

    warm = n_batches // 3                     # first passes pay compiles
    lat_p, lat_u = [], []
    protected = []
    t_all = 0.0
    for k, b in enumerate(batches):
        t1 = time.perf_counter()
        w = tx.protect_rtp(b)
        dt = time.perf_counter() - t1
        protected.append(w)
        if k >= warm:
            lat_p.append(dt)
            t_all += dt
        if time.monotonic() > deadline and lat_p:
            break
    out["table_protect_pps"] = round(batch * len(lat_p) / t_all, 1)
    out["table_protect_p99_batch_ms"] = round(
        float(np.percentile(lat_p, 99) * 1e3), 3)
    print(json.dumps(out), flush=True)   # cumulative partial (see probe)
    t_all = 0.0
    auth_fail = 0
    for k, b in enumerate(protected):
        t1 = time.perf_counter()
        _, ok = rx.unprotect_rtp(b)
        dt = time.perf_counter() - t1
        auth_fail += int(len(ok) - int(np.sum(ok)))
        if k >= warm:
            lat_u.append(dt)
            t_all += dt
        if time.monotonic() > deadline and lat_u:
            break
    if lat_u:
        out["table_unprotect_pps"] = round(
            batch * len(lat_u) / t_all, 1)
        out["table_unprotect_p99_batch_ms"] = round(
            float(np.percentile(lat_u, 99) * 1e3), 3)
    if auth_fail:        # degradation field, not a fatal assert
        out["table_auth_failures"] = auth_fail
    print(json.dumps(out), flush=True)   # cumulative partial

    # double-buffered production path: protect_rtp_async keeps DEPTH
    # batches in flight (host state commits at dispatch; bytes
    # materialize later), overlapping H2D/compute/D2H across batches —
    # the naive path above drains every batch before the next dispatch
    if time.monotonic() < deadline:
        depth = 3
        more = make_batches(n_batches, 3000, batch)
        t1 = time.perf_counter()
        inflight = []
        for b in more:
            inflight.append(tx.protect_rtp_async(b))
            if len(inflight) >= depth:
                inflight.pop(0).result()
        for p in inflight:
            p.result()
        out["table_protect_pps_pipelined"] = round(
            batch * n_batches / (time.perf_counter() - t1), 1)

    # host control plane alone (parse, chain index, IV build, bucketing,
    # replay max update) — the part this bench adds over the kernel bench
    b = batches[-1]
    t1 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        hdr = rtp_header.parse(b)
        stream = np.asarray(b.stream, dtype=np.int64)
        idx = chain_packet_indices(stream, hdr.seq, tx.tx_ext)
        _ = bucket_by_size(b)
        _ = tx._cm_iv(tx._salt_rtp[stream], hdr.ssrc, idx)
        np.maximum.at(tx.tx_ext, stream, idx)
    out["table_host_plane_pps"] = round(
        batch * reps / (time.perf_counter() - t1), 1)

    # tunnel/PCIe probe: one synchronous H2D of the batch-sized buffer
    import jax
    import jax.numpy as jnp
    probe = np.zeros_like(batches[0].data)
    d = jnp.asarray(probe)
    jax.block_until_ready(d)
    t1 = time.perf_counter()
    for _ in range(3):
        d = jnp.asarray(probe)
        jax.block_until_ready(d)
    out["h2d_transfer_probe_ms"] = round(
        (time.perf_counter() - t1) / 3 * 1e3, 3)
    print(json.dumps(out), flush=True)


def mesh_plan(deadline: float, b: int = BATCH, n_dev: int = 8) -> None:
    """Host routing plane of the sharded table (VERDICT r4 #3/#6): one
    vectorized `_OwnerPlan` + chip-local row map + grouped-GCM grid
    build at the headline batch size over 8 devices.  Pure host cost —
    this is the per-batch overhead mesh mode adds BEFORE any device
    work, the thing the r4 Python-loop plan left unmeasured."""
    from libjitsi_tpu.mesh.table import (_OwnerPlan, local_rows,
                                         mesh_gcm_grid)

    rng = np.random.default_rng(31)
    ids = rng.integers(0, N_STREAMS, b).astype(np.int64)
    rows_per = N_STREAMS // n_dev
    t_plan = t_grid = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        plan = _OwnerPlan(ids, N_STREAMS, rows_per, n_dev)
        local = local_rows(plan, ids, N_STREAMS, rows_per, n_dev)
        t1 = time.perf_counter()
        mesh_gcm_grid(local)
        t2 = time.perf_counter()
        t_plan = min(t_plan, t1 - t0)
        t_grid = min(t_grid, t2 - t1)
        if time.monotonic() > deadline:
            break
    EXTRA["mesh_plan_ms"] = {
        "batch": b, "n_dev": n_dev,
        "owner_plan_ms": round(t_plan * 1e3, 3),
        "gcm_grid_ms": round(t_grid * 1e3, 3),
        "plan_pps_ceiling": round(b / t_plan, 1)}


def mesh_seam(deadline: float) -> None:
    """Sharded-table seam overhead on the REAL chip (VERDICT r4 #3):
    `ShardedSrtpTable` on a ONE-device mesh vs the plain table — same
    host control plane, same chip; the delta is the owner-plan /
    shard_map / deferred-scatter seam.  Subprocess-isolated (fresh
    shard_map compiles have stalled the tunnel before)."""
    _run_in_child("_mesh_seam_child", deadline, 60)


def _mesh_seam_child(n_streams: int = N_STREAMS, batch: int = 4096,
                     iters: int = 3) -> None:
    deadline = time.monotonic() + 45
    import jax
    from jax.sharding import Mesh

    from libjitsi_tpu.mesh import ShardedSrtpTable
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    rng = np.random.default_rng(9)
    mks = rng.integers(0, 256, (n_streams, 16), dtype=np.uint8)
    mss = rng.integers(0, 256, (n_streams, 14), dtype=np.uint8)
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("streams",))
    out: dict = {}

    def drive(table, key):
        lat = []
        for k in range(iters):
            streams = rng.permutation(n_streams)[:batch]
            b = rtp_header.build(
                [b"\xcd" * 160] * batch,
                [4000 + iters * int(key == "mesh1") + k] * batch,
                [k * 960] * batch, (0x30000 + streams).tolist(),
                [96] * batch, stream=streams.tolist())
            t0 = time.perf_counter()
            w = table.protect_rtp(b)
            lat.append(time.perf_counter() - t0)
            if time.monotonic() > deadline and len(lat) >= 2:
                break
        warm = lat[max(len(lat) // 3, 1):] or lat
        out[f"mesh_seam_{key}_ms"] = round(
            float(np.median(warm)) * 1e3, 3)

    plain = SrtpStreamTable(capacity=n_streams)
    plain.add_streams(np.arange(n_streams), mks, mss)
    drive(plain, "plain")
    print(json.dumps(out), flush=True)      # cumulative partial
    sh = ShardedSrtpTable(n_streams, mesh1)
    sh.add_streams(np.arange(n_streams), mks, mss)
    drive(sh, "mesh1")
    if out.get("mesh_seam_plain_ms"):
        out["mesh_seam_overhead_ratio"] = round(
            out["mesh_seam_mesh1_ms"] / out["mesh_seam_plain_ms"], 3)
    print(json.dumps(out), flush=True)


def mesh_cpu8(deadline: float) -> None:
    """The sharded product path END-TO-END on the virtual 8-device CPU
    mesh (the same geometry the driver's dryrun validates): sharded vs
    plain `protect_rtp` per-batch time.  CPU numbers — the point is the
    host-plane share and the seam scaling at 8 devices, not chip
    throughput."""
    _run_in_child("_mesh_cpu8_child", deadline, 55, env={
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (os.environ.get("XLA_FLAGS", "") +
                      " --xla_force_host_platform_device_count=8"
                      ).strip()})


def _mesh_cpu8_child(n_streams: int = N_STREAMS, batch: int = 1024,
                     iters: int = 2) -> None:
    # batch sized for the CPU backend's exec floor (~7 ms/packet-KB on
    # this box): the section's value is the 8-device seam RATIO, not
    # absolute CPU throughput — and batch must stay <= n_streams for
    # the permutation below.  Self-bound sits UNDER the parent's 55s
    # kill cap so the final print always happens.
    deadline = time.monotonic() + 40
    import jax

    jax.config.update("jax_platforms", "cpu")
    from libjitsi_tpu.mesh import ShardedSrtpTable, make_media_mesh
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    rng = np.random.default_rng(10)
    mks = rng.integers(0, 256, (n_streams, 16), dtype=np.uint8)
    mss = rng.integers(0, 256, (n_streams, 14), dtype=np.uint8)
    mesh = make_media_mesh()
    out: dict = {"mesh_cpu8_batch": batch,
                 "mesh_cpu8_n_dev": int(mesh.devices.size)}

    def drive(table, key):
        lat = []
        for k in range(iters):
            streams = rng.permutation(n_streams)[:batch]
            b = rtp_header.build(
                [b"\xef" * 160] * batch,
                [6000 + iters * int(key == "mesh8") + k] * batch,
                [k * 960] * batch, (0x40000 + streams).tolist(),
                [96] * batch, stream=streams.tolist())
            t0 = time.perf_counter()
            table.protect_rtp(b)
            lat.append(time.perf_counter() - t0)
            if time.monotonic() > deadline and len(lat) >= 2:
                break
        warm = lat[max(len(lat) // 3, 1):] or lat
        out[f"mesh_cpu8_{key}_ms"] = round(
            float(np.median(warm)) * 1e3, 3)

    plain = SrtpStreamTable(capacity=n_streams)
    plain.add_streams(np.arange(n_streams), mks, mss)
    drive(plain, "plain")
    print(json.dumps(out), flush=True)      # cumulative partial
    sh = ShardedSrtpTable(n_streams, mesh)
    sh.add_streams(np.arange(n_streams), mks, mss)
    drive(sh, "mesh8")
    if out.get("mesh_cpu8_plain_ms") and out.get("mesh_cpu8_mesh8_ms"):
        out["mesh_cpu8_ratio_vs_plain"] = round(
            out["mesh_cpu8_mesh8_ms"] / out["mesh_cpu8_plain_ms"], 3)
    print(json.dumps(out), flush=True)


def dense_tick(deadline: float, n_streams: int = 10_240) -> None:
    """Host cost of one decode-path tick at 10k streams: dense jitter
    insert+pop plus the batched GCC feed — the plane that used to be
    per-stream Python objects.  Pure host time (no device)."""
    from libjitsi_tpu.bwe.batched import BatchedRemoteBitrateEstimator
    from libjitsi_tpu.rtp.dense_jitter import DenseJitterBank

    jb = DenseJitterBank(capacity=n_streams, depth=16, payload_cap=64)
    bwe = BatchedRemoteBitrateEstimator(capacity=64)
    rng = np.random.default_rng(13)
    sids = np.arange(n_streams)
    tids = sids % 64
    pay = rng.integers(0, 256, (n_streams, 64), dtype=np.uint8)
    best = float("inf")
    for k in range(12):
        now = 5.0 + 0.02 * k
        t0 = time.perf_counter()
        jb.insert_batch(sids, np.full(n_streams, 100 + k),
                        np.full(n_streams, 160 * k), pay,
                        np.full(n_streams, 64), now)
        jb.pop_all(now + 0.001)
        bwe.incoming_batch(tids, np.full(n_streams, now * 1000),
                           np.full(n_streams,
                                   (int(now * (1 << 18)) & 0xFFFFFF)),
                           np.full(n_streams, 172))
        if k >= 2:
            best = min(best, time.perf_counter() - t0)
        if time.monotonic() > deadline and k >= 3:
            break
    bwe.update_estimate(6.0 * 1000)
    EXTRA["dense_receive_tick_ms_10k"] = round(best * 1e3, 3)


def _loop_fixture():
    """Fresh registry/SRTP-tables/chain for one echo-loop run (tables
    are stateful: each run needs its own).  Callers libjitsi_tpu.init()
    once themselves."""
    import libjitsi_tpu
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.service.media_stream import StreamRegistry
    from libjitsi_tpu.transform import (SrtpTransformEngine,
                                        TransformEngineChain)
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    mk, ms = bytes(range(16)), bytes(range(30, 44))
    mk2, ms2 = bytes(range(60, 76)), bytes(range(80, 94))
    reg = StreamRegistry(libjitsi_tpu.configuration_service(), capacity=16)
    rx_tab = SrtpStreamTable(capacity=16)
    rx_tab.add_stream(3, mk, ms)
    tx_tab = SrtpStreamTable(capacity=16)
    tx_tab.add_stream(3, mk2, ms2)
    chain = TransformEngineChain([SrtpTransformEngine(tx_tab, rx_tab)])

    def on_media(batch, ok):
        rows = np.nonzero(ok)[0]
        if len(rows) == 0:
            return None
        return PacketBatch(batch.data[rows],
                           np.asarray(batch.length)[rows],
                           batch.stream[rows])

    return reg, chain, on_media, (mk, ms), (mk2, ms2)


def loop_rtt(deadline: float) -> None:
    """End-to-end MediaLoop tick over REAL loopback UDP (SURVEY
    §3.2/§3.4's socket→chain→socket hot loop).  Subprocess-isolated
    (see _run_in_child)."""
    _run_in_child("_loop_rtt_child", deadline, 60)


def loop_pipelined_gain(deadline: float) -> None:
    """SURVEY §7 step 4's dispatch/flush overlap seam, sync vs
    pipelined MediaLoop on the same echo workload.  Subprocess-isolated
    (see _run_in_child)."""
    _run_in_child("_loop_gain_child", deadline, 70)


def _loop_rtt_child(n_pkts: int = 256, cycles: int = 12) -> None:
    """Subprocess body of loop_rtt: client protect → send → bridge
    recv_batch → SSRC demux → unprotect → echo → re-protect → send →
    client recv, the path the 2 ms p99 budget governs.

    NOTE: on this box every device launch crosses the axon TPU tunnel,
    so the cycle time includes 4 tunnel round trips (client
    protect/unprotect + bridge unprotect/protect) — a wildly pessimistic
    floor vs local PCIe.
    """
    # self-bound comfortably inside the parent's kill cap: a killed
    # child prints nothing, a self-bounded one prints what it measured
    deadline = time.monotonic() + 45
    import libjitsi_tpu
    from libjitsi_tpu.io import UdpEngine
    from libjitsi_tpu.io.loop import MediaLoop
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    reg, chain, on_media, (mk, ms), (mk2, ms2) = _loop_fixture()
    bridge = MediaLoop(UdpEngine(port=0, max_batch=n_pkts + 8), reg,
                       on_media=on_media, chain=chain, recv_window_ms=0)
    reg.map_ssrc(0xBEEF01, 3)
    c_tx = SrtpStreamTable(capacity=1)
    c_tx.add_stream(0, mk, ms)
    c_rx = SrtpStreamTable(capacity=1)
    c_rx.add_stream(0, mk2, ms2)
    client = UdpEngine(port=0, max_batch=n_pkts + 8)

    lat = []
    done_pkts = 0
    sent_pkts = 0
    t_all = time.perf_counter()
    try:
        for cyc in range(cycles):
            payloads = [b"\xab" * 160] * n_pkts
            b = rtp_header.build(payloads, list(range(cyc * n_pkts,
                                                      (cyc + 1) * n_pkts)),
                                 [cyc * 960] * n_pkts, [0xBEEF01] * n_pkts,
                                 [96] * n_pkts, stream=[0] * n_pkts)
            t1 = time.perf_counter()
            wire = c_tx.protect_rtp(b)
            client.send_batch(wire, "127.0.0.1", bridge.engine.port)
            sent_pkts += n_pkts
            got = 0
            back_parts = []
            cyc_deadline = time.perf_counter() + 5.0
            while got < n_pkts and time.perf_counter() < cyc_deadline:
                bridge.tick()
                back, _, _ = client.recv_batch(timeout_ms=1)
                if back.batch_size:
                    back_parts.append(back)
                    got += back.batch_size
            for back in back_parts:
                back.stream[:] = 0
                _, ok = c_rx.unprotect_rtp(back)
                done_pkts += int(ok.sum())
            lat.append(time.perf_counter() - t1)
            if time.monotonic() > deadline and cyc >= 3:
                break
        total = time.perf_counter() - t_all
    finally:
        bridge.engine.close()
        client.close()
    warm = len(lat) // 3
    tail = np.asarray(lat[warm:])
    out = {"loop_udp_echo_pps": round(done_pkts / total, 1),
           "loop_udp_cycle_p99_ms": round(
               float(np.percentile(tail, 99) * 1e3), 3),
           "loop_udp_cycle_p50_ms": round(
               float(np.percentile(tail, 50) * 1e3), 3)}
    if done_pkts != sent_pkts:      # degradation field, not a fatal assert
        out["loop_udp_lost_pkts"] = sent_pkts - done_pkts
    print(json.dumps(out), flush=True)


def _loop_gain_child(n_pkts: int = 512, cycles: int = 12) -> None:
    """Subprocess body of loop_pipelined_gain: the pipelined MediaLoop
    dispatches the reply protect and flushes it at the top of the next
    tick, so the device launch overlaps the next recv window instead of
    serializing with it.  Same echo workload both ways."""
    # self-bound comfortably inside the parent's kill cap (see
    # _loop_rtt_child); one sync+pipelined pair is the minimum result
    deadline = time.monotonic() + 55
    import libjitsi_tpu
    from libjitsi_tpu.io import UdpEngine
    from libjitsi_tpu.io.loop import MediaLoop
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    libjitsi_tpu.stop()
    libjitsi_tpu.init()

    def run_mode(pipelined: bool) -> float:
        # fresh fixture per run: SRTP tables are stateful
        reg, chain, on_media, (mk, ms), _ = _loop_fixture()
        loop = MediaLoop(UdpEngine(port=0, max_batch=n_pkts + 8), reg,
                         on_media=on_media, chain=chain,
                         recv_window_ms=0, pipelined=pipelined)
        reg.map_ssrc(0xBEEF01, 3)
        c_tx = SrtpStreamTable(capacity=1)
        c_tx.add_stream(0, mk, ms)
        client = UdpEngine(port=0, max_batch=n_pkts + 8)
        # streaming shape: bursts keep flowing without waiting for
        # their echoes, so the pipelined loop holds a dispatched batch
        # in flight across each next tick (the sync loop materializes
        # per tick); echoes drain opportunistically
        echoed = 0
        t0 = time.perf_counter()
        for cyc in range(cycles):
            b = rtp_header.build([b"\xab" * 160] * n_pkts,
                                 list(range(cyc * n_pkts,
                                            (cyc + 1) * n_pkts)),
                                 [cyc * 960] * n_pkts,
                                 [0xBEEF01] * n_pkts, [96] * n_pkts,
                                 stream=[0] * n_pkts)
            client.send_batch(c_tx.protect_rtp(b), "127.0.0.1",
                              loop.engine.port)
            loop.tick()
            back, _, _ = client.recv_batch(timeout_ms=0)
            echoed += back.batch_size
        for _ in range(8 * cycles):
            loop.tick()
            back, _, _ = client.recv_batch(timeout_ms=1)
            echoed += back.batch_size
            if echoed >= cycles * n_pkts:
                break
        loop.flush_sends()
        back, _, _ = client.recv_batch(timeout_ms=5)
        echoed += back.batch_size
        dt = time.perf_counter() - t0
        loop.engine.close()
        client.close()
        return echoed / dt

    # the tunnel's dispatch noise (1.4-2x run spread) can bury the
    # overlap effect in a single pair; interleave runs per mode while
    # the box allows and keep each mode's best (the least-stalled
    # sample)
    sync_pps = pipe_pps = 0.0
    for _ in range(3):
        sync_pps = max(sync_pps, run_mode(False))
        pipe_pps = max(pipe_pps, run_mode(True))
        # cumulative partial print per pair (parent keeps the last line)
        print(json.dumps({"loop_echo_sync_pps": round(sync_pps, 1),
                          "loop_echo_pipelined_pps": round(pipe_pps, 1)}),
              flush=True)
        if time.monotonic() > deadline:
            break


def main():
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    # The watchdog thread fires even when the main thread sits in a
    # native call (a compile on a stalled tunnel) where a SIGALRM-style
    # handler would be deferred until the call returns.
    wd = threading.Timer(BUDGET_S + 50, _watchdog)
    wd.daemon = True
    wd.start()
    try:
        # Headline-first: the tunnel link degrades over process lifetime
        # (observed: the same microbench measures ~4 orders slower after
        # several minutes of heavy sections), so the latency-sensitive
        # device microbenches run first and the host/production-path
        # sections (tunnel-floored anyway) run last.
        section("tpu_pps", 20, 200, tpu_pps)
        section("cpu_pps", 3, 20, cpu_pps)
        section("dense_tick", 3, 25, dense_tick)
        section("mesh_plan", 2, 15, mesh_plan)
        # quick device sections before the compile-heavy sweeps so a
        # cold-cache run still records them (fetch-verified sampling
        # made every section ~10x pricier; warm cache covers the rest)
        section("mixer", 6, 20, mixer)
        section("bridge_mixes", 6, 20, bridge_mixes)
        section("fanout", 8, 30, fanout)
        section("gcm_fanout", 8, 30, gcm_fanout)
        section("aes_cores", 15, 90, aes_core_blocks_per_sec)
        section("table_roundtrip_probe", 25, 90, table_roundtrip_probe)
        section("gcm_sweep", 25, 90, gcm_sweep)
        section("table_path", 25, 75, table_path)
        section("mesh_seam", 20, 65, mesh_seam)
        section("mesh_cpu8", 20, 60, mesh_cpu8)
        # boxes exceed the children's self-bounds (60s/80s + startup):
        # a child must always outlive its own deadline to print
        section("loop_rtt", 20, 65, loop_rtt)
        section("loop_pipelined_gain", 25, 75, loop_pipelined_gain)
    finally:
        emit()


if __name__ == "__main__":
    main()
