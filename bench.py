"""Headline benchmark: SRTP protect throughput at 10k streams on one chip.

Mirrors BASELINE.json's metric ("SRTP packets/sec/chip @ 10k streams") and
config #1's CPU reference: the vs_baseline denominator is a single-thread
OpenSSL SRTP protect (AES-128-CTR + HMAC-SHA1-80 via the `cryptography`
package — the same libcrypto the reference's fastest JNI provider binds).

Prints exactly one JSON line.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


from libjitsi_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

N_STREAMS = 10_240
# Launch size: throughput scales with batch because the round trip is
# dispatch-dominated, not compute-bound (recorded runs: 2048 -> 39M,
# 16384 -> 345M, 65536 -> ~1.1B pps pipelined ~= 0.26 TB/s of packet
# payload, ~2x that in HBM read+write traffic) while sync p99 latency
# stays flat (~0.2-0.3 ms across 2048..65536), so the big launch still
# meets the 2 ms p99 budget with >8x headroom — p99 is measured at THIS
# batch size.  131072+ was rejected: compile time blows up.
BATCH = 65536
# GCM scales with launch like CM (observed 62-92M pps @4096 -> 140-270M
# @16384 -> ~740M @32768): each row carries a 16 KiB GHASH matrix, so
# 32768 rows = 536 MB of tables — fine in 16 GB HBM, and the per-LEG
# grouped kernel (gcm_protect_fanout) removes the per-row matrix cost
# entirely for the SFU fan-out case.
GCM_BATCH = 32768
WIDTH = 192          # capacity; 20 ms Opus packet ≈ 12B header + 160B payload
PKT_LEN = 172
TAG_LEN = 10
ITERS = 20


def tpu_pps() -> tuple[float, float, float, dict]:
    import jax
    import jax.numpy as jnp

    from libjitsi_tpu.transform.srtp import kernel

    rng = np.random.default_rng(3)
    tab_rk = rng.integers(0, 256, (N_STREAMS, 11, 16), dtype=np.uint8)
    tab_mid = rng.integers(0, 2**32, (N_STREAMS, 2, 5), dtype=np.uint64
                           ).astype(np.uint32)
    stream = rng.integers(0, N_STREAMS, BATCH).astype(np.int32)
    data = rng.integers(0, 256, (BATCH, WIDTH), dtype=np.uint8)
    length = np.full(BATCH, PKT_LEN, dtype=np.int32)
    payload_off = np.full(BATCH, 12, dtype=np.int32)
    iv = rng.integers(0, 256, (BATCH, 16), dtype=np.uint8)
    roc = np.zeros(BATCH, dtype=np.uint32)

    import functools

    @functools.partial(jax.jit, donate_argnums=())
    def step(tab_rk, tab_mid, stream, data, length, payload_off, iv, roc):
        return kernel.srtp_protect(
            data, length, payload_off, tab_rk[stream], iv, tab_mid[stream],
            roc, TAG_LEN, True, payload_off_const=12)

    args = [jnp.asarray(a) for a in
            (tab_rk, tab_mid, stream, data, length, payload_off, iv, roc)]
    out = step(*args)
    jax.block_until_ready(out)          # compile
    # The remote-TPU tunnel injects multi-x transport stalls (observed:
    # a single 47 ms RPC stall in an otherwise 0.1 ms/iter pass) that are
    # not chip throughput.  Three estimators, all reported:
    #   sync best pass   — classic wall-clock over 20 blocking iters;
    #   min-latency      — BATCH / fastest single iteration (one clean
    #                      round trip; still *includes* one tunnel RTT,
    #                      so it underestimates the chip);
    #   pipelined        — enqueue 50 independent steps, block once at
    #                      the end: async dispatch overlaps transport
    #                      with execution the way a real deployment runs.
    # The headline value is the pipelined estimator (the one sustained
    # measurement; the others are printed for methodology); p99 is
    # reported for the best sync pass (chip tail) and pooled over every
    # sample (stalls included) so the filtering is visible, not hidden.
    best_sync, best_p99 = 0.0, float("inf")
    min_lat = float("inf")
    all_lat = []
    for _ in range(5):
        lat = []
        t0 = time.perf_counter()
        for _ in range(ITERS):
            t1 = time.perf_counter()
            out = step(*args)
            jax.block_until_ready(out)
            lat.append(time.perf_counter() - t1)
        dt = time.perf_counter() - t0
        all_lat.extend(lat)
        min_lat = min(min_lat, min(lat))
        pps = BATCH * ITERS / dt
        p99_ms = float(np.percentile(np.asarray(lat), 99) * 1e3)
        if pps > best_sync:
            best_sync, best_p99 = pps, p99_ms
    best_pipelined = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(50):
            out = step(*args)
        jax.block_until_ready(out)
        best_pipelined = max(best_pipelined,
                             BATCH * 50 / (time.perf_counter() - t0))
    pooled_p99 = float(np.percentile(np.asarray(all_lat), 99) * 1e3)
    estimators = {"sync_best_pass": best_sync,
                  "min_latency": BATCH / min_lat,
                  "pipelined": best_pipelined}
    # Headline the pipelined estimator: it is a genuinely sustained
    # measurement (50 launches in flight), where min_latency extrapolates
    # one best-case round trip and sync pays a full drain per launch.
    return estimators["pipelined"], best_p99, pooled_p99, estimators


def cpu_pps() -> float:
    """Single-thread OpenSSL SRTP protect (keystream XOR + HMAC-SHA1-80)."""
    import hmac as pyhmac
    import hashlib

    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    rng = np.random.default_rng(4)
    n = 2000
    pkts = [rng.integers(0, 256, PKT_LEN, dtype=np.uint8).tobytes()
            for _ in range(n)]
    keys = [rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
            for _ in range(64)]
    akeys = [rng.integers(0, 256, 20, dtype=np.uint8).tobytes()
             for _ in range(64)]
    iv = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
    t0 = time.perf_counter()
    for i, p in enumerate(pkts):
        enc = Cipher(algorithms.AES(keys[i % 64]), modes.CTR(iv)).encryptor()
        ct = p[:12] + enc.update(p[12:]) + enc.finalize()
        tag = pyhmac.new(akeys[i % 64], ct + b"\x00\x00\x00\x00",
                         hashlib.sha1).digest()[:TAG_LEN]
        _ = ct + tag
    return n / (time.perf_counter() - t0)


def _time_fn(fn, args, iters=10):
    """Best per-iteration time across sync passes, single iterations and
    a pipelined pass (see tpu_pps: tunnel stalls are not chip
    throughput)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            t1 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t1)
        best = min(best, (time.perf_counter() - t0) / iters)
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(3 * iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / (3 * iters))
    return best


def gcm_pps() -> dict:
    """BASELINE config #2's AEAD_AES_128_GCM leg of the cipher sweep.

    `grouped` is the production table path at full BATCH: rows grouped
    by stream (1024 streams here), one GHASH matrix read per stream per
    launch (VERDICT r2 #7) — the per-row form's 16 KiB-per-row matrix
    gather capped it at 32768 rows and 4x below CM.  `per_row` keeps
    the old number (same config as BENCH_r02) for continuity.
    """
    import functools as _ft

    import jax.numpy as jnp

    from libjitsi_tpu.kernels import gcm as G
    from libjitsi_tpu.transform.srtp.context import _gcm_grid

    rng = np.random.default_rng(5)
    out = {}

    b, n_streams = BATCH, 1024
    rks = rng.integers(0, 256, (b, 11, 16), dtype=np.uint8)
    data = rng.integers(0, 256, (b, WIDTH), dtype=np.uint8)
    length = np.full(b, PKT_LEN, np.int32)
    aad = np.full(b, 12, np.int32)
    iv = rng.integers(0, 256, (b, 12), dtype=np.uint8)
    stream = np.repeat(np.arange(n_streams), b // n_streams)
    rng.shuffle(stream)
    grid, _us, inv = _gcm_grid(stream)
    gms_g = rng.integers(0, 2, (grid.shape[0], 128, 128), dtype=np.int8)
    args = [jnp.asarray(x) for x in (data, length, aad, rks, gms_g, iv,
                                     grid, inv)]
    dt = _time_fn(_ft.partial(G.gcm_protect_grouped, aad_const=12), args)
    out["grouped"] = round(b / dt, 1)

    b = GCM_BATCH
    rks = rng.integers(0, 256, (b, 11, 16), dtype=np.uint8)
    gms = rng.integers(0, 2, (b, 128, 128), dtype=np.int8)
    data = rng.integers(0, 256, (b, WIDTH), dtype=np.uint8)
    length = np.full(b, PKT_LEN, np.int32)
    aad = np.full(b, 12, np.int32)
    iv = rng.integers(0, 256, (b, 12), dtype=np.uint8)
    args = [jnp.asarray(x) for x in (data, length, aad, rks, gms, iv)]
    dt = _time_fn(G.gcm_protect, args)
    out["per_row"] = round(b / dt, 1)
    return out


def aes_core_blocks_per_sec(b: int = 65536) -> dict:
    """Provider sweep for the AES core (SURVEY §7 'hard parts'): the
    table/S-box-gather core vs the gather-free bitsliced Boolean circuit
    (kernels/aes_bitsliced.py), plus the Pallas lowering attempt.
    Standalone block-encrypt rate, pipelined.  The bitsliced circuit
    measures ~1.3x the table core standalone; inside the fused SRTP
    kernel (where HMAC dominates) the two are within noise, so 'table'
    stays the default (set LIBJITSI_TPU_AES_CORE=bitsliced to swap)."""
    import jax
    import jax.numpy as jnp

    from libjitsi_tpu.kernels.aes import aes_encrypt_table, \
        expand_keys_batch
    from libjitsi_tpu.kernels.aes_bitsliced import (
        aes_encrypt_bitsliced, aes_encrypt_pallas_bitsliced)

    rng = np.random.default_rng(21)
    rks = expand_keys_batch(rng.integers(0, 256, (b, 16), dtype=np.uint8))
    blocks = rng.integers(0, 256, (b, 16), dtype=np.uint8)
    rksd, blkd = jnp.asarray(rks), jnp.asarray(blocks)
    out = {}
    table = jax.jit(aes_encrypt_table)
    for name, fn in (("xla_table", table),
                     ("xla_bitsliced", aes_encrypt_bitsliced),
                     ("pallas_bitsliced", aes_encrypt_pallas_bitsliced)):
        try:
            o = fn(rksd, blkd)
            jax.block_until_ready(o)
            best = 0.0
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(30):
                    o = fn(rksd, blkd)
                jax.block_until_ready(o)
                best = max(best, b * 30 / (time.perf_counter() - t0))
            out[name] = round(best, 1)
        except Exception as e:   # Mosaic lowering refusal, recorded
            out[name] = f"error: {type(e).__name__}"
    return out


def gcm_fanout_rows_per_sec(packets: int = 128, receivers: int = 512
                            ) -> float:
    """AEAD leg of BASELINE config #5: full-mesh GCM fan-out via the
    grouped kernel (per-LEG GHASH matrices — 16 KiB x receivers, not
    x rows, of key-material traffic).  Measured sweep: 128x256 245M,
    128x512 1.27B, 256x1024 4.3B rows/s — the launch shape matches the
    CM fan-out bench's 128x512 for comparability."""
    import jax.numpy as jnp

    from libjitsi_tpu.kernels import gcm as G

    rng = np.random.default_rng(12)
    rks = rng.integers(0, 256, (receivers, 11, 16), dtype=np.uint8)
    gms = rng.integers(0, 2, (receivers, 128, 128), dtype=np.int8)
    data = rng.integers(0, 256, (packets, WIDTH), dtype=np.uint8)
    length = np.full(packets, PKT_LEN, np.int32)
    iv = rng.integers(0, 256, (receivers, packets, 12), dtype=np.uint8)
    args = [jnp.asarray(x) for x in (data, length, rks, gms, iv)]
    dt = _time_fn(G.gcm_protect_fanout, args)
    return packets * receivers / dt


def mixer_mix_per_sec(n_participants: int = 256) -> float:
    """BASELINE config #3: N-participant 48 kHz mono 20 ms mix-minus."""
    import jax.numpy as jnp

    from libjitsi_tpu.conference.mixer import _mix_jit

    rng = np.random.default_rng(6)
    pcm = jnp.asarray(rng.integers(-8000, 8000, (n_participants, 960))
                      .astype(np.int16))
    active = jnp.ones(n_participants, dtype=bool)
    dt = _time_fn(_mix_jit, (pcm, active))
    return 1.0 / dt


def bridge_mixes_per_sec(conferences: int = 64,
                         participants: int = 64) -> float:
    """Whole-bridge mixing: C conferences of N participants per launch
    (a single conference launch is dispatch-bound; see MixerBridge)."""
    import jax.numpy as jnp

    from libjitsi_tpu.conference.mixer import _mix_many_jit

    rng = np.random.default_rng(8)
    pcm = jnp.asarray(rng.integers(
        -8000, 8000, (conferences, participants, 960)).astype(np.int16))
    active = jnp.ones((conferences, participants), dtype=bool)
    dt = _time_fn(_mix_many_jit, (pcm, active))
    return conferences / dt


def fanout_rows_per_sec(packets: int = 128, receivers: int = 512) -> float:
    """BASELINE config #5 core: per-receiver re-encrypt of a fan-out
    matrix (rows = packets x receivers) in one launch."""
    import functools

    import jax
    import jax.numpy as jnp

    from libjitsi_tpu.transform.srtp import kernel

    rng = np.random.default_rng(7)
    rows = packets * receivers
    tab_rk = rng.integers(0, 256, (receivers, 11, 16), dtype=np.uint8)
    tab_mid = rng.integers(0, 2**32, (receivers, 2, 5), dtype=np.uint64
                           ).astype(np.uint32)
    recv = np.repeat(np.arange(receivers, dtype=np.int32), packets)
    data = rng.integers(0, 256, (rows, WIDTH), dtype=np.uint8)
    length = np.full(rows, PKT_LEN, np.int32)
    off = np.full(rows, 12, np.int32)
    iv = rng.integers(0, 256, (rows, 16), dtype=np.uint8)
    roc = np.zeros(rows, np.uint32)

    # same math as translator._fanout_protect, without buffer donation
    # (donation would invalidate the timed args between iterations)
    @jax.jit
    def step(tab_rk, tab_mid, recv, data, length, off, iv, roc):
        return kernel.srtp_protect(data, length, off, tab_rk[recv], iv,
                                   tab_mid[recv], roc, TAG_LEN, True,
                                   payload_off_const=12)

    args = [jnp.asarray(x) for x in
            (tab_rk, tab_mid, recv, data, length, off, iv, roc)]
    dt = _time_fn(step, args)
    return rows / dt


def table_pps(n_streams: int = N_STREAMS, batch: int = 4096,
              n_batches: int = 9):
    """PRODUCTION-path SRTP: `SrtpStreamTable.protect_rtp/unprotect_rtp`
    with the full host control plane — header parse, chain-index /
    index-estimation, replay window update, size-class bucketing — at
    10k installed streams and mixed packet sizes (the kernel-only bench
    above deliberately excludes all of that).

    Returns (protect_pps, protect_p99_ms, unprotect_pps,
    unprotect_p99_ms, install_streams_per_sec, host_plane_pps,
    transfer_probe_ms, pipelined_pps).  On this box every call crosses
    the axon TPU
    tunnel (~120 ms fixed cost per synchronous transfer, measured by the
    probe); the wall numbers are tunnel-floored, so the host-plane
    ceiling and the probe are reported alongside to keep the
    decomposition visible.  On local PCIe the same transfers are <1 ms.
    """
    from libjitsi_tpu.core.packet import bucket_by_size
    from libjitsi_tpu.core.rtp_math import chain_packet_indices
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    rng = np.random.default_rng(9)
    mks = rng.integers(0, 256, (n_streams, 16), dtype=np.uint8)
    mss = rng.integers(0, 256, (n_streams, 14), dtype=np.uint8)
    t0 = time.perf_counter()
    tx = SrtpStreamTable(capacity=n_streams)
    tx.add_streams(np.arange(n_streams), mks, mss)
    install_rate = n_streams / (time.perf_counter() - t0)
    rx = SrtpStreamTable(capacity=n_streams)
    rx.add_streams(np.arange(n_streams), mks, mss)

    # distinct batches (distinct seqs: replay must accept all), mixed
    # sizes hitting all three width classes: 60% small voice, 30% mid
    # video, 10% near-MTU
    sizes = np.array([100, 400, 950])

    def make_batches(count: int, seq_base: int):
        out = []
        for k in range(count):
            streams = rng.permutation(n_streams)[:batch]
            ln = sizes[rng.choice(3, batch, p=[0.6, 0.3, 0.1])]
            payloads = [rng.integers(0, 256, n, dtype=np.uint8).tobytes()
                        for n in ln]
            out.append(rtp_header.build(
                payloads, [seq_base + k] * batch, [k * 960] * batch,
                (0x10000 + streams).tolist(), [96] * batch,
                stream=streams.tolist()))
        return out

    batches = make_batches(n_batches, 100)

    warm = n_batches // 3                     # first passes pay compiles
    lat_p, lat_u = [], []
    protected = []
    t_all = 0.0
    for k, b in enumerate(batches):
        t1 = time.perf_counter()
        out = tx.protect_rtp(b)
        dt = time.perf_counter() - t1
        protected.append(out)
        if k >= warm:
            lat_p.append(dt)
            t_all += dt
    protect_pps = batch * len(lat_p) / t_all
    t_all = 0.0
    for k, b in enumerate(protected):
        t1 = time.perf_counter()
        out, ok = rx.unprotect_rtp(b)
        dt = time.perf_counter() - t1
        assert bool(np.all(ok)), "bench traffic must authenticate"
        if k >= warm:
            lat_u.append(dt)
            t_all += dt
    unprotect_pps = batch * len(lat_u) / t_all

    # double-buffered production path: protect_rtp_async keeps DEPTH
    # batches in flight (host state commits at dispatch; bytes
    # materialize later), overlapping H2D/compute/D2H across batches —
    # the naive path above drains every batch before the next dispatch
    depth = 3
    more = make_batches(n_batches, 200)
    t1 = time.perf_counter()
    inflight = []
    for b in more:
        inflight.append(tx.protect_rtp_async(b))
        if len(inflight) >= depth:
            inflight.pop(0).result()
    for p in inflight:
        p.result()
    pipelined_pps = batch * n_batches / (time.perf_counter() - t1)

    # host control plane alone (parse, chain index, IV build, bucketing,
    # replay max update) — the part this bench adds over the kernel bench
    b = batches[-1]
    t1 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        hdr = rtp_header.parse(b)
        stream = np.asarray(b.stream, dtype=np.int64)
        idx = chain_packet_indices(stream, hdr.seq, tx.tx_ext)
        _ = bucket_by_size(b)
        _ = tx._cm_iv(tx._salt_rtp[stream], hdr.ssrc, idx)
        np.maximum.at(tx.tx_ext, stream, idx)
    host_plane_pps = batch * reps / (time.perf_counter() - t1)

    # tunnel/PCIe probe: one synchronous H2D of the batch-sized buffer
    import jax
    import jax.numpy as jnp
    probe = np.zeros_like(batches[0].data)
    d = jnp.asarray(probe)
    jax.block_until_ready(d)
    t1 = time.perf_counter()
    for _ in range(3):
        d = jnp.asarray(probe)
        jax.block_until_ready(d)
    transfer_probe_ms = (time.perf_counter() - t1) / 3 * 1e3

    return (protect_pps, float(np.percentile(lat_p, 99) * 1e3),
            unprotect_pps, float(np.percentile(lat_u, 99) * 1e3),
            install_rate, host_plane_pps, transfer_probe_ms,
            pipelined_pps)


def dense_receive_tick_ms(n_streams: int = 10_240) -> float:
    """Host cost of one decode-path tick at 10k streams: dense jitter
    insert+pop plus the batched GCC feed — the plane that used to be
    per-stream Python objects.  Pure host time (no device)."""
    from libjitsi_tpu.bwe.batched import BatchedRemoteBitrateEstimator
    from libjitsi_tpu.rtp.dense_jitter import DenseJitterBank

    jb = DenseJitterBank(capacity=n_streams, depth=16, payload_cap=64)
    bwe = BatchedRemoteBitrateEstimator(capacity=64)
    rng = np.random.default_rng(13)
    sids = np.arange(n_streams)
    tids = sids % 64
    pay = rng.integers(0, 256, (n_streams, 64), dtype=np.uint8)
    best = float("inf")
    for k in range(12):
        now = 5.0 + 0.02 * k
        t0 = time.perf_counter()
        jb.insert_batch(sids, np.full(n_streams, 100 + k),
                        np.full(n_streams, 160 * k), pay,
                        np.full(n_streams, 64), now)
        jb.pop_all(now + 0.001)
        bwe.incoming_batch(tids, np.full(n_streams, now * 1000),
                           np.full(n_streams,
                                   (int(now * (1 << 18)) & 0xFFFFFF)),
                           np.full(n_streams, 172))
        if k >= 2:
            best = min(best, time.perf_counter() - t0)
    bwe.update_estimate(6.0 * 1000)
    return best * 1e3


def loop_pipelined_gain(n_pkts: int = 512, cycles: int = 24):
    """SURVEY §7 step 4's seam, measured: the pipelined MediaLoop
    dispatches the reply protect and flushes it at the top of the next
    tick, so the device launch overlaps the next recv window instead of
    serializing with it.  Same echo workload both ways; returns
    (sync_pps, pipelined_pps)."""
    import libjitsi_tpu
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.io import UdpEngine
    from libjitsi_tpu.io.loop import MediaLoop
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.service.media_stream import StreamRegistry
    from libjitsi_tpu.transform import (SrtpTransformEngine,
                                        TransformEngineChain)
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    mk, ms = bytes(range(16)), bytes(range(30, 44))
    mk2, ms2 = bytes(range(60, 76)), bytes(range(80, 94))
    libjitsi_tpu.stop()
    libjitsi_tpu.init()

    def run_mode(pipelined: bool) -> float:
        reg = StreamRegistry(libjitsi_tpu.configuration_service(),
                             capacity=16)
        rx_tab = SrtpStreamTable(capacity=16)
        rx_tab.add_stream(3, mk, ms)
        tx_tab = SrtpStreamTable(capacity=16)
        tx_tab.add_stream(3, mk2, ms2)
        chain = TransformEngineChain([SrtpTransformEngine(tx_tab,
                                                          rx_tab)])

        def on_media(batch, ok):
            rows = np.nonzero(ok)[0]
            if len(rows) == 0:
                return None
            return PacketBatch(batch.data[rows],
                               np.asarray(batch.length)[rows],
                               batch.stream[rows])

        loop = MediaLoop(UdpEngine(port=0, max_batch=n_pkts + 8), reg,
                         on_media=on_media, chain=chain,
                         recv_window_ms=0, pipelined=pipelined)
        reg.map_ssrc(0xBEEF01, 3)
        c_tx = SrtpStreamTable(capacity=1)
        c_tx.add_stream(0, mk, ms)
        client = UdpEngine(port=0, max_batch=n_pkts + 8)
        # streaming shape: bursts keep flowing without waiting for
        # their echoes, so the pipelined loop holds a dispatched batch
        # in flight across each next tick (the sync loop materializes
        # per tick); echoes drain opportunistically
        echoed = 0
        t0 = time.perf_counter()
        for cyc in range(cycles):
            b = rtp_header.build([b"\xab" * 160] * n_pkts,
                                 list(range(cyc * n_pkts,
                                            (cyc + 1) * n_pkts)),
                                 [cyc * 960] * n_pkts,
                                 [0xBEEF01] * n_pkts, [96] * n_pkts,
                                 stream=[0] * n_pkts)
            client.send_batch(c_tx.protect_rtp(b), "127.0.0.1",
                              loop.engine.port)
            loop.tick()
            back, _, _ = client.recv_batch(timeout_ms=0)
            echoed += back.batch_size
        for _ in range(8 * cycles):
            loop.tick()
            back, _, _ = client.recv_batch(timeout_ms=1)
            echoed += back.batch_size
            if echoed >= cycles * n_pkts:
                break
        loop.flush_sends()
        back, _, _ = client.recv_batch(timeout_ms=5)
        echoed += back.batch_size
        dt = time.perf_counter() - t0
        loop.engine.close()
        client.close()
        return echoed / dt

    # the tunnel's dispatch noise (1.4-2x run spread) can bury the
    # overlap effect in a single pair; interleave three runs per mode
    # and keep each mode's best (max = the least-stalled sample)
    sync_pps = pipe_pps = 0.0
    for _ in range(3):
        sync_pps = max(sync_pps, run_mode(False))
        pipe_pps = max(pipe_pps, run_mode(True))
    return sync_pps, pipe_pps


def loop_rtt(n_pkts: int = 256, cycles: int = 24):
    """End-to-end MediaLoop tick over REAL loopback UDP: client protect →
    send → bridge recv_batch → SSRC demux → unprotect → echo →
    re-protect → send → client recv.  This is SURVEY §3.2/§3.4's hot
    loop (socket→chain→socket), the path the 2 ms p99 budget governs.

    Returns (pps_through_loop, p99_cycle_ms, p50_cycle_ms).  NOTE: on
    this box every device launch crosses the axon TPU tunnel, so the
    cycle time includes 4 tunnel round trips (client protect/unprotect +
    bridge unprotect/protect) — a wildly pessimistic floor vs local PCIe.
    """
    import libjitsi_tpu
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.io import UdpEngine
    from libjitsi_tpu.io.loop import MediaLoop
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.service.media_stream import StreamRegistry
    from libjitsi_tpu.transform import (SrtpTransformEngine,
                                        TransformEngineChain)
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    mk, ms = bytes(range(16)), bytes(range(30, 44))
    mk2, ms2 = bytes(range(60, 76)), bytes(range(80, 94))
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    reg = StreamRegistry(libjitsi_tpu.configuration_service(), capacity=16)
    rx_tab = SrtpStreamTable(capacity=16)
    rx_tab.add_stream(3, mk, ms)
    tx_tab = SrtpStreamTable(capacity=16)
    tx_tab.add_stream(3, mk2, ms2)
    chain = TransformEngineChain([SrtpTransformEngine(tx_tab, rx_tab)])

    def on_media(batch, ok):
        rows = np.nonzero(ok)[0]
        if len(rows) == 0:
            return None
        return PacketBatch(batch.data[rows],
                           np.asarray(batch.length)[rows],
                           batch.stream[rows])

    bridge = MediaLoop(UdpEngine(port=0, max_batch=n_pkts + 8), reg,
                       on_media=on_media, chain=chain, recv_window_ms=0)
    reg.map_ssrc(0xBEEF01, 3)
    c_tx = SrtpStreamTable(capacity=1)
    c_tx.add_stream(0, mk, ms)
    c_rx = SrtpStreamTable(capacity=1)
    c_rx.add_stream(0, mk2, ms2)
    client = UdpEngine(port=0, max_batch=n_pkts + 8)

    lat = []
    done_pkts = 0
    t_all = time.perf_counter()
    for cyc in range(cycles):
        payloads = [b"\xab" * 160] * n_pkts
        b = rtp_header.build(payloads, list(range(cyc * n_pkts,
                                                  (cyc + 1) * n_pkts)),
                             [cyc * 960] * n_pkts, [0xBEEF01] * n_pkts,
                             [96] * n_pkts, stream=[0] * n_pkts)
        t1 = time.perf_counter()
        wire = c_tx.protect_rtp(b)
        client.send_batch(wire, "127.0.0.1", bridge.engine.port)
        got = 0
        back_parts = []
        deadline = time.perf_counter() + 5.0
        while got < n_pkts and time.perf_counter() < deadline:
            bridge.tick()
            back, _, _ = client.recv_batch(timeout_ms=1)
            if back.batch_size:
                back_parts.append(back)
                got += back.batch_size
        for back in back_parts:
            back.stream[:] = 0
            _, ok = c_rx.unprotect_rtp(back)
            done_pkts += int(ok.sum())
        lat.append(time.perf_counter() - t1)
    total = time.perf_counter() - t_all
    warm = len(lat) // 3
    tail = np.asarray(lat[warm:])
    assert done_pkts == cycles * n_pkts, \
        f"loop lost packets: {done_pkts}/{cycles * n_pkts}"
    return (done_pkts / total, float(np.percentile(tail, 99) * 1e3),
            float(np.percentile(tail, 50) * 1e3))


def main():
    # Section order matters: the tunnel link degrades over process
    # lifetime (observed: the same microbench measures ~4 orders slower
    # after several minutes of heavy sections), so the latency-sensitive
    # device microbenches run FIRST and the host/production-path
    # sections (which are tunnel-floored anyway) run last.
    pps, p99_ms, p99_pooled, estimators = tpu_pps()
    base = cpu_pps()
    gcm = gcm_pps()
    gcm_fan = gcm_fanout_rows_per_sec()
    aes_cores = aes_core_blocks_per_sec()
    mix = mixer_mix_per_sec()
    bridge = bridge_mixes_per_sec()
    fanout = fanout_rows_per_sec()
    (tab_pps, tab_p99, untab_pps, untab_p99, install_rate,
     host_plane_pps, transfer_probe_ms, tab_pipelined_pps) = table_pps()
    lp_pps, lp_p99, lp_p50 = loop_rtt()
    lp_sync, lp_pipe = loop_pipelined_gain()
    print(json.dumps({
        "metric": "srtp_protect_pps_at_10k_streams",
        "value": round(pps, 1),
        "unit": "packets/sec/chip",
        "vs_baseline": round(pps / base, 3),
        "extra": {"batch": BATCH, "pkt_len": PKT_LEN, "p99_batch_ms":
                  round(p99_ms, 3),
                  "p99_ms_pooled_all_passes": round(p99_pooled, 3),
                  "estimators_pps": {k: round(v, 1)
                                     for k, v in estimators.items()},
                  "cpu_openssl_pps": round(base, 1),
                  "table_protect_pps": round(tab_pps, 1),
                  "table_protect_pps_pipelined":
                      round(tab_pipelined_pps, 1),
                  "table_protect_p99_batch_ms": round(tab_p99, 3),
                  "table_unprotect_pps": round(untab_pps, 1),
                  "table_unprotect_p99_batch_ms": round(untab_p99, 3),
                  "install_streams_per_sec": round(install_rate, 1),
                  "table_host_plane_pps": round(host_plane_pps, 1),
                  "dense_receive_tick_ms_10k":
                      round(dense_receive_tick_ms(), 3),
                  "h2d_transfer_probe_ms": round(transfer_probe_ms, 3),
                  "loop_udp_echo_pps": round(lp_pps, 1),
                  "loop_udp_cycle_p99_ms": round(lp_p99, 3),
                  "loop_udp_cycle_p50_ms": round(lp_p50, 3),
                  "loop_echo_sync_pps": round(lp_sync, 1),
                  "loop_echo_pipelined_pps": round(lp_pipe, 1),
                  "gcm_pps": gcm["grouped"],
                  "gcm_pps_per_row": gcm["per_row"],
                  "gcm_fanout_rows_per_sec": round(gcm_fan, 1),
                  "aes_core_blocks_per_sec": aes_cores,
                  "mix_256p_per_sec": round(mix, 1),
                  "bridge_64conf_64p_mixes_per_sec": round(bridge, 1),
                  "sfu_fanout_rows_per_sec": round(fanout, 1)},
    }))


if __name__ == "__main__":
    main()
