"""Slow twin of scripts/chaos_soak.py: the same live-fault soak loop
(in-chain loss/corrupt/reorder/duplicate + Gilbert–Elliott bursts,
mid-run kill + checkpoint recovery) in a short configuration, asserting
every invariant in the report."""

import importlib.util
import os

import pytest

_SCRIPT = os.path.join(os.path.dirname(__file__), os.pardir,
                       "scripts", "chaos_soak.py")


def _load_soak():
    spec = importlib.util.spec_from_file_location("chaos_soak", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_chaos_soak_invariants(tmp_path):
    soak = _load_soak()
    report = soak.run_soak(ticks=50, participants=3, loss=0.08,
                           corrupt=0.05, reorder=0.1, duplicate=0.03,
                           burst=(0.03, 0.3), kill_frac=0.5, seed=7,
                           ckpt_path=str(tmp_path / "soak.ckpt"),
                           verbose=False)
    failed = {k: v for k, v in report.items()
              if k.startswith("ok_") and not v}
    assert not failed, (failed, report)
    assert report["fault_dropped"] > 0
    assert report["checkpoints_written"] >= 1
