"""Tracing/profiling hooks (SURVEY §5 aux): jax trace capture, timeline
annotations, device memory stats."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libjitsi_tpu.utils import profiling


@pytest.mark.slow   # jax profiler start/stop serializes a full trace
def test_trace_captures_device_work(tmp_path):
    d = str(tmp_path / "trace")
    with profiling.trace(d) as logdir:
        with profiling.annotate("test-phase"):
            x = jnp.asarray(np.arange(1024, dtype=np.float32))
            jax.block_until_ready(jnp.dot(x, x))
    files = glob.glob(os.path.join(logdir, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace artifacts"


def test_annotate_without_trace_is_noop():
    """Fast twin of the trace test: the annotation context must be
    transparent when no trace is active (the hot path wears these
    markers permanently; they may cost nothing outside a capture)."""
    with profiling.annotate("fast-twin"):
        x = jnp.asarray(np.arange(16, dtype=np.float32))
        jax.block_until_ready(x + 1)
    with profiling.annotate("outer"), profiling.annotate("inner"):
        pass


def test_device_memory_stats_shape():
    info = profiling.device_memory()
    assert "device" in info and "bytes_in_use" in info
