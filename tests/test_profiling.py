"""Tracing/profiling hooks (SURVEY §5 aux): jax trace capture, timeline
annotations, device memory stats."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np

from libjitsi_tpu.utils import profiling


def test_trace_captures_device_work(tmp_path):
    d = str(tmp_path / "trace")
    with profiling.trace(d) as logdir:
        with profiling.annotate("test-phase"):
            x = jnp.asarray(np.arange(1024, dtype=np.float32))
            jax.block_until_ready(jnp.dot(x, x))
    files = glob.glob(os.path.join(logdir, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace artifacts"


def test_device_memory_stats_shape():
    info = profiling.device_memory()
    assert "device" in info and "bytes_in_use" in info
