"""jitlint: the four checkers against seeded true/false positives, the
pragma + baseline machinery, and the real CLI against the real tree
(the gate itself is tier-1-tested).

Every TP fixture is drawn from a failure class this repo actually hit:
seq-wrap (PR 2: jitter buffer / lookup_nack / build_nack), host-sync
(the ~100 ms scalar-fetch floor in bench.py), secret-dependent lookup
(the reason kernels/aes_bitsliced.py exists), counter drift (the
recovery-ladder counters of PR 2).
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from libjitsi_tpu.analysis import baseline as baseline_mod
from libjitsi_tpu.analysis.checkers.drift import (check_metrics_drift,
                                                  check_snapshot_drift)
from libjitsi_tpu.analysis.checkers.hotalloc import check_hotpath_alloc
from libjitsi_tpu.analysis.checkers.hotpath import check_hotpath_purity
from libjitsi_tpu.analysis.checkers.rtpmod16 import check_rtp_mod16
from libjitsi_tpu.analysis.checkers.secrets import check_secret_taint
from libjitsi_tpu.analysis.core import FileContext
from libjitsi_tpu.analysis.driver import run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "libjitsi_tpu")


def ctx_of(src: str, relpath: str = "libjitsi_tpu/somefile.py"):
    return FileContext(relpath, relpath, textwrap.dedent(src))


def rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------- hotpath-purity

def test_hotpath_item_and_int_fire():
    """Seeded from the host-sync class: one .item() in a jitted path
    re-introduces the ~100 ms scalar-fetch floor."""
    src = """
    import jax

    @jax.jit
    def f(x):
        n = x.sum().item()
        m = int(x[0])
        return n + m
    """
    found = check_hotpath_purity(ctx_of(src))
    assert len(found) == 2
    assert all(f.rule == "hotpath-purity" for f in found)
    assert "host sync" in found[0].message


def test_hotpath_python_branch_on_tracer_fires():
    src = """
    import jax

    @jax.jit
    def f(x):
        if x > 0:
            return x
        while x < 3:
            x = x + 1
        return -x
    """
    found = check_hotpath_purity(ctx_of(src))
    assert len(found) == 2
    assert "tracer-derived" in found[0].message


def test_hotpath_partial_jit_and_static_argnames():
    """static_argnames are Python values at trace time: int() on them
    must NOT fire; the traced arg still must."""
    src = """
    import functools, jax

    @functools.partial(jax.jit, static_argnames=("n",))
    def f(x, n):
        k = int(n)          # static: fine
        j = int(x)          # traced: host sync
        return k + j
    """
    found = check_hotpath_purity(ctx_of(src))
    assert len(found) == 1
    assert "`int()`" in found[0].message


def test_hotpath_lax_cond_and_none_checks_do_not_fire():
    """lax.cond on tracers is THE sanctioned branch; `is None` tests
    are pytree-structure checks; shape reads are static."""
    src = """
    import jax
    from jax import lax
    import jax.numpy as jnp

    @jax.jit
    def f(x, aux=None):
        y = lax.cond(x[0] > 0, lambda v: v, lambda v: -v, x)
        if aux is None:
            y = y + 1
        if x.shape[0] > 4:
            y = y * 2
        if len(x) > 2:
            y = y - 1
        return jnp.where(x > 0, y, -y)
    """
    assert check_hotpath_purity(ctx_of(src)) == []


def test_hotpath_np_asarray_and_nonzero_fire():
    src = """
    import jax
    import numpy as np
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        h = np.asarray(x)
        r = jnp.nonzero(x)
        ok = jnp.nonzero(x, size=4)      # static size: fine
        return h, r, ok
    """
    found = check_hotpath_purity(ctx_of(src))
    assert len(found) == 2


def test_hotpath_call_wrapped_jit_detected():
    """mesh-style `jax.jit(shard_map(fn, ...))` wrapping."""
    src = """
    import jax

    def inner(x):
        return x.item()

    wrapped = jax.jit(jax.shard_map(inner, mesh=None))
    """
    found = check_hotpath_purity(ctx_of(src))
    assert len(found) == 1


def test_hotpath_unjitted_host_code_is_free():
    src = """
    def host(x):
        if x > 0:
            return int(x)
        return x.item()
    """
    assert check_hotpath_purity(ctx_of(src)) == []


# -------------------------------------------------------- secret-taint

def test_secret_branch_and_table_lookup_fire():
    """Seeded from the secret-dependent-lookup class the bitsliced AES
    core eliminates."""
    src = """
    SBOX = list(range(256))

    def leak(key, data):
        if key[0] == 0x80:            # secret-dependent branch
            return data
        return SBOX[key[1]]           # secret-indexed lookup
    """
    found = check_secret_taint(ctx_of(src, "libjitsi_tpu/kernels/fx.py"))
    rules = rules_of(found)
    assert rules.count("secret-taint") == len(found)
    msgs = " | ".join(f.message for f in found)
    assert "secret-dependent branch" in msgs
    assert "secret-indexed lookup" in msgs


def test_secret_taint_propagates_through_assignment():
    src = """
    def leak(master_key):
        derived = master_key[:16]
        t = derived
        if t == b"16-byte-constant":
            return 1
        return 0
    """
    found = check_secret_taint(ctx_of(src, "libjitsi_tpu/kernels/fx.py"))
    assert len(found) == 1


def test_secret_structure_checks_do_not_fire():
    """len()/shape/dtype/`is None` are about structure, not contents —
    kdf.py validates key lengths everywhere and must stay clean."""
    src = """
    def derive(master_key, salt=None):
        if len(master_key) != 16:
            raise ValueError("bad key size")
        if salt is None:
            salt = b"\\x00" * 14
        if master_key is None:
            return None
        return master_key + salt
    """
    assert check_secret_taint(
        ctx_of(src, "libjitsi_tpu/transform/srtp/fx.py")) == []


def test_secret_vectorized_compare_does_not_fire():
    """`ok = tags == expected` is the constant-time idiom — a verdict
    array, not a branch."""
    src = """
    import numpy as np

    def verify(tags, expected_tags):
        ok = tags == expected_tags
        return np.where(ok, 1, 0)
    """
    assert check_secret_taint(
        ctx_of(src, "libjitsi_tpu/kernels/fx.py")) == []


def test_secret_scope_is_kernels_and_srtp_only():
    src = """
    def host(key):
        if key[0]:
            return 1
        return 0
    """
    assert check_secret_taint(ctx_of(src, "libjitsi_tpu/service/fx.py")) == []
    assert len(check_secret_taint(
        ctx_of(src, "libjitsi_tpu/kernels/fx.py"))) == 1


# ----------------------------------------------------------- rtp-mod16

def test_mod16_raw_compare_fires():
    """Seeded from the PR 2 seq-wrap class: raw `<` on seqs misorders
    across 65535->0 (the jitter-buffer / lookup_nack bug)."""
    src = """
    def newest(a_seq, b_seq):
        if a_seq < b_seq:
            return b_seq
        return a_seq
    """
    found = check_rtp_mod16(ctx_of(src))
    assert len(found) == 1
    assert "wrap" in found[0].message


def test_mod16_unmasked_arith_and_augassign_fire():
    src = """
    class Tx:
        def bump(self, n):
            self._tx_seq += n
            nxt = self.base_seq + 1
            return nxt
    """
    found = check_rtp_mod16(ctx_of(src))
    assert len(found) == 2


def test_mod16_masked_and_helper_forms_do_not_fire():
    src = """
    from libjitsi_tpu.core.rtp_math import seq_delta, is_newer_seq

    def ok(seq, last_seq, roc):
        a = (seq + 1) & 0xFFFF
        b = (seq - last_seq) % 65536
        d = seq_delta(seq + 1, last_seq)
        n = is_newer_seq(seq, last_seq)
        hi = seq >> 8
        lo = seq & 0xFF
        if seq >= 0:                      # sentinel compare
            pass
        new_roc = (roc + 1) & 0xFFFFFFFF
        return a, b, d, n, hi, lo, new_roc
    """
    assert check_rtp_mod16(ctx_of(src)) == []


def test_mod16_seq_delta_internals_do_not_fire():
    """The helper module itself subtracts raw seqs by design."""
    path = os.path.join(PKG, "core", "rtp_math.py")
    with open(path) as fh:
        ctx = FileContext(path, "libjitsi_tpu/core/rtp_math.py", fh.read())
    assert check_rtp_mod16(ctx) == []


def test_mod16_ext_counters_exempt():
    """`*_ext` names are 64-bit extended counters — raw math is the
    point (SeqNumUnwrapper output, RFC 3711 indices)."""
    src = """
    def unwrapped(next_seq_ext, k):
        top = next_seq_ext + k
        next_seq_ext += 1
        return top
    """
    assert check_rtp_mod16(ctx_of(src)) == []


def test_mod16_slice_and_range_and_max_fire():
    src = """
    import numpy as np

    def walk(buf, start_seq, end_seq, seqs):
        a = buf[start_seq:end_seq]
        for s in range(start_seq, end_seq):
            pass
        hi = max(start_seq, end_seq)
        return a, hi
    """
    found = check_rtp_mod16(ctx_of(src))
    assert len(found) == 3


# --------------------------------------------------------------- drift

def test_drift_snapshot_missing_field_fires():
    """Seeded from the crash-recover class: a field outside
    _SNAP_FIELDS restores as stale zeros."""
    src = """
    import numpy as np
    from libjitsi_tpu.utils.checkpoint import ArraySnapshotMixin

    class Bank(ArraySnapshotMixin):
        _SNAP_FIELDS = ("a",)

        def __init__(self):
            self.a = np.zeros(4)
            self.forgotten = np.zeros(4)
    """
    found = check_snapshot_drift(ctx_of(src))
    assert len(found) == 1
    assert "forgotten" in found[0].message


def test_drift_snapshot_covered_and_stale_entry():
    src = """
    import numpy as np
    from libjitsi_tpu.utils.checkpoint import ArraySnapshotMixin

    class Bank(ArraySnapshotMixin):
        _SNAP_FIELDS = ("a", "ghost")

        def __init__(self):
            self.a = np.zeros(4)
    """
    found = check_snapshot_drift(ctx_of(src))
    assert len(found) == 1
    assert "ghost" in found[0].message


def test_drift_metrics_partial_coverage_fires():
    """Seeded from the recovery-ladder counters: a class exporting SOME
    counters that silently grew another one."""
    src = """
    class Recovery:
        def __init__(self):
            self.nacks_sent = 0
            self.rtx_cache_miss = 0

        def work(self):
            self.nacks_sent += 1
            self.rtx_cache_miss += 1

        def register_metrics(self, registry):
            registry.register_counters(self, (
                ("nacks_sent", "lost seqs NACKed"),
            ), prefix="r")
    """
    ctx = ctx_of(src)
    found = check_metrics_drift({ctx.relpath: ctx})
    assert len(found) == 1
    assert "rtx_cache_miss" in found[0].message


def test_drift_metrics_full_coverage_and_unregistered_class_clean():
    src = """
    class Covered:
        def __init__(self):
            self.frames_sent = 0

        def work(self):
            self.frames_sent += 1

        def register_metrics(self, registry):
            registry.register_counters(self, ("frames_sent",))

    class Internal:
        def __init__(self):
            self.cache_miss = 0

        def work(self):
            self.cache_miss += 1
    """
    ctx = ctx_of(src)
    assert check_metrics_drift({ctx.relpath: ctx}) == []


def test_drift_metrics_dangling_registration_fires():
    src = """
    class R:
        def __init__(self):
            self.hits_count = 0

        def work(self):
            self.hits_count += 1

        def register_metrics(self, registry):
            registry.register_counters(self, (
                ("hits_count", "ok"),
                ("hits_cuont", "typo"),
            ))
    """
    ctx = ctx_of(src)
    found = check_metrics_drift({ctx.relpath: ctx})
    assert any("hits_cuont" in f.message for f in found)


def test_drift_trunk_counters_partial_coverage_fires():
    """Seeded from the cascade trunk (mesh/cascade.py): a relay class
    that grows a recovery counter without exporting it — the failover
    dashboard would silently under-report trunk RTX."""
    src = """
    class Relay:
        def __init__(self):
            self.relay_frames_total = 0
            self.rtx_served_total = 0
            self.plc_fallthrough_total = 0

        def relay(self):
            self.relay_frames_total += 1

        def serve_nack(self):
            self.rtx_served_total += 1

        def expire(self):
            self.plc_fallthrough_total += 1

        def register_metrics(self, registry):
            registry.register_counters(self, (
                ("relay_frames_total", "frames relayed"),
                ("plc_fallthrough_total", "losses conceded to PLC"),
            ), prefix="trunk")
    """
    ctx = ctx_of(src)
    found = check_metrics_drift({ctx.relpath: ctx})
    assert len(found) == 1
    assert "rtx_served_total" in found[0].message


def test_drift_trunk_counters_full_coverage_clean():
    """The same relay with every counter registered (the shape
    mesh/cascade.py actually ships) must not fire."""
    src = """
    class Relay:
        def __init__(self):
            self.relay_frames_total = 0
            self.rtx_served_total = 0

        def relay(self):
            self.relay_frames_total += 1

        def serve_nack(self):
            self.rtx_served_total += 1

        def register_metrics(self, registry):
            registry.register_counters(self, (
                ("relay_frames_total", "frames relayed"),
                ("rtx_served_total", "RTX served from cache"),
            ), prefix="trunk")
    """
    ctx = ctx_of(src)
    assert check_metrics_drift({ctx.relpath: ctx}) == []


def test_drift_slospec_unregistered_metric_fires():
    """An SloSpec naming a family no registration defines burns
    against a permanently-absent signal — the SLO can never fire."""
    src = """
    from libjitsi_tpu.utils.slo import SloSpec

    SPECS = [
        SloSpec("ghost", objective=0.99,
                bad_metric="never_registered_bad",
                total_metric="bridge_forwarded"),
    ]

    def register(registry):
        registry.register_scalar("bridge_forwarded", lambda: 0,
                                 kind="counter")
    """
    ctx = ctx_of(src)
    found = check_metrics_drift({ctx.relpath: ctx})
    assert len(found) == 1
    assert "never_registered_bad" in found[0].message
    assert "ghost" in found[0].message


def test_drift_slospec_exact_and_suffix_matched_refs_clean():
    """Refs resolved by an exact constant registration AND by a
    register_counters suffix under a call-site prefix are both clean
    (prefix-parameterized names must not false-positive)."""
    src = """
    from libjitsi_tpu.utils.slo import SloSpec

    SPECS = [
        SloSpec("loss", objective=0.999,
                bad_metric="recovery_nacks_abandoned",
                total_metric="bridge_forwarded"),
    ]

    class Recovery:
        def __init__(self):
            self.nacks_abandoned = 0

        def work(self):
            self.nacks_abandoned += 1

        def register_metrics(self, registry):
            registry.register_counters(self, (
                ("nacks_abandoned", "deadline passed"),
            ), prefix="recovery")

    def register(registry):
        registry.register_scalar("bridge_forwarded", lambda: 0,
                                 kind="counter")
    """
    ctx = ctx_of(src)
    assert check_metrics_drift({ctx.relpath: ctx}) == []


def test_drift_exemplar_histogram_never_fed_fires():
    """exemplars=True reserves exemplar slots; if no observe call ever
    passes exemplar=, every OpenMetrics scrape ships them empty."""
    src = """
    class Loop:
        def __init__(self, registry):
            self.journey = registry.histogram(
                "packet_journey_seconds", (0.001, 0.01),
                exemplars=True)

        def on_egress(self, dt):
            self.journey.observe(dt)
    """
    ctx = ctx_of(src)
    found = check_metrics_drift({ctx.relpath: ctx})
    assert len(found) == 1
    assert "exemplar" in found[0].message
    assert "journey" in found[0].message


def test_drift_exemplar_histogram_fed_anywhere_clean():
    """The exemplar feed may live in another file — the check is over
    the whole-tree index, not per file."""
    src_def = """
    class Loop:
        def __init__(self, registry):
            self.journey = registry.histogram(
                "packet_journey_seconds", (0.001, 0.01),
                exemplars=True)
    """
    src_use = """
    class Egress:
        def flush(self, loop, dt, trace):
            loop.journey.observe(
                dt, exemplar={"trace_id": str(trace)})
    """
    a = ctx_of(src_def, relpath="libjitsi_tpu/io/loop.py")
    b = ctx_of(src_use, relpath="libjitsi_tpu/service/x.py")
    assert check_metrics_drift({a.relpath: a, b.relpath: b}) == []


def test_drift_exemplar_histogram_vec_never_fed_fires():
    """A hop-labeled HistogramVec created with exemplars=True whose
    children only ever observe WITHOUT exemplar= ships empty exemplar
    slots on every label — same bug as the plain-histogram case, one
    label axis over."""
    src = """
    class Sup:
        def __init__(self, registry):
            self.journey_vec = registry.histogram_vec(
                "packet_journey_seconds", (0.001, 0.01), "hop",
                exemplars=True)

        def note_hop(self, hop, dt):
            self.journey_vec.labels(hop).observe(dt)
    """
    ctx = ctx_of(src)
    found = check_metrics_drift({ctx.relpath: ctx})
    assert len(found) == 1
    assert "exemplar" in found[0].message
    assert "journey_vec" in found[0].message


def test_drift_exemplar_histogram_vec_chained_labels_feed_clean():
    """The chained `vec.labels(hop).observe(..., exemplar=...)` idiom
    feeds the vec's exemplar slots — must not false-positive; the same
    for a bound child (`h = vec.labels("local")`) fed through its
    local name."""
    src = """
    class Sup:
        def __init__(self, registry):
            self.journey_vec = registry.histogram_vec(
                "packet_journey_seconds", (0.001, 0.01), "hop",
                exemplars=True)
            self.local_hist = self.journey_vec.labels("local")

        def note_hop(self, hop, dt, trace):
            self.journey_vec.labels(hop).observe(
                dt, exemplar={"trace_id": str(trace)})
    """
    ctx = ctx_of(src)
    assert check_metrics_drift({ctx.relpath: ctx}) == []


def test_drift_exemplar_vec_fed_via_bound_child_alias_clean():
    """A vec fed ONLY through a bound child histogram
    (`h = vec.labels(x)` then `h.observe(..., exemplar=...)`) is fed —
    the labels() alias edge credits the parent vec."""
    src = """
    class Loop:
        def __init__(self, registry):
            self.journey_vec = registry.histogram_vec(
                "packet_journey_seconds", (0.001, 0.01), "hop",
                exemplars=True)
            self.journey_hist = self.journey_vec.labels("local")

        def on_egress(self, dt, trace):
            self.journey_hist.observe(
                dt, exemplar={"trace_id": str(trace)})
    """
    ctx = ctx_of(src)
    assert check_metrics_drift({ctx.relpath: ctx}) == []


def test_drift_histogram_observed_but_never_registered_fires():
    """A Histogram constructed and fed but never handed to the
    registry records distributions nobody can scrape."""
    src = """
    from libjitsi_tpu.utils.metrics import Histogram

    class Bank:
        def __init__(self):
            self.jitter_hist = Histogram((0.01, 0.1))

        def tick(self, vals):
            self.jitter_hist.observe_array(vals)
    """
    ctx = ctx_of(src)
    found = check_metrics_drift({ctx.relpath: ctx})
    assert len(found) == 1
    assert "jitter_hist" in found[0].message
    assert "never registered" in found[0].message


def test_drift_histogram_registered_forms_are_clean():
    """Both registration idioms clear the check — an explicit
    register_histogram (even in ANOTHER file) and the
    registry.histogram factory, which registers on creation.  An
    `.observe()` on a non-histogram attr (Watchdog-style) is out of
    scope entirely."""
    src = """
    from libjitsi_tpu.utils.metrics import Histogram

    class Bank:
        def __init__(self):
            self.jitter_hist = Histogram((0.01, 0.1))

        def tick(self, vals):
            self.jitter_hist.observe_array(vals)
    """
    reg = """
    def wire(bank, registry):
        registry.register_histogram("jitter", bank.jitter_hist)
    """
    factory = """
    class Loop:
        def __init__(self, registry):
            self.size_hist = registry.histogram("sizes", (64, 1500))
            self.watchdog = object()

        def tick(self, lens):
            self.size_hist.observe_array(lens)
            self.watchdog.observe(0.1)
    """
    c1, c2 = ctx_of(src), ctx_of(reg, "libjitsi_tpu/other.py")
    assert check_metrics_drift({c1.relpath: c1, c2.relpath: c2}) == []
    c3 = ctx_of(factory, "libjitsi_tpu/loop.py")
    assert check_metrics_drift({c3.relpath: c3}) == []


def test_drift_undeclared_admit_reason_fires():
    """A refusal literal outside the ADMIT_REASONS tuple is an untyped
    reason — the soak gates' `refused <= ADMIT_REASONS` assertions and
    the admit_rejected{reason=...} label set never heard of it.  The
    cross-file shape mirrors the real tree: the tuple lives in
    lifecycle, the refusal site in the supervisor."""
    decl = """
    ADMIT_REASONS = ("capacity", "fast_burn", "trunk_down")
    """
    refuse = """
    class Supervisor:
        def admission_decision(self):
            if self.burning:
                return False, "fast_burn"
            if self.haunted:
                return False, "mystery"
            return True, "ok"
    """
    c1 = ctx_of(decl, "libjitsi_tpu/service/lifecycle.py")
    c2 = ctx_of(refuse, "libjitsi_tpu/service/supervisor.py")
    found = check_metrics_drift({c1.relpath: c1, c2.relpath: c2})
    assert len(found) == 1
    assert "mystery" in found[0].message
    assert "ADMIT_REASONS" in found[0].message
    assert found[0].path == "libjitsi_tpu/service/supervisor.py"


def test_drift_declared_admit_reasons_are_clean():
    """Declared refusal literals clear the check in both shapes — the
    `(False, "reason")` pair and the bare-string `admit_reason` form —
    and the `"ok"` accept token is never read as a reason.  A tree
    with no ADMIT_REASONS declaration at all is out of scope (fixture
    trees without an admission plane)."""
    decl = """
    ADMIT_REASONS = ("capacity", "fast_burn", "trunk_down",
                     "trunk_backlog")
    """
    refuse = """
    class Supervisor:
        def admission_decision(self):
            if self.burning:
                return False, "fast_burn"
            return True, "ok"

    class Trunk:
        def admit_reason(self):
            if self.state != "up":
                return "trunk_down"
            if self.backlog:
                return "trunk_backlog"
            return None
    """
    c1 = ctx_of(decl, "libjitsi_tpu/service/lifecycle.py")
    c2 = ctx_of(refuse, "libjitsi_tpu/service/supervisor.py")
    assert check_metrics_drift({c1.relpath: c1, c2.relpath: c2}) == []
    # no declaration anywhere -> the refusal site alone is out of scope
    assert check_metrics_drift({c2.relpath: c2}) == []


def test_drift_capacity_forecast_without_families_fires():
    """Declaring the `capacity_forecast` reason contracts the tree to
    export the capacity_* families — a forecast that refuses joins
    with no scrapeable headroom explanation is exactly the silent
    wiring bug the drift rule exists for."""
    decl = """
    ADMIT_REASONS = ("capacity", "capacity_forecast")
    """
    ctx = ctx_of(decl, "libjitsi_tpu/service/lifecycle.py")
    found = check_metrics_drift({ctx.relpath: ctx})
    fams = {f.message.split("`")[3] for f in found}
    assert fams == {"capacity_headroom_users", "capacity_bottleneck",
                    "capacity_estimate_confidence",
                    "capacity_forecast_refusals"}


def test_drift_capacity_forecast_with_families_clean():
    """The real wiring — CapacityModel registering all four families
    (in another file, like utils/capacity.py does) — clears the
    contract."""
    decl = """
    ADMIT_REASONS = ("capacity", "capacity_forecast")
    """
    model = """
    class CapacityModel:
        def register_metrics(self, registry):
            registry.register_scalar(
                "capacity_headroom_users", lambda: self.headroom)
            registry.register_multi(
                "capacity_bottleneck", self._bottleneck_samples)
            registry.register_scalar(
                "capacity_estimate_confidence", self.confidence)
            registry.register_scalar(
                "capacity_forecast_refusals",
                lambda: self.forecast_refusals)
    """
    c1 = ctx_of(decl, "libjitsi_tpu/service/lifecycle.py")
    c2 = ctx_of(model, "libjitsi_tpu/utils/capacity.py")
    assert check_metrics_drift({c1.relpath: c1, c2.relpath: c2}) == []


def _perf_tree(tmp_path, baseline_keys, scenario_ids):
    """Fake repo: PERF_BASELINE.json + scripts/perf_gate.py + one
    indexed file whose path anchors the disk walk-up."""
    tmp_path.joinpath("PERF_BASELINE.json").write_text(json.dumps(
        {"_meta": {"git": "0123abc"}, **{k: {"value": 1.0}
                                         for k in baseline_keys}}))
    sdir = tmp_path / "scripts"
    sdir.mkdir()
    body = "\n".join(f'def _s{i}():\n    return 1.0'
                     for i in range(len(scenario_ids)))
    entries = ", ".join(f'"{sid}": _s{i}'
                        for i, sid in enumerate(scenario_ids))
    sdir.joinpath("perf_gate.py").write_text(
        body + "\nSCENARIOS = {" + entries + "}\n")
    pkg = tmp_path / "libjitsi_tpu"
    pkg.mkdir()
    mod = pkg / "mod.py"
    mod.write_text("x = 1\n")
    ctx = FileContext(str(mod), "libjitsi_tpu/mod.py", "x = 1\n")
    return {ctx.relpath: ctx}


def test_drift_perf_baseline_stale_and_ungated_fire(tmp_path):
    """Both directions in one tree: a baseline key no scenario backs
    (the gate never compares it) AND a scenario with no baseline entry
    (free to regress forever)."""
    index = _perf_tree(tmp_path, baseline_keys={"old_pps", "loop_x"},
                       scenario_ids={"loop_x", "new_y"})
    found = [f for f in check_metrics_drift(index)
             if f.path == "PERF_BASELINE.json"]
    msgs = "\n".join(f.message for f in found)
    assert len(found) == 2
    assert "`old_pps` matches no perf_gate scenario" in msgs
    assert "`new_y` has no PERF_BASELINE.json entry" in msgs
    assert all(f.rule == "drift" for f in found)


def test_drift_perf_baseline_in_sync_is_clean(tmp_path):
    """Matching key sets (plus the ignored _meta) produce nothing; a
    corrupt baseline is a single loud finding, not a crash."""
    index = _perf_tree(tmp_path, baseline_keys={"loop_x", "prot_y"},
                       scenario_ids={"loop_x", "prot_y"})
    assert [f for f in check_metrics_drift(index)
            if f.path == "PERF_BASELINE.json"] == []
    tmp_path.joinpath("PERF_BASELINE.json").write_text("{nope")
    found = [f for f in check_metrics_drift(index)
             if f.path == "PERF_BASELINE.json"]
    assert len(found) == 1 and "not valid JSON" in found[0].message


def test_drift_perf_baseline_pure_helper_and_real_files_agree():
    """check_perf_baseline is a set comparison; and the REAL checked-in
    baseline must match the REAL gate script right now."""
    from libjitsi_tpu.analysis.checkers.drift import (
        _perf_gate_scenario_ids, check_perf_baseline)

    assert check_perf_baseline({"a"}, {"a"}) == []
    msgs = check_perf_baseline({"a", "stale"}, {"a", "ungated"})
    assert len(msgs) == 2
    real_ids = _perf_gate_scenario_ids(
        os.path.join(REPO, "scripts", "perf_gate.py"))
    with open(os.path.join(REPO, "PERF_BASELINE.json")) as fh:
        real_keys = {k for k in json.load(fh) if not k.startswith("_")}
    assert real_ids, "SCENARIOS literal not found in perf_gate.py"
    assert check_perf_baseline(real_keys, real_ids) == []


def test_drift_baseline_meta_git_must_be_a_hash(tmp_path):
    """A baseline whose _meta.git is not a commit hash (the PR 11
    failure class: `--write-baseline` once left a stale hand-edited
    stamp) fires exactly one finding; a real hash is clean."""
    from libjitsi_tpu.analysis.checkers.drift import check_baseline_meta

    assert check_baseline_meta({"git": "0123abc"}) == []
    assert check_baseline_meta({"git": "c041577" + "0" * 33}) == []
    for bad in ({"git": "unknown"}, {"git": ""}, {}, None,
                {"git": "v1.2.3"}, {"git": "0123ABC"}):
        msgs = check_baseline_meta(bad)
        assert len(msgs) == 1 and "_meta.git" in msgs[0]
    # end to end through the walk-up: the fixture tree with a mangled
    # stamp yields the finding on PERF_BASELINE.json
    index = _perf_tree(tmp_path, baseline_keys={"loop_x"},
                       scenario_ids={"loop_x"})
    doc = json.loads(tmp_path.joinpath("PERF_BASELINE.json").read_text())
    doc["_meta"]["git"] = "unknown"
    tmp_path.joinpath("PERF_BASELINE.json").write_text(json.dumps(doc))
    found = [f for f in check_metrics_drift(index)
             if f.path == "PERF_BASELINE.json"]
    assert len(found) == 1 and "_meta.git" in found[0].message


def test_drift_baseline_meta_dirty_tree_fires():
    """A baseline stamped on a dirty working tree points _meta.git at
    a commit that is NOT the measured code (the PR 11 failure class);
    `tree: "clean"` and absent-key (pre-rule) stamps are clean."""
    from libjitsi_tpu.analysis.checkers.drift import check_baseline_meta

    ok = {"git": "0123abc"}
    assert check_baseline_meta(dict(ok, tree="clean")) == []
    assert check_baseline_meta(ok) == []        # pre-rule baseline
    msgs = check_baseline_meta(dict(ok, tree="dirty"))
    assert len(msgs) == 1 and "_meta.tree" in msgs[0]
    # the git-hash rule still wins when both are wrong
    msgs = check_baseline_meta({"git": "unknown", "tree": "dirty"})
    assert len(msgs) == 1 and "_meta.git" in msgs[0]


def test_drift_syscall_and_reap_counters_in_scope():
    """ISSUE 12's ingest telemetry suffixes (`_syscalls`, `_reaps`)
    are counter-shaped: a class growing an unregistered one next to a
    registered sibling fires; registering both via the reading-lambda
    form is clean."""
    src = """
    class Loop:
        def __init__(self):
            self.ingest_syscalls = 0
            self.ingest_ring_reaps = 0

        def sync(self):
            self.ingest_syscalls += 1
            self.ingest_ring_reaps += 1

        def register_metrics(self, registry):
            registry.register_scalar(
                "loop_ingest_syscalls",
                lambda: self.ingest_syscalls, kind="counter")
    """
    ctx = ctx_of(src)
    found = check_metrics_drift({ctx.relpath: ctx})
    assert len(found) == 1
    assert "ingest_ring_reaps" in found[0].message

    covered = src.replace(
        'kind="counter")',
        'kind="counter")\n'
        '            registry.register_scalar(\n'
        '                "loop_ingest_ring_reaps",\n'
        '                lambda: self.ingest_ring_reaps,'
        ' kind="counter")')
    ctx = ctx_of(covered)
    assert check_metrics_drift({ctx.relpath: ctx}) == []


def test_drift_handshake_plane_counters_in_scope():
    """The reconnect-storm plane's counters (`retransmits_total`,
    `inbox_dropped` — `_total`/`_dropped` suffixes) are counter-shaped:
    a deferred-table class growing an unregistered one next to a
    registered sibling fires; registering both via the reading-lambda
    form is clean."""
    src = """
    class AssocTable:
        def __init__(self):
            self.retransmits_total = 0
            self.inbox_dropped = 0

        def tick(self):
            self.retransmits_total += 1

        def on_dtls(self):
            self.inbox_dropped += 1

        def register_metrics(self, registry):
            registry.register_scalar(
                "dtls_retransmits_total",
                lambda: self.retransmits_total, kind="counter")
    """
    ctx = ctx_of(src)
    found = check_metrics_drift({ctx.relpath: ctx})
    assert len(found) == 1
    assert "inbox_dropped" in found[0].message

    covered = src.replace(
        'kind="counter")',
        'kind="counter")\n'
        '            registry.register_scalar(\n'
        '                "dtls_inbox_dropped",\n'
        '                lambda: self.inbox_dropped,'
        ' kind="counter")')
    ctx = ctx_of(covered)
    assert check_metrics_drift({ctx.relpath: ctx}) == []


def test_drift_real_baseline_meta_is_a_fresh_hash():
    """The checked-in baseline's stamp must be a real hash — the
    --write-baseline path stamps HEAD automatically now."""
    from libjitsi_tpu.analysis.checkers.drift import check_baseline_meta

    with open(os.path.join(REPO, "PERF_BASELINE.json")) as fh:
        meta = json.load(fh).get("_meta", {})
    assert check_baseline_meta(meta) == []


# ------------------------------------------------- pragmas and baseline

def test_line_pragma_suppresses():
    src = """
    def newest(a_seq, b_seq):
        if a_seq < b_seq:  # jitlint: disable=rtp-mod16
            return b_seq
        return a_seq
    """
    assert check_rtp_mod16(ctx_of(src)) == []


def test_def_level_pragma_suppresses_whole_function():
    src = """
    def newest(a_seq, b_seq):  # jitlint: disable=rtp-mod16
        c = a_seq + 1
        if a_seq < b_seq:
            return b_seq
        return a_seq
    """
    assert check_rtp_mod16(ctx_of(src)) == []


def test_file_pragma_suppresses_everything():
    src = """
    # jitlint: disable-file=all

    def newest(a_seq, b_seq):
        return a_seq < b_seq
    """
    assert check_rtp_mod16(ctx_of(src)) == []


def test_pragma_for_other_rule_does_not_suppress():
    src = """
    def newest(a_seq, b_seq):
        if a_seq < b_seq:  # jitlint: disable=secret-taint
            return b_seq
        return a_seq
    """
    assert len(check_rtp_mod16(ctx_of(src))) == 1


def test_baseline_roundtrip(tmp_path):
    bad = tmp_path / "pkg" / "bad_seq.py"
    bad.parent.mkdir()
    bad.write_text(textwrap.dedent("""
        def newest(a_seq, b_seq):
            if a_seq < b_seq:
                return b_seq
            return a_seq
    """))
    bpath = str(tmp_path / "baseline.json")

    r1 = run_lint([str(bad.parent)], baseline_path=bpath)
    assert r1.exit_code == 1 and len(r1.findings) == 1
    baseline_mod.save_baseline(r1.findings, bpath, why="fixture")

    r2 = run_lint([str(bad.parent)], baseline_path=bpath)
    assert r2.exit_code == 0
    assert len(r2.grandfathered) == 1 and r2.findings == []

    # unrelated edits (line drift) keep the baseline key stable
    bad.write_text("x = 1\n\n\n" + bad.read_text())
    r3 = run_lint([str(bad.parent)], baseline_path=bpath)
    assert r3.exit_code == 0

    # fixing the line retires the entry: stale, not matched
    bad.write_text(textwrap.dedent("""
        from libjitsi_tpu.core.rtp_math import is_newer_seq

        def newest(a_seq, b_seq):
            if is_newer_seq(b_seq, a_seq):
                return b_seq
            return a_seq
    """))
    r4 = run_lint([str(bad.parent)], baseline_path=bpath)
    assert r4.exit_code == 0 and len(r4.stale_baseline) == 1


# ------------------------------------------- regression: the fixed TPs

def test_fixed_zrtp_is_lint_clean():
    """Production fix: ZRTP's 16-bit wire seq wraps at the increment
    (AST check only — runs even without the `cryptography` package)."""
    path = os.path.join(PKG, "control", "zrtp.py")
    with open(path) as fh:
        ctx = FileContext(path, "libjitsi_tpu/control/zrtp.py", fh.read())
    assert check_rtp_mod16(ctx) == []


def test_fixed_zrtp_seq_wraps_mod16():
    """Production fix, runtime half: _send at seq 0xFFFF lands on 0."""
    pytest.importorskip("cryptography")
    from libjitsi_tpu.control import zrtp as zrtp_mod

    ep = zrtp_mod.ZrtpEndpoint(ssrc=7)
    ep._seq = 0xFFFF
    pkt = ep._send(b"\\x00" * 12)
    assert ep._seq == 0          # wrapped, not 65536
    assert pkt[2:4] == b"\\x00\\x00"


def test_fixed_header_ext_is_lint_clean_and_lookup_survives_wrap():
    """Production fix: TransportCC's extended counter is `_ext`-named
    and lookup unwraps via rtp_math.seq_delta."""
    from libjitsi_tpu.transform.header_ext import TransportCCEngine

    path = os.path.join(PKG, "transform", "header_ext.py")
    with open(path) as fh:
        ctx = FileContext(
            path, "libjitsi_tpu/transform/header_ext.py", fh.read())
    assert check_rtp_mod16(ctx) == []

    eng = TransportCCEngine(ext_id=5, clock=lambda: 42.0)
    eng.next_seq_ext = 0x10000 + 3       # past one 16-bit wrap
    eng.sent_seq[(0x10000 + 2) % eng.HISTORY] = 0x10000 + 2
    eng.sent_time[(0x10000 + 2) % eng.HISTORY] = 42.0
    assert eng.lookup_send_time((0x10000 + 2) & 0xFFFF) == 42.0
    assert eng.lookup_send_time(500) is None


def test_fixed_receive_pump_counters_registered():
    """Production fix: the scalar pump's counters export through
    MetricsRegistry (drift rule)."""
    import numpy as np

    from libjitsi_tpu.service.pump import ReceivePump, g711_codec
    from libjitsi_tpu.utils.metrics import MetricsRegistry

    class _NullStream:
        def receive(self, datagrams, arrival=None):
            raise NotImplementedError

    pump = ReceivePump(_NullStream(), g711_codec(), plc=False)
    reg = MetricsRegistry()
    pump.register_metrics(reg)
    pump.tick(now=1.0)                       # one underrun
    text = reg.render()
    assert "rx_pump_lost_frames 1" in text
    assert "rx_pump_decoded_frames 0" in text
    assert "rx_pump_decode_errors 0" in text


# ------------------------------------------------------- the real gate

def test_cli_clean_on_real_tree_under_20s():
    """The merged tree lints clean, fast, through the real CLI — the
    exact command scripts/tier1.sh gates on.  The 20 s budget holds
    even for a COLD index (~19 s for 137 files); a warm index runs in
    ~2 s, and the gate line reports which one this was."""
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "lint.py"),
         "libjitsi_tpu"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    elapsed = time.perf_counter() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 20.0, f"lint gate took {elapsed:.1f}s (>20s budget)"
    assert "index cache" in proc.stdout     # hit/miss stats on the gate line


def test_cli_json_contract(tmp_path):
    bad = tmp_path / "pkg" / "f.py"
    bad.parent.mkdir()
    bad.write_text("def f(a_seq, b_seq):\n    return a_seq + 1\n")
    empty_base = tmp_path / "b.json"
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "lint.py"), "--json",
         "--baseline", str(empty_base), str(bad.parent)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert data["exit_code"] == 1
    assert data["findings"][0]["rule"] == "rtp-mod16"
    assert data["findings"][0]["path"].endswith("f.py")


def test_cli_internal_error_is_exit_2(tmp_path):
    broken = tmp_path / "pkg" / "broken.py"
    broken.parent.mkdir()
    broken.write_text("def f(:\n")
    proc = subprocess.run(
        [sys.executable, os.path.join("scripts", "lint.py"),
         str(broken.parent)],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2


def test_checkers_have_seeded_true_positive_coverage():
    """Acceptance guard: each of the four rules has at least one TP
    fixture test in this file (greps itself)."""
    with open(os.path.abspath(__file__)) as fh:
        me = fh.read()
    for rule in ("hotpath", "hotalloc", "secret", "mod16", "drift"):
        assert me.count(f"def test_{rule}") >= 2


# -------------------------------------------------------- hotpath-alloc

def test_hotalloc_copy_and_ascontiguousarray_fire_in_io():
    """Seeded from the zero-copy arena work: buf[:n].copy() per recv
    window was the dominant host cost in the phase ledger."""
    src = """
    import numpy as np

    def recv_window(self, buf, n):
        batch = buf[:n].copy()
        return batch

    def egress(self, data):
        return np.ascontiguousarray(data)
    """
    found = check_hotpath_alloc(
        ctx_of(src, "libjitsi_tpu/io/fake.py"))
    assert len(found) == 2
    assert all(f.rule == "hotpath-alloc" for f in found)
    assert "per" in found[0].message  # says it allocates per tick


def test_hotalloc_pragma_suppresses():
    src = """
    import numpy as np

    def recv_window(self, buf, n):
        batch = buf[:n].copy()  # jitlint: disable=hotpath-alloc
        return batch
    """
    assert check_hotpath_alloc(
        ctx_of(src, "libjitsi_tpu/io/fake.py")) == []


def test_hotalloc_scope_is_io_only():
    """The same allocation outside io/ is not a tick-path concern."""
    src = """
    import numpy as np

    def anywhere(self, buf, n):
        return buf[:n].copy()
    """
    assert check_hotpath_alloc(
        ctx_of(src, "libjitsi_tpu/transform/fake.py")) == []
    assert check_hotpath_alloc(
        ctx_of(src, "libjitsi_tpu/service/fake.py")) == []


def test_hotalloc_cold_functions_do_not_fire():
    """Constructors and teardown allocate by design; dict.copy-style
    non-numpy receivers still fire (conservative) but np.copy via the
    module alias is caught by the function arm, not the method arm."""
    src = """
    import numpy as np

    class Engine:
        def __init__(self):
            self.buf = np.zeros((4, 1504), np.uint8).copy()

        def close(self):
            self.last = self.buf.copy()

        def register_metrics(self, reg):
            snap = self.buf.copy()
            return snap
    """
    assert check_hotpath_alloc(
        ctx_of(src, "libjitsi_tpu/io/fake.py")) == []


def test_hotalloc_module_level_and_views_do_not_fire():
    src = """
    import numpy as np

    _SCRATCH = np.zeros(16, np.uint8).copy()

    def recv_view(self, buf, n):
        return buf[:n]          # a view, not an allocation
    """
    assert check_hotpath_alloc(
        ctx_of(src, "libjitsi_tpu/io/fake.py")) == []


def test_hotalloc_repo_io_modules_are_clean():
    """The shipped host-I/O modules carry no unpragma'd tick-path
    allocations (every deliberate one states its rationale)."""
    for mod in ("udp.py", "loop.py", "tcp.py"):
        path = os.path.join(PKG, "io", mod)
        with open(path) as fh:
            ctx = FileContext(path, f"libjitsi_tpu/io/{mod}", fh.read())
        assert check_hotpath_alloc(ctx) == [], mod


# ------------------------------------------------------ mesh-collective

from libjitsi_tpu.analysis.checkers.meshcollective import (  # noqa: E402
    check_mesh_collectives)

_PLACEMENT_STUB = """
SANCTIONED_COLLECTIVE_SITES = (
    ("libjitsi_tpu/mesh/sharded.py", "sharded_mix_minus"),
)
"""


def _mesh_index(src, relpath="libjitsi_tpu/mesh/sharded.py"):
    return {
        "libjitsi_tpu/mesh/placement.py": ctx_of(
            _PLACEMENT_STUB, "libjitsi_tpu/mesh/placement.py"),
        relpath: ctx_of(src, relpath),
    }


def test_mesh_collective_unsanctioned_psum_fires():
    """Seeded from the PR 10 failure class: a psum creeping back into
    a steady-state mesh tick silently re-couples every chip and voids
    the mesh_agg_pps_ratio extrapolation."""
    src = """
    import jax

    def my_new_mixer(mesh):
        def _mix(pcm):
            return jax.lax.psum(pcm, "streams")
        return _mix
    """
    found = check_mesh_collectives(_mesh_index(src))
    assert rules_of(found) == ["mesh-collective"]
    assert "psum" in found[0].message


def test_mesh_collective_sanctioned_site_clean():
    """The giant-conference escape hatch named in
    SANCTIONED_COLLECTIVE_SITES keeps its psum (nested defs count:
    the collective lives in the shard_map body closure)."""
    src = """
    import jax

    def sharded_mix_minus(mesh):
        def _mix(pcm):
            return jax.lax.psum(pcm, "streams")
        return _mix
    """
    assert check_mesh_collectives(_mesh_index(src)) == []


def test_mesh_collective_bare_names_and_kin_fire():
    src = """
    from jax.lax import all_gather, ppermute

    def fan_in(x):
        y = all_gather(x, "streams")
        return ppermute(y, "streams", [(0, 1)])
    """
    found = check_mesh_collectives(_mesh_index(src))
    assert len(found) == 2
    assert all(f.rule == "mesh-collective" for f in found)


def test_mesh_collective_scope_is_mesh_only():
    """FP guard: collectives outside mesh/ are someone else's policy."""
    src = """
    import jax

    def f(x):
        return jax.lax.psum(x, "d")
    """
    idx = {"libjitsi_tpu/conference/mixer.py":
           ctx_of(src, "libjitsi_tpu/conference/mixer.py")}
    assert check_mesh_collectives(idx) == []


def test_mesh_collective_segment_sum_clean():
    """FP guard: the shard-local segment_sum mixer is the POINT of the
    affinity layout; it must never be confused with a collective."""
    src = """
    import jax

    def shard_local(pcm, conf):
        return jax.ops.segment_sum(pcm, conf, num_segments=8)
    """
    assert check_mesh_collectives(
        _mesh_index(src, "libjitsi_tpu/mesh/local.py")) == []


def test_mesh_collective_placement_itself_never_sanctioned():
    """A collective in placement.py fires even inside a function whose
    name appears in the sanction list — the list sanctions sites in
    OTHER files, and the placement tick regressing is exactly the bug."""
    src = """
    import jax

    SANCTIONED_COLLECTIVE_SITES = (
        ("libjitsi_tpu/mesh/sharded.py", "sharded_mix_minus"),
    )

    def sharded_mix_minus(x):
        return jax.lax.psum(x, "streams")
    """
    idx = {"libjitsi_tpu/mesh/placement.py":
           ctx_of(src, "libjitsi_tpu/mesh/placement.py")}
    found = check_mesh_collectives(idx)
    assert rules_of(found) == ["mesh-collective"]


_HIERARCHY_STUB = """
SANCTIONED_COLLECTIVE_SITES = (
    ("libjitsi_tpu/mesh/sharded.py", "sharded_mix_minus"),
    ("libjitsi_tpu/mesh/hierarchy.py", "broadcast_bus_fanout"),
)
"""


def _hierarchy_index(src):
    rel = "libjitsi_tpu/mesh/hierarchy.py"
    return {
        "libjitsi_tpu/mesh/placement.py": ctx_of(
            _HIERARCHY_STUB, "libjitsi_tpu/mesh/placement.py"),
        rel: ctx_of(src, rel),
    }


def test_mesh_collective_second_psum_in_hierarchy_fires():
    """TP, seeded from the PR 11 temptation: a helper in hierarchy.py
    adding its OWN collective (say, gathering listener levels) breaks
    the one-collective-per-tick contract even though the file already
    hosts a sanctioned psum."""
    src = """
    import jax

    def broadcast_bus_fanout(mesh, n_conf):
        def _total(seg):
            return jax.lax.psum(seg, "streams")
        return _total

    def listener_level_rollup(mesh):
        def _roll(lvl):
            return jax.lax.all_gather(lvl, "streams")
        return _roll
    """
    found = check_mesh_collectives(_hierarchy_index(src))
    assert rules_of(found) == ["mesh-collective"]
    assert "all_gather" in found[0].message


def test_mesh_collective_sanctioned_bus_fanout_clean():
    """FP guard: the registered broadcast fan-out site keeps its one
    psum (nested closure depth included)."""
    src = """
    import jax

    def broadcast_bus_fanout(mesh, n_conf):
        def _total(seg):
            return jax.lax.psum(seg, "streams")
        return _total
    """
    assert check_mesh_collectives(_hierarchy_index(src)) == []


def test_mesh_collective_real_tree_clean():
    """The shipped mesh/ package holds the zero-collective invariant:
    only the sanctioned participant-sharded escape hatches remain."""
    idx = {}
    mesh_dir = os.path.join(PKG, "mesh")
    for fn in sorted(os.listdir(mesh_dir)):
        if fn.endswith(".py"):
            rel = f"libjitsi_tpu/mesh/{fn}"
            with open(os.path.join(mesh_dir, fn)) as fh:
                idx[rel] = FileContext(rel, rel, fh.read())
    assert check_mesh_collectives(idx) == []


# ===================================================== interprocedural
# secret-flow + plane-affinity run over the whole-tree facts index, so
# these fixtures are real on-disk trees linted through run_lint with a
# tmp baseline (which also pins the facts cache into the tmp dir).

def _tree(tmp_path, files):
    """Write {relpath: source} under tmp_path and lint the tree root;
    returns the LintResult."""
    root = None
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        root = root or rel.split("/")[0]
    return run_lint([str(tmp_path / root)],
                    baseline_path=str(tmp_path / "baseline.json"))


def _flow(findings, rule="secret-flow"):
    return [f for f in findings if f.rule == rule]


def test_secretflow_cross_module_helper_leak(tmp_path):
    """TP: DTLS-exported key material crosses a module boundary through
    a helper's return value and lands in a flight-recorder payload; the
    finding carries the whole source-to-sink path."""
    r = _tree(tmp_path, {
        "pkg/keysrc.py": """
            def fetch_rx_key(ep):
                profile, tk, tsalt, rk, rsalt = ep.srtp_keys()
                return rk
        """,
        "pkg/svc.py": """
            from pkg.keysrc import fetch_rx_key

            class Mgr:
                def install(self, flight, ep):
                    k = fetch_rx_key(ep)
                    flight.record("install", key=k)
        """,
    })
    flows = _flow(r.findings)
    assert len(flows) == 1
    f = flows[0]
    assert f.path == "pkg/svc.py"
    assert "srtp_keys" in f.message
    assert f.trace[0]["path"] == "pkg/keysrc.py"      # source module
    assert f.trace[-1]["path"] == "pkg/svc.py"        # sink module
    assert "flight-payload" in f.trace[-1]["note"]
    # --format=json carries the same path
    d = f.to_dict()
    assert [h["path"] for h in d["trace"]] == \
        ["pkg/keysrc.py", "pkg/svc.py"]


def test_secretflow_sink_side_hop_recorded(tmp_path):
    """TP: key passed INTO a helper that logs it — the trace records
    the call hop into the sink function."""
    r = _tree(tmp_path, {
        "pkg/a.py": """
            from pkg.b import audit

            def go(log, ep):
                key = ep.export_keying_material()
                audit(log, key)
        """,
        "pkg/b.py": """
            def audit(log, material):
                _log = log
                _log.info("audit", material=material)
        """,
    })
    flows = _flow(r.findings)
    assert len(flows) == 1
    notes = [h["note"] for h in flows[0].trace]
    assert any("passed to" in n for n in notes)
    assert flows[0].path == "pkg/b.py"


def test_secretflow_structure_only_access_clean(tmp_path):
    """FP guard: shape/len/dtype reads of key material are structure,
    not secrets."""
    r = _tree(tmp_path, {
        "pkg/svc.py": """
            def install(flight, ep):
                profile, tk, tsalt, rk, rsalt = ep.srtp_keys()
                flight.record("install", n=len(rk), shape=tk.shape,
                              profile=profile)
        """,
    })
    assert _flow(r.findings) == []


def test_secretflow_pragma_scope(tmp_path):
    """A sink-line pragma suppresses exactly that flow."""
    files = {
        "pkg/svc.py": """
            def install(flight, ep):
                k = ep.export_keying_material()
                flight.record("a", key=k)  # jitlint: disable=secret-flow

                flight.record("b", key=k)
        """,
    }
    r = _tree(tmp_path, files)
    flows = _flow(r.findings)
    assert len(flows) == 1 and flows[0].line == 6


def test_secretflow_local_name_not_reseeded(tmp_path):
    """FP guard: a locally-assigned variable that merely SOUNDS secret
    (a conference dict key) follows dataflow, not its name."""
    r = _tree(tmp_path, {
        "pkg/service/lifecycle.py": """
            class Mgr:
                def _conf_key(self, shard, conf):
                    return f"{shard}:{conf}"

                def promote(self, flight, conf):
                    key = self._conf_key(0, conf)
                    flight.record("promoted", conf=key)
        """,
    })
    assert _flow(r.findings) == []


def test_secretflow_declassified_transform_output_clean(tmp_path):
    """FP guard: protect/unprotect outputs are wire data — taint stops
    at the AEAD boundary instead of smearing into unpacked verdicts."""
    r = _tree(tmp_path, {
        "pkg/service/lifecycle.py": """
            def on_media(flight, table, batch):
                data, auth_ok, sid = table.unprotect_rtp(batch)
                flight.record("rx", sid=sid, ok=auth_ok)
        """,
    })
    assert _flow(r.findings) == []


def test_secretflow_cycle_terminates_and_flows(tmp_path):
    """Call-graph property: mutual recursion converges and still
    carries taint through the cycle's return values."""
    r = _tree(tmp_path, {
        "pkg/m.py": """
            def bounce(key, n):
                if n:
                    return rebound(key, n - 1)
                return key

            def rebound(key, n):
                return bounce(key, n)

            def go(flight, ep):
                k = bounce(ep.export_keying_material(), 3)
                flight.record("x", k=k)
        """,
    })
    assert len(_flow(r.findings)) == 1


def test_secretflow_ambiguous_dispatch_no_summary(tmp_path):
    """Call-graph property: a method name defined by several classes
    does not resolve — no summary flows, no phantom finding."""
    r = _tree(tmp_path, {
        "pkg/m.py": """
            class Dtls:
                def grab(self, ep):
                    return ep.export_keying_material()

            class Stats:
                def grab(self, ep):
                    return 42

            def go(flight, obj, ep):
                v = obj.grab(ep)
                flight.record("x", v=v)
        """,
    })
    assert _flow(r.findings) == []


def test_planeaffinity_tick_reachable_handshake_fires(tmp_path):
    """TP: the tick root reaching `ep.feed(...)`-driving control code
    is the static twin of handshake_tick_thread_feeds == 0."""
    r = _tree(tmp_path, {
        "libjitsi_tpu/io/loop.py": """
            class MediaLoop:
                def tick(self):
                    self.assoc.ingest(b"x", ("h", 1))
        """,
        "libjitsi_tpu/control/dtls.py": """
            class AssocTable:
                def ingest(self, dgram, addr):
                    ep = self.pending[addr]
                    return ep.feed(dgram)
        """,
    })
    flags = _flow(r.findings, "plane-affinity")
    assert len(flags) == 1
    assert "handshake" in flags[0].message
    assert flags[0].trace[0]["note"] == "plane root"
    assert flags[0].trace[0]["symbol"] == "MediaLoop.tick"


def test_planeaffinity_dual_annotation_cuts(tmp_path):
    """The reviewable escape hatch: plane=dual cuts traversal at the
    documented legacy boundary without flagging."""
    r = _tree(tmp_path, {
        "libjitsi_tpu/io/loop.py": """
            class MediaLoop:
                def tick(self):
                    self.assoc.ingest(b"x", ("h", 1))
        """,
        "libjitsi_tpu/control/dtls.py": """
            class AssocTable:
                # jitlint: plane=dual
                def ingest(self, dgram, addr):
                    ep = self.pending[addr]
                    return ep.feed(dgram)
        """,
    })
    assert _flow(r.findings, "plane-affinity") == []


def test_planeaffinity_barrier_mediated_install_clean(tmp_path):
    """FP guard + TP pair: an install inside the commit barrier is the
    sanctioned surface; the same install reached around the barrier
    fires."""
    r = _tree(tmp_path, {
        "libjitsi_tpu/service/lifecycle.py": """
            class StreamLifecycleManager:
                def poll(self):
                    self.commit_endpoints()
                    self._sneak_install()

                def commit_endpoints(self):
                    self.rx_table.add_stream(1, b"k", b"s")

                def _sneak_install(self):
                    self.rx_table.add_stream(2, b"k", b"s")
        """,
    })
    flags = _flow(r.findings, "plane-affinity")
    assert len(flags) == 1
    assert flags[0].symbol.endswith("_sneak_install")
    assert "staged commit barrier" in flags[0].message


def test_index_cache_roundtrip_and_stale_invalidation(tmp_path):
    """Second run over an unchanged tree is all cache hits with
    identical findings; editing one file re-checks exactly that file."""
    files = {
        "pkg/svc.py": """
            def install(flight, ep):
                k = ep.export_keying_material()
                flight.record("x", key=k)
        """,
        "pkg/other.py": """
            def helper():
                return 1
        """,
    }
    r1 = _tree(tmp_path, files)
    assert r1.cache_misses == 2 and r1.cache_hits == 0
    assert len(_flow(r1.findings)) == 1

    r2 = _tree(tmp_path, files)
    assert r2.cache_hits == 2 and r2.cache_misses == 0
    assert len(_flow(r2.findings)) == 1
    assert r2.findings[0].content_key == r1.findings[0].content_key

    # content edit invalidates exactly the edited file
    files["pkg/other.py"] = "def helper():\n    return 2\n"
    r3 = _tree(tmp_path, files)
    assert r3.cache_hits == 1 and r3.cache_misses == 1

    # a cache written by a different analysis version is discarded
    from libjitsi_tpu.analysis import index as index_mod
    cpath = tmp_path / ".jitlint_index.json"
    doc = json.loads(cpath.read_text())
    doc["version"] = "stale"
    cpath.write_text(json.dumps(doc))
    assert index_mod.load_cache(str(cpath)) == {}
    r4 = _tree(tmp_path, files)
    assert r4.cache_misses == 2


def test_changed_mode_trusts_unchanged_files(tmp_path, monkeypatch):
    """--changed: git names the changed set; everything outside its
    reverse-dependency closure is served from the cache untouched."""
    if subprocess.run(["git", "--version"], capture_output=True).returncode:
        pytest.skip("git unavailable")
    files = {
        "pkg/__init__.py": "",
        "pkg/base.py": """
            def helper():
                return 1
        """,
        "pkg/user.py": """
            from pkg.base import helper

            def go():
                return helper()
        """,
    }
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    monkeypatch.chdir(tmp_path)
    for cmd in (["git", "init", "-q"],
                ["git", "add", "."],
                ["git", "-c", "user.email=t@t", "-c", "user.name=t",
                 "commit", "-qm", "seed"]):
        subprocess.run(cmd, check=True, capture_output=True)

    bpath = str(tmp_path / "baseline.json")
    r1 = run_lint([str(tmp_path / "pkg")], baseline_path=bpath)
    assert r1.cache_misses == 3

    # no changes: --changed trusts the whole tree from the cache
    r2 = run_lint([str(tmp_path / "pkg")], baseline_path=bpath,
                  changed_only=True)
    assert r2.cache_hits == 3 and r2.cache_misses == 0

    # editing base.py: it and its importer (user.py) leave the trusted
    # set — base.py re-parses (miss), user.py is re-read but its sha
    # still matches (hit), __init__ is trusted without a read
    (tmp_path / "pkg/base.py").write_text(
        "def helper():\n    return 2\n")
    r3 = run_lint([str(tmp_path / "pkg")], baseline_path=bpath,
                  changed_only=True)
    assert r3.cache_misses == 1 and r3.cache_hits == 2


def test_baseline_justification_required(tmp_path):
    """Drift guard: a baseline entry with no `why` is itself a
    finding."""
    files = {
        "pkg/clean.py": """
            def ok():
                return 1
        """,
    }
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    bpath = tmp_path / "baseline.json"
    bpath.write_text(json.dumps({"entries": [
        {"key": "secret-taint:pkg/x.py:f:abc:0", "why": ""},
    ]}))
    r = run_lint([str(tmp_path / "pkg")], baseline_path=str(bpath))
    msgs = [f.message for f in r.findings if f.rule == "drift"]
    assert any("justification" in m or "why" in m for m in msgs)


def test_fixed_process_one_is_plane_dual():
    """Production fix: the legacy inline-DTLS path is a declared
    plane=dual boundary — tick-reachable handshake work is otherwise a
    finding (static twin of handshake_tick_thread_feeds == 0)."""
    path = os.path.join(PKG, "control", "dtls.py")
    with open(path) as fh:
        ctx = FileContext(path, "libjitsi_tpu/control/dtls.py",
                          fh.read())
    from libjitsi_tpu.analysis.callgraph import extract_defs
    functions, _ = extract_defs(ctx)
    fn = functions["DtlsAssociationTable._process_one"]
    assert fn["plane"] == "dual"
