"""Property test of the replay-window state machine (SURVEY §5 test
strategy: "property tests on replay-window state machine").

Model: a brute-force per-stream set of accepted indices with the RFC 3711
§3.3.2 rules, BATCH-ATOMIC: every row of a batch is checked against the
state as of the batch start (the batched implementation's documented
semantic — rows arrive in one batching window and are mutually
"simultaneous"), with in-batch exact duplicates removed.  The security
invariant — no index is ever accepted twice, nothing older than
WINDOW-1 behind the committed max is accepted — is what the model
enforces; it differs from a strictly sequential checker only in
accepting distinct reordered indices that would have become "too old"
mid-batch, which is a freshness relaxation, not a replay.
"""

import numpy as np

from libjitsi_tpu.transform.srtp import replay


def _model_check(accepted, max_idx, idx):
    if idx in accepted:
        return False
    if max_idx >= 0 and max_idx - idx >= replay.WINDOW:
        return False
    return True


def test_replay_window_matches_bruteforce_model():
    rng = np.random.default_rng(42)
    n_streams = 4
    for trial in range(20):
        max_index = np.full(n_streams, -1, dtype=np.int64)
        mask = np.zeros(n_streams, dtype=np.uint64)
        accepted = {s: set() for s in range(n_streams)}
        model_max = {s: -1 for s in range(n_streams)}
        ever_accepted = set()       # (stream, idx) over the whole trial

        # a jumpy index sequence per stream: forward runs, reorders,
        # duplicates, and occasional ancient indices
        cursor = {s: int(rng.integers(0, 1000)) for s in range(n_streams)}
        for batch_no in range(12):
            bsz = int(rng.integers(1, 24))
            streams = rng.integers(0, n_streams, bsz).astype(np.int64)
            idxs = np.zeros(bsz, dtype=np.int64)
            for i, s in enumerate(streams):
                roll = rng.random()
                if roll < 0.55:                       # in-order advance
                    cursor[s] += int(rng.integers(1, 4))
                    idxs[i] = cursor[s]
                elif roll < 0.75 and accepted[s]:     # duplicate
                    idxs[i] = int(rng.choice(sorted(accepted[s])))
                elif roll < 0.9:                      # nearby reorder
                    idxs[i] = max(0, cursor[s] - int(rng.integers(0, 20)))
                else:                                 # ancient
                    idxs[i] = max(0, cursor[s] - int(
                        rng.integers(replay.WINDOW, replay.WINDOW + 50)))

            fresh = replay.check(max_index, mask, streams, idxs)
            dup = replay.dedup_first(streams, idxs, fresh)
            ok = fresh & ~dup
            expect = np.zeros(bsz, dtype=bool)
            seen_in_batch = set()
            for i in range(bsz):
                s = int(streams[i])
                key = (s, int(idxs[i]))
                e = (_model_check(accepted[s], model_max[s], int(idxs[i]))
                     and key not in seen_in_batch)
                expect[i] = e
                if e:
                    seen_in_batch.add(key)
            assert (ok == expect).all(), (
                trial, batch_no, streams.tolist(), idxs.tolist(),
                ok.tolist(), expect.tolist())
            # commit accepted rows to both states
            for i in range(bsz):
                if expect[i]:
                    s = int(streams[i])
                    accepted[s].add(int(idxs[i]))
                    model_max[s] = max(model_max[s], int(idxs[i]))
            replay.update(max_index, mask, streams, idxs, ok)
            for s in range(n_streams):
                assert max_index[s] == model_max[s]
            # SECURITY INVARIANT regardless of batching semantics: no
            # (stream, index) pair is ever accepted twice
            for i in range(bsz):
                if ok[i]:
                    key = (int(streams[i]), int(idxs[i]))
                    assert key not in ever_accepted, key
                    ever_accepted.add(key)


def test_replay_window_exact_boundary():
    """Index exactly WINDOW-1 behind max is acceptable; WINDOW is not."""
    max_index = np.array([-1], dtype=np.int64)
    mask = np.zeros(1, dtype=np.uint64)
    s = np.array([0], dtype=np.int64)
    hi = np.array([1000], dtype=np.int64)
    ok = replay.check(max_index, mask, s, hi)
    replay.update(max_index, mask, s, hi, ok)
    edge_ok = replay.check(max_index, mask, s,
                           np.array([1000 - replay.WINDOW + 1], np.int64))
    edge_bad = replay.check(max_index, mask, s,
                            np.array([1000 - replay.WINDOW], np.int64))
    assert edge_ok[0] and not edge_bad[0]
