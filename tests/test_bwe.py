"""Google Congestion Control behavior tests.

Reference behaviors (remotebitrateestimator / sendsidebandwidthestimation
packages): steady network → rate grows; queuing-delay buildup → OVERUSING
→ multiplicative decrease; loss-based send-side moves; REMB/delay caps.
"""

import numpy as np

from libjitsi_tpu.bwe import (
    RateStatistics,
    RemoteBitrateEstimator,
    SendSideBandwidthEstimation,
)
from libjitsi_tpu.bwe.overuse import NORMAL, OVERUSING
from libjitsi_tpu.rtp.rtcp import TccFeedback


def _drive(est, seconds, jitter_ramp_ms_per_pkt=0.0, fps=100,
           size=1200, t0=0.0, tick_ms=100):
    """Send `fps` pkts/s with periodic estimator ticks; arrival delay
    optionally grows each packet.  Returns (t, states seen)."""
    t = t0
    delay = 0.0
    states = set()
    for i in range(int(seconds * fps)):
        t += 1000.0 / fps
        delay += jitter_ramp_ms_per_pkt
        ast = int((t / 1000.0) * (1 << 18)) & 0xFFFFFF
        est.incoming_packet(t + delay, ast, size)
        states.add(est.state)
        if i % max(1, int(tick_ms * fps / 1000)) == 0:
            est.update_estimate(t + delay)
    return t, states


def test_rate_statistics_window():
    rs = RateStatistics(window_ms=1000)
    for ms in range(0, 1000, 10):
        rs.update(1250, ms)  # 125 kB over 1 s = 1 Mbps
    assert abs(rs.rate(999) - 1_000_000) / 1_000_000 < 0.02
    # window slides: after 2 s of silence the rate decays to 0
    assert rs.rate(2999) == 0


def test_remote_estimator_grows_on_clean_network():
    est = RemoteBitrateEstimator(start_bitrate_bps=300_000)
    t, states = _drive(est, 5.0)
    assert est.state == NORMAL
    b = est.update_estimate(t)
    assert b > 300_000 * 1.2


def test_remote_estimator_detects_overuse_and_backs_off():
    # clean counterfactual: same duration, no congestion
    clean = RemoteBitrateEstimator(start_bitrate_bps=300_000)
    t, _ = _drive(clean, 5.0)
    b_clean = clean.update_estimate(t)

    est = RemoteBitrateEstimator(start_bitrate_bps=300_000)
    t, _ = _drive(est, 2.0)
    # 1 ms of extra queuing delay per packet = 100 ms/s of buildup
    t, states = _drive(est, 3.0, jitter_ramp_ms_per_pkt=1.0, t0=t)
    assert OVERUSING in states
    b1 = est.update_estimate(t)
    # overuse clamps the estimate to ~0.85x the measured throughput
    # (clean growth is unclamped: it may exceed that bound)
    incoming = est._incoming.rate(int(t + 300))
    assert b1 <= max(0.9 * incoming, 300_000)
    assert b_clean > 300_000 * 1.2  # sanity: clean trajectory did grow


def test_send_side_loss_controller():
    ss = SendSideBandwidthEstimation(start_bitrate_bps=1_000_000)
    # clean RRs: grows
    b = ss.on_receiver_report(0, now_ms=1000)
    b = ss.on_receiver_report(0, now_ms=2000)
    assert b > 1_000_000
    # 20% loss: halves-ish (1 - 0.5*0.2 = 0.9 factor)
    b2 = ss.on_receiver_report(51, now_ms=3000)
    assert b2 < b
    # rapid repeat within 300 ms does not double-punish
    b3 = ss.on_receiver_report(51, now_ms=3100)
    assert abs(b3 - b2) < 1e-6


def test_send_side_remb_cap():
    ss = SendSideBandwidthEstimation(start_bitrate_bps=2_000_000)
    assert ss.on_remb(500_000) == 500_000
    assert ss.estimate_bps == 500_000
    # cap released
    assert ss.on_remb(5_000_000) >= 2_000_000


def test_send_side_tcc_delay_cap():
    ss = SendSideBandwidthEstimation(start_bitrate_bps=5_000_000)
    # feedback showing growing queuing delay over several bursts
    now = 0.0
    delay = 0.0
    seq = 0
    for burst in range(60):
        n = 10
        send = [now + i * 10 for i in range(n)]
        delay += 15.0
        arrivals = np.array([(send[i] + delay) * 4 for i in range(n)],
                            dtype=np.int64)  # 0.25 ms units
        fb = TccFeedback(
            sender_ssrc=1, media_ssrc=2, base_seq=seq,
            reference_time=0, fb_pkt_count=burst,
            received=np.ones(n, dtype=bool), arrival_250us=arrivals)
        ss.on_tcc_feedback(fb, send, now_ms=send[-1] + delay)
        seq += n
        now += n * 10
    assert ss.delay_cap is not None
    assert ss.estimate_bps <= ss.delay_cap + 1
    assert ss.delay_cap < 5_000_000
