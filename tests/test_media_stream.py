"""End-to-end slice: SDES negotiation → MediaStream → SRTP wire → back.

This is the framework's "one model running end-to-end" milestone
(SURVEY §7 step 3): two MediaStreams exchange protected RTP over a
simulated wire, byte-identical payloads come out, stats and RTCP flow.
"""

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.control.sdes import CryptoAttribute, SdesControl
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.service.media_stream import Direction
from libjitsi_tpu.transform.srtp.policy import SrtpProfile


@pytest.fixture()
def svc():
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    return libjitsi_tpu.media_service()


def make_pair(svc):
    """Two connected streams: a (initiator) <-> b (responder)."""
    a = svc.create_media_stream(local_ssrc=0xA)
    b = svc.create_media_stream(local_ssrc=0xB)
    offer = a.sdes.create_offer()
    answer = b.sdes.create_answer(offer)
    a.sdes.accept_answer(answer)
    # responder's local key protects b->a; wire the crypto directions:
    # a encrypts with a.local, b decrypts with its remote (= a.local)
    a.set_remote_ssrc(b.local_ssrc)
    b.set_remote_ssrc(a.local_ssrc)
    a.start()
    b.start()
    return a, b


def test_sdes_attribute_roundtrip():
    c = SdesControl()
    offer = c.create_offer()
    assert all(o.split()[1] in (p.value for p in SrtpProfile) for o in offer)
    att = CryptoAttribute.parse("a=crypto:" + offer[0])
    assert att.profile is SrtpProfile.AES_CM_128_HMAC_SHA1_80
    assert len(att.master_key) == 16 and len(att.master_salt) == 14


def test_sdes_answer_selects_common_suite():
    a = SdesControl(profiles=[SrtpProfile.AES_CM_128_HMAC_SHA1_32,
                              SrtpProfile.AES_CM_128_HMAC_SHA1_80])
    b = SdesControl(profiles=[SrtpProfile.AES_CM_128_HMAC_SHA1_80])
    answer = b.create_answer(a.create_offer())
    a.accept_answer(answer)
    assert a.negotiated and b.negotiated
    assert a.profile is SrtpProfile.AES_CM_128_HMAC_SHA1_80
    assert a.local.master_key == b.remote.master_key
    assert a.remote.master_key == b.local.master_key


@pytest.mark.slow
def test_e2e_media_roundtrip(svc):
    a, b = make_pair(svc)
    payloads = [b"opus-frame-%02d" % i for i in range(8)]
    wire = a.send(payloads, pt=111)
    assert len(wire) == 8
    # ciphertext on the wire
    assert payloads[0] not in wire[0]
    dec, ok = b.receive(wire)
    assert ok.all()
    hdr_len = 12
    got = [dec.to_bytes(i)[hdr_len:] for i in range(8)]
    assert got == payloads
    # stats flowed
    assert a.stats["tx_packets"] == 8
    assert b.stats["rx_packets"] == 8
    assert b.stats["cumulative_lost"] == 0


def test_e2e_bidirectional(svc):
    a, b = make_pair(svc)
    dec, ok = b.receive(a.send([b"ping"]))
    assert ok.all()
    dec2, ok2 = a.receive(b.send([b"pong"]))
    assert ok2.all()
    assert dec2.to_bytes(0).endswith(b"pong")


def test_e2e_tampered_dropped(svc):
    a, b = make_pair(svc)
    wire = a.send([b"x" * 50, b"y" * 50])
    bad = bytearray(wire[0])
    bad[30] ^= 1
    _, ok = b.receive([bytes(bad), wire[1]])
    assert ok.tolist() == [False, True]


def test_direction_enforcement(svc):
    a, b = make_pair(svc)
    a.set_direction(Direction.RECVONLY)
    with pytest.raises(RuntimeError):
        a.send([b"nope"])
    a.set_direction(Direction.SENDONLY)
    with pytest.raises(RuntimeError):
        a.receive([b"\x80" * 40])


def test_rtcp_report_and_rtt(svc):
    a, b = make_pair(svc)
    b.receive(a.send([b"data"] * 4), arrival=10.0)
    blob = b.make_rtcp_report(now=10.5)
    pkts = rtcp.parse_compound(blob)
    # receiver-only b emits RR + SDES cname
    assert isinstance(pkts[0], rtcp.ReceiverReport)
    assert pkts[0].reports[0].ssrc == a.local_ssrc
    assert isinstance(pkts[1], list)  # sdes chunks
    a.handle_rtcp(blob, now=10.6)

    # a (sender) emits SR after sending
    sr_blob = a.make_rtcp_report(now=11.0)
    sr = rtcp.parse_compound(sr_blob)[0]
    assert isinstance(sr, rtcp.SenderReport)
    assert sr.packet_count == 4
    b.handle_rtcp(sr_blob, now=11.05)
    # b echoes the SR in its next RR; a computes RTT
    rr_blob = b.make_rtcp_report(now=11.2)
    a.handle_rtcp(rr_blob, now=11.25)
    assert 0 <= a.stats["rtt_seconds"] < 0.3


def test_registry_demux_and_release(svc):
    a, b = make_pair(svc)
    reg = svc.registry
    wire = a.send([b"zzz"])
    from libjitsi_tpu.core.packet import PacketBatch
    batch = PacketBatch.from_payloads(wire)
    sids = reg.demux(batch)
    assert sids[0] == b.sid  # a's ssrc routes to b (its receiver)
    sid = a.sid
    a.close()
    assert sid not in reg.streams
    c = svc.create_media_stream()
    assert c.sid == sid  # row recycled


@pytest.mark.slow
def test_stats2_pull_api_and_rtcp_listener(svc):
    """MediaStreamStats2 shape: typed Send/ReceiveTrackStats with
    windowed rates from the registry poller, plus RTCP listeners."""
    a, b = make_pair(svc)
    reg = a.registry
    reg.stats2.poll(now=100.0)
    payloads = [bytes([i]) * 120 for i in range(20)]
    wire = a.send(payloads, pt=96)
    b.receive(wire, arrival=100.5)
    reg.stats2.poll(now=101.0)            # close a 1 s interval

    s = a.send_stats()
    assert s.packets == 20 and s.bytes > 20 * 120
    assert s.packet_rate_pps == pytest.approx(20.0, rel=0.01)
    assert s.bitrate_bps == pytest.approx(s.bytes * 8.0, rel=0.01)
    assert s.rtt_ms == -1.0               # no RR echoed yet

    r = b.receive_stats()
    assert r.packets == 20
    assert r.packet_rate_pps == pytest.approx(20.0, rel=0.01)
    assert r.cumulative_lost == 0 and r.fraction_lost == 0.0
    assert r.highest_seq >= 0

    # RTCP listener sees parsed packets
    seen = []
    b.add_rtcp_listener(lambda stream, p: seen.append(type(p).__name__))
    blob = a.make_rtcp_report(now=101.0)
    b.handle_rtcp(blob, now=101.1)
    assert "SenderReport" in seen or "ReceiverReport" in seen
    assert "SdesPacket" in "".join(seen) or len(seen) >= 2


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_stats2_poller_resets_on_row_recycle(svc):
    """A recycled stream row must not difference rates against the dead
    stream's totals (would show huge negative pps)."""
    a, b = make_pair(svc)
    reg = a.registry
    reg.stats2.poll(now=10.0)
    a.send([b"x" * 100] * 50, pt=96)
    reg.stats2.poll(now=11.0)
    sid = a.sid
    a.close()
    c = svc.create_media_stream(local_ssrc=0xC)
    assert c.sid == sid                      # row recycled
    reg.stats2.poll(now=12.0)
    assert c.send_stats().packet_rate_pps == 0.0
    assert c.send_stats().packets == 0


def test_stream_keyed_by_zrtp_control(svc):
    """MediaStream.start accepts any completed keying control exposing
    srtp_keys() — here ZRTP (reference: MediaStream + ZrtpControlImpl),
    no SDES involved."""
    from libjitsi_tpu.control.zrtp import ZrtpEndpoint
    from test_zrtp import run_zrtp

    a_ctl, b_ctl = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    run_zrtp(a_ctl, b_ctl)
    a = svc.create_media_stream(local_ssrc=0x5A)
    b = svc.create_media_stream(local_ssrc=0x5B)
    a.set_remote_ssrc(b.local_ssrc)
    b.set_remote_ssrc(a.local_ssrc)
    a.start(srtp_control=a_ctl)
    b.start(srtp_control=b_ctl)
    from libjitsi_tpu.rtp import header as rtp_header

    wire = a.send([b"zrtp-keyed-stream"])
    got, ok = b.receive(wire)
    assert ok.all()
    hdr = rtp_header.parse(got)
    assert got.to_bytes(0)[int(hdr.payload_off[0]):] == \
        b"zrtp-keyed-stream"


def test_stream_keyed_by_dtls_control(svc):
    """Same uniform surface with DTLS-SRTP as the control."""
    from libjitsi_tpu.control.dtls import DtlsSrtpEndpoint
    from test_dtls import run_handshake

    c = DtlsSrtpEndpoint("client")
    s = DtlsSrtpEndpoint("server",
                         remote_fingerprint=c.local_fingerprint)
    run_handshake(c, s)
    a = svc.create_media_stream(local_ssrc=0x6A)
    b = svc.create_media_stream(local_ssrc=0x6B)
    a.set_remote_ssrc(b.local_ssrc)
    b.set_remote_ssrc(a.local_ssrc)
    a.start(srtp_control=c)
    b.start(srtp_control=s)
    wire = a.send([b"dtls-keyed-stream"])
    got, ok = b.receive(wire)
    assert ok.all()
    # profile mismatch is refused loudly
    x = svc.create_media_stream(
        local_ssrc=0x6C, profile=SrtpProfile.AEAD_AES_128_GCM)
    with pytest.raises(ValueError):
        x.start(srtp_control=c)
