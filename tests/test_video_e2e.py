"""Video SFU end-to-end: IVF fixture -> VP8 packetize -> SRTP -> SFU
fan-out -> per-receiver unprotect -> depacketize/reassemble -> WebM.

This is SURVEY §3.4's call stack plus BASELINE config #4's bookkeeping,
driven entirely by the offline fixture layer (the reference validates
its video path the same way: ivffile capture + rtpdumpfile replay).
"""

import numpy as np

from libjitsi_tpu.codecs import vp8
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.device import IvfReader, IvfWriter
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.sfu import RtpTranslator
from libjitsi_tpu.transform.srtp import SrtpStreamTable

import pytest

pytestmark = pytest.mark.slow   # cold-compile-heavy e2e tier

MK = bytes(range(16))
MS = bytes(range(30, 44))
RECV_KEYS = {1: (b"\x01" * 16, b"\x65" * 14), 2: (b"\x02" * 16, b"\x66" * 14)}


def _fake_vp8_frame(rng, size: int, key: bool) -> bytes:
    body = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
    # VP8 payload header P bit (bit 0 of byte 0): 0 = keyframe
    first = (body[0] & 0xFE) if key else (body[0] | 0x01)
    return bytes([first]) + body[1:]


def _author_ivf(path, rng, n_frames=6):
    w = IvfWriter(path, 320, 180)
    frames = []
    for i in range(n_frames):
        f = _fake_vp8_frame(rng, int(rng.integers(900, 3500)), key=(i == 0))
        frames.append(f)
        w.write(f, pts=i)
    w.close()
    return frames


def test_vp8_packetize_assemble_roundtrip():
    rng = np.random.default_rng(5)
    frame = _fake_vp8_frame(rng, 3000, key=True)
    payloads = vp8.packetize(frame, picture_id=7, max_payload=1000)
    # 3-byte descriptor budgeted out of max_payload: ceil(3000/997) = 4
    assert len(payloads) == 4 and all(len(p) <= 1000 for p in payloads)
    n = len(payloads)
    batch = rtp_header.build(
        payloads, [100 + i for i in range(n)], [9000] * n, [0xABC] * n,
        [96] * n, marker=[0] * (n - 1) + [1])
    fa = vp8.FrameAssembler()
    fa.push_batch(batch)
    frames = fa.pop_frames()
    assert len(frames) == 1
    ts, pid, key, data = frames[0]
    assert (ts, pid, key, data) == (9000, 7, True, frame)


def test_assembler_tolerates_reorder_and_gaps():
    rng = np.random.default_rng(6)
    f1 = _fake_vp8_frame(rng, 2500, key=True)
    f2 = _fake_vp8_frame(rng, 2500, key=False)
    p1 = vp8.packetize(f1, picture_id=1, max_payload=1000)
    p2 = vp8.packetize(f2, picture_id=2, max_payload=1000)
    rows = []
    for i, p in enumerate(p1):
        rows.append((p, 200 + i, 1000, int(i == len(p1) - 1)))
    for i, p in enumerate(p2):
        rows.append((p, 203 + i, 2000, int(i == len(p2) - 1)))
    order = [4, 0, 5, 2, 1]            # drop row 3 (middle of f2), reorder
    fa = vp8.FrameAssembler()
    for k in order:
        p, seq, ts, mk = rows[k]
        fa.push_batch(rtp_header.build([p], [seq], [ts], [0xABC], [96],
                                       marker=[mk]))
    frames = fa.pop_frames()
    assert [(t, d) for t, _, _, d in frames] == [(1000, f1)]  # f2 incomplete


def test_assembler_burst_of_complete_frames_not_evicted():
    """A backlog flush completing >max_pending frames in one push must
    deliver every frame (only incomplete frames evict at the limit)."""
    rng = np.random.default_rng(10)
    fa = vp8.FrameAssembler(max_pending=8)
    frames, seq = [], 0
    pls_all, seqs, tss, mks = [], [], [], []
    for i in range(20):
        f = _fake_vp8_frame(rng, 600, key=(i == 0))
        frames.append(f)
        for j, p in enumerate(vp8.packetize(f, max_payload=700)):
            pls_all.append(p); seqs.append(seq); tss.append(1000 + i * 90)
            mks.append(1); seq += 1
    fa.push_batch(rtp_header.build(pls_all, seqs, tss, [7] * len(pls_all),
                                   [96] * len(pls_all), marker=mks))
    got = fa.pop_frames()
    assert [d for _, _, _, d in got] == frames
    assert fa.dropped_incomplete == 0


def test_assembler_drops_late_completion_keeps_order():
    """A frame completing after a newer one was delivered is dropped,
    never delivered out of order."""
    rng = np.random.default_rng(11)
    f1 = _fake_vp8_frame(rng, 1400, key=True)
    f2 = _fake_vp8_frame(rng, 600, key=False)
    p1 = vp8.packetize(f1, max_payload=800)        # 2 fragments
    p2 = vp8.packetize(f2, max_payload=800)        # 1 fragment
    fa = vp8.FrameAssembler()
    # f1 fragment 0 arrives; f2 completes and is delivered
    fa.push_batch(rtp_header.build([p1[0]], [10], [1000], [7], [96],
                                   marker=[0]))
    fa.push_batch(rtp_header.build(p2, [12], [2000], [7], [96], marker=[1]))
    assert [t for t, _, _, _ in fa.pop_frames()] == [2000]
    # the retransmitted tail of f1 completes it late -> dropped
    fa.push_batch(rtp_header.build([p1[1]], [11], [1000], [7], [96],
                                   marker=[1]))
    assert fa.pop_frames() == []
    assert fa.dropped_late == 1


def test_bridge_rejects_stale_and_wrapping_ids():
    import pytest

    from libjitsi_tpu.conference import MixerBridge

    br = MixerBridge(conferences=2, capacity=2, frame_samples=80)
    cid = br.alloc_conference()
    br.add_participant(cid, 0)
    br.release_conference(cid)
    with pytest.raises(KeyError):
        br.push(cid, 0, np.zeros(80, np.int16))    # stale cid
    with pytest.raises(KeyError):
        br.release_conference(cid)                 # double release
    with pytest.raises(KeyError):
        br.release_conference(-1)                  # would wrap a row
    cid2 = br.alloc_conference()
    with pytest.raises(IndexError):
        br.add_participant(cid2, -1)               # would wrap a row
    with pytest.raises(KeyError):
        br.push(-1, 0, np.zeros(80, np.int16))


def test_assembler_eviction_spares_newest_inflight_frame():
    """A backlog of complete frames must not evict the newest frame
    that is still arriving."""
    rng = np.random.default_rng(12)
    fa = vp8.FrameAssembler(max_pending=4)
    frames, rowspec = [], []
    for i in range(6):                          # 6 complete old frames
        f = _fake_vp8_frame(rng, 400, key=(i == 0))
        frames.append(f)
        for p in vp8.packetize(f, max_payload=500):
            rowspec.append((p, i, 100 + i * 90, 1))
    newest = _fake_vp8_frame(rng, 900, key=False)
    first_frag = vp8.packetize(newest, max_payload=500)[0]
    rowspec.append((first_frag, 6, 100 + 6 * 90, 0))   # no marker yet
    pls, seqs, tss, mks = zip(*rowspec)
    fa.push_batch(rtp_header.build(list(pls), list(seqs), list(tss),
                                   [7] * len(pls), [96] * len(pls),
                                   marker=list(mks)))
    # newest in-flight frame survived; complete backlog under the 4x
    # hard cap survived too
    assert fa.dropped_incomplete == 0 and fa.dropped_backlog == 0
    assert [d for _, _, _, d in fa.pop_frames()] == frames
    # its tail arrives -> the newest frame still completes
    tail = vp8.packetize(newest, max_payload=500)[1:]
    fa.push_batch(rtp_header.build(
        tail, [7 + k for k in range(len(tail))], [100 + 6 * 90] * len(tail),
        [7] * len(tail), [96] * len(tail),
        marker=[0] * (len(tail) - 1) + [1]))
    assert [d for _, _, _, d in fa.pop_frames()] == [newest]


def test_assembler_survives_ts_wraparound():
    rng = np.random.default_rng(8)
    fs = [_fake_vp8_frame(rng, 1200, key=(i == 0)) for i in range(3)]
    # timestamps straddle the 32-bit wrap: order must hold across it
    tss = [0xFFFFF000, 0xFFFFFB00, 0x00000600]
    fa = vp8.FrameAssembler()
    seq = 10
    for f, ts in zip(fs, tss):
        pls = vp8.packetize(f, picture_id=ts & 0x7F, max_payload=700)
        n = len(pls)
        fa.push_batch(rtp_header.build(
            pls, [seq + i for i in range(n)], [ts] * n, [0xABC] * n,
            [96] * n, marker=[0] * (n - 1) + [1]))
        seq += n
    got = fa.pop_frames()
    assert [d for _, _, _, d in got] == fs       # post-wrap frame is last


def test_packetize_respects_max_payload():
    rng = np.random.default_rng(9)
    frame = _fake_vp8_frame(rng, 5000, key=False)
    pls = vp8.packetize(frame, picture_id=300, tl0picidx=2, tid=1,
                        max_payload=500)
    assert all(len(p) <= 500 for p in pls)
    batch = rtp_header.build(
        pls, list(range(len(pls))), [77] * len(pls), [1] * len(pls),
        [96] * len(pls), marker=[0] * (len(pls) - 1) + [1])
    fa = vp8.FrameAssembler()
    fa.push_batch(batch)
    assert fa.pop_frames()[0][3] == frame


def test_video_sfu_e2e_ivf_to_webm(tmp_path):
    rng = np.random.default_rng(7)
    ivf_path = str(tmp_path / "in.ivf")
    frames = _author_ivf(ivf_path, rng)

    # sender leg: packetize each IVF frame, SRTP-protect
    tx = SrtpStreamTable(capacity=4)
    tx.add_stream(0, MK, MS)
    sfu_rx = SrtpStreamTable(capacity=4)
    sfu_rx.add_stream(0, MK, MS)
    tr = RtpTranslator(capacity=8)
    for r, (mk, ms) in RECV_KEYS.items():
        tr.add_receiver(r, mk, ms)
    tr.connect(0, list(RECV_KEYS))

    legs = {}
    for r, (mk, ms) in RECV_KEYS.items():
        leg = SrtpStreamTable(capacity=8)
        leg.add_stream(3, mk, ms)
        legs[r] = (leg, vp8.FrameAssembler())

    seq = 400
    reader = IvfReader(ivf_path)
    assert reader.frame_count == len(frames)
    for pts, frame in reader:
        payloads = vp8.packetize(frame, picture_id=pts, max_payload=1100)
        n = len(payloads)
        batch = rtp_header.build(
            payloads, [seq + i for i in range(n)], [pts * 3000] * n,
            [0xCAFE] * n, [100] * n, marker=[0] * (n - 1) + [1],
            stream=[0] * n)
        seq += n
        wire = tx.protect_rtp(batch)
        # SFU: decrypt once, fan out re-encrypted per receiver
        dec, ok, idx = sfu_rx.unprotect_rtp(wire, return_index=True)
        assert ok.all()
        out, recv = tr.translate(dec, idx)
        for r, (leg, fa) in legs.items():
            rows = np.nonzero(recv == r)[0]
            sub = PacketBatch.from_payloads(
                [out.to_bytes(int(i)) for i in rows], stream=[3] * len(rows))
            dec_r, ok_r = leg.unprotect_rtp(sub)
            assert ok_r.all()
            fa.push_batch(dec_r)

    # every receiver reassembles the original frames byte-identically
    popped = {}
    for r, (leg, fa) in legs.items():
        got = fa.pop_frames()
        assert [d for _, _, _, d in got] == frames
        assert bool(got[0][2])              # first frame is the keyframe
        popped[r] = got

    # record receiver 1's stream to WebM; sanity-check container magic
    from libjitsi_tpu.recording.webm import WebmWriter

    out_path = str(tmp_path / "out.webm")
    w = WebmWriter(out_path, width=320, height=180)
    for ts, pid, key, data in popped[1]:
        w.write_frame(data, ts_ms=int(ts) // 90, keyframe=bool(key))
    w.close()
    blob = open(out_path, "rb").read()
    assert blob[:4] == b"\x1a\x45\xdf\xa3" and len(blob) > sum(
        len(f) for f in frames)
