"""Gather-free bitsliced AES vs the table core.

The circuit is derived from GF(2^8) algebra at import (and the module
asserts its full S-box truth table then); these tests pin the batched
device paths: XLA bitsliced, Pallas interpret mode, the nd wrapper the
CTR/GCM call sites use, and the `set_core` seam end-to-end through
`srtp_protect`.
"""

import numpy as np
import pytest

from libjitsi_tpu.kernels import aes
from libjitsi_tpu.kernels.aes import aes_encrypt_table, expand_keys_batch
from libjitsi_tpu.kernels.aes_bitsliced import (
    aes_encrypt_bitsliced, aes_encrypt_bitsliced_nd,
    aes_encrypt_pallas_bitsliced)


@pytest.mark.slow      # the Boolean-circuit HLO is big; cold CPU
@pytest.mark.parametrize("key_len", [16, 32])   # compiles take minutes
def test_bitsliced_matches_table(key_len):
    rng = np.random.default_rng(1)
    rks = expand_keys_batch(
        rng.integers(0, 256, (24, key_len), dtype=np.uint8))
    blocks = rng.integers(0, 256, (24, 16), dtype=np.uint8)
    want = np.asarray(aes_encrypt_table(rks, blocks))
    assert np.array_equal(np.asarray(aes_encrypt_bitsliced(rks, blocks)),
                          want)
    got_p = np.asarray(aes_encrypt_pallas_bitsliced(rks, blocks,
                                                    interpret=True))
    assert np.array_equal(got_p, want)


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_bitsliced_nd_wrapper_broadcast_keys():
    """The CTR path calls with [B, n, R, 16] broadcast keys."""
    rng = np.random.default_rng(2)
    rks = expand_keys_batch(rng.integers(0, 256, (6, 16), dtype=np.uint8))
    rk4 = np.broadcast_to(rks[:, None], (6, 3, 11, 16))
    blocks = rng.integers(0, 256, (6, 3, 16), dtype=np.uint8)
    want = np.asarray(aes_encrypt_table(rk4, blocks))
    got = np.asarray(aes_encrypt_bitsliced_nd(rk4, blocks))
    assert np.array_equal(got, want)


@pytest.mark.slow          # set_core clears jax caches -> recompiles
def test_set_core_switches_srtp_protect_bit_identically():
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    rng = np.random.default_rng(3)
    mk, ms = bytes(range(16)), bytes(range(40, 54))

    def protect():
        t = SrtpStreamTable(capacity=2)
        t.add_stream(0, mk, ms)
        b = rtp_header.build([b"core-check-%d" % i for i in range(4)],
                             [50 + i for i in range(4)], [0] * 4,
                             [0xC0DE] * 4, [96] * 4, stream=[0] * 4)
        return [t.protect_rtp(b).to_bytes(i) for i in range(4)]

    assert aes.get_core() == "table"
    want = protect()
    try:
        aes.set_core("bitsliced")
        assert protect() == want
        aes.set_core("bitsliced_tower")   # the TPU production default
        assert protect() == want
    finally:
        aes.set_core("table")


def test_registry_lists_aes_providers():
    from libjitsi_tpu.kernels import registry

    assert set(registry.providers("aes_encrypt")) >= {
        "xla_table", "xla_bitsliced", "pallas_bitsliced"}


@pytest.mark.slow   # two fresh packed-circuit compiles (~1-2 min cold)
def test_bitsliced32_packed_words_bit_exact():
    """The packed-word provider (32 blocks per uint32 word, per-block
    keys packed the same way) must match the table core bit for bit,
    including the non-multiple-of-32 pad path and AES-256."""
    rng = np.random.default_rng(9)
    from libjitsi_tpu.kernels.aes_bitsliced import aes_encrypt_bitsliced32

    for n, kl in ((33, 16), (64, 32)):
        rks = aes.expand_keys_batch(
            rng.integers(0, 256, (n, kl), dtype=np.uint8))
        blocks = rng.integers(0, 256, (n, 16), dtype=np.uint8)
        want = np.asarray(aes.aes_encrypt_table(rks, blocks))
        got = np.asarray(aes_encrypt_bitsliced32(rks, blocks))
        assert np.array_equal(got, want), (n, kl)


@pytest.mark.slow   # full tower-cipher compile, AES-128 + AES-256
def test_bitsliced_tower_sbox_and_provider_bit_exact():
    """The composite-field (GF((2^4)^2)) provider must match the table
    core bit for bit — AES-128 and AES-256 (the tower parameters and
    basis-change matrices are derived+asserted at import; this pins the
    full cipher)."""
    rng = np.random.default_rng(5)
    from libjitsi_tpu.kernels.aes_bitsliced import \
        aes_encrypt_bitsliced_tower

    for n, kl in ((48, 16), (48, 32)):
        rks = aes.expand_keys_batch(
            rng.integers(0, 256, (n, kl), dtype=np.uint8))
        blocks = rng.integers(0, 256, (n, 16), dtype=np.uint8)
        want = np.asarray(aes.aes_encrypt_table(rks, blocks))
        got = np.asarray(aes_encrypt_bitsliced_tower(rks, blocks))
        assert np.array_equal(got, want), (n, kl)
    # the _nd wrapper with BROADCAST keys — the exact shape the
    # CTR/GCM call sites feed the TPU default dispatch
    from libjitsi_tpu.kernels.aes_bitsliced import \
        aes_encrypt_bitsliced_tower_nd

    rks = aes.expand_keys_batch(
        rng.integers(0, 256, (6, 16), dtype=np.uint8))
    blocks = rng.integers(0, 256, (6, 3, 16), dtype=np.uint8)
    rk_b = np.broadcast_to(rks[:, None], (6, 3, 11, 16))
    want = np.asarray(aes.aes_encrypt_table(
        rks[:, None].repeat(3, 1).reshape(-1, 11, 16),
        blocks.reshape(-1, 16))).reshape(6, 3, 16)
    got = np.asarray(aes_encrypt_bitsliced_tower_nd(rk_b, blocks))
    assert np.array_equal(got, want)


def test_tower_sbox_circuit_matches_table_fast():
    """Fast twin of the tower provider test: the composite-field S-box
    circuit over all 256 inputs, evaluated in plain numpy (no jit, no
    full-cipher compile).  The slow twin pins the assembled cipher."""
    from libjitsi_tpu.kernels.aes import _SBOX
    from libjitsi_tpu.kernels.aes_bitsliced import (_sbox_bits,
                                                    _sbox_bits_tower)

    xs = np.arange(256, dtype=np.uint8)
    bits = [((xs >> p) & 1).astype(np.uint8) for p in range(8)]
    for impl in (_sbox_bits, _sbox_bits_tower):
        out = impl(bits)
        got = np.zeros(256, dtype=np.uint16)
        for p in range(8):
            got |= out[p].astype(np.uint16) << p
        assert np.array_equal(got.astype(np.uint8), _SBOX), impl.__name__
