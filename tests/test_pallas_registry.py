"""Pallas kernel twins + provider registry (reference pattern: crypto.Aes
benchmarks AES providers at startup and installs the fastest)."""

import numpy as np
import pytest

from libjitsi_tpu.conference.mixer import AudioMixer, _mix_jit
from libjitsi_tpu.kernels import registry
from libjitsi_tpu.kernels.pallas_ops import mix_minus_pallas


def _rand_frame(n=32, f=960, seed=0):
    rng = np.random.default_rng(seed)
    pcm = rng.integers(-20000, 20000, (n, f)).astype(np.int16)
    active = rng.random(n) < 0.8
    active[1] = False
    pcm[2] = 0                      # silent-but-active row
    return pcm, active


def test_pallas_mixer_bit_identical_to_xla():
    pcm, active = _rand_frame()
    out_x, lvl_x = _mix_jit(pcm, active)
    out_p, lvl_p = mix_minus_pallas(pcm, active, interpret=True)
    np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))
    np.testing.assert_array_equal(np.asarray(lvl_x), np.asarray(lvl_p))
    assert np.asarray(lvl_p)[1] == 127      # inactive -> silence level
    assert np.asarray(lvl_p)[2] == 127      # silent   -> silence level


def test_registry_selects_and_pins_a_provider():
    assert sorted(registry.providers("mix_minus")) == ["pallas", "xla"]
    registry.force("mix_minus", None)
    mixer = AudioMixer(capacity=16, frame_samples=960)
    for sid in range(4):
        mixer.add_participant(sid)
        mixer.push(sid, np.full(960, 100 * (sid + 1), np.int16))
    out, lvl = mixer.mix()
    total = sum(100 * (s + 1) for s in range(4))
    for sid in range(4):
        assert out[sid, 0] == total - 100 * (sid + 1)
    rep = registry.report()["mix_minus"]
    assert rep["choices"], "first call must have pinned a provider"
    assert all(len(t) == 2 for t in rep["timings_ms"].values()), \
        "both providers must have been timed"


def test_registry_force_each_provider_same_result():
    pcm, active = _rand_frame(seed=7)
    results = {}
    for prov in registry.providers("mix_minus"):
        registry.force("mix_minus", prov)
        try:
            out, lvl = registry.call("mix_minus", pcm, active)
            results[prov] = (np.asarray(out), np.asarray(lvl))
        finally:
            registry.force("mix_minus", None)
    a, b = results["xla"], results["pallas"]
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_array_equal(a[1], b[1])


def test_registry_force_unknown_provider_rejected():
    with pytest.raises(KeyError):
        registry.force("mix_minus", "cuda")


def test_warmup_pins_before_first_tick_and_errors_are_recorded():
    registry.force("mix_minus", None)
    mixer = AudioMixer(capacity=8, frame_samples=960)   # warms in __init__
    sig_choices = registry.report()["mix_minus"]["choices"]
    assert any("(8, 960)" in k for k in sig_choices), sig_choices
    # a broken provider is excluded WITH a recorded reason, not silently
    def boom(pcm, active):
        raise RuntimeError("mosaic lowering failed")
    registry.register("mix_minus_err", "xla", _mix_jit)
    registry.register("mix_minus_err", "broken", boom)
    pcm, active = _rand_frame(n=8)
    out, lvl = registry.call("mix_minus_err", pcm, active)
    rep = registry.report()["mix_minus_err"]
    errs = list(rep["errors"].values())
    assert errs and "mosaic lowering failed" in str(errs[0])


def test_config_key_overrides_selection():
    import libjitsi_tpu
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    registry.force("mix_minus", None)
    cfg.set("kernels.provider.mix_minus", "pallas")
    try:
        pcm, active = _rand_frame(n=8, seed=3)
        out, lvl = registry.call("mix_minus", pcm, active)
        # config forced pallas: no benchmarking entry for this signature
        out_x, lvl_x = _mix_jit(pcm, active)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out_x))
    finally:
        cfg.set("kernels.provider.mix_minus", None)
