"""Conference-affinity placement (PR 10): the placer's invariants
(never straddles, deterministic, hysteresis rebalance), the shard row
allocator, the zero-collective shard-local kernels against numpy and
the single-device reference, and the lifecycle integration — shard-
ranged row draw, shard-burn admission, and the rebalance move that
relocates a whole conference bit-exactly through the commit barrier.
"""

import numpy as np
import jax
import pytest

import libjitsi_tpu
from libjitsi_tpu.mesh import make_media_mesh
from libjitsi_tpu.mesh.placement import (ConferencePlacer,
                                         PlacementMove,
                                         ShardRowAllocator,
                                         shard_local_mix, size_class)
from libjitsi_tpu.mesh.parity import assert_affinity_parity
from libjitsi_tpu.service.lifecycle import StreamLifecycleManager
from libjitsi_tpu.service.sfu_bridge import SfuBridge
from libjitsi_tpu.service.supervisor import (BridgeSupervisor,
                                             SupervisorConfig)
from libjitsi_tpu.utils.metrics import MetricsRegistry
from libjitsi_tpu.utils.slo import SloEngine, SlicedSloSpec


# ------------------------------------------------------------- placer

def test_size_class_ladder():
    assert size_class(1) == 4
    assert size_class(4) == 4
    assert size_class(5) == 8
    assert size_class(200) == 256
    assert size_class(5000) == 5000     # giant: costed at true size


def test_never_straddles_under_random_churn():
    """The module's one invariant, property-tested: through any mix of
    places, grows, shrinks and releases, every conference maps to
    exactly one shard and per-shard row accounting stays exact."""
    rng = np.random.default_rng(42)
    p = ConferencePlacer(4, rows_per_shard=32)
    alive = {}                                   # conf -> n
    next_conf = 0
    for _ in range(600):
        op = rng.integers(0, 4)
        if op == 0 or not alive:
            shard = p.place(next_conf, int(rng.integers(1, 9)))
            if shard is not None:
                alive[next_conf] = p._size_of[next_conf]
            next_conf += 1
        elif op == 1:
            conf = int(rng.choice(list(alive)))
            if p.try_grow(conf):
                alive[conf] += 1
        elif op == 2:
            conf = int(rng.choice(list(alive)))
            p.shrink(conf)
            alive[conf] -= 1
            if alive[conf] <= 0:
                del alive[conf]
        else:
            conf = int(rng.choice(list(alive)))
            p.release(conf)
            del alive[conf]
        # invariant 1: one shard per conference, never more
        for conf in alive:
            assert p.shard_of(conf) is not None
        # invariant 2: accounting is exactly the sum of its members
        rows = [0] * p.n_shards
        for conf, n in alive.items():
            rows[p.shard_of(conf)] += n
        assert rows == [ld.rows for ld in p._loads]
        assert all(ld.rows <= p.rows_per_shard for ld in p._loads)


def test_identical_join_order_places_identically():
    seq = [(c, 1 + (c * 7) % 6) for c in range(40)]
    a = ConferencePlacer(8, rows_per_shard=16)
    b = ConferencePlacer(8, rows_per_shard=16)
    for conf, n in seq:
        assert a.place(conf, n) == b.place(conf, n)
    assert a._shard_of == b._shard_of


def test_place_least_loaded_ties_low_and_avoid_steers():
    p = ConferencePlacer(3, rows_per_shard=8)
    assert p.place(1, 2) == 0               # all empty: lowest index
    assert p.place(2, 2) == 1
    assert p.place(3, 2) == 2
    # avoid steers a new conference off the tied-lowest shard
    assert p.place(4, 2, avoid={0}) == 1
    # avoided shards are still used when they are the only room left
    p2 = ConferencePlacer(1, rows_per_shard=8)
    assert p2.place(1, 2, avoid={0}) == 0


def test_reject_when_full_is_typed_and_counted():
    p = ConferencePlacer(2, rows_per_shard=4)
    assert p.place(1, 4) == 0
    assert p.place(2, 4) == 1
    assert p.place(3, 1) is None
    assert p.rejects == 1
    # grow past the shard range is refused, never straddled
    assert not p.try_grow(1)
    assert p.shard_of(1) == 0 and p._size_of[1] == 4


def test_shrink_releases_empty_and_frees_room():
    p = ConferencePlacer(2, rows_per_shard=4)
    p.place(1, 2)
    p.shrink(1)
    assert p.shard_of(1) == 0
    p.shrink(1)
    assert p.shard_of(1) is None
    assert p.loads()[0] == (0.0, 0, 0)


def test_plan_rebalance_respects_hysteresis_then_moves():
    p = ConferencePlacer(4, rows_per_shard=4, max_moves=4)
    for conf, shard in ((1, 0), (2, 1), (3, 2), (4, 3)):
        assert p.place(conf, 2) == shard
    assert p.place(5, 2) == 0               # doubles up on shard 0
    assert p.plan_rebalance() == []         # balanced enough? no: hot
    # ... shard 0 carries 2x the mean but every move would just swap
    # who is hot (all conferences equal) — the planner must see that
    for conf in (2, 3, 4):
        p.release(conf)
    moves = p.plan_rebalance()              # now shards 1-3 are empty
    assert len(moves) == 1
    mv = moves[0]
    assert mv.src == 0 and mv.dst == 1 and mv.conf_id == 1
    # planning never mutates accounting; apply_move commits it
    assert p.shard_of(1) == 0
    p.apply_move(mv)
    assert p.shard_of(1) == 1
    assert p._loads[0].confs == 1 and p._loads[1].confs == 1


def test_apply_move_rejects_stale_plan():
    p = ConferencePlacer(2, rows_per_shard=4)
    p.place(1, 2)
    with pytest.raises(ValueError):
        p.apply_move(PlacementMove(1, 1, 0, 2))


def test_rebuild_matches_incremental_accounting():
    p = ConferencePlacer(4, rows_per_shard=16)
    for conf in range(10):
        p.place(conf, 1 + conf % 5)
    q = ConferencePlacer(4, rows_per_shard=16)
    q.rebuild((c, p.shard_of(c), p._size_of[c]) for c in range(10))
    assert q._shard_of == p._shard_of
    assert q.loads() == p.loads()


# ------------------------------------------------------ row allocator

def test_row_allocator_contiguous_ranges():
    a = ShardRowAllocator(16, 4)
    rows = a.alloc_many(2, 3)
    assert rows == [8, 9, 10]               # lowest rows of shard 2
    assert all(a.shard_of_row(r) == 2 for r in rows)
    assert a.free_rows(2) == 1
    with pytest.raises(RuntimeError):
        a.alloc_many(2, 2)
    a.free_many([9, 8])
    assert a.alloc_many(2, 2) == [8, 9]
    a2 = ShardRowAllocator(16, 4)
    a2.reserve([0, 1, 4])
    assert a2.alloc_many(0, 1) == [2]
    assert a2.alloc_many(1, 1) == [5]


# ------------------------------------------ zero-collective kernels

@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_media_mesh(jax.devices()[:8])


def test_shard_local_mix_matches_numpy(mesh):
    """Segment-sum mix-minus on the mesh vs a per-shard numpy model:
    each shard mixes only its own conferences — nothing crosses."""
    n_dev, per_shard, n_conf = 8, 8, 2
    B, F = n_dev * per_shard, 40
    rng = np.random.default_rng(9)
    pcm = rng.integers(-5000, 5000, (B, F)).astype(np.int16)
    active = rng.random(B) < 0.8
    conf = ((np.arange(B) // 4) % n_conf).astype(np.int32)
    got_mix, got_lvl = shard_local_mix(mesh, n_conf)(pcm, active, conf)
    got_mix = np.asarray(got_mix)
    p = pcm.astype(np.int64)
    contrib = np.where(active[:, None], p, 0)
    for s in range(n_dev):
        sl = slice(s * per_shard, (s + 1) * per_shard)
        for c in range(n_conf):
            rows = np.nonzero(conf[sl] == c)[0] + s * per_shard
            total = contrib[rows].sum(axis=0)
            want = np.clip(total[None, :] - contrib[rows],
                           -32768, 32767)
            np.testing.assert_array_equal(got_mix[rows], want)


def test_affinity_tick_parity_with_single_device_reference(mesh):
    """The full steady-state tick (unprotect -> segment-sum mix ->
    protect) on the mesh is bit-identical, shard by shard, to the same
    body run alone on one device — the structural zero-collective
    proof (shared harness with the driver dryrun and the perf gate)."""
    assert_affinity_parity(mesh, 8, b_shard=8, part=4)


# ------------------------------------------------ lifecycle integration

def _keys(k):
    return ((bytes([k & 0xFF]) * 16, bytes([(k + 1) & 0xFF]) * 14),
            (bytes([(k + 2) & 0xFF]) * 16, bytes([(k + 3) & 0xFF]) * 14))


def _universe(capacity=16, n_shards=4, slo=None, supervised=True):
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    bridge = SfuBridge(cfg, port=0, capacity=capacity, recv_window_ms=0)
    sup = None
    if supervised:
        sup = BridgeSupervisor(bridge,
                               SupervisorConfig(deadline_ms=1000.0),
                               slo=slo)
    lc = StreamLifecycleManager(bridge, supervisor=sup)
    lc._warm_bucket = 1 << 30           # warmup cadence tested elsewhere
    lc.enable_placement(n_shards)
    return bridge, sup, lc


def _settle(sup, lc, admits, t=100.0):
    for _ in range(64):
        if lc.admits >= admits:
            return t
        sup.tick(now=t)
        t += 0.02
    raise AssertionError(f"settle: admits={lc.admits}, want {admits}")


def test_joins_draw_rows_from_their_conference_shard():
    bridge, sup, lc = _universe()
    for i, conf in enumerate((7, 7, 8, 8, 7)):
        rx, tx = _keys(i)
        assert lc.request_join(0x100 + i, rx, tx, conference=conf)[0]
    _settle(sup, lc, 5)
    rows_per = lc._rows_per_shard
    conf_rows = {}
    for sid, conf in bridge._conf_of.items():
        conf_rows.setdefault(conf, []).append(sid)
    assert set(conf_rows) == {7, 8}
    assert len(conf_rows[7]) == 3 and len(conf_rows[8]) == 2
    for conf, sids in conf_rows.items():
        shard = lc.placer.shard_of(conf)
        assert shard is not None
        lo = shard * rows_per
        assert all(lo <= s < lo + rows_per for s in sids), \
            f"conference {conf} straddles shard ranges: {sids}"
    bridge.close()


def test_solo_joins_are_singleton_conferences():
    bridge, sup, lc = _universe()
    assert lc.request_join(0x200, *_keys(0))[0]
    assert lc.request_join(0x201, *_keys(1))[0]
    _settle(sup, lc, 2)
    confs = set(bridge._conf_of.values())
    assert len(confs) == 2
    assert all(c < 0 for c in confs)    # solo keys: never user ids
    bridge.close()


def test_conference_cannot_grow_past_its_shard_range():
    bridge, sup, lc = _universe(capacity=8, n_shards=2)  # 4 rows/shard
    for i in range(4):
        assert lc.request_join(0x300 + i, *_keys(i), conference=1)[0]
    _settle(sup, lc, 4)
    ok, why = lc.request_join(0x310, *_keys(9), conference=1)
    assert not ok and why == "capacity"
    # a NEW conference still fits: the other shard has the room
    ok, why = lc.request_join(0x311, *_keys(10), conference=2)
    assert ok, why
    _settle(sup, lc, 5)
    assert lc.placer.shard_of(2) == 1
    bridge.close()


def test_shard_burn_refuses_joins_and_steers_new_conferences():
    slo = SloEngine(MetricsRegistry())
    slo.add_sliced(SlicedSloSpec(
        name="shard_auth", objective=0.99, label="shard",
        reader=lambda: ()))
    bridge, sup, lc = _universe(capacity=8, n_shards=2, slo=slo)
    assert lc.request_join(0x400, *_keys(0), conference=1)[0]  # shard 0
    assert lc.request_join(0x401, *_keys(1), conference=2)[0]  # shard 1
    _settle(sup, lc, 2)
    assert lc.placer.shard_of(1) == 0 and lc.placer.shard_of(2) == 1
    # shard 0 starts burning its error budget fast
    slo._sstate["shard_auth"]["0"] = "fast_burn"
    ok, reason = sup.admission_decision(shard=0)
    assert not ok and reason == "shard_burn"
    assert sup.admission_decision(shard=1)[0]
    # join into the conference PINNED to the burning shard: refused
    # (it cannot straddle to a healthy one), reason typed + counted
    ok, reason = lc.request_join(0x402, *_keys(2), conference=1)
    assert not ok and reason == "shard_burn"
    assert lc.admit_rejected["shard_burn"] == 1
    # a new conference steers around the burning shard even though
    # placement cost alone would tie to it
    assert lc.request_join(0x403, *_keys(3), conference=3)[0]
    assert lc.placer.shard_of(3) == 1
    bridge.close()


def test_rebalance_moves_whole_conference_bit_exact():
    """The tentpole end-to-end: imbalance -> plan -> migrate through
    the commit barrier.  The moved conference's SRTP state (keys,
    salts, replay windows, rollover counters) must land bit-identical
    on the destination rows and the source rows must be fully torn
    down.  Driven without a supervisor so each pipeline stage
    (commit/poll/rebalance) is observable in isolation."""
    bridge, _sup, lc = _universe(capacity=16, n_shards=4,
                                 supervised=False)
    ssrc = 0x500
    joins = {1: 2, 2: 2, 3: 2, 4: 2, 5: 2}   # conf -> members
    k = 0
    for conf, n in joins.items():
        for _ in range(n):
            assert lc.request_join(ssrc + k, *_keys(k),
                                   conference=conf)[0]
            k += 1
    lc.poll()
    lc.commit()
    assert lc.admits == k
    # layout: confs 1..4 on shards 0..3, conf 5 doubled onto shard 0
    assert lc.placer.shard_of(5) == 0
    movers = sorted(s for s, c in bridge._conf_of.items() if c == 1)
    # give the movers non-trivial replay/rollover state: bit-exact
    # means THIS survives, not just virgin zeros
    bridge.rx_table.rx_max[movers] = [100_000, 200_000]
    bridge.rx_table.rx_mask[movers] = \
        np.array([0xDEAD, 0xBEEF], dtype=np.uint64)
    bridge.tx_table.tx_ext[movers] = [70_001, 80_001]
    before = {
        "ssrc": [bridge._ssrc_of[s] for s in movers],
        "rk": bridge.rx_table._rk_rtp[movers].copy(),
        "salt": bridge.rx_table._salt_rtp[movers].copy(),
        "rx_max": bridge.rx_table.rx_max[movers].copy(),
        "rx_mask": bridge.rx_table.rx_mask[movers].copy(),
        "tx_ext": bridge.tx_table.tx_ext[movers].copy(),
    }
    # drain confs 2..4 so shard 0 is hot against an empty field
    for sid, conf in list(bridge._conf_of.items()):
        if conf in (2, 3, 4):
            lc.request_leave(sid=sid)
    lc.commit()
    assert lc.evicts == 6
    moved = lc.rebalance()
    assert moved == 1 and lc.moves_applied == 1
    assert lc.placer.shard_of(1) == 1
    new_rows = sorted(s for s, c in bridge._conf_of.items() if c == 1)
    rows_per = lc._rows_per_shard
    assert all(rows_per <= s < 2 * rows_per for s in new_rows)
    # bit-exact: every per-row plane rode along unchanged
    assert [bridge._ssrc_of[s] for s in new_rows] == before["ssrc"]
    np.testing.assert_array_equal(
        bridge.rx_table._rk_rtp[new_rows], before["rk"])
    np.testing.assert_array_equal(
        bridge.rx_table._salt_rtp[new_rows], before["salt"])
    np.testing.assert_array_equal(
        bridge.rx_table.rx_max[new_rows], before["rx_max"])
    np.testing.assert_array_equal(
        bridge.rx_table.rx_mask[new_rows], before["rx_mask"])
    np.testing.assert_array_equal(
        bridge.tx_table.tx_ext[new_rows], before["tx_ext"])
    # source rows fully torn down and recyclable
    for s in movers:
        assert s not in bridge._ssrc_of
        assert not bridge.rx_table.active[s]
        assert s in bridge.registry._free
    ev = [e for e in lc.flight.dump_all()["global"]
          if e["kind"] == "placement_move"]
    assert ev and ev[-1]["conf"] == 1
    # once balanced, the planner stays quiet (hysteresis)
    assert lc.rebalance() == 0
    bridge.close()


def test_queued_or_staged_conference_skips_its_move():
    """Moving half a conference would straddle it — a conference with
    members still queued or staged must sit out the rebalance window
    and move whole in a later one."""
    bridge, _sup, lc = _universe(capacity=16, n_shards=4,
                                 supervised=False)
    k = 0
    for conf, n in ((1, 1), (2, 2), (3, 2), (4, 2), (5, 2)):
        for _ in range(n):
            assert lc.request_join(0x600 + k, *_keys(k),
                                   conference=conf)[0]
            k += 1
    lc.poll()
    lc.commit()
    assert lc.admits == k
    assert lc.placer.shard_of(1) == 0 and lc.placer.shard_of(5) == 0
    for sid, conf in list(bridge._conf_of.items()):
        if conf in (2, 3, 4):
            lc.request_leave(sid=sid)
    # a member of the would-move conference joins again: QUEUED
    assert lc.request_join(0x700, *_keys(99), conference=1)[0]
    lc.commit()                          # evicts land; shard 0 is hot
    assert lc.rebalance() == 0           # queued member: move waits
    lc.poll()                            # member now STAGED
    assert lc.rebalance() == 0           # still not whole: waits again
    lc.commit()                          # member live
    assert lc.rebalance() == 1           # whole again: move proceeds
    assert lc.placer.shard_of(1) == 1
    rows = [s for s, c in bridge._conf_of.items() if c == 1]
    rows_per = lc._rows_per_shard
    assert len(rows) == 2
    assert all(rows_per <= s < 2 * rows_per for s in rows)
    bridge.close()


def test_tick_bracket_stays_clean_under_placement_churn():
    """Acceptance criterion: placement-enabled churn lands zero NEW
    data-path recompiles inside tick brackets (the compile-cache guard
    active on every supervisor tick).  The first wave may pay one-time
    warmup of the idle-tick path; churn after it must add nothing."""
    bridge, sup, lc = _universe(capacity=8, n_shards=2)
    t = 100.0
    warmed = None
    for wave in range(3):
        base = 0x800 + 16 * wave
        for i in range(3):
            assert lc.request_join(base + i, *_keys(base + i),
                                   conference=wave + 1)[0]
        t = _settle(sup, lc, 3 * (wave + 1), t=t)
        for sid, conf in list(bridge._conf_of.items()):
            if conf == wave + 1:
                lc.request_leave(sid=sid)
        for _ in range(6):
            sup.tick(now=t)
            t += 0.02
        if warmed is None:
            warmed = lc.datapath_recompiles
    assert lc.datapath_recompiles == warmed
    bridge.close()
