"""Property test: snapshot -> serialize -> restore is the identity for
EVERY ArraySnapshotMixin subclass, over randomized array state.

The walk over `__subclasses__()` is the point: a new checkpointable
component (or a new array added to an existing one) that forgets to
list a field in `_SNAP_FIELDS` fails here — the restored instance keeps
the constructor default where the original held random state — instead
of surfacing as silent state loss after a crash-restart in production.
"""

import pickle

import numpy as np
import pytest

# import every module that defines subclasses so the walk sees them
import libjitsi_tpu.bwe.batched  # noqa: F401
import libjitsi_tpu.rtp.dense_jitter  # noqa: F401
from libjitsi_tpu.bwe.batched import BatchedRemoteBitrateEstimator
from libjitsi_tpu.rtp.dense_jitter import DenseJitterBank
from libjitsi_tpu.utils.checkpoint import ArraySnapshotMixin

# one small-but-nontrivial instance per class; a subclass missing here
# fails the coverage test below rather than being silently skipped
FACTORIES = {
    DenseJitterBank: lambda: DenseJitterBank(
        capacity=6, depth=8, payload_cap=32),
    BatchedRemoteBitrateEstimator:
        lambda: BatchedRemoteBitrateEstimator(6),
}


def _all_subclasses(cls):
    out = set()
    for sub in cls.__subclasses__():
        out.add(sub)
        out |= _all_subclasses(sub)
    return out


def _randomize(inst, rng):
    """Overwrite every ndarray attribute with random same-dtype data."""
    for name, val in vars(inst).items():
        if not isinstance(val, np.ndarray):
            continue
        if val.dtype == bool:
            val[...] = rng.random(val.shape) < 0.5
        elif np.issubdtype(val.dtype, np.floating):
            val[...] = rng.standard_normal(val.shape) * 1e3
        else:
            info = np.iinfo(val.dtype)
            val[...] = rng.integers(info.min, info.max, val.shape,
                                    dtype=val.dtype, endpoint=True)


def test_every_snapshot_subclass_has_a_factory():
    missing = [c.__name__ for c in _all_subclasses(ArraySnapshotMixin)
               if c not in FACTORIES]
    assert not missing, (
        f"register {missing} in FACTORIES so their snapshot/restore "
        f"identity is property-tested")


@pytest.mark.parametrize("cls", sorted(FACTORIES, key=lambda c: c.__name__),
                         ids=lambda c: c.__name__)
def test_snapshot_serialize_restore_identity(cls):
    rng = np.random.default_rng(0xC0FFEE)
    for trial in range(5):
        inst = FACTORIES[cls]()
        _randomize(inst, rng)
        blob = pickle.dumps(inst.snapshot(),
                            protocol=pickle.HIGHEST_PROTOCOL)
        back = cls.restore(pickle.loads(blob))
        for name, val in vars(inst).items():
            if not isinstance(val, np.ndarray):
                continue
            got = getattr(back, name)
            assert got.dtype == val.dtype, (cls.__name__, name)
            assert np.array_equal(got, val), (
                f"{cls.__name__}.{name} did not survive the roundtrip "
                f"(trial {trial}) — missing from _SNAP_FIELDS?")


def test_snapshot_is_a_copy_not_a_view():
    inst = FACTORIES[DenseJitterBank]()
    snap = inst.snapshot()
    field = DenseJitterBank._SNAP_FIELDS[0]
    before = snap[field].copy()
    getattr(inst, field)[...] = 0
    assert np.array_equal(snap[field], before), \
        "snapshot aliases live arrays; later mutation corrupts it"
