"""Deep-pipeline correctness: depths 1-3 must deliver every packet
exactly once and in per-stream order, the drain barrier must collapse
the pipeline at checkpoint / lifecycle commit points, arena views must
survive pinning, and the adaptive batcher must move its knobs the way
io/batching.py documents.

The property under test (ISSUE 9): pipelining reorders WORK, never
PACKETS — a depth-3 loop's observable output is the depth-1 loop's
output shifted in time.
"""

import socket
import struct
import time

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io.batching import AdaptiveBatcher
from libjitsi_tpu.io.loop import MediaLoop
from libjitsi_tpu.io.udp import UdpEngine
from libjitsi_tpu.service.media_stream import StreamRegistry
from libjitsi_tpu.transform.engine import TransformEngineChain
from libjitsi_tpu.transform.srtp.context import SrtpStreamTable
from libjitsi_tpu.transform.srtp.engine import SrtpTransformEngine

LOCALHOST = struct.unpack("!I", socket.inet_aton("127.0.0.1"))[0]
SSRCS = (0x1111, 0x2222)


def _table(cap=8, n_streams=2):
    t = SrtpStreamTable(capacity=cap)
    for sid in range(n_streams):
        t.add_stream(sid, bytes(range(16)), bytes(range(14)))
    return t


def _chain():
    return TransformEngineChain([SrtpTransformEngine(_table(), _table())],
                                names=["srtp"])


def _registry(cap=8):
    reg = StreamRegistry(libjitsi_tpu.configuration_service(),
                         capacity=cap)
    for i, ssrc in enumerate(SSRCS):
        reg.map_ssrc(ssrc, i)
    return reg


def _rtp(ssrc, seq, payload=b"x" * 40):
    hdr = struct.pack("!BBHII", 0x80, 96, seq & 0xFFFF, seq, ssrc)
    return hdr + payload


def _echo_loop(engine, depth, on_media=None):
    if on_media is None:
        def on_media(batch, ok):
            rows = np.nonzero(ok)[0]
            if len(rows) == 0:
                return None
            return PacketBatch(batch.data[rows].copy(),
                               np.asarray(batch.length)[rows].copy(),
                               np.asarray(batch.stream)[rows].copy())
    return MediaLoop(engine, _registry(), on_media=on_media,
                     chain=_chain(), recv_window_ms=1,
                     pipeline_depth=depth)


def _drain_replies(engine, want, timeout_s=2.0):
    """Collect reply datagrams at the peer; returns list of raw bytes."""
    out = []
    deadline = time.time() + timeout_s
    while time.time() < deadline and len(out) < want:
        rb, _, _ = engine.recv_batch(timeout_ms=20)
        lens = np.asarray(rb.length)
        for i in range(rb.batch_size):
            out.append(bytes(rb.data[i, :lens[i]]))
    return out


def _reply_seqs(raw_replies):
    """(ssrc, seq) of each reply — RTP headers ride in cleartext under
    SRTP, so the wire bytes demux without the reply-direction keys."""
    out = []
    for raw in raw_replies:
        seq = struct.unpack("!H", raw[2:4])[0]
        ssrc = struct.unpack("!I", raw[8:12])[0]
        out.append((ssrc, seq))
    return out


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_depth_delivers_every_packet_once_in_stream_order(depth):
    """No drop, no duplicate, no reorder-within-stream at any depth."""
    peer = UdpEngine(port=0)
    engine = UdpEngine(port=0)
    loop = _echo_loop(engine, depth)
    tx = _table()

    n_ticks, per_stream = 30, 2
    seq = {s: i * 1000 for i, s in enumerate(SSRCS)}
    sent = 0
    for _ in range(n_ticks):
        pkts, sids = [], []
        for sid, ssrc in enumerate(SSRCS):
            for _ in range(per_stream):
                pkts.append(_rtp(ssrc, seq[ssrc]))
                seq[ssrc] += 1
                sids.append(sid)
        b = PacketBatch.from_payloads(pkts, stream=sids)
        peer.send_batch(tx.protect_rtp(b), LOCALHOST, engine.port)
        sent += len(pkts)
        loop.tick()
    # idle ticks collapse the pipeline (n==0 -> drain)
    for _ in range(depth + 2):
        loop.tick()
    loop.drain()
    assert not loop._rx_inflight and not loop._inflight

    replies = _drain_replies(peer, sent)
    assert len(replies) == sent, f"lost/duplicated at depth {depth}"
    got = _reply_seqs(replies)
    assert len(set(got)) == sent, "duplicate (ssrc, seq) delivered"
    for ssrc in SSRCS:
        seqs = [s for (ss, s) in got if ss == ssrc]
        assert seqs == sorted(seqs), \
            f"reordered within stream {ssrc:#x} at depth {depth}"
    peer.close()
    engine.close()


def _hist_p99_upper(counts, uppers):
    """p99 upper bound from per-bucket counts: the `le` edge of the
    bucket holding the 99th-percentile sample (+Inf if it overflowed)."""
    counts = np.asarray(counts, dtype=np.int64)
    total = int(counts.sum())
    assert total > 0
    cum = np.cumsum(counts)
    idx = int(np.searchsorted(cum, int(np.ceil(0.99 * total))))
    return float(uppers[idx]) if idx < len(uppers) else float("inf")


def test_depth3_journey_p99_inside_tick_budget():
    """Acceptance (ISSUE 9): under pipelined load the end-to-end packet
    journey p99 — stamped at ingress arrival, observed at egress send,
    so it INCLUDES the depth-3 aging delay — stays inside the 0.02 s
    tick/ptime budget the `journey_p99` SLO keys on.  Pipelining
    overlaps work; it must not park packets.  Warmup ticks are
    snapshotted out so bucket compiles don't pollute the measurement
    (same discipline as perf_gate's host-share scenario)."""
    peer = UdpEngine(port=0)
    engine = UdpEngine(port=0)
    loop = _echo_loop(engine, depth=3)
    tx = _table()

    seq = {s: i * 1000 for i, s in enumerate(SSRCS)}

    def burst_and_tick():
        pkts, sids = [], []
        for sid, ssrc in enumerate(SSRCS):
            for _ in range(4):
                pkts.append(_rtp(ssrc, seq[ssrc]))
                seq[ssrc] += 1
                sids.append(sid)
        b = PacketBatch.from_payloads(pkts, stream=sids)
        peer.send_batch(tx.protect_rtp(b), LOCALHOST, engine.port)
        loop.tick()
        return len(pkts)

    for _ in range(12):                        # warm: compiles land here
        burst_and_tick()
    loop.drain()
    h = loop.journey_hist
    warm_counts = h.bucket_counts.copy()

    sent = sum(burst_and_tick() for _ in range(100))
    loop.drain()

    steady = h.bucket_counts - warm_counts
    assert int(steady.sum()) >= sent           # every packet observed
    p99 = _hist_p99_upper(steady, h.uppers)
    assert p99 <= 0.02, f"journey p99 bucket {p99}s blows the tick budget"
    peer.close()
    engine.close()


class _StubBridge:
    """Minimal bridge for BridgeSupervisor: the loop IS the tick."""

    def __init__(self, loop):
        self.loop = loop
        self.degraded = False

    def tick(self, now=None):
        return self.loop.tick()

    def snapshot(self):
        return {"stub": True}


def test_checkpoint_mid_pipeline_drains_then_delivers_exactly_once(
        tmp_path):
    """save_checkpoint is a drain barrier: a depth-3 checkpoint taken
    with work in flight materializes everything first, and nothing is
    lost or double-sent across it."""
    from libjitsi_tpu.service.supervisor import (BridgeSupervisor,
                                                 SupervisorConfig)

    peer = UdpEngine(port=0)
    engine = UdpEngine(port=0)
    loop = _echo_loop(engine, depth=3)
    sup = BridgeSupervisor(_StubBridge(loop),
                           SupervisorConfig(deadline_ms=1000.0))
    tx = _table()

    seq = {s: 0 for s in SSRCS}
    sent = 0
    ckpt = str(tmp_path / "mid.ckpt")
    for t in range(20):
        pkts, sids = [], []
        for sid, ssrc in enumerate(SSRCS):
            pkts.append(_rtp(ssrc, seq[ssrc]))
            seq[ssrc] += 1
            sids.append(sid)
        b = PacketBatch.from_payloads(pkts, stream=sids)
        peer.send_batch(tx.protect_rtp(b), LOCALHOST, engine.port)
        sent += len(pkts)
        sup.tick()
        if t == 9:
            # mid-run, with entries in flight: the barrier must clear
            # them BEFORE the snapshot is cut
            assert loop._rx_inflight or loop._inflight
            sup.save_checkpoint(ckpt)
            assert not loop._rx_inflight and not loop._inflight
    for _ in range(5):
        sup.tick()
    loop.drain()

    replies = _drain_replies(peer, sent)
    assert len(replies) == sent
    assert len(set(_reply_seqs(replies))) == sent
    peer.close()
    engine.close()


def test_lifecycle_commit_runs_behind_drain_barrier():
    """StreamLifecycleManager.commit() collapses the loop pipeline
    before evicting rows the in-flight work may still reference."""
    from libjitsi_tpu.service.lifecycle import StreamLifecycleManager

    calls = []

    class _Loop:
        def drain(self):
            calls.append("drain")

    class _Reg:
        free_slots = 4

    class _Bridge:
        loop = _Loop()
        registry = _Reg()
        _ssrc_of = {3: 0xAA}
        flight = None

        def remove_endpoints(self, sids):
            calls.append(("remove", list(sids)))

        def commit_endpoints(self, sids):
            calls.append(("commit", list(sids)))

    lc = StreamLifecycleManager(_Bridge())
    lc.commit()                      # nothing staged: no barrier needed
    assert calls == []
    lc._evict_q.append(3)
    lc.commit()
    assert calls == ["drain", ("remove", [3])], \
        "drain must precede the population flip"


def test_arena_views_survive_pinning_and_ring_growth():
    """A pinned recv view's bytes are never clobbered by later recv
    windows, even when every arena is pinned and the ring must grow.
    Engine-agnostic: each tag sends a full arena's worth of rows, so
    both the recvmmsg engine (fresh arena per window) and the io_uring
    engine (multiple windows share one armed arena until it exhausts —
    the registered-buffer mode this test must also hold under, see
    tests/test_io_uring.py for the mode-parametrized twins) run out of
    unpinned arenas and must grow."""
    tx_eng = UdpEngine(port=0)
    rx = UdpEngine(port=0, max_batch=8, arenas=2)
    rows = rx._rows

    def send_tagged(tag, n):
        pkts = [bytes([tag]) * 60 for _ in range(n)]
        tx_eng.send_batch(PacketBatch.from_payloads(pkts),
                          LOCALHOST, rx.port)

    views = []
    for tag in (0xA1, 0xB2, 0xC3):      # third round exceeds the ring
        send_tagged(tag, rows)
        got, batches = 0, []
        for _ in range(100):
            batch, _sip, _sport = rx.recv_batch_view(timeout_ms=20)
            if batch.batch_size:
                batches.append(batch)
            got += batch.batch_size
            if got >= rows:
                break
        assert got == rows
        views.append((tag, batches))
    assert rx.arena_grows >= 1, "ring should have grown while pinned"
    for tag, batches in views:
        for batch in batches:
            assert (batch.data[:, :60] == tag).all(), \
                f"arena bytes for {tag:#x} clobbered while pinned"
    # release: arenas recycle; double-release must not steal a pin
    for _tag, batches in views:
        for batch in batches:
            rx.release_arena(batch.arena_token)
            rx.release_arena(batch.arena_token)
    assert all(a.pins == 0 for a in rx._ring)
    tx_eng.close()
    rx.close()


def test_unknown_ssrc_warning_is_interval_suppressed(monkeypatch):
    """A flood of unmapped senders logs at most one warning per
    interval; the drop counter still counts every packet."""
    from libjitsi_tpu.io import loop as loop_mod

    warns = []
    monkeypatch.setattr(loop_mod._log, "warn",
                        lambda *a, **kw: warns.append(kw))

    peer = UdpEngine(port=0)
    engine = UdpEngine(port=0)
    loop = _echo_loop(engine, depth=1)
    loop.unknown_warn_interval = 10

    for _ in range(12):
        b = PacketBatch.from_payloads([_rtp(0xDEAD, 1), _rtp(0xBEEF, 2)])
        peer.send_batch(b, LOCALHOST, engine.port)
        for _ in range(50):
            if loop.tick():
                break
    unknown_warns = [w for w in warns if "suppressed" in w]
    assert loop.unknown_ssrc_dropped == 24
    assert 1 <= len(unknown_warns) <= 2, \
        f"expected ~1 warning per 10-tick interval, got {len(unknown_warns)}"
    if len(unknown_warns) == 2:
        assert unknown_warns[1]["suppressed"] > 0
        assert unknown_warns[1]["total"] > unknown_warns[0]["total"]
    peer.close()
    engine.close()


# ---------------------------------------------------- adaptive batching

class _FakeEngine:
    def __init__(self, max_batch=64):
        self.max_batch = max_batch


class _FakeLoop:
    def __init__(self, engine, recv_window_ms=1):
        self.engine = engine
        self.recv_window_ms = recv_window_ms
        self.rx_packets = 0


class _FakeSlo:
    def __init__(self):
        self._state = "ok"

    def state(self):
        return self._state


def test_batcher_backlog_forces_poll_mode_and_recovers():
    loop = _FakeLoop(_FakeEngine(64))
    slo = _FakeSlo()
    b = AdaptiveBatcher(loop, slo=slo)
    loop.rx_packets += 64               # window saturated
    b.on_tick()
    assert loop.recv_window_ms == 0 and loop.engine.max_batch == 64
    assert b.backlog_polls == 1
    loop.rx_packets += 3                # calm again
    b.on_tick()
    assert loop.recv_window_ms == b.base_window_ms


def test_batcher_fast_burn_shrinks_batch_then_recovers_additively():
    loop = _FakeLoop(_FakeEngine(64))
    slo = _FakeSlo()
    b = AdaptiveBatcher(loop, slo=slo, min_batch=8)
    slo._state = "fast_burn"
    for _ in range(5):
        b.on_tick()
    assert loop.engine.max_batch == 8   # halved to the floor
    assert loop.recv_window_ms == 0
    slo._state = "ok"
    b.on_tick()
    assert loop.engine.max_batch == 8 + max(1, 64 // 8)
    assert loop.recv_window_ms == b.base_window_ms
    for _ in range(20):
        b.on_tick()
    assert loop.engine.max_batch == 64  # fully recovered, never above


def test_batcher_respects_ladder_clamp():
    """While the supervisor's recv_window rung is held, the batcher
    must not write the window (the ladder owns it); the cap stays
    adaptive."""
    loop = _FakeLoop(_FakeEngine(64))
    slo = _FakeSlo()
    b = AdaptiveBatcher(loop, slo=slo)
    loop.recv_window_ms = 0             # ladder squeezed it
    b.clamp_window(True)
    slo._state = "fast_burn"
    b.on_tick()
    assert loop.recv_window_ms == 0
    assert loop.engine.max_batch == 32  # cap still adapts
    slo._state = "ok"
    b.on_tick()
    assert loop.recv_window_ms == 0, "clamped window must not re-widen"
    b.clamp_window(False)
    b.on_tick()
    assert loop.recv_window_ms == b.base_window_ms


def test_batcher_live_cap_bounds_next_recv_window():
    """engine.max_batch is honored live by the recv path: lowering it
    mid-run bounds the very next window."""
    tx_eng = UdpEngine(port=0)
    rx = UdpEngine(port=0, max_batch=32)
    pkts = [bytes([7]) * 60 for _ in range(16)]
    tx_eng.send_batch(PacketBatch.from_payloads(pkts), LOCALHOST, rx.port)
    rx.max_batch = 4
    time.sleep(0.05)
    batch, _, _ = rx.recv_batch(timeout_ms=100)
    assert 0 < batch.batch_size <= 4
    tx_eng.close()
    rx.close()
