"""SRTP/SRTCP tests: RFC 3711 KDF vectors, differential vs an independent
OpenSSL-backed oracle, replay/ROC state machine, SRTCP, checkpoint/restore.

The oracle below reimplements RFC 3711 protection scalar-per-packet straight
from the RFC using the `cryptography` package (OpenSSL) — no shared code
with the device path, so agreement is meaningful (mirrors the reference's
provider cross-check in `.srtp.crypto.Aes`).
"""

import hmac as hmac_mod
import hashlib

import numpy as np
import pytest
from cryptography.hazmat.primitives.ciphers import Cipher as CCipher
from cryptography.hazmat.primitives.ciphers import algorithms, modes

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable
from libjitsi_tpu.transform.srtp.kdf import derive_session_keys


# ---------------------------------------------------------------- oracle ---

def aes_ctr(key: bytes, iv16: bytes, data: bytes) -> bytes:
    enc = CCipher(algorithms.AES(key), modes.CTR(iv16)).encryptor()
    return enc.update(data) + enc.finalize()


def kdf_oracle(mk: bytes, ms: bytes, label: int, n: int) -> bytes:
    x = int.from_bytes(ms, "big") ^ (label << 48)
    return aes_ctr(mk, (x << 16).to_bytes(16, "big"), b"\x00" * n)


def protect_oracle(mk: bytes, ms: bytes, pkt: bytes, index: int,
                   tag_len: int) -> bytes:
    ke = kdf_oracle(mk, ms, 0, len(mk))
    ka = kdf_oracle(mk, ms, 1, 20)
    ksalt = int.from_bytes(kdf_oracle(mk, ms, 2, 14), "big")
    cc = pkt[0] & 0x0F
    off = 12 + 4 * cc
    ssrc = int.from_bytes(pkt[8:12], "big")
    iv = ((ksalt << 16) ^ (ssrc << 64) ^ (index << 16)).to_bytes(16, "big")
    ct = pkt[:off] + aes_ctr(ke, iv, pkt[off:])
    roc = index >> 16
    tag = hmac_mod.new(ka, ct + roc.to_bytes(4, "big"), hashlib.sha1).digest()
    return ct + tag[:tag_len]


def protect_rtcp_oracle(mk: bytes, ms: bytes, pkt: bytes, index: int,
                        tag_len: int) -> bytes:
    ke = kdf_oracle(mk, ms, 3, len(mk))
    ka = kdf_oracle(mk, ms, 4, 20)
    ksalt = int.from_bytes(kdf_oracle(mk, ms, 5, 14), "big")
    ssrc = int.from_bytes(pkt[4:8], "big")
    iv = ((ksalt << 16) ^ (ssrc << 64) ^ (index << 16)).to_bytes(16, "big")
    ct = pkt[:8] + aes_ctr(ke, iv, pkt[8:])
    word = ((1 << 31) | index).to_bytes(4, "big")
    tag = hmac_mod.new(ka, ct + word, hashlib.sha1).digest()
    return ct + word + tag[:tag_len]


MK = bytes(range(16))
MS = bytes(range(100, 114))


def make_table(profile=SrtpProfile.AES_CM_128_HMAC_SHA1_80, n=8, mk=MK, ms=MS):
    t = SrtpStreamTable(capacity=n, profile=profile)
    for i in range(n):
        t.add_stream(i, mk, ms)
    return t


def rtp_pkt(seq, ssrc=0x1234, payload=b"\xabuvwxyz123", pt=96, ts=3000):
    b = rtp_header.build([payload], [seq], [ts], [ssrc], [pt])
    return b.to_bytes(0)


# ------------------------------------------------------------------- KDF ---

def test_kdf_rfc3711_b3_vectors():
    mk = bytes.fromhex("E1F97A0D3E018BE0D64FA32C06DE4139")
    ms = bytes.fromhex("0EC675AD498AFEEBB6960B3AABE6")
    ks = derive_session_keys(mk, ms)
    assert ks.rtp_enc.hex().upper() == "C61E7A93744F39EE10734AFE3FF7A087"
    assert ks.rtp_salt.hex().upper() == "30CBBC08863D8C85D49DB34A9AE1"
    assert ks.rtp_auth.hex().upper() == (
        "CEBE321F6FF7716B6FD4AB49AF256A156D38BAA4")


def test_kdf_matches_independent_oracle():
    ks = derive_session_keys(MK, MS)
    assert ks.rtp_enc == kdf_oracle(MK, MS, 0, 16)
    assert ks.rtcp_auth == kdf_oracle(MK, MS, 4, 20)
    assert ks.rtcp_salt == kdf_oracle(MK, MS, 5, 14)


# --------------------------------------------------------------- protect ---

@pytest.mark.parametrize("profile,tag_len", [
    (SrtpProfile.AES_CM_128_HMAC_SHA1_80, 10),
    (SrtpProfile.AES_CM_128_HMAC_SHA1_32, 4),
    (SrtpProfile.AES_256_CM_HMAC_SHA1_80, 10),
])
def test_protect_differential_vs_oracle(profile, tag_len):
    mk = bytes(range(profile.policy.enc_key_len))
    t = make_table(profile, n=4, mk=mk)
    rng = np.random.default_rng(7)
    pkts, streams, indices = [], [], []
    per_stream_seq = {0: 100, 1: 65530, 2: 0, 3: 7}
    for i in range(24):
        sid = i % 4
        seq = per_stream_seq[sid]
        per_stream_seq[sid] = (seq + 1) & 0xFFFF
        payload = bytes(rng.integers(0, 256, rng.integers(1, 120), dtype=np.uint8))
        pkts.append(rtp_pkt(seq, ssrc=0x1000 + sid, payload=payload))
        streams.append(sid)
    batch = PacketBatch.from_payloads(pkts, stream=streams)
    out = t.protect_rtp(batch)

    # track expected 48-bit index per stream exactly like a sender would
    ext = {s: None for s in range(4)}
    for i, (p, sid) in enumerate(zip(pkts, streams)):
        seq = int.from_bytes(p[2:4], "big")
        if ext[sid] is None:
            ext[sid] = seq
        else:
            d = (seq - (ext[sid] & 0xFFFF) + 0x8000) % 0x10000 - 0x8000
            ext[sid] = ext[sid] + d
        expected = protect_oracle(mk, MS, p, ext[sid], tag_len)
        assert out.to_bytes(i) == expected, f"packet {i} mismatch"


@pytest.mark.slow
def test_roundtrip_and_auth_failure():
    t_tx = make_table()
    t_rx = make_table()
    pkts = [rtp_pkt(s, payload=bytes([s] * 50)) for s in range(20)]
    batch = PacketBatch.from_payloads(pkts, stream=[0] * 20)
    prot = t_tx.protect_rtp(batch)
    dec, ok = t_rx.unprotect_rtp(prot)
    assert ok.all()
    for i in range(20):
        assert dec.to_bytes(i) == pkts[i]
    # tamper one byte of each: auth must fail for all
    prot2 = t_tx.protect_rtp(PacketBatch.from_payloads(
        [rtp_pkt(s + 100) for s in range(5)], stream=[1] * 5))
    prot2 = prot2.copy()  # device output arrays are read-only views
    prot2.data[:, 20] ^= 0xFF
    _, ok2 = t_rx.unprotect_rtp(prot2)
    assert not ok2.any()


def test_roc_wraparound():
    """Sequence wrap 65535->0 must bump ROC in IV and auth (RFC 3711 App A)."""
    t = make_table(n=1)
    seqs = [65533, 65534, 65535, 0, 1, 2]
    pkts = [rtp_pkt(s) for s in seqs]
    out = t.protect_rtp(PacketBatch.from_payloads(pkts, stream=[0] * 6))
    for i, s in enumerate(seqs):
        index = s if s >= 65533 else (1 << 16) + s
        assert out.to_bytes(i) == protect_oracle(MK, MS, pkts[i], index, 10)
    assert t.tx_ext[0] == (1 << 16) + 2
    # receiver side: unprotect across the wrap works too
    rx = make_table(n=1)
    dec, ok = rx.unprotect_rtp(out)
    assert ok.all()
    assert rx.rx_max[0] == (1 << 16) + 2


def test_replay_rejection():
    t_tx, t_rx = make_table(), make_table()
    pkts = [rtp_pkt(s) for s in range(8)]
    prot = t_tx.protect_rtp(PacketBatch.from_payloads(pkts, stream=[0] * 8))
    _, ok1 = t_rx.unprotect_rtp(prot)
    assert ok1.all()
    # exact replay of the same batch: all rejected
    _, ok2 = t_rx.unprotect_rtp(prot)
    assert not ok2.any()


def test_replay_in_batch_duplicate():
    t_tx, t_rx = make_table(), make_table()
    p = rtp_pkt(500)
    prot = t_tx.protect_rtp(PacketBatch.from_payloads([p], stream=[0]))
    dup = PacketBatch.from_payloads([prot.to_bytes(0)] * 3, stream=[0] * 3)
    _, ok = t_rx.unprotect_rtp(dup)
    assert ok.sum() == 1 and ok[0]


@pytest.mark.slow
def test_replay_window_reorder_and_too_old():
    t_tx, t_rx = make_table(), make_table()
    pkts = {s: rtp_pkt(s) for s in range(0, 200)}
    prot = {}
    batch = PacketBatch.from_payloads([pkts[s] for s in range(200)],
                                      stream=[0] * 200)
    p = t_tx.protect_rtp(batch)
    for s in range(200):
        prot[s] = p.to_bytes(s)
    # deliver 199 first, then reordered 190 (inside window), then 100 (too old)
    _, ok = t_rx.unprotect_rtp(PacketBatch.from_payloads(
        [prot[199]], stream=[0]))
    assert ok.all()
    _, ok = t_rx.unprotect_rtp(PacketBatch.from_payloads(
        [prot[190], prot[100]], stream=[0, 0]))
    assert ok[0] and not ok[1]
    # replay of the reordered one is now rejected
    _, ok = t_rx.unprotect_rtp(PacketBatch.from_payloads(
        [prot[190]], stream=[0]))
    assert not ok.any()


def test_multi_stream_isolation():
    """Streams use independent key rows; wrong-row auth must fail."""
    t_tx = SrtpStreamTable(capacity=2)
    t_tx.add_stream(0, MK, MS)
    t_tx.add_stream(1, bytes(range(50, 66)), bytes(range(14)))
    p = rtp_pkt(10, ssrc=0xAAAA)
    prot0 = t_tx.protect_rtp(PacketBatch.from_payloads([p], stream=[0]))
    rx = SrtpStreamTable(capacity=2)
    rx.add_stream(0, MK, MS)
    rx.add_stream(1, bytes(range(50, 66)), bytes(range(14)))
    # right stream id: ok; wrong stream id: auth failure
    _, ok = rx.unprotect_rtp(PacketBatch(prot0.data.copy(),
                                         prot0.length.copy(),
                                         np.array([1], dtype=np.int32)))
    assert not ok.any()
    _, ok = rx.unprotect_rtp(prot0)
    assert ok.all()


def test_padded_packet_roundtrip():
    """P=1 packets must survive: pad length is ciphertext until decrypt."""
    t_tx, t_rx = make_table(), make_table()
    raw = bytearray(rtp_pkt(42, payload=b"hello" + bytes([0, 0, 3])))
    raw[0] |= 0x20  # set P bit; last payload byte 3 = pad count
    pkts = [bytes(raw)] * 1
    for trial in range(3):
        raw2 = bytearray(raw)
        raw2[2:4] = (42 + trial).to_bytes(2, "big")
        prot = t_tx.protect_rtp(PacketBatch.from_payloads([bytes(raw2)],
                                                          stream=[0]))
        dec, ok = t_rx.unprotect_rtp(prot)
        assert ok.all(), f"padded packet dropped on trial {trial}"
        assert dec.to_bytes(0) == bytes(raw2)


def test_forged_packet_does_not_poison_established_stream():
    """A garbage packet in the same batch must not shift the index estimate
    of a later genuine packet on an established stream."""
    t_tx, t_rx = make_table(), make_table()
    prot = t_tx.protect_rtp(PacketBatch.from_payloads(
        [rtp_pkt(100)], stream=[0]))
    _, ok = t_rx.unprotect_rtp(prot)
    assert ok.all()
    forged = bytearray(rtp_pkt(32868, payload=b"junkjunk"))
    genuine = t_tx.protect_rtp(PacketBatch.from_payloads(
        [rtp_pkt(101)], stream=[0]))
    both = PacketBatch.from_payloads(
        [bytes(forged), genuine.to_bytes(0)], stream=[0, 0])
    _, ok = t_rx.unprotect_rtp(both)
    assert not ok[0] and ok[1]


@pytest.mark.slow
def test_protect_near_capacity_grows_not_truncates():
    """A packet whose tag would overflow the input capacity gets a
    grown output buffer (size-class headroom), never silent truncation
    (it used to raise ValueError before bucketing added headroom)."""
    t = make_table()
    big = rtp_pkt(1, payload=bytes(1500 - 12))
    out = t.protect_rtp(PacketBatch.from_payloads([big], stream=[0]))
    assert out.length[0] == 1500 + 10
    assert out.capacity >= 1510
    assert out.to_bytes(0)[:12] == big[:12]          # header intact
    rx = make_table()
    dec, ok = rx.unprotect_rtp(out)
    assert ok.all() and dec.to_bytes(0) == big       # full roundtrip


# ------------------------------------------------------------------ RTCP ---

def rtcp_sr(ssrc=0x5678, n_extra=40):
    """Minimal RTCP SR: header + sender info (28 bytes) + padding filler."""
    body = bytearray()
    body += bytes([0x80, 200, 0, 6 + n_extra // 4 - 1])
    body += ssrc.to_bytes(4, "big")
    body += bytes(20)  # NTP/RTP ts, counts
    body += bytes(range(n_extra % 256)) * 1
    return bytes(body[: 28 + n_extra])


@pytest.mark.slow
def test_rtcp_differential_and_roundtrip():
    t_tx, t_rx = make_table(), make_table()
    pkts = [rtcp_sr(0x5678, 40), rtcp_sr(0x5678, 40), rtcp_sr(0x9999, 12)]
    batch = PacketBatch.from_payloads(pkts, stream=[0, 0, 1])
    prot = t_tx.protect_rtcp(batch)
    # index assignment: stream 0 gets 0,1; stream 1 gets 0
    assert prot.to_bytes(0) == protect_rtcp_oracle(MK, MS, pkts[0], 0, 10)
    assert prot.to_bytes(1) == protect_rtcp_oracle(MK, MS, pkts[1], 1, 10)
    assert prot.to_bytes(2) == protect_rtcp_oracle(MK, MS, pkts[2], 0, 10)
    dec, ok = t_rx.unprotect_rtcp(prot)
    assert ok.all()
    for i in range(3):
        assert dec.to_bytes(i) == pkts[i]
    # replay
    _, ok2 = t_rx.unprotect_rtcp(prot)
    assert not ok2.any()


# ------------------------------------------------------------ checkpoint ---

def test_snapshot_restore_preserves_replay_and_roc():
    t_tx, t_rx = make_table(), make_table()
    pkts = [rtp_pkt(s) for s in range(5)]
    prot = t_tx.protect_rtp(PacketBatch.from_payloads(pkts, stream=[0] * 5))
    _, ok = t_rx.unprotect_rtp(prot)
    assert ok.all()
    t_rx2 = SrtpStreamTable.restore(t_rx.snapshot())
    # replays still rejected after restore; fresh packets still accepted
    _, ok = t_rx2.unprotect_rtp(prot)
    assert not ok.any()
    p6 = t_tx.protect_rtp(PacketBatch.from_payloads([rtp_pkt(5)], stream=[0]))
    _, ok = t_rx2.unprotect_rtp(p6)
    assert ok.all()


def test_forged_frontrunner_does_not_block_genuine_duplicate_index():
    """A forged copy of a packet arriving EARLIER in the same batch must not
    knock out the authentic one (post-auth dedup, not pre-auth)."""
    t_tx, t_rx = make_table(), make_table()
    p = rtp_pkt(700)
    prot = t_tx.protect_rtp(PacketBatch.from_payloads([p], stream=[0]))
    genuine = prot.to_bytes(0)
    forged = bytearray(genuine)
    forged[14] ^= 0xFF  # corrupt payload -> auth fails, same seq/ssrc
    batch = PacketBatch.from_payloads([bytes(forged), genuine], stream=[0, 0])
    dec, ok = t_rx.unprotect_rtp(batch)
    assert not ok[0] and ok[1]
    assert dec.to_bytes(1) == p


def test_protect_rejects_unmapped_stream():
    """Protect must raise on stream=-1 / inactive rows instead of silently
    corrupting another row's tx state via negative indexing."""
    t = make_table(n=4)
    t.remove_stream(3)
    before = t.tx_ext.copy()
    p = rtp_pkt(1)
    with pytest.raises(KeyError):
        t.protect_rtp(PacketBatch.from_payloads([p], stream=[-1]))
    with pytest.raises(KeyError):
        t.protect_rtp(PacketBatch.from_payloads([p], stream=[3]))  # inactive
    with pytest.raises(KeyError):
        t.protect_rtp(PacketBatch.from_payloads([p], stream=[99]))  # range
    np.testing.assert_array_equal(t.tx_ext, before)


# ----------------------------------------------------- batch install ---

def test_add_streams_matches_scalar_install_all_profiles():
    """The vectorized install plane (bulk joins / restore / bootstrap)
    must produce bit-identical tables and state to per-stream
    add_stream, for CM, GCM and F8 profiles, incl. kdr streams."""
    rng = np.random.default_rng(11)
    for prof in (SrtpProfile.AES_CM_128_HMAC_SHA1_80,
                 SrtpProfile.AES_256_CM_HMAC_SHA1_80,
                 SrtpProfile.AEAD_AES_128_GCM,
                 SrtpProfile.F8_128_HMAC_SHA1_80):
        n = 6
        mks = rng.integers(0, 256, (n, prof.policy.enc_key_len),
                           dtype=np.uint8)
        mss = rng.integers(0, 256, (n, prof.policy.salt_len),
                           dtype=np.uint8)
        kdrs = np.array([0, 0, 16, 0, 256, 0])
        t1 = SrtpStreamTable(capacity=n, profile=prof)
        for i in range(n):
            t1.add_stream(i, mks[i].tobytes(), mss[i].tobytes(),
                          kdr=int(kdrs[i]))
        t2 = SrtpStreamTable(capacity=n, profile=prof)
        t2.add_streams(np.arange(n), mks, mss, kdr=kdrs)
        for attr in ('_rk_rtp', '_rk_rtcp', '_mid_rtp', '_mid_rtcp',
                     '_salt_rtp', '_salt_rtcp', 'tx_ext', 'rx_max',
                     'rx_mask', 'kdr', 'active'):
            assert np.array_equal(getattr(t1, attr), getattr(t2, attr)), \
                (prof, attr)
        if t1._gcm:
            assert np.array_equal(t1._gm_rtp, t2._gm_rtp)
            assert np.array_equal(t1._gm_rtcp, t2._gm_rtcp)
        if t1._f8:
            assert np.array_equal(t1._rk_f8_rtp, t2._rk_f8_rtp)
            assert np.array_equal(t1._rk_f8_rtcp, t2._rk_f8_rtcp)
        assert t1._masters == t2._masters


def test_kdf_batch_matches_scalar_with_epochs():
    from libjitsi_tpu.transform.srtp.kdf import derive_session_keys_batch

    rng = np.random.default_rng(12)
    for ekl in (16, 32):
        mks = rng.integers(0, 256, (5, ekl), dtype=np.uint8)
        mss = rng.integers(0, 256, (5, 14), dtype=np.uint8)
        r = np.array([0, 1, 5, 1000, 2**40], dtype=np.int64)
        rc = np.array([0, 2, 9, 0, 77], dtype=np.int64)
        ksb = derive_session_keys_batch(mks, mss, enc_key_len=ekl,
                                        r=r, rc=rc)
        for i in range(5):
            want = derive_session_keys(
                mks[i].tobytes(), mss[i].tobytes(), enc_key_len=ekl,
                kdr=1, index=int(r[i]), srtcp_index=int(rc[i]))
            got = ksb.row(i)
            for f in ('rtp_enc', 'rtp_auth', 'rtp_salt', 'rtcp_enc',
                      'rtcp_auth', 'rtcp_salt'):
                assert getattr(got, f) == getattr(want, f), (ekl, i, f)


def test_protect_rtp_async_matches_sync():
    """Double-buffered dispatch: N in-flight protects materialize to
    exactly what the sync path produces, with identical TX state."""
    rng = np.random.default_rng(21)
    t_sync = make_table(n=4)
    t_async = make_table(n=4)
    pendings = []
    batches = []
    for k in range(3):                      # three batches in flight
        pkts, sids = [], []
        for i in range(12):
            payload = bytes(rng.integers(0, 256, 30 + 40 * (i % 3),
                                         dtype=np.uint8))
            pkts.append(rtp_pkt(200 + 3 * k + i // 4,
                                ssrc=0x2000 + i % 4, payload=payload))
            sids.append(i % 4)
        b = PacketBatch.from_payloads(pkts, stream=sids)
        batches.append(b)
        pendings.append(t_async.protect_rtp_async(b))
    for k, (b, p) in enumerate(zip(batches, pendings)):
        want = t_sync.protect_rtp(b)
        got = p.result()
        for i in range(b.batch_size):
            assert got.to_bytes(i) == want.to_bytes(i), (k, i)
        assert p.result() is got            # single-shot cache
    assert np.array_equal(t_sync.tx_ext, t_async.tx_ext)


def test_key_mutation_while_protect_pending_is_safe():
    """CPU-backend jnp.asarray can alias host buffers: installing or
    removing keys while async protects are in flight must not corrupt
    the dispatched batches (copy-on-write in the mutators)."""
    rng = np.random.default_rng(30)
    t = make_table(n=4)
    ref = make_table(n=4)
    pkts = [rtp_pkt(700 + i, ssrc=0x3000 + i % 4,
                    payload=bytes(rng.integers(0, 256, 60, dtype=np.uint8)))
            for i in range(8)]
    b = PacketBatch.from_payloads(pkts, stream=[i % 4 for i in range(8)])
    want = ref.protect_rtp(b)
    pend = t.protect_rtp_async(b)
    # mutate the tables while the batch is (potentially) in flight
    t.add_stream(2, bytes(range(50, 66)), bytes(range(70, 84)))
    t.remove_stream(3)
    got = pend.result()
    for i in range(8):
        assert got.to_bytes(i) == want.to_bytes(i), i
