"""Native UDP engine (recvmmsg/sendmmsg batching) + pcap/rtpdump codecs.

Loopback tests on ephemeral ports exercise the real syscalls; the pcap
written here is also cross-checked structurally.
"""

import os

import numpy as np
import pytest

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io import (
    PcapReader,
    PcapWriter,
    RtpdumpReader,
    RtpdumpWriter,
    UdpEngine,
)
from libjitsi_tpu.io.udp import ip_to_u32


def test_udp_loopback_batch_roundtrip():
    rx = UdpEngine(port=0, capacity=256, max_batch=64)
    tx = UdpEngine(port=0, capacity=256, max_batch=64)
    pkts = [b"pkt-%03d" % i + bytes(i) for i in range(32)]
    batch = PacketBatch.from_payloads(pkts, capacity=256)
    sent = tx.send_batch(batch, "127.0.0.1", rx.port)
    assert sent == 32
    got, sip, sport = rx.recv_batch(timeout_ms=500)
    # UDP may reorder within the kernel queue, though loopback rarely does
    got_set = {got.to_bytes(i) for i in range(got.batch_size)}
    assert got_set == set(pkts)
    assert (sip == ip_to_u32("127.0.0.1")).all()
    assert (sport == tx.port).all()
    rx.close()
    tx.close()


def test_udp_recv_timeout_and_empty_send():
    rx = UdpEngine(port=0)
    got, _, _ = rx.recv_batch(timeout_ms=10)
    assert got.batch_size == 0
    assert rx.send_batch(PacketBatch.empty(0), "127.0.0.1", 1) == 0
    rx.close()


def test_udp_reuseport_sharding():
    a = UdpEngine(port=0, reuseport=True)
    b = UdpEngine(port=a.port, reuseport=True)  # same port, second engine
    tx = UdpEngine(port=0)
    n = 64
    batch = PacketBatch.from_payloads([b"x%d" % i for i in range(n)])
    tx.send_batch(batch, "127.0.0.1", a.port)
    got_a, _, _ = a.recv_batch(timeout_ms=300)
    got_b, _, _ = b.recv_batch(timeout_ms=50)
    assert got_a.batch_size + got_b.batch_size == n
    for e in (a, b, tx):
        e.close()


def test_pcap_roundtrip(tmp_path):
    p = str(tmp_path / "cap.pcap")
    w = PcapWriter(p)
    pkts = [b"\x80\x60" + bytes([i]) * 20 for i in range(5)]
    for i, pkt in enumerate(pkts):
        w.write(pkt, ts=100.0 + i * 0.02, src_port=5004, dst_port=5006)
    w.close()
    r = PcapReader(p)
    got = list(r)
    r.close()
    assert len(got) == 5
    ts0, payload0, sp, dp = got[0]
    assert payload0 == pkts[0]
    assert (sp, dp) == (5004, 5006)
    assert abs(ts0 - 100.0) < 1e-3
    assert abs(got[4][0] - got[0][0] - 0.08) < 1e-3


def test_rtpdump_roundtrip(tmp_path):
    p = str(tmp_path / "trace.rtpdump")
    w = RtpdumpWriter(p, start=50.0)
    pkts = [b"\x80\x00" + bytes(12 + i) for i in range(4)]
    for i, pkt in enumerate(pkts):
        w.write(pkt, ts=50.0 + i * 0.02)
    w.close()
    got = list(RtpdumpReader(p))
    assert [g[1] for g in got] == pkts
    assert [g[0] for g in got] == [0, 20, 40, 60]


def test_pcap_tap_for_batch(tmp_path):
    """The PacketLoggingService analog: tap a whole batch."""
    p = str(tmp_path / "tap.pcap")
    w = PcapWriter(p)
    batch = PacketBatch.from_payloads([b"aaa", b"bbbb"])
    w.write_batch(batch, ts=1.0)
    w.close()
    got = [x[1] for x in PcapReader(p)]
    assert got == [b"aaa", b"bbbb"]


def test_kernel_timestamps_recv():
    """SO_TIMESTAMPNS path: arrival stamps are sane CLOCK_REALTIME ns,
    monotonic-ish, and close to the send time."""
    import time

    from libjitsi_tpu.io import UdpEngine

    rx = UdpEngine(port=0, max_batch=16, kernel_timestamps=True)
    tx = UdpEngine(port=0, max_batch=16)
    from libjitsi_tpu.core.packet import PacketBatch

    t0 = time.time()
    b = PacketBatch.from_payloads([b"stamp-%d" % i for i in range(5)])
    tx.send_batch(b, "127.0.0.1", rx.port)
    got, _, _, ats = rx.recv_batch_ts(timeout_ms=500)
    t1 = time.time()
    assert got.batch_size == 5
    assert ats.dtype == np.int64
    secs = ats / 1e9
    assert np.all(secs >= t0 - 1.0) and np.all(secs <= t1 + 1.0)
    assert np.all(np.diff(ats) >= 0)     # recvmmsg preserves order
    # with SO_TIMESTAMPNS active the stamps should be kernel-made
    assert rx.kernel_timestamps
