"""Generation-2 host-I/O engine: the io_uring ring mode and its
recvmmsg fallback twin.

Every behavioural test is parametrized over the engine modes THIS box
can run — the recvmmsg arm is always active, so tier-1 passes
bit-for-bit on a box with no io_uring at all; the ring arm skips (not
fails) when `uring_available()` is False.  The invariants under test
(ISSUE 12): ordered arena delivery in both modes, idempotent token
release, generation-tag invalidation across re-occupancy, and
grow-never-reuse while the kernel (or a live view) owns a buffer.
"""

import socket
import struct
import time

import numpy as np
import pytest

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io.udp import (UdpEngine, _ArenaToken,
                                 probe_engine_mode, uring_available)

LOCALHOST = struct.unpack("!I", socket.inet_aton("127.0.0.1"))[0]

MODES = ["recvmmsg"] + (["io_uring"] if uring_available() else [])

ring_only = pytest.mark.skipif(not uring_available(),
                               reason="io_uring engine not available "
                                      "on this box")


def _send(tx, rx, payloads):
    tx.send_batch(PacketBatch.from_payloads(payloads), LOCALHOST,
                  rx.port)


def _drain_views(rx, want, timeout_ms=50, max_windows=200):
    """Collect (payload bytes, token) via zero-copy views until `want`
    packets arrived; copies the bytes out before returning."""
    out, toks = [], []
    for _ in range(max_windows):
        batch, _sip, _sport = rx.recv_batch_view(timeout_ms=timeout_ms)
        lens = np.asarray(batch.length)
        for i in range(batch.batch_size):
            out.append(bytes(batch.data[i, :lens[i]]))
        if batch.batch_size:
            toks.append(batch.arena_token)
        if len(out) >= want:
            break
    return out, toks


# ------------------------------------------------------------- probing

def test_probe_default_is_recvmmsg_without_env_pin(monkeypatch):
    """"auto" resolves to the measured default (recvmmsg) unless the
    environment pins io_uring AND the box can run it — the ring engine
    is selectable, not the default (loopback medians lose ~30%)."""
    monkeypatch.delenv("LIBJITSI_TPU_ENGINE_MODE", raising=False)
    monkeypatch.delenv("LIBJITSI_TPU_NO_IOURING", raising=False)
    assert probe_engine_mode() == "recvmmsg"


def test_force_disable_env_wins(monkeypatch):
    """LIBJITSI_TPU_NO_IOURING=1 is the fallback-proof switch: the
    capability probe reports unavailable, "auto" resolves to recvmmsg,
    and even an explicit io_uring request degrades (with a warning)
    instead of arming a ring."""
    monkeypatch.setenv("LIBJITSI_TPU_NO_IOURING", "1")
    assert not uring_available()
    assert probe_engine_mode() == "recvmmsg"
    eng = UdpEngine(port=0, engine_mode="io_uring")
    try:
        assert eng.engine_mode == "recvmmsg"
        assert eng._u is None
    finally:
        eng.close()


def test_engine_mode_pin_recvmmsg_counts_as_disabled(monkeypatch):
    monkeypatch.setenv("LIBJITSI_TPU_ENGINE_MODE", "recvmmsg")
    monkeypatch.delenv("LIBJITSI_TPU_NO_IOURING", raising=False)
    assert not uring_available()
    assert probe_engine_mode() == "recvmmsg"


def test_invalid_engine_mode_rejected():
    with pytest.raises(ValueError):
        UdpEngine(port=0, engine_mode="dpdk")


# --------------------------------------------------- mode-twin ingest

@pytest.mark.parametrize("mode", MODES)
def test_ordered_ingest_and_parity_accept_set(mode):
    """Both engines deliver every datagram exactly once, in arrival
    order, with correct lengths — the recvmmsg run is the reference
    accept set, the ring run must be bit-identical to it."""
    tx = UdpEngine(port=0)
    rx = UdpEngine(port=0, max_batch=16, engine_mode=mode)
    try:
        assert rx.engine_mode == mode
        sent = [bytes([0x40 + i]) * (20 + i) for i in range(12)]
        _send(tx, rx, sent)
        got, toks = _drain_views(rx, len(sent))
        assert got == sent, f"{mode} scrambled or lost the accept set"
        for t in toks:
            rx.release_arena(t)
    finally:
        tx.close()
        rx.close()


@pytest.mark.parametrize("mode", MODES)
def test_double_release_is_idempotent(mode):
    """Releasing the same token twice within one occupancy must not
    steal the pin of another live view (the `released` flag, not just
    the generation check, guards this)."""
    tx = UdpEngine(port=0)
    rx = UdpEngine(port=0, max_batch=8, engine_mode=mode)
    try:
        _send(tx, rx, [b"\xAA" * 32, b"\xBB" * 32])
        got, toks = _drain_views(rx, 2)
        assert len(got) == 2 and toks
        tok = toks[0]
        assert isinstance(tok, _ArenaToken)
        a = tok.arena
        pins_before = a.pins
        rx.release_arena(tok)
        rx.release_arena(tok)               # double release: no-op
        assert a.pins == pins_before - 1
        assert tok.released
        for t in toks[1:]:
            rx.release_arena(t)
        assert all(ar.pins == 0 for ar in rx._ring)
    finally:
        tx.close()
        rx.close()


@pytest.mark.parametrize("mode", MODES)
def test_generation_tag_invalidates_stale_tokens(mode):
    """A token from a previous occupancy of an arena can never unpin
    the current occupancy: the gen bump (at arm time for the ring, per
    window for recvmmsg) invalidates it."""
    tx = UdpEngine(port=0)
    rx = UdpEngine(port=0, max_batch=4, arenas=2, engine_mode=mode)
    try:
        _send(tx, rx, [b"\x01" * 24] * 2)
        _, toks = _drain_views(rx, 2)
        tok0 = toks[0]
        a0, g0 = tok0.arena, tok0.gen
        rx.release_arena(tok0)
        # drive traffic (releasing promptly so arenas recycle) until
        # arena a0 is re-occupied and its generation moves on
        for round_ in range(64):
            _send(tx, rx, [bytes([0x10 + round_]) * 24] * 2)
            _, tk = _drain_views(rx, 2)
            for t in tk:
                rx.release_arena(t)
            if a0.gen > g0:
                break
        assert a0.gen > g0, "arena never re-occupied"
        # pin the current occupancy, then try to unpin it with the
        # STALE token's coordinates — the gen check must reject it
        _send(tx, rx, [b"\x77" * 24] * 2)
        _, live = _drain_views(rx, 2)
        pins_now = a0.pins
        rx.release_arena((a0, g0))          # stale legacy tuple
        assert a0.pins == pins_now, \
            "stale-generation token stole a live pin"
        for t in live:
            rx.release_arena(t)
    finally:
        tx.close()
        rx.close()


@pytest.mark.parametrize("mode", MODES)
def test_grow_never_reuse_while_owned(mode):
    """When every arena is pinned by a live view (and, in ring mode,
    the kernel owns the armed one), new ingest GROWS the ring instead
    of reusing a buffer — pinned bytes are never clobbered."""
    tx = UdpEngine(port=0)
    rx = UdpEngine(port=0, max_batch=4, arenas=2, engine_mode=mode)
    try:
        views = []
        for tag in (0xA1, 0xB2, 0xC3, 0xD4, 0xE5):
            _send(tx, rx, [bytes([tag]) * 48] * 2)
            got, toks = _drain_views(rx, 2)
            assert len(got) == 2
            # hold the token: the arena stays pinned across the rest
            views.append((tag, toks))
        assert rx.arena_grows >= 1, \
            f"{mode}: ring should have grown while all arenas pinned"
        for tag, toks in views:
            a = toks[0].arena
            # the payloads were copied out in _drain_views; verify the
            # ARENA rows still carry this occupancy's bytes
            rows = np.nonzero((a.buf[:, 0] == tag))[0]
            assert len(rows) >= 2, \
                f"{mode}: pinned arena bytes for {tag:#x} clobbered"
        for _tag, toks in views:
            for t in toks:
                rx.release_arena(t)
        assert all(a.pins == 0 for a in rx._ring)
    finally:
        tx.close()
        rx.close()


# ------------------------------------------------- syscall telemetry

@pytest.mark.parametrize("mode", MODES)
def test_syscall_telemetry_shape(mode):
    """`syscall_enters` is monotone in both modes; `ring_reaps` is zero
    for recvmmsg and positive for the ring once packets flowed."""
    tx = UdpEngine(port=0)
    rx = UdpEngine(port=0, max_batch=8, engine_mode=mode)
    try:
        e0 = rx.syscall_enters
        _send(tx, rx, [b"\x55" * 30] * 4)
        got, toks = _drain_views(rx, 4)
        assert len(got) == 4
        assert rx.syscall_enters >= e0
        if mode == "recvmmsg":
            assert rx.ring_reaps == 0
            assert rx.syscall_enters > e0     # every window enters
        else:
            assert rx.ring_reaps >= 4, \
                "completed ring SQEs not accounted as reaps"
        for t in toks:
            rx.release_arena(t)
    finally:
        tx.close()
        rx.close()


@ring_only
def test_uring_steady_state_recv_is_zero_syscall():
    """Once the chain is armed, reaping landed completions is entirely
    ring-side: a 0 ms poll never enters the kernel, so the enters
    counter stays FLAT across delivered windows (recvmmsg pays one
    enter per window — the contrast test_syscall_telemetry_shape
    pins)."""
    tx = UdpEngine(port=0)
    rx = UdpEngine(port=0, max_batch=16, engine_mode="io_uring")
    try:
        # warm: prove the chain is armed and delivering
        _send(tx, rx, [b"\x66" * 30] * 4)
        got, toks = _drain_views(rx, 4, timeout_ms=100)
        assert len(got) == 4
        e0 = rx.syscall_enters
        sent = [bytes([0x90 + i]) * 30 for i in range(4)]
        _send(tx, rx, sent)
        got2 = []
        for _ in range(500):
            batch, _s, _p = rx.recv_batch_view(timeout_ms=0)
            lens = np.asarray(batch.length)
            for i in range(batch.batch_size):
                got2.append(bytes(batch.data[i, :lens[i]]))
            if batch.batch_size:
                toks.append(batch.arena_token)
            if len(got2) >= 4:
                break
            time.sleep(0.002)
        assert got2 == sent
        assert rx.syscall_enters == e0, \
            "ring-side reaps entered the kernel"
        for t in toks:
            rx.release_arena(t)
    finally:
        tx.close()
        rx.close()


@ring_only
def test_uring_gather_egress_roundtrip(monkeypatch):
    """Linked-SQE gather egress (opt-in via LIBJITSI_TPU_URING_EGRESS)
    delivers the same bytes sendmmsg would."""
    monkeypatch.setenv("LIBJITSI_TPU_URING_EGRESS", "1")
    tx = UdpEngine(port=0, engine_mode="io_uring")
    rx = UdpEngine(port=0, max_batch=16)
    try:
        assert tx.uring_egress
        sent = [bytes([0x70 + i]) * (25 + i) for i in range(6)]
        _send(tx, rx, sent)
        got, toks = _drain_views(rx, len(sent))
        assert got == sent
        for t in toks:
            rx.release_arena(t)
    finally:
        tx.close()
        rx.close()


@ring_only
def test_uring_arena_exhaustion_rearms_across_boundary():
    """Delivering more packets than one arena holds forces the
    EXHAUSTED -> re-arm path; nothing is lost at the boundary and the
    new occupancy carries a fresh generation."""
    tx = UdpEngine(port=0)
    rx = UdpEngine(port=0, max_batch=8, arenas=2,
                   engine_mode="io_uring")
    try:
        rows = rx._rows
        n = rows + 4                     # spill into the second arena
        sent = [struct.pack("!I", i) + b"z" * 20 for i in range(n)]
        for i in range(0, n, 8):
            _send(tx, rx, sent[i:i + 8])
        got, toks = _drain_views(rx, n)
        assert got == sent, "packets lost/reordered at arena boundary"
        gens = {t.arena: t.gen for t in toks}
        assert len(gens) >= 2, "re-arm never moved to a second arena"
        for t in toks:
            rx.release_arena(t)
    finally:
        tx.close()
        rx.close()


def test_token_legacy_tuple_unpacking():
    a_like = _ArenaToken.__new__(_ArenaToken)
    a_like.arena, a_like.gen, a_like.released = "arena", 7, False
    arena, gen = a_like
    assert (arena, gen) == ("arena", 7)


def test_engine_mode_env_pin_selects_ring_when_available(monkeypatch):
    """LIBJITSI_TPU_ENGINE_MODE=io_uring flips "auto" to the ring —
    only on a box that can actually run it."""
    monkeypatch.setenv("LIBJITSI_TPU_ENGINE_MODE", "io_uring")
    monkeypatch.delenv("LIBJITSI_TPU_NO_IOURING", raising=False)
    want = "io_uring" if uring_available() else "recvmmsg"
    assert probe_engine_mode() == want
    eng = UdpEngine(port=0, engine_mode="auto")
    try:
        assert eng.engine_mode == want
    finally:
        eng.close()


@pytest.mark.parametrize("profile_name", ["ctr", "gcm"])
def test_donated_unprotect_twin_matches_plain(profile_name,
                                              monkeypatch):
    """ISSUE 12's H2D donation leg: the `donate_argnums` unprotect
    twins are selected only off-CPU, so force the selector on and
    prove the donated jit produces the byte-identical accept set (XLA
    treats donation on CPU as a no-op hint, which makes this a pure
    correctness check of the twin dispatch)."""
    from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable
    from libjitsi_tpu.transform.srtp import context as ctx_mod

    if profile_name == "ctr":
        profile, salt_len = SrtpProfile.AES_CM_128_HMAC_SHA1_80, 14
    else:
        profile, salt_len = SrtpProfile.AEAD_AES_128_GCM, 12

    def make_table():
        t = SrtpStreamTable(capacity=4, profile=profile)
        t.add_stream(0, bytes(range(16)), bytes(range(salt_len)))
        return t

    pkts = []
    for s in range(8):
        hdr = struct.pack("!BBHII", 0x80, 96, s, 3000 + s, 0x1234)
        pkts.append(hdr + bytes([s]) * 40)
    batch = PacketBatch.from_payloads(pkts, stream=[0] * 8)
    prot = make_table().protect_rtp(batch)

    dec_plain, ok_plain = make_table().unprotect_rtp(prot)
    assert np.asarray(ok_plain).all()

    monkeypatch.setattr(ctx_mod, "_donate_ingest", lambda: True)
    dec_don, ok_don = make_table().unprotect_rtp(prot)
    assert np.array_equal(np.asarray(ok_don), np.asarray(ok_plain))
    for i in range(8):
        assert dec_don.to_bytes(i) == dec_plain.to_bytes(i) == pkts[i]


def test_loop_exports_engine_metrics():
    """MediaLoop surfaces the two-engine telemetry: mode gauge, ring
    count, and the delta-accumulated ingest syscall/reap counters."""
    import libjitsi_tpu
    from libjitsi_tpu.io.loop import MediaLoop
    from libjitsi_tpu.service.media_stream import StreamRegistry

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    eng = UdpEngine(port=0)
    loop = MediaLoop(eng, StreamRegistry(
        libjitsi_tpu.configuration_service(), capacity=4),
        recv_window_ms=0)
    try:
        tx = UdpEngine(port=0)
        _send(tx, eng, [b"\x80" * 28] * 3)
        for _ in range(20):
            loop.tick()
        tx.close()
        reg = loop.metrics
        assert reg.sample_total("loop_ingest_rings") == 1.0
        assert reg.sample_total("loop_ingest_syscalls") >= 1
        assert reg.sample_total("loop_ingest_ring_reaps") >= 0
        is_ring = reg.sample_total("loop_engine_io_uring")
        assert is_ring == (1.0 if eng.engine_mode == "io_uring"
                           else 0.0)
        assert loop.engine_mode == eng.engine_mode
    finally:
        eng.close()
