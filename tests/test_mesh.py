"""Sharded paths on the virtual 8-device CPU mesh (conftest forces cpu).

Validates that stream-sharded SRTP and the psum mixer produce outputs
byte-identical to the single-device kernels — the multi-chip design's
correctness contract (SURVEY §2.7).
"""

import numpy as np
import jax
import pytest

from libjitsi_tpu.conference.mixer import mix_minus
from libjitsi_tpu.mesh import (
    make_media_mesh,
    sharded_mix_minus,
    sharded_srtp_protect,
)
from libjitsi_tpu.transform.srtp import kernel
from libjitsi_tpu.kernels.aes import expand_key
from libjitsi_tpu.kernels.sha1 import hmac_precompute


def _protect_args(batch, width, rng):
    rk = np.stack([
        expand_key(rng.integers(0, 256, 16, dtype=np.uint8).tobytes())
        for _ in range(batch)])
    mid = np.stack([
        hmac_precompute(rng.integers(0, 256, 20, dtype=np.uint8).tobytes())
        for _ in range(batch)])
    data = rng.integers(0, 256, (batch, width), dtype=np.uint8)
    length = np.full(batch, width - 16, dtype=np.int32)
    payload_off = np.full(batch, 12, dtype=np.int32)
    iv = rng.integers(0, 256, (batch, 16), dtype=np.uint8)
    roc = np.zeros(batch, dtype=np.uint32)
    return data, length, payload_off, rk, iv, mid, roc


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_media_mesh(jax.devices()[:8])


@pytest.mark.slow
def test_sharded_protect_matches_single(mesh):
    rng = np.random.default_rng(5)
    args = _protect_args(32, 128, rng)
    want_d, want_l = kernel.srtp_protect(*args, tag_len=10, encrypt=True)
    got_d, got_l = sharded_srtp_protect(mesh, tag_len=10)(*args)
    np.testing.assert_array_equal(np.asarray(got_d), np.asarray(want_d))
    np.testing.assert_array_equal(np.asarray(got_l), np.asarray(want_l))


def test_sharded_mix_matches_single(mesh):
    rng = np.random.default_rng(6)
    pcm = rng.integers(-5000, 5000, (32, 160)).astype(np.int16)
    active = rng.random(32) < 0.8
    want_out, want_lvl = mix_minus(pcm, active)
    got_out, got_lvl = sharded_mix_minus(mesh)(pcm, active)
    np.testing.assert_array_equal(np.asarray(got_out), np.asarray(want_out))
    np.testing.assert_array_equal(np.asarray(got_lvl), np.asarray(want_lvl))


@pytest.mark.slow   # ~75s: full driver dryrun incl. round-5 pipelined/
# F8/GCM parity + async-overlap steps; the DRIVER runs this same entry
# every round (MULTICHIP_r{N}.json), so core-tier coverage is redundant
def test_dryrun_multichip():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_entry_compiles():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    d, l = out
    assert d.shape == args[0].shape
    assert np.all(np.asarray(l) == args[1] + 10)


def test_multihost_2d_mesh_mixer():
    """(dcn, streams) mesh: conference psum over ICI then DCN."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from libjitsi_tpu.mesh import make_multihost_mesh, sharded_mix_minus_2d

    mesh = make_multihost_mesh(2, jax.devices()[:8])  # 2 "hosts" x 4 chips
    assert mesh.shape == {"dcn": 2, "streams": 4}
    rng = np.random.default_rng(9)
    pcm = rng.integers(-3000, 3000, (32, 64)).astype(np.int16)
    active = np.ones(32, dtype=bool)
    out, lvl = sharded_mix_minus_2d(mesh)(pcm, active)
    want, want_lvl = mix_minus(pcm, active)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(lvl), np.asarray(want_lvl))


def test_sharded_bridge_mix_matches_host(mesh):
    from libjitsi_tpu.mesh import sharded_bridge_mix

    rng = np.random.default_rng(12)
    C, N, F = 16, 6, 96          # C divisible by the 8-device mesh
    pcm = rng.integers(-9000, 9000, (C, N, F)).astype(np.int16)
    active = rng.random((C, N)) < 0.8
    out, lvl = sharded_bridge_mix(mesh)(pcm, active)
    from libjitsi_tpu.conference import mix_minus_many

    want, want_lvl = mix_minus_many(pcm, active)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))
    np.testing.assert_array_equal(np.asarray(lvl), np.asarray(want_lvl))


def test_sharded_gcm_fanout_matches_single_device():
    """Receiver legs sharded over the mesh seal identically to the
    single-device grouped kernel (zero collectives — leg-parallel)."""
    import jax

    from libjitsi_tpu.kernels.gcm import gcm_protect_fanout
    from libjitsi_tpu.mesh import make_media_mesh, sharded_gcm_fanout

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    rng = np.random.default_rng(17)
    G, Pk, W = 16, 4, 128                # 2 legs per device
    rks = rng.integers(0, 256, (G, 11, 16), dtype=np.uint8)
    gms = rng.integers(0, 2, (G, 128, 128), dtype=np.int8)
    data = rng.integers(0, 256, (Pk, W), dtype=np.uint8)
    length = np.full(Pk, 100, np.int32)
    iv = rng.integers(0, 256, (G, Pk, 12), dtype=np.uint8)

    mesh = make_media_mesh(jax.devices()[:8])
    out_s, len_s = sharded_gcm_fanout(mesh)(data, length, rks, gms, iv)
    out_1, len_1 = gcm_protect_fanout(data, length, rks, gms, iv,
                                      aad_const=12)
    assert np.array_equal(np.asarray(out_s), np.asarray(out_1))
    assert np.array_equal(np.asarray(len_s), np.asarray(len_1))
