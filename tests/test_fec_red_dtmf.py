"""FEC recovery, RED encap/decap, DTMF event transforms.

Reference behaviors: fec.FECReceiver single-loss XOR recovery,
red.REDTransformEngine primary/redundant blocks, dtmf.DtmfTransformEngine
tone lifecycle (marker on first, E-bit at end, audio suppressed).
"""

import numpy as np
import pytest

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.dtmf import DtmfEvent, DtmfTransformEngine, decode_event
from libjitsi_tpu.transform.fec import FecReceiver, FecSender, build_fec, parse_fec
from libjitsi_tpu.transform.red import RedTransformEngine, decode_red, encode_red


def _rtp(seq, payload, ts=1000, ssrc=5, pt=96, marker=0):
    b = rtp_header.build([payload], [seq], [ts], [ssrc], [pt],
                         marker=[marker])
    return b.to_bytes(0)


# ------------------------------------------------------------------- FEC ---

def test_fec_recovers_single_loss():
    pkts = [_rtp(100 + i, bytes([i]) * (20 + i), ts=1000 + 160 * i)
            for i in range(5)]
    fec = build_fec(pkts, seq_base=100)
    rx = FecReceiver()
    for i, p in enumerate(pkts):
        if i != 2:
            rx.push_media(p)
    rec = rx.push_fec(fec, ssrc=5)
    assert rec == pkts[2]
    assert rx.recovered == 1


def test_fec_no_recovery_when_two_missing():
    pkts = [_rtp(200 + i, b"x" * 30) for i in range(4)]
    fec = build_fec(pkts, seq_base=200)
    rx = FecReceiver()
    rx.push_media(pkts[0])
    rx.push_media(pkts[3])
    assert rx.push_fec(fec, ssrc=5) is None


def test_fec_sender_groups():
    tx = FecSender(k=3)
    outs = [tx.push(_rtp(i, b"d" * 10)) for i in range(7)]
    fecs = [o for o in outs if o is not None]
    assert len(fecs) == 2
    f = parse_fec(fecs[0])
    assert f["seq_base"] == 0 and bin(f["mask"]).count("1") == 3


def test_fec_recovers_different_lengths_and_marker():
    pkts = [_rtp(10, b"short", marker=1), _rtp(11, b"a-much-longer-payload"),
            _rtp(12, b"mid-size!!")]
    fec = build_fec(pkts, seq_base=10)
    rx = FecReceiver()
    rx.push_media(pkts[0])
    rx.push_media(pkts[2])
    rec = rx.push_fec(fec, ssrc=5)
    assert rec == pkts[1]


# ------------------------------------------------------------------- RED ---

def test_red_codec_roundtrip():
    blob = encode_red(b"primary", 96, [(96, 960, b"older"), (96, 480, b"old")])
    blocks = decode_red(blob)
    assert blocks[-1] == (96, 0, b"primary")
    assert blocks[0] == (96, 960, b"older")
    assert blocks[1] == (96, 480, b"old")


def test_red_engine_wrap_unwrap():
    eng = RedTransformEngine(red_pt=104, distance=1)
    b1 = PacketBatch.from_payloads([_rtp(1, b"frame-1", ts=960)], stream=[0])
    b2 = PacketBatch.from_payloads([_rtp(2, b"frame-2", ts=1920)], stream=[0])
    w1, _ = eng.rtp_transformer.transform(b1)
    w2, _ = eng.rtp_transformer.transform(b2)
    assert rtp_header.parse(w2).pt[0] == 104
    # second packet carries frame-1 as redundancy
    hdr = rtp_header.parse(w2)
    blocks = decode_red(w2.to_bytes(0)[int(hdr.payload_off[0]):])
    assert blocks[0][2] == b"frame-1" and blocks[-1][2] == b"frame-2"
    assert blocks[0][1] == 960  # ts offset
    # receiver unwraps to the primary
    dec, ok = eng.rtp_transformer.reverse_transform(w2)
    assert ok.all()
    h = rtp_header.parse(dec)
    assert h.pt[0] == 96
    assert dec.to_bytes(0)[int(h.payload_off[0]):] == b"frame-2"


# ------------------------------------------------------------------ DTMF ---

def test_dtmf_tone_lifecycle():
    eng = DtmfTransformEngine(dtmf_pt=101)
    eng.start_tone(0, "5")
    outs = []
    for i in range(3):
        b = PacketBatch.from_payloads(
            [_rtp(10 + i, b"audio", ts=1000 + 160 * i)], stream=[0])
        w, _ = eng.rtp_transformer.transform(b)
        outs.append(w)
    eng.stop_tone(0)
    for i in range(3):
        b = PacketBatch.from_payloads(
            [_rtp(13 + i, b"audio", ts=1480 + 160 * i)], stream=[0])
        w, _ = eng.rtp_transformer.transform(b)
        outs.append(w)

    hdrs = [rtp_header.parse(o) for o in outs]
    assert all(h.pt[0] == 101 for h in hdrs)
    assert hdrs[0].marker[0] == 1 and hdrs[1].marker[0] == 0
    # all packets share the event-start timestamp
    assert len({int(h.ts[0]) for h in hdrs}) == 1
    evs = [decode_event(o.to_bytes(0)[int(h.payload_off[0]):])
           for o, h in zip(outs, hdrs)]
    assert all(e.event == 5 for e in evs)
    assert [e.end for e in evs] == [False] * 3 + [True] * 3
    assert evs[-1].duration > evs[0].duration


def test_dtmf_receive_extracts_and_consumes():
    got = []
    eng = DtmfTransformEngine(dtmf_pt=101,
                              on_event=lambda sid, ev: got.append((sid, ev)))
    from libjitsi_tpu.transform.dtmf import encode_event
    evt = _rtp(1, encode_event(DtmfEvent(7, False, 10, 320)), pt=101)
    audio = _rtp(2, b"normal-audio", pt=96)
    b = PacketBatch.from_payloads([evt, audio], stream=[3, 3])
    out, ok = eng.rtp_transformer.reverse_transform(b)
    assert ok.tolist() == [False, True]   # event consumed, audio passes
    assert got and got[0][0] == 3 and got[0][1].event == 7
