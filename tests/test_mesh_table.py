"""ShardedSrtpTable: the PRODUCT table sharded over the mesh must be
bit-identical to the single-chip SrtpStreamTable (VERDICT r3 #2 — shard
the product objects, not just the kernels)."""

import numpy as np
import pytest

from libjitsi_tpu.mesh import ShardedSrtpTable, make_media_mesh
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable

CAP = 16


def _tables(profile=SrtpProfile.AES_CM_128_HMAC_SHA1_80):
    rng = np.random.default_rng(41)
    mks = rng.integers(0, 256, (CAP, 16), dtype=np.uint8)
    mss = rng.integers(0, 256, (CAP, 14), dtype=np.uint8)
    mesh = make_media_mesh()
    sh = ShardedSrtpTable(CAP, mesh, profile)
    sh.add_streams(np.arange(CAP), mks, mss)
    plain = SrtpStreamTable(CAP, profile)
    plain.add_streams(np.arange(CAP), mks, mss)
    return sh, plain


def _batch(rng, n, seq0, sizes=(160,)):   # one size class: one compile pair
    streams = rng.integers(0, CAP, n)
    lens = rng.choice(sizes, n)
    payloads = [rng.integers(0, 256, l, dtype=np.uint8).tobytes()
                for l in lens]
    return rtp_header.build(
        payloads, [seq0 + i for i in range(n)], [i * 160 for i in range(n)],
        (0x5000 + streams).tolist(), [96] * n, stream=streams.tolist())


def test_sharded_protect_unprotect_bit_identical():
    sh_tx, plain_tx = _tables()
    sh_rx, plain_rx = _tables()
    rng = np.random.default_rng(42)
    for k in range(2):
        b = _batch(np.random.default_rng(100 + k), 24, 100 + 24 * k)
        b2 = _batch(np.random.default_rng(100 + k), 24, 100 + 24 * k)
        w_sh = sh_tx.protect_rtp(b)
        w_pl = plain_tx.protect_rtp(b2)
        for i in range(w_sh.batch_size):
            assert w_sh.to_bytes(i) == w_pl.to_bytes(i), f"row {i}"
        # host tx plane advanced identically
        np.testing.assert_array_equal(sh_tx.tx_ext, plain_tx.tx_ext)

        d_sh, ok_sh = sh_rx.unprotect_rtp(w_sh)
        d_pl, ok_pl = plain_rx.unprotect_rtp(w_pl)
        assert bool(np.all(ok_sh)) and bool(np.all(ok_pl))
        for i in range(d_sh.batch_size):
            assert d_sh.to_bytes(i) == d_pl.to_bytes(i)
        np.testing.assert_array_equal(sh_rx.rx_max, plain_rx.rx_max)
        np.testing.assert_array_equal(sh_rx.rx_mask, plain_rx.rx_mask)


def test_sharded_replay_and_tamper_rejection():
    sh_tx, _ = _tables()
    sh_rx, _ = _tables()
    b = _batch(np.random.default_rng(7), 16, 500)
    w = sh_tx.protect_rtp(b)
    d, ok = sh_rx.unprotect_rtp(w)
    assert bool(np.all(ok))
    # replay: same wire again must be rejected by the (host) windows
    w2 = sh_tx.protect_rtp(_batch(np.random.default_rng(7), 16, 500))
    _, ok2 = sh_rx.unprotect_rtp(w2)
    assert not bool(np.any(ok2))
    # tamper: flip one payload byte on a fresh batch -> that row fails
    w3 = sh_tx.protect_rtp(_batch(np.random.default_rng(8), 16, 600))
    w3.data[3, 20] ^= 0xFF
    _, ok3 = sh_rx.unprotect_rtp(w3)
    assert not ok3[3] and bool(np.sum(ok3) >= 14)


def test_sharded_table_rejects_indivisible_capacity():
    mesh = make_media_mesh()
    with pytest.raises(ValueError):
        ShardedSrtpTable(CAP + 1, mesh)


def test_sharded_f8_parity():
    """AES-F8 on the sharded table (VERDICT r4 #6): the second key
    schedule shards on the same row partition — protect/unprotect must
    be bit-identical to the single-chip F8 table."""
    from libjitsi_tpu.mesh.parity import assert_table_parity

    assert_table_parity(make_media_mesh(), capacity=CAP, batch_size=24,
                        rounds=1,
                        profile=SrtpProfile.F8_128_HMAC_SHA1_80)


@pytest.mark.parametrize("profile,salt", [
    (SrtpProfile.AES_CM_128_HMAC_SHA1_80, 14),
    (SrtpProfile.F8_128_HMAC_SHA1_80, 14),
    (SrtpProfile.AEAD_AES_128_GCM, 12),
])
def test_sharded_srtcp_parity(profile, salt):
    """SRTCP runs SHARDED on the mesh table's RTCP key tables (VERDICT
    r4 #6: control traffic must not silently hop to a single-chip
    path) — wire and decrypt byte-identical to the plain table."""
    from libjitsi_tpu.core.packet import PacketBatch

    rng = np.random.default_rng(3)
    mks = rng.integers(0, 256, (CAP, 16), dtype=np.uint8)
    mss = rng.integers(0, 256, (CAP, salt), dtype=np.uint8)
    mesh = make_media_mesh()

    def build(cls, *extra):
        tx = cls(CAP, *extra, profile)
        tx.add_streams(np.arange(CAP), mks, mss)
        rx = cls(CAP, *extra, profile)
        rx.add_streams(np.arange(CAP), mks, mss)
        return tx, rx

    sh_tx, sh_rx = build(ShardedSrtpTable, mesh)
    pl_tx, pl_rx = build(SrtpStreamTable)
    blobs = [b"\x81\xc8\x00\x06" + int(0x1000 + s).to_bytes(4, "big")
             + bytes([s]) * 20 for s in (2, 9, 2, 13)]
    b1 = PacketBatch.from_payloads(blobs, stream=[2, 9, 2, 13])
    b2 = PacketBatch.from_payloads(blobs, stream=[2, 9, 2, 13])
    w_sh = sh_tx.protect_rtcp(b1)
    w_pl = pl_tx.protect_rtcp(b2)
    for i in range(w_sh.batch_size):
        assert w_sh.to_bytes(i) == w_pl.to_bytes(i), f"rtcp row {i}"
    d_sh, ok_sh = sh_rx.unprotect_rtcp(w_sh)
    d_pl, ok_pl = pl_rx.unprotect_rtcp(w_pl)
    assert bool(np.all(ok_sh)) and bool(np.all(ok_pl))
    for i in range(d_sh.batch_size):
        assert d_sh.to_bytes(i) == d_pl.to_bytes(i)
    np.testing.assert_array_equal(sh_rx.rtcp_rx_max, pl_rx.rtcp_rx_max)
    np.testing.assert_array_equal(sh_tx.rtcp_tx_index,
                                  pl_tx.rtcp_tx_index)


def test_sharded_async_protect_matches_sync():
    """`protect_rtp_async` on the MESH table (VERDICT r4 #2): the
    deferred-scatter seam must produce bit-identical wire to the sync
    mesh path, with host TX state committed at dispatch."""
    sh_a, _ = _tables()
    sh_b, _ = _tables()
    pends = []
    for k in range(3):
        b = _batch(np.random.default_rng(900 + k), 24, 700 + 24 * k)
        pends.append(sh_a.protect_rtp_async(b))
    # all three dispatched before any materialization: TX state already
    # committed (the async contract) and not touched by result()
    tx_at_dispatch = sh_a.tx_ext.copy()
    outs = [p.result() for p in pends]
    np.testing.assert_array_equal(sh_a.tx_ext, tx_at_dispatch)
    for k in range(3):
        b = _batch(np.random.default_rng(900 + k), 24, 700 + 24 * k)
        w = sh_b.protect_rtp(b)
        for i in range(w.batch_size):
            assert outs[k].to_bytes(i) == w.to_bytes(i), f"batch {k} row {i}"
    np.testing.assert_array_equal(sh_a.tx_ext, sh_b.tx_ext)


def test_mesh_gcm_grouped_and_per_row_parity():
    """The sharded GCM table's grouped-GHASH path (VERDICT r4 #4) must
    match the sharded per-row path and the single-chip table bit for
    bit; the live seam picks between them by registry measurement."""
    from libjitsi_tpu.kernels import registry

    prof = SrtpProfile.AEAD_AES_128_GCM
    rng = np.random.default_rng(41)
    mks = rng.integers(0, 256, (CAP, 16), dtype=np.uint8)
    mss = rng.integers(0, 256, (CAP, 12), dtype=np.uint8)
    mesh = make_media_mesh()

    def mk_pair(cls, *extra):
        tx = cls(CAP, *extra, prof)
        tx.add_streams(np.arange(CAP), mks, mss)
        rx = cls(CAP, *extra, prof)
        rx.add_streams(np.arange(CAP), mks, mss)
        return tx, rx

    wires = {}
    try:
        for prov in ("grouped", "per_row"):
            registry.force("mesh_gcm_rtp_protect", prov)
            registry.force("mesh_gcm_rtp_unprotect", prov)
            sh_tx, sh_rx = mk_pair(ShardedSrtpTable, mesh)
            # heavy stream reuse so the grouped grid is structurally
            # usable (24 lanes over <= 8 streams)
            r = np.random.default_rng(77)
            streams = r.integers(0, 8, 24)
            pls = [r.integers(0, 256, 40, dtype=np.uint8).tobytes()
                   for _ in range(24)]
            b = rtp_header.build(
                pls, list(range(200, 224)), [0] * 24,
                (0x5000 + streams).tolist(), [96] * 24,
                stream=streams.tolist())
            w = sh_tx.protect_rtp(b)
            wires[prov] = [w.to_bytes(i) for i in range(w.batch_size)]
            d, ok = sh_rx.unprotect_rtp(w)
            assert bool(np.all(ok)), f"{prov}: auth failed"
            for i in range(d.batch_size):
                assert d.to_bytes(i) == b.to_bytes(i)
    finally:
        registry.force("mesh_gcm_rtp_protect", None)
        registry.force("mesh_gcm_rtp_unprotect", None)
    assert wires["grouped"] == wires["per_row"]


def test_mesh_bridge_tick_matches_single_chip():
    """The ASSEMBLED ConferenceBridge in mesh mode (sharded SRTP tables
    + psum mixer) must emit byte-identical wire packets to the plain
    single-chip bridge — via the parity harness shared with the
    driver's multi-chip dryrun (libjitsi_tpu.mesh.parity)."""
    import libjitsi_tpu
    from libjitsi_tpu.mesh.parity import assert_bridge_parity
    from libjitsi_tpu.service.bridge import ConferenceBridge

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    mesh = make_media_mesh()
    assert_bridge_parity(cfg, mesh, capacity=16)
    # mesh COMPOSES with pipelined (VERDICT r4 #2): the deferred-scatter
    # seam lets the dispatch overlap, and the wire stays byte-identical
    assert_bridge_parity(cfg, mesh, capacity=16, pipelined=True)


@pytest.mark.slow
def test_mesh_bridge_restore_stays_sharded_and_warmup():
    """A checkpointed mesh bridge must resume with MESH tables (not a
    silent single-chip fallback), and warmup() must pre-compile the
    lane ladder / measurement off the tick path."""
    import libjitsi_tpu
    from libjitsi_tpu.service.bridge import ConferenceBridge

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    mesh = make_media_mesh()
    bridge = ConferenceBridge(cfg, port=0, capacity=16,
                              recv_window_ms=0, mesh=mesh)
    bridge.add_participant(5, (b"\x05" * 16, b"\x06" * 14),
                           (b"\x07" * 16, b"\x08" * 14))
    snap = bridge.snapshot()
    bridge.close()
    b2 = ConferenceBridge.restore(cfg, snap, port=0, recv_window_ms=0,
                                  mesh=mesh)
    assert isinstance(b2.rx_table, ShardedSrtpTable)
    assert isinstance(b2.tx_table, ShardedSrtpTable)
    # sharded warmup ladder: compiles banked before any tick
    b2.rx_table.warmup(max_batch=8)
    assert ("protect", 10, True, 12) in b2.rx_table._sh_fns
    b2.close()
    # non-mesh warmup path (scratch table, real state untouched)
    b3 = ConferenceBridge(cfg, port=0, capacity=8, recv_window_ms=0)
    b3.add_participant(6, (b"\x01" * 16, b"\x02" * 14),
                       (b"\x03" * 16, b"\x04" * 14))
    tx_before = b3.tx_table.tx_ext.copy()
    b3.warmup()
    np.testing.assert_array_equal(b3.tx_table.tx_ext, tx_before)
    b3.close()


def test_sharded_gcm_table_parity_and_rtcp():
    """AEAD-GCM on the sharded table: per-row-form shard_map must be
    bit-identical to the single-chip GCM table (which itself picks
    grouped/per-row by measurement), and the inherited single-chip
    SRTCP path must work on the sharded object."""
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.mesh.parity import assert_table_parity

    mesh = make_media_mesh()
    assert_table_parity(mesh, capacity=CAP, batch_size=24,
                        profile=SrtpProfile.AEAD_AES_128_GCM)
    # SRTCP through the sharded object (inherited path)
    rng = np.random.default_rng(3)
    mks = rng.integers(0, 256, (CAP, 16), dtype=np.uint8)
    mss = rng.integers(0, 256, (CAP, 12), dtype=np.uint8)
    tx = ShardedSrtpTable(CAP, mesh, SrtpProfile.AEAD_AES_128_GCM)
    tx.add_streams(np.arange(CAP), mks, mss)
    rx = ShardedSrtpTable(CAP, mesh, SrtpProfile.AEAD_AES_128_GCM)
    rx.add_streams(np.arange(CAP), mks, mss)
    blob = b"\x81\xc8\x00\x06" + (0x1234).to_bytes(4, "big") + b"x" * 20
    b = PacketBatch.from_payloads([blob], stream=[2])
    wire = tx.protect_rtcp(b)
    dec, ok = rx.unprotect_rtcp(wire)
    assert bool(np.all(ok)) and dec.to_bytes(0) == blob
    # warmup and the live seams must share one fn-cache key (the gcm
    # ops normalize tag/encrypt out of the key)
    tx.warmup(max_batch=8)
    assert ("gcm_protect", 0, True, 12) in tx._sh_fns


@pytest.mark.slow
def test_mesh_sfu_bridge_fanout_matches_single_chip():
    """The ASSEMBLED SfuBridge in mesh mode (sharded tables + leg-
    sharded fan-out translator) must emit byte-identical forwarded wire
    to the single-chip bridge."""
    import libjitsi_tpu
    from libjitsi_tpu.mesh.parity import assert_sfu_parity
    from libjitsi_tpu.service.sfu_bridge import SfuBridge

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    mesh = make_media_mesh()
    assert_sfu_parity(cfg, mesh, capacity=16)
    # mesh + pipelined composes (VERDICT r4 #2): the pipelined MESH
    # bridge's forwarded wire matches the sync single-chip bridge
    assert_sfu_parity(cfg, mesh, capacity=16, pipelined=True)
    # a mesh snapshot refuses a single-chip restore (un-sharding a
    # deployment must be loud, not silent)
    sfu = SfuBridge(cfg, port=0, capacity=16, recv_window_ms=0,
                    mesh=mesh)
    snap = sfu.snapshot()
    sfu.close()
    with pytest.raises(ValueError):
        SfuBridge.restore(cfg, snap, port=0)
    back = SfuBridge.restore(cfg, snap, port=0, mesh=mesh)
    back.close()


def test_sharded_table_on_2d_multihost_mesh():
    """DCN rehearsal at PRODUCT level: the sharded table partitions its
    rows over the 2-D (dcn, streams) mesh — same parity contract as the
    1-D mesh (SURVEY §2.7 DCN row)."""
    from libjitsi_tpu.mesh import make_multihost_mesh
    from libjitsi_tpu.mesh.parity import assert_table_parity

    mesh2d = make_multihost_mesh(2)
    assert mesh2d.devices.shape == (2, 4)
    assert_table_parity(mesh2d, capacity=CAP, batch_size=24, rounds=1)


@pytest.mark.slow
def test_mesh_bridge_on_2d_multihost_mesh():
    """The assembled ConferenceBridge also runs on the 2-D multi-host
    mesh (rows over (dcn x streams); mixer psums over both axes) —
    byte-identical to single-chip."""
    import libjitsi_tpu
    from libjitsi_tpu.mesh import make_multihost_mesh
    from libjitsi_tpu.mesh.parity import assert_bridge_parity

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    assert_bridge_parity(libjitsi_tpu.configuration_service(),
                         make_multihost_mesh(2), capacity=16)


def test_sharded_translator_cm_and_gcm_parity():
    """The leg-sharded fan-out translator must produce byte-identical
    wire to the single-chip RtpTranslator for BOTH CM and GCM (GCM via
    the sharded per-row form; the single-chip side free to pick its
    full-mesh fast path — outputs must agree regardless)."""
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.mesh import ShardedRtpTranslator
    from libjitsi_tpu.sfu.translator import RtpTranslator

    mesh = make_media_mesh()
    for profile, salt in ((SrtpProfile.AES_CM_128_HMAC_SHA1_80, 14),
                          (SrtpProfile.AEAD_AES_128_GCM, 12)):
        rng = np.random.default_rng(11)
        keys = {r: (bytes([r]) * 16, bytes([r + 1]) * salt)
                for r in range(8)}
        pair = []
        for cls, args in ((RtpTranslator, {"capacity": 8,
                                           "profile": profile}),
                          (ShardedRtpTranslator,
                           {"capacity": 8, "mesh": mesh,
                            "profile": profile})):
            tr = cls(**args)
            for r, (mk, ms) in keys.items():
                tr.add_receiver(r, mk, ms)
            tr.connect(0, list(range(1, 8)))
            pair.append(tr)
        pls = [rng.integers(0, 256, 40, dtype=np.uint8).tobytes()
               for _ in range(4)]
        # MIXED header sizes (CSRC lists on half the packets): payload
        # offsets differ per row, so _uniform_off returns None and the
        # sharded non-constant-offset trace is exercised too
        csrcs = [[], [0xAA], [], [0xBB, 0xCC]]
        outs = []
        for tr in pair:
            b = rtp_header.build(pls, [700 + i for i in range(4)],
                                 [0] * 4, [0x1234] * 4, [96] * 4,
                                 csrcs=csrcs, stream=[0] * 4)
            # fan-out needs tag headroom beyond the payload
            wide = PacketBatch.empty(b.batch_size, b.capacity + 32)
            wide.data[:, :b.capacity] = b.data
            wide.length[:] = b.length
            wide.stream[:] = b.stream
            out, recv = tr.translate(wide, np.arange(700, 704))
            outs.append({(int(recv[i]), i): out.to_bytes(i)
                         for i in range(out.batch_size)})
        assert outs[0] == outs[1], f"{profile} sharded fan-out diverged"


def test_sharded_table_kdr_rekey_parity():
    """kdr epoch re-keying on the SHARDED table: _install_session_keys
    mutates the key masters mid-stream, which must invalidate the
    sharded device copies through the _dev mirror — wire stays byte-
    identical to the plain table across an epoch boundary."""
    kdr = 8
    rng = np.random.default_rng(77)
    mk = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
    ms = rng.integers(0, 256, 14, dtype=np.uint8).tobytes()
    mesh = make_media_mesh()
    sh = ShardedSrtpTable(8, mesh)
    sh.add_stream(3, mk, ms, kdr=kdr)
    pl = SrtpStreamTable(8)
    pl.add_stream(3, mk, ms, kdr=kdr)

    def batch(start):
        return rtp_header.build([b"k" * 48] * 4,
                                [start + i for i in range(4)],
                                [0] * 4, [0x42] * 4, [96] * 4,
                                stream=[3] * 4)

    for start in (0, 6, 14, 30):       # crosses epochs 0->1->3
        w_sh = sh.protect_rtp(batch(start))
        w_pl = pl.protect_rtp(batch(start))
        for i in range(4):
            assert w_sh.to_bytes(i) == w_pl.to_bytes(i), (start, i)
    assert sh._epoch_rtp[3] == pl._epoch_rtp[3] >= 1
