import numpy as np
import pytest

from libjitsi_tpu.core import rtp_math as rm


def test_seq_delta_basic():
    assert rm.seq_delta(10, 5) == 5
    assert rm.seq_delta(5, 10) == -5
    # wrap
    assert rm.seq_delta(2, 65534) == 4
    assert rm.seq_delta(65534, 2) == -4
    # extremes
    assert rm.seq_delta(0x8000, 0) == -32768
    assert rm.seq_delta(0, 0) == 0


def test_seq_delta_vectorized():
    a = np.array([10, 2, 65534, 0])
    b = np.array([5, 65534, 2, 0x8000])
    np.testing.assert_array_equal(rm.seq_delta(a, b), [5, 4, -4, -32768])


def test_is_newer_seq():
    assert rm.is_newer_seq(1, 65535)
    assert not rm.is_newer_seq(65535, 1)
    assert not rm.is_newer_seq(7, 7)


def test_ts_delta_wrap():
    assert rm.ts_delta(5, 0xFFFFFFFF) == 6
    assert rm.ts_delta(0xFFFFFFFF, 5) == -6
    assert rm.ts_delta(123, 123) == 0


@pytest.mark.parametrize(
    "seq,s_l,roc,expect_v",
    [
        (100, 50, 0, 0),  # in order, same roc
        (5, 65000, 3, 4),  # just wrapped: guess roc+1
        (65000, 5, 4, 3),  # late packet from before wrap: guess roc-1
        (40000, 30000, 2, 2),  # large forward jump, no wrap (s_l < 32768... no)
    ],
)
def test_estimate_packet_index(seq, s_l, roc, expect_v):
    v, idx = rm.estimate_packet_index(seq, s_l, roc)
    assert int(v) == expect_v
    assert int(idx) == expect_v * 65536 + seq


def test_estimate_index_never_negative_roc():
    v, idx = rm.estimate_packet_index(65000, 5, 0)
    assert int(v) == 0  # clamped; a "before stream start" packet
    assert int(idx) == 65000


def test_update_index_state():
    # normal advance
    assert rm.update_index_state(100, 0, 50, 0) == (100, 0)
    # reordered old packet: no update
    assert rm.update_index_state(40, 0, 50, 0) == (50, 0)
    # rollover commit
    assert rm.update_index_state(3, 1, 65530, 0) == (3, 1)


def test_unwrapper_monotone_and_reorder():
    u = rm.SeqNumUnwrapper()
    seqs = [65530, 65531, 65535, 0, 1, 65533, 2, 3]
    exts = [u.unwrap(s) for s in seqs]
    assert exts[0] == 65530
    assert exts[3] == 65536  # wrapped
    assert exts[5] == 65533  # reordered pre-wrap packet keeps old epoch
    assert exts[-1] == 65536 + 3


def test_unwrapper_many_cycles():
    u = rm.SeqNumUnwrapper()
    ext = 0
    rng = np.random.default_rng(0)
    seq = 0
    last = 0
    for _ in range(5000):
        step = int(rng.integers(1, 50))
        seq = (seq + step) % 65536
        ext = u.unwrap(seq)
        assert ext > last
        last = ext


def test_unwrapper_pre_start_reorder_keeps_ordering():
    u = rm.SeqNumUnwrapper()
    assert u.unwrap(5) == 5
    # reordered packet from before stream start must not jump to the future
    assert u.unwrap(65530) == 0
    assert u.unwrap(6) == 6
