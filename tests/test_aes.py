"""AES kernel KATs (FIPS-197, NIST SP 800-38A) + OpenSSL differential tests."""

import numpy as np
import pytest

from libjitsi_tpu.kernels import aes


def test_sbox_known_values():
    assert aes._SBOX[0x00] == 0x63
    assert aes._SBOX[0x01] == 0x7C
    assert aes._SBOX[0x53] == 0xED
    assert aes._SBOX[0xFF] == 0x16
    # S-box is a permutation
    assert len(set(aes._SBOX.tolist())) == 256


def _encrypt_one(key: bytes, block: bytes) -> bytes:
    rk = aes.expand_key(key)[None]
    out = aes.aes_encrypt(rk, np.frombuffer(block, dtype=np.uint8)[None])
    return bytes(np.asarray(out)[0])


def test_fips197_aes128():
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    assert _encrypt_one(key, pt).hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"


def test_fips197_aes256():
    key = bytes.fromhex(
        "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f"
    )
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    assert _encrypt_one(key, pt).hex() == "8ea2b7ca516745bfeafc49904b496089"


def test_nist_sp800_38a_ctr128():
    # SP 800-38A F.5.1 CTR-AES128.Encrypt, first two blocks
    key = bytes.fromhex("2b7e151628aed2a6abf7158809cf4f3c")
    iv = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff")
    pt = bytes.fromhex(
        "6bc1bee22e409f96e93d7e117393172a"
        "ae2d8a571e03ac9c9eb76fac45af8e51"
    )
    rk = aes.expand_key(key)[None]
    ks = np.asarray(
        aes.ctr_keystream(rk, np.frombuffer(iv, dtype=np.uint8)[None], 2)
    )[0]
    ct = bytes(a ^ b for a, b in zip(pt, bytes(ks)))
    assert ct.hex() == (
        "874d6191b620e3261bef6864990db6ce"
        "9806f66b7970fdff8617187bb9fffdff"
    )


def test_ctr_counter_carry():
    """128-bit counter increment must carry across limb boundaries."""
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    key = bytes(range(16))
    iv = bytes.fromhex("00000000000000000000000000ffffff")  # carries into limb 2
    rk = aes.expand_key(key)[None]
    ks = bytes(
        np.asarray(
            aes.ctr_keystream(rk, np.frombuffer(iv, dtype=np.uint8)[None], 4)
        )[0]
    )
    enc = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    assert ks == enc.update(b"\x00" * 64)


@pytest.mark.parametrize("keylen", [16, 32])
def test_differential_vs_openssl(keylen):
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    rng = np.random.default_rng(1234 + keylen)
    bsz = 8
    keys = rng.integers(0, 256, (bsz, keylen), dtype=np.uint8)
    ivs = rng.integers(0, 256, (bsz, 16), dtype=np.uint8)
    rk = aes.expand_keys_batch(keys)
    ks = np.asarray(aes.ctr_keystream(rk, ivs, 8))  # 128 bytes per row
    for i in range(bsz):
        enc = Cipher(
            algorithms.AES(bytes(keys[i])), modes.CTR(bytes(ivs[i]))
        ).encryptor()
        assert bytes(ks[i]) == enc.update(b"\x00" * 128), f"row {i}"


def test_ctr_crypt_offset_window():
    """Keystream must align to each row's offset and leave outside bytes."""
    rng = np.random.default_rng(7)
    bsz, width = 4, 96
    keys = rng.integers(0, 256, (bsz, 16), dtype=np.uint8)
    ivs = rng.integers(0, 256, (bsz, 16), dtype=np.uint8)
    data = rng.integers(0, 256, (bsz, width), dtype=np.uint8)
    offset = np.array([12, 16, 0, 40], dtype=np.int32)
    length = np.array([60, 80, 96, 13], dtype=np.int32)
    rk = aes.expand_keys_batch(keys)
    out = np.asarray(aes.ctr_crypt_offset(rk, ivs, data, offset, length))
    ks = np.asarray(aes.ctr_keystream(rk, ivs, (width + 15) // 16))
    for i in range(bsz):
        o, l = int(offset[i]), int(length[i])
        expect = data[i].copy()
        expect[o : o + l] ^= ks[i, :l]
        np.testing.assert_array_equal(out[i], expect)
    # decrypt round-trips
    back = np.asarray(aes.ctr_crypt_offset(rk, ivs, out, offset, length))
    np.testing.assert_array_equal(back, data)
