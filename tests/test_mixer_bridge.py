"""Whole-bridge multi-conference mixing (one launch for C conferences)."""

import numpy as np
import pytest

from libjitsi_tpu.conference import AudioMixer, MixerBridge, mix_minus_many


def test_mix_many_matches_per_conference_mix():
    rng = np.random.default_rng(1)
    C, N, F = 5, 8, 160
    pcm = rng.integers(-20000, 20000, (C, N, F)).astype(np.int16)
    active = rng.random((C, N)) < 0.7
    out, levels = mix_minus_many(pcm, active)
    for c in range(C):
        mixer = AudioMixer(capacity=N, frame_samples=F)
        for s in range(N):
            if active[c, s]:
                mixer.add_participant(s)
                mixer.push(s, pcm[c, s])
        # AudioMixer levels include inactive rows' pcm? mix() consumes
        # only deposited frames; emulate by pushing zeros for inactive
        want_out, want_lv = mixer.mix()
        got_out = np.asarray(out[c])
        # inactive rows in mix_many keep their (undeposited) pcm in the
        # level calc; compare levels only on active rows
        assert np.array_equal(got_out[active[c]], want_out[active[c]])
        assert np.array_equal(np.asarray(levels[c])[active[c]],
                              want_lv[active[c]])


def test_bridge_lifecycle_and_mix_minus():
    br = MixerBridge(conferences=4, capacity=6, frame_samples=80)
    a = br.alloc_conference()
    b = br.alloc_conference()
    assert a != b
    rng = np.random.default_rng(2)
    fa = {s: rng.integers(-3000, 3000, 80).astype(np.int16) for s in (0, 1)}
    fb = {s: rng.integers(-3000, 3000, 80).astype(np.int16)
          for s in (2, 3, 4)}
    for s, f in fa.items():
        br.add_participant(a, s)
        br.push(a, s, f)
    for s, f in fb.items():
        br.add_participant(b, s)
        br.push(b, s, f)
    out, levels = br.tick()
    # conference a: each hears the other
    assert np.array_equal(out[a, 0], fa[1])
    assert np.array_equal(out[a, 1], fa[0])
    # conference b: mix-minus of three
    tot = sum(f.astype(np.int64) for f in fb.values())
    for s, f in fb.items():
        want = np.clip(tot - f, -32768, 32767).astype(np.int16)
        assert np.array_equal(out[b, s], want)
    # conferences are isolated: a's rows never see b's audio
    assert not np.array_equal(out[a, 0], out[b, 2])
    # frames consumed: next tick is silence
    out2, _ = br.tick()
    assert not out2[a].any() and not out2[b].any()


def test_bridge_alloc_release_exhaustion():
    br = MixerBridge(conferences=2, capacity=2, frame_samples=80)
    c0, c1 = br.alloc_conference(), br.alloc_conference()
    with pytest.raises(RuntimeError):
        br.alloc_conference()
    br.release_conference(c0)
    assert br.alloc_conference() == c0


def test_bridge_rejects_bad_frame_shape():
    br = MixerBridge(conferences=1, capacity=2, frame_samples=80)
    cid = br.alloc_conference()
    br.add_participant(cid, 0)
    with pytest.raises(ValueError):
        br.push(cid, 0, np.zeros(81, np.int16))
