"""StreamLifecycleManager unit tests: the admission state machine
(queue -> stage -> commit), every typed rejection reason, evict
bookkeeping vs overload shedding, bucketed warmup cadence, the
tick-bracket compile guard, and checkpoint reconciliation — all
against a host-only dummy bridge (no sockets, no device).  The e2e
staged-install recovery proof lives in tests/test_chaos_recovery.py
and the full churn soak in scripts/churn_soak.py (slow twin below).
"""

import importlib.util
import os
import types

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.control.dtls import StubDtlsEndpoint
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.service.lifecycle import (ADMIT_REASONS,
                                            LifecycleConfig,
                                            StreamLifecycleManager)
from libjitsi_tpu.service.sfu_bridge import SfuBridge
from libjitsi_tpu.service.supervisor import (BridgeSupervisor,
                                             SupervisorConfig)
from libjitsi_tpu.utils.metrics import MetricsRegistry

_SOAK = os.path.join(os.path.dirname(__file__), os.pardir,
                     "scripts", "churn_soak.py")


class WarmTable:
    """Records warmup calls: the lifecycle plane's pre-compile cadence
    is observable as the exact (row_class) sequence it warms."""

    def __init__(self):
        self.rtp_warms = []
        self.rtcp_warms = []
        self.active = np.zeros(64, dtype=bool)

    def warmup_rtp(self, rows, payload_len=160):
        self.rtp_warms.append(rows)

    def warmup_rtcp(self, batch_size=1):
        self.rtcp_warms.append(batch_size)


class LcBridge:
    """Host-side stand-in implementing exactly the surface the manager
    drives: slot registry, stage/commit/remove, warmable tables."""

    def __init__(self, capacity=8):
        self.capacity = capacity
        self._free = list(range(capacity))
        self._ssrc_of = {}
        self._tx_keys = {}
        self._staged = set()
        self.rx_table = WarmTable()
        self.tx_table = WarmTable()
        self.calls = []
        bridge = self

        class _Reg:
            @property
            def free_slots(self):
                return len(bridge._free)

        self.registry = _Reg()

    def stage_endpoints(self, specs):
        sids = []
        for ssrc, rx, tx, _name in specs:
            sid = self._free.pop(0)
            self._ssrc_of[sid] = ssrc
            self._tx_keys[sid] = tuple(tx)
            self._staged.add(sid)
            sids.append(sid)
        self.calls.append(("stage", tuple(sids)))
        return sids

    def commit_endpoints(self, sids):
        for sid in sids:
            self._staged.discard(int(sid))
        self.calls.append(("commit", tuple(int(s) for s in sids)))

    def remove_endpoints(self, sids):
        for sid in sids:
            sid = int(sid)
            self._ssrc_of.pop(sid, None)
            self._tx_keys.pop(sid, None)
            self._staged.discard(sid)
            self._free.append(sid)
        self.calls.append(("remove", tuple(int(s) for s in sids)))


def _keys(b):
    return (bytes([b]) * 16, bytes([b + 1]) * 14)


def _lc(capacity=8, supervisor=None, **cfg):
    bridge = LcBridge(capacity=capacity)
    lc = StreamLifecycleManager(bridge, supervisor=supervisor,
                                config=LifecycleConfig(**cfg))
    return lc, bridge


def _all_events(flight):
    """Flatten global + per-stream rings, in record order (sid-keyed
    events route to per-stream rings; `seq` restores the interleave)."""
    d = flight.dump_all()
    evs = list(d["global"])
    for ring in d["streams"].values():
        evs.extend(ring)
    return sorted(evs, key=lambda e: e["seq"])


def _global_kinds(lc):
    return [e["kind"] for e in lc.flight.dump_all()["global"]]


# ---------------------------------------------------- admit pipeline

def test_join_queues_then_stages_then_commits_off_tick():
    lc, bridge = _lc()
    ok, why = lc.request_join(0x10, _keys(2), _keys(4))
    assert (ok, why) == (True, "queued")
    # nothing touched the bridge yet — admission is pure bookkeeping
    assert not bridge.calls and lc.admits == 0
    # barrier 1: commit (nothing staged) then stage the install wave
    lc.run_between_ticks()
    assert bridge.calls == [("stage", (0,))]
    assert lc.key_installs == 1 and lc.admits == 0
    assert 0 in bridge._staged            # staged, not yet live
    # barrier 2: the staged batch flips live atomically
    lc.run_between_ticks()
    assert bridge.calls[1] == ("commit", (0,))
    assert lc.admits == 1 and 0 not in bridge._staged
    kinds = [e["kind"] for e in _all_events(lc.flight)]
    assert kinds.index("admit_queued") < kinds.index("key_install") \
        < kinds.index("admit_commit")


def test_install_wave_is_batch_limited():
    lc, bridge = _lc(capacity=8, install_batch=2)
    for i in range(5):
        assert lc.request_join(0x100 + i, _keys(2), _keys(4))[0]
    lc.run_between_ticks()
    assert len(bridge._staged) == 2       # install_batch, not all 5
    assert lc.key_installs_pending == 5   # 3 queued + 2 staged
    lc.run_between_ticks()                # commit 2, stage next 2
    assert lc.admits == 2 and len(bridge._staged) == 2
    lc.run_between_ticks()
    lc.run_between_ticks()
    assert lc.admits == 5 and lc.key_installs_pending == 0


def test_leave_cancels_queued_join_without_touching_bridge():
    lc, bridge = _lc()
    lc.request_join(0x42, _keys(2), _keys(4))
    assert lc.request_leave(ssrc=0x42)
    lc.run_between_ticks()
    assert not bridge.calls and lc.admits == 0 and lc.evicts == 0
    assert "admit_cancelled" in _global_kinds(lc)
    # unknown ssrc: nothing to cancel or evict
    assert not lc.request_leave(ssrc=0xDEAD)


def test_live_evict_lands_at_the_barrier_and_recycles_the_slot():
    lc, bridge = _lc(capacity=2)
    lc.request_join(0x21, _keys(2), _keys(4))
    lc.request_join(0x22, _keys(6), _keys(8))
    lc.run_between_ticks()
    lc.run_between_ticks()
    assert lc.admits == 2 and bridge.registry.free_slots == 0
    assert lc.request_leave(ssrc=0x21)
    # queued evict frees nothing until the barrier
    assert bridge.registry.free_slots == 0
    lc.run_between_ticks()
    assert lc.evicts == 1 and bridge.registry.free_slots == 1
    assert ("remove", (0,)) in bridge.calls
    # duplicate evict requests de-dup; departed sid is simply gone
    lc.request_leave(sid=0)
    lc.request_leave(sid=0)
    lc.run_between_ticks()
    assert lc.evicts == 1
    # the freed slot admits a NEW stream
    assert lc.request_join(0x23, _keys(10), _keys(12))[0]
    lc.run_between_ticks()
    lc.run_between_ticks()
    assert lc.admits == 3 and 0x23 in bridge._ssrc_of.values()


# ------------------------------------------------- typed rejections

def test_host_side_rejections_are_typed_and_counted():
    lc, bridge = _lc(capacity=2, max_pending=8)
    assert lc.request_join(0x31, _keys(2), _keys(4))[0]
    # duplicate: already queued
    assert lc.request_join(0x31, _keys(2), _keys(4)) \
        == (False, "duplicate")
    # capacity: queued joins have slots spoken for (2 slots, 1 queued,
    # next join fits; the one after does not)
    assert lc.request_join(0x32, _keys(6), _keys(8))[0]
    assert lc.request_join(0x33, _keys(10), _keys(12)) \
        == (False, "capacity")
    lc.run_between_ticks()
    lc.run_between_ticks()
    # duplicate: already live
    assert lc.request_join(0x31, _keys(2), _keys(4)) \
        == (False, "duplicate")
    assert lc.admit_rejected == {"duplicate": 2, "capacity": 1}
    rejects = [e for e in lc.flight.dump_all()["global"]
               if e["kind"] == "admit_reject"]
    assert [e["reason"] for e in rejects] \
        == ["duplicate", "capacity", "duplicate"]
    assert all(e["reason"] in ADMIT_REASONS for e in rejects)


def test_backlog_rejection_bounds_the_queue():
    lc, _bridge = _lc(capacity=8, max_pending=3)
    for i in range(3):
        assert lc.request_join(0x50 + i, _keys(2), _keys(4))[0]
    assert lc.request_join(0x60, _keys(2), _keys(4)) \
        == (False, "backlog")
    assert lc.admit_rejected == {"backlog": 1}


def test_supervisor_burn_reasons_pass_through():
    for reason in ("fast_burn", "stalled", "shedding", "host_bound"):
        sup = types.SimpleNamespace(
            ticks=7, flight=None, pending_lifecycle=None,
            admission_decision=lambda r=reason: (False, r))
        lc, _bridge = _lc()
        lc.supervisor = sup        # attach after init: flight stays own
        assert lc.request_join(0x70, _keys(2), _keys(4)) \
            == (False, reason)
        assert lc.admit_rejected == {reason: 1}
        assert reason in ADMIT_REASONS
        (ev,) = [e for e in lc.flight.dump_all()["global"]
                 if e["kind"] == "admit_reject"]
        assert ev["tick"] == 7 and ev["reason"] == reason


def test_rejections_render_as_typed_metric_labels():
    reg = MetricsRegistry()
    bridge = LcBridge(capacity=1)
    lc = StreamLifecycleManager(bridge, config=LifecycleConfig(),
                                metrics=reg)
    lc.request_join(0x10, _keys(2), _keys(4))
    lc.request_join(0x10, _keys(2), _keys(4))     # duplicate
    lc.request_join(0x11, _keys(2), _keys(4))     # capacity
    txt = reg.render()
    assert ('libjitsi_tpu_lifecycle_admit_rejected'
            '{reason="duplicate"} 1') in txt
    assert ('libjitsi_tpu_lifecycle_admit_rejected'
            '{reason="capacity"} 1') in txt
    assert "# TYPE libjitsi_tpu_lifecycle_admits counter" in txt


# ------------------------------------------------- bucketed warmup

def test_warmups_fire_only_at_bucket_boundaries():
    lc, bridge = _lc(capacity=64, min_bucket=4, pkts_per_stream=4,
                     install_batch=64, max_pending=512)
    lc.request_join(0x80, _keys(2), _keys(4))
    lc.run_between_ticks()
    # bucket 4 -> aggregate estimate 16 rows -> one class of headroom
    # covers 64; both tables warm RTP and RTCP for each class
    assert bridge.rx_table.rtp_warms == [16, 64]
    assert bridge.tx_table.rtp_warms == [16, 64]
    assert bridge.rx_table.rtcp_warms == [16, 64]
    # admits WITHIN the bucket compile nothing new
    for i in range(3):
        lc.request_join(0x81 + i, _keys(2), _keys(4))
    lc.run_between_ticks()
    assert bridge.rx_table.rtp_warms == [16, 64]
    # crossing the boundary warms only the NEW classes, off-tick
    for i in range(10):
        lc.request_join(0x90 + i, _keys(2), _keys(4))
    lc.run_between_ticks()
    assert bridge.rx_table.rtp_warms == [16, 64, 256]
    assert lc._warm_bucket == 16


# -------------------------------------------- tick compile bracket

def test_tick_bracket_counts_in_window_compiles(monkeypatch):
    from libjitsi_tpu.service import lifecycle as lc_mod
    events = {"n": 0}
    monkeypatch.setattr(
        lc_mod, "compile_stats",
        lambda: types.SimpleNamespace(compile_events=events["n"]))
    lc, _bridge = _lc()
    lc.tick_begin()
    lc.tick_end()                 # quiet tick: clean
    assert lc.datapath_recompiles == 0
    lc.assert_datapath_clean()
    lc.tick_begin()
    events["n"] += 3              # a compile landed INSIDE the tick
    lc.tick_end()
    assert lc.datapath_recompiles == 3
    assert "datapath_recompile" in _global_kinds(lc)
    with pytest.raises(AssertionError, match="3 compile event"):
        lc.assert_datapath_clean()
    # compiles between brackets (off-tick) never count
    events["n"] += 5
    lc.tick_begin()
    lc.tick_end()
    assert lc.datapath_recompiles == 3


# ------------------------------------------ shed vs evict separation

class DummyLoop:
    def __init__(self, cap):
        self.registry = types.SimpleNamespace(capacity=cap)
        self.recv_window_ms = 1
        self.inbound_drop = np.zeros(cap, dtype=bool)
        self.inbound_dropped = np.zeros(cap, dtype=np.int64)
        self.inbound_dropped_total = 0


class DummyBridge:
    def __init__(self, cap=8, sids=(0, 1, 2, 3)):
        self.loop = DummyLoop(cap)
        self.degraded = False
        self._ssrc_of = {s: 100 + s for s in sids}
        self.rx_table = types.SimpleNamespace(
            auth_fail=np.zeros(cap, dtype=np.int64),
            replay_reject=np.zeros(cap, dtype=np.int64))
        self.speaker = types.SimpleNamespace(dominant=0)

    def tick(self, now=None):
        return {"rx": 0}


class FakeClock:
    def __init__(self, durations):
        self.durations = list(durations)
        self.t = 0.0
        self.half = False

    def __call__(self):
        if self.half:
            self.t += self.durations.pop(0) if self.durations else 0.0
        self.half = not self.half
        return self.t


def test_lifo_unwind_never_resurrects_an_evicted_stream():
    # drive the ladder until streams shed, evict one of them via the
    # lifecycle path, then recover: the LIFO unwind must restore the
    # OTHER shed streams and skip the departed one
    sup = BridgeSupervisor(
        DummyBridge(), SupervisorConfig(deadline_ms=10.0,
                                        overload_after=1, shed_step=2,
                                        overload_exit=1),
        clock=FakeClock([0.05] * 7 + [0.001] * 30))
    for _ in range(7):
        sup.tick()
    shed = list(sup._shed)
    assert len(shed) >= 2
    gone = shed[-1]
    sup.note_evicted([gone])
    assert gone not in sup._shed_set      # membership cleared at once
    assert gone in sup._evicted
    assert sup.health()["evicted"] == 1
    for _ in range(30):
        sup.tick()
    assert sup.level == 0 and not sup._shed
    restored = [e["sid"] for e in _all_events(sup.flight)
                if e["kind"] == "shed_restore"]
    assert gone not in restored
    assert set(restored) == set(shed) - {gone}
    # flight keeps the two mortalities distinct
    kinds_gone = [e["kind"] for e in sup.flight.dump(gone)["events"]]
    assert "evicted" in kinds_gone and "shed" in kinds_gone
    # a NEW stream admitted into the recycled row is shed-eligible again
    sup.note_admitted([gone])
    assert gone not in sup._evicted and sup.health()["evicted"] == 0


def test_eviction_clears_quarantine_and_strike_history():
    cfg = SupervisorConfig(deadline_ms=1000.0, quarantine_window=5,
                           quarantine_auth_threshold=10,
                           quarantine_backoff_ticks=4)
    bridge = DummyBridge()
    sup = BridgeSupervisor(bridge, cfg)
    for _ in range(3):
        bridge.rx_table.auth_fail[2] += 4
        sup.tick(now=0.0)
    assert 2 in sup._quarantined
    sup.note_evicted([2])
    # the departed stream's ban and strike history die with it: the
    # row's next occupant starts with a clean record
    assert 2 not in sup._quarantined and 2 not in sup._q_strikes
    assert not bridge.loop.inbound_drop[2]


def test_admission_decision_reflects_live_pressure():
    sup = BridgeSupervisor(DummyBridge(),
                           SupervisorConfig(deadline_ms=10.0))
    assert sup.admission_decision() == (True, "ok")
    sup._shed_set.add(3)
    assert sup.admission_decision() == (False, "shedding")
    sup._shed_set.clear()
    sup.slo = types.SimpleNamespace(state=lambda *a: "fast_burn",
                                    on_tick=lambda: None)
    assert sup.admission_decision() == (False, "fast_burn")


# ------------------------------------------------- handshake plane

def _dtls_lc(**cfg):
    """Real SfuBridge (the handshake plane wraps its association
    table) + supervisor + lifecycle manager, stub endpoints so the
    tests run without the `cryptography` package."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    bridge = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                       capacity=8, recv_window_ms=0)
    bridge._dtls.endpoint_factory = StubDtlsEndpoint
    sup = BridgeSupervisor(bridge, SupervisorConfig(deadline_ms=1000.0))
    lc = StreamLifecycleManager(bridge, supervisor=sup,
                                config=LifecycleConfig(**cfg))
    # bucketed warmups are the churn soak's subject; skip them here so
    # the tests pin handshake semantics without minutes of pre-compiles
    lc._warm_bucket = 1 << 30
    return lc, bridge, sup


def test_request_handshake_requires_a_dtls_table():
    lc, _bridge = _lc()                  # LcBridge has no _dtls
    with pytest.raises(RuntimeError, match="no DTLS association table"):
        lc.request_handshake(0x10)


def test_handshake_backpressure_is_typed_with_retry_hint():
    lc, bridge, sup = _dtls_lc(max_handshakes=2)
    try:
        assert lc.request_handshake(0x61) == (True, "queued", 0.0)
        assert lc.request_handshake(0x62)[0]
        assert lc.handshakes.depth == 2
        # the refusal originates in the supervisor's burn-aware
        # admission decision, typed like shard_burn/fast_burn
        assert sup.admission_decision(handshake_backlog=2,
                                      handshake_bound=2) \
            == (False, "handshake_backlog")
        assert sup.admission_decision(handshake_backlog=1,
                                      handshake_bound=2) == (True, "ok")
        ok, reason, retry = lc.request_handshake(0x63)
        assert (ok, reason) == (False, "handshake_backlog")
        assert reason in ADMIT_REASONS
        assert retry > 0.0 and retry == lc.handshakes.retry_after()
        # duplicate outranks backlog and carries no retry hint
        assert lc.request_handshake(0x61) == (False, "duplicate", 0.0)
        assert lc.admit_rejected \
            == {"handshake_backlog": 1, "duplicate": 1}
        ev = [e for e in _all_events(lc.flight)
              if e["kind"] == "handshake_reject"]
        assert [e["reason"] for e in ev] \
            == ["handshake_backlog", "duplicate"]
        assert ev[0]["retry_after_s"] == retry
        # a deeper backlog raises the hint: refused clients scale
        # their exponential backoff on it, spreading the retry wave
        lc.handshakes.table._inbox.extend(
            (b"", (9, 9)) for _ in range(3 * lc.cfg.handshake_batch))
        assert lc.handshakes.retry_after() > retry
    finally:
        bridge.close()


def test_handshake_keys_land_only_via_the_commit_barrier():
    """End-to-end against a real bridge: the tick thread only ENQUEUES
    handshake datagrams, every endpoint feed runs on the off-tick
    drain, completion stages the keys, and only the commit barrier
    flips the row live."""
    lc, bridge, _sup = _dtls_lc()
    eng = UdpEngine(port=0, max_batch=32)
    try:
        caddr = (0x7F000001, eng.port)          # 127.0.0.1 as uint32
        assert lc.request_handshake(0x60, remote_addr=caddr)[0]
        sid = next(s for s, v in bridge._ssrc_of.items() if v == 0x60)
        fp = bridge._dtls.pending[sid].local_fingerprint
        client = StubDtlsEndpoint("client", remote_fingerprint=fp)
        # in-tick ingest: enqueue only — zero endpoint feeds
        lc.tick_begin()
        for d in client.handshake_packets():
            bridge._dtls.on_dtls(d, caddr)
        lc.tick_end()
        assert bridge._dtls.feeds_total == 0
        assert lc.tick_thread_handshake_feeds == 0
        # off-tick drain passes until the server side completes; the
        # client's flights re-enter through the same enqueue-only path
        for _ in range(80):
            lc.handshakes.drain()
            if sid in bridge._staged:
                break
            back, _, _ = eng.recv_batch(timeout_ms=20)
            lc.tick_begin()
            for i in range(back.batch_size):
                for out in client.feed(back.to_bytes(i)):
                    bridge._dtls.on_dtls(out, caddr)
            lc.tick_end()
        # completed: STAGED with keys, not yet live, never inline
        assert sid in bridge._staged and sid in bridge._tx_keys
        assert sid not in bridge._dtls.pending
        assert lc.key_installs == 1 and lc.handshakes.completed == 1
        assert lc.admits == 0
        assert bridge._dtls.feeds_total > 0
        assert lc.tick_thread_handshake_feeds == 0
        assert lc.handshakes.off_tick_seconds > 0.0
        # the commit barrier flips it live
        lc.commit()
        assert sid not in bridge._staged and lc.admits == 1
        kinds = [e["kind"] for e in _all_events(lc.flight)]
        assert kinds.index("handshake_queued") \
            < kinds.index("handshake_complete") \
            < kinds.index("admit_commit")
        # the client side finishes off the DONE flight and both ends
        # export the same traffic keys (bridge tx == client's rx half)
        back, _, _ = eng.recv_batch(timeout_ms=100)
        for i in range(back.batch_size):
            client.feed(back.to_bytes(i))
        assert client.complete
        _prof, _ck, _cs, sk, ss = client.srtp_keys()
        assert bridge._tx_keys[sid] == (sk, ss)
    finally:
        eng.close()
        bridge.close()


# --------------------------------------------------- reconciliation

def test_reconcile_completes_surviving_staged_and_rolls_back_rest():
    lc, bridge = _lc()
    # survivor: keys + ssrc mapping rode the bridge snapshot
    bridge._ssrc_of[3] = 0xA1
    bridge._tx_keys[3] = _keys(4)
    bridge._free.remove(3)
    # half state: row mapped but its keys did NOT survive
    bridge._ssrc_of[5] = 0xA2
    bridge._free.remove(5)
    lc._reconcile({
        "staged": [(3, 0xA1), (5, 0xA2), (6, 0xA3)],
        "queued": [(0xB1, _keys(2), _keys(4), None)],
    })
    # survivor completed
    assert lc.admits == 1
    assert any(e["kind"] == "admit_commit" and e.get("recovered")
               for e in _all_events(lc.flight))
    # half-installed row rolled back — removed, slot freed
    assert ("remove", (5,)) in bridge.calls
    assert 5 not in bridge._ssrc_of and 5 in bridge._free
    # fully-absent row: rollback recorded, nothing to remove
    rb = [e for e in _all_events(lc.flight)
          if e["kind"] == "admit_rollback"]
    assert sorted(e["sid"] for e in rb) == [5, 6]
    # queued join re-entered the normal pipeline
    assert lc.key_installs_pending == 1
    lc.run_between_ticks()
    lc.run_between_ticks()
    assert 0xB1 in bridge._ssrc_of.values() and lc.admits == 2
    # invariant: nothing is left half-installed
    for sid in (3, 5, 6):
        assert (sid in bridge._ssrc_of) == (sid in bridge._tx_keys)


def test_constructor_consumes_supervisor_pending_lifecycle():
    sup = BridgeSupervisor(DummyBridge(sids=()),
                           SupervisorConfig(deadline_ms=10.0))
    sup.pending_lifecycle = {
        "staged": [], "queued": [(0xC1, _keys(2), _keys(4), None)]}
    bridge = LcBridge()
    lc = StreamLifecycleManager(bridge, supervisor=sup)
    assert sup.lifecycle is lc and sup.pending_lifecycle is None
    assert lc.key_installs_pending == 1


# --------------------------------------------------------- slow twin

@pytest.mark.slow
def test_churn_soak_invariants():
    spec = importlib.util.spec_from_file_location("churn_soak", _SOAK)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.run_soak(duration_s=2.0, ramp_s=1.0, join_rate_hz=60.0,
                          mean_hold_s=0.5, capacity=128, probes=2,
                          target_events_per_sec=100.0, seed=0,
                          verbose=False)
    failed = {k: v for k, v in report.items()
              if k.startswith("ok_") and not v}
    assert not failed, (failed, report)
    assert report["window_recompiles"] == 0
    assert report["window_admits"] > 0 and report["window_evicts"] > 0


@pytest.mark.slow
def test_broadcast_churn_soak_invariants():
    """Small-config twin of `churn_soak.py --broadcast`: Poisson
    listener churn plus periodic speaker flips on a broadcast
    conference must hold zero data-path recompiles in the steady
    window, refuse no listener, keep the fanout-only mask in lockstep
    with the live listener set, and bound listener-join p99."""
    spec = importlib.util.spec_from_file_location("churn_soak", _SOAK)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.run_broadcast_soak(
        duration_s=3.0, ramp_s=2.0, n_speakers=4, n_listeners=192,
        mean_hold_s=2.0, n_shards=8, capacity=512,
        flip_every_ticks=50, seed=0, verbose=False)
    failed = {k: v for k, v in report.items()
              if k.startswith("ok_") and not v}
    assert not failed, (failed, report)
    assert report["window_recompiles"] == 0
    assert report["speaker_flips"] > 0
    assert report["join_p99_s"] > 0.0


@pytest.mark.slow
def test_reconnect_soak_invariants():
    """Small-config twin of `churn_soak.py --reconnect --smoke`: a
    mass simultaneous-reconnect storm with a mid-storm kill/recover
    must restore media for every client within the p99 bound, keep
    every handshake feed off the tick thread, refuse only with typed
    `handshake_backlog` (retry-after honored), land keys exclusively
    through the staged commit barrier, and reconcile every
    association after recovery — completed, rolled back, or requeued,
    never torn."""
    spec = importlib.util.spec_from_file_location("churn_soak", _SOAK)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.run_reconnect_soak(
        n_clients=24, max_handshakes=6, handshake_batch=8,
        capacity=128, storm_budget_s=60.0, restore_p99_bound_s=10.0,
        seed=0, verbose=False)
    failed = {k: v for k, v in report.items()
              if k.startswith("ok_") and not v}
    assert not failed, (failed, report)
    assert report["window_recompiles"] == 0
    assert report["torn_rows"] == []
    assert report["handshakes_completed"] == report["key_installs_staged"]
    assert report["refusals"].get("handshake_backlog", 0) > 0


@pytest.mark.slow
def test_cascade_soak_invariants():
    """Small-config twin of `churn_soak.py --cascade --smoke`: a
    two-bridge cascade carrying the speaker bus over the trunk, bridge
    A killed mid-call — the survivor must detect the failover, adopt
    the evicted orphan through the commit barrier, restore media within
    the p99 bound with zero data-path recompiles, refuse only with
    typed `trunk_down` (retry-after honored), and reconcile every row
    — committed-with-keys or staged, never torn."""
    spec = importlib.util.spec_from_file_location("churn_soak", _SOAK)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    report = mod.run_cascade_soak(
        n_senders=3, n_receivers=2, pre_rounds=10, post_rounds=60,
        restore_p99_bound_s=2.0, seed=0, verbose=False)
    failed = {k: v for k, v in report.items()
              if k.startswith("ok_") and not v}
    assert not failed, (failed, report)
    assert report["window_recompiles"] == 0
    assert report["torn_rows"] == []
    assert report["failovers"] == 1
    assert report["orphans_adopted"] >= 1
    assert report["refusals"].get("trunk_down", 0) > 0
    assert report["conf_bridge_home"] == 1
