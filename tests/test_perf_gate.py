"""perf_gate: the floor-aware comparison logic with synthetic
baseline/result pairs, the CLI exit contract with stubbed scenarios,
and (slow twin) the real gate against the checked-in baseline.

The invariants that make the gate trustworthy rather than flaky:
a `below_floor:` record on EITHER side is never numerically compared,
tolerance is an exact boundary (not fuzz), and a value slowed past
tolerance always exits 1.
"""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

import perf_gate  # noqa: E402


# ------------------------------------------------------------ judge()

def test_judge_ok_and_regression_boundary():
    # tolerance 0.5 of baseline 100 -> bar at 50; at the bar is OK,
    # below it is a regression (exact boundary, no fuzz)
    assert perf_gate.judge(50.0, 100.0, 0.5)[0] == "ok"
    assert perf_gate.judge(49.999, 100.0, 0.5)[0] == "regression"
    assert perf_gate.judge(100.0, 100.0, 0.5)[0] == "ok"
    assert perf_gate.judge(500.0, 100.0, 0.5)[0] == "ok"


def test_judge_tolerance_respected_per_entry():
    assert perf_gate.judge(30.0, 100.0, 0.75)[0] == "ok"
    assert perf_gate.judge(30.0, 100.0, 0.6)[0] == "regression"


def test_judge_below_floor_never_compared():
    # measured below floor: no comparison even against a tiny baseline
    s, detail = perf_gate.judge("below_floor: net=0.1ms", 1e9, 0.1)
    assert s == "below_floor" and "net=0.1ms" in detail
    # baseline below floor: a huge measured value is not judged either
    s, _ = perf_gate.judge(1e9, "below_floor: net=0.1ms", 0.1)
    assert s == "below_floor"


def test_judge_new_scenario_and_lower_is_better():
    assert perf_gate.judge(123.0, None, 0.5)[0] == "new"
    assert perf_gate.judge(
        1.4, 1.0, 0.5, higher_is_better=False)[0] == "ok"
    assert perf_gate.judge(
        1.6, 1.0, 0.5, higher_is_better=False)[0] == "regression"


def test_judge_ceiling_is_absolute():
    # under the ceiling and near baseline: ok
    assert perf_gate.judge(0.30, 0.30, 0.6, higher_is_better=False,
                           ceiling=0.35)[0] == "ok"
    # over the ceiling fails even within tolerance of a drifted
    # baseline — the bar must not ratchet upward with the baseline
    s, detail = perf_gate.judge(0.40, 0.38, 0.6,
                                higher_is_better=False, ceiling=0.35)
    assert s == "regression" and "ceiling" in detail
    # the ceiling binds even without a baseline value
    assert perf_gate.judge(0.40, None, 0.6,
                           ceiling=0.35)[0] == "regression"
    # a below_floor record is still never numerically compared
    assert perf_gate.judge("below_floor: x", 0.3, 0.6,
                           ceiling=0.35)[0] == "below_floor"


def test_judge_floor_is_absolute():
    """The mesh_agg_pps_ratio bar: a hard floor is judged BEFORE any
    baseline-relative tolerance, so re-baselining on a degraded run
    can never ratchet the bar away (mirror of the ceiling)."""
    # above the floor and near baseline: ok
    assert perf_gate.judge(6.5, 6.4, 0.6, floor=4.0)[0] == "ok"
    # below the floor fails even within tolerance of a drifted-down
    # baseline (3.5 is well inside 0.6 tolerance of 4.2)
    s, detail = perf_gate.judge(3.5, 4.2, 0.6, floor=4.0)
    assert s == "regression" and "floor" in detail
    # the floor binds even without a baseline value
    assert perf_gate.judge(3.5, None, 0.6, floor=4.0)[0] == "regression"
    # below_floor (the TIMER floor) still wins over the absolute bar
    assert perf_gate.judge("below_floor: x", 6.4, 0.6,
                           floor=4.0)[0] == "below_floor"


def test_compare_passes_floor_through():
    baseline = {"m": {"value": 6.4, "tolerance": 0.6,
                      "higher_is_better": True, "floor": 4.0}}
    failures, rows = perf_gate.compare({"m": 3.9}, baseline)
    assert [name for name, _ in failures] == ["m"]
    assert "floor" in rows[0][2]
    # healthy value passes both the floor and the baseline check
    failures, rows = perf_gate.compare({"m": 7.2}, baseline)
    assert failures == [] and rows[0][1] == "ok"


def test_write_baseline_pins_mesh_agg_floor(tmp_path):
    """--write-baseline must re-emit the 4.0 floor on
    mesh_agg_pps_ratio — the cannot-ratchet bar survives honest
    re-baselining."""
    path = tmp_path / "b.json"
    doc = perf_gate.write_baseline(
        str(path), {"mesh_agg_pps_ratio": 6.9, "loop_echo_pps": 1000.0})
    assert doc["mesh_agg_pps_ratio"]["floor"] == 4.0
    assert doc["mesh_agg_pps_ratio"]["higher_is_better"] is True
    on_disk = json.loads(path.read_text())
    assert on_disk["mesh_agg_pps_ratio"]["floor"] == 4.0


def test_ref_floor_resolves_against_same_run(tmp_path):
    """The box-calibration fix (ISSUE 17): a floor of
    {"ref": "protect_small_pps", "mult": 2.0} is judged against the
    SAME-RUN stock result — a slow box where the 2x ratio holds
    passes, even though the old constant floor (stamped on a faster
    machine) would have failed it."""
    baseline = {
        "protect_small_pps": {"value": 44619.1, "tolerance": 0.6},
        "protect_cached_pps": {
            "value": 123927.5, "tolerance": 0.6,
            "floor": {"ref": "protect_small_pps", "mult": 2.0}},
    }
    # slow box: both scenarios at ~58% of the stamped values, ratio
    # 2.35x intact -> green (the old 89238.2 constant would fail)
    failures, rows = perf_gate.compare(
        {"protect_small_pps": 27498.7, "protect_cached_pps": 64589.3},
        baseline)
    assert failures == []
    # ratio actually broken (cache path regressed) -> red, and the
    # failure names the ratio, not a bare number
    failures, rows = perf_gate.compare(
        {"protect_small_pps": 27498.7, "protect_cached_pps": 48000.0},
        baseline)
    assert [n for n, _ in failures] == ["protect_cached_pps"]
    detail = dict((n, d) for n, _s, d in rows)["protect_cached_pps"]
    assert "2x protect_small_pps" in detail


def test_ref_floor_falls_back_to_baseline_value():
    """`--scenarios protect_cached_pps` alone: the referenced sibling
    wasn't re-run, so the bar resolves from the baseline's recorded
    value instead of silently vanishing."""
    baseline = {
        "protect_small_pps": {"value": 40000.0, "tolerance": 0.6},
        "protect_cached_pps": {
            "value": 123927.5, "tolerance": 0.6,
            "floor": {"ref": "protect_small_pps", "mult": 2.0}},
    }
    failures, _rows = perf_gate.compare(
        {"protect_cached_pps": 79000.0}, baseline)   # < 2x 40000
    assert [n for n, _ in failures] == ["protect_cached_pps"]
    failures, _rows = perf_gate.compare(
        {"protect_cached_pps": 81000.0}, baseline)   # >= 2x 40000
    assert failures == []


def test_resolve_bar_passthrough_and_unresolvable():
    # numeric bars pass through untouched (mesh_agg / bcast ratios)
    assert perf_gate.resolve_bar(4.0, {}, {}) == (4.0, None)
    assert perf_gate.resolve_bar(None, {}, {}) == (None, None)
    # unresolvable ref (no same-run result, no baseline entry): the
    # bar is skipped, not crashed on
    floor, label = perf_gate.resolve_bar(
        {"ref": "nope", "mult": 2.0}, {}, {})
    assert floor is None and label is None


def test_write_baseline_cannot_ratchet_ref_floor(tmp_path):
    """Re-stamping emits the REFERENCE floor with the pinned mult
    regardless of what was measured: the mult lives in code, so an
    honest re-baseline on any box can never relax the bar."""
    path = tmp_path / "b.json"
    doc = perf_gate.write_baseline(
        str(path), {"protect_cached_pps": 64589.3,
                    "protect_small_pps": 27498.7,
                    "bcast_fanout_pps": 3.7})
    assert doc["protect_cached_pps"]["floor"] == {
        "ref": "protect_small_pps", "mult": 1.5}
    assert doc["bcast_fanout_pps"]["floor"] == 2.5
    on_disk = json.loads(path.read_text())
    assert on_disk["protect_cached_pps"]["floor"]["mult"] == 1.5


def test_compare_passes_ceiling_through():
    baseline = {"h": {"value": 0.5, "tolerance": 0.6,
                      "higher_is_better": False, "ceiling": 0.35}}
    failures, rows = perf_gate.compare({"h": 0.4}, baseline)
    assert [name for name, _ in failures] == ["h"]
    assert rows[0][1] == "regression"


def test_compare_collects_failures():
    baseline = {
        "a": {"value": 100.0, "tolerance": 0.5},
        "b": {"value": 100.0, "tolerance": 0.5},
        "c": {"value": "below_floor: old box", "tolerance": 0.5},
    }
    failures, rows = perf_gate.compare(
        {"a": 90.0, "b": 10.0, "c": 5.0, "d": 7.0}, baseline)
    statuses = {name: status for name, status, _ in rows}
    assert statuses == {"a": "ok", "b": "regression",
                        "c": "below_floor", "d": "new"}
    assert [name for name, _ in failures] == ["b"]


def test_floor_check_records_string_under_bar(monkeypatch):
    monkeypatch.setitem(perf_gate._FLOOR, "median", 1e-4)
    monkeypatch.setitem(perf_gate._FLOOR, "jitter", 1e-3)
    # bar = 10 * 1ms = 10ms: a 5ms net span is not measurable
    rec = perf_gate.floor_check(1234.0, 0.005)
    assert isinstance(rec, str) and rec.startswith("below_floor:")
    assert perf_gate.floor_check(1234.0, 0.5) == 1234.0


# ------------------------------------------------------- exit contract

@pytest.fixture
def stub_gate(monkeypatch, tmp_path):
    """perf_gate with cheap deterministic scenarios and tmp paths."""
    monkeypatch.setattr(perf_gate, "SCENARIOS", {
        "loop_echo_pps": lambda: 1000.0,
        "protect_small_pps": lambda: 50000.0,
        "install_streams_per_sec": lambda: "below_floor: stub",
    })
    monkeypatch.setattr(
        "libjitsi_tpu.utils.compile_cache.enable_compile_cache",
        lambda *a, **k: None)
    # hermetic: the stub gate's exit contract must not depend on the
    # developer's actual working-tree state
    monkeypatch.setattr(perf_gate, "_git_dirty_files", lambda: [])
    monkeypatch.delenv("PERF_GATE_ALLOW_DIRTY", raising=False)
    base = tmp_path / "base.json"
    trend = tmp_path / "trend.jsonl"
    return base, trend


def _args(base, trend, *extra):
    return ["--baseline", str(base), "--trend", str(trend),
            *extra]


def test_gate_green_and_trend_row(stub_gate, capsys):
    base, trend = stub_gate
    assert perf_gate.main(_args(base, trend, "--write-baseline")) == 0
    doc = json.loads(base.read_text())
    assert doc["loop_echo_pps"] == {
        "value": 1000.0, "tolerance": 0.75, "higher_is_better": True}
    assert doc["install_streams_per_sec"]["value"].startswith(
        "below_floor:")
    assert "_meta" in doc
    assert perf_gate.main(_args(base, trend)) == 0
    assert "PERF_GATE_OK" in capsys.readouterr().out
    rows = [json.loads(ln) for ln in
            trend.read_text().splitlines()]
    assert len(rows) == 1 and rows[0]["results"][
        "loop_echo_pps"] == 1000.0
    assert perf_gate.main(_args(base, trend, "--no-trend")) == 0
    assert len(trend.read_text().splitlines()) == 1    # unchanged


def test_gate_injected_slowdown_exits_nonzero(stub_gate, monkeypatch,
                                              capsys):
    base, trend = stub_gate
    assert perf_gate.main(_args(base, trend, "--write-baseline")) == 0
    monkeypatch.setenv("PERF_GATE_INJECT_SLOW", "loop_echo_pps=10")
    assert perf_gate.main(_args(base, trend, "--no-trend")) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "loop_echo_pps" in out
    # a below_floor scenario is immune to injection (string, no math)
    monkeypatch.setenv("PERF_GATE_INJECT_SLOW",
                       "install_streams_per_sec=1000")
    assert perf_gate.main(_args(base, trend, "--no-trend")) == 0


def test_write_baseline_refuses_dirty_tree(stub_gate, monkeypatch,
                                           capsys):
    """ISSUE 12 hygiene: --write-baseline on a dirty tree would stamp
    _meta.git at a commit that is not the measured code (how PR 11's
    baseline landed one commit behind).  The gate refuses; the escape
    hatch stamps `_meta.tree: "dirty"` so the drift checker flags the
    file until an honest clean-tree run replaces it."""
    base, trend = stub_gate
    monkeypatch.setattr(perf_gate, "_git_dirty_files",
                        lambda: ["libjitsi_tpu/io/udp.py"])
    assert perf_gate.main(_args(base, trend, "--write-baseline")) == 2
    out = capsys.readouterr().out
    assert "dirty" in out and "libjitsi_tpu/io/udp.py" in out
    assert not base.exists()

    monkeypatch.setenv("PERF_GATE_ALLOW_DIRTY", "1")
    assert perf_gate.main(_args(base, trend, "--write-baseline")) == 0
    doc = json.loads(base.read_text())
    assert doc["_meta"]["tree"] == "dirty"

    monkeypatch.delenv("PERF_GATE_ALLOW_DIRTY")
    monkeypatch.setattr(perf_gate, "_git_dirty_files", lambda: [])
    assert perf_gate.main(_args(base, trend, "--write-baseline")) == 0
    doc = json.loads(base.read_text())
    assert doc["_meta"]["tree"] == "clean"
    assert doc["_meta"]["engine_mode"] in ("recvmmsg", "io_uring")


def test_gate_usage_errors_exit_two(stub_gate):
    base, trend = stub_gate
    assert perf_gate.main(_args(base, trend)) == 2    # no baseline yet
    assert perf_gate.main(_args(base, trend,
                                "--scenarios", "nope")) == 2


def test_gate_subset_runs_named_scenario_only(stub_gate, capsys):
    base, trend = stub_gate
    assert perf_gate.main(_args(base, trend, "--write-baseline",
                                "--scenarios", "loop_echo_pps")) == 0
    doc = json.loads(base.read_text())
    assert set(doc) == {"_meta", "loop_echo_pps"}


# ----------------------------------------------------------- slow twin

def _run_gate_subprocess(extra_env=None):
    """Run the real gate exactly the way tier-1 does: a fresh
    interpreter WITHOUT conftest's
    `--xla_force_host_platform_device_count=8` mesh split.  The gate's
    baseline (and the `loop_host_share` ceiling) are calibrated against
    the default single-device CPU backend; the virtual 8-way mesh
    splits XLA's thread pool and shifts the host/device balance, so
    running the scenarios in-process under pytest measures a different
    machine than the one tier-1 gates."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    if extra_env:
        env.update(extra_env)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "perf_gate.py"),
         "--no-trend"],
        cwd=root, env=env, capture_output=True, text=True, timeout=600)


@pytest.mark.slow
def test_real_gate_green_against_checked_in_baseline():
    """The full run tier-1 smokes, as a pytest twin: real scenarios vs
    the checked-in PERF_BASELINE.json."""
    assert os.path.exists(perf_gate.BASELINE_PATH), \
        "PERF_BASELINE.json missing (run --write-baseline)"
    proc = _run_gate_subprocess()
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_real_gate_detects_injected_regression():
    proc = _run_gate_subprocess(
        {"PERF_GATE_INJECT_SLOW": "loop_echo_pps=100,protect_small_pps=100"})
    assert proc.returncode == 1, proc.stdout + proc.stderr
