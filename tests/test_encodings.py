"""EncodingConfiguration registry (reference: EncodingConfigurationImpl
+ FMJPlugInConfiguration's role)."""

import pytest

import libjitsi_tpu
from libjitsi_tpu.codecs import gsm_available, opus_available
from libjitsi_tpu.service.encodings import Encoding, EncodingConfiguration

needs_codecs = pytest.mark.skipif(
    not (opus_available() and gsm_available()),
    reason="libopus/libgsm not present")


@needs_codecs
def test_supported_order_and_disable():
    ec = EncodingConfiguration()
    names = [e.name for e in ec.supported("audio")]
    assert names[0] == "opus"                   # highest default priority
    assert "PCMU" in names and "GSM" in names
    ec.set_priority("opus", 0)                  # disable
    assert "opus" not in [e.name for e in ec.supported("audio")]
    ec.set_priority("GSM", 5000)
    assert [e.name for e in ec.supported("audio")][0] == "GSM"


@needs_codecs
def test_payload_type_assignment():
    ec = EncodingConfiguration()
    table = ec.assign_payload_types("audio")
    # static PTs keep RFC 3551 numbers
    assert table[0].name == "PCMU" and table[8].name == "PCMA"
    assert table[3].name == "GSM"
    # dynamic PTs start at 96, priority order
    dyn = {pt: e.name for pt, e in table.items() if pt >= 96}
    assert dyn[96] == "opus"
    assert all(96 <= pt <= 127 for pt in dyn)


@needs_codecs
def test_apply_to_stream_and_service_accessor():
    libjitsi_tpu.init()
    svc = libjitsi_tpu.media_service()
    ec = svc.encoding_configuration
    s = svc.create_media_stream(media_type="audio")
    table = ec.apply_to_stream(s, "audio")
    pt_opus = next(pt for pt, e in table.items() if e.name == "opus")
    assert s._formats[pt_opus] == ("opus", 48000)
    # the PRIMARY encoding's clock rate is the one the jitter stat keeps
    assert svc.registry.stats.clock_rate[s.sid] == 48000


def test_custom_registration():
    ec = EncodingConfiguration()
    ec.register(Encoding("L16", "audio", 44100, 2, 11), priority=2000)
    assert ec.assign_payload_types("audio")[11].name == "L16"


def test_static_pt_in_dynamic_range_not_clobbered():
    ec = EncodingConfiguration()
    ec.register(Encoding("X", "audio", 8000, 1, 96), priority=9000)
    table = ec.assign_payload_types("audio")
    assert table[96].name == "X"            # static claim holds
    assert "X" in {e.name for e in table.values()}
    # dynamic encodings moved past the occupied PT
    dyn_names = {pt: e.name for pt, e in table.items() if pt > 96}
    assert len(dyn_names) >= 1


def test_dynamic_exhaustion_keeps_statics():
    ec = EncodingConfiguration()
    for k in range(40):                     # flood the dynamic space
        ec.register(Encoding(f"dyn{k}", "audio", 8000), priority=5000 + k)
    table = ec.assign_payload_types("audio")
    assert table[0].name == "PCMU" and table[8].name == "PCMA"


def test_static_pt_priority_not_clobbered():
    from libjitsi_tpu.service.encodings import (Encoding,
                                                EncodingConfiguration)

    ec = EncodingConfiguration()
    ec.register(Encoding("PCMU-wide", "audio", 16000, 1, static_pt=0),
                priority=1)
    table = ec.assign_payload_types("audio")
    assert table[0].name == "PCMU"       # higher priority keeps PT 0
