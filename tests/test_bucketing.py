"""Size-class bucketing (SURVEY §7), applied inside the SRTP table's
protect/unprotect: narrow rows run narrow kernels, the jit cache stays
bounded, and chain engines never see padded/bucketed batches."""

import numpy as np

from libjitsi_tpu.core.packet import bucket_by_size, unbucket
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.engine import TransformEngineChain
from libjitsi_tpu.transform.header_ext import TransportCCEngine
from libjitsi_tpu.transform.srtp import SrtpStreamTable
import pytest

KEY, SALT = bytes(16), bytes(14)


def _mixed_batch(n_small=5, n_big=3, base_seq=1):
    pls = [bytes([i]) * 100 for i in range(n_small)] + \
          [bytes([i]) * 900 for i in range(n_big)]
    n = n_small + n_big
    return rtp_header.build(pls, list(range(base_seq, base_seq + n)),
                            [0] * n, [7] * n, [96] * n, stream=[0] * n)


def test_bucket_shapes_and_reassembly_identity():
    batch = _mixed_batch()
    parts = bucket_by_size(batch)
    assert len(parts) == 2
    for rows, p, n_real in parts:
        assert p.batch_size in (16, 64, 256, 1024, 4096)
        # padding CYCLES the real rows (pad k duplicates real k mod n —
        # keeps per-stream multiplicity <= 2x for the GCM grid's skew
        # statistics)
        for k in range(n_real, p.batch_size):
            assert p.to_bytes(k) == p.to_bytes(k % n_real)
    out, _ = unbucket(parts, batch.batch_size, batch.capacity)
    for i in range(batch.batch_size):
        assert out.to_bytes(i) == batch.to_bytes(i)


@pytest.mark.slow
def test_unbucket_grows_capacity_for_near_mtu_rows():
    # a 1500B packet + 10B tag must not be truncated on reassembly
    batch = rtp_header.build([b"x" * 1488], [1], [0], [7], [96], stream=[0])
    tx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, KEY, SALT)
    prot = tx.protect_rtp(batch)
    assert prot.length[0] == 1500 + 10
    assert prot.capacity >= 1510
    rx = SrtpStreamTable(capacity=1)
    rx.add_stream(0, KEY, SALT)
    dec, ok = rx.unprotect_rtp(prot)
    assert ok.all() and dec.to_bytes(0) == batch.to_bytes(0)


@pytest.mark.slow
def test_bucketed_srtp_roundtrip_mixed_sizes():
    tx = SrtpStreamTable(capacity=2)
    rx = SrtpStreamTable(capacity=2)
    for sid in (0, 1):
        tx.add_stream(sid, KEY, SALT)
        rx.add_stream(sid, KEY, SALT)
    pls = [bytes([i]) * 100 for i in range(6)] + [b"v" * 1100, b"w" * 1100]
    batch = rtp_header.build(pls, list(range(10, 18)), [0] * 8, [7, 8] * 4,
                             [96] * 8, stream=[0, 1] * 4)
    prot = tx.protect_rtp(batch)
    dec, ok = rx.unprotect_rtp(prot)
    assert ok.all()
    for i in range(8):
        assert dec.to_bytes(i) == batch.to_bytes(i)


@pytest.mark.slow
def test_bucketed_equals_wide_single_class():
    """Same keys, same packets: a mixed batch's small row must produce
    the exact bytes a homogeneous small batch produces."""
    def mk():
        t = SrtpStreamTable(capacity=1)
        t.add_stream(0, KEY, SALT)
        return t
    small = rtp_header.build([b"a" * 100], [5], [0], [7], [96], stream=[0])
    mixed = rtp_header.build([b"a" * 100, b"b" * 1100], [5, 6], [0, 0],
                             [7, 7], [96, 96], stream=[0, 0])
    lone = mk().protect_rtp(small)
    both = mk().protect_rtp(mixed)
    assert both.to_bytes(0) == lone.to_bytes(0)


@pytest.mark.slow
def test_padding_rows_do_not_advance_state():
    """Row counts that force padding (5 real rows -> 16) must leave
    tx/rx state exactly as an unpadded equivalent run."""
    tx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, KEY, SALT)
    batch = _mixed_batch(5, 0)
    tx.protect_rtp(batch)
    assert tx.tx_ext[0] == 5                 # seqs 1..5 -> max index 5
    rx = SrtpStreamTable(capacity=1)
    rx.add_stream(0, KEY, SALT)
    tx2 = SrtpStreamTable(capacity=1)
    tx2.add_stream(0, KEY, SALT)
    dec, ok = rx.unprotect_rtp(tx2.protect_rtp(batch))
    assert ok.all()
    assert rx.rx_max[0] == 5
    # replay mask counts only the 5 real packets
    assert bin(int(rx.rx_mask[0])).count("1") == 5


@pytest.mark.slow
def test_sfu_translator_index_passthrough_bucketed():
    """unprotect_rtp(return_index=True) merges per-bucket indices."""
    tx = SrtpStreamTable(capacity=1)
    rx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, KEY, SALT)
    rx.add_stream(0, KEY, SALT)
    pls = [b"s" * 90, b"L" * 1000, b"s" * 90]
    batch = rtp_header.build(pls, [40, 41, 42], [0] * 3, [7] * 3,
                             [96] * 3, stream=[0] * 3)
    prot = tx.protect_rtp(batch)
    dec, ok, idx = rx.unprotect_rtp(prot, return_index=True)
    assert ok.all()
    assert list(idx) == [40, 41, 42]


def test_tcc_mask_skips_state_for_masked_rows():
    eng = TransportCCEngine(ext_id=5)
    chain = TransformEngineChain([eng])
    batch = _mixed_batch(4, 0)
    mask = np.array([True, False, True, True])
    chain.rtp_transformer.transform(batch, mask)
    assert eng.next_seq_ext == 3                 # masked row consumed no seq


def test_empty_batch_protect_unprotect():
    from libjitsi_tpu.core.packet import PacketBatch
    t = SrtpStreamTable(capacity=1)
    t.add_stream(0, KEY, SALT)
    empty = PacketBatch.empty(0)
    out = t.protect_rtp(empty)
    assert out.batch_size == 0
    dec, ok = t.unprotect_rtp(empty)
    assert dec.batch_size == 0 and len(ok) == 0


@pytest.mark.slow
def test_class_exact_row_count_near_mtu():
    """Exactly ROW_CLASSES[0] near-MTU rows must still get headroom (the
    old direct-path shortcut bypassed the padded sub-batch and raised)."""
    tx = SrtpStreamTable(capacity=16)
    rx = SrtpStreamTable(capacity=16)
    for sid in range(16):
        tx.add_stream(sid, KEY, SALT)
        rx.add_stream(sid, KEY, SALT)
    pls = [bytes([i]) * 1488 for i in range(16)]
    batch = rtp_header.build(pls, list(range(16)), [0] * 16, [9] * 16,
                             [96] * 16, stream=list(range(16)))
    prot = tx.protect_rtp(batch)           # 1500+10 > 1504: needs headroom
    assert (np.asarray(prot.length) == 1510).all()
    dec, ok = rx.unprotect_rtp(prot)
    assert ok.all()
    for i in range(16):
        assert dec.to_bytes(i) == batch.to_bytes(i)


def test_row_class_bounded_beyond_table():
    """Row counts beyond the largest class round to multiples of it —
    distinct big batches must share compiled shapes."""
    from libjitsi_tpu.core.packet import ROW_CLASSES, _round_rows

    top = ROW_CLASSES[-1]
    assert _round_rows(top) == top
    assert _round_rows(top + 1) == 2 * top
    assert _round_rows(2 * top + 7) == 3 * top
    assert _round_rows(5 * top) == 5 * top
