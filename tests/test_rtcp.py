"""RTCP codec roundtrips + vectorized stats (jitter, loss, RTT).

Reference behaviors: RTCPPacketParserEx/RTCPREMBPacket/RTCPTCCPacket/
NACKPacket encode-decode; MediaStreamStatsImpl counters per RFC 3550.
"""

import numpy as np

from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.rtp.stats import StreamStatsTable, ntp_middle32


def test_sr_rr_roundtrip():
    rb = rtcp.ReportBlock(ssrc=7, fraction_lost=12, cumulative_lost=34,
                          highest_seq=70000, jitter=55, lsr=0xAABBCCDD,
                          dlsr=123)
    sr = rtcp.SenderReport(ssrc=1, ntp_sec=100, ntp_frac=200, rtp_ts=300,
                           packet_count=40, octet_count=50, reports=[rb])
    out = rtcp.parse_compound(rtcp.build_sr(sr))
    assert out == [sr]
    rr = rtcp.ReceiverReport(ssrc=2, reports=[rb])
    assert rtcp.parse_compound(rtcp.build_rr(rr)) == [rr]
    # negative cumulative lost survives (24-bit signed)
    rb2 = rtcp.ReportBlock(7, 0, -5, 100, 0, 0, 0)
    got = rtcp.parse_compound(
        rtcp.build_rr(rtcp.ReceiverReport(2, [rb2])))[0]
    assert got.reports[0].cumulative_lost == -5


def test_sdes_bye_compound():
    sd = [rtcp.SdesChunk(ssrc=9, items=[(1, b"user@host")])]
    bye = rtcp.Bye(ssrcs=[9], reason=b"leaving")
    blob = rtcp.build_compound([rtcp.build_sdes(sd), rtcp.build_bye(bye)])
    got = rtcp.parse_compound(blob)
    assert got[0] == sd and got[1] == bye


def test_nack_encode_decode():
    lost = [100, 101, 105, 116, 300]
    n = rtcp.Nack(sender_ssrc=1, media_ssrc=2, lost_seqs=lost)
    got = rtcp.parse_compound(rtcp.build_nack(n))[0]
    assert sorted(got.lost_seqs) == sorted(lost)
    assert (got.sender_ssrc, got.media_ssrc) == (1, 2)


def test_remb_roundtrip():
    r = rtcp.Remb(sender_ssrc=3, bitrate_bps=2_500_000, ssrcs=[10, 11])
    got = rtcp.parse_compound(rtcp.build_remb(r))[0]
    assert got.ssrcs == [10, 11]
    assert abs(got.bitrate_bps - 2_500_000) / 2_500_000 < 0.01  # mantissa rounding


def test_pli_fir():
    assert rtcp.parse_compound(rtcp.build_pli(rtcp.Pli(1, 2)))[0] == rtcp.Pli(1, 2)
    f = rtcp.Fir(1, 0, [(5, 9)])
    assert rtcp.parse_compound(rtcp.build_fir(f))[0] == f


def test_tcc_roundtrip():
    received = np.array([True, False, True, True, False, True, True])
    arrival = np.array([4, 0, 8, 1000, 0, 1004, 1010], dtype=np.int64)
    fb = rtcp.TccFeedback(sender_ssrc=1, media_ssrc=2, base_seq=65530,
                          reference_time=5, fb_pkt_count=3,
                          received=received, arrival_250us=arrival)
    got = rtcp.parse_compound(rtcp.build_tcc(fb))[0]
    assert got.base_seq == 65530 and got.reference_time == 5
    np.testing.assert_array_equal(got.received, received)
    np.testing.assert_array_equal(got.arrival_250us[received],
                                  arrival[received])
    assert got.seqs()[-1] == (65530 + 6) & 0xFFFF


def test_unknown_packet_skipped():
    weird = bytes([0x80, 195, 0, 1]) + b"\x00" * 4
    blob = weird + rtcp.build_pli(rtcp.Pli(1, 2))
    got = rtcp.parse_compound(blob)
    assert got == [rtcp.Pli(1, 2)]


# ------------------------------------------------------------------ stats --

def test_stats_loss_and_ext_seq():
    t = StreamStatsTable(capacity=4)
    # stream 0: seqs 65534..65537 wrapping, one gap (65536 missing)
    seqs = np.array([65534, 65535, 1])  # ext: 65534, 65535, 65537
    t.on_received(np.zeros(3, np.int64), seqs,
                  np.array([0, 160, 480]), np.array([100, 100, 100]),
                  arrival=np.zeros(3))
    assert t.expected(0) == 4
    assert t.cumulative_lost(0) == 1
    rb = t.make_report_block(0, remote_ssrc=9, now=0.0)
    assert rb.cumulative_lost == 1
    assert rb.fraction_lost == (1 << 8) // 4
    assert rb.highest_seq == 65537


def test_stats_jitter_ewma():
    t = StreamStatsTable(capacity=2)
    t.clock_rate[0] = 8000
    # packets 20 ms apart in RTP time but arriving 25 ms apart:
    # |D| = 0.005 s * 8000 = 40 units each step
    n = 10
    arrival = np.arange(n) * 0.025
    ts = np.arange(n) * 160
    t.on_received(np.zeros(n, np.int64), np.arange(n), ts,
                  np.full(n, 100), arrival)
    j = t.jitter[0]
    # EWMA converging toward 40: after 9 steps it's 40*(1-(15/16)^9)
    want = 40 * (1 - (15 / 16) ** (n - 1))
    assert abs(j - want) < 1e-6


def test_stats_rtt_from_rr():
    t = StreamStatsTable(capacity=1)
    now = 1000.0
    sr = t.make_sr(0, ssrc=1, rtp_ts=0, now=now)
    # remote echoes our SR after holding it 0.1 s; RR arrives 0.3 s later
    rb = rtcp.ReportBlock(ssrc=1, fraction_lost=0, cumulative_lost=0,
                          highest_seq=0, jitter=0,
                          lsr=ntp_middle32(now),
                          dlsr=int(0.1 * 65536))
    t.on_rr_received(0, rb, now=now + 0.3)
    assert abs(t.rtt[0] - 0.2) < 0.01


def test_stats_sr_contents():
    t = StreamStatsTable(capacity=1)
    t.on_sent(np.zeros(5, np.int64), np.full(5, 200))
    sr = t.make_sr(0, ssrc=42, rtp_ts=999, now=123.5)
    assert sr.packet_count == 5 and sr.octet_count == 1000
    assert sr.ntp_sec == 123 + 2208988800
    blob = rtcp.build_sr(sr)
    assert rtcp.parse_compound(blob)[0].rtp_ts == 999
