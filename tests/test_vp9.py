"""VP9 payload descriptor parse/build (draft-ietf-payload-vp9)."""

import numpy as np

from libjitsi_tpu.codecs import vp9
from libjitsi_tpu.rtp import header as rtp_header


def _pack(descs_payloads, seqs, ssrc=0x9999):
    payloads = [d + p for d, p in descs_payloads]
    return rtp_header.build(payloads, seqs, [0] * len(seqs),
                            [ssrc] * len(seqs), [98] * len(seqs),
                            stream=[0] * len(seqs))


def test_parse_minimal_and_picture_ids():
    batch = _pack([
        (vp9.build_descriptor(begin=True, inter_predicted=False,
                              picture_id=5), b"k" * 40),
        (vp9.build_descriptor(begin=False, picture_id=300), b"d" * 40),
        (vp9.build_descriptor(begin=True, end=True), b"x" * 40),
    ], [1, 2, 3])
    d = vp9.parse_descriptors(batch)
    assert d.valid.all()
    assert list(d.picture_id) == [5, 300, -1]
    assert list(d.is_keyframe) == [True, False, False]
    assert d.desc_len[0] == 2 and d.desc_len[1] == 3 and d.desc_len[2] == 1
    assert d.begin_frame[0] and not d.begin_frame[1]
    assert d.end_frame[2]


def test_parse_layers_and_flexible_pdiffs():
    batch = _pack([
        (vp9.build_descriptor(begin=True, picture_id=9, tid=2, sid=1,
                              tl0picidx=77), b"a" * 20),
        (vp9.build_descriptor(begin=True, picture_id=9, tid=1, sid=0,
                              flexible=True, pdiffs=[1, 4]), b"b" * 20),
    ], [10, 11])
    d = vp9.parse_descriptors(batch)
    assert d.valid.all()
    assert d.tid[0] == 2 and d.sid[0] == 1 and d.tl0picidx[0] == 77
    assert not d.flexible[0] and d.flexible[1]
    assert d.tid[1] == 1 and d.sid[1] == 0 and d.tl0picidx[1] == -1
    assert d.num_pdiff[1] == 2
    # keyframe requires SID 0 when layers present
    assert not d.is_keyframe[0]


def test_parse_scalability_structure_len():
    ss = [(640, 360), (1280, 720)]
    desc = vp9.build_descriptor(begin=True, inter_predicted=False,
                                picture_id=1, tid=0, sid=0, tl0picidx=0,
                                ss_sizes=ss)
    batch = _pack([(desc, b"kf" * 30)], [20])
    d = vp9.parse_descriptors(batch)
    assert d.valid.all() and d.has_ss[0] and d.is_keyframe[0]
    assert d.desc_len[0] == len(desc)


def test_frame_assembly():
    pid = 42
    batch = _pack([
        (vp9.build_descriptor(begin=True, picture_id=pid, tid=0, sid=0,
                              tl0picidx=1), b"AAA"),
        (vp9.build_descriptor(begin=False, picture_id=pid, tid=0, sid=0,
                              tl0picidx=1), b"BBB"),
        (vp9.build_descriptor(begin=False, end=True, picture_id=pid,
                              tid=0, sid=0, tl0picidx=1), b"CCC"),
    ], [30, 31, 32])
    d = vp9.parse_descriptors(batch)
    asm = vp9.Vp9FrameAssembler()
    outs = [asm.push(d, batch, r) for r in range(3)]
    assert outs[:2] == [None, None]
    assert outs[2] == b"AAABBBCCC"
    # mid-frame packet without a start is dropped
    asm2 = vp9.Vp9FrameAssembler()
    assert asm2.push(d, batch, 1) is None


def test_truncated_descriptor_invalid():
    # descriptor claims fields beyond the payload
    desc = vp9.build_descriptor(begin=True, picture_id=300, tid=1, sid=1,
                                tl0picidx=3)
    batch = _pack([(desc[:1], b"")], [40])
    d = vp9.parse_descriptors(batch)
    assert not d.valid[0]


def test_padding_excluded_and_ng_overflow_rejected():
    import numpy as np
    from libjitsi_tpu.core.packet import PacketBatch
    # padded end packet: P bit set, 3 pad bytes; payload must exclude them
    desc = vp9.build_descriptor(begin=True, end=True, picture_id=4,
                                tid=0, sid=0, tl0picidx=0)
    raw = bytearray(rtp_header.build([desc + b"PAYLOAD"], [50], [0], [9],
                                     [98], stream=[0]).to_bytes(0))
    raw[0] |= 0x20                                  # P bit
    raw += bytes([0, 0, 3])                         # 3 padding bytes
    batch = PacketBatch.from_payloads([bytes(raw)])
    batch.stream[:] = 0
    d = vp9.parse_descriptors(batch)
    assert d.valid[0]
    asm = vp9.Vp9FrameAssembler()
    assert asm.push(d, batch, 0) == b"PAYLOAD"
    # SS with N_G > supported entries: rejected, not mis-sized
    ssb = bytes([0b00000001 | (0 << 5) | (1 << 3)])  # N_S=1,Y=0,G=1
    big = bytes([0x0A | 0x02]) + ssb + bytes([200]) + bytes([0] * 250)
    b2 = rtp_header.build([big], [51], [0], [9], [98], stream=[0])
    d2 = vp9.parse_descriptors(b2)
    assert not d2.valid[0]


def test_flexible_builder_requires_pdiff_and_assembler_evicts():
    import pytest
    with pytest.raises(ValueError):
        vp9.build_descriptor(begin=True, flexible=True)
    # lost end packet: new begin on same sid evicts the stale partial
    mk = lambda pid, begin, end, pay: (vp9.build_descriptor(
        begin=begin, end=end, picture_id=pid, tid=0, sid=0,
        tl0picidx=0), pay)
    batch = _pack([mk(1, True, False, b"LOST"),
                   mk(2, True, False, b"NEW"),
                   mk(2, False, True, b"TAIL")], [60, 61, 62])
    d = vp9.parse_descriptors(batch)
    asm = vp9.Vp9FrameAssembler()
    assert asm.push(d, batch, 0) is None
    assert asm.push(d, batch, 1) is None
    assert asm.push(d, batch, 2) == b"NEWTAIL"
    assert asm._partial == {}                       # nothing leaked
