"""AEAD AES-GCM: GHASH/GCM kernel KATs, OpenSSL differentials, and the
AEAD_AES_128_GCM SRTP/SRTCP profile (RFC 7714) through SrtpStreamTable.
"""

import os

import numpy as np
import jax.numpy as jnp
import pytest
from cryptography.hazmat.primitives.ciphers.aead import AESGCM

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.kernels import gcm as G
from libjitsi_tpu.kernels.aes import aes_encrypt_np, expand_key
from libjitsi_tpu.kernels.ghash import ghash, ghash_matrix, ghash_ref, gf_mult
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable

MK = bytes(range(16))
MS = bytes(range(100, 112))  # 12-byte GCM salt


def _gm(key: bytes) -> np.ndarray:
    h = bytes(aes_encrypt_np(expand_key(key), np.zeros((1, 16), np.uint8))[0])
    return ghash_matrix(h).astype(np.int8)


# ------------------------------------------------------------------ GHASH --

def test_gf_mult_identity_and_commutes():
    one = 1 << 127  # the GCM field's multiplicative identity (b0 = 1)
    x = int.from_bytes(os.urandom(16), "big")
    y = int.from_bytes(os.urandom(16), "big")
    assert gf_mult(x, one) == x
    assert gf_mult(one, y) == y
    assert gf_mult(x, y) == gf_mult(y, x)


def test_ghash_matrix_matches_reference():
    h = os.urandom(16)
    data = os.urandom(96)
    m = ghash_matrix(h).astype(np.int8)
    got = ghash(jnp.asarray(np.broadcast_to(m, (1, 128, 128))),
                jnp.asarray(np.frombuffer(data, np.uint8)[None, :]),
                jnp.asarray(np.array([6], np.int32)), 6)
    assert bytes(np.asarray(got)[0]) == ghash_ref(h, data)


def test_ghash_row_lengths_independent():
    """Rows with fewer blocks take identity steps, not extra multiplies."""
    h = os.urandom(16)
    m = np.broadcast_to(ghash_matrix(h).astype(np.int8), (2, 128, 128))
    long = os.urandom(64)
    short = long[:32]
    buf = np.zeros((2, 64), np.uint8)
    buf[0] = np.frombuffer(long, np.uint8)
    buf[1, :32] = np.frombuffer(short, np.uint8)
    got = ghash(jnp.asarray(m), jnp.asarray(buf),
                jnp.asarray(np.array([4, 2], np.int32)), 4)
    assert bytes(np.asarray(got)[0]) == ghash_ref(h, long)
    assert bytes(np.asarray(got)[1]) == ghash_ref(h, short)


# ----------------------------------------------------------- GCM vs OpenSSL

def test_gcm_differential_vs_openssl_mixed_lengths():
    rng = np.random.default_rng(2)
    B, W = 6, 160
    keys = [os.urandom(16) for _ in range(B)]
    ivs = [os.urandom(12) for _ in range(B)]
    aad_lens = [12, 12, 16, 20, 12, 28]
    pt_lens = [40, 0, 33, 77, 1, 100]
    data = np.zeros((B, W), np.uint8)
    for i in range(B):
        blob = os.urandom(aad_lens[i] + pt_lens[i])
        data[i, :len(blob)] = np.frombuffer(blob, np.uint8)
    length = np.array([a + p for a, p in zip(aad_lens, pt_lens)], np.int32)
    aad_len = np.array(aad_lens, np.int32)
    rks = np.stack([expand_key(k) for k in keys])
    gms = np.stack([_gm(k) for k in keys])
    iv12 = np.stack([np.frombuffer(v, np.uint8) for v in ivs])

    out, outlen = G.gcm_protect(data, length, aad_len, jnp.asarray(rks),
                                jnp.asarray(gms), jnp.asarray(iv12))
    out, outlen = np.asarray(out), np.asarray(outlen)
    for i in range(B):
        aad = bytes(data[i, :aad_lens[i]])
        pt = bytes(data[i, aad_lens[i]:length[i]])
        want = AESGCM(keys[i]).encrypt(ivs[i], pt, aad)
        got = bytes(out[i, aad_lens[i]:length[i] + 16])
        assert got == want, f"row {i}"

    dec, mlen, ok = G.gcm_unprotect(out, outlen, aad_len, jnp.asarray(rks),
                                    jnp.asarray(gms), jnp.asarray(iv12))
    assert np.asarray(ok).all()
    dec = np.asarray(dec)
    for i in range(B):
        assert bytes(dec[i, :length[i]]) == bytes(data[i, :length[i]])

    # any flipped bit (aad, ct or tag) kills that row only
    for pos in (2, aad_lens[0] + 3, int(length[0]) + 5):
        bad = out.copy()
        bad[0, pos] ^= 1
        _, _, ok2 = G.gcm_unprotect(bad, outlen, aad_len, jnp.asarray(rks),
                                    jnp.asarray(gms), jnp.asarray(iv12))
        ok2 = np.asarray(ok2)
        assert not ok2[0] and ok2[1:].all()


# ------------------------------------------------------------ SRTP profile

def make_gcm_table(n=4):
    t = SrtpStreamTable(capacity=n, profile=SrtpProfile.AEAD_AES_128_GCM)
    for i in range(n):
        t.add_stream(i, MK, MS)
    return t


def _rtp_batch(seqs, ssrc=0x4242, stream=0):
    return rtp_header.build([b"gcm-payload-%02d" % s for s in seqs],
                            list(seqs), [0] * len(seqs), [ssrc] * len(seqs),
                            [96] * len(seqs), stream=[stream] * len(seqs))


def test_srtp_gcm_rfc7714_vector():
    """RFC 7714 §16.1.1 AEAD_AES_128_GCM SRTP protection known answer."""
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    salt = bytes.fromhex("517569642070726f2071756f")
    pkt = bytes.fromhex(
        "8040f17b8041f8d35501a0b247616c6c"
        "696120657374206f6d6e697320646976"
        "69736120696e207061727465732074726573")
    roc = 0
    # direct kernel path with the RFC's session key/iv construction:
    # RFC 7714 uses the master key directly as session key in the example
    iv = bytearray(salt)
    ssrc = int.from_bytes(pkt[8:12], "big")
    seq = int.from_bytes(pkt[2:4], "big")
    for k in range(4):
        iv[2 + k] ^= (ssrc >> (8 * (3 - k))) & 0xFF
    idx = (roc << 16) | seq
    for k in range(6):
        iv[6 + k] ^= (idx >> (8 * (5 - k))) & 0xFF
    data = np.zeros((1, 128), np.uint8)
    data[0, :len(pkt)] = np.frombuffer(pkt, np.uint8)
    out, outlen = G.gcm_protect(
        data, np.array([len(pkt)], np.int32), np.array([12], np.int32),
        jnp.asarray(expand_key(key)[None]), jnp.asarray(_gm(key)[None]),
        jnp.asarray(np.frombuffer(bytes(iv), np.uint8)[None]))
    got = bytes(np.asarray(out)[0, :int(np.asarray(outlen)[0])])
    want = bytes.fromhex(
        "8040f17b8041f8d35501a0b2f24de3a3"
        "fb34de6cacba861c9d7e4bcabe633bd5"
        "0d294e6f42a5f47a51c7d19b36de3adf"
        "8833899d7f27beb16a9152cf765ee439"
        "0cce")
    assert got == want


def test_srtp_gcm_table_roundtrip():
    tx, rx = make_gcm_table(), make_gcm_table()
    b = _rtp_batch(range(100, 108))
    wire = tx.protect_rtp(b)
    assert np.all(np.asarray(wire.length) == np.asarray(b.length) + 16)
    dec, ok = rx.unprotect_rtp(wire)
    assert ok.all()
    for i in range(8):
        assert dec.to_bytes(i) == b.to_bytes(i)
    # replay rejected
    _, ok2 = rx.unprotect_rtp(wire)
    assert not ok2.any()
    # tamper rejected
    bad = tx.protect_rtp(_rtp_batch([200])).copy()
    bad.data[0, 20] ^= 1
    _, ok3 = rx.unprotect_rtp(bad)
    assert not ok3.any()


def test_srtp_gcm_seq_wrap_roc():
    tx, rx = make_gcm_table(), make_gcm_table()
    seqs = [65534, 65535, 0, 1]
    b = rtp_header.build([b"w%d" % s for s in seqs], seqs, [0] * 4,
                         [0x99] * 4, [96] * 4, stream=[0] * 4)
    dec, ok = rx.unprotect_rtp(tx.protect_rtp(b))
    assert ok.all()
    assert rx.rx_max[0] == (1 << 16) + 1


def test_srtcp_gcm_roundtrip():
    tx, rx = make_gcm_table(), make_gcm_table()
    from libjitsi_tpu.rtp import rtcp
    sr = rtcp.build_sr(rtcp.SenderReport(0x77, 1, 2, 3, 4, 5, []))
    b = PacketBatch.from_payloads([sr, sr], stream=[0, 1])
    wire = tx.protect_rtcp(b)
    assert np.all(np.asarray(wire.length) == len(sr) + 16 + 4)
    dec, ok = rx.unprotect_rtcp(wire)
    assert ok.all()
    assert dec.to_bytes(0) == sr and dec.to_bytes(1) == sr
    # replay
    _, ok2 = rx.unprotect_rtcp(wire)
    assert not ok2.any()


def test_gcm_snapshot_restore():
    tx = make_gcm_table()
    rx = make_gcm_table()
    wire = tx.protect_rtp(_rtp_batch([5]))
    rx.unprotect_rtp(wire)
    rx2 = SrtpStreamTable.restore(rx.snapshot())
    # replay still rejected after restore; next packet accepted
    _, ok = rx2.unprotect_rtp(wire)
    assert not ok.any()
    dec, ok2 = rx2.unprotect_rtp(tx.protect_rtp(_rtp_batch([6])))
    assert ok2.all()


def test_gcm_grouped_table_path_matches_per_row():
    """VERDICT r2 #7: the grouped-GHASH table path (one matrix read per
    stream per launch) must be bit-identical to the per-row path on a
    mixed-stream batch, and round-trip through a grouped unprotect.
    Paths are pinned via the kernels registry (the measured-choice
    mechanism, VERDICT r3 #6), not a batch-size constant."""
    from libjitsi_tpu.kernels import registry
    from libjitsi_tpu.transform.srtp import context as ctx_mod

    n_streams, per = 8, 40                 # 320 rows >= grouping floor
    rng = np.random.default_rng(5)
    streams = np.repeat(np.arange(n_streams), per)
    rng.shuffle(streams)
    seqs = np.zeros(len(streams), np.int64)
    for s in range(n_streams):
        rows = np.nonzero(streams == s)[0]
        seqs[rows] = 100 + np.arange(len(rows))
    pls = [bytes(rng.integers(0, 256, int(rng.integers(8, 60)),
                              dtype=np.uint8).tobytes())
           for _ in streams]
    b = rtp_header.build(pls, list(seqs), [0] * len(streams),
                         [0x1000 + int(s) for s in streams],
                         [96] * len(streams), stream=list(streams))

    grid = ctx_mod._gcm_grid(np.asarray(streams, np.int64))
    assert grid is not None, "uniform batch must form a grouped grid"

    try:
        registry.force("gcm_rtp_protect", "grouped")
        tx_g = make_gcm_table(n_streams)
        wire_g = tx_g.protect_rtp(b)
        registry.force("gcm_rtp_protect", "per_row")
        tx_r = make_gcm_table(n_streams)
        wire_r = tx_r.protect_rtp(b)
        assert np.asarray(wire_g.length).tolist() == \
            np.asarray(wire_r.length).tolist()
        for i in range(wire_g.batch_size):
            assert wire_g.to_bytes(i) == wire_r.to_bytes(i), i
        # grouped unprotect round-trips
        registry.force("gcm_rtp_unprotect", "grouped")
        rx = make_gcm_table(n_streams)
        dec, ok = rx.unprotect_rtp(wire_g)
        assert ok.all()
        for i in range(b.batch_size):
            assert dec.to_bytes(i) == b.to_bytes(i), i
    finally:
        registry.force("gcm_rtp_protect", None)
        registry.force("gcm_rtp_unprotect", None)


def test_gcm_grid_skew_falls_back():
    from libjitsi_tpu.transform.srtp import context as ctx_mod

    # one hot stream dominating: padded grid would exceed 2x the batch
    streams = np.concatenate([np.zeros(500, np.int64),
                              np.arange(1, 40, dtype=np.int64)])
    assert ctx_mod._gcm_grid(streams) is None
    # all-distinct-streams batches skip the grid (grouped ≡ per-row
    # there); beyond these structural floors the grouped/per-row choice
    # is the registry's measured pick, not a size constant
    assert ctx_mod._gcm_grid(np.arange(8, dtype=np.int64)) is None
    assert ctx_mod._gcm_grid(
        np.repeat(np.arange(4, dtype=np.int64), 4)) is not None
