"""Key-derivation-rate re-keying (RFC 3711 §4.3; reference:
BaseSRTPCryptoContext.keyDerivationRate): session keys re-derive every
2^n packets, batches spanning an epoch boundary split, tx/rx agree."""

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpStreamTable
from libjitsi_tpu.transform.srtp.kdf import derive_session_keys
import pytest

MK = bytes(range(16))
MS = bytes(range(50, 64))
KDR = 16            # re-derive every 16 packets


def _oracle_kdr(pkt: bytes, index: int) -> bytes:
    """Scalar oracle with per-index key epoch: re-derive the session keys
    for epoch index//KDR, then protect with plain RFC 3711."""
    ks = derive_session_keys(MK, MS, kdr=KDR, index=index)
    # protect_oracle derives its own keys from a master; here we emulate
    # by building a one-packet table seeded at the right epoch instead.
    t = SrtpStreamTable(capacity=1)
    t._install_session_keys(0, ks)
    t.active[0] = True
    t.tx_ext[0] = index - 1 if index > 0 else -1
    b = PacketBatch.from_payloads([pkt], stream=[0])
    return t.protect_rtp(b).to_bytes(0)


def _pkt(seq, payload=b"kdrpayload" * 8):
    return rtp_header.build([payload], [seq], [0], [9], [96],
                            stream=[0]).to_bytes(0)


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_kdr_rekeys_across_epochs_single_packets():
    tx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, MK, MS, kdr=KDR)
    outs = []
    for seq in range(40):                  # epochs 0,1,2
        b = PacketBatch.from_payloads([_pkt(seq)], stream=[0])
        outs.append(tx.protect_rtp(b).to_bytes(0))
    for seq in (0, 15, 16, 17, 31, 32, 39):
        assert outs[seq] == _oracle_kdr(_pkt(seq), seq), f"seq {seq}"
    # epochs actually produce different keys
    keys_epoch0 = derive_session_keys(MK, MS, kdr=KDR, index=15)
    keys_epoch1 = derive_session_keys(MK, MS, kdr=KDR, index=16)
    assert keys_epoch0.rtp_enc != keys_epoch1.rtp_enc


def test_kdr_batch_spanning_epoch_boundary():
    tx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, MK, MS, kdr=KDR)
    pkts = [_pkt(s) for s in range(12, 20)]       # spans 15|16 boundary
    batch = PacketBatch.from_payloads(pkts, stream=[0] * 8)
    out = tx.protect_rtp(batch)
    for i, s in enumerate(range(12, 20)):
        assert out.to_bytes(i) == _oracle_kdr(pkts[i], s), f"seq {s}"
    assert tx._epoch_rtp[0] == 1


def test_kdr_roundtrip_tx_rx():
    tx = SrtpStreamTable(capacity=1)
    rx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, MK, MS, kdr=KDR)
    rx.add_stream(0, MK, MS, kdr=KDR)
    highest = -1
    for start in (0, 8, 14, 16, 30):              # batches cross epochs
        pkts = [_pkt(s) for s in range(start, start + 4)]
        batch = PacketBatch.from_payloads(pkts, stream=[0] * 4)
        prot = tx.protect_rtp(batch)
        dec, ok = rx.unprotect_rtp(prot)
        for i, s in enumerate(range(start, start + 4)):
            if s > highest:                        # fresh index MUST pass
                assert ok[i], f"fresh seq {s} failed auth"
                assert dec.to_bytes(i) == pkts[i]
            else:                                  # replayed: MUST drop
                assert not ok[i], f"replayed seq {s} accepted"
        highest = max(highest, start + 3)
    assert rx._epoch_rtp[0] >= 1


def test_kdr_zero_streams_unaffected():
    tx = SrtpStreamTable(capacity=2)
    tx.add_stream(0, MK, MS, kdr=KDR)
    tx.add_stream(1, MK, MS)                       # kdr=0
    pkts = [_pkt(20), _pkt(20)]
    batch = rtp_header.build([b"x" * 50, b"x" * 50], [20, 20], [0, 0],
                             [9, 9], [96, 96], stream=[0, 1])
    out = tx.protect_rtp(batch)                    # no crash, both protect
    assert out.length[0] == out.length[1]
    assert tx._epoch_rtp[1] == 0


def test_kdr_snapshot_restore():
    tx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, MK, MS, kdr=KDR)
    b = PacketBatch.from_payloads([_pkt(17)], stream=[0])
    tx.protect_rtp(b)                              # epoch 1 installed
    t2 = SrtpStreamTable.restore(tx.snapshot())
    assert t2._epoch_rtp[0] == 1 and t2.kdr[0] == KDR
    p18 = PacketBatch.from_payloads([_pkt(18)], stream=[0])
    a = tx.protect_rtp(p18).to_bytes(0)
    p18b = PacketBatch.from_payloads([_pkt(18)], stream=[0])
    assert t2.protect_rtp(p18b).to_bytes(0) == a
    # restored table can still cross the NEXT epoch (masters survived)
    p40 = PacketBatch.from_payloads([_pkt(40)], stream=[0])
    assert t2.protect_rtp(p40).to_bytes(0) == _oracle_kdr(_pkt(40), 40)


@pytest.mark.slow
def test_kdr_one_every_packet_epoch_no_recursion():
    """kdr=1 (re-key EVERY packet, RFC-legal) over a large batch: the
    wave loop must handle one epoch per row without recursion blowup."""
    tx = SrtpStreamTable(capacity=1)
    rx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, MK, MS, kdr=1)
    rx.add_stream(0, MK, MS, kdr=1)
    n = 64
    pkts = [_pkt(s, payload=bytes([s]) * 40) for s in range(n)]
    batch = PacketBatch.from_payloads(pkts, stream=[0] * n)
    prot = tx.protect_rtp(batch)
    dec, ok = rx.unprotect_rtp(prot)
    assert ok.all()
    for i in range(n):
        assert dec.to_bytes(i) == pkts[i]
    assert tx._epoch_rtp[0] == n - 1


def test_kdr_unmapped_rows_do_not_fragment_batches():
    """stream=-1 rows (unknown SSRC junk) must ride wave 0 and not force
    epoch splits (they are dropped by validity, not by key epoch)."""
    tx = SrtpStreamTable(capacity=1)
    rx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, MK, MS, kdr=KDR)
    rx.add_stream(0, MK, MS, kdr=KDR)
    pkts = [_pkt(s) for s in (3, 4)]
    batch = PacketBatch.from_payloads(pkts, stream=[0, 0])
    prot = tx.protect_rtp(batch)
    junk = rtp_header.build([b"j" * 50], [40000], [0], [0xBAD], [96],
                            stream=[-1])
    mixed = PacketBatch.from_payloads(
        [prot.to_bytes(0), junk.to_bytes(0), prot.to_bytes(1)],
        stream=[0, -1, 0])
    waves, _ = rx._epoch_plan(np.asarray(mixed.stream, np.int64),
                              rx._estimate_rx_indices(
                                  np.asarray(mixed.stream, np.int64),
                                  rtp_header.parse(mixed).seq),
                              rtcp=False)
    assert waves is None                 # single wave despite junk row
    dec, ok = rx.unprotect_rtp(mixed)
    assert list(ok) == [True, False, True]
