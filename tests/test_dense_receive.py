"""Dense receive plane vs scalar references.

The 10k-stream decode path must not run per-stream Python state
machines; `DenseJitterBank` and `BatchedRemoteBitrateEstimator` replay
the exact laws of the scalar `JitterBuffer` / GCC classes as array
programs.  These tests drive both on identical random traces and demand
agreement (bit-exact for the jitter bank's integer state; float-rounding
tolerance for the Kalman/AIMD chain).
"""

import numpy as np
import pytest

from libjitsi_tpu.bwe.batched import (SIG_NORMAL, SIG_OVERUSING,
                                      SIG_UNDERUSING,
                                      BatchedRemoteBitrateEstimator)
from libjitsi_tpu.bwe.overuse import NORMAL, OVERUSING, UNDERUSING
from libjitsi_tpu.bwe.remote_estimator import RemoteBitrateEstimator
from libjitsi_tpu.rtp.dense_jitter import DenseJitterBank
from libjitsi_tpu.rtp.jitter_buffer import JitterBuffer

_SIG = {NORMAL: SIG_NORMAL, OVERUSING: SIG_OVERUSING,
        UNDERUSING: SIG_UNDERUSING}


def _trace(rng, n=120, clock=8000, frame=160):
    """A jittery, lossy, reordering packet trace for one stream."""
    base = int(rng.integers(0, 60000))
    rows = []
    t = 10.0
    for i in range(n):
        if rng.random() < 0.08:
            continue                      # loss
        jitter = float(rng.random()) * 0.03
        rows.append((base + i, i * frame, t + i * 0.020 + jitter))
    # windowed reorder
    for _ in range(len(rows) // 4):
        a = int(rng.integers(0, len(rows)))
        b = min(len(rows) - 1, a + int(rng.integers(0, 3)))
        rows[a], rows[b] = rows[b], rows[a]
    rows.sort(key=lambda r: r[2])
    return rows


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_dense_jitter_matches_scalar(seed):
    rng = np.random.default_rng(seed)
    n_streams = 7
    traces = {s: _trace(rng) for s in range(n_streams)}
    scalars = {s: JitterBuffer(clock_rate=8000, frame_ms=20.0)
               for s in range(n_streams)}
    bank = DenseJitterBank(capacity=n_streams, depth=64, payload_cap=64,
                           clock_rate=8000, frame_ms=20.0)

    # interleave the traces into tick-aligned arrival batches
    events = []
    for s, rows in traces.items():
        for seq, ts, at in rows:
            events.append((at, s, seq, ts))
    events.sort()
    t0 = 10.0
    ei = 0
    for tick in range(160):
        now = t0 + tick * 0.020
        batch = []
        while ei < len(events) and events[ei][0] <= now:
            batch.append(events[ei])
            ei += 1
        if batch:
            sids = np.array([b[1] for b in batch])
            seqs = np.array([b[2] for b in batch])
            tss = np.array([b[3] for b in batch])
            ats = np.array([b[0] for b in batch])
            pay = np.zeros((len(batch), 8), np.uint8)
            pay[:, 0] = seqs & 0xFF
            pay[:, 1] = sids
            bank.insert_batch(sids, seqs, tss, pay, [8] * len(batch),
                              ats)
            for at, s, seq, ts in batch:
                scalars[s].insert(seq & 0xFFFF, ts,
                                  bytes([seq & 0xFF, s] + [0] * 6), at)
        ready, pays, lens = bank.pop_all(now)
        for s in range(n_streams):
            want = scalars[s].pop(now)
            if want is None:
                assert not ready[s], (tick, s)
            else:
                assert ready[s], (tick, s)
                assert pays[s, :lens[s]].tobytes() == want

    for s in range(n_streams):
        assert bank.lost[s] == scalars[s].lost, s
        assert bank.late_dropped[s] == scalars[s].late_dropped, s
        assert bank.jitter_s[s] == pytest.approx(scalars[s]._jitter_s,
                                                 abs=1e-12)


def test_dense_jitter_ten_k_streams_single_tick_is_loop_free():
    """10k streams, one insert batch + one pop tick: must complete fast
    (vector ops only) and release every due frame."""
    import time

    s = 10_000
    bank = DenseJitterBank(capacity=s, depth=16, payload_cap=64)
    sids = np.arange(s)
    pay = np.zeros((s, 64), np.uint8)
    t0 = time.perf_counter()
    bank.insert_batch(sids, np.full(s, 100), np.zeros(s), pay,
                      np.full(s, 64), 5.0)
    ready, _, _ = bank.pop_all(5.1)
    host_ms = (time.perf_counter() - t0) * 1e3
    assert ready.all()
    # generous bound: a per-stream Python loop at 10k streams costs
    # hundreds of ms; the vector path is ~a few ms
    assert host_ms < 200, f"dense tick took {host_ms:.1f} ms"


@pytest.mark.parametrize("seed", [0, 1])
def test_batched_bwe_matches_scalar(seed):
    rng = np.random.default_rng(seed)
    n_tr = 5
    scalars = [RemoteBitrateEstimator() for _ in range(n_tr)]
    bank = BatchedRemoteBitrateEstimator(capacity=n_tr)

    now = 1000.0
    for step in range(400):
        tids, arrivals, asts, sizes = [], [], [], []
        for tr in range(n_tr):
            # per-transport congestion character: growing queues on some
            n_pkts = int(rng.integers(0, 4))
            for _ in range(n_pkts):
                send_s = now / 1000.0 + float(rng.random()) * 0.004
                queue = (step * 0.0005 * (tr % 3)
                         + float(rng.random()) * 0.002)
                arr = now + queue * 1000.0 + float(rng.random())
                ast = int(send_s * (1 << 18)) & 0xFFFFFF
                size = int(rng.integers(200, 1200))
                tids.append(tr)
                arrivals.append(arr)
                asts.append(ast)
                sizes.append(size)
        if tids:
            bank.incoming_batch(tids, arrivals, asts, sizes)
            for tr, a, s_, z in zip(tids, arrivals, asts, sizes):
                scalars[tr].incoming_packet(a, s_, z)
        if step % 10 == 9:
            rates = bank.update_estimate(now)
            for tr in range(n_tr):
                want = scalars[tr].update_estimate(now)
                assert rates[tr] == pytest.approx(want, rel=1e-9), \
                    (step, tr)
                assert bank.signal[tr] == _SIG[scalars[tr].state], \
                    (step, tr)
        now += 20.0

    for tr in range(n_tr):
        assert bank.offset[tr] == pytest.approx(
            scalars[tr]._est.offset, rel=1e-9, abs=1e-12), tr
        assert bank.threshold[tr] == pytest.approx(
            scalars[tr]._det.threshold, rel=1e-9), tr


def test_batched_bwe_ten_k_transports_tick():
    import time

    t = 10_000
    bank = BatchedRemoteBitrateEstimator(capacity=t)
    rng = np.random.default_rng(0)
    tids = np.arange(t)
    now = 1000.0
    t0 = time.perf_counter()
    for step in range(3):
        ast = ((now / 1000.0 + step * 0.006) * (1 << 18))
        bank.incoming_batch(tids, np.full(t, now + step),
                            np.full(t, int(ast) & 0xFFFFFF),
                            np.full(t, 900))
        now += 20.0
    bank.update_estimate(now)
    host_ms = (time.perf_counter() - t0) * 1e3
    assert host_ms < 500, f"bwe tick took {host_ms:.1f} ms"


def test_receive_bank_g711_and_stateful_mix_deposit():
    """ReceiveBank: batched insert from a decrypted batch, per-tick
    decode (vectorized G.711 + stateful GSM), mixer deposit."""
    from libjitsi_tpu.conference.mixer import AudioMixer
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.service.pump import (ReceiveBank, g711_codec,
                                           gsm_codec)

    mixer = AudioMixer(capacity=8, frame_samples=160)
    bank = ReceiveBank(capacity=8, mixer=mixer, payload_cap=256)
    bank.add_stream(0, g711_codec())          # PCMU
    bank.add_stream(1, g711_codec(ulaw=False))  # PCMA
    gsm = gsm_codec()
    bank.add_stream(2, gsm_codec())
    for s in range(3):
        mixer.add_participant(s)

    rng = np.random.default_rng(4)
    pcm = rng.integers(-3000, 3000, (3, 160)).astype(np.int16)
    payloads = [g711_codec().encode(pcm[0]),
                g711_codec(ulaw=False).encode(pcm[1]),
                gsm.encode(pcm[2])]
    batch = rtp_header.build(payloads, [100, 200, 300], [0, 0, 0],
                             [0xA, 0xB, 0xC], [0, 8, 3],
                             stream=[0, 1, 2])
    n = bank.push_decrypted(batch, np.ones(3, bool), now=50.0)
    assert n == 3
    sids, frames = bank.tick(now=50.1)
    assert sorted(sids) == [0, 1, 2]
    # G.711 decode must match the scalar codec decode bit-exactly
    by_sid = dict(zip(sids, frames))
    assert np.array_equal(by_sid[0],
                          g711_codec().decode(payloads[0]))
    assert np.array_equal(by_sid[1],
                          g711_codec(ulaw=False).decode(payloads[1]))
    assert np.array_equal(by_sid[2], gsm_codec().decode(payloads[2]))
    # mixer rows carry the deposits
    out, levels = mixer.mix()
    total = np.stack(frames).astype(np.int64).sum(axis=0)
    want0 = np.clip(total - by_sid[0].astype(np.int64), -32768, 32767)
    assert np.array_equal(out[0].astype(np.int64), want0)

    # next tick with nothing buffered: loss counted, no frames
    sids2, frames2 = bank.tick(now=50.2)
    assert sids2 == []
    assert bank.lost_frames[:3].tolist() == [1, 1, 1]


def test_receive_bank_review_hardening():
    """Pin the review fixes: forged ext-header intake, mixed G.711
    ptimes, sid recycling, loud mixer frame mismatch."""
    from libjitsi_tpu.conference.mixer import AudioMixer
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.service.pump import ReceiveBank, g711_codec

    # (4) mixer frame mismatch is rejected at config time
    mixer = AudioMixer(capacity=4, frame_samples=960)
    bank_bad = ReceiveBank(capacity=4, mixer=mixer)
    with pytest.raises(ValueError):
        bank_bad.add_stream(0, g711_codec())      # 160 != 960

    bank = ReceiveBank(capacity=8, payload_cap=512)
    bank.add_stream(0, g711_codec(ptime_ms=20))
    bank.add_stream(1, g711_codec(ptime_ms=30))   # same kind, 240 samp

    # (1) forged extension header: X=1 with lying ext_words must be
    # filtered, not crash the batch intake
    rng = np.random.default_rng(9)
    good0 = g711_codec(ptime_ms=20).encode(
        rng.integers(-2000, 2000, 160).astype(np.int16))
    good1 = g711_codec(ptime_ms=30).encode(
        rng.integers(-2000, 2000, 240).astype(np.int16))
    batch = rtp_header.build([good0, good1, b"x"],
                             [5, 6, 7], [0, 0, 0], [1, 2, 3],
                             [0, 0, 0], stream=[0, 1, 0])
    batch.data[2, 0] |= 0x10                      # X bit, tiny packet
    batch.data[2, 12:16] = (0xBE, 0xDE, 0x7F, 0xFF)
    n = bank.push_decrypted(batch, np.ones(3, bool), now=50.0)
    assert n == 2                                 # forged row filtered

    # (2) mixed ptimes decode at their own widths
    sids, frames = bank.tick(now=50.1)
    by = dict(zip(sids, frames))
    assert len(by[0]) == 160 and len(by[1]) == 240

    # (3) recycling a sid resets the jitter window: a fresh random seq
    # far below the old one must not be late-dropped
    bank.remove_stream(0)
    bank.add_stream(0, g711_codec(ptime_ms=20))
    b2 = rtp_header.build([good0], [40000], [0], [9], [0], stream=[0])
    assert bank.push_decrypted(b2, np.ones(1, bool), now=51.0) == 1
    sids2, _ = bank.tick(now=51.05)
    assert 0 in sids2
    assert bank.jb.late_dropped[0] == 0


def test_dense_jitter_large_seq_jump_catches_up_in_one_tick():
    """A sender restart that jumps seq by ~1000 must not stall for
    depth-bounded ticks: the gap skips in one pop (like the scalar
    recursion), counting the whole gap lost."""
    bank = DenseJitterBank(capacity=2, depth=16, payload_cap=32,
                           clock_rate=8000, frame_ms=20.0)
    sc = JitterBuffer(clock_rate=8000, frame_ms=20.0)
    pay = np.zeros((1, 8), np.uint8)
    bank.insert_batch([0], [100], [0], pay, [8], 5.0)
    sc.insert(100, 0, bytes(8), 5.0)
    assert bank.pop_all(5.0)[0][0] and sc.pop(5.0) is not None
    # jump: next packet at seq 1100
    bank.insert_batch([0], [1100], [160], pay, [8], 5.02)
    sc.insert(1100, 160, bytes(8), 5.02)
    # after the wait law expires, one tick releases the new packet
    ready, _, _ = bank.pop_all(5.5)
    want = sc.pop(5.5)
    assert ready[0] and want is not None
    assert bank.lost[0] == sc.lost == 999


def test_receive_bank_drops_oversize_frames_not_truncates():
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.service.pump import ReceiveBank, gsm_codec

    bank = ReceiveBank(capacity=2, payload_cap=64)
    bank.add_stream(0, gsm_codec())
    big = bytes(100)                          # > payload_cap
    b = rtp_header.build([big], [5], [0], [1], [3], stream=[0])
    assert bank.push_decrypted(b, np.ones(1, bool), now=50.0) == 0
    assert bank.oversize_dropped[0] == 1
    assert bank.decode_errors[0] == 0


def test_dense_jitter_snapshot_resume_equals_uninterrupted():
    """Checkpoint mid-stream: the restored bank must behave exactly
    like the uninterrupted one for the rest of the trace."""
    a = DenseJitterBank(capacity=3, depth=16, payload_cap=32,
                        clock_rate=8000, frame_ms=20.0)
    pay = np.zeros((3, 16), np.uint8)
    for k in range(6):
        a.insert_batch([0, 1, 2], [50 + k] * 3, [160 * k] * 3,
                       pay + k, [16] * 3, 5.0 + 0.02 * k)
        a.pop_all(5.0 + 0.02 * k + 0.001)
    b = DenseJitterBank.restore(a.snapshot())
    for k in range(6, 12):
        now = 5.0 + 0.02 * k
        for bank in (a, b):
            bank.insert_batch([0, 1, 2], [50 + k] * 3, [160 * k] * 3,
                              pay + k, [16] * 3, now)
        ra, pa, la = a.pop_all(now + 0.001)
        rb, pb, lb = b.pop_all(now + 0.001)
        assert np.array_equal(ra, rb)
        assert np.array_equal(pa, pb) and np.array_equal(la, lb)
    assert np.array_equal(a.lost, b.lost)
    assert np.array_equal(a.jitter_s, b.jitter_s)


def test_batched_bwe_snapshot_resume_equals_uninterrupted():
    a = BatchedRemoteBitrateEstimator(capacity=3)

    def feed(est, step, now):
        ast = int((now / 1000.0 + step * 0.006) * (1 << 18)) & 0xFFFFFF
        est.incoming_batch([0, 1, 2], [now + step] * 3, [ast] * 3,
                           [900] * 3)

    now = 1000.0
    for step in range(50):
        feed(a, step, now)
        now += 20.0
    b = BatchedRemoteBitrateEstimator.restore(a.snapshot())
    for step in range(50, 100):
        feed(a, step, now)
        feed(b, step, now)
        ra = a.update_estimate(now)
        rb = b.update_estimate(now)
        assert np.array_equal(ra, rb), step
        now += 20.0
    assert np.array_equal(a.offset, b.offset)
    assert np.array_equal(a.threshold, b.threshold)
