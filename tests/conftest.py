"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; per the build contract the
sharded paths are validated on a virtual CPU mesh
(`--xla_force_host_platform_device_count=8`).  Must run before jax imports.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
