"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; per the build contract the
sharded paths are validated on a virtual CPU mesh
(`--xla_force_host_platform_device_count=8`).

Note: this environment's sitecustomize imports jax at interpreter start
with JAX_PLATFORMS=axon (the TPU tunnel), so mutating os.environ here is
too late for the platform choice — use jax.config.update, which still
takes effect because no backend has been initialized before conftest runs.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache (same one bench.py/__graft_entry__ use):
# the suite is dominated by CPU XLA compiles; caching them on disk makes
# re-runs start warm.
from libjitsi_tpu.utils.compile_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()
