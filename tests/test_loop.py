"""Host I/O loop over real loopback sockets: a mini SFU bridge tick.

Exercises the production wiring end to end: client protects RTP ->
UDP -> bridge MediaLoop (recvmmsg batch, SSRC demux, address latching,
batched SRTP reverse chain) -> echo sink -> forward chain -> UDP ->
client decrypts.  Also covers rtcp-mux and DTLS first-byte splitting.
"""

import numpy as np

import libjitsi_tpu
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.io.loop import MediaLoop
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.service.media_stream import StreamRegistry
from libjitsi_tpu.transform import SrtpTransformEngine, TransformEngineChain
from libjitsi_tpu.transform.srtp import SrtpStreamTable
import pytest

MK, MS = bytes(range(16)), bytes(range(30, 44))
MK2, MS2 = bytes(range(60, 76)), bytes(range(80, 94))


def _registry():
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    return StreamRegistry(libjitsi_tpu.configuration_service(), capacity=16)


@pytest.mark.slow
def test_bridge_echo_over_udp():
    reg = _registry()
    # bridge rx context (client->bridge key), tx context (bridge->client)
    rx_tab = SrtpStreamTable(capacity=16)
    rx_tab.add_stream(3, MK, MS)
    tx_tab = SrtpStreamTable(capacity=16)
    tx_tab.add_stream(3, MK2, MS2)
    chain = TransformEngineChain([SrtpTransformEngine(tx_tab, rx_tab)])

    got_media = []

    def on_media(batch, ok):
        got_media.append(int(ok.sum()))
        rows = np.nonzero(ok)[0]
        if len(rows) == 0:
            return None
        return PacketBatch(batch.data[rows],
                           np.asarray(batch.length)[rows],
                           batch.stream[rows])  # echo back

    rtcp_seen = []
    bridge = MediaLoop(UdpEngine(port=0, max_batch=64), reg,
                       on_media=on_media,
                       on_rtcp=lambda b, ok: rtcp_seen.append(b.batch_size),
                       chain=chain)
    reg.map_ssrc(0xC11E27, 3)

    # client: protect 8 packets and send them to the bridge
    c_tx = SrtpStreamTable(capacity=1)
    c_tx.add_stream(0, MK, MS)
    c_rx = SrtpStreamTable(capacity=1)
    c_rx.add_stream(0, MK2, MS2)
    payloads = [b"frame-%02d" % i for i in range(8)]
    b = rtp_header.build(payloads, list(range(8)), [0] * 8,
                         [0xC11E27] * 8, [96] * 8, stream=[0] * 8)
    wire = c_tx.protect_rtp(b)
    client = UdpEngine(port=0, max_batch=64)
    client.send_batch(wire, "127.0.0.1", bridge.engine.port)

    # bridge processes one tick (recv batch -> decrypt -> echo -> encrypt)
    for _ in range(50):
        if bridge.tick():
            break
    assert sum(got_media) == 8
    assert bridge.addr_port[3] == client.port  # address latched

    # client receives the re-protected echo and decrypts with MK2
    back, _, _ = client.recv_batch(timeout_ms=500)
    assert back.batch_size == 8
    back.stream[:] = 0
    dec, ok = c_rx.unprotect_rtp(back)
    assert ok.all()
    hdr = rtp_header.parse(dec)
    got = {dec.to_bytes(i)[int(hdr.payload_off[i]):] for i in range(8)}
    assert got == set(payloads)
    client.close()
    bridge.engine.close()


def test_loop_splits_dtls_and_rtcp():
    reg = _registry()
    dtls_in = []

    def on_dtls(pkt, addr):
        dtls_in.append(pkt)
        return [b"\x16\xfe\xfd-reply"]

    rtcp_seen = []
    bridge = MediaLoop(UdpEngine(port=0, max_batch=16), reg,
                       on_rtcp=lambda b, ok: rtcp_seen.append(b.batch_size),
                       on_dtls=on_dtls, chain=None)
    reg.map_ssrc(0xABC, 1)

    client = UdpEngine(port=0, max_batch=16)
    dtls_pkt = b"\x16\xfe\xfd\x00\x00hello"         # handshake record
    rr = rtcp.build_rr(rtcp.ReceiverReport(0xABC, []))
    media = rtp_header.build([b"m"], [1], [0], [0xABC], [96]).to_bytes(0)
    batch = PacketBatch.from_payloads([dtls_pkt, rr, media])
    client.send_batch(batch, "127.0.0.1", bridge.engine.port)

    for _ in range(50):
        if bridge.tick():
            break
    assert dtls_in == [dtls_pkt]
    assert rtcp_seen == [1]
    # the DTLS reply came back to the client
    back, _, _ = client.recv_batch(timeout_ms=500)
    assert back.batch_size == 1 and back.to_bytes(0).startswith(b"\x16")
    # metrics rendered timing quantiles
    assert "reverse_chain_seconds" in bridge.metrics.render()
    client.close()
    bridge.engine.close()


def test_loop_kernel_arrival_ns_aligned_with_media_rows():
    """MediaLoop with a timestamped engine exposes per-row kernel
    arrival times aligned with the batch handed to on_media."""
    reg = _registry()
    rx_tab = SrtpStreamTable(capacity=16)
    rx_tab.add_stream(3, MK, MS)
    tx_tab = SrtpStreamTable(capacity=16)
    tx_tab.add_stream(3, MK2, MS2)
    chain = TransformEngineChain([SrtpTransformEngine(tx_tab, rx_tab)])
    seen = {}

    def on_media(batch, ok):
        seen["n"] = batch.batch_size
        seen["ats"] = bridge.last_rtp_arrival_ns
        return None

    bridge = MediaLoop(
        UdpEngine(port=0, max_batch=64, kernel_timestamps=True), reg,
        on_media=on_media, chain=chain)
    assert bridge.use_kernel_ts
    reg.map_ssrc(0xC11E27, 3)
    c_tx = SrtpStreamTable(capacity=1)
    c_tx.add_stream(0, MK, MS)
    b = rtp_header.build([b"k-%d" % i for i in range(4)],
                         list(range(4)), [0] * 4, [0xC11E27] * 4,
                         [96] * 4, stream=[0] * 4)
    client = UdpEngine(port=0, max_batch=64)
    client.send_batch(c_tx.protect_rtp(b), "127.0.0.1",
                      bridge.engine.port)
    import time as _t
    t0 = _t.time()
    for _ in range(50):
        if bridge.tick():
            break
    assert seen["n"] == 4
    ats = seen["ats"]
    assert ats is not None and len(ats) == 4
    assert np.all(np.abs(ats / 1e9 - t0) < 5.0)


def test_send_media_async_flush_matches_sync():
    """The pipelined seam (VERDICT r2 #3): dispatch-only protect +
    next-tick flush must emit byte-identical datagrams to the sync
    path, with TX state advancing identically."""
    import libjitsi_tpu
    from libjitsi_tpu.io.loop import MediaLoop
    from libjitsi_tpu.service.media_stream import StreamRegistry
    from libjitsi_tpu.transform import (SrtpTransformEngine,
                                        TransformEngineChain)
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    mk, ms = bytes(range(16)), bytes(range(30, 44))

    class _CaptureEngine:
        port = 0

        def __init__(self):
            self.sent = []

        def recv_batch(self, timeout_ms):
            return (PacketBatch.from_payloads([]),
                    np.zeros(0, np.uint32), np.zeros(0, np.uint16))

        def send_batch(self, batch, ip, port):
            for i in range(batch.batch_size):
                self.sent.append(batch.to_bytes(i))
            return batch.batch_size

    def build_loop(pipelined):
        reg = StreamRegistry(libjitsi_tpu.configuration_service(),
                             capacity=4)
        tx = SrtpStreamTable(capacity=4)
        tx.add_stream(2, mk, ms)
        rx = SrtpStreamTable(capacity=4)
        rx.add_stream(2, mk, ms)
        chain = TransformEngineChain([SrtpTransformEngine(tx, rx)])
        eng = _CaptureEngine()
        loop = MediaLoop(eng, reg, chain=chain, pipelined=pipelined)
        loop.addr_ip[2] = 0x7F000001
        loop.addr_port[2] = 4444
        return loop, eng

    batch = rtp_header.build([b"pipelined-%d" % i for i in range(5)],
                             [800 + i for i in range(5)], [0] * 5,
                             [0xF00D] * 5, [96] * 5, stream=[2] * 5)

    sync_loop, sync_eng = build_loop(False)
    sync_loop.send_media(batch)

    pipe_loop, pipe_eng = build_loop(True)
    n = pipe_loop.send_media_async(batch)
    assert n == 5 and pipe_eng.sent == [], "async sent before flush"
    pipe_loop.tick()                 # next tick flushes the in-flight
    assert pipe_eng.sent == sync_eng.sent
    # idempotent: nothing left in flight
    assert pipe_loop.flush_sends() == 0


def test_scrape_sees_live_inflight_age_not_last_tick_note():
    """Staleness regression for the deep pipeline's age gauge: the
    exporter reads `_inflight_age()` LIVE, so a scrape between tick
    boundaries sees the dispatch aging (and sees zero right after a
    drain) instead of the value frozen at the last per-tick note."""
    reg = _registry()
    tx = SrtpStreamTable(capacity=16)
    tx.add_stream(2, MK, MS)
    rx = SrtpStreamTable(capacity=16)
    rx.add_stream(2, MK2, MS2)
    chain = TransformEngineChain([SrtpTransformEngine(tx, rx)])
    loop = MediaLoop(UdpEngine(port=0, max_batch=16), reg,
                     chain=chain, pipelined=True)
    loop.addr_ip[2] = 0x7F000001
    loop.addr_port[2] = 9                # discard; nothing listens
    batch = rtp_header.build([b"inflight-x"], [1], [0], [0xF00D],
                             [96], stream=[2])
    assert loop.send_media_async(batch) == 1
    loop.ticks += 3                      # ticks pass, no flush, no note
    assert loop._inflight_age() == 3
    assert loop.dispatch_inflight_ticks == 0, \
        "per-tick note is only taken at tick boundaries"
    assert "libjitsi_tpu_dispatch_inflight_ticks 3" \
        in loop.metrics.render()
    loop.flush_sends()
    # live again after the drain, still before any tick boundary
    assert "libjitsi_tpu_dispatch_inflight_ticks 0" \
        in loop.metrics.render()
