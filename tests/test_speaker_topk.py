"""Top-K active-speaker ranker properties (conference/speaker.py):
K=1 degenerates bit-for-bit to the classic dominant-speaker
trajectory, hysteresis keeps the member set from flapping under
oscillating levels, ties resolve deterministically (lowest sid wins
promotion, highest sid loses demotion), and membership churn is
bounded to one swap per tick once the set is full."""

import numpy as np
import pytest

from libjitsi_tpu.conference.speaker import (DominantSpeakerIdentification,
                                             SILENCE_LEVEL)


class _ClassicDSI:
    """Verbatim inline copy of the pre-top-K dominant-speaker
    algorithm — the oracle the K=1 degeneracy property compares
    against (kept here on purpose: the shipping class must match THIS
    trajectory, not whatever it evolves into)."""

    def __init__(self, capacity, speech_threshold=0.12, margin=1.15):
        self.capacity = capacity
        self.speech_threshold = speech_threshold
        self.margin = margin
        self.immediate = np.zeros(capacity)
        self.medium = np.zeros(capacity)
        self.long = np.zeros(capacity)
        self.active = np.zeros(capacity, dtype=bool)
        self.dominant = -1

    def add_participant(self, sid):
        self.active[sid] = True
        self.immediate[sid] = self.medium[sid] = self.long[sid] = 0.0

    def remove_participant(self, sid):
        self.active[sid] = False
        if self.dominant == sid:
            self.dominant = -1

    def levels(self, levels):
        lv = np.full(self.capacity, SILENCE_LEVEL, dtype=np.float64)
        lv[: len(levels)] = np.asarray(levels, dtype=np.float64)
        loud = np.clip((70.0 - lv) / 70.0, 0.0, 1.0)
        loud[~self.active] = 0.0
        speaking = loud > self.speech_threshold
        self.immediate += (loud - self.immediate) / 3.0
        self.medium += (speaking * self.immediate - self.medium) / 10.0
        self.long += (self.medium - self.long) / 50.0
        scores = np.where(self.active, self.long, -1.0)
        best = int(np.argmax(scores))
        if scores[best] <= 0:
            return self.dominant
        if self.dominant < 0 or not self.active[self.dominant]:
            self.dominant = best
            return self.dominant
        cur = self.dominant
        if best != cur and (
                self.long[best] > self.margin * self.long[cur]
                and self.medium[best] > self.margin * self.medium[cur]
                and self.immediate[best] > self.immediate[cur]):
            self.dominant = best
        return self.dominant


def _talk(dsi, frames, level_fn):
    out = []
    for t in range(frames):
        out.append(dsi.levels(level_fn(t)))
    return out


def test_k_must_be_positive():
    with pytest.raises(ValueError):
        DominantSpeakerIdentification(capacity=4, k=0)


def test_k1_degenerates_to_classic_dominant_trajectory():
    """600 random ticks with joins/leaves: the k=1 ranker's dominant
    must equal the classic algorithm's at every tick, and its member
    set must be exactly {dominant}."""
    rng = np.random.default_rng(7)
    cap = 12
    new = DominantSpeakerIdentification(capacity=cap, k=1)
    old = _ClassicDSI(cap)
    present = set()
    for tick in range(600):
        r = rng.random()
        if r < 0.05 and len(present) < cap:
            sid = int(rng.integers(cap))
            if sid not in present:
                present.add(sid)
                new.add_participant(sid)
                old.add_participant(sid)
        elif r < 0.08 and present:
            sid = int(rng.choice(sorted(present)))
            present.discard(sid)
            new.remove_participant(sid)
            old.remove_participant(sid)
        lv = rng.integers(0, 128, cap)
        got = new.levels(lv)
        want = old.levels(lv)
        assert got == want, f"tick {tick}: new={got} old={want}"
        if got >= 0:
            assert new.speakers == (got,)
        else:
            assert new.speakers == ()


def test_topk_fills_vacancies_and_holds_k_speakers():
    dsi = DominantSpeakerIdentification(capacity=8, k=3)
    for sid in range(5):
        dsi.add_participant(sid)

    def lv(_t):
        # sids 0..2 loud, 3..4 quiet-ish, rest silent
        out = np.full(8, SILENCE_LEVEL)
        out[:3] = 10
        out[3:5] = 50
        return out

    _talk(dsi, 100, lv)
    assert dsi.speakers == (0, 1, 2)
    assert dsi.dominant == 0          # lowest sid won the first fill


def test_hysteresis_no_flap_under_oscillating_levels():
    """Two participants alternating loud/soft every frame around a
    steady third: once the k=2 set settles, oscillation that never
    clears the margin must produce ZERO membership churn."""
    dsi = DominantSpeakerIdentification(capacity=4, k=2)
    for sid in range(3):
        dsi.add_participant(sid)

    def settle(_t):
        out = np.full(4, SILENCE_LEVEL)
        out[0] = 10
        out[1] = 12
        out[2] = 60                    # barely above threshold
        return out

    _talk(dsi, 120, settle)
    assert dsi.speakers == (0, 1)
    p0, d0 = dsi.promotions, dsi.demotions
    notifications = []
    dsi.on_speakers_change = notifications.append

    def flap(t):
        out = np.full(4, SILENCE_LEVEL)
        # members oscillate; challenger 2 wobbles but stays well below
        out[0] = 10 if t % 2 else 20
        out[1] = 20 if t % 2 else 10
        out[2] = 55 if t % 2 else 65
        return out

    _talk(dsi, 200, flap)
    assert dsi.speakers == (0, 1)
    assert (dsi.promotions, dsi.demotions) == (p0, d0)
    assert notifications == []


def test_sustained_takeover_does_swap_exactly_once():
    """A challenger that goes loud FOR GOOD must displace the weakest
    member — once, not repeatedly."""
    dsi = DominantSpeakerIdentification(capacity=4, k=2)
    for sid in range(3):
        dsi.add_participant(sid)
    _talk(dsi, 120, lambda t: np.array([10, 12, 80, SILENCE_LEVEL]))
    assert dsi.speakers == (0, 1)
    p0 = dsi.promotions
    _talk(dsi, 300, lambda t: np.array([10, 90, 5, SILENCE_LEVEL]))
    assert dsi.speakers == (0, 2)     # 2 displaced the now-quiet 1
    assert dsi.promotions == p0 + 1


def test_ties_promote_lowest_sid_and_demote_highest():
    """Bit-identical levels everywhere: promotion ties go to the
    LOWEST sid; when a demotion must pick among equally-weak members
    the HIGHEST sid loses."""
    dsi = DominantSpeakerIdentification(capacity=8, k=2)
    for sid in (2, 3, 5):
        dsi.add_participant(sid)
    _talk(dsi, 80, lambda t: np.full(8, 30))
    assert dsi.speakers == (2, 3)     # lowest sids won the fill
    assert dsi.dominant == 2
    # now 5 goes clearly loud while 2 and 3 stay tied: the swap must
    # demote 3 (highest of the tied weak members), never 2
    lv = np.full(8, 30)
    lv[5] = 5
    _talk(dsi, 300, lambda t: lv)
    assert dsi.speakers == (2, 5)


def test_member_leaving_frees_slot_and_notifies():
    seen = []
    dsi = DominantSpeakerIdentification(capacity=4, k=2,
                                        on_speakers_change=seen.append)
    for sid in range(3):
        dsi.add_participant(sid)
    _talk(dsi, 80, lambda t: np.array([10, 15, 40, SILENCE_LEVEL]))
    assert dsi.speakers == (0, 1)
    dsi.remove_participant(0)
    assert dsi.speakers == (1,)
    assert seen[-1] == (1,)
    # vacancy refills from the remaining field on the next tick
    _talk(dsi, 20, lambda t: np.array([SILENCE_LEVEL, 15, 40,
                                       SILENCE_LEVEL]))
    assert dsi.speakers == (1, 2)


def test_at_most_one_swap_per_tick():
    """Even when three challengers simultaneously dwarf the members,
    membership changes by at most one swap per tick."""
    dsi = DominantSpeakerIdentification(capacity=8, k=2)
    for sid in range(6):
        dsi.add_participant(sid)
    _talk(dsi, 100, lambda t: np.array(
        [20, 25, SILENCE_LEVEL, SILENCE_LEVEL,
         SILENCE_LEVEL, SILENCE_LEVEL, SILENCE_LEVEL, SILENCE_LEVEL]))
    assert dsi.speakers == (0, 1)
    prev = set(dsi.speakers)
    churn_per_tick = []
    for t in range(300):
        dsi.levels(np.array([70, 75, 5, 6, 7, SILENCE_LEVEL,
                             SILENCE_LEVEL, SILENCE_LEVEL]))
        cur = set(dsi.speakers)
        churn_per_tick.append(len(cur ^ prev))
        prev = cur
    assert max(churn_per_tick) <= 2   # one swap = one out + one in
    assert prev == {2, 3}             # strongest challengers landed
