"""Codecs: Opus binding, VP8 depacketizer, G.711 kernels, resampler.

Reference behaviors: opus.Opus JNI surface, vp8.DePacketizer descriptor
logic, alaw/ulaw codecs (differential vs stdlib audioop-style math),
speex resampler role (spectral fidelity on a sine).
"""

import numpy as np
import pytest

from libjitsi_tpu.codecs import OpusDecoder, OpusEncoder, opus_available
from libjitsi_tpu.codecs.vp8 import (
    SimulcastReceiver,
    build_descriptor,
    parse_descriptors,
)
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.kernels.g711 import (
    alaw_decode,
    alaw_encode,
    ulaw_decode,
    ulaw_encode,
)
from libjitsi_tpu.kernels.resample import resample
from libjitsi_tpu.rtp import header as rtp_header


# ------------------------------------------------------------------ Opus ---

@pytest.mark.skipif(not opus_available(), reason="libopus not present")
def test_opus_roundtrip_sine():
    enc = OpusEncoder()
    enc.set_bitrate(64000)
    enc.set_complexity(5)
    dec = OpusDecoder()
    t = np.arange(960) / 48000.0
    pcm = (np.sin(2 * np.pi * 440 * t) * 10000).astype(np.int16)
    # prime the codec, then check correlation on a steady frame
    out = None
    for _ in range(5):
        pkt = enc.encode(pcm)
        out = dec.decode(pkt, 960)
    assert out.shape == (960,)
    # Opus has ~6.5 ms algorithmic lookahead: compare spectra, not samples
    spec = np.abs(np.fft.rfft(out * np.hanning(960)))
    peak = np.argmax(spec) * 48000 / 960
    assert abs(peak - 440) < 60
    # decoded energy in the same ballpark as the input
    assert 0.5 < np.std(out.astype(float)) / np.std(pcm.astype(float)) < 2.0
    assert 10 < len(pkt) < 400


@pytest.mark.skipif(not opus_available(), reason="libopus not present")
def test_opus_plc():
    dec = OpusDecoder()
    out = dec.decode(None, 960)   # concealment with no prior audio
    assert out.shape == (960,)


# ------------------------------------------------------------------- VP8 ---

def _vp8_pkt(desc: bytes, payload: bytes, seq=1, ssrc=0x10):
    b = rtp_header.build([desc + payload], [seq], [0], [ssrc], [100])
    return b.to_bytes(0)


def test_vp8_descriptor_roundtrip_parse():
    desc = build_descriptor(start=True, picture_id=345, tl0picidx=7, tid=2)
    payload = bytes([0x00, 0xAA, 0xBB])  # P bit 0 -> keyframe candidate
    pkt = _vp8_pkt(desc, payload)
    batch = PacketBatch.from_payloads([pkt])
    d = parse_descriptors(batch)
    assert d.valid[0]
    assert d.start_of_partition[0] == 1 and d.partition_id[0] == 0
    assert d.picture_id[0] == 345
    assert d.tl0picidx[0] == 7 and d.tid[0] == 2
    assert d.is_keyframe[0]
    assert d.desc_len[0] == len(desc)


def test_vp8_short_picture_id_and_interframe():
    desc = build_descriptor(start=True, picture_id=5)
    payload = bytes([0x01])  # P=1 -> interframe
    d = parse_descriptors(PacketBatch.from_payloads([_vp8_pkt(desc, payload)]))
    assert d.picture_id[0] == 5
    assert not d.is_keyframe[0]
    # continuation packet (S=0)
    d2 = parse_descriptors(PacketBatch.from_payloads(
        [_vp8_pkt(build_descriptor(start=False), b"\x00\xff")]))
    assert d2.start_of_partition[0] == 0
    assert not d2.is_keyframe[0]


def test_simulcast_receiver_layers():
    ssrcs = [0x100, 0x200, 0x300]
    rx = SimulcastReceiver(ssrcs)
    pkts = []
    for layer, ssrc in enumerate(ssrcs):
        key = bytes([0x00])
        desc = build_descriptor(start=True, picture_id=10 + layer,
                                tl0picidx=layer)
        pkts.append(_vp8_pkt(desc, key, seq=layer, ssrc=ssrc))
    rx.ingest(PacketBatch.from_payloads(pkts))
    assert rx.keyframe_seen.all()
    np.testing.assert_array_equal(rx.last_picture_id, [10, 11, 12])
    assert rx.select_layer(5e6, [0.5e6, 1.5e6, 3e6]) == 2
    assert rx.select_layer(1e6, [0.5e6, 1.5e6, 3e6]) == 0


# ----------------------------------------------------------------- G.711 ---

def _g711_ref_ulaw(x: int) -> int:
    """Scalar reference µ-law encoder straight from G.711."""
    BIAS, CLIP = 0x84, 32635
    sign = 0x80 if x < 0 else 0
    x = min(abs(x), CLIP) + BIAS
    exp = 7
    mask = 0x4000
    while exp > 0 and not (x & mask):
        exp -= 1
        mask >>= 1
    mant = (x >> (exp + 3)) & 0x0F
    return ~(sign | (exp << 4) | mant) & 0xFF


def test_ulaw_encode_matches_scalar_reference():
    rng = np.random.default_rng(3)
    pcm = rng.integers(-32768, 32768, 500).astype(np.int16)
    got = np.asarray(ulaw_encode(pcm))
    want = np.array([_g711_ref_ulaw(int(v)) for v in pcm], dtype=np.uint8)
    np.testing.assert_array_equal(got, want)


def test_g711_roundtrip_error_bounds():
    pcm = np.linspace(-30000, 30000, 2000).astype(np.int16)
    for enc, dec in ((ulaw_encode, ulaw_decode), (alaw_encode, alaw_decode)):
        back = np.asarray(dec(enc(pcm))).astype(np.int64)
        err = np.abs(back - pcm)
        # logarithmic quantization: error scales with magnitude
        assert np.all(err <= np.maximum(np.abs(pcm) // 16, 64))
        # codec is idempotent through a second pass
        again = np.asarray(dec(enc(back.astype(np.int16))))
        np.testing.assert_array_equal(again, back)


# ------------------------------------------------------------- resampler ---

def _tone(rate, freq, seconds=0.1):
    t = np.arange(int(rate * seconds)) / rate
    return (np.sin(2 * np.pi * freq * t) * 8000).astype(np.int16)


@pytest.mark.parametrize("rate_in", [8000, 16000, 24000])
def test_resample_preserves_tone(rate_in):
    freq = 440.0
    x = _tone(rate_in, freq)[None, :]
    y = np.asarray(resample(x, rate_in, 48000))[0]
    assert abs(len(y) - len(x[0]) * 48000 // rate_in) <= 1
    # dominant frequency survives
    spec = np.abs(np.fft.rfft(y * np.hanning(len(y))))
    peak = np.argmax(spec) * 48000 / len(y)
    assert abs(peak - freq) < 15
    # energy preserved within 3 dB (ignore edges)
    mid = slice(len(y) // 4, 3 * len(y) // 4)
    ratio = np.std(y[mid].astype(float)) / np.std(x[0].astype(float))
    assert 0.7 < ratio < 1.4


def test_resample_identity_and_batch():
    x = _tone(48000, 1000)[None, :]
    y = resample(x, 48000, 48000)
    np.testing.assert_array_equal(np.asarray(y), x)
    xb = np.vstack([_tone(16000, 300), _tone(16000, 1200)])
    yb = np.asarray(resample(xb, 16000, 48000))
    assert yb.shape == (2, xb.shape[1] * 3)
