"""libvpx binding + real-bitstream VP8 media path.

The crown-jewel integration: REAL VP8 frames (encoded by libvpx)
through the full secure SFU path — packetize, SRTP protect, fan out,
per-receiver unprotect, reassemble, decode — and the picture survives.
"""

import numpy as np
import pytest

from libjitsi_tpu.codecs import vpx
from libjitsi_tpu.codecs import vp8 as vp8rtp

pytestmark = pytest.mark.skipif(not vpx.vpx_available(),
                                reason="libvpx not present")

W, H = 64, 48


def _frames(n, seed=0):
    out = []
    for i in range(n):
        y = (np.add.outer(np.arange(H), np.arange(W)) * 2
             + i * 9 + seed * 31).astype(np.uint8)
        y[10:20, (8 + i * 4) % (W - 10):(18 + i * 4) % (W - 10) or 10] = 255
        u = np.full((H // 2, W // 2), 90 + i, np.uint8)
        v = np.full((H // 2, W // 2), 150 + i, np.uint8)
        out.append((y, u, v))
    return out


def _psnr(a, b):
    err = np.mean((a.astype(np.float64) - b.astype(np.float64)) ** 2)
    return 10 * np.log10(255.0 ** 2 / max(err, 1e-9))


def test_encode_decode_roundtrip_vp8():
    enc = vpx.VpxEncoder(W, H, "vp8")
    dec = vpx.VpxDecoder("vp8")
    frames = _frames(6)
    n_dec = 0
    for i, (y, u, v) in enumerate(frames):
        for pkt, key in enc.encode(y, u, v):
            assert key == (i == 0)
            for dy, du, dv in dec.decode(pkt):
                assert dy.shape == (H, W)
                assert _psnr(frames[n_dec][0], dy) > 30
                n_dec += 1
    assert n_dec == 6
    enc.close(); dec.close()


def test_encode_decode_roundtrip_vp9():
    enc = vpx.VpxEncoder(W, H, "vp9")
    dec = vpx.VpxDecoder("vp9")
    frames = _frames(3)
    pkts = []
    for y, u, v in frames:
        pkts += enc.encode(y, u, v)
    pkts += enc.flush()          # VP9 defaults to multi-frame lookahead
    n_dec = 0
    for pkt, _key in pkts:
        for dy, _du, _dv in dec.decode(pkt):
            assert _psnr(frames[n_dec][0], dy) > 30
            n_dec += 1
    assert n_dec == 3
    enc.close(); dec.close()


@pytest.mark.slow
def test_real_vp8_through_secure_sfu_path():
    """Real bitstream -> RTP -> SRTP -> SFU fan-out -> decode -> PSNR."""
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.sfu import RtpTranslator
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    enc = vpx.VpxEncoder(W, H, "vp8")
    frames = _frames(5, seed=3)
    tx = SrtpStreamTable(capacity=2); tx.add_stream(0, b"k" * 16, b"s" * 14)
    sfu = SrtpStreamTable(capacity=2); sfu.add_stream(0, b"k" * 16, b"s" * 14)
    tr = RtpTranslator(capacity=4)
    tr.add_receiver(1, b"\x07" * 16, b"\x08" * 14)
    tr.connect(0, [1])
    leg = SrtpStreamTable(capacity=4)
    leg.add_stream(2, b"\x07" * 16, b"\x08" * 14)
    fa = vp8rtp.FrameAssembler()
    dec = vpx.VpxDecoder("vp8")

    seq, n_out = 50, 0
    for i, (y, u, v) in enumerate(frames):
        for pkt, _key in enc.encode(y, u, v):
            pls = vp8rtp.packetize(pkt, picture_id=0x4000 | i,
                                   max_payload=300)
            n = len(pls)
            batch = rtp_header.build(
                pls, list(range(seq, seq + n)), [i * 3000] * n,
                [0xCAFE] * n, [100] * n, marker=[0] * (n - 1) + [1],
                stream=[0] * n)
            seq += n
            wire = tx.protect_rtp(batch)
            decd, ok, idx = sfu.unprotect_rtp(wire, return_index=True)
            assert ok.all()
            out, recv = tr.translate(decd, idx)
            sub = PacketBatch.from_payloads(
                [out.to_bytes(j) for j in range(out.batch_size)],
                stream=[2] * out.batch_size)
            dec_r, ok_r = leg.unprotect_rtp(sub)
            assert ok_r.all()
            fa.push_batch(dec_r)
        for _ts, _pid, key, data in fa.pop_frames():
            for dy, _du, _dv in dec.decode(data):
                assert _psnr(frames[n_out][0], dy) > 30
                n_out += 1
    assert n_out == 5
    enc.close(); dec.close()


def test_ivf_fixture_with_real_bitstream(tmp_path):
    """Author an IVF with real VP8 frames, replay as a fake camera."""
    from libjitsi_tpu.device import IvfReader, IvfWriter

    enc = vpx.VpxEncoder(W, H, "vp8")
    path = str(tmp_path / "real.ivf")
    w = IvfWriter(path, W, H)
    n_in = 0
    for i, (y, u, v) in enumerate(_frames(4)):
        for pkt, _key in enc.encode(y, u, v):
            w.write(pkt, pts=i)
            n_in += 1
    w.close()
    dec = vpx.VpxDecoder("vp8")
    n_out = 0
    for _pts, data in IvfReader(path):
        n_out += len(dec.decode(data))
    assert n_out == n_in == 4
    enc.close(); dec.close()
