"""VP9 SVC projection: per-receiver spatial/temporal subsetting of one
layered stream (the layered twin of the VP8 simulcast forwarder)."""

import numpy as np
import pytest

from libjitsi_tpu.codecs import vp9
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.sfu.svc import Vp9SvcForwarder

SSRC = 0xC0DE


def _pkt(seq, pid, sid, tid, begin, end, key=False, marker=None):
    desc = vp9.build_descriptor(
        begin=begin, end=end, picture_id=pid, tid=tid, sid=sid,
        tl0picidx=pid & 0xFF, inter_predicted=not key)
    body = desc + bytes([0x80 | sid]) * 24
    if marker is None:
        marker = end and sid == 2
    return rtp_header.build([body], [seq], [pid * 3000], [SSRC], [98],
                            marker=[int(marker)], stream=[0])


def _stream(n_pics, layers=3, key_every=6, start_seq=100):
    """Pictures of `layers` spatial layers (tid alternates 0/1), one
    packet per (picture, layer)."""
    pkts, seq = [], start_seq
    for p in range(n_pics):
        key = (p % key_every) == 0
        tid = 0 if key else (p % 2)
        for s in range(layers):
            pkts.append(_pkt(seq, 400 + p, s, tid, begin=(True),
                             end=True, key=key and s == 0))
            seq += 1
    return pkts


def _batch(pkts):
    datas = [p.to_bytes(0) for p in pkts]
    return PacketBatch.from_payloads(datas, stream=[0] * len(datas))


def _seqs_and_markers(outs):
    b = PacketBatch.from_payloads(outs)
    h = rtp_header.parse(b)
    return [int(s) for s in h.seq], [int(m) for m in h.marker]


def test_base_layer_projection_is_gapless_and_remarked():
    fwd = Vp9SvcForwarder(initial_sid=0)
    outs = fwd.forward(_batch(_stream(6)))
    # one packet per picture survives (sid 0), seq gapless from 0
    assert len(outs) == 6
    seqs, marks = _seqs_and_markers(outs)
    assert seqs == list(range(6))
    # every forwarded packet ends its (single-layer) picture: marker set
    # even though the ORIGINAL marker rode the dropped sid-2 packet
    assert all(m == 1 for m in marks)
    assert fwd.dropped == 12


def test_spatial_raise_waits_for_keyframe():
    fwd = Vp9SvcForwarder(initial_sid=0)
    pkts = _stream(13, key_every=6)        # keyframes at pictures 0, 6, 12
    fwd.forward(_batch(pkts[:6]))          # pictures 0..1 projected @0
    assert fwd.request_layers(sid=2) is True
    assert fwd.awaiting_keyframe
    # pictures 2..5: no keyframe yet -> still base layer only
    outs = fwd.forward(_batch(pkts[6:18]))
    assert len(outs) == 4 and fwd.current_sid == 0
    # picture 6 is a keyframe: the raise lands, all 3 layers flow
    outs = fwd.forward(_batch(pkts[18:21]))
    assert fwd.current_sid == 2 and not fwd.awaiting_keyframe
    assert len(outs) == 3
    seqs, marks = _seqs_and_markers(outs)
    assert seqs == sorted(seqs) and seqs[0] > 0    # continuous space
    assert marks == [0, 0, 1]                      # top layer marks


def test_temporal_downswitch_at_picture_boundary():
    fwd = Vp9SvcForwarder(initial_sid=2)
    pkts = _stream(8, key_every=100)       # keyframe only at picture 0
    fwd.forward(_batch(pkts[:3]))
    fwd.request_layers(tid=0)
    outs = fwd.forward(_batch(pkts[3:]))
    # odd pictures carry tid=1 and are dropped entirely
    got = PacketBatch.from_payloads(outs)
    d = vp9.parse_descriptors(got)
    assert (np.asarray(d.tid)[np.asarray(d.valid)] <= 0).all()
    seqs, _ = _seqs_and_markers(outs)
    assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))


def test_redelivered_packet_keeps_its_output_seq():
    fwd = Vp9SvcForwarder(initial_sid=0)
    pkts = _stream(4)
    fwd.forward(_batch(pkts))
    # re-deliver picture 2's base-layer packet (e.g. RTX recovery):
    # same original seq -> same output seq, not a fresh number
    again = fwd.forward(_batch([pkts[6]]))
    seqs, _ = _seqs_and_markers(again)
    assert seqs == [2]


def test_late_first_arrival_of_older_original_is_dropped():
    """An upstream-lost kept packet recovered AFTER its successors were
    compacted has no output hole left: dropped, not emitted with a
    scrambled fresh seq (recovery rides the keyframe/PLI path)."""
    fwd = Vp9SvcForwarder(initial_sid=0)
    pkts = _stream(4)                      # originals 100,103,106,109...
    fwd.forward(_batch([pkts[0], pkts[6], pkts[9]]))   # pic 0,2,3 kept
    assert fwd.forwarded == 3
    late = fwd.forward(_batch([pkts[3]]))  # pic 1 base, orig 103, late
    assert late == [] and fwd.late_dropped == 1
    # but a RE-delivery of an already-forwarded one still reuses its seq
    again = fwd.forward(_batch([pkts[6]]))
    seqs, _ = _seqs_and_markers(again)
    assert seqs == [1]


def test_marker_follows_actual_top_layer():
    """Sender stops emitting upper layers: the marker re-derivation
    follows the observed top (previous picture), not the stale target."""
    fwd = Vp9SvcForwarder(initial_sid=2)
    fwd.forward(_batch(_stream(2)))        # 3-layer pictures
    only_base = [_pkt(900 + k, 500 + k, 0, 0, begin=True, end=True,
                      key=(k == 0), marker=False) for k in range(3)]
    outs = fwd.forward(_batch(only_base))
    _, marks = _seqs_and_markers(outs)
    # first base-only picture still judged against the 3-layer previous
    # picture; from the next boundary on, markers flow again
    assert marks[-1] == 1


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_svc_projection_properties_random_trace(seed):
    """Property check over random traces (loss, reorder within a
    window, REMB-driven target changes): every forwarded packet is
    within the layer targets that were CURRENT at its picture, output
    seqs are strictly increasing with no gaps among first deliveries,
    and spatial raises only ever land on keyframe pictures."""
    rng = np.random.default_rng(seed)
    fwd = Vp9SvcForwarder(initial_sid=0)
    layers = 3
    seq = 200
    sent = []                       # (orig_seq, pid, sid, key)
    for p in range(60):
        key = p % 12 == 0
        for s in range(layers):
            sent.append((seq, 700 + p, s, key))
            seq += 1
    # drop ~10%, reorder within a small window
    keep = [pkt for pkt in sent if rng.random() > 0.10]
    for _ in range(len(keep) // 5):
        a = int(rng.integers(0, len(keep) - 1))
        b = min(len(keep) - 1, a + int(rng.integers(1, 3)))
        keep[a], keep[b] = keep[b], keep[a]

    out_seqs, out_sids = [], []
    raise_pics = []
    for i, (q, pid, s, key) in enumerate(keep):
        if i % 17 == 5:            # REMB churn
            want = int(rng.integers(0, layers))
            fwd.request_layers(sid=want)
        before = fwd.current_sid
        outs = fwd.forward(_batch([_pkt(q, pid, s, 0, begin=True,
                                        end=True, key=key and s == 0)]))
        if fwd.current_sid > before:
            raise_pics.append(pid)
        for o in outs:
            b2 = PacketBatch.from_payloads([o])
            h = rtp_header.parse(b2)
            d = vp9.parse_descriptors(b2)
            out_seqs.append(int(h.seq[0]))
            sid_out = max(int(np.asarray(d.sid)[0]), 0)
            out_sids.append(sid_out)
            # the layer-target property, asserted per packet: nothing
            # above the projection's CURRENT spatial layer is emitted
            assert sid_out <= fwd.current_sid, \
                (sid_out, fwd.current_sid, pid)

    assert out_sids, "trace forwarded nothing"
    # gapless, strictly increasing output space (first deliveries only)
    assert out_seqs == list(range(out_seqs[0],
                                  out_seqs[0] + len(out_seqs)))
    # spatial raises landed only on keyframe pictures
    key_pids = {700 + p for p in range(60) if p % 12 == 0}
    assert set(raise_pics) <= key_pids, (raise_pics, key_pids)
    assert fwd.forwarded == len(out_seqs)
