import struct

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as H


def scalar_parse(pkt: bytes):
    """Independent scalar reference parser for differential testing."""
    b0, b1 = pkt[0], pkt[1]
    out = {
        "version": b0 >> 6,
        "padding": (b0 >> 5) & 1,
        "extension": (b0 >> 4) & 1,
        "cc": b0 & 0xF,
        "marker": b1 >> 7,
        "pt": b1 & 0x7F,
        "seq": struct.unpack(">H", pkt[2:4])[0],
        "ts": struct.unpack(">I", pkt[4:8])[0],
        "ssrc": struct.unpack(">I", pkt[8:12])[0],
    }
    off = 12 + 4 * out["cc"]
    ext_words = 0
    if out["extension"]:
        ext_words = struct.unpack(">H", pkt[off + 2 : off + 4])[0]
        off += 4 + 4 * ext_words
    out["header_len"] = off
    out["pad_len"] = pkt[-1] if out["padding"] else 0
    out["payload_len"] = len(pkt) - off - out["pad_len"]
    return out


def random_packet(rng):
    cc = int(rng.integers(0, 4))
    has_ext = bool(rng.integers(0, 2))
    has_pad = bool(rng.integers(0, 2))
    payload = bytes(rng.integers(0, 256, size=int(rng.integers(0, 200)), dtype=np.uint8))
    hdr = bytearray(12)
    hdr[0] = (2 << 6) | (int(has_pad) << 5) | (int(has_ext) << 4) | cc
    hdr[1] = (int(rng.integers(0, 2)) << 7) | int(rng.integers(0, 128))
    hdr[2:4] = struct.pack(">H", int(rng.integers(0, 65536)))
    hdr[4:8] = struct.pack(">I", int(rng.integers(0, 2**32)))
    hdr[8:12] = struct.pack(">I", int(rng.integers(0, 2**32)))
    pkt = bytes(hdr)
    for _ in range(cc):
        pkt += struct.pack(">I", int(rng.integers(0, 2**32)))
    if has_ext:
        words = int(rng.integers(0, 4))
        pkt += struct.pack(">HH", 0xBEDE, words)
        pkt += bytes(rng.integers(0, 256, size=4 * words, dtype=np.uint8))
    pkt += payload
    if has_pad:
        pad = int(rng.integers(1, 5))
        pkt += b"\x00" * (pad - 1) + bytes([pad])
    return pkt


def test_parse_differential_random():
    rng = np.random.default_rng(42)
    pkts = [random_packet(rng) for _ in range(256)]
    batch = PacketBatch.from_payloads(pkts)
    h = H.parse(batch)
    for i, p in enumerate(pkts):
        ref = scalar_parse(p)
        assert h.version[i] == ref["version"]
        assert h.padding[i] == ref["padding"]
        assert h.extension[i] == ref["extension"]
        assert h.cc[i] == ref["cc"]
        assert h.marker[i] == ref["marker"]
        assert h.pt[i] == ref["pt"]
        assert h.seq[i] == ref["seq"]
        assert h.ts[i] == ref["ts"]
        assert h.ssrc[i] == ref["ssrc"]
        assert h.header_len[i] == ref["header_len"]
        assert h.pad_len[i] == ref["pad_len"]
        assert h.payload_len[i] == ref["payload_len"]
        assert bool(h.valid[i])


def test_build_then_parse_roundtrip():
    payloads = [b"hello", b"", b"x" * 100]
    batch = H.build(
        payloads,
        seq=[1, 65535, 7],
        ts=[0, 2**32 - 1, 12345],
        ssrc=0xDEADBEEF,
        pt=111,
        marker=[1, 0, 0],
        csrcs=[[], [1, 2], [0xFFFFFFFF]],
    )
    h = H.parse(batch)
    np.testing.assert_array_equal(h.seq, [1, 65535, 7])
    np.testing.assert_array_equal(h.ts, [0, 2**32 - 1, 12345])
    assert all(h.ssrc == 0xDEADBEEF)
    assert all(h.pt == 111)
    np.testing.assert_array_equal(h.marker, [1, 0, 0])
    np.testing.assert_array_equal(h.cc, [0, 2, 1])
    np.testing.assert_array_equal(
        h.payload_len, [len(p) for p in payloads]
    )
    assert batch.to_bytes(0)[h.payload_off[0] :] == b"hello"


def test_mutators():
    batch = H.build([b"abc"] * 4, seq=0, ts=0, ssrc=0, pt=0)
    H.set_seq(batch.data, [10, 20, 30, 65535])
    H.set_ts(batch.data, 0xCAFEBABE)
    H.set_ssrc(batch.data, [1, 2, 3, 4])
    H.set_pt(batch.data, 96)
    H.set_marker(batch.data, [0, 1, 0, 1])
    h = H.parse(batch)
    np.testing.assert_array_equal(h.seq, [10, 20, 30, 65535])
    assert all(h.ts == 0xCAFEBABE)
    np.testing.assert_array_equal(h.ssrc, [1, 2, 3, 4])
    assert all(h.pt == 96)
    np.testing.assert_array_equal(h.marker, [0, 1, 0, 1])


def test_invalid_flagged():
    batch = PacketBatch.from_payloads([b"\x00" * 12, b"short"])
    h = H.parse(batch)
    assert not h.valid[0]  # version 0
    assert not h.valid[1]  # too short
