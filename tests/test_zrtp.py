"""ZRTP (RFC 6189): in-memory agreement, SAS, commitment/chain checks,
retroactive message-MAC checks, robustness against malformed/out-of-order
packets, keys driving SRTP tables.
"""

import struct

from libjitsi_tpu.control.zrtp import ZrtpEndpoint, crc32c, is_zrtp, sas_b32
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpStreamTable


def _reseal(pkt: bytes) -> bytes:
    """Recompute the CRC-32C trailer after tampering with the body."""
    body = pkt[:-4]
    return body + struct.pack("!I", crc32c(body))


def run_zrtp(a: ZrtpEndpoint, b: ZrtpEndpoint):
    """a initiates after the Hello exchange."""
    wire = [(0, p) for p in a.hello_packets()] + \
           [(1, p) for p in b.hello_packets()]
    started = False
    rounds = 0
    while (not a.complete or not b.complete) and rounds < 30:
        rounds += 1
        nxt = []
        for who, pkt in wire:
            ep = b if who == 0 else a
            nxt += [(1 - who, p) for p in ep.feed(pkt)]
        wire = nxt
        if not started and b"Hello   " in a._peer:
            wire += [(0, p) for p in a.initiate()]
            started = True
    assert a.complete and b.complete, "zrtp did not complete"


def test_crc32c_kat():
    # the canonical CRC-32C check value (RFC 3720 §B.4)
    assert crc32c(b"123456789") == 0xE3069283


def test_zrtp_agreement_sas_and_keys():
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    run_zrtp(a, b)
    assert a.role == "initiator" and b.role == "responder"
    assert a.sas == b.sas and len(a.sas) == 4
    pa, a_txk, a_txs, a_rxk, a_rxs = a.srtp_keys()
    pb, b_txk, b_txs, b_rxk, b_rxs = b.srtp_keys()
    assert (a_txk, a_txs) == (b_rxk, b_rxs)
    assert (a_rxk, a_rxs) == (b_txk, b_txs)

    # keys drive real SRTP tables end to end
    tx = SrtpStreamTable(capacity=1, profile=pa)
    tx.add_stream(0, a_txk, a_txs)
    rx = SrtpStreamTable(capacity=1, profile=pb)
    rx.add_stream(0, b_rxk, b_rxs)
    pkt = rtp_header.build([b"zrtp-keyed"], [1], [0], [5], [96], stream=[0])
    dec, ok = rx.unprotect_rtp(tx.protect_rtp(pkt))
    assert ok.all() and dec.to_bytes(0) == pkt.to_bytes(0)


def test_zrtp_demux_and_crc():
    a = ZrtpEndpoint()
    pkt = a.hello_packets()[0]
    assert is_zrtp(pkt)
    assert not is_zrtp(b"\x80\x60" + bytes(20))      # RTP
    assert not is_zrtp(bytes([22, 254, 253]))        # DTLS
    # corrupted CRC: silently dropped
    bad = pkt[:-1] + bytes([pkt[-1] ^ 1])
    b = ZrtpEndpoint()
    assert b.feed(bad) == []


def test_zrtp_commitment_binds_dhpart2():
    """A MITM swapping DHPart2 after Commit is caught by the hvi check."""
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    commit = a.initiate()[0]
    dh1 = b.feed(commit)[0]
    dh2 = a.feed(dh1)[0]
    # attacker substitutes a different DHPart2 (new key pair)
    evil = ZrtpEndpoint(ssrc=1)
    evil_dh2_msg = evil._make_dhpart(b"DHPart2 ")
    forged = _reseal(dh2[:12] + evil_dh2_msg + dh2[12 + len(evil_dh2_msg):])
    assert b.feed(forged) == []
    assert any("hvi" in a_ or "MITM" in a_ for a_ in b.alerts)
    assert not b.complete


def test_zrtp_commit_must_chain_to_hello():
    """A Commit whose H2 does not hash to the Hello's H3 is rejected."""
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    commit = bytearray(a.initiate()[0])
    commit[12 + 12 + 5] ^= 0xFF  # corrupt H2 inside the commit message
    assert b.feed(_reseal(bytes(commit))) == []
    assert any("chain" in a_ for a_ in b.alerts)
    assert b.role is None


def test_zrtp_tampered_hello_caught_retroactively():
    """Flipping a MAC-covered Hello field (the client-id) is detected when
    H2 is later revealed by the Commit (RFC 6189 §8.1.1)."""
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    hello = bytearray(a.hello_packets()[0])
    hello[12 + 12 + 4 + 2] ^= 0xFF   # client-id byte: not in H3/ZID/algos
    b.feed(_reseal(bytes(hello)))
    for p in b.hello_packets():
        a.feed(p)
    assert b.feed(a.initiate()[0]) == []
    assert any("MAC" in a_ for a_ in b.alerts)


def test_zrtp_out_of_order_and_garbage_dropped():
    """Commit before Hello, unknown message types, and truncated or
    non-UTF-8 types are dropped, not crashes."""
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    for p in b.hello_packets():
        a.feed(p)
    commit = a.initiate()[0]
    fresh = ZrtpEndpoint()
    assert fresh.feed(commit) == []           # Commit before Hello: dropped
    # unknown/binary message type: dropped
    from libjitsi_tpu.control import zrtp as z
    junk = z._wrap(z._msg(b"\xff" * 8, b"pay"), 1, 0)
    assert fresh.feed(junk) == []
    # reflected Confirm2 at the initiator: dropped (wrong role)
    aa, bb = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    run_zrtp(aa, bb)
    conf2 = aa._send(aa._make_confirm(b"Confirm2"))
    assert aa.feed(conf2) == []


def test_zrtp_duplicate_commit_is_idempotent():
    """A duplicated Commit must re-elicit the SAME DHPart1 (a regenerated
    one would fork total_hash between the sides) and still converge."""
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    commit = a.initiate()[0]
    dh1_first = b.feed(commit)[0]
    dh1_dup = b.feed(commit)[0]
    assert dh1_first[12:-4] == dh1_dup[12:-4]   # same message, new seq
    dh2 = a.feed(dh1_first)[0]
    conf1 = b.feed(dh2)[0]
    conf2 = a.feed(conf1)[0]
    b.feed(conf2)
    assert a.complete and b.complete and a.sas == b.sas


def test_zrtp_midhandshake_hello_replacement_ignored():
    """A forged Hello injected after the exchange must not replace the
    pinned first Hello that feeds the key derivation."""
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    pinned = b._peer[b"Hello   "]
    forged_src = ZrtpEndpoint(ssrc=1)
    b.feed(forged_src.hello_packets()[0])
    assert b._peer[b"Hello   "] == pinned
    # handshake still completes with the pinned Hello
    commit = a.initiate()[0]
    dh1 = b.feed(commit)[0]
    dh2 = a.feed(dh1)[0]
    conf1 = b.feed(dh2)[0]
    conf2 = a.feed(conf1)[0]
    b.feed(conf2)
    assert a.complete and b.complete and a.sas == b.sas


def test_sas_encoding():
    assert len(sas_b32(bytes(32))) == 4
    assert sas_b32(bytes.fromhex("ffffffff" + "00" * 28)) != \
        sas_b32(bytes(32))


def test_zrtp_initiate_is_idempotent():
    """Retrying initiate() resends the SAME Commit (a regenerated one
    would fork the hvi commitment the peer pinned)."""
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    c1 = a.initiate()[0]
    c2 = a.initiate()[0]
    assert c1[12:-4] == c2[12:-4]       # same message, new seq/CRC
    dh1 = b.feed(c1)[0]
    dh2 = a.feed(dh1)[0]
    conf1 = b.feed(dh2)[0]
    conf2 = a.feed(conf1)[0]
    b.feed(conf2)
    assert a.complete and b.complete and a.sas == b.sas


def test_zrtp_forged_confirm_after_complete_dropped():
    """A spoofed Confirm2 (valid CRC, random MAC) after completion is
    dropped with an alert — it must not raise into the I/O loop."""
    from libjitsi_tpu.control import zrtp as z
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    run_zrtp(a, b)
    forged = z._wrap(z._msg(b"Confirm2", bytes(40)), 9, 2)
    assert b.feed(forged) == []
    assert any("Confirm MAC" in a_ for a_ in b.alerts)
    assert b.complete                   # session state untouched

def test_zrtp_invalid_ec_point_dropped():
    """A DHPart with a non-curve or truncated public value is dropped
    with an alert, not a ValueError into the I/O loop."""
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    commit = a.initiate()[0]
    dh1 = bytearray(b.feed(commit)[0])
    # corrupt the x coordinate of the EC point (offset: 12B pkt hdr +
    # 12B msg hdr + 32B H1 + 32B rs)
    for i in range(64):
        dh1[12 + 12 + 64 + i] = 0xFF
    assert a.feed(_reseal(bytes(dh1))) == []
    assert any("EC point" in x or "MAC" in x for x in a.alerts)


def test_zrtp_commit_contention_resolves():
    """Both sides commit (glare): the higher hvi wins, the lower backs
    down to responder (RFC 6189 §4.2) and the handshake completes."""
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    ca = a.initiate()[0]
    cb = b.initiate()[0]
    outs_a = a.feed(cb)       # each side sees the other's Commit
    outs_b = b.feed(ca)
    # exactly one side backed down and answered with DHPart1
    roles = sorted([a.role, b.role])
    assert roles == ["initiator", "responder"], roles
    wire = [(a if x is b else b, pkt)
            for x, outs in ((a, outs_a), (b, outs_b)) for pkt in outs]
    # drive to completion
    for _ in range(20):
        nxt = []
        for dst, pkt in wire:
            for out in dst.feed(pkt):
                nxt.append((a if dst is b else b, out))
        wire = nxt
        if a.complete and b.complete:
            break
    assert a.complete and b.complete and a.sas == b.sas
    # loser cannot re-initiate
    loser = a if a.role == "responder" else b
    import pytest
    with pytest.raises(RuntimeError, match="responder"):
        loser.initiate()


def test_zrtp_alerts_bounded():
    from libjitsi_tpu.control import zrtp as z
    a, b = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    run_zrtp(a, b)
    forged = z._wrap(z._msg(b"Confirm2", bytes(40)), 9, 2)
    for _ in range(300):
        b.feed(forged)
    assert len(b.alerts) <= 64


def test_zrtp_retained_secret_continuity_across_sessions():
    """VERDICT r3 #8 (RFC 6189 §4.3/§4.9): a second session between the
    same endpoints mixes the cached retained secret into s0 — key
    continuity holds and the caches rotate in lockstep."""
    from libjitsi_tpu.control.zrtp import ZidCache

    ca, cb = ZidCache(), ZidCache()
    zid_a, zid_b = b"A" * 12, b"B" * 12
    a1 = ZrtpEndpoint(zid=zid_a, ssrc=1, cache=ca)
    b1 = ZrtpEndpoint(zid=zid_b, ssrc=2, cache=cb)
    run_zrtp(a1, b1)
    # first contact: nothing cached yet
    assert not a1.secret_continuity and not b1.secret_continuity
    rs1_a, rs2_a = ca.lookup(zid_b)
    assert rs1_a is not None and rs2_a is None
    assert ca.lookup(zid_b) == cb.lookup(zid_a), "caches must rotate in sync"

    a2 = ZrtpEndpoint(zid=zid_a, ssrc=1, cache=ca)
    b2 = ZrtpEndpoint(zid=zid_b, ssrc=2, cache=cb)
    run_zrtp(a2, b2)
    assert a2.secret_continuity and b2.secret_continuity
    assert a2.srtp_keys()[1] != a1.srtp_keys()[1], "sessions must re-key"
    # rotation: old rs1 shifted to rs2
    assert ca.lookup(zid_b) == (ca.lookup(zid_b)[0], rs1_a)

    # one-generation drift: A lost its newest secret (restored old
    # cache) -> rs2 cross-match still gives continuity
    ca2 = ZidCache.restore({zid_b: (rs1_a, None)})
    a3 = ZrtpEndpoint(zid=zid_a, ssrc=1, cache=ca2)
    b3 = ZrtpEndpoint(zid=zid_b, ssrc=2, cache=cb)
    run_zrtp(a3, b3)
    assert a3.secret_continuity and b3.secret_continuity


def test_zrtp_cache_mismatch_still_completes():
    """A peer with no (or a wrong) cache falls back to a null s1: the
    handshake completes, continuity just reads False on both sides."""
    from libjitsi_tpu.control.zrtp import ZidCache

    ca, cb = ZidCache(), ZidCache()
    zid_a, zid_b = b"C" * 12, b"D" * 12
    run_zrtp(ZrtpEndpoint(zid=zid_a, ssrc=1, cache=ca),
             ZrtpEndpoint(zid=zid_b, ssrc=2, cache=cb))
    a = ZrtpEndpoint(zid=zid_a, ssrc=1, cache=ZidCache())  # lost cache
    b = ZrtpEndpoint(zid=zid_b, ssrc=2, cache=cb)
    run_zrtp(a, b)
    assert not a.secret_continuity and not b.secret_continuity
    pa, atk, ats, ark, ars = a.srtp_keys()
    pb, btk, bts, brk, brs = b.srtp_keys()
    assert (atk, ats) == (brk, brs), "mismatch must not fork the keys"


def test_zrtp_multistream_keys_second_stream_without_dh():
    """RFC 6189 §4.4.3: a second media stream keys off the first
    association's ZRTPSess — Commit(Mult, nonce) -> Confirm, no DH
    round, per-stream keys distinct from the parent's."""
    a1, b1 = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    run_zrtp(a1, b1)
    assert a1.session_key == b1.session_key is not None

    a2 = ZrtpEndpoint(ssrc=3, multistream_from=a1)
    b2 = ZrtpEndpoint(ssrc=4, multistream_from=b1)
    run_zrtp(a2, b2)
    # no DH messages crossed the wire for the second stream
    assert b"DHPart1 " not in a2._peer and b"DHPart2 " not in b2._peer
    pa, atk, ats, ark, ars = a2.srtp_keys()
    pb, btk, bts, brk, brs = b2.srtp_keys()
    assert (atk, ats) == (brk, brs) and (ark, ars) == (btk, bts)
    assert atk != a1.srtp_keys()[1], "per-stream keys must differ"

    # keys drive real SRTP both streams
    tx = SrtpStreamTable(capacity=1, profile=pa)
    tx.add_stream(0, atk, ats)
    rx = SrtpStreamTable(capacity=1, profile=pb)
    rx.add_stream(0, brk, brs)
    pkt = rtp_header.build([b"mult-keyed"], [1], [0], [9], [96],
                           stream=[0])
    dec, ok = rx.unprotect_rtp(tx.protect_rtp(pkt))
    assert ok.all() and dec.to_bytes(0) == pkt.to_bytes(0)

    # a non-multistream endpoint refuses a Mult commit (alert, drop)
    c = ZrtpEndpoint(ssrc=5)
    a3 = ZrtpEndpoint(ssrc=6, multistream_from=a1)
    wire = [(0, p) for p in a3.hello_packets()] + \
           [(1, p) for p in c.hello_packets()]
    for _ in range(4):
        nxt = []
        for who, pkt in wire:
            ep = c if who == 0 else a3
            nxt += [(1 - who, p) for p in ep.feed(pkt)]
        wire = nxt
        if b"Hello   " in a3._peer and a3.role is None:
            wire += [(0, p) for p in a3.initiate()]
    assert not c.complete
    assert any("session key" in s for s in c.alerts)


def test_zrtp_duplicate_confirm_does_not_double_rotate():
    """Retransmitted Confirms must not rotate the retained-secret cache
    twice (a double rotation overwrites both generations with the same
    value, losing the one-generation drift tolerance)."""
    from libjitsi_tpu.control.zrtp import ZidCache

    ca, cb = ZidCache(), ZidCache()
    zid_a, zid_b = b"E" * 12, b"F" * 12
    run_zrtp(ZrtpEndpoint(zid=zid_a, ssrc=1, cache=ca),
             ZrtpEndpoint(zid=zid_b, ssrc=2, cache=cb))
    gen1 = ca.lookup(zid_b)

    a = ZrtpEndpoint(zid=zid_a, ssrc=1, cache=ca)
    b = ZrtpEndpoint(zid=zid_b, ssrc=2, cache=cb)
    # capture + replay every packet once (lossy-path retransmit shape)
    wire = [(0, p) for p in a.hello_packets()] + \
           [(1, p) for p in b.hello_packets()]
    started = False
    for _ in range(30):
        nxt = []
        for who, pkt in wire:
            ep = b if who == 0 else a
            nxt += [(1 - who, p) for p in ep.feed(pkt)]
            nxt += [(1 - who, p) for p in ep.feed(pkt)]   # duplicate
        wire = nxt
        if not started and b"Hello   " in a._peer:
            wire += [(0, p) for p in a.initiate()]
            started = True
        if a.complete and b.complete:
            break
    assert a.complete and b.complete
    rs1, rs2 = ca.lookup(zid_b)
    assert rs2 == gen1[0], "old generation must survive one rotation"
    assert rs1 != rs2
    assert ca.lookup(zid_b) == cb.lookup(zid_a)


def test_zrtp_mult_capable_endpoint_follows_peer_dh_commit():
    """A multistream-capable responder whose peer commits in DH mode
    must key via DH (the negotiated mode, not the constructor flag)."""
    a1, b1 = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    run_zrtp(a1, b1)
    dh_init = ZrtpEndpoint(ssrc=3)                       # plain DH peer
    mult_resp = ZrtpEndpoint(ssrc=4, multistream_from=b1)
    run_zrtp(dh_init, mult_resp)
    assert dh_init.complete and mult_resp.complete
    assert not mult_resp._mult, "wire-negotiated mode must win"
    pa, atk, ats, _, _ = dh_init.srtp_keys()
    _, _, _, brk, brs = mult_resp.srtp_keys()
    assert (atk, ats) == (brk, brs)


def test_zrtp_mult_vs_dh_commit_contention_resolves_to_dh():
    """RFC 6189 §4.2 cross-mode contention: when a Multistream Commit
    races a DH Commit, the DH side wins (a DH peer cannot process Mult)
    and the handshake completes in DH mode."""
    a1, b1 = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    run_zrtp(a1, b1)
    mult = ZrtpEndpoint(ssrc=3, multistream_from=a1)
    dh = ZrtpEndpoint(ssrc=4)
    # both initiate after the hello exchange
    wire = [(0, p) for p in mult.hello_packets()] + \
           [(1, p) for p in dh.hello_packets()]
    committed = False
    for _ in range(30):
        nxt = []
        for who, pkt in wire:
            ep = dh if who == 0 else mult
            nxt += [(1 - who, p) for p in ep.feed(pkt)]
        wire = nxt
        if not committed and b"Hello   " in mult._peer \
                and b"Hello   " in dh._peer:
            wire += [(0, p) for p in mult.initiate()]
            wire += [(1, p) for p in dh.initiate()]
            committed = True
        if mult.complete and dh.complete:
            break
    assert mult.complete and dh.complete, "cross-mode contention wedged"
    assert dh.role == "initiator" and mult.role == "responder"
    assert not mult._mult, "resolved session must be DH mode"
    pa, atk, ats, _, _ = dh.srtp_keys()
    _, _, _, brk, brs = mult.srtp_keys()
    assert (atk, ats) == (brk, brs)


def test_zrtp_multistream_chains_from_mult_endpoint():
    """ZRTPSess is per association: a further stream can key off the
    NEWEST completed endpoint, not only the original DH one."""
    a1, b1 = ZrtpEndpoint(ssrc=1), ZrtpEndpoint(ssrc=2)
    run_zrtp(a1, b1)
    a2 = ZrtpEndpoint(ssrc=3, multistream_from=a1)
    b2 = ZrtpEndpoint(ssrc=4, multistream_from=b1)
    run_zrtp(a2, b2)
    assert a2.session_key == a1.session_key is not None
    a3 = ZrtpEndpoint(ssrc=5, multistream_from=a2)   # chained off mult
    b3 = ZrtpEndpoint(ssrc=6, multistream_from=b2)
    run_zrtp(a3, b3)
    assert a3.srtp_keys()[1] == b3.srtp_keys()[3]
    assert a3.srtp_keys()[1] != a2.srtp_keys()[1]


# ------------------------------------------------ algorithm agility (§4.1.2)

def test_negotiation_converges_with_different_orderings():
    """RFC 6189 §4.1.2 preference intersection: endpoints with DIFFERENT
    orderings converge on ONE suite — the initiator's first preference
    the responder also advertised — and both export identical keys."""
    from libjitsi_tpu.control.zrtp import (
        AUTH_HS32, AUTH_HS80, CIPHER_AES1, CIPHER_AES3, HASH_S256,
        HASH_S384, KA_DH3K, KA_EC25)
    from libjitsi_tpu.transform.srtp import SrtpProfile

    a = ZrtpEndpoint(ssrc=1, algorithms={
        "hash": (HASH_S384, HASH_S256),
        "cipher": (CIPHER_AES3, CIPHER_AES1),
        "auth": (AUTH_HS80, AUTH_HS32),
        "ka": (KA_DH3K, KA_EC25)})
    b = ZrtpEndpoint(ssrc=2, algorithms={
        "hash": (HASH_S256, HASH_S384),
        "cipher": (CIPHER_AES1, CIPHER_AES3),
        "auth": (AUTH_HS32, AUTH_HS80),
        "ka": (KA_EC25, KA_DH3K)})
    run_zrtp(a, b)
    # initiator (a) preference wins on the intersection
    assert a.suite == b.suite
    assert a.suite["hash"] == HASH_S384
    assert a.suite["cipher"] == CIPHER_AES3
    assert a.suite["auth"] == AUTH_HS80
    assert a.suite["ka"] == KA_DH3K
    assert a.sas == b.sas
    pa, aki, asi, akr, asr = a.srtp_keys()
    pb, bki, bsi, bkr, bsr = b.srtp_keys()
    assert pa == pb == SrtpProfile.AES_256_CM_HMAC_SHA1_80
    assert len(aki) == 32                   # AES3 -> 256-bit master key
    assert (aki, asi) == (bkr, bsr) and (akr, asr) == (bki, bsi)


def test_negotiated_keys_drive_srtp_roundtrip_aes256():
    """The negotiated AES-256 suite's exported keys must key working
    SRTP tables (the provider -> table contract, same as SDES/DTLS)."""
    from libjitsi_tpu.control.zrtp import CIPHER_AES1, CIPHER_AES3

    a = ZrtpEndpoint(ssrc=1,
                     algorithms={"cipher": (CIPHER_AES3, CIPHER_AES1)})
    b = ZrtpEndpoint(ssrc=2)
    run_zrtp(a, b)
    prof, tx_k, tx_s, rx_k, rx_s = a.srtp_keys()
    _, btx_k, btx_s, brx_k, brx_s = b.srtp_keys()
    tx = SrtpStreamTable(capacity=1, profile=prof)
    tx.add_stream(0, tx_k, tx_s)
    rx = SrtpStreamTable(capacity=1, profile=prof)
    rx.add_stream(0, brx_k, brx_s)
    wire = tx.protect_rtp(rtp_header.build(
        [b"negotiated-256"], [7], [0], [0xAB], [96], stream=[0]))
    dec, ok = rx.unprotect_rtp(wire)
    assert bool(ok.all())
    assert dec.to_bytes(0)[12:] == b"negotiated-256"


def test_dh3k_fallback_when_peer_lacks_ec25():
    """A peer that only offers DH3k forces the 3072-bit MODP group —
    the handshake still completes and both sides agree."""
    from libjitsi_tpu.control.zrtp import KA_DH3K, KA_EC25

    a = ZrtpEndpoint(ssrc=1)                       # default: EC25 first
    b = ZrtpEndpoint(ssrc=2, algorithms={"ka": (KA_DH3K,)})
    run_zrtp(a, b)
    assert a.suite["ka"] == KA_DH3K == b.suite["ka"]
    assert a.sas == b.sas
    assert a.srtp_keys()[1] == b.srtp_keys()[3]


def test_no_common_algorithm_refuses_commit():
    """Disjoint cipher offers: initiate() must refuse loudly (no
    silent fallback to a suite the peer never advertised)."""
    import pytest

    from libjitsi_tpu.control.zrtp import (CIPHER_AES1, CIPHER_AES3,
                                           ZrtpProtocolError)

    a = ZrtpEndpoint(ssrc=1, algorithms={"cipher": (CIPHER_AES3,)})
    b = ZrtpEndpoint(ssrc=2, algorithms={"cipher": (CIPHER_AES1,)})
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    with pytest.raises(ZrtpProtocolError):
        a.initiate()


def test_commit_with_unoffered_algorithm_rejected():
    """A Commit naming an algorithm the responder never advertised is
    dropped and alerted (downgrade defense)."""
    from libjitsi_tpu.control.zrtp import CIPHER_AES1, CIPHER_AES3

    a = ZrtpEndpoint(ssrc=1)
    b = ZrtpEndpoint(ssrc=2, algorithms={"cipher": (CIPHER_AES1,)})
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    commit = bytearray(a.initiate()[0])
    # forge the cipher code in the Commit: 12B packet header + 12B
    # message header + payload offset 48
    commit[12 + 12 + 48:12 + 12 + 52] = CIPHER_AES3
    replies = b.feed(_reseal(bytes(commit)))
    assert replies == []
    assert any("did not offer" in al or "MAC mismatch" in al
               for al in b.alerts)


def test_commit_contention_dh_vs_dh_different_ka_converges():
    """Both sides commit DH mode with DIFFERENT KA picks (possible with
    KA agility): §4.2's hvi tie-break must apply — exactly one side
    backs down and the handshake completes (review r5: the old
    KA-mismatch branch made both sides 'win' and deadlocked)."""
    from libjitsi_tpu.control.zrtp import KA_DH3K, KA_EC25

    a = ZrtpEndpoint(ssrc=1, algorithms={"ka": (KA_DH3K, KA_EC25)})
    b = ZrtpEndpoint(ssrc=2, algorithms={"ka": (KA_EC25, KA_DH3K)})
    for p in a.hello_packets():
        b.feed(p)
    for p in b.hello_packets():
        a.feed(p)
    # BOTH initiate: contention
    wire = [(0, p) for p in a.initiate()] + [(1, p) for p in b.initiate()]
    rounds = 0
    while (not a.complete or not b.complete) and rounds < 30:
        rounds += 1
        nxt = []
        for who, pkt in wire:
            ep = b if who == 0 else a
            nxt += [(1 - who, p) for p in ep.feed(pkt)]
        wire = nxt
    assert a.complete and b.complete, "contention deadlocked"
    assert {a.role, b.role} == {"initiator", "responder"}
    assert a.suite == b.suite and a.sas == b.sas
    # winner's KA pick is in force on both sides
    assert a.suite["ka"] in (KA_DH3K, KA_EC25)
