"""ConferenceBridge: the whole-conference tick as one object, e2e.

Three SRTP clients over real loopback UDP; each must hear the
mix-minus of the OTHERS (their own tone absent), all through the
batched unprotect -> dense bank -> mixer -> encode -> protect tail.
"""

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.service.bridge import ConferenceBridge
from libjitsi_tpu.service.pump import g711_codec
from libjitsi_tpu.transform.srtp import SrtpStreamTable


class _Client:
    def __init__(self, ssrc, freq, bridge_port):
        self.ssrc = ssrc
        self.freq = freq
        self.codec = g711_codec()
        self.rx_key = (bytes([ssrc]) * 16, bytes([ssrc + 1]) * 14)
        self.tx_key = (bytes([ssrc + 2]) * 16, bytes([ssrc + 3]) * 14)
        self.protect = SrtpStreamTable(capacity=1)
        self.protect.add_stream(0, *self.rx_key)
        self.unprotect = SrtpStreamTable(capacity=1)
        self.unprotect.add_stream(0, *self.tx_key)
        self.engine = UdpEngine(port=0, max_batch=32)
        self.bridge_port = bridge_port
        self.seq = 100
        self.t = 0
        self.heard = []

    def send_frame(self):
        n = np.arange(160)
        pcm = (8000 * np.sin(2 * np.pi * self.freq *
                             (self.t + n) / 8000)).astype(np.int16)
        self.t += 160
        b = rtp_header.build([self.codec.encode(pcm)], [self.seq],
                             [self.t], [self.ssrc], [0], stream=[0])
        self.seq += 1
        self.engine.send_batch(self.protect.protect_rtp(b),
                               "127.0.0.1", self.bridge_port)

    def drain(self):
        back, _, _ = self.engine.recv_batch(timeout_ms=1)
        if back.batch_size:
            back.stream[:] = 0
            dec, ok = self.unprotect.unprotect_rtp(back)
            hdr = rtp_header.parse(dec)
            for i in np.nonzero(ok)[0]:
                pay = dec.to_bytes(int(i))[int(hdr.payload_off[i]):]
                self.heard.append(self.codec.decode(pay))


@pytest.mark.slow
def test_bridge_three_party_mix_minus():
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    bridge = ConferenceBridge(libjitsi_tpu.configuration_service(),
                              port=0, capacity=16, recv_window_ms=0)
    clients = [_Client(10, 400.0, bridge.port),
               _Client(20, 900.0, bridge.port),
               _Client(30, 1600.0, bridge.port)]
    for c in clients:
        bridge.add_participant(c.ssrc, c.rx_key, c.tx_key)

    now = 100.0
    for tick in range(30):
        for c in clients:
            c.send_frame()
        for _ in range(10):       # let the datagrams land
            stats = bridge.tick(now=now)
            if stats["rx"]:
                break
        bridge.tick(now=now + 0.001)   # decode tick (frames due)
        for c in clients:
            c.drain()
        now += 0.020

    for c in clients:
        assert len(c.heard) >= 10, f"ssrc {c.ssrc} heard too little"
        pcm = np.concatenate(c.heard[5:]).astype(np.float64)
        spec = np.abs(np.fft.rfft(pcm * np.hanning(len(pcm))))
        freqs = np.fft.rfftfreq(len(pcm), 1 / 8000.0)

        def power_at(f):
            return spec[np.argmin(np.abs(freqs - f))]

        own = power_at(c.freq)
        others = [power_at(o.freq) for o in clients if o is not c]
        # mix-minus: both other tones clearly present, own tone absent
        assert min(others) > 10 * own, \
            (c.ssrc, own, others)

    # stats2 / counters sanity through the bridge registry
    assert bridge.bank.decoded_frames[:3].sum() > 30
    bridge.close()


def test_bridge_rejects_mismatched_codec_frame():
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    bridge = ConferenceBridge(libjitsi_tpu.configuration_service(),
                              port=0, capacity=4)
    bridge.add_participant(1, (b"\x01" * 16, b"\x02" * 14),
                           (b"\x03" * 16, b"\x04" * 14))
    with pytest.raises(ValueError):
        bridge.add_participant(
            2, (b"\x05" * 16, b"\x06" * 14),
            (b"\x07" * 16, b"\x08" * 14),
            codec=g711_codec(ptime_ms=30))
    bridge.close()


def test_bridge_participant_churn_clears_row_residue():
    """A leave must clear ssrc demux, SRTP rows, and the latched
    address — the recycled sid must not redirect the new occupant's
    media to the old participant's socket."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    bridge = ConferenceBridge(libjitsi_tpu.configuration_service(),
                              port=0, capacity=4, recv_window_ms=0)
    sid = bridge.add_participant(0x10, (b"\x01" * 16, b"\x02" * 14),
                                 (b"\x03" * 16, b"\x04" * 14))
    # simulate a latched address from a received packet
    bridge.loop.addr_ip[sid] = 0x7F000001
    bridge.loop.addr_port[sid] = 55555
    bridge.remove_participant(sid)
    assert bridge.loop.addr_port[sid] == 0
    assert not bridge.rx_table.active[sid]
    assert not bridge.tx_table.active[sid]
    # same ssrc can rejoin; duplicate join is rejected while mapped
    sid2 = bridge.add_participant(0x10, (b"\x05" * 16, b"\x06" * 14),
                                  (b"\x07" * 16, b"\x08" * 14))
    assert sid2 == sid                    # LIFO row recycle
    with pytest.raises(ValueError):
        bridge.add_participant(0x10, (b"\x09" * 16, b"\x0a" * 14),
                               (b"\x0b" * 16, b"\x0c" * 14))
    # empty-tick return shape is stable (levels key always present)
    bridge2 = ConferenceBridge(libjitsi_tpu.configuration_service(),
                               port=0, capacity=4, recv_window_ms=0)
    assert "levels" in bridge2.tick(now=1.0)
    bridge.close()
    bridge2.close()


@pytest.mark.slow
def test_bridge_levels_ext_and_speaker_events():
    """VERDICT r2 #8: egress packets carry the RFC 6465 audio-level
    extension, and the dominant-speaker detector fires change events
    when the loud tone moves to another participant."""
    from libjitsi_tpu.rtp import ext as rtp_ext

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    events = []
    bridge = ConferenceBridge(
        libjitsi_tpu.configuration_service(), port=0, capacity=16,
        recv_window_ms=0,
        on_speaker_change=lambda sid, ssrc: events.append((sid, ssrc)))
    clients = [_Client(10, 400.0, bridge.port),
               _Client(20, 900.0, bridge.port),
               _Client(30, 1600.0, bridge.port)]
    sids = [bridge.add_participant(c.ssrc, c.rx_key, c.tx_key)
            for c in clients]
    amps = {10: 16000, 20: 0, 30: 0}      # client 10 talks first

    ext_seen = []

    def send_frame(c):
        n = np.arange(160)
        pcm = (amps[c.ssrc] * np.sin(2 * np.pi * c.freq *
                                     (c.t + n) / 8000)).astype(np.int16)
        c.t += 160
        b = rtp_header.build([c.codec.encode(pcm)], [c.seq], [c.t],
                             [c.ssrc], [0], stream=[0])
        c.seq += 1
        c.engine.send_batch(c.protect.protect_rtp(b),
                            "127.0.0.1", c.bridge_port)

    def drain_ext(c):
        back, _, _ = c.engine.recv_batch(timeout_ms=1)
        if back.batch_size:
            back.stream[:] = 0
            dec, ok = c.unprotect.unprotect_rtp(back)
            hdr = rtp_header.parse(dec)
            off, _l, found = rtp_ext.find_one_byte_ext(dec, hdr, 1)
            for i in np.nonzero(ok)[0]:
                if found[i]:
                    lvl = int(dec.data[int(i), int(off[int(i)])]) & 0x7F
                    ext_seen.append((c.ssrc, lvl))

    now = 200.0
    for phase, talker in ((0, 10), (1, 20)):
        amps = {s: (16000 if s == talker else 0) for s in amps}
        for tick in range(45):
            for c in clients:
                send_frame(c)
            for _ in range(10):
                if bridge.tick(now=now)["rx"]:
                    break
            bridge.tick(now=now + 0.001)
            for c in clients:
                drain_ext(c)
            now += 0.020
        want = sids[[c.ssrc for c in clients].index(talker)]
        assert bridge.speaker.dominant == want, (phase, talker)

    # both talkers produced a change event, in order
    assert [e[0] for e in events[:2]] == [sids[0], sids[1]]
    assert events[0][1] == 10 and events[1][1] == 20
    # the audio-level ext rode the wire; listeners of the active talker
    # saw loud (low dBov) levels, the talker itself heard silence-ish
    assert ext_seen, "no audio-level extension seen on egress"
    loud_at_listener = [lv for ssrc, lv in ext_seen if ssrc != 10]
    assert min(loud_at_listener) < 30
    bridge.close()


@pytest.mark.slow
def test_bridge_mixed_rate_g711_and_g722():
    """VERDICT r2 #9: a G.711 8 kHz phone and a G.722 16 kHz endpoint
    share one conference; each hears the other's tone at its own rate
    (deposit path upsamples to the bridge clock, egress path resamples
    the mix back down/up per leg)."""
    from libjitsi_tpu.service.pump import g722_codec

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    bridge = ConferenceBridge(libjitsi_tpu.configuration_service(),
                              port=0, capacity=16, recv_window_ms=0)

    class _C16(_Client):
        def __init__(self, ssrc, freq, port):
            super().__init__(ssrc, freq, port)
            self.codec = g722_codec()
            self.rate = 16000

        def send_frame(self):
            n = np.arange(320)
            pcm = (8000 * np.sin(2 * np.pi * self.freq *
                                 (self.t + n) / 16000)).astype(np.int16)
            self.t += 320
            b = rtp_header.build([self.codec.encode(pcm)], [self.seq],
                                 [self.t // 2], [self.ssrc], [9],
                                 stream=[0])
            self.seq += 1
            self.engine.send_batch(self.protect.protect_rtp(b),
                                   "127.0.0.1", self.bridge_port)

    wide = _C16(40, 1800.0, bridge.port)     # 16 kHz leg joins FIRST:
    narrow = _Client(50, 400.0, bridge.port)  # bridge clock = 16 kHz
    narrow.rate = 8000
    bridge.add_participant(wide.ssrc, wide.rx_key, wide.tx_key,
                           codec=g722_codec())
    bridge.add_participant(narrow.ssrc, narrow.rx_key, narrow.tx_key)

    now = 300.0
    for tick in range(40):
        wide.send_frame()
        narrow.send_frame()
        for _ in range(10):
            if bridge.tick(now=now)["rx"]:
                break
        bridge.tick(now=now + 0.001)
        for c in (wide, narrow):
            c.drain()
        now += 0.020

    for c, hear_freq, own_freq in ((wide, 400.0, 1800.0),
                                   (narrow, 1800.0, 400.0)):
        assert len(c.heard) >= 10, f"ssrc {c.ssrc} heard too little"
        pcm = np.concatenate(c.heard[5:]).astype(np.float64)
        spec = np.abs(np.fft.rfft(pcm * np.hanning(len(pcm))))
        freqs = np.fft.rfftfreq(len(pcm), 1.0 / c.rate)

        def power_at(f):
            return spec[np.argmin(np.abs(freqs - f))]

        other, own = power_at(hear_freq), power_at(own_freq)
        assert other > 20 * own, \
            (f"ssrc {c.ssrc}: other tone {other:.0f} !>> own "
             f"{own:.0f} (mix-minus across rates)")
    bridge.close()


@pytest.mark.slow
def test_conference_bridge_snapshot_resume_mid_call():
    """A live G.711 conference checkpoints, tears down, and resumes on
    a new port: mix-minus keeps flowing on continuing SRTP counters and
    replayed pre-snapshot wire is rejected (windows resumed)."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    bridge = ConferenceBridge(libjitsi_tpu.configuration_service(),
                              port=0, capacity=16, recv_window_ms=0)
    clients = [_Client(60, 400.0, bridge.port),
               _Client(70, 900.0, bridge.port),
               _Client(80, 1600.0, bridge.port)]
    for c in clients:
        bridge.add_participant(c.ssrc, c.rx_key, c.tx_key)
    now = 400.0
    for tick in range(8):
        for c in clients:
            c.send_frame()
        for _ in range(10):
            if bridge.tick(now=now)["rx"]:
                break
        bridge.tick(now=now + 0.001)
        for c in clients:
            c.drain()
        now += 0.020

    snap = bridge.snapshot()
    bridge.close()
    bridge2 = ConferenceBridge.restore(
        libjitsi_tpu.configuration_service(), snap, port=0,
        recv_window_ms=0)
    for c in clients:
        c.bridge_port = bridge2.port
        c.heard.clear()
    for tick in range(20):
        for c in clients:
            c.send_frame()              # SRTP counters CONTINUE
        for _ in range(10):
            if bridge2.tick(now=now)["rx"]:
                break
        bridge2.tick(now=now + 0.001)
        for c in clients:
            c.drain()
        now += 0.020

    for c in clients:
        assert len(c.heard) >= 8, \
            f"ssrc {c.ssrc} heard too little post-restore"
        pcm = np.concatenate(c.heard[4:]).astype(np.float64)
        spec = np.abs(np.fft.rfft(pcm * np.hanning(len(pcm))))
        freqs = np.fft.rfftfreq(len(pcm), 1 / 8000.0)

        def power_at(f):
            return spec[np.argmin(np.abs(freqs - f))]

        own = power_at(c.freq)
        others = [power_at(o.freq) for o in clients if o is not c]
        assert min(others) > 3 * own, \
            f"post-restore mix-minus broken for {c.ssrc}"
    # replayed pre-snapshot wire is rejected: the SRTP replay windows
    # moved with the checkpoint (seq 100 was consumed pre-snapshot)
    drops_before = bridge2.chain.drop_counts.get("SrtpTransformEngine",
                                                 0)
    old_tab = SrtpStreamTable(capacity=1)
    old_tab.add_stream(0, *clients[0].rx_key)
    replay = rtp_header.build([b"replayed"], [100], [160], [60], [0],
                              stream=[0])
    clients[0].engine.send_batch(old_tab.protect_rtp(replay),
                                 "127.0.0.1", bridge2.port)
    for _ in range(10):
        bridge2.tick(now=now)
    assert bridge2.chain.drop_counts.get("SrtpTransformEngine", 0) \
        > drops_before, "pre-snapshot replay was not rejected"

    # stateful-codec legs checkpoint as DEGRADED rows (codec re-inits
    # on restore), no longer a refusal — see the opus resume test
    from libjitsi_tpu.service.pump import g722_codec
    b3 = ConferenceBridge(libjitsi_tpu.configuration_service(), port=0,
                          capacity=4, recv_window_ms=0)
    sid = b3.add_participant(0x91, (b"\x01" * 16, b"\x02" * 14),
                             (b"\x03" * 16, b"\x04" * 14),
                             codec=g722_codec())
    s3 = b3.snapshot()
    assert s3["degraded_rows"] == [sid]
    assert s3["codec_name"][sid] == "G722"
    b3.close()
    bridge2.close()


@pytest.mark.slow
def test_bridge_opus_conference_degraded_resume():
    """VERDICT r3 #5: an OPUS conference (stateful C codec on every
    leg) snapshots and resumes: SRTP counters/replay windows carry over
    exactly, codec state re-initializes (decoder PLC warms up, encoder
    restarts clean), and after a bounded startup artifact the mix-minus
    audio is correct again."""
    from libjitsi_tpu.service.pump import opus_codec

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    bridge = ConferenceBridge(libjitsi_tpu.configuration_service(),
                              port=0, capacity=16, recv_window_ms=0)

    class _C48(_Client):
        def __init__(self, ssrc, freq, port):
            super().__init__(ssrc, freq, port)
            self.codec = opus_codec()
            self.rate = 48000

        def send_frame(self):
            n = np.arange(960)
            pcm = (8000 * np.sin(2 * np.pi * self.freq *
                                 (self.t + n) / 48000)).astype(np.int16)
            self.t += 960
            b = rtp_header.build([self.codec.encode(pcm)], [self.seq],
                                 [self.t], [self.ssrc], [111],
                                 stream=[0])
            self.seq += 1
            self.engine.send_batch(self.protect.protect_rtp(b),
                                   "127.0.0.1", self.bridge_port)

    clients = [_C48(0xA1, 400.0, bridge.port),
               _C48(0xB1, 900.0, bridge.port),
               _C48(0xC1, 1600.0, bridge.port)]
    for c in clients:
        bridge.add_participant(c.ssrc, c.rx_key, c.tx_key,
                               codec=opus_codec())
    now = 500.0
    for tick in range(8):
        for c in clients:
            c.send_frame()
        for _ in range(10):
            if bridge.tick(now=now)["rx"]:
                break
        bridge.tick(now=now + 0.001)
        for c in clients:
            c.drain()
        now += 0.020

    snap = bridge.snapshot()
    assert sorted(snap["degraded_rows"]) == sorted(snap["ssrc_of"])
    bridge.close()
    bridge2 = ConferenceBridge.restore(
        libjitsi_tpu.configuration_service(), snap, port=0,
        recv_window_ms=0)
    for c in clients:
        c.bridge_port = bridge2.port
        c.heard.clear()
    for tick in range(24):
        for c in clients:
            c.send_frame()              # SRTP counters CONTINUE
        for _ in range(10):
            if bridge2.tick(now=now)["rx"]:
                break
        bridge2.tick(now=now + 0.001)
        for c in clients:
            c.drain()
        now += 0.020

    for c in clients:
        assert len(c.heard) >= 10, \
            f"ssrc {c.ssrc:#x} heard too little post-restore"
        # bounded startup artifact: skip the PLC/encoder warmup frames,
        # then the spectrum must be a clean mix-minus again
        pcm = np.concatenate(c.heard[6:]).astype(np.float64)
        spec = np.abs(np.fft.rfft(pcm * np.hanning(len(pcm))))
        freqs = np.fft.rfftfreq(len(pcm), 1 / 48000.0)

        def power_at(f):
            return spec[np.argmin(np.abs(freqs - f))]

        own = power_at(c.freq)
        others = [power_at(o.freq) for o in clients if o is not c]
        assert min(others) > 3 * own, \
            f"post-restore opus mix-minus broken for {c.ssrc:#x}"
    # pre-snapshot wire must NOT re-enter (replay windows resumed)
    drops_before = bridge2.chain.drop_counts.get("SrtpTransformEngine",
                                                 0)
    old_tab = SrtpStreamTable(capacity=1)
    old_tab.add_stream(0, *clients[0].rx_key)
    replay = rtp_header.build([b"replayed"], [100], [960], [0xA1],
                              [111], stream=[0])
    clients[0].engine.send_batch(old_tab.protect_rtp(replay),
                                 "127.0.0.1", bridge2.port)
    for _ in range(10):
        bridge2.tick(now=now)
    assert bridge2.chain.drop_counts.get("SrtpTransformEngine", 0) \
        > drops_before, "pre-snapshot replay was not rejected"
    bridge2.close()
