"""Dominant speaker detection, jitter buffer, recorder/synchronizer."""

import json
import os

import numpy as np

from libjitsi_tpu.conference.speaker import DominantSpeakerIdentification
from libjitsi_tpu.recording import Recorder, Synchronizer
from libjitsi_tpu.rtp.jitter_buffer import JitterBuffer
from libjitsi_tpu.rtp.rtcp import SenderReport
from libjitsi_tpu.rtp.stats import NTP_EPOCH_OFFSET
from libjitsi_tpu.io.pcap import RtpdumpReader


# ------------------------------------------------------------ speaker ---

def test_dominant_speaker_switches_with_hysteresis():
    changes = []
    dsi = DominantSpeakerIdentification(capacity=4,
                                        on_change=changes.append)
    for s in range(3):
        dsi.add_participant(s)
    lv = np.full(4, 127, np.uint8)
    # participant 0 speaks
    lv[0] = 20
    for _ in range(30):
        dsi.levels(lv)
    assert dsi.dominant == 0
    # brief noise from 1 must NOT switch
    lv2 = lv.copy()
    lv2[1] = 25
    dsi.levels(lv2)
    assert dsi.dominant == 0
    # sustained speech from 1 while 0 goes quiet: switch
    lv3 = np.full(4, 127, np.uint8)
    lv3[1] = 15
    for _ in range(200):
        dsi.levels(lv3)
    assert dsi.dominant == 1
    assert changes == [0, 1]


def test_dominant_speaker_leaves():
    dsi = DominantSpeakerIdentification(capacity=2)
    dsi.add_participant(0)
    lv = np.array([10, 127], np.uint8)
    for _ in range(20):
        dsi.levels(lv)
    assert dsi.dominant == 0
    dsi.remove_participant(0)
    assert dsi.dominant == -1


# ------------------------------------------------------- jitter buffer ---

def test_jitter_buffer_reorders():
    jb = JitterBuffer(clock_rate=8000, min_delay_ms=0)
    t = 0.0
    jb.insert(11, 160, b"b", t + 0.001)   # arrives first but is second
    jb.insert(10, 0, b"a", t + 0.002)
    out = [jb.pop(t + 0.01), jb.pop(t + 0.01)]
    assert out == [b"a", b"b"]
    assert jb.lost == 0


def test_jitter_buffer_declares_loss_and_moves_on():
    jb = JitterBuffer(clock_rate=8000, frame_ms=20, max_delay_ms=40)
    jb.insert(5, 0, b"p5", 0.0)
    assert jb.pop(0.1) == b"p5"
    # p6 lost; p7 arrives
    jb.insert(7, 320, b"p7", 0.12)
    assert jb.pop(0.125) is None          # still waiting for 6
    got = jb.pop(0.4)                     # gap timer expired
    assert got == b"p7"
    assert jb.lost == 1
    # a very late p6 now gets dropped
    jb.insert(6, 160, b"p6", 0.5)
    assert jb.late_dropped == 1


def test_jitter_buffer_adapts_depth():
    jb = JitterBuffer(clock_rate=8000, min_delay_ms=0, max_delay_ms=500)
    # feed steadily varying arrival offsets -> jitter grows
    for i in range(50):
        jitter = 0.03 if i % 2 else 0.0
        jb.insert(i, i * 160, b"x", i * 0.02 + jitter)
        jb.pop(i * 0.02 + 0.25)
    assert jb.target_delay > 0.01


# ------------------------------------------------------------ recorder ---

def test_synchronizer_maps_rtp_to_wall_clock():
    s = Synchronizer()
    sr = SenderReport(ssrc=7, ntp_sec=NTP_EPOCH_OFFSET + 1000, ntp_frac=0,
                      rtp_ts=48000, packet_count=0, octet_count=0,
                      reports=[])
    s.on_sender_report(7, sr, clock_rate=48000)
    # one second of RTP time later
    assert abs(s.wall_time(7, 96000) - 1001.0) < 1e-6
    # half a second before the SR
    assert abs(s.wall_time(7, 24000) - 999.5) < 1e-6
    assert s.wall_time(99, 0) is None


def test_recorder_writes_rtpdump_and_events(tmp_path):
    d = str(tmp_path / "rec")
    r = Recorder(d)
    pkts = [b"\x80\x00" + bytes([i]) * 16 for i in range(3)]
    for i, p in enumerate(pkts):
        r.write_rtp(0xABC, p, ts=r._started + 0.02 * i)
    r.on_speaker_change(0xABC)
    meta = r.close()
    got = [x[1] for x in RtpdumpReader(os.path.join(d, "00000abc.rtpdump"))]
    assert got == pkts
    events = json.load(open(meta))["events"]
    kinds = [e["type"] for e in events]
    assert kinds == ["RECORDING_STARTED", "STREAM_STARTED",
                     "SPEAKER_CHANGED", "RECORDING_ENDED"]


def test_recorder_mixed_audio_wav(tmp_path):
    """RecorderImpl parity: the conference mix lands in a playable WAV."""
    import wave

    from libjitsi_tpu.recording.recorder import Recorder

    rec = Recorder(str(tmp_path / "conf"))
    rec.enable_audio(sample_rate=8000)
    tone = (3000 * np.sin(2 * np.pi * 440 / 8000
                          * np.arange(8000))).astype(np.int16)
    for k in range(0, 8000, 160):
        rec.write_mixed_audio(tone[k:k + 160])
    meta = rec.close()
    path = tmp_path / "conf" / "conference.wav"
    with wave.open(str(path), "rb") as w:
        assert w.getnchannels() == 1
        assert w.getframerate() == 8000
        assert w.getsampwidth() == 2
        assert w.getnframes() == 8000
        got = np.frombuffer(w.readframes(8000), dtype="<i2")
    assert np.array_equal(got, tone)
    import json as _json
    events = _json.load(open(meta))["events"]
    assert any(e["type"] == "AUDIO_RECORDING_STARTED" for e in events)
