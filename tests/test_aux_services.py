"""File access / resources / audio notifier substrate services."""

import os

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.service.aux_services import (AudioNotifierService,
                                               FileAccessService,
                                               ResourceManagementService)


def test_file_access_scoped(tmp_path):
    from libjitsi_tpu.core.config import ConfigurationService

    cfg = ConfigurationService({"libjitsi_tpu.data_dir": str(tmp_path)})
    fas = FileAccessService(cfg)
    assert fas.data_dir == str(tmp_path)
    p = fas.get_private_file("logs/pkt.pcap")
    assert p.startswith(str(tmp_path)) and os.path.isdir(os.path.dirname(p))
    t = fas.create_temp_file(suffix=".webm")
    assert os.path.exists(t) and t.startswith(str(tmp_path))
    with pytest.raises(ValueError):
        fas.get_private_file("../escape")


def test_file_access_relative_data_dir(tmp_path, monkeypatch):
    from libjitsi_tpu.core.config import ConfigurationService

    monkeypatch.chdir(tmp_path)
    fas = FileAccessService(ConfigurationService(
        {"libjitsi_tpu.data_dir": "var/data"}))
    p = fas.get_private_file("x.bin")          # must not false-positive
    assert p == str(tmp_path / "var" / "data" / "x.bin")


def test_default_data_dir_is_private(tmp_path):
    fas = FileAccessService()
    assert os.path.isdir(fas.data_dir)
    assert (os.stat(fas.data_dir).st_mode & 0o077) == 0  # mkdtemp 0700


def test_resources_lookup():
    rms = ResourceManagementService({"srtp.window": 64})
    assert rms.get_setting("srtp.window") == 64
    assert rms.get_setting("absent", "d") == "d"
    rms.register("greeting", 5)
    assert rms.get_string("greeting") == "5"
    assert rms.get_string("absent") is None


def test_audio_notifier_renders_tone_and_mute():
    n = AudioNotifierService()
    pcm = n.play(880.0, duration_s=0.05, sample_rate=8000)
    assert pcm.dtype == np.int16 and len(pcm) == 400 and pcm.any()
    n.set_mute(True)
    assert len(n.play()) == 0


def test_libjitsi_service_accessors(tmp_path):
    libjitsi_tpu.init({"libjitsi_tpu.data_dir": str(tmp_path)})
    try:
        assert libjitsi_tpu.file_access_service().data_dir == str(tmp_path)
        assert libjitsi_tpu.resource_management_service() is \
            libjitsi_tpu.resource_management_service()
        pcm = libjitsi_tpu.audio_notifier_service().play(duration_s=0.01)
        assert len(pcm) == 480
    finally:
        libjitsi_tpu.stop()
