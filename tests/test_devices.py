"""Device framework: synthetic sources/sinks, selection, hotplug, fixtures."""

import numpy as np
import pytest

from libjitsi_tpu.core.config import ConfigurationService
from libjitsi_tpu.device import (AudioMixerMediaDevice, AudioSystem, DataFlow,
                                 DeviceSystem, IvfReader, IvfWriter,
                                 MediaDevice, NoiseSource, NullSink,
                                 PcmFileSink, PcmFileSource,
                                 RtpdumpCaptureDevice, SilenceSource,
                                 ToneSource, WavFileSink)


def test_silence_and_noise_sources():
    assert not SilenceSource().read(480).any()
    n1, n2 = NoiseSource(seed=7), NoiseSource(seed=7)
    a, b = n1.read(480), n2.read(480)
    assert np.array_equal(a, b) and a.dtype == np.int16 and a.any()


def test_tone_source_is_phase_continuous():
    src = ToneSource(1000.0, amplitude=0.5, sample_rate=48000)
    chunks = np.concatenate([src.read(160) for _ in range(6)])
    whole = ToneSource(1000.0, amplitude=0.5, sample_rate=48000).read(960)
    assert np.array_equal(chunks, whole)
    # spectral peak at 1 kHz
    spec = np.abs(np.fft.rfft(whole.astype(np.float64)))
    assert abs(np.argmax(spec[1:]) + 1 - round(1000 * 960 / 48000)) <= 1


def test_pcm_file_source_raw_loop_and_pad(tmp_path):
    pcm = np.arange(-100, 100, dtype=np.int16)
    p = tmp_path / "a.pcm"
    p.write_bytes(pcm.tobytes())
    src = PcmFileSource(str(p))
    got = src.read(300)
    assert np.array_equal(got[:200], pcm) and not got[200:].any()
    looped = PcmFileSource(str(p), loop=True).read(500)
    assert np.array_equal(looped[:400], np.tile(pcm, 2))


def test_wav_roundtrip(tmp_path):
    p = str(tmp_path / "t.wav")
    sink = WavFileSink(p, sample_rate=16000)
    tone = ToneSource(440.0, sample_rate=16000).read(1600)
    sink.write(tone)
    sink.close()
    src = PcmFileSource(p)
    assert src.sample_rate == 16000
    assert np.array_equal(src.read(1600), tone)


def test_pcm_sink_and_null_sink(tmp_path):
    p = str(tmp_path / "o.pcm")
    s = PcmFileSink(p)
    s.write(np.array([1, -2, 3], dtype=np.int16))
    s.close()
    assert np.array_equal(np.fromfile(p, dtype="<i2"), [1, -2, 3])
    n = NullSink()
    n.write(np.zeros(480, np.int16))
    assert n.samples_written == 480


def test_audio_system_selection_persists():
    cfg = ConfigurationService()
    sys1 = AudioSystem(cfg)
    names = [d.name for d in sys1.devices(DataFlow.CAPTURE)]
    assert names == ["silence", "tone:440", "noise"]
    assert sys1.selected_device(DataFlow.CAPTURE).name == "silence"
    sys1.set_selected_device(DataFlow.CAPTURE, "noise")
    # a fresh system over the same config restores the choice
    assert AudioSystem(cfg).selected_device(
        DataFlow.CAPTURE).name == "noise"
    with pytest.raises(KeyError):
        sys1.set_selected_device(DataFlow.CAPTURE, "mic-that-does-not-exist")


def test_hotplug_events_and_removal():
    cfg = ConfigurationService()
    ds = DeviceSystem(cfg)
    events = []
    ds.audio.add_listener(events.append)
    dev = MediaDevice("file:cap", "audio", "sendonly",
                      source_factory=SilenceSource)
    ds.audio.add_device(dev, DataFlow.CAPTURE)
    ds.audio.set_selected_device(DataFlow.CAPTURE, "file:cap")
    ds.audio.remove_device("file:cap", DataFlow.CAPTURE)
    assert "added:capture:file:cap" in events
    assert "removed:capture:file:cap" in events
    # selection fell back to the default after the unplug
    assert ds.audio.selected_device(DataFlow.CAPTURE).name == "silence"
    ds.reinitialize()
    assert events[-1] == "initialized"
    # re-init restored the builtin set
    assert len(ds.audio.devices(DataFlow.CAPTURE)) == 3


def test_hotplug_preserves_app_devices():
    sys_ = AudioSystem(ConfigurationService())
    dev = MediaDevice("file:cap", "audio", "sendonly",
                      source_factory=SilenceSource)
    sys_.add_device(dev, DataFlow.CAPTURE)
    sys_.set_selected_device(DataFlow.CAPTURE, "file:cap")
    sys_.initialize()                    # hotplug rescan
    assert any(d.name == "file:cap"
               for d in sys_.devices(DataFlow.CAPTURE))
    assert sys_.selected_device(DataFlow.CAPTURE).name == "file:cap"


def test_rtpdump_capture_device_paced_and_looped(tmp_path):
    from libjitsi_tpu.io.pcap import RtpdumpWriter

    p = str(tmp_path / "t.rtpdump")
    w = RtpdumpWriter(p, start=100.0)
    for i, off in enumerate([0.0, 0.020, 0.040]):
        w.write(bytes([0x80, 96, 0, i]) + b"\x00" * 8, ts=100.0 + off)
    w.close()

    dev = RtpdumpCaptureDevice(p)
    assert [b[3] for b in dev.due(0)] == [0]
    assert [b[3] for b in dev.due(39)] == [1]
    assert [b[3] for b in dev.due(1000)] == [2]
    assert dev.due(2000) == []

    looped = RtpdumpCaptureDevice(p, loop=True)
    seq = [b[3] for b in looped.due(100)]     # one full pass + rewound head
    assert seq[:4] == [0, 1, 2, 0]


def test_rtpdump_loop_is_bounded(tmp_path):
    from libjitsi_tpu.io.pcap import RtpdumpWriter

    p = str(tmp_path / "t.rtpdump")
    w = RtpdumpWriter(p, start=0.0)
    for i in range(3):
        w.write(bytes([0x80, 96, 0, i]) + b"\x00" * 8, ts=0.020 * i)
    w.close()
    dev = RtpdumpCaptureDevice(p, loop=True, max_packets=10)
    got = dev.due(10 ** 12)    # absurd jump must not hang or OOM
    assert len(got) == 10
    # the stream continues coherently on the next call
    assert [b[3] for b in dev.due(10 ** 12)][:3] == [1, 2, 0]


def test_mixer_device_queue_bounded():
    from libjitsi_tpu.conference import AudioMixer

    dev = AudioMixerMediaDevice(AudioMixer(capacity=4, frame_samples=160))
    dev.add_participant(0)
    dev.add_participant(1)
    for _ in range(dev.MAX_QUEUED_FRAMES + 20):
        dev.push(1, np.ones(160, np.int16))
        dev.tick()
    assert len(dev._out[0]) == dev.MAX_QUEUED_FRAMES


def test_ivf_truncated_tail_dropped(tmp_path):
    p = str(tmp_path / "trunc.ivf")
    w = IvfWriter(p, 64, 64)
    w.write(b"\xaa" * 30, 0)
    w.write(b"\xbb" * 40, 1)
    w.close()
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-25])     # cut mid-way through frame 1
    assert [pts for pts, _ in IvfReader(p)] == [0]


def test_ivf_roundtrip(tmp_path):
    p = str(tmp_path / "v.ivf")
    w = IvfWriter(p, 320, 240, fourcc=b"VP80", timebase=(1, 30))
    frames = [(0, b"\x10" * 50), (1, b"\x20" * 9), (2, b"\x30" * 120)]
    for pts, data in frames:
        w.write(data, pts)
    w.close()
    r = IvfReader(p)
    assert (r.width, r.height, r.fourcc, r.frame_count) == \
        (320, 240, b"VP80", 3)
    assert [(pts, d) for pts, d in r] == frames
    with pytest.raises(ValueError):
        bad = tmp_path / "bad.ivf"
        bad.write_bytes(b"nope")
        IvfReader(str(bad))


def test_mixer_media_device_mix_minus():
    from libjitsi_tpu.conference import AudioMixer

    F = 160
    dev = AudioMixerMediaDevice(AudioMixer(capacity=8, frame_samples=F))
    srcs = {sid: NoiseSource(seed=sid, amplitude=0.1) for sid in (0, 1, 2)}
    frames = {sid: s.read(F) for sid, s in srcs.items()}
    caps = {sid: dev.capture_for(sid) for sid in srcs}
    for sid, f in frames.items():
        dev.push(sid, f)
    dev.tick()
    total = sum(f.astype(np.int64) for f in frames.values())
    for sid in srcs:
        want = np.clip(total - frames[sid], -32768, 32767).astype(np.int16)
        assert np.array_equal(caps[sid].read(F), want)
    # no further frames queued -> silence pad
    assert not caps[0].read(F).any()


def test_media_service_exposes_devices():
    import libjitsi_tpu

    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        ds = svc.device_system
        assert ds is svc.device_system  # cached
        assert ds.audio.selected_device(DataFlow.PLAYBACK).name == "null"
        mixdev = svc.audio_mixer_device(frame_samples=480)
        mixdev.add_participant(0)
        mixdev.push(0, np.zeros(480, np.int16))
        out, levels = mixdev.tick()
        assert out.shape[1] == 480 and levels[0] == 127
    finally:
        libjitsi_tpu.stop()
