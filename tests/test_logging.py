"""Structured logging: level guards, k=v fields, token-bucket limiting."""

import logging

import pytest

from libjitsi_tpu.utils.logging import MediaLogger, configure, get_logger


def _capture(name):
    records = []

    class H(logging.Handler):
        def emit(self, r):
            records.append(r.getMessage())

    lg = logging.getLogger(f"libjitsi_tpu.{name}")
    lg.setLevel(logging.DEBUG)
    lg.addHandler(H())
    return records


def test_structured_fields_and_levels():
    log = MediaLogger("t1")
    records = _capture("t1")
    log.warn("auth_fail", sid=7, seq=1234, reason="bad tag")
    assert records == ["auth_fail sid=7 seq=1234 reason=bad tag"]
    assert log.debug_enabled          # handler set DEBUG
    log.debug("x", a=1)
    assert records[-1] == "x a=1"


def test_rate_limit_suppresses_floods_and_reports():
    log = MediaLogger("t2", rate_hz=1000.0, burst=5)
    records = _capture("t2")
    t = 100.0
    for i in range(50):
        log._emit(logging.WARNING, "flood", {"i": i}, now=t)
    assert len(records) == 5          # burst only; 45 suppressed
    t += 0.01                          # 10 ms at 1000 Hz -> 10 tokens
    log._emit(logging.WARNING, "flood", {"i": 99}, now=t)
    assert records[-1] == "flood i=99 suppressed=45"
    # independent sites do not share buckets
    log._emit(logging.WARNING, "other", {}, now=t)
    assert records[-1].startswith("other")


def test_level_guard_skips_rate_accounting():
    log = MediaLogger("t3")
    logging.getLogger("libjitsi_tpu.t3").setLevel(logging.ERROR)
    log.warn("nope", a=1)             # below level: no site created
    assert "nope" not in log._sites


def test_get_logger_shared():
    assert get_logger("shared") is get_logger("shared")
