"""Media pumps: device -> codec -> SRTP stream -> jitter buffer -> mixer.

Exercises the reference's full send/receive call stacks (SURVEY §3.2,
§3.3) end to end with synthetic devices, G.711/G.722 codecs, SDES-keyed
SRTP, and the conference mixer.
"""

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.device import NullSink, ToneSource
from libjitsi_tpu.service.pump import (ReceivePump, SendPump, g711_codec,
                                       g722_codec)


def _keyed_pair(svc):
    a = svc.create_media_stream("audio")
    b = svc.create_media_stream("audio")
    answer = b.sdes.create_answer(a.sdes.create_offer())
    a.sdes.accept_answer(answer)
    a.set_remote_ssrc(b.local_ssrc)
    b.set_remote_ssrc(a.local_ssrc)
    a.start(); b.start()
    return a, b


def test_send_pump_produces_protected_rtp():
    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        a, b = _keyed_pair(svc)
        codec = g711_codec(ulaw=True)
        pump = SendPump(a, ToneSource(440.0, sample_rate=8000), codec)
        wire = pump.tick()
        assert len(wire) == 1 and len(wire[0]) == 12 + 160 + 10  # +tag
        batch, ok = b.receive(wire)
        assert all(ok)
    finally:
        libjitsi_tpu.stop()


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_send_receive_pump_g722_roundtrip():
    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        a, b = _keyed_pair(svc)
        codec_tx, codec_rx = g722_codec(), g722_codec()
        src = ToneSource(800.0, sample_rate=16000)
        sink = NullSink()
        tx = SendPump(a, src, codec_tx)
        rx = ReceivePump(b, codec_rx, sink=sink)
        t = 1000.0
        for i in range(10):
            rx.push(tx.tick(), now=t)
            pcm = rx.tick(now=t)        # zero target delay: due at once
            assert pcm.shape == (320,)
            t += 0.020
        assert rx.decoded_frames == 10 and rx.jb.lost == 0
        assert sink.samples_written == 3200
    finally:
        libjitsi_tpu.stop()


def test_pump_loss_plays_silence_and_recovers():
    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        a, b = _keyed_pair(svc)
        tx = SendPump(a, ToneSource(440.0, sample_rate=8000),
                      g711_codec())
        rx = ReceivePump(b, g711_codec())
        t = 1000.0
        frames = [tx.tick() for _ in range(6)]
        lost = frames[2]                # drop one packet in transit
        for i, f in enumerate(frames):
            if i != 2:
                rx.push(f, now=t + 0.001 * i)
        outs = []
        for i in range(6):
            outs.append(rx.tick(now=t + 0.5 + 0.020 * i))
        assert rx.decoded_frames == 5
        silence = [o for o in outs if not o.any()]
        assert len(silence) >= 1        # the gap played as silence
    finally:
        libjitsi_tpu.stop()


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_conference_via_pumps_three_parties():
    """3 participants: send pumps -> receive pumps -> mixer device; each
    hears the other two (mix-minus)."""
    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        mixdev = svc.audio_mixer_device(frame_samples=160)
        freqs = {0: 350.0, 1: 800.0, 2: 1300.0}
        pairs = {}
        for sid in freqs:
            s, r = _keyed_pair(svc)
            tx = SendPump(s, ToneSource(freqs[sid], sample_rate=8000),
                          g711_codec())
            rx = ReceivePump(r, g711_codec(), mixer=mixdev,
                             mixer_sid=sid)
            mixdev.add_participant(sid)
            pairs[sid] = (tx, rx)
        caps = {sid: mixdev.capture_for(sid) for sid in freqs}
        t = 1000.0
        decoded = {sid: [] for sid in freqs}
        for i in range(5):
            for sid, (tx, rx) in pairs.items():
                rx.push(tx.tick(), now=t)
                decoded[sid].append(rx.tick(now=t))
            mixdev.tick()
            t += 0.020
        # verify one frame of mix-minus equality
        for sid in freqs:
            got = np.concatenate(
                [caps[sid].read(160) for _ in range(5)]).astype(np.int64)
            want_frames = []
            for i in range(5):
                tot = sum(decoded[s][i].astype(np.int64) for s in freqs)
                want_frames.append(
                    np.clip(tot - decoded[sid][i], -32768, 32767))
            assert np.array_equal(got, np.concatenate(want_frames))
    finally:
        libjitsi_tpu.stop()


def test_pump_gsm_and_speex_roundtrip():
    from libjitsi_tpu.codecs import gsm_available, speex_available
    from libjitsi_tpu.service.pump import gsm_codec, speex_codec

    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        cases = []
        if gsm_available():
            cases.append((gsm_codec, 8000, 160))
        if speex_available():
            cases.append((lambda: speex_codec("wb"), 16000, 320))
        if not cases:
            pytest.skip("no gsm/speex libs present")
        for make, rate, n in cases:
            a, b = _keyed_pair(svc)
            tx = SendPump(a, ToneSource(440.0, sample_rate=rate), make())
            rx = ReceivePump(b, make(), sink=NullSink())
            t = 2000.0
            for _ in range(5):
                rx.push(tx.tick(), now=t)
                pcm = rx.tick(now=t)
                assert pcm.shape == (n,)
                t += 0.020
            assert rx.decoded_frames == 5
            # tail of the stream carries real audio (codec warmup aside)
            assert np.abs(pcm.astype(np.int32)).max() > 200
    finally:
        libjitsi_tpu.stop()


def test_pump_survives_malformed_payload():
    """A malformed (authenticated) payload plays silence, never crashes."""
    from libjitsi_tpu.codecs import gsm_available
    from libjitsi_tpu.service.pump import gsm_codec

    if not gsm_available():
        pytest.skip("no libgsm")
    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        a, b = _keyed_pair(svc)
        rx = ReceivePump(b, gsm_codec())
        wire = a.send([b"\x01" * 32], pt=3)    # not a multiple of 33B
        rx.push(wire, now=70.0)
        pcm = rx.tick(now=71.0)
        assert not pcm.any() and rx.decode_errors == 1
    finally:
        libjitsi_tpu.stop()


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_receive_pump_clamps_oversize_payload():
    """A remote peer sending over-long payloads must not crash the tick."""
    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        a, b = _keyed_pair(svc)
        mixdev = svc.audio_mixer_device(frame_samples=160)
        mixdev.add_participant(0)
        rx = ReceivePump(b, g711_codec(), mixer=mixdev, mixer_sid=0)
        wire = a.send([b"\xff" * 200], pt=0)   # 200 > 160 samples
        rx.push(wire, now=50.0)
        pcm = rx.tick(now=51.0)
        assert pcm.shape == (160,)
    finally:
        libjitsi_tpu.stop()


def test_send_pump_rejects_rate_mismatch():
    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        a, _ = _keyed_pair(svc)
        with pytest.raises(ValueError):
            SendPump(a, ToneSource(440.0, sample_rate=48000),
                     g711_codec())
    finally:
        libjitsi_tpu.stop()


def test_g722_pump_codec_is_stateful_across_frames():
    """G.722 is sub-band ADPCM: predictor state must persist per stream.

    The pump codec's output over two consecutive frames must equal one
    continuous stateful encode of the concatenated PCM (and differ from
    what per-frame reset encoders would produce)."""
    from libjitsi_tpu.codecs.g722 import G722Decoder, G722Encoder
    from libjitsi_tpu.codecs.g722 import encode as oneshot_encode
    from libjitsi_tpu.service.pump import g722_codec

    rng = np.random.default_rng(3)
    pcm = rng.integers(-8000, 8000, 640, dtype=np.int16)
    c = g722_codec()
    f1, f2 = c.encode(pcm[:320]), c.encode(pcm[320:])
    ref = G722Encoder(1).encode(pcm.reshape(1, -1))[0].tobytes()
    assert f1 + f2 == ref
    assert f2 != oneshot_encode(pcm[320:])   # reset-per-frame is wrong

    d = g722_codec()
    out = np.concatenate([d.decode(f1), d.decode(f2)])
    refd = G722Decoder(1).decode(
        np.frombuffer(ref, np.uint8).reshape(1, -1))[0]
    assert np.array_equal(out, refd)


def test_codec_from_name_rebuilds_receive_only_legs():
    """Checkpoint restore must rebuild receive-only codec legs (G.729 /
    iLBC decode via libavcodec) — a conference with such a leg would
    otherwise snapshot fine and then fail at restore time, when the
    original bridge is gone (advisor r4, medium)."""
    import numpy as np
    import pytest

    from libjitsi_tpu.service.pump import codec_from_name

    try:
        g729 = codec_from_name("G729", 20)
        ilbc = codec_from_name("iLBC", 20)
    except Exception:
        pytest.skip("libavcodec without G.729/iLBC decoders")
    # decode-only semantics preserved: decode works, encode refuses
    assert g729.name == "G729" and ilbc.name == "iLBC"
    pcm = g729.decode(b"\x00" * 20)   # 2 x 10 ms frames = one ptime
    assert np.asarray(pcm).shape[-1] == g729.frame_samples
    with pytest.raises(RuntimeError):
        g729.encode(np.zeros(g729.frame_samples, np.int16))
    with pytest.raises(RuntimeError):
        ilbc.encode(np.zeros(ilbc.frame_samples, np.int16))
