"""Performance-attribution plane: PhaseProfiler phase ledger,
HistogramVec exposition, compile-cache stats, and the trace_report
occupancy analyzer.

The load-bearing property: on a SAMPLED tick the six phases sum to the
tick's wall time exactly (host_python is the clamped residual), and on
a fence-free tick the profiler adds ZERO probe overhead — steady-state
ticks must not pay for attribution.
"""

import gzip
import json
import os
import sys
import time

import pytest

from libjitsi_tpu.utils.compile_cache import CompileCacheStats
from libjitsi_tpu.utils.metrics import (MetricsRegistry,
                                        validate_exposition)
from libjitsi_tpu.utils.perf import (DEVICE_PHASES, HOST_PHASES, PHASES,
                                     PhaseProfiler, classify_bound,
                                     host_share)
from libjitsi_tpu.utils.tracing import PipelineTracer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))


# ------------------------------------------------------- phase ledger

def test_sampled_tick_phases_sum_to_wall():
    prof = PhaseProfiler(sample_every=1)
    t0 = time.perf_counter()
    prof.begin_tick()
    with prof.phase("idle"):
        time.sleep(0.004)
    with prof.phase("device_compute"):
        time.sleep(0.002)
    prof.end_tick()
    outer_wall = time.perf_counter() - t0
    phases = prof.last_phases
    assert set(phases) == set(PHASES)
    assert phases["idle"] >= 0.004
    assert phases["device_compute"] >= 0.002
    assert phases["host_python"] >= 0.0
    total = sum(phases.values())
    # the six phases sum to the profiler's wall: bounded above by the
    # outer measurement and below by what we provably slept
    assert 0.006 <= total <= outer_wall + 1e-4
    # residual construction: total - explicit spans == host_python
    explicit = phases["idle"] + phases["device_compute"]
    assert phases["host_python"] == pytest.approx(total - explicit)


def test_unsampled_ticks_are_fence_free():
    prof = PhaseProfiler(sample_every=0)
    prof.begin_tick()
    with prof.phase("device_compute"):
        time.sleep(0.001)
    prof.probe_h2d([None])
    prof.fence(object())
    prof.note_h2d(100)
    prof.note_d2h(50)
    prof.end_tick()
    assert prof.probe_overhead_s == 0.0
    assert prof.last_phases == {}
    assert prof.sampled_ticks == 0
    # byte accounting stays live even with fencing disabled
    assert prof.h2d_bytes == 100 and prof.d2h_bytes == 50


def test_sample_every_n_selects_first_tick_of_each_window():
    prof = PhaseProfiler(sample_every=16)
    sampled_at = []
    for t in range(1, 41):
        prof.begin_tick()
        if prof.sampled:
            sampled_at.append(t)
        prof.end_tick()
    assert sampled_at == [1, 17, 33]
    assert prof.sampled_ticks == 3


def test_fence_counts_into_named_phase_and_overhead():
    class SlowPending:
        def block_until_ready(self):
            time.sleep(0.003)

    prof = PhaseProfiler(sample_every=1)
    prof.begin_tick()
    prof.fence(SlowPending(), phase="d2h_transfer")
    prof.end_tick()
    assert prof.last_phases["d2h_transfer"] >= 0.003
    assert prof.probe_overhead_s >= 0.003


def test_phase_ledger_reaches_tracer_and_drains_once():
    tracer = PipelineTracer()
    prof = PhaseProfiler(sample_every=1, tracer=tracer)
    prof.begin_tick()
    with prof.phase("dispatch"):
        time.sleep(0.001)
    prof.end_tick()
    led = tracer.take_phase_ledger()
    assert led["dispatch"] >= 0.001
    assert tracer.take_phase_ledger() == {}         # drained
    assert tracer.last_phase_ledger == led          # but remembered


def test_phase_totals_accumulate_across_sampled_ticks():
    prof = PhaseProfiler(sample_every=1)
    for _ in range(3):
        prof.begin_tick()
        with prof.phase("idle"):
            time.sleep(0.001)
        prof.end_tick()
    assert prof.phase_totals["idle"] >= 0.003


def test_classify_bound_and_host_share():
    host = {"host_python": 0.01, "dispatch": 0.004,
            "device_compute": 0.002, "idle": 0.001}
    dev = {"host_python": 0.001, "h2d_transfer": 0.002,
           "device_compute": 0.02, "d2h_transfer": 0.003}
    assert classify_bound(host) == "host"
    assert classify_bound(dev) == "device"
    assert classify_bound({"idle": 1.0}) == "idle"
    assert classify_bound({}) == "unknown"
    assert classify_bound({"host_python": 0.0}) == "unknown"
    assert host_share(host) == pytest.approx(0.014 / 0.016)
    assert host_share({}) == 0.0
    assert set(HOST_PHASES) | set(DEVICE_PHASES) | {"idle"} == \
        set(PHASES)


# ----------------------------------------------------- metrics surface

def test_profiler_metrics_render_and_validate():
    reg = MetricsRegistry()
    prof = PhaseProfiler(metrics=reg, sample_every=1,
                         inflight_fn=lambda: 2)
    prof.begin_tick()
    with prof.phase("device_compute"):
        time.sleep(0.001)
    prof.note_h2d(1234)
    prof.end_tick()
    text = reg.render()
    assert not validate_exposition(text)
    ns = reg.ns
    assert f"# TYPE {ns}_tick_phase_seconds histogram" in text
    for p in PHASES:       # family complete even for untouched phases
        assert f'{ns}_tick_phase_seconds_bucket{{phase="{p}",' in text
    assert f'{ns}_tick_phase_seconds_count{{phase="device_compute"}} 1' \
        in text
    assert f"{ns}_dispatch_inflight_ticks 2" in text
    assert f"{ns}_h2d_bytes_total 1234" in text
    assert f"# TYPE {ns}_compile_events counter" in text


def test_histogram_vec_children_and_count():
    reg = MetricsRegistry()
    vec = reg.histogram_vec("demo_seconds", (0.1, 1.0), "phase")
    vec.labels("a").observe(0.05)
    vec.labels("a").observe(0.5)
    vec.labels("b").observe(2.0)
    assert vec.labels("a") is vec.labels("a")       # create-or-get
    assert vec.count == 3
    assert reg.get_histogram_vec("demo_seconds") is vec
    assert reg.histogram_vec("demo_seconds", (9.9,), "phase") is vec
    text = reg.render()
    assert not validate_exposition(text)
    assert f'{reg.ns}_demo_seconds_bucket{{phase="a",le="0.1"}} 1' \
        in text
    assert f'{reg.ns}_demo_seconds_bucket{{phase="b",le="+Inf"}} 1' \
        in text
    assert f'{reg.ns}_demo_seconds_count{{phase="b"}} 1' in text


# -------------------------------------------------- compile-cache stats

def test_compile_cache_stats_listener_contract():
    st = CompileCacheStats()
    st.on_event("/jax/compilation_cache/cache_hit")
    st.on_event("/jax/compilation_cache/cache_miss")
    st.on_event("/jax/compilation_cache/cache_miss")
    st.on_event("/jax/unrelated/event")
    st.on_duration("/jax/core/compile", 0.25)
    st.on_duration("/jax/backend_compile", 0.5)
    st.on_duration("/jax/unrelated", 99.0)
    assert st.hits == 1
    assert st.misses == 2
    assert st.compile_events == 2
    assert st.compile_seconds == pytest.approx(0.75)


# -------------------------------------------------------- trace report

def _slice(pid, name, ts, dur):
    return {"ph": "X", "pid": pid, "tid": 1, "name": name,
            "ts": ts, "dur": dur}


def _device_events():
    """Synthetic Chrome trace: host pid 1, device pid 2; device busy
    [0,100) and [300,400) us over a 0..1000 us capture."""
    return [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "python host"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        _slice(1, "host_stuff", 0, 1000),
        _slice(2, "fusion.1", 0, 60),
        _slice(2, "copy.h2d", 60, 40),
        _slice(2, "fusion.1", 300, 100),
    ]


def test_trace_report_occupancy_math():
    import trace_report

    rep = trace_report.build_report(_device_events())
    assert rep["device_tracks"] == ["/device:TPU:0"]
    assert rep["trace_wall_s"] == pytest.approx(1000e-6)
    assert rep["device_busy_s"] == pytest.approx(200e-6)
    assert rep["device_idle_pct"] == pytest.approx(80.0)
    assert rep["device_transfer_s"] == pytest.approx(40e-6)
    # one gap: busy [0,100) then [300,400) -> 200us stall
    assert rep["largest_dispatch_gaps_s"][0] == pytest.approx(200e-6)
    top = dict(rep["top_kernels"])
    assert top["fusion.1"] == pytest.approx(160e-6)
    text = trace_report.format_report(rep)
    assert "device idle" in text and "80.0 %" in text


def test_trace_report_host_only_capture_degrades_gracefully():
    import trace_report

    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "python host"}},
        _slice(1, "host_stuff", 0, 500),
    ]
    rep = trace_report.build_report(events)
    assert "error" in rep and "no device track" in rep["error"]
    assert "NOTE:" in trace_report.format_report(rep)
    assert "error" in trace_report.build_report([])


def test_trace_report_loads_gzipped_trace(tmp_path):
    import trace_report

    doc = {"traceEvents": _device_events()}
    path = tmp_path / "run" / "x.trace.json.gz"
    path.parent.mkdir()
    with gzip.open(path, "wt") as f:
        json.dump(doc, f)
    found = trace_report.find_trace_file(str(tmp_path))
    assert found == str(path)
    rep = trace_report.build_report(trace_report.load_events(found))
    assert rep["device_idle_pct"] == pytest.approx(80.0)
    with pytest.raises(FileNotFoundError):
        trace_report.find_trace_file(str(tmp_path / "run" / "empty"))
