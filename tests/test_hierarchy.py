"""Hierarchical two-level mixing (PR 11): the broadcast tick is
bit-exact versus the single-device reference, carries EXACTLY ONE
cross-chip collective, the placer's broadcast size class keeps speaker
rows on the home shard while listener rows straddle with linear cost
and atomic rollback, and the fanout-only listener mask drops uplink
RTP at the loop while letting RTCP through for downlink recovery."""

import struct
import time

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import libjitsi_tpu
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.mesh import make_media_mesh
from libjitsi_tpu.mesh.hierarchy import (broadcast_bus_fanout,
                                         broadcast_step_ref)
from libjitsi_tpu.mesh.parity import assert_hierarchy_parity
from libjitsi_tpu.mesh.placement import ConferencePlacer
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.service.lifecycle import StreamLifecycleManager
from libjitsi_tpu.service.sfu_bridge import SfuBridge
from libjitsi_tpu.service.supervisor import (BridgeSupervisor,
                                             SupervisorConfig)
from libjitsi_tpu.transform.srtp import SrtpStreamTable


# ------------------------------------------------- mesh: tick parity

@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_media_mesh(jax.devices()[:8])


def test_hierarchy_tick_parity_with_single_device_reference(mesh):
    """Speaker mix-minus, per-conference bus and levels from the
    two-level mesh tick are bit-identical to `broadcast_step_ref` on
    one device and to a numpy oracle (int32 associativity makes
    psum-of-partials exact)."""
    assert_hierarchy_parity(mesh, 8)


def test_broadcast_tick_has_exactly_one_collective(mesh):
    """The structural half of the `bcast_fanout_pps` story: the traced
    broadcast tick contains ONE psum (the bus fan-out) and no other
    cross-chip collective of any flavor."""
    n_conf, B, F = 3, 64, 160
    fn = broadcast_bus_fanout(mesh, n_conf)
    jaxpr = str(jax.make_jaxpr(fn)(
        jnp.zeros((B, F), jnp.int16), jnp.zeros(B, bool),
        jnp.zeros(B, jnp.int32)))
    assert jaxpr.count("psum") == 1, jaxpr
    for other in ("all_gather", "all_to_all", "ppermute",
                  "reduce_scatter", "pmax", "pmin"):
        assert other not in jaxpr, f"unexpected collective {other}"


def test_bus_is_replicated_and_listener_leg_needs_no_gather(mesh):
    """out_spec P(None, None): every shard sees the SAME full bus, so
    the listener re-protect leg can read it locally — the reason the
    tick stays at one collective."""
    n_conf, B, F = 2, 64, 16
    rng = np.random.default_rng(3)
    pcm = rng.integers(-1000, 1000, (B, F)).astype(np.int16)
    active = np.ones(B, dtype=bool)
    conf = (np.arange(B) % n_conf).astype(np.int32)
    _spk, bus, _lvl = broadcast_bus_fanout(mesh, n_conf)(pcm, active,
                                                         conf)
    assert bus.shape == (n_conf, F)
    # replicated: each device's copy of the bus is the global total
    _rspk, rbus, _rlvl = broadcast_step_ref(n_conf)(pcm, active, conf)
    np.testing.assert_array_equal(np.asarray(bus), np.asarray(rbus))


# ------------------------------------------- placer: broadcast class

def test_place_broadcast_spreads_listeners_and_costs_linearly():
    p = ConferencePlacer(4, rows_per_shard=8)
    home = p.place_broadcast(1, n_speakers=2, n_listeners=12)
    assert home == 0
    assert p.is_broadcast(1)
    assert p.size_of(1) == 2                  # speakers only
    assert p.listener_count(1) == 12
    shards = p.listener_shards(1)
    assert sum(shards.values()) == 12
    assert len(shards) > 1, "listeners must be allowed to straddle"
    # accounting: rows exact, listener cost linear (alpha/8 per row)
    rows = [ld for (_c, ld, _n) in p.loads()]
    assert sum(rows) == 2 + 12
    cost = sum(c for (c, _r, _n) in p.loads())
    assert cost == pytest.approx(
        p.cost(2) + p.listener_cost(12))
    assert p.listener_cost(12) == pytest.approx(
        12 * p.alpha * ConferencePlacer.LISTENER_COST)


def test_place_broadcast_rolls_back_atomically_when_full():
    """If the listener leg cannot be satisfied, NOTHING stays placed —
    no half-placed home shard, accounting back to zero."""
    p = ConferencePlacer(2, rows_per_shard=4)
    assert p.place_broadcast(9, n_speakers=2, n_listeners=100) is None
    assert not p.is_broadcast(9)
    assert p.shard_of(9) is None
    assert all(r == 0 and c == 0.0 for (c, r, _n) in p.loads())
    assert p.rejects >= 1


def test_grow_listeners_least_loaded_pinned_and_shrink():
    p = ConferencePlacer(3, rows_per_shard=8)
    p.place_broadcast(5, n_speakers=3)        # home=0 carries 3 rows
    assert p.grow_listeners(5) in (1, 2)      # steers off the home
    assert p.grow_listeners(5, shard=0) == 0  # pin: demoted speaker
    assert p.listener_shards(5).get(0) == 1
    with pytest.raises(ValueError):
        p.grow_listeners(7)                   # not a broadcast conf
    p.shrink_listeners(5, 0)
    assert 0 not in p.listener_shards(5)      # empty shard entry gone
    before = p.listener_count(5)
    assert before == 1


def test_release_drains_listener_rows_and_rebuild_restores():
    p = ConferencePlacer(4, rows_per_shard=8)
    p.place_broadcast(3, n_speakers=2, n_listeners=10)
    snapshot = (p.shard_of(3), p.listener_shards(3))
    p.release(3)
    assert all(r == 0 and c == 0.0 for (c, r, _n) in p.loads())
    assert not p.is_broadcast(3)
    # checkpoint-recovery path: rebuild(broadcast=) reproduces the
    # exact same loads the live placer had
    q = ConferencePlacer(4, rows_per_shard=8)
    q.rebuild([(3, snapshot[0], 2)], broadcast=[(3, snapshot[1])])
    assert q.is_broadcast(3)
    assert q.listener_shards(3) == snapshot[1]
    assert sum(r for (_c, r, _n) in q.loads()) == 12


def test_plan_rebalance_never_moves_broadcast_conferences():
    """A broadcast conference's listener rows straddle by design; the
    rebalancer must not try to 'fix' that by moving the conference."""
    p = ConferencePlacer(2, rows_per_shard=64, hysteresis=1.0)
    p.place_broadcast(1, n_speakers=8, n_listeners=0)   # heavy, shard 0
    p.place(2, 2)                                       # light, shard 1
    moves = p.plan_rebalance()
    assert all(m.conf_id != 1 for m in moves)


# ------------------------- loop: fanout-only mask + bridge routing

def _keys(k):
    return ((bytes([k & 0xFF]) * 16, bytes([(k + 1) & 0xFF]) * 14),
            (bytes([(k + 2) & 0xFF]) * 16, bytes([(k + 3) & 0xFF]) * 14))


def _universe(capacity=16, n_shards=4):
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    bridge = SfuBridge(cfg, port=0, capacity=capacity, recv_window_ms=0)
    sup = BridgeSupervisor(bridge, SupervisorConfig(deadline_ms=1000.0))
    lc = StreamLifecycleManager(bridge, supervisor=sup)
    lc._warm_bucket = 1 << 30
    lc._warm_lbucket = 1 << 30
    lc.enable_placement(n_shards)
    return bridge, sup, lc


def _settle(sup, lc, admits, t=100.0):
    for _ in range(64):
        if lc.admits >= admits:
            return t
        sup.tick(now=t)
        t += 0.02
    raise AssertionError(f"settle: admits={lc.admits}, want {admits}")


def _pump(sup, now, want):
    got = 0
    for i in range(200):
        got += sup.tick(now=now)["rx"]
        if got >= want:
            break
        if i > 3:
            time.sleep(0.001)
    return got


def _send_rtp(engine, port, ssrc, seq=1):
    rx, _tx = _keys(ssrc & 0xFF)
    prot = SrtpStreamTable(capacity=1)
    prot.add_stream(0, *rx)
    b = rtp_header.build([bytes([seq & 0xFF]) * 80], [seq], [160 * seq],
                         [ssrc], [0], stream=[0])
    pb = prot.protect_rtp(b)
    engine.send_batch(PacketBatch.from_payloads([pb.to_bytes(0)]),
                      "127.0.0.1", port)


def test_fanout_only_listener_rtp_dropped_rtcp_passes():
    """The loop-level contract of a fanout-only row: uplink RTP is
    dropped before the reverse chain (counted in fanout_rtp_dropped),
    while RTCP from the same row still reaches on_rtcp so downlink
    loss recovery keeps working.  Speaker RTP is untouched."""
    bridge, sup, lc = _universe()
    lc.declare_broadcast(77)
    spk_ssrc, lis_ssrc = 0x10, 0x20
    assert lc.request_join(spk_ssrc, *_keys(spk_ssrc), conference=77,
                           role="speaker")[0]
    assert lc.request_join(lis_ssrc, *_keys(lis_ssrc),
                           conference=77)[0]          # defaults listener
    _settle(sup, lc, 2)
    sid_of = {s: k for k, s in bridge._ssrc_of.items()}
    spk_sid = sid_of[spk_ssrc]
    lis_sid = sid_of[lis_ssrc]
    assert not bridge.loop.fanout_only[spk_sid]
    assert bridge.loop.fanout_only[lis_sid]

    rtcp_seen = []
    inner = bridge.loop.on_rtcp

    def spy(batch, ok):
        rtcp_seen.extend(int(s) for s in batch.stream)
        return inner(batch, ok) if inner is not None else None

    bridge.loop.on_rtcp = spy
    engine = UdpEngine(port=0)
    try:
        # listener uplink RTP: dropped at the mask, never decrypted
        drops0 = bridge.loop.fanout_rtp_dropped
        _send_rtp(engine, bridge.port, lis_ssrc)
        _pump(sup, 200.0, 1)
        assert bridge.loop.fanout_rtp_dropped == drops0 + 1
        # speaker uplink RTP: passes the mask untouched
        _send_rtp(engine, bridge.port, spk_ssrc)
        _pump(sup, 200.1, 1)
        assert bridge.loop.fanout_rtp_dropped == drops0 + 1
        # listener RTCP (minimal RR, PT=201): passes to on_rtcp
        rr = struct.pack("!BBH I I", 0x80, 201, 1, lis_ssrc, 0)
        engine.send_batch(PacketBatch.from_payloads([rr]),
                          "127.0.0.1", bridge.port)
        _pump(sup, 200.2, 1)
        assert lis_sid in rtcp_seen
    finally:
        engine.close()
        bridge.close()


def test_set_broadcast_speakers_scopes_routes_to_speakers():
    """Fan-out routing: listeners receive every speaker's media but
    forward nothing of their own; clear_broadcast restores the full
    mesh."""
    bridge, sup, lc = _universe()
    lc.declare_broadcast(5)
    ssrcs = (0x30, 0x31, 0x40, 0x41)        # 2 speakers, 2 listeners
    for i, ssrc in enumerate(ssrcs):
        role = "speaker" if i < 2 else "listener"
        assert lc.request_join(ssrc, *_keys(ssrc), conference=5,
                               role=role)[0]
    _settle(sup, lc, 4)
    sid_of = {s: k for k, s in bridge._ssrc_of.items()}
    sid = {s: sid_of[s] for s in ssrcs}
    speakers = {sid[0x30], sid[0x31]}

    def routes(s):
        return {int(x) for x in bridge.translator._routes.get(s, ())}

    for s in sid.values():
        if s in speakers:
            # a speaker forwards to every OTHER member of the conf
            assert routes(s) == set(sid.values()) - {s}
        else:
            assert routes(s) == set(), "listener rows are fanout-only"
    bridge.clear_broadcast(5)
    for s in sid.values():
        assert routes(s) == set(sid.values()) - {s}
    bridge.close()


def test_promote_demote_ride_the_commit_barrier():
    """Role flips are commit-barrier events: a promoted off-home
    listener's row MIGRATES to the home shard and sheds its fanout-only
    mask; a demoted speaker stays physically put but re-books as a
    listener row; both leave speaker_flip events in the flight
    recorder and bump the promotion/demotion counters."""
    bridge, sup, lc = _universe(capacity=16, n_shards=4)
    home = lc.declare_broadcast(9)
    ssrcs = (0x50, 0x60, 0x61, 0x62)        # 1 speaker, 3 listeners
    for i, ssrc in enumerate(ssrcs):
        role = "speaker" if i == 0 else "listener"
        assert lc.request_join(ssrc, *_keys(ssrc), conference=9,
                               role=role)[0]
    _settle(sup, lc, 4)
    sid_of = {s: k for k, s in bridge._ssrc_of.items()}
    rows_per = lc._rows_per_shard
    off_home = next(s for s in ssrcs[1:]
                    if sid_of[s] // rows_per != home)
    old_sid = sid_of[off_home]

    lc.promote_speaker(9, old_sid)
    t = _settle(sup, lc, 4)                  # flips apply on commit
    for _ in range(8):
        sup.tick(now=t)
        t += 0.02
    sid_of = {s: k for k, s in bridge._ssrc_of.items()}
    new_sid = sid_of[off_home]
    assert new_sid != old_sid, "promotion must migrate the row home"
    assert new_sid // rows_per == home
    assert not bridge.loop.fanout_only[new_sid]
    assert lc.speaker_promotions == 1
    assert old_sid not in lc._listener_sids

    lc.demote_speaker(9, new_sid)
    for _ in range(8):
        sup.tick(now=t)
        t += 0.02
    assert sid_of == {s: k for k, s in bridge._ssrc_of.items()}, \
        "demotion must not move the row"
    assert bridge.loop.fanout_only[new_sid]
    assert lc.speaker_demotions == 1
    assert new_sid in lc._listener_sids
    flips = sorted((e for ring in
                    lc.flight.dump_all()["streams"].values()
                    for e in ring if e["kind"] == "speaker_flip"),
                   key=lambda e: e["seq"])
    assert [f["role"] for f in flips] == ["speaker", "listener"]
    bridge.close()
