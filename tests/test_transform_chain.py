"""Transform chain composition + header-extension engines.

Reference behaviors under test: TransformEngineChain ordering (send runs
engines first→last, receive last→first, SRTP outermost on the wire),
AbsSendTimeEngine/TransportCCEngine/CsrcAudioLevel stamping, PT remap,
SSRC rewrite, and the RFC 5285 one-byte extension codec.
"""

import numpy as np
import pytest

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import ext as rtp_ext
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform import (
    AbsSendTimeEngine,
    CsrcAudioLevelEngine,
    PayloadTypeTransformEngine,
    SrtpTransformEngine,
    SsrcRewriteEngine,
    TransformEngineChain,
    TransportCCEngine,
)
from libjitsi_tpu.transform.srtp import SrtpStreamTable

MK, MS = bytes(range(16)), bytes(range(100, 114))


def make_batch(n=4, seq0=100, ssrc=0x42, stream=0):
    return rtp_header.build(
        [b"payload-%02d" % i for i in range(n)],
        [seq0 + i for i in range(n)], [0] * n, [ssrc] * n, [96] * n,
        stream=[stream] * n)


def make_srtp(n=8):
    tx, rx = SrtpStreamTable(capacity=n), SrtpStreamTable(capacity=n)
    for t in (tx, rx):
        for i in range(n):
            t.add_stream(i, MK, MS)
    return SrtpTransformEngine(tx, rx)


# --------------------------------------------------------- one-byte exts ---

def test_ext_set_and_find_fresh():
    b = make_batch()
    hdr = rtp_header.parse(b)
    pay = np.tile(np.array([1, 2, 3], np.uint8), (b.batch_size, 1))
    out = rtp_ext.set_one_byte_ext(b, hdr, 3, pay)
    h2 = rtp_header.parse(out)
    assert np.all(h2.extension == 1)
    assert np.all(h2.ext_profile == 0xBEDE)
    off, ln, found = rtp_ext.find_one_byte_ext(out, h2, 3)
    assert found.all() and np.all(ln == 3)
    got = np.stack([out.data[i, off[i]:off[i] + 3] for i in range(4)])
    np.testing.assert_array_equal(got, pay)
    # payload follows intact
    assert out.to_bytes(0).endswith(b"payload-00")


def test_ext_append_to_existing_block_and_rewrite():
    b = make_batch()
    hdr = rtp_header.parse(b)
    p1 = np.full((4, 2), 7, np.uint8)
    out = rtp_ext.set_one_byte_ext(b, hdr, 2, p1)
    # append a second element
    h2 = rtp_header.parse(out)
    p2 = np.full((4, 3), 9, np.uint8)
    out2 = rtp_ext.set_one_byte_ext(out, h2, 5, p2)
    h3 = rtp_header.parse(out2)
    for eid, pay in ((2, p1), (5, p2)):
        off, ln, found = rtp_ext.find_one_byte_ext(out2, h3, eid)
        assert found.all() and np.all(ln == pay.shape[1])
    # rewrite element 2 in place: length unchanged
    p1b = np.full((4, 2), 8, np.uint8)
    out3 = rtp_ext.set_one_byte_ext(out2, rtp_header.parse(out2), 2, p1b)
    assert np.all(np.asarray(out3.length) == np.asarray(out2.length))
    off, _, found = rtp_ext.find_one_byte_ext(out3, rtp_header.parse(out3), 2)
    assert found.all()
    assert np.all(out3.data[np.arange(4), off] == 8)
    assert out3.to_bytes(0).endswith(b"payload-00")


def test_abs_send_time_stamp():
    eng = AbsSendTimeEngine(ext_id=4, clock=lambda: 1.5)
    b = make_batch()
    out, ok = eng.rtp_transformer.transform(b)
    assert ok.all()
    h = rtp_header.parse(out)
    off, ln, found = rtp_ext.find_one_byte_ext(out, h, 4)
    assert found.all() and np.all(ln == 3)
    v = int(1.5 * (1 << 18)) & 0xFFFFFF
    want = [(v >> 16) & 0xFF, (v >> 8) & 0xFF, v & 0xFF]
    np.testing.assert_array_equal(out.data[0, off[0]:off[0] + 3], want)


def test_transport_cc_seq_and_send_times():
    eng = TransportCCEngine(ext_id=5, clock=lambda: 2.0)
    b1, b2 = make_batch(3), make_batch(2, seq0=200)
    o1, _ = eng.rtp_transformer.transform(b1)
    o2, _ = eng.rtp_transformer.transform(b2)
    h = rtp_header.parse(o2)
    off, _, found = rtp_ext.find_one_byte_ext(o2, h, 5)
    assert found.all()
    got = [(int(o2.data[i, off[i]]) << 8) | int(o2.data[i, off[i] + 1])
           for i in range(2)]
    assert got == [3, 4]          # continues across batches
    assert eng.lookup_send_time(0) == 2.0
    assert eng.lookup_send_time(4) == 2.0
    assert eng.lookup_send_time(99) is None


def test_audio_level_stamp_and_extract():
    levels = np.array([13] + [127] * 7, np.uint8)
    tx = CsrcAudioLevelEngine(ext_id=1, capacity=8,
                              level_of=lambda sid: levels[sid])
    rx = CsrcAudioLevelEngine(ext_id=1, capacity=8)
    b = make_batch(stream=0)
    out, _ = tx.rtp_transformer.transform(b)
    _, ok = rx.rtp_transformer.reverse_transform(out)
    assert ok.all()
    assert rx.last_levels[0] == 13


def test_pt_remap_and_ssrc_rewrite():
    pt = PayloadTypeTransformEngine(capacity=8)
    pt.add_mapping(0, 96, 100)
    b = make_batch()
    out, _ = pt.rtp_transformer.transform(b)
    assert np.all(rtp_header.parse(out).pt == 100)

    sw = SsrcRewriteEngine(capacity=8)
    sw.set_mapping(0, 0xCAFEBABE)
    out2, _ = sw.rtp_transformer.transform(b)
    assert np.all(rtp_header.parse(out2).ssrc == 0xCAFEBABE)


# ---------------------------------------------------------------- chain ---

def test_chain_srtp_roundtrip_with_extensions():
    """Send chain: abs-send-time → TCC → SRTP; receive chain reverses and
    the decrypted packets still carry the stamped extensions."""
    srtp = make_srtp()
    chain_tx = TransformEngineChain([
        AbsSendTimeEngine(ext_id=4, clock=lambda: 1.0),
        TransportCCEngine(ext_id=5, clock=lambda: 1.0),
        srtp,
    ])
    b = make_batch()
    wire, ok = chain_tx.rtp_transformer.transform(b)
    assert ok.all()
    # on the wire the packets are encrypted: payload differs
    assert wire.to_bytes(0)[-10:] != b.to_bytes(0)[-10:]

    srtp2 = make_srtp()
    rx_levels = CsrcAudioLevelEngine(ext_id=1, capacity=8)
    chain_rx = TransformEngineChain([rx_levels, srtp2])
    dec, ok = chain_rx.rtp_transformer.reverse_transform(wire)
    assert ok.all()
    h = rtp_header.parse(dec)
    for eid in (4, 5):
        _, _, found = rtp_ext.find_one_byte_ext(dec, h, eid)
        assert found.all()
    assert dec.to_bytes(0).endswith(b"payload-00")


def test_chain_drop_accounting():
    srtp_tx, srtp_rx = make_srtp(), make_srtp()
    chain = TransformEngineChain([srtp_rx], names=["srtp"])
    b = make_batch()
    wire, _ = TransformEngineChain([srtp_tx]).rtp_transformer.transform(b)
    tampered = wire.copy()
    tampered.data[1, 20] ^= 0xFF
    dec, ok = chain.rtp_transformer.reverse_transform(tampered)
    assert ok.tolist() == [True, False, True, True]
    assert chain.drop_counts["srtp"] == 1
