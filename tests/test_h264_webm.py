"""H.264 packetization (RFC 6184) + WebM muxer."""

import numpy as np
import pytest

from libjitsi_tpu.codecs.h264 import (
    H264Depacketizer,
    NAL_FU_A,
    NAL_STAP_A,
    is_keyframe_payload,
    packetize,
)
from libjitsi_tpu.recording.webm import WebmWriter


def _nal(typ, size, fill=0x41):
    return bytes([0x60 | typ]) + bytes([fill]) * (size - 1)


def test_h264_small_nals_aggregate_stap_a():
    nals = [_nal(7, 20), _nal(8, 10), _nal(5, 40)]
    pkts = packetize(nals, mtu=200)
    assert len(pkts) == 1
    assert pkts[0][0] & 0x1F == NAL_STAP_A
    d = H264Depacketizer()
    out = d.push(pkts[0])
    assert out == nals
    assert d.keyframe_seen
    assert is_keyframe_payload(pkts[0])


def test_h264_large_nal_fragments_fu_a():
    nal = _nal(5, 3000)
    pkts = packetize([nal], mtu=1200)
    assert len(pkts) == 3
    assert all(p[0] & 0x1F == NAL_FU_A for p in pkts)
    assert pkts[0][1] & 0x80 and pkts[-1][1] & 0x40  # start/end bits
    assert is_keyframe_payload(pkts[0])
    assert not is_keyframe_payload(pkts[1])
    d = H264Depacketizer()
    outs = [d.push(p) for p in pkts]
    assert outs[0] == [] and outs[1] == []
    assert outs[2] == [nal]


def test_h264_single_nal_and_interleaving():
    small = _nal(1, 50)
    big = _nal(1, 2000)
    pkts = packetize([small, big], mtu=1200)
    d = H264Depacketizer()
    got = []
    for p in pkts:
        got += d.push(p)
    assert got == [small, big]
    assert not d.keyframe_seen
    assert not is_keyframe_payload(pkts[0])


def test_h264_mixed_aggregate_then_fragment():
    nals = [_nal(7, 30), _nal(8, 15), _nal(5, 5000), _nal(1, 100)]
    pkts = packetize(nals, mtu=1000)
    d = H264Depacketizer()
    got = []
    for p in pkts:
        got += d.push(p)
    assert got == nals


def test_webm_writer_structure(tmp_path):
    p = str(tmp_path / "out.webm")
    w = WebmWriter(p, width=640, height=480)
    w.write_frame(b"\x10keyframe-data", 0, keyframe=True)
    w.write_frame(b"\x11delta", 33, keyframe=False)
    w.write_frame(b"\x12delta", 2500, keyframe=False)  # new cluster
    w.close()
    blob = open(p, "rb").read()
    assert blob.startswith(bytes.fromhex("1a45dfa3"))  # EBML magic
    assert b"webm" in blob[:64]
    assert b"V_VP8" in blob
    assert blob.count(bytes.fromhex("1f43b675")) == 2  # two clusters
    assert b"keyframe-data" in blob and b"delta" in blob
    assert w.frames == 3
