import os

import libjitsi_tpu
from libjitsi_tpu.core.config import ConfigurationService


def test_precedence_and_types(monkeypatch):
    monkeypatch.setenv("LIBJITSI_TPU_A_B", "42")
    c = ConfigurationService(overrides={"x.y": 7})
    c.register_default("a.b", 1)
    c.register_default("z", "true")
    assert c.get_int("a.b") == 42  # env beats default
    assert c.get_int("x.y") == 7  # override beats all
    assert c.get_bool("z") is True
    c.set("a.b", 99)
    assert c.get_int("a.b") == 99  # explicit set beats env


def test_bad_env_value_falls_back(monkeypatch):
    monkeypatch.setenv("LIBJITSI_TPU_FOO_BAR", "not-a-number")
    c = ConfigurationService()
    assert c.get_int("foo.bar", 7) == 7
    assert c.get_float("foo.bar", 2.5) == 2.5
    monkeypatch.setenv("LIBJITSI_TPU_EMPTY", "")
    assert c.get_bool("empty", True) is True  # empty env == unset


def test_listeners_and_prefix(monkeypatch):
    monkeypatch.setenv("LIBJITSI_TPU_SRTP_WINDOW", "128")
    c = ConfigurationService()
    seen = []
    c.add_listener(lambda k, old, new: seen.append((k, old, new)))
    c.set("srtp.replay", 1)
    assert seen == [("srtp.replay", None, 1)]
    props = c.properties_by_prefix("srtp.")
    assert props["srtp.replay"] == 1
    assert props["srtp.window"] == "128"  # env-only key included


def test_reinit_merges_config():
    libjitsi_tpu.stop()
    libjitsi_tpu.configuration_service()  # auto-init with empty config
    libjitsi_tpu.init({"mixer.frame_ms": 10})  # must merge, not drop
    assert libjitsi_tpu.configuration_service().get_int("mixer.frame_ms") == 10
    libjitsi_tpu.stop()
