"""SFU translator fan-out + retransmission cache.

Reference behaviors: RTPTranslatorImpl decrypt-once/re-encrypt-per-
receiver (SURVEY §3.4), CachingTransformer NACK service.
"""

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.sfu import PacketCache, RtpTranslator
from libjitsi_tpu.transform.srtp import SrtpStreamTable
import pytest

MK_A = bytes(range(16))            # sender A's master key
MS_A = bytes(range(50, 64))
RECV_KEYS = {r: (bytes([r] * 16), bytes([r + 100] * 14)) for r in (1, 2, 3)}


def _sender_batch(n=4, ssrc=0xAAA, sid=0):
    return rtp_header.build(
        [b"media-%d" % i for i in range(n)],
        [1000 + i for i in range(n)], [i * 960 for i in range(n)],
        [ssrc] * n, [96] * n, stream=[sid] * n)


@pytest.mark.slow
def test_fanout_reencrypts_per_receiver():
    # sender -> SFU leg
    tx = SrtpStreamTable(capacity=4)
    tx.add_stream(0, MK_A, MS_A)
    rx = SrtpStreamTable(capacity=4)
    rx.add_stream(0, MK_A, MS_A)
    wire_in = tx.protect_rtp(_sender_batch())
    dec, ok, idx = rx.unprotect_rtp(wire_in, return_index=True)
    assert ok.all()

    # SFU -> receivers
    tr = RtpTranslator(capacity=8)
    for r, (mk, ms) in RECV_KEYS.items():
        tr.add_receiver(r, mk, ms)
    tr.connect(0, [1, 2, 3])
    out, recv = tr.translate(dec, idx)
    assert out.batch_size == 4 * 3
    np.testing.assert_array_equal(np.unique(recv), [1, 2, 3])

    # each receiver decrypts its copies with its own key; payloads match
    for r, (mk, ms) in RECV_KEYS.items():
        leg = SrtpStreamTable(capacity=8)
        leg.add_stream(5, mk, ms)
        rows = np.nonzero(recv == r)[0]
        sub = PacketBatch.from_payloads(
            [out.to_bytes(i) for i in rows], stream=[5] * len(rows))
        dec_r, ok_r = leg.unprotect_rtp(sub)
        assert ok_r.all()
        for j in range(len(rows)):
            assert dec_r.to_bytes(j) == dec.to_bytes(j)
    # different receivers got different ciphertext for the same packet
    c1 = out.to_bytes(int(np.nonzero(recv == 1)[0][0]))
    c2 = out.to_bytes(int(np.nonzero(recv == 2)[0][0]))
    assert c1 != c2


@pytest.mark.slow
def test_fanout_respects_routes_and_removal():
    tr = RtpTranslator(capacity=8)
    for r, (mk, ms) in RECV_KEYS.items():
        tr.add_receiver(r, mk, ms)
    tr.connect(0, [1, 2])
    tr.connect(7, [3])          # other sender, not in this batch
    b = _sender_batch(n=2)
    out, recv = tr.translate(b, np.array([1000, 1001]))
    assert sorted(np.unique(recv)) == [1, 2]
    tr.remove_receiver(2)
    out2, recv2 = tr.translate(b, np.array([1000, 1001]))
    assert sorted(np.unique(recv2)) == [1]
    # unrouted sender: nothing out
    b2 = _sender_batch(sid=9)
    out3, recv3 = tr.translate(b2, np.arange(4))
    assert out3.batch_size == 0


def test_roc_carried_into_fanout():
    """Sender past a seq wrap (index > 2^16): receivers still decrypt."""
    tx = SrtpStreamTable(capacity=2)
    tx.add_stream(0, MK_A, MS_A)
    rx = SrtpStreamTable(capacity=2)
    rx.add_stream(0, MK_A, MS_A)
    seqs = [65534, 65535, 0, 1]  # wraps: ROC increments mid-batch
    b = rtp_header.build([b"wrap-%d" % s for s in seqs], seqs,
                         [0] * 4, [0xAAA] * 4, [96] * 4, stream=[0] * 4)
    dec, ok, idx = rx.unprotect_rtp(tx.protect_rtp(b), return_index=True)
    assert ok.all()
    assert idx[-1] == (1 << 16) + 1

    tr = RtpTranslator(capacity=4)
    mk, ms = RECV_KEYS[1]
    tr.add_receiver(1, mk, ms)
    tr.connect(0, [1])
    out, recv = tr.translate(dec, idx)
    leg = SrtpStreamTable(capacity=4)
    leg.add_stream(0, mk, ms)
    # receiver leg must accept across the wrap too
    sub = PacketBatch.from_payloads(
        [out.to_bytes(i) for i in range(out.batch_size)], stream=[0] * 4)
    dec_r, ok_r = leg.unprotect_rtp(sub)
    assert ok_r.all()


# ------------------------------------------------------------------ cache --

def test_cache_insert_lookup_nack():
    c = PacketCache(max_bytes=10_000, max_age=10.0)
    c.insert_batch([5, 5, 5], [100, 101, 102],
                   [b"p100", b"p101", b"p102"], now=0.0)
    assert c.get(5, 101) == b"p101"
    nack = rtcp.Nack(sender_ssrc=9, media_ssrc=5, lost_seqs=[100, 102, 999])
    got = c.lookup_nack(5, nack.lost_seqs)
    assert got == [b"p100", b"p102"]


def test_cache_eviction_by_bytes_and_age():
    c = PacketCache(max_bytes=250, max_age=0.5)
    for i in range(3):
        c.insert(1, i, bytes(100), now=0.0)
    assert len(c) == 2           # 300B > 250B: oldest evicted
    assert c.get(1, 0) is None
    c.insert(1, 50, bytes(10), now=1.0)   # age evicts the 0.0-era entries
    assert c.get(1, 1) is None and c.get(1, 2) is None
    assert c.get(1, 50) is not None


# -------------------------------------------------------- rtcp termination

def test_rtcp_termination_aggregates_and_throttles():
    from libjitsi_tpu.sfu.rtcp_termination import RtcpTermination

    t = RtcpTermination(bridge_ssrc=0xBEEF, pli_interval_s=1.0)
    media = 0xAAA
    # three receivers report different loss about the forwarded stream
    for rid, (fl, cum, jit) in enumerate([(10, 5, 100), (80, 50, 900),
                                          (0, 0, 10)]):
        rr = rtcp.ReceiverReport(0x100 + rid, [rtcp.ReportBlock(
            media, fl, cum, 5000, jit, 0, 0)])
        t.on_receiver_rtcp(rid, [rr])
    t.on_receiver_rtcp(0, [rtcp.Remb(0x100, 2_000_000, [media])])
    t.on_receiver_rtcp(1, [rtcp.Remb(0x101, 500_000, [media])])
    t.on_receiver_rtcp(0, [rtcp.Nack(0x100, media, [10, 11])])
    t.on_receiver_rtcp(1, [rtcp.Nack(0x101, media, [11, 12])])
    t.on_receiver_rtcp(2, [rtcp.Pli(0x102, media)])
    t.on_receiver_rtcp(1, [rtcp.Pli(0x101, media)])

    out = t.make_sender_feedback(media, now=100.0)
    parsed = [p for blob in out for p in rtcp.parse_compound(blob)]
    rrs = [p for p in parsed if isinstance(p, rtcp.ReceiverReport)]
    assert len(rrs) == 1                       # N receiver RRs -> one
    agg = rrs[0].reports[0]
    assert agg.fraction_lost == 80 and agg.jitter == 900
    rembs = [p for p in parsed if isinstance(p, rtcp.Remb)]
    assert rembs[0].bitrate_bps == 500_000     # bottleneck receiver wins
    nacks = [p for p in parsed if isinstance(p, rtcp.Nack)]
    assert sorted(nacks[0].lost_seqs) == [10, 11, 12]
    plis = [p for p in parsed if isinstance(p, rtcp.Pli)]
    assert len(plis) == 1                      # storm -> one PLI

    # PLI rate limit: another request inside the interval is held
    t.on_receiver_rtcp(0, [rtcp.Pli(0x100, media)])
    out2 = t.make_sender_feedback(media, now=100.2)
    assert not any(isinstance(p, rtcp.Pli) for blob in out2
                   for p in rtcp.parse_compound(blob))
    out3 = t.make_sender_feedback(media, now=101.5)
    assert any(isinstance(p, rtcp.Pli) for blob in out3
               for p in rtcp.parse_compound(blob))

    # a leaving bottleneck receiver releases the REMB cap
    t.forget_receiver(1)
    assert t.min_remb(media) == 2_000_000


# --------------------------------------------------------- GCM fan-out ---

GCM_RECV_KEYS = {r: (bytes([r] * 16), bytes([r + 100] * 12))
                 for r in (1, 2, 3)}


def _gcm_fanout_roundtrip(routes):
    """Protect with a GCM sender, fan out, decrypt each leg, compare."""
    from libjitsi_tpu.transform.srtp import SrtpProfile

    prof = SrtpProfile.AEAD_AES_128_GCM
    mk_a, ms_a = bytes(range(16)), bytes(range(50, 62))
    tx = SrtpStreamTable(capacity=4, profile=prof)
    tx.add_stream(0, mk_a, ms_a)
    rx = SrtpStreamTable(capacity=4, profile=prof)
    rx.add_stream(0, mk_a, ms_a)
    wire_in = tx.protect_rtp(_sender_batch())
    dec, ok, idx = rx.unprotect_rtp(wire_in, return_index=True)
    assert ok.all()

    tr = RtpTranslator(capacity=8, profile=prof)
    for r, (mk, ms) in GCM_RECV_KEYS.items():
        tr.add_receiver(r, mk, ms)
    for sid, rr in routes.items():
        tr.connect(sid, rr)
    out, recv = tr.translate(dec, idx)
    n_legs = len(routes[0])
    assert out.batch_size == 4 * n_legs

    for r in routes[0]:
        mk, ms = GCM_RECV_KEYS[r]
        leg = SrtpStreamTable(capacity=8, profile=prof)
        leg.add_stream(5, mk, ms)
        rows = np.nonzero(recv == r)[0]
        sub = PacketBatch.from_payloads(
            [out.to_bytes(i) for i in rows], stream=[5] * len(rows))
        dec_r, ok_r = leg.unprotect_rtp(sub)
        assert ok_r.all(), f"receiver {r} failed GCM auth"
        for j in range(len(rows)):
            assert dec_r.to_bytes(j) == dec.to_bytes(j)
    c1 = out.to_bytes(int(np.nonzero(recv == routes[0][0])[0][0]))
    c2 = out.to_bytes(int(np.nonzero(recv == routes[0][1])[0][0]))
    assert c1 != c2
    return tr


@pytest.mark.slow
def test_gcm_fanout_full_mesh_grouped_path():
    """Uniform routes take the grouped (per-leg H matrix) kernel; every
    leg must still open the AEAD against its own session keys."""
    _gcm_fanout_roundtrip({0: [1, 2, 3]})


@pytest.mark.slow
def test_gcm_fanout_general_path_matches_grouped():
    """Non-uniform routes fall back to the per-row gather path; the
    ciphertext for a shared (packet, receiver) pair must be identical
    to the grouped path's (same keys, same IVs => same AEAD output)."""
    from libjitsi_tpu.transform.srtp import SrtpProfile

    prof = SrtpProfile.AEAD_AES_128_GCM
    mk_a, ms_a = bytes(range(16)), bytes(range(50, 62))
    rx = SrtpStreamTable(capacity=4, profile=prof)
    rx.add_stream(0, mk_a, ms_a)
    tx = SrtpStreamTable(capacity=4, profile=prof)
    tx.add_stream(0, mk_a, ms_a)
    wire_in = tx.protect_rtp(_sender_batch())
    dec, ok, idx = rx.unprotect_rtp(wire_in, return_index=True)

    tr = RtpTranslator(capacity=8, profile=prof)
    for r, (mk, ms) in GCM_RECV_KEYS.items():
        tr.add_receiver(r, mk, ms)
    tr.connect(0, [1, 2, 3])
    out_grouped, recv_g = tr.translate(dec, idx)

    # force the general path: batch with two senders, different routes
    tr2 = RtpTranslator(capacity=8, profile=prof)
    for r, (mk, ms) in GCM_RECV_KEYS.items():
        tr2.add_receiver(r, mk, ms)
    tr2.connect(0, [1, 2])
    tr2.connect(9, [3])
    two = rtp_header.build(
        [dec.to_bytes(0)[12:], b"other-sender"],
        [1000, 7], [0, 0], [0xAAA, 0xBBB], [96, 96], stream=[0, 9])
    out_mixed, recv_m = tr2.translate(two, np.array([int(idx[0]), 7]))
    assert sorted(np.unique(recv_m)) == [1, 2, 3]
    # packet 0 to receiver 1: identical bytes via either path
    g_row = int(np.nonzero(recv_g == 1)[0][0])
    m_row = int(np.nonzero(recv_m == 1)[0][0])
    assert out_grouped.to_bytes(g_row) == out_mixed.to_bytes(m_row)


def test_gcm_fanout_forged_ext_header_does_not_crash():
    """A (validly authenticated) packet whose X/ext_words claims a header
    bigger than the packet must not crash translate(): the grouped fast
    path's static-offset gate rejects it and the general path clamps."""
    from libjitsi_tpu.transform.srtp import SrtpProfile

    prof = SrtpProfile.AEAD_AES_128_GCM
    tr = RtpTranslator(capacity=8, profile=prof)
    for r, (mk, ms) in GCM_RECV_KEYS.items():
        tr.add_receiver(r, mk, ms)
    tr.connect(0, [1, 2, 3])
    b = _sender_batch(n=2)
    # forge X=1 + huge ext_words on both rows (same offset -> would take
    # the uniform path if the bound didn't gate it)
    for i in range(2):
        b.data[i, 0] |= 0x10                    # X bit
        b.data[i, 12:14] = (0xBE, 0xDE)         # ext profile
        b.data[i, 14] = 0x03                    # ext_words hi
        b.data[i, 15] = 0xE8                    # 0x3E8 = 1000 words
    out, recv = tr.translate(b, np.array([1000, 1001]))
    assert out.batch_size == 2 * 3              # processed, not crashed
