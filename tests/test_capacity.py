"""CapacityModel unit + integration tests: the per-resource linear
fit (rows fit exactly: slope = 1/capacity), the forecast refusal and
its exponential retry-after streak, forecast-exhausted shard steering,
the capacity_* metric families, and the /debug/capacity endpoint —
the live half of what scripts/global_day.py validates end-to-end
against measured saturation."""

import json
import types
import urllib.error
import urllib.request

import libjitsi_tpu
from libjitsi_tpu.mesh.placement import ConferencePlacer
from libjitsi_tpu.service.lifecycle import StreamLifecycleManager
from libjitsi_tpu.service.obs_server import ObservabilityServer
from libjitsi_tpu.service.sfu_bridge import SfuBridge
from libjitsi_tpu.service.supervisor import (BridgeSupervisor,
                                             SupervisorConfig)
from libjitsi_tpu.utils.capacity import (RESOURCES, CapacityConfig,
                                         CapacityModel,
                                         predicted_saturation)
from libjitsi_tpu.utils.metrics import (MetricsRegistry,
                                        validate_exposition)

CAP = 64


def _fake_sup(capacity=CAP):
    """The exact attribute surface `CapacityModel._signals` reads,
    with a registry whose occupancy the test moves by hand."""
    reg = types.SimpleNamespace(capacity=capacity, free_slots=capacity)
    bridge = types.SimpleNamespace(registry=reg)
    sup = types.SimpleNamespace(
        cfg=types.SimpleNamespace(deadline_ms=1000.0),
        last_tick_s=0.0, last_phases={}, bridge=bridge,
        lifecycle=None, slo=None, capacity=None)
    return sup, reg


def _grow(model, sup, reg, populations):
    for pop in populations:
        reg.free_slots = reg.capacity - pop
        model.on_tick(sup)


def test_rows_fit_predicts_the_row_wall():
    """Rows are deterministic — occupancy/capacity — so the fit must
    recover slope 1/capacity and predict saturation at `capacity`
    users (alpha 1.0: no EWMA lag, the fit is exact)."""
    model = CapacityModel(CapacityConfig(ewma_alpha=1.0), fit_every=1)
    sup, reg = _fake_sup()
    model.attach(sup)
    assert sup.capacity is model
    _grow(model, sup, reg, range(0, 49))
    assert model.bottleneck() == "rows"
    rows = model.tracks["rows"]
    assert abs(rows.slope - 1.0 / CAP) < 1e-9
    assert rows.r2 > 0.999
    # at population 48 of 64 the wall is 16 users away
    assert abs(model.headroom_users() - 16.0) < 0.5
    assert model.confidence() > 0.9
    assert abs(predicted_saturation(model) - CAP) < 0.5


def test_no_fit_means_infinite_headroom_and_zero_confidence():
    model = CapacityModel()
    sup, reg = _fake_sup()
    model.attach(sup)
    _grow(model, sup, reg, [5] * 4)      # too few samples, no spread
    assert model.headroom_users() == float("inf")
    assert model.confidence() == 0.0
    assert predicted_saturation(model) is None
    assert not model.should_refuse()


def test_forecast_refusal_streak_backs_retry_after():
    """Near the wall a confident fit refuses; consecutive refusals
    double the retry-after hint (capped), and one green tick resets
    the streak."""
    cfg = CapacityConfig(ewma_alpha=1.0, guard_users=1.0,
                         retry_base_s=0.1, retry_cap_doublings=4)
    model = CapacityModel(cfg, fit_every=1)
    sup, reg = _fake_sup()
    model.attach(sup)
    _grow(model, sup, reg, range(0, 41))
    assert not model.should_refuse()     # 24 users of headroom
    _grow(model, sup, reg, [63])         # one row left: below guard+1
    assert model.should_refuse()
    assert model.forecast_refusals == 1
    assert model.retry_after() == 0.1    # streak 1 -> base
    assert model.should_refuse() and model.should_refuse()
    assert model.retry_after() == 0.4    # streak 3 -> base * 4
    for _ in range(10):
        model.should_refuse()
    assert model.retry_after() == 0.1 * (2 ** 4)   # cap holds
    _grow(model, sup, reg, [30])         # load drains
    assert not model.should_refuse()
    assert model.retry_after() == 0.1    # streak reset


def test_capacity_families_render_and_validate():
    reg = MetricsRegistry()
    model = CapacityModel(CapacityConfig(ewma_alpha=1.0), fit_every=1)
    sup, sreg = _fake_sup()
    model.attach(sup, registry=reg)
    _grow(model, sup, sreg, range(0, 30))
    text = reg.render()
    assert validate_exposition(text) == []
    assert "# TYPE libjitsi_tpu_capacity_headroom_users gauge" in text
    assert ("# TYPE libjitsi_tpu_capacity_estimate_confidence gauge"
            in text)
    assert ("# TYPE libjitsi_tpu_capacity_forecast_refusals counter"
            in text)
    # the bottleneck family is complete from the first scrape: one
    # labeled sample per resource, fit or no fit
    for r in RESOURCES:
        assert (f'libjitsi_tpu_capacity_bottleneck{{resource="{r}"}}'
                in text)


def test_exhausted_shards_steer_placement():
    """A shard whose row range is `shard_exhaust_frac` full is
    forecast-exhausted: it shows up in the lifecycle plane's avoidance
    set next to burning shards, BEFORE it is actually full."""
    placer = ConferencePlacer(2, rows_per_shard=8)
    assert placer.place(1, 8) == 0       # shard 0 now 100% occupied
    model = CapacityModel()
    model.supervisor = types.SimpleNamespace(
        lifecycle=types.SimpleNamespace(placer=placer))
    assert model.exhausted_shards() == [0]
    # the lifecycle avoidance surface merges it with SLO burn steering
    lc = StreamLifecycleManager.__new__(StreamLifecycleManager)
    lc.supervisor = types.SimpleNamespace(slo=None, capacity=model)
    assert lc._burning_shards() == {0}
    # and the forecast refuses joins targeting the exhausted shard
    # while a join elsewhere stays green (no confident global fit here)
    assert model.should_refuse(shard=0)
    assert not model.should_refuse(shard=1)


def test_forecast_refuses_join_end_to_end():
    """Real bridge, supervisor and lifecycle: grow to near the row
    wall one user per tick, then assert the next join is refused
    `capacity_forecast` (typed, before any hard signal) with a
    positive retry-after hint from the model's streak."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    bridge = SfuBridge(cfg, port=0, capacity=16, recv_window_ms=0)
    try:
        sup = BridgeSupervisor(bridge,
                               SupervisorConfig(deadline_ms=1000.0))
        lc = StreamLifecycleManager(bridge, supervisor=sup)
        lc._warm_bucket = 1 << 30        # warm cadence tested elsewhere
        model = CapacityModel(
            CapacityConfig(ewma_alpha=1.0, min_samples=8,
                           min_pop_spread=4.0, guard_users=4.0),
            fit_every=1).attach(sup)
        t = 100.0
        for i in range(12):
            rx = (bytes([i]) * 16, bytes([i + 1]) * 14)
            tx = (bytes([i + 2]) * 16, bytes([i + 3]) * 14)
            ok, reason = lc.request_join(0x900 + i, rx, tx)
            assert ok, reason
            for _ in range(4):
                sup.tick(now=t)
                t += 0.02
        assert len(bridge._ssrc_of) == 12
        # headroom 4 < guard 4 + 1: the forecast bars the door while
        # 4 hard rows are still free
        assert bridge.registry.free_slots == 4
        assert model.confidence() >= 0.5
        ok, reason = lc.request_join(
            0xA00, (b"\x70" * 16, b"\x71" * 14),
            (b"\x72" * 16, b"\x73" * 14))
        assert (ok, reason) == (False, "capacity_forecast")
        assert lc.admit_rejected.get("capacity_forecast") == 1
        assert lc.retry_after_hint("capacity_forecast") > 0.0
        assert model.forecast_refusals >= 1
    finally:
        bridge.close()


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def test_debug_capacity_endpoint():
    """/debug/capacity mirrors CapacityModel.status(); without a model
    attached anywhere the endpoint 404s instead of serving junk."""
    model = CapacityModel(CapacityConfig(ewma_alpha=1.0), fit_every=1)
    sup, reg = _fake_sup()
    model.attach(sup)
    _grow(model, sup, reg, range(0, 30))
    sup.health = lambda: {"state": "healthy"}
    sup.flight, sup.postmortems = None, []
    with ObservabilityServer(supervisor=sup) as srv:
        code, body = _get(srv.port, "/debug/capacity")
        doc = json.loads(body)
        assert code == 200
        assert doc["ticks"] == 30 and doc["bottleneck"] == "rows"
        assert set(doc["resources"]) == set(RESOURCES)
        assert doc["resources"]["rows"]["slope_per_user"] is not None
    bare = types.SimpleNamespace(
        health=lambda: {"state": "healthy"}, flight=None,
        postmortems=[])
    with ObservabilityServer(supervisor=bare) as srv:
        code, body = _get(srv.port, "/debug/capacity")
        assert code == 404 and "no capacity model" in body
