"""FaultInjectionEngine unit tests: Gilbert–Elliott burst statistics,
the vectorized single-byte corrupt, the send-side (tx) path, per-seed
determinism, and the Prometheus counter export.  Pure numpy — no
device, no sockets (the SRTP-composed fault tests live in
test_utils.py and are marked slow)."""

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.utils.faults import FaultInjectionEngine, GilbertElliott
from libjitsi_tpu.utils.metrics import MetricsRegistry


def _batch(n, fill=0x42, cap=64, length=32):
    data = np.full((n, cap), fill, dtype=np.uint8)
    return PacketBatch(data, np.full(n, length, dtype=np.int32),
                       np.arange(n, dtype=np.int32))


# ------------------------------------------------------ Gilbert–Elliott

def test_ge_long_run_loss_rate_and_burstiness():
    rng = np.random.default_rng(7)
    ge = GilbertElliott(p_gb=0.02, p_bg=0.25)       # ~7.4% loss, 4-pkt bursts
    drops = np.concatenate([ge.losses(1000, rng) for _ in range(50)])
    rate = drops.mean()
    assert 0.04 < rate < 0.12, rate
    # burstiness: mean run length of consecutive losses ~ 1/p_bg = 4,
    # far above the ~1.07 an independent Bernoulli of the same rate gives
    edges = np.diff(drops.astype(np.int8))
    starts = (edges == 1).sum() + int(drops[0])
    mean_burst = drops.sum() / max(starts, 1)
    assert mean_burst > 2.0, mean_burst


def test_ge_state_persists_across_batches():
    rng = np.random.default_rng(0)
    ge = GilbertElliott(p_gb=1.0, p_bg=0.0)     # enters BAD, never leaves
    assert not ge.losses(1, rng)[0]             # first packet still GOOD
    assert ge.losses(5, rng).all()              # absorbed in BAD
    assert ge.losses(5, rng).all()              # ... across batches too


def test_ge_validates_probabilities():
    import pytest
    with pytest.raises(ValueError):
        GilbertElliott(p_gb=1.5, p_bg=0.1)


# ------------------------------------------------------------- corrupt

def test_corrupt_flips_exactly_one_byte_per_row():
    eng = FaultInjectionEngine(corrupt=1.0, seed=3)
    b = _batch(40)
    out, ok = eng.rtp_transformer.reverse_transform(b)
    assert ok.all() and eng.corrupted == 40
    diff = (out.data != 0x42).sum(axis=1)
    assert (diff == 1).all(), "each corrupted packet flips ONE byte"
    cols = np.nonzero(out.data != 0x42)[1]
    assert (cols < np.asarray(out.length)).all(), \
        "corruption landed past the packet length"


def test_zero_length_rows_are_never_corrupted():
    eng = FaultInjectionEngine(corrupt=1.0, seed=3)
    data = np.zeros((4, 16), dtype=np.uint8)
    b = PacketBatch(data, np.zeros(4, dtype=np.int32),
                    np.zeros(4, dtype=np.int32))
    out, ok = eng.rtp_transformer.reverse_transform(b)
    assert ok.all() and (out.data == 0).all()


# ------------------------------------------------------------- tx path

def test_tx_disabled_send_path_is_identity():
    eng = FaultInjectionEngine(loss=1.0, seed=1)     # rx drops everything
    b = _batch(8)
    out, ok = eng.rtp_transformer.transform(b)
    assert ok.all() and out is b and eng.tx_dropped == 0


def test_tx_enabled_faults_send_path_with_separate_counters():
    eng = FaultInjectionEngine(loss=0.5, seed=1, tx=True)
    b = _batch(200)
    _, ok_tx = eng.rtp_transformer.transform(b)
    assert 0 < eng.tx_dropped < 200 and eng.dropped == 0
    assert int((~ok_tx).sum()) == eng.tx_dropped
    _, ok_rx = eng.rtp_transformer.reverse_transform(b)
    assert eng.dropped == int((~ok_rx).sum()) > 0


def test_burst_loss_composes_with_bernoulli():
    eng = FaultInjectionEngine(loss=0.0, seed=5,
                               burst=(0.05, 0.2))
    total = 0
    for _ in range(20):
        _, ok = eng.rtp_transformer.reverse_transform(_batch(100))
        total += int((~ok).sum())
    assert eng.dropped == total > 0


def test_same_seed_same_fates():
    outs = []
    for _ in range(2):
        eng = FaultInjectionEngine(loss=0.3, corrupt=0.3, duplicate=0.2,
                                   reorder=0.2, seed=11, burst=(0.1, 0.3))
        b = _batch(64)
        out, ok = eng.rtp_transformer.reverse_transform(b)
        outs.append((out.data.copy(), np.asarray(out.length).copy(),
                     ok.copy()))
    assert np.array_equal(outs[0][0], outs[1][0])
    assert np.array_equal(outs[0][1], outs[1][1])
    assert np.array_equal(outs[0][2], outs[1][2])


# ------------------------------------------------------------- metrics

def test_fault_counters_render_as_prometheus_counters():
    eng = FaultInjectionEngine(loss=1.0, seed=2, tx=True)
    eng.rtp_transformer.reverse_transform(_batch(5))
    eng.rtp_transformer.transform(_batch(3))
    reg = MetricsRegistry()
    eng.register_metrics(reg)
    txt = reg.render()
    assert "# TYPE libjitsi_tpu_fault_dropped counter" in txt
    assert "libjitsi_tpu_fault_dropped 5" in txt
    assert "libjitsi_tpu_fault_tx_dropped 3" in txt
    assert "# HELP libjitsi_tpu_fault_tx_corrupted" in txt
