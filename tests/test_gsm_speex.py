"""GSM 06.10 (libgsm) and Speex (libspeex) codec bindings — the
reference's telephony/legacy codecs (SURVEY §2.5), host-side like its
JNI wrappers."""

import numpy as np
import pytest

from libjitsi_tpu.codecs.gsm import (FRAME_BYTES, FRAME_SAMPLES, GsmCodec,
                                     gsm_available)
from libjitsi_tpu.codecs.speex import (MODE_NB, MODE_WB, SpeexDecoder,
                                       SpeexEncoder, speex_available)


def _lagged_snr(ref: np.ndarray, out: np.ndarray, max_lag: int = 250,
                lo: int = 400, hi: int = 1200) -> float:
    """Best SNR over alignment lags (codecs have lookahead delay)."""
    best = -99.0
    a = ref[lo:hi].astype(float)
    for lag in range(max_lag):
        b = out[lo + lag:hi + lag].astype(float)
        if len(b) < len(a):
            break
        err = a - b
        snr = 10 * np.log10((a ** 2).mean() / max((err ** 2).mean(), 1e-9))
        best = max(best, snr)
    return best


def _tone(n, rate, hz=300, amp=5000):
    t = np.arange(n)
    return (amp * np.sin(2 * np.pi * hz * t / rate)).astype(np.int16)


@pytest.mark.skipif(not gsm_available(), reason="libgsm not present")
def test_gsm_roundtrip_rate_and_quality():
    c = GsmCodec()
    pcm = _tone(10 * FRAME_SAMPLES, 8000)
    enc = c.encode(pcm)
    assert len(enc) == 10 * FRAME_BYTES          # 13 kbit/s exactly
    dec = c.decode(enc)
    assert dec.shape == pcm.shape
    assert _lagged_snr(pcm, dec) > 8.0           # LPC codec on a tone
    with pytest.raises(ValueError):
        c.encode(pcm[:100])
    with pytest.raises(ValueError):
        c.decode(enc[:10])


@pytest.mark.skipif(not speex_available(), reason="libspeex not present")
@pytest.mark.parametrize("mode,rate", [(MODE_NB, 8000), (MODE_WB, 16000)])
def test_speex_roundtrip(mode, rate):
    enc, dec = SpeexEncoder(mode), SpeexDecoder(mode)
    assert enc.frame_size == dec.frame_size
    n = enc.frame_size
    pcm = _tone(10 * n, rate)
    outs = [dec.decode(enc.encode(pcm[k * n:(k + 1) * n]))
            for k in range(10)]
    out = np.concatenate(outs)
    assert _lagged_snr(pcm, out) > 10.0
    with pytest.raises(ValueError):
        enc.encode(pcm[: n // 2])


@pytest.mark.skipif(not speex_available(), reason="libspeex not present")
def test_speex_packet_loss_concealment():
    enc, dec = SpeexEncoder(MODE_NB), SpeexDecoder(MODE_NB)
    n = enc.frame_size
    pcm = _tone(4 * n, 8000)
    for k in range(3):
        dec.decode(enc.encode(pcm[k * n:(k + 1) * n]))
    plc = dec.decode(None)                       # lost frame
    assert plc.shape == (n,)
    assert np.abs(plc.astype(np.int32)).max() > 0   # extrapolated, not mute


@pytest.mark.skipif(not speex_available(), reason="libspeex not present")
def test_speex_invalid_mode_and_input_safety():
    with pytest.raises(ValueError):
        SpeexEncoder(mode=3)
    with pytest.raises(ValueError):
        SpeexDecoder(mode=-1)
    # encoder must not scribble over the caller's buffer
    enc = SpeexEncoder(MODE_NB)
    pcm = _tone(enc.frame_size, 8000)
    keep = pcm.copy()
    enc.encode(pcm)
    assert np.array_equal(pcm, keep)
    # read-only views are accepted
    ro = pcm.copy()
    ro.setflags(write=False)
    enc.encode(ro)
