"""Metrics exporter, timing ring, fault-injection engine."""

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform import TransformEngineChain
from libjitsi_tpu.transform.srtp import SrtpStreamTable
from libjitsi_tpu.transform.srtp.engine import SrtpTransformEngine
from libjitsi_tpu.utils import FaultInjectionEngine, MetricsRegistry
import pytest


def test_metrics_render_arrays_and_scalars():
    m = MetricsRegistry()
    arr = np.array([5, 0, 9], dtype=np.int64)
    m.register_array("rx_packets", arr, help_="received")
    m.register_scalar("streams_active", lambda: 2)
    active = np.array([True, False, True])
    text = m.render(active=active)
    assert 'libjitsi_tpu_rx_packets{stream="0"} 5' in text
    assert 'stream="1"' not in text          # masked
    assert 'libjitsi_tpu_rx_packets{stream="2"} 9' in text
    assert "libjitsi_tpu_streams_active 2" in text
    # live view: mutating the array changes the next render
    arr[0] = 6
    assert 'stream="0"} 6' in m.render(active=active)


def test_timing_ring_percentiles():
    m = MetricsRegistry()
    ring = m.timing("srtp_batch")
    for v in [0.001] * 98 + [0.05, 0.06]:
        ring.record(v)
    assert ring.percentile(50) == 0.001
    assert ring.percentile(99) >= 0.05
    render = m.render()
    # Prometheus summary form: numeric quantile labels + _sum/_count
    assert 'quantile="0.99"' in render
    assert "libjitsi_tpu_srtp_batch_seconds_count 100" in render


@pytest.mark.slow
def test_fault_injection_loss_and_corrupt_against_srtp():
    MK, MS = bytes(16), bytes(14)
    tx = SrtpStreamTable(capacity=2)
    tx.add_stream(0, MK, MS)
    rx = SrtpStreamTable(capacity=2)
    rx.add_stream(0, MK, MS)
    n = 200
    b = rtp_header.build([b"m%03d" % i for i in range(n)], list(range(n)),
                         [0] * n, [7] * n, [96] * n, stream=[0] * n)
    wire = tx.protect_rtp(b)
    faults = FaultInjectionEngine(loss=0.2, corrupt=0.1, seed=42)
    # engine list is send-order: SRTP last before the wire, the network
    # simulator after it — so on receive faults run FIRST (on ciphertext)
    chain = TransformEngineChain([SrtpTransformEngine(tx, rx), faults])
    dec, ok = chain.rtp_transformer.reverse_transform(wire)
    # dropped rows are masked, corrupted rows fail auth; the rest decode
    assert faults.dropped > 10 and faults.corrupted > 5
    assert ok.sum() <= n - faults.dropped
    assert ok.sum() >= n - faults.dropped - faults.corrupted - 5
    hdr = rtp_header.parse(dec)
    good = np.nonzero(ok)[0]
    for i in good[:20]:
        raw = dec.to_bytes(int(i))
        assert raw[int(hdr.payload_off[i]):].startswith(b"m")


@pytest.mark.slow
def test_fault_injection_duplicates_rejected_by_replay():
    MK, MS = bytes(16), bytes(14)
    tx = SrtpStreamTable(capacity=2)
    tx.add_stream(0, MK, MS)
    rx = SrtpStreamTable(capacity=2)
    rx.add_stream(0, MK, MS)
    n = 100
    b = rtp_header.build([b"x"] * n, list(range(n)), [0] * n, [7] * n,
                         [96] * n, stream=[0] * n)
    wire = tx.protect_rtp(b)
    faults = FaultInjectionEngine(duplicate=0.3, seed=7)
    chain = TransformEngineChain([SrtpTransformEngine(tx, rx), faults])
    dec, ok = chain.rtp_transformer.reverse_transform(wire)
    assert faults.duplicated > 10
    # exactly one accept per original packet: dups killed by replay dedup
    assert ok.sum() == n
