"""RFC 4571 TCP media connector: framing, loopback transport, SRTP leg."""

import numpy as np
import pytest

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io.tcp import TcpConnector, _FrameBuffer, frame


def test_framing_roundtrip_incremental():
    pkts = [b"\x80" + bytes(range(20)), b"x" * 1, b"y" * 1400]
    blob = b"".join(frame(p) for p in pkts)
    fb = _FrameBuffer()
    got = []
    # feed in adversarial chunk sizes (1, 3, 7, ...) across frame edges
    i, step = 0, 1
    while i < len(blob):
        got += fb.feed(blob[i:i + step])
        i += step
        step = (step % 9) + 1
    assert got == pkts


def test_frame_rejects_oversize():
    with pytest.raises(ValueError):
        frame(b"z" * 65536)


def test_loopback_batch_transport():
    srv = TcpConnector(listen=True)
    cli = TcpConnector()
    dst = cli.connect("127.0.0.1", srv.port)
    payloads = [bytes([0x80, 96, 0, i, 0, 0, 0, i, 0, 0, 0, 7]) + b"p" * i
                for i in range(5)]
    cli.send_batch(PacketBatch.from_payloads(payloads), dst)
    got, addrs = srv.recv_batch(timeout_ms=2000)
    assert got.to_payloads() == payloads
    assert len(set(addrs)) == 1
    # reverse direction over the accepted connection
    peer = srv.peers()[0]
    srv.send_batch(PacketBatch.from_payloads(payloads[:2]), peer)
    back, _ = cli.recv_batch(timeout_ms=2000)
    assert back.to_payloads() == payloads[:2]
    cli.close()
    srv.close()


def test_peer_close_is_dropped():
    srv = TcpConnector(listen=True)
    cli = TcpConnector()
    cli.connect("127.0.0.1", srv.port)
    assert len(srv.peers()) == 1
    cli.close()
    srv.recv_batch(timeout_ms=50)
    assert len(srv.peers()) == 0
    srv.close()


def test_oversize_frame_counted_not_silent():
    srv = TcpConnector(listen=True, mtu=100)
    cli = TcpConnector()
    dst = cli.connect("127.0.0.1", srv.port)
    big = b"\x80" + b"K" * 300          # legitimate RFC 4571, > row width
    small = b"\x80" + b"s" * 20
    cli.send_batch(PacketBatch.from_payloads([big, small], capacity=1500),
                   dst)
    got, _ = srv.recv_batch(timeout_ms=2000)
    assert got.to_payloads() == [small]
    assert srv.dropped_oversize == 1
    cli.close(); srv.close()


def test_stalled_peer_send_times_out_and_drops():
    srv = TcpConnector(listen=True)
    cli = TcpConnector(send_timeout_s=0.5)
    dst = cli.connect("127.0.0.1", srv.port)
    srv.peers()                          # accept, then never read
    payload = [b"\x80" + b"z" * 1400] * 64
    batch = PacketBatch.from_payloads(payload)
    # shrink buffers so the zero-window stall happens fast
    import socket as pysock
    cli._conns[dst].setsockopt(pysock.SOL_SOCKET, pysock.SO_SNDBUF, 4096)
    with pytest.raises(ConnectionError):
        for _ in range(600):             # ~80 MB >> buffers
            cli.send_batch(batch, dst)
    assert dst not in cli._conns         # peer dropped
    cli.close(); srv.close()


def test_recv_batch_respects_max_batch_with_overflow():
    srv = TcpConnector(listen=True, max_batch=8)
    cli = TcpConnector()
    dst = cli.connect("127.0.0.1", srv.port)
    pkts = [bytes([0x80, 96, 0, i]) + b"\x00" * 8 for i in range(30)]
    cli.send_batch(PacketBatch.from_payloads(pkts), dst)
    got = []
    for _ in range(10):
        b, _addrs = srv.recv_batch(timeout_ms=500)
        assert b.batch_size <= 8         # the contract, even mid-flood
        got += b.to_payloads()
        if len(got) == 30:
            break
    assert got == pkts                   # nothing lost, order kept
    cli.close(); srv.close()


def test_media_loop_runs_over_tcp_engine():
    """The production MediaLoop with the TCP adapter: protected RTP in,
    SRTP reverse chain, echo back over the same TCP connection."""
    import libjitsi_tpu
    from libjitsi_tpu.io.loop import MediaLoop
    from libjitsi_tpu.io.tcp import TcpMediaEngine
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.service.media_stream import StreamRegistry
    from libjitsi_tpu.transform import (SrtpTransformEngine,
                                        TransformEngineChain)
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    MK, MS = bytes(range(16)), bytes(range(30, 44))
    libjitsi_tpu.stop(); libjitsi_tpu.init()
    try:
        reg = StreamRegistry(libjitsi_tpu.configuration_service(),
                             capacity=8)
        rx = SrtpStreamTable(capacity=8); rx.add_stream(2, MK, MS)
        tx = SrtpStreamTable(capacity=8); tx.add_stream(2, MK, MS)
        chain = TransformEngineChain([SrtpTransformEngine(tx, rx)])

        def on_media(batch, ok):
            rows = np.nonzero(ok)[0]
            if not len(rows):
                return None
            return PacketBatch(batch.data[rows],
                               np.asarray(batch.length)[rows],
                               batch.stream[rows])

        srv = TcpConnector(listen=True, max_batch=64)
        bridge = MediaLoop(TcpMediaEngine(srv), reg, on_media=on_media,
                           chain=chain)
        reg.map_ssrc(0xFEED, 2)

        cli_tab = SrtpStreamTable(capacity=1)
        cli_tab.add_stream(0, MK, MS)
        wire = cli_tab.protect_rtp(rtp_header.build(
            [b"media-%d" % i for i in range(6)],
            list(range(300, 306)), [0] * 6, [0xFEED] * 6, [96] * 6,
            stream=[0] * 6))
        cli = TcpConnector()
        dst = cli.connect("127.0.0.1", srv.port)
        cli.send_batch(wire, dst)

        got = 0
        for _ in range(200):
            got += bridge.tick()
            if got >= 6:
                break
        assert got == 6

        # the echoes may straddle recv windows on a stream transport:
        # keep ticking the bridge and draining until all 6 arrive
        echoes = []
        for _ in range(200):
            bridge.tick()
            back, _ = cli.recv_batch(timeout_ms=50)
            echoes += back.to_payloads()
            if len(echoes) >= 6:
                break
        assert len(echoes) == 6              # echo re-protected by tx
        dec_tab = SrtpStreamTable(capacity=1)
        dec_tab.add_stream(0, MK, MS)
        dec, ok = dec_tab.unprotect_rtp(PacketBatch.from_payloads(
            echoes, stream=[0] * 6))
        assert ok.all()
        cli.close(); bridge.engine.close()
    finally:
        libjitsi_tpu.stop()


def test_srtp_protected_media_over_tcp():
    """Full leg: SDES-keyed SRTP protect -> RFC 4571 TCP -> unprotect."""
    import libjitsi_tpu

    libjitsi_tpu.init()
    try:
        svc = libjitsi_tpu.media_service()
        a = svc.create_media_stream("audio")
        b = svc.create_media_stream("audio")
        answer = b.sdes.create_answer(a.sdes.create_offer())
        a.sdes.accept_answer(answer)
        a.set_remote_ssrc(b.local_ssrc)
        b.set_remote_ssrc(a.local_ssrc)
        a.start(); b.start()

        srv = TcpConnector(listen=True)
        cli = TcpConnector()
        dst = cli.connect("127.0.0.1", srv.port)
        wire = a.send([b"g722-frame-" + bytes(40)], pt=9)
        cli.send_batch(PacketBatch.from_payloads(wire), dst)
        got, _ = srv.recv_batch(timeout_ms=2000)
        batch, ok = b.receive(got.to_payloads())
        assert all(ok)
        cli.close(); srv.close()
    finally:
        libjitsi_tpu.stop()
