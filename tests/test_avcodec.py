"""libavcodec H.264 bitstream codec + RFC 6184 media path.

The H.264 analog of test_vpx: REAL bitstreams (libx264-encoded) through
the framework's packetization and back through the native decoder.
"""

import numpy as np
import pytest

from libjitsi_tpu.codecs import avcodec
from libjitsi_tpu.codecs import h264 as h264rtp

pytestmark = pytest.mark.skipif(not avcodec.h264_available(),
                                reason="libavcodec/libx264 not present")

W, H = 64, 48


def _frames(n, seed=0):
    out = []
    for i in range(n):
        y = (np.add.outer(np.arange(H), np.arange(W)) * 3
             + i * 17 + seed).astype(np.uint8)
        u = np.full((H // 2, W // 2), 80 + 5 * i, np.uint8)
        v = np.full((H // 2, W // 2), 160 - 5 * i, np.uint8)
        out.append((y, u, v))
    return out


def test_h264_encode_decode_roundtrip():
    enc = avcodec.H264Encoder(W, H, fps=30)
    dec = avcodec.H264Decoder()
    frames = _frames(5)
    decoded = []
    for y, u, v in frames:
        for au in enc.encode(y, u, v):
            decoded += dec.decode(au)
    for au in enc.flush():
        decoded += dec.decode(au)
    decoded += dec.flush()
    assert len(decoded) == len(frames)
    for (y, u, v), (gy, gu, gv) in zip(frames, decoded):
        assert gy.shape == (H, W)
        assert abs(gy.astype(int) - y.astype(int)).mean() < 4.0
        assert abs(gu.astype(int) - u.astype(int)).mean() < 4.0


def test_h264_through_rfc6184_packetization():
    """encoder AU -> split_annexb -> packetize (MTU-bounded) ->
    depacketize -> decode: the full RTP-layer media path."""
    enc = avcodec.H264Encoder(W, H, fps=30)
    dec = avcodec.H264Decoder()
    depkt = h264rtp.H264Depacketizer()
    frames = _frames(4, seed=9)
    n_out = 0
    for y, u, v in frames:
        for au in enc.encode(y, u, v):
            nals = h264rtp.split_annexb(au)
            assert nals and all(n[0] & 0x80 == 0 for n in nals)
            payloads = h264rtp.packetize(nals, mtu=120)  # force FU-A
            assert all(len(p) <= 120 for p in payloads)
            got_nals = []
            for p in payloads:
                got_nals += depkt.push(p)
            assert got_nals == nals          # byte-exact NAL recovery
            rebuilt = b"".join(b"\x00\x00\x00\x01" + n
                               for n in got_nals)
            out = dec.decode(rebuilt)
            n_out += len(out)
            for gy, _gu, _gv in out:
                assert gy.shape == (H, W)
    assert n_out >= len(frames) - 1          # decoder may buffer one


def test_split_annexb_mixed_start_codes():
    nals = [bytes([0x67, 1, 2, 3]), bytes([0x68, 9]),
            bytes([0x65] + list(range(60)))]
    au = (b"\x00\x00\x00\x01" + nals[0] + b"\x00\x00\x01" + nals[1]
          + b"\x00\x00\x00\x01" + nals[2])
    assert h264rtp.split_annexb(au) == nals
