"""Conference mixer: mix-minus math, clipping, RFC 6465 levels.

Reference behavior under test: org.jitsi.impl.neomedia.conference.AudioMixer
(total-sum-minus-self with int16 saturation) and
org.jitsi.impl.neomedia.audiolevel.AudioLevelCalculator (0..127 dBov).
"""

import numpy as np
import pytest

from libjitsi_tpu.conference import AudioMixer, mix_minus


def test_mix_minus_matches_naive():
    rng = np.random.default_rng(1)
    n, f = 16, 160
    pcm = rng.integers(-1000, 1000, (n, f)).astype(np.int16)
    out, levels = mix_minus(pcm)
    out = np.asarray(out, dtype=np.int64)
    for i in range(n):
        want = pcm.astype(np.int64).sum(axis=0) - pcm[i]
        np.testing.assert_array_equal(out[i], want)


def test_mix_minus_saturates():
    pcm = np.full((4, 8), 30000, dtype=np.int16)
    out, _ = mix_minus(pcm)
    assert np.all(np.asarray(out) == 32767)
    pcm = np.full((4, 8), -30000, dtype=np.int16)
    out, _ = mix_minus(pcm)
    assert np.all(np.asarray(out) == -32768)


def test_inactive_rows_excluded_but_hear_all():
    pcm = np.stack([np.full(8, 100, np.int16),
                    np.full(8, 200, np.int16),
                    np.full(8, 999, np.int16)])  # row 2 inactive
    active = np.array([True, True, False])
    out, levels = mix_minus(pcm, active)
    out = np.asarray(out)
    assert np.all(out[0] == 200)
    assert np.all(out[1] == 100)
    assert np.all(out[2] == 300)          # full mix, self not in it
    assert levels[2] == 127               # inactive reports silence


def test_levels_scale():
    f = 480
    full = (np.sin(np.linspace(0, 40 * np.pi, f)) * 32767).astype(np.int16)
    quiet = (full / 1000).astype(np.int16)
    silent = np.zeros(f, np.int16)
    _, levels = mix_minus(np.stack([full, quiet, silent]))
    levels = np.asarray(levels)
    assert levels[0] <= 5                  # ~ -3 dBov sine
    assert 55 <= levels[1] <= 75           # ~ -63 dBov
    assert levels[2] == 127


def test_audio_mixer_device():
    m = AudioMixer(capacity=8, frame_samples=16)
    m.add_participant(0)
    m.add_participant(1)
    m.push(0, np.full(16, 10, np.int16))
    m.push(1, np.full(16, 20, np.int16))
    out, levels = m.mix()
    assert np.all(out[0] == 20) and np.all(out[1] == 10)
    # frames are consumed: next tick without push mixes silence
    out, _ = m.mix()
    assert np.all(out[:2] == 0)
    with pytest.raises(ValueError):
        m.push(0, np.zeros(8, np.int16))
