"""SLO engine: TickWindowRing algebra, burn-rate math against the
analytic value, the multi-window alert pairing, spec/metric reads over
a live registry, and the metric/flight-event exports.

Burn rate is the SRE-workbook quantity `bad_fraction / (1 - objective)`
— the properties tested here are the ones the engine's correctness
hangs on: a steady error rate converges to the analytic burn in every
window (step-change property), totals survive ring wrap-around without
leaking old buckets, and empty windows read as zero burn rather than
NaN.
"""

import numpy as np
import pytest

from libjitsi_tpu.utils.flight import FlightRecorder
from libjitsi_tpu.utils.metrics import MetricsRegistry
from libjitsi_tpu.utils.slo import (SloEngine, SloSpec, TickWindowRing,
                                    default_slos)


# ------------------------------------------------------ TickWindowRing

def test_ring_totals_match_naive_sliding_window():
    """Property: after every push, ring totals equal a naive sliding
    sum over the last `covered` pushes, where covered is within one
    bucket of the window (the quantization the ring trades for O(1)
    pushes): sum(last window-bucket+1) <= totals <= sum(last window)."""
    rng = np.random.default_rng(3)
    window, buckets = 100, 10
    ring = TickWindowRing(window, buckets=buckets)
    bt = ring.bucket_ticks
    assert bt == 10 and ring.n_buckets == 10
    goods, bads = [], []
    for _ in range(350):
        g, b = float(rng.integers(0, 50)), float(rng.integers(0, 5))
        goods.append(g)
        bads.append(b)
        ring.push(g, b)
        got_g, got_b = ring.totals()
        for series, got in ((goods, got_g), (bads, got_b)):
            lo = sum(series[-(window - bt + 1):])
            hi = sum(series[-window:])
            assert lo <= got <= hi, (len(series), lo, got, hi)


def test_ring_wraps_without_leaking_old_buckets():
    ring = TickWindowRing(64, buckets=8)     # 8 ticks per bucket
    for _ in range(64):
        ring.push(1.0, 1.0)
    assert ring.totals() == (64.0, 64.0)
    # 64 more zero pushes flush every bucket: nothing may survive
    for _ in range(64 + 8):
        ring.push(0.0, 0.0)
    assert ring.totals() == (0.0, 0.0)


def test_ring_tiny_and_degenerate_windows():
    r = TickWindowRing(1, buckets=64)        # window smaller than buckets
    r.push(2.0, 3.0)
    assert r.totals() == (2.0, 3.0)
    assert r.n_buckets >= 1
    r0 = TickWindowRing(0)                   # clamps, never div-zero
    r0.push(1.0, 0.0)
    assert r0.totals()[0] >= 0.0


# --------------------------------------------------------------- specs

def test_slospec_validation():
    with pytest.raises(ValueError):
        SloSpec("x", objective=1.0)
    with pytest.raises(ValueError):
        SloSpec("x", objective=0.0)
    with pytest.raises(ValueError):
        SloSpec("x", objective=0.5, kind="weird")
    assert default_slos()[0].kind == "latency"


def test_engine_rejects_duplicate_slo():
    eng = SloEngine(MetricsRegistry(), [SloSpec("a", objective=0.9)])
    with pytest.raises(ValueError):
        eng.add(SloSpec("a", objective=0.99))


# ----------------------------------------------------- burn-rate math

def _ratio_engine(objective=0.99, **kw):
    reg = MetricsRegistry()
    state = {"bad": 0.0, "total": 0.0}
    reg.register_scalar("bad_things", lambda: state["bad"],
                        kind="counter")
    reg.register_scalar("all_things", lambda: state["total"],
                        kind="counter")
    eng = SloEngine(reg, [SloSpec("r", objective=objective,
                                  bad_metric="bad_things",
                                  total_metric="all_things")], **kw)
    return eng, state


def test_step_change_converges_to_analytic_burn_rate():
    """A steady bad-fraction p must converge to burn = p/(1-objective)
    in every window once the window fills."""
    p, objective = 0.02, 0.99
    eng, state = _ratio_engine(objective=objective)
    for t in range(1, 4001):
        state["total"] = 100.0 * t           # 100 events/tick
        state["bad"] = 100.0 * t * p
        eng.on_tick()
    analytic = p / (1.0 - objective)         # = 2.0
    burns = eng.burn_rates("r")
    # 1m/5m windows (3000/15000 ticks at 20 ms) have fully converged
    assert burns["1m"] == pytest.approx(analytic, rel=1e-6)
    assert burns["5m"] == pytest.approx(analytic, rel=1e-6)
    # longer windows are still part-full but must agree on the RATE
    assert burns["30m"] == pytest.approx(analytic, rel=1e-6)


def test_empty_windows_read_zero_burn_not_nan():
    eng, _state = _ratio_engine()
    assert eng.burn_rates("r") == {"1m": 0.0, "5m": 0.0,
                                   "30m": 0.0, "6h": 0.0}
    eng.on_tick()                            # zero traffic tick
    assert all(v == 0.0 for v in eng.burn_rates("r").values())
    assert eng.state("r") == "ok"


def test_burn_survives_window_wrap_after_burst_clears():
    """An error burst must age out of the fast windows: burn returns
    to ~0 once the window has rotated past the burst."""
    eng, state = _ratio_engine()
    wt = eng._rings["r"]["1m"]
    window_ticks = wt.bucket_ticks * wt.n_buckets
    state["total"], state["bad"] = 1000.0, 100.0   # 10% bad burst
    eng.on_tick()
    assert eng.burn_rates("r")["1m"] > 0.0
    for t in range(window_ticks + wt.bucket_ticks):
        state["total"] += 100.0              # clean traffic after
        eng.on_tick()
    assert eng.burn_rates("r")["1m"] == pytest.approx(0.0)


def test_counter_rewind_is_clamped_not_negative():
    """A checkpoint restore can rewind counters; deltas clamp at 0."""
    eng, state = _ratio_engine()
    state["total"], state["bad"] = 1000.0, 10.0
    eng.on_tick()
    state["total"], state["bad"] = 100.0, 1.0    # rewind
    eng.on_tick()
    good, bad = eng._rings["r"]["1m"].totals()
    assert good >= 0.0 and bad >= 0.0


# ------------------------------------------------- alert state machine

def test_fast_burn_requires_both_fast_windows_and_emits_event():
    fr = FlightRecorder()
    eng, state = _ratio_engine(flight=fr)
    # saturate fast windows with a catastrophic error rate
    for t in range(1, 3001):
        state["total"] = 100.0 * t
        state["bad"] = 50.0 * t              # 50% bad, burn = 50
        eng.on_tick()
    assert eng.state("r") == "fast_burn"
    assert eng.alerts_total >= 1
    alerts = [e for e in fr.dump_all()["global"]
              if e["kind"] == "slo_alert"]
    assert alerts and alerts[-1]["slo"] == "r"
    assert alerts[-1]["state"] in ("fast_burn", "slow_burn")
    assert set(alerts[-1]["burn"]) == {"1m", "5m", "30m", "6h"}


def test_short_blip_does_not_fast_burn():
    """One bad tick cannot trip the pair: the 5m window dilutes it."""
    eng, state = _ratio_engine()
    # fill with clean traffic first so the 5m window has ballast
    for t in range(1, 15001):
        state["total"] = 100.0 * t
        eng.on_tick()
    state["bad"] = 200.0                     # one nasty tick
    state["total"] += 100.0
    eng.on_tick()
    assert eng.state("r") != "fast_burn"


def test_worst_state_ranking():
    reg = MetricsRegistry()
    eng = SloEngine(reg, [SloSpec("a", objective=0.9),
                          SloSpec("b", objective=0.9)])
    eng._state["a"] = "slow_burn"
    assert eng.state() == "slow_burn"
    eng._state["b"] = "fast_burn"
    assert eng.state() == "fast_burn"
    assert SloEngine(reg).state() == "ok"


# ------------------------------------------------------ latency + reads

def test_latency_spec_reads_histogram_buckets():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", (0.01, 0.02, 0.05))
    eng = SloEngine(reg, [SloSpec("lat", objective=0.9, kind="latency",
                                  metric="lat_seconds",
                                  budget_s=0.02)])
    h.observe_array(np.array([0.005, 0.015, 0.03, 0.08]))
    eng.on_tick()
    good, bad = eng._rings["lat"]["1m"].totals()
    assert (good, bad) == (2.0, 2.0)         # le=0.02 cumulative = 2


def test_missing_family_reads_none_and_pushes_zero():
    reg = MetricsRegistry()
    eng = SloEngine(reg, [SloSpec("ghost", objective=0.9,
                                  bad_metric="nope",
                                  total_metric="also_nope")])
    eng.on_tick()                            # must not raise
    assert eng.burn_rates("ghost")["1m"] == 0.0
    assert eng.state("ghost") == "ok"


# ------------------------------------------------------------- exports

def test_register_metrics_exports_burn_state_and_alert_families():
    reg = MetricsRegistry()
    eng, state = _ratio_engine()
    eng.register_metrics(reg)
    eng.on_tick()
    text = reg.render()
    assert "# TYPE libjitsi_tpu_slo_burn_rate gauge" in text
    assert 'libjitsi_tpu_slo_burn_rate{slo="r",window="1m"}' in text
    assert 'libjitsi_tpu_slo_state{slo="r"} 0' in text
    assert "libjitsi_tpu_slo_alerts_total 0" in text


def test_status_is_json_ready_and_complete():
    import json

    eng, state = _ratio_engine()
    state["total"], state["bad"] = 100.0, 1.0
    eng.on_tick()
    doc = json.loads(json.dumps(eng.status()))
    assert doc["ticks"] == 1 and doc["state"] == "ok"
    (slo,) = doc["slos"]
    assert slo["name"] == "r"
    assert set(slo["burn"]) == {"1m", "5m", "30m", "6h"}
    assert slo["totals"]["1m"]["bad"] == 1.0
