"""DTLS-SRTP: in-memory handshake, profile negotiation, key export,
fingerprint verification, demux, and keys driving real SRTP tables.

Reference behaviors: DtlsControlImpl/DtlsPacketTransformer (RFC 5764).
"""

import numpy as np
import pytest

from libjitsi_tpu.control.dtls import (
    HAVE_CRYPTOGRAPHY,
    DtlsAssociationTable,
    DtlsSrtpEndpoint,
    StubDtlsEndpoint,
    fingerprint,
    generate_certificate,
    is_dtls,
)
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable


needs_openssl = pytest.mark.skipif(
    not HAVE_CRYPTOGRAPHY,
    reason="gated dependency: the 'cryptography' package is not installed")


class _FakeEng:
    def __init__(self):
        self.out = []

    def send_batch(self, batch, ip, port):
        for i in range(batch.batch_size):
            self.out.append((batch.to_bytes(i), (ip, port)))
        return batch.batch_size


class _FakeLoop:
    def __init__(self, n=8):
        self.addr_ip = np.zeros(n, np.uint32)
        self.addr_port = np.zeros(n, np.uint16)
        self.engine = _FakeEng()
        self.released = []
        self.discarded = []

    def hold_stream(self, sid, max_packets=64):
        pass

    def release_stream(self, sid):
        self.released.append(sid)
        return 0

    def discard_stream(self, sid):
        self.discarded.append(sid)


def _assert_complementary(server_ep, client_ep):
    """The keys that landed are THIS client's keys (never cross-row)."""
    _, stk, sts, srk, srs = server_ep.srtp_keys()
    _, ctk, cts, crk, crs = client_ep.srtp_keys()
    assert (ctk, cts) == (srk, srs)
    assert (crk, crs) == (stk, sts)


def run_handshake(client: DtlsSrtpEndpoint, server: DtlsSrtpEndpoint,
                  drop=lambda i: False):
    """Pump datagrams between the endpoints until both complete."""
    wire = [(0, p) for p in client.handshake_packets()]
    i = 0
    rounds = 0
    while (not client.complete or not server.complete) and rounds < 50:
        rounds += 1
        nxt = []
        for who, pkt in wire:
            i += 1
            if drop(i):
                continue
            ep = server if who == 0 else client
            nxt += [(1 - who, p) for p in ep.feed(pkt)]
        wire = nxt
        if not wire and (not client.complete or not server.complete):
            wire = [(0, p) for p in client.handshake_packets()] + \
                   [(1, p) for p in server.handshake_packets()]
    assert client.complete and server.complete, "handshake did not finish"


@needs_openssl
def test_handshake_and_key_agreement():
    c = DtlsSrtpEndpoint("client")
    s = DtlsSrtpEndpoint("server")
    run_handshake(c, s)
    pc, c_txk, c_txs, c_rxk, c_rxs = c.srtp_keys()
    ps, s_txk, s_txs, s_rxk, s_rxs = s.srtp_keys()
    assert pc is ps
    # client's tx keys are the server's rx keys and vice versa
    assert (c_txk, c_txs) == (s_rxk, s_rxs)
    assert (c_rxk, c_rxs) == (s_txk, s_txs)
    assert len(c_txk) == pc.policy.enc_key_len


@needs_openssl
def test_profile_negotiation_intersection():
    c = DtlsSrtpEndpoint("client",
                         profiles=[SrtpProfile.AEAD_AES_128_GCM])
    s = DtlsSrtpEndpoint("server",
                         profiles=[SrtpProfile.AES_CM_128_HMAC_SHA1_80,
                                   SrtpProfile.AEAD_AES_128_GCM])
    run_handshake(c, s)
    assert c.selected_profile is SrtpProfile.AEAD_AES_128_GCM


@needs_openssl
def test_fingerprint_verification():
    cert, key, fp = generate_certificate()
    c = DtlsSrtpEndpoint("client", cert_der=cert, key_der=key)
    # server pinned to the RIGHT fingerprint: fine
    s = DtlsSrtpEndpoint("server", remote_fingerprint=fp)
    run_handshake(c, s)

    # server pinned to a WRONG fingerprint: handshake completion raises
    wrong = fingerprint(b"not-the-cert")
    c2 = DtlsSrtpEndpoint("client", cert_der=cert, key_der=key)
    s2 = DtlsSrtpEndpoint("server", remote_fingerprint=wrong)
    with pytest.raises((RuntimeError, AssertionError)):
        run_handshake(c2, s2)


def test_demux_first_byte():
    assert is_dtls(bytes([22, 0xfe, 0xfd]))       # handshake record
    assert is_dtls(bytes([20]))                    # ccs
    assert not is_dtls(bytes([0x80, 96]))          # RTP v2
    assert not is_dtls(bytes([0x81, 200]))         # RTCP
    assert not is_dtls(b"")
    assert not is_dtls(bytes([0]))                 # STUN would be 0..3


@needs_openssl
@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_exported_keys_drive_srtp_tables():
    """End to end: DTLS handshake keys installed into SrtpStreamTables,
    protected media flows client -> server."""
    c = DtlsSrtpEndpoint("client")
    s = DtlsSrtpEndpoint("server")
    run_handshake(c, s)
    prof, c_txk, c_txs, _, _ = c.srtp_keys()
    _, _, _, s_rxk, s_rxs = s.srtp_keys()
    tx = SrtpStreamTable(capacity=2, profile=prof)
    tx.add_stream(0, c_txk, c_txs)
    rx = SrtpStreamTable(capacity=2, profile=prof)
    rx.add_stream(0, s_rxk, s_rxs)
    b = rtp_header.build([b"dtls-keyed-media"], [42], [0], [9], [96],
                         stream=[0])
    dec, ok = rx.unprotect_rtp(tx.protect_rtp(b))
    assert ok.all()
    assert dec.to_bytes(0) == b.to_bytes(0)


@needs_openssl
@pytest.mark.slow
def test_lossy_handshake_completes_via_retransmission():
    """VERDICT r2 #5: 30% datagram loss each way; the RFC 6347 flight
    timers (DtlsSrtpEndpoint.tick) must still complete the handshake.
    Real-time test: OpenSSL's initial flight timer is 1 s."""
    import time as _t

    rng = np.random.default_rng(7)
    c = DtlsSrtpEndpoint("client")
    s = DtlsSrtpEndpoint("server", cookie_exchange=True)

    def deliver(dst, datagrams):
        out = []
        for d in datagrams:
            if rng.random() < 0.30:
                continue                      # lost
            out.extend(dst.feed(d))
        return out

    pend_to_s = c.handshake_packets()
    t0 = _t.time()
    while not (c.complete and s.complete):
        assert _t.time() - t0 < 25, "handshake deadlocked under loss"
        pend_to_c = deliver(s, pend_to_s)
        pend_to_s = deliver(c, pend_to_c)
        pend_to_s += c.tick()
        for d in s.tick():
            pend_to_s.extend(c.feed(d))
        _t.sleep(0.05)
    assert c.retransmits + s.retransmits > 0, \
        "loss seeded but no flight was ever retransmitted"
    pc, ps = c.srtp_keys(), s.srtp_keys()
    assert pc[0] == ps[0]
    assert (pc[1], pc[2]) == (ps[3], ps[4])
    assert (pc[3], pc[4]) == (ps[1], ps[2])


def test_media_loop_hold_queues_and_releases():
    """Early media (racing the DTLS Finished flight) queues raw and
    replays through the chain once keys install."""
    import libjitsi_tpu
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.io.loop import MediaLoop
    from libjitsi_tpu.service.media_stream import StreamRegistry

    libjitsi_tpu.stop()
    libjitsi_tpu.init()

    class _FakeEngine:
        port = 0

        def recv_batch(self, timeout_ms):
            b = self._next
            self._next = (PacketBatch.from_payloads([]),
                          np.zeros(0, np.uint32), np.zeros(0, np.uint16))
            return b

        def send_batch(self, batch, ip, port):
            return batch.batch_size

    reg = StreamRegistry(libjitsi_tpu.configuration_service(),
                         capacity=4)
    seen = []
    eng = _FakeEngine()
    loop = MediaLoop(eng, reg,
                     on_media=lambda b, ok: seen.append(
                         (b.batch_size, ok.sum())) or None)
    reg.map_ssrc(0xABC, 2)
    loop.hold_stream(2)
    wire = rtp_header.build([b"early-%d" % i for i in range(3)],
                            [10, 11, 12], [0] * 3, [0xABC] * 3,
                            [96] * 3, stream=[0] * 3)
    pkts = [wire.to_bytes(i) for i in range(3)]
    eng._next = (PacketBatch.from_payloads(pkts),
                 np.full(3, 0x7F000001, np.uint32),
                 np.full(3, 5555, np.uint16))
    loop.tick()
    assert seen == [], "held media leaked through"
    n = loop.release_stream(2)
    assert n == 3
    assert seen == [(3, 3)]
    # bounded: queue holds max_packets, oldest evicted
    loop.hold_stream(2, max_packets=2)
    eng._next = (PacketBatch.from_payloads(pkts),
                 np.full(3, 0x7F000001, np.uint32),
                 np.full(3, 5555, np.uint16))
    loop.tick()
    assert loop.release_stream(2) == 2


def test_claim_ambiguity_and_recycled_address():
    """`_claim` under storm: an unknown source facing MULTIPLE unclaimed
    rows is dropped (never guessed onto a row), and a forgotten
    5-tuple's queued datagrams are purged so a rejoin on the recycled
    ip:port never gets the old association's bytes fed into its row."""
    installed = []
    loop = _FakeLoop()
    table = DtlsAssociationTable(
        loop, SrtpProfile.AES_CM_128_HMAC_SHA1_80,
        lambda sid, ep: installed.append((sid, ep)),
        deferred=True, endpoint_factory=StubDtlsEndpoint)

    # two unclaimed pending rows: ambiguous source is dropped
    table.join(1)
    table.join(2)
    stray = StubDtlsEndpoint("client")
    for d in stray.handshake_packets():
        table.on_dtls(d, (0x0A000001, 5000))
    table.process()
    assert (0x0A000001, 5000) not in table.addr_of
    table.forget(1)
    table.forget(2)

    # recycled 5-tuple: old association queues a datagram, the stream
    # leaves (forget), a new association re-binds the same addr
    addr = (0x0A000002, 6000)
    old_client = StubDtlsEndpoint("client")
    table.join(3, remote_addr=addr)
    for d in old_client.handshake_packets():
        table.on_dtls(d, addr)           # queued, NOT yet drained
    assert table._inbox
    table.forget(3)                      # purges the forgotten addr
    assert not table._inbox
    assert 3 in loop.discarded

    new_client = StubDtlsEndpoint("client")
    table.join(4, remote_addr=addr)
    for d in new_client.handshake_packets():
        table.on_dtls(d, addr)
    for _ in range(8):                   # off-tick drain to completion
        table.process()
        for d, a in loop.engine.out:
            if a == addr:
                for r in new_client.feed(d):
                    table.on_dtls(r, a)
        loop.engine.out.clear()
        if installed and new_client.complete:
            break
    assert [s for s, _ in installed] == [4]
    assert table.addr_of[addr] == 4
    _assert_complementary(installed[0][1], new_client)


def test_cookie_spoof_protection_at_queue_depth_two():
    """With queue depth > 1 and cookie exchange on, a spoofed-source
    copy of a victim's ClientHello may bind the fresh row first, but it
    never round-trips the cookie, so the real peer supersedes it — and
    both in-flight handshakes complete on their OWN rows through the
    bounded off-tick drain, keys never crossing."""
    installed = {}
    loop = _FakeLoop()
    table = DtlsAssociationTable(
        loop, SrtpProfile.AES_CM_128_HMAC_SHA1_80,
        lambda sid, ep: installed.__setitem__(sid, ep),
        deferred=True, endpoint_factory=StubDtlsEndpoint)
    r1, r2 = (0x0A000011, 5001), (0x0A000012, 5002)
    spoof = (0x0A999999, 9999)

    table.join(1, cookie_exchange=True)
    c1 = StubDtlsEndpoint("client")
    for d in c1.handshake_packets():
        table.on_dtls(d, r1)
    table.process()                      # c1 claims row 1 -> challenge
    assert table.addr_of[r1] == 1
    for d, a in loop.engine.out:         # c1 answers the cookie
        for r in c1.feed(d):
            table.on_dtls(r, a)
    loop.engine.out.clear()
    table.process()                      # row 1 sends its cert flight
    assert table.pending[1].progressed

    table.join(2, cookie_exchange=True)
    c2 = StubDtlsEndpoint("client")
    c2_hello = c2.handshake_packets()
    # attacker races c2's captured hello bytes from a spoofed source:
    # binds row 2 first, but only ever elicits the cookie challenge
    for d in c2_hello:
        table.on_dtls(d, spoof)
    table.process()
    assert table.addr_of[spoof] == 2
    assert not table.pending[2].progressed

    # the real c2 supersedes the unprogressed binding; both handshakes
    # then interleave through a BOUNDED drain (queue depth > 1)
    for d in c2_hello:
        table.on_dtls(d, r2)
    by_addr = {r1: c1, r2: c2}
    for _ in range(16):
        table.process(budget=2)
        for d, a in loop.engine.out:
            cl = by_addr.get(a)
            if cl is not None:
                for r in cl.feed(d):
                    table.on_dtls(r, a)
        loop.engine.out.clear()
        if len(installed) == 2 and c1.complete and c2.complete:
            break
    assert set(installed) == {1, 2}
    assert table.addr_of[r1] == 1 and table.addr_of[r2] == 2
    assert spoof not in table.addr_of
    _assert_complementary(installed[1], c1)
    _assert_complementary(installed[2], c2)
    # authenticated addresses latched for media return
    assert int(loop.addr_port[1]) == r1[1]
    assert int(loop.addr_port[2]) == r2[1]


def test_storm_interleaving_never_crosses_keys():
    """Property-style: N signaling-bound associations, their datagrams
    drained in randomized interleavings with a bounded budget — every
    install lands its own client's keys, across several seeds."""
    rng = np.random.default_rng(7)
    for _trial in range(4):
        installed = {}
        loop = _FakeLoop(n=16)
        table = DtlsAssociationTable(
            loop, SrtpProfile.AES_CM_128_HMAC_SHA1_80,
            lambda sid, ep: installed.__setitem__(sid, ep),
            deferred=True, endpoint_factory=StubDtlsEndpoint)
        clients = {}
        for k in range(6):
            addr = (0x0A000100 + k, 7000 + k)
            table.join(k, remote_addr=addr)
            clients[addr] = StubDtlsEndpoint("client")
        wire = []
        for addr, cl in clients.items():
            wire += [(d, addr) for d in cl.handshake_packets()]
        for _ in range(40):
            idx = rng.permutation(len(wire))
            for i in idx:
                table.on_dtls(*wire[int(i)])
            wire = []
            table.process(budget=3)
            for d, a in loop.engine.out:
                wire += [(r, a) for r in clients[a].feed(d)]
            loop.engine.out.clear()
            if (len(installed) == len(clients)
                    and all(c.complete for c in clients.values())):
                break
        assert len(installed) == len(clients)
        for k, (addr, cl) in enumerate(sorted(clients.items())):
            assert table.addr_of[addr] == k
            _assert_complementary(installed[k], cl)


@needs_openssl
@pytest.mark.slow      # rides OpenSSL's real flight-timer backoff
def test_association_table_spoofed_hello_cannot_lock_out_peer():
    """A spoofed-source ClientHello may bind the pending row's address
    first, but with cookie_exchange it can never round-trip the cookie,
    so it never 'progresses' — the real peer supersedes the binding
    (via its own flight retransmission) and completes."""
    import time as _t

    from libjitsi_tpu.control.dtls import DtlsAssociationTable

    class _Eng:
        def __init__(self):
            self.out = []

        def send_batch(self, batch, ip, port):
            for i in range(batch.batch_size):
                self.out.append((batch.to_bytes(i), (ip, port)))
            return batch.batch_size

    class _Loop:
        def __init__(self):
            self.addr_ip = np.zeros(8, np.uint32)
            self.addr_port = np.zeros(8, np.uint16)
            self.engine = _Eng()
            self.released = []

        def hold_stream(self, sid, max_packets=64):
            pass

        def release_stream(self, sid):
            self.released.append(sid)
            return 0

        def discard_stream(self, sid):
            pass

    installed = []
    loop = _Loop()
    table = DtlsAssociationTable(
        loop, SrtpProfile.AES_CM_128_HMAC_SHA1_80,
        lambda sid, ep: installed.append(sid))
    server_ep = table.join(3, role="server", cookie_exchange=True)

    client = DtlsSrtpEndpoint("client")
    first_flight = client.handshake_packets()
    spoofed, real = (0x0A090909, 6666), (0x0A000002, 5004)
    # attacker races the ClientHello bytes from a spoofed source: binds
    # the row, receives the HelloVerifyRequest it can never answer
    for d in first_flight:
        table.on_dtls(d, spoofed)
    assert table.addr_of[spoofed] == 3 and not server_ep.progressed

    # the real peer drives from its own address; its retransmission
    # timer re-elicits the HVR after the supersede (real-time: ~1-2 s)
    pend = list(first_flight)
    t0 = _t.time()
    while not (client.complete and installed) and _t.time() - t0 < 40:
        nxt = []
        for d in pend:
            for r in table.on_dtls(d, real):
                nxt.extend(client.feed(r))
        nxt.extend(client.tick())
        table.tick()                     # server-side flight resends
        for d, addr in loop.engine.out:
            if addr == real:
                nxt.extend(client.feed(d))
        loop.engine.out.clear()
        pend = nxt
        _t.sleep(0.05)
    assert installed == [3], "real peer never completed"
    assert table.addr_of.get(real) == 3
    assert loop.released == [3]
    # the authenticated handshake's address latched for media return
    assert int(loop.addr_port[3]) == real[1]
