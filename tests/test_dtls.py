"""DTLS-SRTP: in-memory handshake, profile negotiation, key export,
fingerprint verification, demux, and keys driving real SRTP tables.

Reference behaviors: DtlsControlImpl/DtlsPacketTransformer (RFC 5764).
"""

import numpy as np
import pytest

from libjitsi_tpu.control.dtls import (
    DtlsSrtpEndpoint,
    fingerprint,
    generate_certificate,
    is_dtls,
)
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable


def run_handshake(client: DtlsSrtpEndpoint, server: DtlsSrtpEndpoint,
                  drop=lambda i: False):
    """Pump datagrams between the endpoints until both complete."""
    wire = [(0, p) for p in client.handshake_packets()]
    i = 0
    rounds = 0
    while (not client.complete or not server.complete) and rounds < 50:
        rounds += 1
        nxt = []
        for who, pkt in wire:
            i += 1
            if drop(i):
                continue
            ep = server if who == 0 else client
            nxt += [(1 - who, p) for p in ep.feed(pkt)]
        wire = nxt
        if not wire and (not client.complete or not server.complete):
            wire = [(0, p) for p in client.handshake_packets()] + \
                   [(1, p) for p in server.handshake_packets()]
    assert client.complete and server.complete, "handshake did not finish"


def test_handshake_and_key_agreement():
    c = DtlsSrtpEndpoint("client")
    s = DtlsSrtpEndpoint("server")
    run_handshake(c, s)
    pc, c_txk, c_txs, c_rxk, c_rxs = c.srtp_keys()
    ps, s_txk, s_txs, s_rxk, s_rxs = s.srtp_keys()
    assert pc is ps
    # client's tx keys are the server's rx keys and vice versa
    assert (c_txk, c_txs) == (s_rxk, s_rxs)
    assert (c_rxk, c_rxs) == (s_txk, s_txs)
    assert len(c_txk) == pc.policy.enc_key_len


def test_profile_negotiation_intersection():
    c = DtlsSrtpEndpoint("client",
                         profiles=[SrtpProfile.AEAD_AES_128_GCM])
    s = DtlsSrtpEndpoint("server",
                         profiles=[SrtpProfile.AES_CM_128_HMAC_SHA1_80,
                                   SrtpProfile.AEAD_AES_128_GCM])
    run_handshake(c, s)
    assert c.selected_profile is SrtpProfile.AEAD_AES_128_GCM


def test_fingerprint_verification():
    cert, key, fp = generate_certificate()
    c = DtlsSrtpEndpoint("client", cert_der=cert, key_der=key)
    # server pinned to the RIGHT fingerprint: fine
    s = DtlsSrtpEndpoint("server", remote_fingerprint=fp)
    run_handshake(c, s)

    # server pinned to a WRONG fingerprint: handshake completion raises
    wrong = fingerprint(b"not-the-cert")
    c2 = DtlsSrtpEndpoint("client", cert_der=cert, key_der=key)
    s2 = DtlsSrtpEndpoint("server", remote_fingerprint=wrong)
    with pytest.raises((RuntimeError, AssertionError)):
        run_handshake(c2, s2)


def test_demux_first_byte():
    assert is_dtls(bytes([22, 0xfe, 0xfd]))       # handshake record
    assert is_dtls(bytes([20]))                    # ccs
    assert not is_dtls(bytes([0x80, 96]))          # RTP v2
    assert not is_dtls(bytes([0x81, 200]))         # RTCP
    assert not is_dtls(b"")
    assert not is_dtls(bytes([0]))                 # STUN would be 0..3


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_exported_keys_drive_srtp_tables():
    """End to end: DTLS handshake keys installed into SrtpStreamTables,
    protected media flows client -> server."""
    c = DtlsSrtpEndpoint("client")
    s = DtlsSrtpEndpoint("server")
    run_handshake(c, s)
    prof, c_txk, c_txs, _, _ = c.srtp_keys()
    _, _, _, s_rxk, s_rxs = s.srtp_keys()
    tx = SrtpStreamTable(capacity=2, profile=prof)
    tx.add_stream(0, c_txk, c_txs)
    rx = SrtpStreamTable(capacity=2, profile=prof)
    rx.add_stream(0, s_rxk, s_rxs)
    b = rtp_header.build([b"dtls-keyed-media"], [42], [0], [9], [96],
                         stream=[0])
    dec, ok = rx.unprotect_rtp(tx.protect_rtp(b))
    assert ok.all()
    assert dec.to_bytes(0) == b.to_bytes(0)


@pytest.mark.slow
def test_lossy_handshake_completes_via_retransmission():
    """VERDICT r2 #5: 30% datagram loss each way; the RFC 6347 flight
    timers (DtlsSrtpEndpoint.tick) must still complete the handshake.
    Real-time test: OpenSSL's initial flight timer is 1 s."""
    import time as _t

    rng = np.random.default_rng(7)
    c = DtlsSrtpEndpoint("client")
    s = DtlsSrtpEndpoint("server", cookie_exchange=True)

    def deliver(dst, datagrams):
        out = []
        for d in datagrams:
            if rng.random() < 0.30:
                continue                      # lost
            out.extend(dst.feed(d))
        return out

    pend_to_s = c.handshake_packets()
    t0 = _t.time()
    while not (c.complete and s.complete):
        assert _t.time() - t0 < 25, "handshake deadlocked under loss"
        pend_to_c = deliver(s, pend_to_s)
        pend_to_s = deliver(c, pend_to_c)
        pend_to_s += c.tick()
        for d in s.tick():
            pend_to_s.extend(c.feed(d))
        _t.sleep(0.05)
    assert c.retransmits + s.retransmits > 0, \
        "loss seeded but no flight was ever retransmitted"
    pc, ps = c.srtp_keys(), s.srtp_keys()
    assert pc[0] == ps[0]
    assert (pc[1], pc[2]) == (ps[3], ps[4])
    assert (pc[3], pc[4]) == (ps[1], ps[2])


def test_media_loop_hold_queues_and_releases():
    """Early media (racing the DTLS Finished flight) queues raw and
    replays through the chain once keys install."""
    import libjitsi_tpu
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.io.loop import MediaLoop
    from libjitsi_tpu.service.media_stream import StreamRegistry

    libjitsi_tpu.stop()
    libjitsi_tpu.init()

    class _FakeEngine:
        port = 0

        def recv_batch(self, timeout_ms):
            b = self._next
            self._next = (PacketBatch.from_payloads([]),
                          np.zeros(0, np.uint32), np.zeros(0, np.uint16))
            return b

        def send_batch(self, batch, ip, port):
            return batch.batch_size

    reg = StreamRegistry(libjitsi_tpu.configuration_service(),
                         capacity=4)
    seen = []
    eng = _FakeEngine()
    loop = MediaLoop(eng, reg,
                     on_media=lambda b, ok: seen.append(
                         (b.batch_size, ok.sum())) or None)
    reg.map_ssrc(0xABC, 2)
    loop.hold_stream(2)
    wire = rtp_header.build([b"early-%d" % i for i in range(3)],
                            [10, 11, 12], [0] * 3, [0xABC] * 3,
                            [96] * 3, stream=[0] * 3)
    pkts = [wire.to_bytes(i) for i in range(3)]
    eng._next = (PacketBatch.from_payloads(pkts),
                 np.full(3, 0x7F000001, np.uint32),
                 np.full(3, 5555, np.uint16))
    loop.tick()
    assert seen == [], "held media leaked through"
    n = loop.release_stream(2)
    assert n == 3
    assert seen == [(3, 3)]
    # bounded: queue holds max_packets, oldest evicted
    loop.hold_stream(2, max_packets=2)
    eng._next = (PacketBatch.from_payloads(pkts),
                 np.full(3, 0x7F000001, np.uint32),
                 np.full(3, 5555, np.uint16))
    loop.tick()
    assert loop.release_stream(2) == 2


@pytest.mark.slow      # rides OpenSSL's real flight-timer backoff
def test_association_table_spoofed_hello_cannot_lock_out_peer():
    """A spoofed-source ClientHello may bind the pending row's address
    first, but with cookie_exchange it can never round-trip the cookie,
    so it never 'progresses' — the real peer supersedes the binding
    (via its own flight retransmission) and completes."""
    import time as _t

    from libjitsi_tpu.control.dtls import DtlsAssociationTable

    class _Eng:
        def __init__(self):
            self.out = []

        def send_batch(self, batch, ip, port):
            for i in range(batch.batch_size):
                self.out.append((batch.to_bytes(i), (ip, port)))
            return batch.batch_size

    class _Loop:
        def __init__(self):
            self.addr_ip = np.zeros(8, np.uint32)
            self.addr_port = np.zeros(8, np.uint16)
            self.engine = _Eng()
            self.released = []

        def hold_stream(self, sid, max_packets=64):
            pass

        def release_stream(self, sid):
            self.released.append(sid)
            return 0

        def discard_stream(self, sid):
            pass

    installed = []
    loop = _Loop()
    table = DtlsAssociationTable(
        loop, SrtpProfile.AES_CM_128_HMAC_SHA1_80,
        lambda sid, ep: installed.append(sid))
    server_ep = table.join(3, role="server", cookie_exchange=True)

    client = DtlsSrtpEndpoint("client")
    first_flight = client.handshake_packets()
    spoofed, real = (0x0A090909, 6666), (0x0A000002, 5004)
    # attacker races the ClientHello bytes from a spoofed source: binds
    # the row, receives the HelloVerifyRequest it can never answer
    for d in first_flight:
        table.on_dtls(d, spoofed)
    assert table.addr_of[spoofed] == 3 and not server_ep.progressed

    # the real peer drives from its own address; its retransmission
    # timer re-elicits the HVR after the supersede (real-time: ~1-2 s)
    pend = list(first_flight)
    t0 = _t.time()
    while not (client.complete and installed) and _t.time() - t0 < 40:
        nxt = []
        for d in pend:
            for r in table.on_dtls(d, real):
                nxt.extend(client.feed(r))
        nxt.extend(client.tick())
        table.tick()                     # server-side flight resends
        for d, addr in loop.engine.out:
            if addr == real:
                nxt.extend(client.feed(d))
        loop.engine.out.clear()
        pend = nxt
        _t.sleep(0.05)
    assert installed == [3], "real peer never completed"
    assert table.addr_of.get(real) == 3
    assert loop.released == [3]
    # the authenticated handshake's address latched for media return
    assert int(loop.addr_port[3]) == real[1]
