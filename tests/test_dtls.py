"""DTLS-SRTP: in-memory handshake, profile negotiation, key export,
fingerprint verification, demux, and keys driving real SRTP tables.

Reference behaviors: DtlsControlImpl/DtlsPacketTransformer (RFC 5764).
"""

import numpy as np
import pytest

from libjitsi_tpu.control.dtls import (
    DtlsSrtpEndpoint,
    fingerprint,
    generate_certificate,
    is_dtls,
)
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable


def run_handshake(client: DtlsSrtpEndpoint, server: DtlsSrtpEndpoint,
                  drop=lambda i: False):
    """Pump datagrams between the endpoints until both complete."""
    wire = [(0, p) for p in client.handshake_packets()]
    i = 0
    rounds = 0
    while (not client.complete or not server.complete) and rounds < 50:
        rounds += 1
        nxt = []
        for who, pkt in wire:
            i += 1
            if drop(i):
                continue
            ep = server if who == 0 else client
            nxt += [(1 - who, p) for p in ep.feed(pkt)]
        wire = nxt
        if not wire and (not client.complete or not server.complete):
            wire = [(0, p) for p in client.handshake_packets()] + \
                   [(1, p) for p in server.handshake_packets()]
    assert client.complete and server.complete, "handshake did not finish"


def test_handshake_and_key_agreement():
    c = DtlsSrtpEndpoint("client")
    s = DtlsSrtpEndpoint("server")
    run_handshake(c, s)
    pc, c_txk, c_txs, c_rxk, c_rxs = c.srtp_keys()
    ps, s_txk, s_txs, s_rxk, s_rxs = s.srtp_keys()
    assert pc is ps
    # client's tx keys are the server's rx keys and vice versa
    assert (c_txk, c_txs) == (s_rxk, s_rxs)
    assert (c_rxk, c_rxs) == (s_txk, s_txs)
    assert len(c_txk) == pc.policy.enc_key_len


def test_profile_negotiation_intersection():
    c = DtlsSrtpEndpoint("client",
                         profiles=[SrtpProfile.AEAD_AES_128_GCM])
    s = DtlsSrtpEndpoint("server",
                         profiles=[SrtpProfile.AES_CM_128_HMAC_SHA1_80,
                                   SrtpProfile.AEAD_AES_128_GCM])
    run_handshake(c, s)
    assert c.selected_profile is SrtpProfile.AEAD_AES_128_GCM


def test_fingerprint_verification():
    cert, key, fp = generate_certificate()
    c = DtlsSrtpEndpoint("client", cert_der=cert, key_der=key)
    # server pinned to the RIGHT fingerprint: fine
    s = DtlsSrtpEndpoint("server", remote_fingerprint=fp)
    run_handshake(c, s)

    # server pinned to a WRONG fingerprint: handshake completion raises
    wrong = fingerprint(b"not-the-cert")
    c2 = DtlsSrtpEndpoint("client", cert_der=cert, key_der=key)
    s2 = DtlsSrtpEndpoint("server", remote_fingerprint=wrong)
    with pytest.raises((RuntimeError, AssertionError)):
        run_handshake(c2, s2)


def test_demux_first_byte():
    assert is_dtls(bytes([22, 0xfe, 0xfd]))       # handshake record
    assert is_dtls(bytes([20]))                    # ccs
    assert not is_dtls(bytes([0x80, 96]))          # RTP v2
    assert not is_dtls(bytes([0x81, 200]))         # RTCP
    assert not is_dtls(b"")
    assert not is_dtls(bytes([0]))                 # STUN would be 0..3


def test_exported_keys_drive_srtp_tables():
    """End to end: DTLS handshake keys installed into SrtpStreamTables,
    protected media flows client -> server."""
    c = DtlsSrtpEndpoint("client")
    s = DtlsSrtpEndpoint("server")
    run_handshake(c, s)
    prof, c_txk, c_txs, _, _ = c.srtp_keys()
    _, _, _, s_rxk, s_rxs = s.srtp_keys()
    tx = SrtpStreamTable(capacity=2, profile=prof)
    tx.add_stream(0, c_txk, c_txs)
    rx = SrtpStreamTable(capacity=2, profile=prof)
    rx.add_stream(0, s_rxk, s_rxs)
    b = rtp_header.build([b"dtls-keyed-media"], [42], [0], [9], [96],
                         stream=[0])
    dec, ok = rx.unprotect_rtp(tx.protect_rtp(b))
    assert ok.all()
    assert dec.to_bytes(0) == b.to_bytes(0)
