"""Chaos acceptance tests (tier-1, deliberately NOT slow).

1. Kill-and-resume under loss+corruption, bit-exact: a conference
   ingests a faulted wire stream whose SRTP sequence space crosses the
   ROC wrap, is checkpointed mid-run, destroyed, and recovered via
   `BridgeSupervisor.recover`; the set of accepted decrypted packets
   (sid, seq -> payload bytes) must be IDENTICAL to an uninterrupted
   run of the same wire — proving ROC and replay windows survive the
   crash bit-exactly.  Replayed pre-checkpoint wire is rejected.

2. Quarantine: an attacker storms garbage under a participant's SSRC
   (wrong key -> auth failures); the supervisor isolates that SSRC
   without disturbing the other participant, then re-admits it after
   the backoff and its legitimate media decodes again.

3. Cascade double fault: bridge A dies mid-call AND the survivor
   crashes while the orphan adoption is still in flight; recovery
   resumes the failover from the checkpointed cascade control plane —
   the orphan commits or rolls back and re-queues, never a torn row.

The faulted wire is generated OFFLINE with a fixed seed and fed
byte-identically to both universes: in-chain fault injection draws RNG
per batch, so two runs that batch differently would diverge — the
fault pattern must be part of the experiment, not of the runtime.
"""

import time

import numpy as np

import libjitsi_tpu
from libjitsi_tpu.control.dtls import StubDtlsEndpoint
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.mesh.cascade import CascadeTrunk, TrunkConfig
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.service.bridge import ConferenceBridge
from libjitsi_tpu.service.lifecycle import (LifecycleConfig,
                                            StreamLifecycleManager)
from libjitsi_tpu.service.sfu_bridge import SfuBridge
from libjitsi_tpu.service.supervisor import (BridgeSupervisor,
                                             CascadeSupervisor,
                                             SupervisorConfig)
from libjitsi_tpu.transform.srtp import SrtpStreamTable

SSRCS = (0x60, 0x70, 0x80)
SEQ0 = 65526          # crosses the ROC wrap at tick 10
N_TICKS = 24
KILL_AT = 14          # post-wrap: recovery must resume with ROC=1


def _keys(ssrc):
    rx = (bytes([ssrc]) * 16, bytes([ssrc + 1]) * 14)
    tx = (bytes([ssrc + 2]) * 16, bytes([ssrc + 3]) * 14)
    return rx, tx


def _make_wire(seed=1234):
    """Per (client, tick) -> wire bytes or None (dropped), faulted
    offline: ~15% loss, ~10% single-byte corruption."""
    rng = np.random.default_rng(seed)
    wire = {}
    for ci, ssrc in enumerate(SSRCS):
        rx, _tx = _keys(ssrc)
        prot = SrtpStreamTable(capacity=1)
        prot.add_stream(0, *rx)
        for t in range(N_TICKS):
            payload = bytes([ci, t]) * 80
            b = rtp_header.build([payload], [(SEQ0 + t) & 0xFFFF],
                                 [160 * (t + 1)], [ssrc], [0], stream=[0])
            pb = prot.protect_rtp(b)
            raw = bytearray(pb.to_bytes(0))
            u = rng.random()
            pos = int(rng.integers(0, len(raw)))    # drawn even if unused
            if u < 0.15:
                wire[(ci, t)] = None
                continue
            if u < 0.25:
                raw[pos] ^= 0xFF
            wire[(ci, t)] = bytes(raw)
    return wire


def _record_media(bridge, accepted):
    """Wrap the loop's media sink to log every ACCEPTED decrypted
    packet as (sid, seq) -> payload bytes."""
    inner = bridge.loop.on_media

    def wrapped(batch, ok):
        hdr = rtp_header.parse(batch)
        for i in np.nonzero(ok)[0]:
            i = int(i)
            pay = batch.to_bytes(i)[int(hdr.payload_off[i]):]
            accepted[(int(batch.stream[i]), int(hdr.seq[i]))] = pay
        return inner(batch, ok)

    bridge.loop.on_media = wrapped


def _pump(sup, now, want):
    """Tick until `want` datagrams landed (loopback is fast, not
    instantaneous)."""
    got = 0
    for i in range(200):
        got += sup.tick(now=now)["rx"]
        if got >= want:
            break
        if i > 3:
            time.sleep(0.001)
    return got


def _run_universe(wire, ckpt_path=None, pipeline_depth=1):
    """Feed the faulted wire tick-by-tick; if ckpt_path is set, the
    bridge is checkpointed, destroyed, and recovered at KILL_AT."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    bridge = ConferenceBridge(cfg, port=0, capacity=8, recv_window_ms=0,
                              pipeline_depth=pipeline_depth)
    # quarantine OFF for this experiment: bans are deliberately
    # ephemeral runtime policy (not part of the checkpoint), so they
    # must not perturb the bit-exact accept-set comparison — the
    # corrupted wire would otherwise convict streams mid-run
    sup = BridgeSupervisor(bridge, SupervisorConfig(
        deadline_ms=1000.0, quarantine_auth_threshold=1 << 30,
        quarantine_replay_threshold=1 << 30))
    for ssrc in SSRCS:
        rx, tx = _keys(ssrc)
        bridge.add_participant(ssrc, rx, tx)
    engines = [UdpEngine(port=0, max_batch=32) for _ in SSRCS]
    accepted = {}
    _record_media(bridge, accepted)
    port = bridge.port
    now = 400.0
    for t in range(N_TICKS):
        if ckpt_path is not None and t == KILL_AT:
            sup.save_checkpoint(ckpt_path)
            bridge.close()                      # the "crash"
            sup = BridgeSupervisor.recover(
                cfg, ckpt_path, ConferenceBridge, port=0,
                supervisor_config=sup.cfg, recv_window_ms=0,
                pipeline_depth=pipeline_depth)
            bridge = sup.bridge
            _record_media(bridge, accepted)
            port = bridge.port
        sent = 0
        for ci, eng in enumerate(engines):
            wb = wire[(ci, t)]
            if wb is not None:
                eng.send_batch(PacketBatch.from_payloads([wb]),
                               "127.0.0.1", port)
                sent += 1
        _pump(sup, now, sent)
        sup.tick(now=now + 0.001)               # decode tick
        now += 0.020
    # collapse any in-flight pipeline stages (idle ticks drain, but be
    # explicit): at depth d, the last d-1 arrivals are still deferred
    for _ in range(4):
        sup.tick(now=now)
        now += 0.020
    drain = getattr(bridge.loop, "drain", None)
    if drain is not None:
        drain()
    for eng in engines:
        eng.close()
    return accepted, bridge, sup


def test_kill_and_resume_is_bit_exact_under_loss_and_corruption(tmp_path):
    wire = _make_wire()
    accepted_a, bridge_a, _ = _run_universe(wire)
    bridge_a.close()

    ckpt = str(tmp_path / "conf.ckpt")
    accepted_b, bridge_b, sup_b = _run_universe(wire, ckpt_path=ckpt)

    # the run actually exercised what it claims: corruption rejected
    # some packets, the sequence space wrapped (ROC=1 in play), and
    # packets were accepted on both sides of the kill
    seqs = [seq for (_sid, seq) in accepted_a]
    assert len(accepted_a) < sum(v is not None for v in wire.values())
    assert max(seqs) > 65525 and min(seqs) < 100, "no ROC wrap seen"
    assert any(seq < (SEQ0 + KILL_AT) & 0xFFFF or seq > 60000
               for seq in seqs)
    post_kill = [(SEQ0 + t) & 0xFFFF for t in range(KILL_AT, N_TICKS)]
    assert any(seq in post_kill for seq in seqs), \
        "nothing accepted after the recovery point"

    # THE invariant: the recovered universe accepted exactly the same
    # packets with exactly the same decrypted bytes
    assert accepted_b == accepted_a

    # the crash-restart left a post-mortem naming the checkpoint it
    # rose from (a destructive action like any other)
    pm = next(p for p in sup_b.postmortems
              if p["trigger"] == "checkpoint_recover")
    assert pm["event"]["kind"] == "recovered"
    assert pm["event"]["path"] == ckpt
    assert pm["event"]["bridge"] == "ConferenceBridge"

    # replayed pre-checkpoint wire must bounce off the restored replay
    # window (find a surviving, uncorrupted pre-kill packet and resend
    # its exact bytes)
    replay_ci, replay_bytes = None, None
    for (ci, t), wb in wire.items():
        if t < KILL_AT and wb is not None:
            sid_seq = (ci, (SEQ0 + t) & 0xFFFF)
            if sid_seq in accepted_a:       # it was accepted => clean
                replay_ci, replay_bytes = ci, wb
                break
    assert replay_bytes is not None
    before = int(bridge_b.rx_table.replay_reject[replay_ci])
    eng = UdpEngine(port=0, max_batch=8)
    eng.send_batch(PacketBatch.from_payloads([replay_bytes]),
                   "127.0.0.1", bridge_b.port)
    _pump(sup_b, 500.0, 1)
    eng.close()
    assert int(bridge_b.rx_table.replay_reject[replay_ci]) > before, \
        "pre-checkpoint replay re-entered after recovery"
    bridge_b.close()


def test_depth3_pipeline_accept_set_is_bit_exact_across_kill(tmp_path):
    """The deep pipeline reorders WORK, not PACKETS: a depth-3 bridge
    fed the identical faulted wire accepts exactly the depth-1 accept
    set — and a kill/recover at KILL_AT (the checkpoint lands with two
    ticks of rx still in flight; save_checkpoint's drain barrier must
    materialize them first) changes nothing."""
    wire = _make_wire()
    accepted_1, bridge_1, _ = _run_universe(wire)
    bridge_1.close()

    accepted_3, bridge_3, _ = _run_universe(wire, pipeline_depth=3)
    bridge_3.close()
    assert accepted_3 == accepted_1, \
        "depth-3 pipeline changed the observable accept set"

    ckpt = str(tmp_path / "deep.ckpt")
    accepted_3k, bridge_3k, _ = _run_universe(wire, ckpt_path=ckpt,
                                              pipeline_depth=3)
    bridge_3k.close()
    assert accepted_3k == accepted_1, \
        "kill/recover mid-pipeline lost or duplicated acceptances"


def test_ingest_engine_mode_does_not_change_accept_set(monkeypatch):
    """ISSUE 12 fallback proof: the ingest engine is a transport
    detail.  With io_uring force-disabled (LIBJITSI_TPU_NO_IOURING=1)
    the recvmmsg engine accepts a bit-identical set on the depth-3
    faulted wire vs the auto-probed default — and, on a box that can
    run the ring, the io_uring engine matches too."""
    from libjitsi_tpu.io.udp import uring_available

    wire = _make_wire()
    monkeypatch.setenv("LIBJITSI_TPU_NO_IOURING", "1")
    accepted_off, bridge_off, _ = _run_universe(wire, pipeline_depth=3)
    bridge_off.close()

    monkeypatch.delenv("LIBJITSI_TPU_NO_IOURING")
    accepted_auto, bridge_auto, _ = _run_universe(wire,
                                                  pipeline_depth=3)
    bridge_auto.close()
    assert accepted_auto == accepted_off, \
        "force-disabling io_uring changed the accept set"

    if uring_available():
        monkeypatch.setenv("LIBJITSI_TPU_ENGINE_MODE", "io_uring")
        accepted_ring, bridge_ring, _ = _run_universe(
            wire, pipeline_depth=3)
        bridge_ring.close()
        assert accepted_ring == accepted_off, \
            "ring-engine ingest changed the accept set"


def test_quarantine_isolates_auth_storm_then_readmits():
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    bridge = ConferenceBridge(libjitsi_tpu.configuration_service(),
                              port=0, capacity=8, recv_window_ms=0)
    sup = BridgeSupervisor(bridge, SupervisorConfig(
        deadline_ms=1000.0, quarantine_window=10,
        quarantine_auth_threshold=8, quarantine_backoff_ticks=6,
        quarantine_backoff_cap=50))
    rx0, tx0 = _keys(0x60)
    rx1, tx1 = _keys(0x70)
    sid0 = bridge.add_participant(0x60, rx0, tx0)
    sid1 = bridge.add_participant(0x70, rx1, tx1)

    prot0 = SrtpStreamTable(capacity=1)
    prot0.add_stream(0, *rx0)
    prot1 = SrtpStreamTable(capacity=1)
    prot1.add_stream(0, *rx1)
    wrong = SrtpStreamTable(capacity=1)          # attacker's key != rx0
    wrong.add_stream(0, b"\xEE" * 16, b"\xFF" * 14)
    eng0 = UdpEngine(port=0, max_batch=32)
    eng1 = UdpEngine(port=0, max_batch=32)
    atk = UdpEngine(port=0, max_batch=32)

    seq = {0x60: 100, 0x70: 100, "atk": 100}

    def send(table, engine, ssrc, key):
        payload = bytes(160)
        b = rtp_header.build([payload], [seq[key]], [160 * seq[key]],
                             [ssrc], [0], stream=[0])
        seq[key] += 1
        engine.send_batch(table.protect_rtp(b), "127.0.0.1", bridge.port)

    now = 500.0

    def round_trip(n_pkts):
        nonlocal now
        _pump(sup, now, n_pkts)
        sup.tick(now=now + 0.001)
        now += 0.020

    # phase 1: p1 talks, attacker storms p0's SSRC with a wrong key
    for _ in range(8):
        send(prot1, eng1, 0x70, 0x70)
        for _ in range(3):
            send(wrong, atk, 0x60, "atk")
        round_trip(4)
    assert int(bridge.rx_table.auth_fail[sid0]) >= 8
    assert sid0 in sup._quarantined and bridge.loop.inbound_drop[sid0]
    assert sid1 not in sup._quarantined
    assert int(bridge.bank.decoded_frames[sid1]) >= 4, \
        "innocent participant was disturbed by the quarantine"
    assert int(bridge.loop.inbound_dropped[sid0]) > 0
    # the conviction dumped a post-mortem: trigger named, and the
    # stream ring shows the auth storm that caused it
    pm = next(p for p in sup.postmortems if p["trigger"] == "quarantine")
    assert pm["sid"] == sid0
    assert pm["event"]["reason"] == "auth_storm"
    assert any(e["kind"] == "srtp_auth_fail"
               for e in pm["dump"]["events"])

    # phase 2: the storm stops; the ban expires after the backoff
    for _ in range(10):
        send(prot1, eng1, 0x70, 0x70)
        round_trip(1)
    assert sid0 not in sup._quarantined
    assert not bridge.loop.inbound_drop[sid0]

    # phase 3: re-admitted — p0's legitimate media decodes again
    base = int(bridge.bank.decoded_frames[sid0])
    for _ in range(4):
        send(prot0, eng0, 0x60, 0x60)
        send(prot1, eng1, 0x70, 0x70)
        round_trip(2)
    assert int(bridge.bank.decoded_frames[sid0]) > base, \
        "re-admitted stream's media did not resume decoding"
    for e in (eng0, eng1, atk):
        e.close()
    bridge.close()


def _ck(b):
    """Deterministic (master key, master salt) from one byte seed."""
    return (bytes([b & 0xFF]) * 16, bytes([(b + 1) & 0xFF]) * 14)


def _no_torn(bridge):
    return [sid for sid in bridge._ssrc_of
            if sid not in bridge._tx_keys and sid not in bridge._staged]


def test_survivor_crash_mid_failover_adopts_or_rolls_back(tmp_path):
    """3. The double fault: bridge A dies mid-call, and the SURVIVOR
    crashes while the orphan adoption is still in flight (queued or
    staged pre-commit).  The adoption rides `cascade_snapshot` on the
    checkpoint spine; `CascadeSupervisor.recover` must RESUME it — the
    orphan either commits on the recovered bridge (fresh deadline) or
    rolls back and re-queues, and at no tick does the bridge hold a
    torn row (keyed-or-staged, never half)."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    TK = (_ck(0xA0), _ck(0xB0))             # A->B, B->A trunk keys
    CONF = 7
    dt = 0.01

    def mk(bid, pid, txk, rxk):
        b = SfuBridge(cfg, port=0, capacity=16, recv_window_ms=0)
        tr = CascadeTrunk(txk, rxk, TrunkConfig(), port=0, seed=bid)
        sup = CascadeSupervisor(
            b, tr, SupervisorConfig(deadline_ms=1000.0),
            metrics=b.loop.metrics, bridge_id=bid, peer_bridge_id=pid)
        lc = StreamLifecycleManager(b, supervisor=sup,
                                    metrics=b.loop.metrics,
                                    config=LifecycleConfig())
        lc.enable_placement(1)
        lc.placer.enable_bridges(2)
        tr.attach(b.loop)
        return b, tr, sup, lc

    bA, tA, supA, lcA = mk(0, 1, TK[0], TK[1])
    bB, tB, supB, lcB = mk(1, 0, TK[1], TK[0])
    now = 100.0
    tA.connect("127.0.0.1", tB.port, now=now)
    tB.connect("127.0.0.1", tA.port, now=now)
    supA.cascade_conference(CONF)
    supB.cascade_conference(CONF, remote=True)

    # one speaker on A (the orphan-to-be), one receiver on B
    orphan_ssrc, orx, otx = 0x1000, _ck(0x10), _ck(0x12)
    ok, why = lcA.request_join(orphan_ssrc, orx, otx,
                               name="spk", conference=CONF)
    assert ok, f"speaker join refused: {why}"
    ok, why = lcB.request_join(0x2000, _ck(0x80), _ck(0x82),
                               name="rcv", conference=CONF)
    assert ok, f"receiver join refused: {why}"

    # trunks up, roster synced: B pre-installs the remote speaker
    for _ in range(400):
        supA.tick(now=now)
        supB.tick(now=now)
        now += dt
        if (tA.state == tB.state == "up"
                and bB._sid_of_ssrc(orphan_ssrc) is not None):
            break
    assert tA.state == tB.state == "up", "trunk never came up"
    assert bB._sid_of_ssrc(orphan_ssrc) is not None, \
        "roster sync never installed the remote speaker"

    # kill A; evict the speaker's row on B mid-outage (nothing can
    # reinstall it — its home bridge is dead) — a genuine orphan
    bA.close()
    tA.close()
    for _ in range(4):
        supB.tick(now=now)
        now += dt
    lcB.request_leave(ssrc=orphan_ssrc)
    for _ in range(2):
        supB.tick(now=now)
        now += dt
    assert bB._sid_of_ssrc(orphan_ssrc) is None, \
        "orphan eviction did not take"
    for _ in range(400):
        supB.tick(now=now)
        now += dt
        if tB.state == "down":
            break
    assert tB.state == "down" and supB.trunk_failovers_total == 1
    assert supB.adopting, "failover queued no adoption"

    # crash the SURVIVOR with the adoption still in flight
    ckpt = str(tmp_path / "cascade.ckpt")
    supB.save_checkpoint(ckpt)
    blob = CascadeSupervisor.load_checkpoint(ckpt)
    cas = blob["cascade"]
    assert cas["adopting"], "checkpoint lost the failover-in-progress"
    mid_flight = [e for e in cas["adopt_q"] + cas["pending_commit"]
                  if e.get("promote")]
    assert mid_flight and any(int(e["m"]["ssrc"]) == orphan_ssrc
                              for e in mid_flight), \
        "checkpoint lost the in-flight orphan adoption"
    bB.close()
    tB.close()

    # recover: fresh trunk (sockets don't survive), control plane and
    # the adoption queue come back from the checkpoint
    tr2 = CascadeTrunk(TK[1], TK[0], TrunkConfig(), port=0, seed=9)
    sup2 = CascadeSupervisor.recover(
        cfg, ckpt, SfuBridge, trunk=tr2,
        supervisor_config=SupervisorConfig(deadline_ms=1000.0),
        bridge_id=1, peer_bridge_id=0, recv_window_ms=0)
    b2 = sup2.bridge
    assert sup2.adopting, "recover dropped the failover-in-progress"
    assert _no_torn(b2) == [], "recovered bridge rose with a torn row"
    # the constructor consumes pending_lifecycle: placement comes back
    # from the checkpoint (re-enabling it here would discard the
    # reconciled placer along with the re-queued adoption's placement)
    lc2 = StreamLifecycleManager(b2, supervisor=sup2,
                                 metrics=b2.loop.metrics,
                                 config=LifecycleConfig())
    assert lc2.placer is not None, \
        "reconciliation did not restore placement"
    lc2.placer.enable_bridges(2)
    tr2.attach(b2.loop)

    # the receiver's committed row survived the crash bit-for-bit
    assert b2._sid_of_ssrc(0x2000) is not None

    # drive the recovered supervisor: adoption must complete through
    # the commit barrier (or roll back and retry — never tear); the
    # commit-deadline requeue path needs >1s of model time
    for _ in range(400):
        sup2.tick(now=now)
        now += dt
        assert _no_torn(b2) == [], "torn row during resumed adoption"
        if not sup2.adopting and sup2.orphans_adopted >= 1:
            break
    assert sup2.orphans_adopted >= 1, \
        "resumed adoption never committed the orphan"
    sid = b2._sid_of_ssrc(orphan_ssrc)
    assert sid is not None and sid in b2._tx_keys, \
        "adopted orphan is not a committed keyed row"
    assert orphan_ssrc not in tr2._remote_ssrcs, \
        "adoption did not claim the orphan from the dead peer"
    assert not sup2._adopt_q and not sup2._pending_commit \
        and not sup2._conf_outstanding, "adoption queues did not drain"

    # the crash-restart post-mortem names the checkpoint it rose from
    pm = next(p for p in sup2.postmortems
              if p["trigger"] == "checkpoint_recover")
    assert pm["event"]["path"] == ckpt
    b2.close()
    tr2.close()


def test_recover_with_half_installed_streams_completes_or_rolls_back(
        tmp_path):
    """Kill mid-admit: the checkpoint lands while one join is STAGED
    (keys installed, commit barrier not yet crossed) and another is
    still QUEUED host-side.  After `recover()` the next lifecycle
    manager reconciles every in-flight admit to a whole state: staged
    rows whose keys survived COMPLETE (fully routed — media decodes),
    staged rows whose keys were torn ROLL BACK (fully absent, slot
    freed), queued joins re-enter the normal pipeline.  Never a half
    state."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    bridge = SfuBridge(cfg, port=0, capacity=8, recv_window_ms=0)
    sup = BridgeSupervisor(bridge, SupervisorConfig(
        deadline_ms=1000.0, quarantine_auth_threshold=1 << 30,
        quarantine_replay_threshold=1 << 30))
    lc = StreamLifecycleManager(bridge, supervisor=sup)
    # bucketed warmups are the churn soak's subject; skip them here so
    # the test pins reconcile semantics without minutes of pre-compiles
    lc._warm_bucket = 1 << 30
    for ssrc in (0x60, 0x70):                   # committed audience
        assert lc.request_join(ssrc, *_keys(ssrc))[0]
    sup.tick(now=100.0)                         # stage
    sup.tick(now=100.02)                        # commit
    assert lc.admits == 2
    # two admits in flight at the crash: both staged, neither committed
    assert lc.request_join(0x80, *_keys(0x80))[0]
    assert lc.request_join(0x84, *_keys(0x84))[0]
    lc.poll()                                   # stage only, NO commit
    assert len(lc._staged) == 2 and lc.admits == 2
    sid80 = next(s for s, v in bridge._ssrc_of.items() if v == 0x80)
    sid84 = next(s for s, v in bridge._ssrc_of.items() if v == 0x84)
    # a third join is still queued host-side
    assert lc.request_join(0x90, *_keys(0x90))[0]
    ckpt = str(tmp_path / "half.ckpt")
    sup.save_checkpoint(ckpt)
    bridge.close()                              # the crash

    sup2 = BridgeSupervisor.recover(cfg, ckpt, SfuBridge, port=0,
                                    supervisor_config=sup.cfg,
                                    recv_window_ms=0)
    bridge2 = sup2.bridge
    # simulate a torn install for ONE staged row (as if the checkpoint
    # raced the key write): reconcile must treat it as unrecoverable
    bridge2._tx_keys.pop(sid84)
    assert sup2.pending_lifecycle is not None
    lc2 = StreamLifecycleManager(bridge2, supervisor=sup2)
    lc2._warm_bucket = 1 << 30
    assert sup2.pending_lifecycle is None       # consumed

    # survivor COMPLETED: counted, routed, flagged recovered
    assert lc2.admits == 1
    assert 0x80 in bridge2._ssrc_of.values()
    assert any(e["kind"] == "admit_commit" and e.get("recovered")
               for e in sup2.flight.dump(sid80)["events"])
    # torn row ROLLED BACK: fully absent, nothing half-installed
    assert 0x84 not in bridge2._ssrc_of.values()
    assert sid84 not in bridge2._tx_keys
    assert not bridge2.rx_table.active[sid84]
    assert any(e["kind"] == "admit_rollback"
               for e in sup2.flight.dump(sid84)["events"])
    # queued join re-entered the pipeline and installs normally
    sup2.tick(now=100.04)                       # stage 0x90
    sup2.tick(now=100.06)                       # commit 0x90
    assert lc2.admits == 2 and 0x90 in bridge2._ssrc_of.values()
    # whole-state invariant across every row the crash touched
    for sid in range(bridge2.capacity):
        assert ((sid in bridge2._ssrc_of) == (sid in bridge2._tx_keys)
                == bool(bridge2.rx_table.active[sid]))

    # the completed admit is not just bookkeeping: its media decodes
    rx80, _tx80 = _keys(0x80)
    prot = SrtpStreamTable(capacity=1)
    prot.add_stream(0, *rx80)
    b = rtp_header.build([bytes(160)], [100], [16000], [0x80], [0],
                         stream=[0])
    eng = UdpEngine(port=0, max_batch=8)
    eng.send_batch(prot.protect_rtp(b), "127.0.0.1", bridge2.port)
    _pump(sup2, 100.08, 1)
    sup2.tick(now=100.10)
    eng.close()
    assert int(bridge2.rx_table.rx_max[sid80]) >= 0, \
        "recovered staged stream's media did not decode"
    bridge2.close()

def _drive_stub_handshake(lc, bridge, eng, sid, client, caddr,
                          rounds=80):
    """Run one stub DTLS handshake to the STAGED landing: client
    flights enter through the deferred table (the tick thread's
    enqueue-only path) and all endpoint work happens on the off-tick
    drain."""
    for d in client.handshake_packets():
        bridge._dtls.on_dtls(d, caddr)
    for _ in range(rounds):
        lc.handshakes.drain()
        if sid in bridge._staged:
            return
        back, _, _ = eng.recv_batch(timeout_ms=20)
        for i in range(back.batch_size):
            for out in client.feed(back.to_bytes(i)):
                bridge._dtls.on_dtls(out, caddr)
    raise AssertionError(f"handshake for sid {sid} never staged")


def test_recover_mid_handshake_storm_reconciles_every_association(
        tmp_path):
    """Kill in the middle of a reconnect storm with an association in
    EVERY lifecycle state — live, staged (keys survived), staged (keys
    torn), mid-flight, and hello-still-inboxed — and recover.  The
    next lifecycle manager must reconcile all of them to a whole
    state: completed, rolled back, or requeued at the bound 5-tuple.
    Never torn."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    bridge = SfuBridge(cfg, port=0, capacity=8, recv_window_ms=0)
    bridge._dtls.endpoint_factory = StubDtlsEndpoint
    sup = BridgeSupervisor(bridge, SupervisorConfig(
        deadline_ms=1000.0, quarantine_auth_threshold=1 << 30,
        quarantine_replay_threshold=1 << 30))
    lc = StreamLifecycleManager(bridge, supervisor=sup)
    lc._warm_bucket = 1 << 30
    SSRC = {"live": 0xA0, "staged_ok": 0xB1, "staged_torn": 0xB2,
            "midflight": 0xC0, "inboxed": 0xD0}
    eng = {k: UdpEngine(port=0, max_batch=32) for k in SSRC}
    caddr = {k: (0x7F000001, e.port) for k, e in eng.items()}
    sid = {}

    def _admit(k):
        assert lc.request_handshake(SSRC[k], remote_addr=caddr[k])[0]
        sid[k] = next(s for s, v in bridge._ssrc_of.items()
                      if v == SSRC[k])

    def _client(b, k):
        fp = b._dtls.pending[sid[k]].local_fingerprint
        return StubDtlsEndpoint("client", remote_fingerprint=fp)

    # A: fully live before the kill
    _admit("live")
    _drive_stub_handshake(lc, bridge, eng["live"], sid["live"],
                          _client(bridge, "live"), caddr["live"])
    lc.commit()
    assert lc.admits == 1
    # B1 + B2: completed and STAGED, commit barrier not yet crossed
    for k in ("staged_ok", "staged_torn"):
        _admit(k)
        _drive_stub_handshake(lc, bridge, eng[k], sid[k],
                              _client(bridge, k), caddr[k])
    assert sorted(lc._staged) == sorted([sid["staged_ok"],
                                         sid["staged_torn"]])
    # C: mid-flight — the server sent its cert flight, nobody answered
    _admit("midflight")
    for d in StubDtlsEndpoint("client").handshake_packets():
        bridge._dtls.on_dtls(d, caddr["midflight"])
    lc.handshakes.drain()
    assert bridge._dtls.pending[sid["midflight"]].progressed
    # D: admitted with its ClientHello still QUEUED in the inbox
    _admit("inboxed")
    for d in StubDtlsEndpoint("client").handshake_packets():
        bridge._dtls.on_dtls(d, caddr["inboxed"])
    assert len(bridge._dtls._inbox) == 1

    ckpt = str(tmp_path / "storm.ckpt")
    sup.save_checkpoint(ckpt)
    bridge.close()                              # the mid-storm crash

    sup2 = BridgeSupervisor.recover(cfg, ckpt, SfuBridge, port=0,
                                    supervisor_config=sup.cfg,
                                    recv_window_ms=0)
    bridge2 = sup2.bridge
    bridge2._dtls.endpoint_factory = StubDtlsEndpoint
    # simulate a torn install for B2 (checkpoint raced the key write)
    bridge2._tx_keys.pop(sid["staged_torn"])
    lc2 = StreamLifecycleManager(bridge2, supervisor=sup2)
    lc2._warm_bucket = 1 << 30

    # live row rode the snapshot untouched
    assert bridge2._ssrc_of[sid["live"]] == SSRC["live"]
    assert sid["live"] in bridge2._tx_keys
    # staged survivor COMPLETED (counted, flagged recovered)
    assert lc2.admits == 1
    assert SSRC["staged_ok"] in bridge2._ssrc_of.values()
    assert any(e["kind"] == "admit_commit" and e.get("recovered")
               for e in sup2.flight.dump(sid["staged_ok"])["events"])
    # torn row ROLLED BACK: fully absent, nothing half-installed
    assert SSRC["staged_torn"] not in bridge2._ssrc_of.values()
    assert sid["staged_torn"] not in bridge2._tx_keys
    assert not bridge2.rx_table.active[sid["staged_torn"]]
    assert any(e["kind"] == "admit_rollback"
               for e in sup2.flight.dump(sid["staged_torn"])["events"])
    # mid-handshake rows REQUEUED as fresh associations at their bound
    # 5-tuples (OpenSSL state cannot serialize; the admission
    # parameters rode the checkpoint instead)
    assert lc2.handshakes.requeued == 2
    req = {bridge2._ssrc_of[s]: s for s in bridge2._dtls.pending}
    assert set(req) == {SSRC["midflight"], SSRC["inboxed"]}
    for k in ("midflight", "inboxed"):
        assert bridge2._dtls.sid_addr[req[SSRC[k]]] == caddr[k]
    rq = [e for e in sup2.flight.dump_all()["global"]
          if e["kind"] == "handshake_requeue"]
    assert sorted(e["ssrc"] for e in rq) \
        == sorted((SSRC["midflight"], SSRC["inboxed"]))
    assert all(e["accepted"] for e in rq)

    # the requeued associations complete against the recovered bridge
    clients2 = {}
    for k in ("midflight", "inboxed"):
        while eng[k].recv_batch(timeout_ms=0)[0].batch_size:
            pass                        # drop pre-kill server flights
        s2 = req[SSRC[k]]
        sid[k] = s2
        fp = bridge2._dtls.pending[s2].local_fingerprint
        clients2[k] = StubDtlsEndpoint("client", remote_fingerprint=fp)
        _drive_stub_handshake(lc2, bridge2, eng[k], s2, clients2[k],
                              caddr[k])
    lc2.commit()
    assert lc2.admits == 3 and not bridge2._dtls.pending
    assert lc2.tick_thread_handshake_feeds == 0
    for k in ("midflight", "inboxed"):       # finish off the DONE flight
        back, _, _ = eng[k].recv_batch(timeout_ms=100)
        for i in range(back.batch_size):
            clients2[k].feed(back.to_bytes(i))
        assert clients2[k].complete

    # whole-state invariant across every row the crash touched
    for s in range(bridge2.capacity):
        assert ((s in bridge2._ssrc_of) == (s in bridge2._tx_keys)
                == bool(bridge2.rx_table.active[s]))

    # a requeued-then-completed association is not just bookkeeping:
    # its handshake-exported keys decrypt media on the recovered bridge
    prof, ck, cs, _sk, _ss = clients2["midflight"].srtp_keys()
    prot = SrtpStreamTable(capacity=1, profile=prof)
    prot.add_stream(0, ck, cs)
    b = rtp_header.build([bytes(160)], [100], [16000],
                         [SSRC["midflight"]], [0], stream=[0])
    eng["midflight"].send_batch(prot.protect_rtp(b), "127.0.0.1",
                                bridge2.port)
    _pump(sup2, 100.0, 1)
    sup2.tick(now=100.02)
    assert int(bridge2.rx_table.rx_max[sid["midflight"]]) >= 100, \
        "requeued association's media did not decode after recovery"
    for e in eng.values():
        e.close()
    bridge2.close()


def test_kill_during_placement_move_completes_or_rolls_back(tmp_path):
    """Kill mid-rebalance: `migrate_endpoints` is host-atomic between
    ticks, so a checkpoint racing a placement move captures either the
    fully-pre-move or fully-post-move row layout, plus the in-flight
    move marker.  Recovery must resolve the move to a WHOLE state —
    rolled back (conference intact on the source shard, the move simply
    re-plans) or completed (conference intact on the destination shard,
    counted as applied) — and never a conference straddling two shard
    ranges.  Both arms, one universe each."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    cfg = libjitsi_tpu.configuration_service()
    bridge = SfuBridge(cfg, port=0, capacity=16, recv_window_ms=0)
    sup = BridgeSupervisor(bridge, SupervisorConfig(deadline_ms=1000.0))
    lc = StreamLifecycleManager(bridge, supervisor=sup)
    lc._warm_bucket = 1 << 30
    lc.enable_placement(4)
    k = 0
    for conf in (1, 2, 3, 4, 5):        # conf 5 doubles onto shard 0
        for _ in range(2):
            assert lc.request_join(0x500 + k, *_keys(k),
                                   conference=conf)[0]
            k += 1
    lc.poll()
    lc.commit()
    assert lc.admits == k and lc.placer.shard_of(5) == 0
    for sid, conf in list(bridge._conf_of.items()):
        if conf in (2, 3, 4):
            lc.request_leave(sid=sid)
    lc.commit()                          # shard 0 now hot: move pending

    # ---- arm 1: crash BEFORE the migration landed -> ROLLED BACK
    movers = sorted(s for s, c in bridge._conf_of.items() if c == 1)
    mapping = {s: s + lc._rows_per_shard for s in movers}
    lc._move_inflight = {"conf": 1, "src": 0, "dst": 1,
                         "mapping": dict(mapping)}
    ckpt_a = str(tmp_path / "midmove_premigrate.ckpt")
    sup.save_checkpoint(ckpt_a)
    bridge.close()                       # the crash

    sup2 = BridgeSupervisor.recover(cfg, ckpt_a, SfuBridge, port=0,
                                    supervisor_config=sup.cfg,
                                    recv_window_ms=0)
    bridge2 = sup2.bridge
    lc2 = StreamLifecycleManager(bridge2, supervisor=sup2)
    lc2._warm_bucket = 1 << 30
    # rolled back: conference 1 whole on its SOURCE shard
    assert lc2.placer.shard_of(1) == 0
    assert lc2.moves_applied == 0
    ev = [e for e in sup2.flight.dump_all()["global"]
          if e["kind"] == "placement_move_recovered"]
    assert ev and ev[-1]["outcome"] == "rolled_back"
    rows_per = lc2._rows_per_shard
    by_conf = {}
    for sid, conf in bridge2._conf_of.items():
        by_conf.setdefault(conf, set()).add(sid // rows_per)
    assert all(len(shards) == 1 for shards in by_conf.values()), \
        f"torn conference after recovery: {by_conf}"
    # the move is not lost, just un-landed: the next window re-plans it
    assert lc2.rebalance() == 1
    assert lc2.placer.shard_of(1) == 1

    # ---- arm 2: crash AFTER the migration landed, BEFORE the
    # placer/bookkeeping caught up -> COMPLETED
    conf5_rows = sorted(s for s, c in bridge2._conf_of.items()
                        if c == 5)
    mapping = {s: s + 2 * rows_per for s in conf5_rows}  # shard 0 -> 2
    bridge2.migrate_endpoints(mapping)
    lc2._move_inflight = {"conf": 5, "src": 0, "dst": 2,
                          "mapping": dict(mapping)}
    ckpt_b = str(tmp_path / "midmove_postmigrate.ckpt")
    sup2.save_checkpoint(ckpt_b)
    bridge2.close()                      # the crash

    sup3 = BridgeSupervisor.recover(cfg, ckpt_b, SfuBridge, port=0,
                                    supervisor_config=sup2.cfg,
                                    recv_window_ms=0)
    bridge3 = sup3.bridge
    lc3 = StreamLifecycleManager(bridge3, supervisor=sup3)
    lc3._warm_bucket = 1 << 30
    # completed: conference 5 whole on its DESTINATION shard, counted
    assert lc3.placer.shard_of(5) == 2
    assert lc3.moves_applied == 1
    ev = [e for e in sup3.flight.dump_all()["global"]
          if e["kind"] == "placement_move_recovered"]
    assert ev and ev[-1]["outcome"] == "completed"
    for sid, conf in bridge3._conf_of.items():
        assert sid in bridge3._ssrc_of
    by_conf = {}
    for sid, conf in bridge3._conf_of.items():
        by_conf.setdefault(conf, set()).add(sid // rows_per)
    assert all(len(shards) == 1 for shards in by_conf.values()), \
        f"torn conference after recovery: {by_conf}"
    # whole-state invariant across every row the crashes touched
    for sid in range(bridge3.capacity):
        assert ((sid in bridge3._ssrc_of) == (sid in bridge3._tx_keys)
                == bool(bridge3.rx_table.active[sid]))
    bridge3.close()
