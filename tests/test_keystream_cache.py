"""Keystream pregeneration cache properties (tier-1, NOT slow).

1. Never-serve-twice, unit level: a claimed slot is consumed and can
   never be claimed again — not by a retransmit, not after a
   whole-cache invalidation + refill (the refill base starts past the
   per-stream served high-water).  In-batch duplicate slots are served
   only when they are exact aliases of each other (the size-class
   padding case, where the stock path also emits identical ciphertext
   from the reused IV); any other in-batch duplicate misses wholesale.

2. Never-serve-twice, property level: a protect-side and an
   unprotect-side cache driven through real tables under random loss /
   reorder / retransmit / rekey chaos must end with a debug serve log
   containing no duplicate (key-epoch, stream, ssrc, index) tuple —
   each keystream byte sequence left the cache at most once.

3. Bit-exactness: a cache-enabled rx table and a stock rx table fed
   the IDENTICAL faulted wire (loss + corruption, SRTP sequence space
   crossing the ROC wrap) must agree byte for byte on the accept mask
   and every decrypted payload; same on the protect side for
   ciphertext.  The cached run must actually hit (else the test is
   vacuous stock-vs-stock).
"""

import numpy as np
import pytest

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpProfile, SrtpStreamTable

SEQ0 = 65526          # crosses the ROC wrap mid-run
SSRCS = (0x4242, 0x5353, 0x6464)


def _keys(b):
    return bytes([b]) * 16, bytes([b + 1]) * 12


def _gcm_table(n=8):
    t = SrtpStreamTable(capacity=n, profile=SrtpProfile.AEAD_AES_128_GCM)
    for i, ssrc in enumerate(SSRCS):
        t.add_stream(i, *_keys(0x10 * (i + 1)))
    return t


def _batch(tick, streams=range(len(SSRCS))):
    streams = list(streams)
    return rtp_header.build(
        [bytes([s, tick & 0xFF]) * 40 for s in streams],
        [(SEQ0 + tick) & 0xFFFF] * len(streams),
        [160 * (tick + 1)] * len(streams),
        [SSRCS[s] for s in streams],
        [96] * len(streams), stream=streams)


# ------------------------------------------------------------- unit


def test_claimed_slot_never_claimable_again():
    t = _gcm_table()
    c = t.enable_keystream_cache(window=32, debug=True)
    c.prime(np.array([0]), np.array([SSRCS[0]]), start=100)
    args = (np.array([0]), np.array([SSRCS[0]]), np.array([100]),
            np.array([64]), True)
    assert c.claim(*args) is not None
    # retransmit of the same index: consumed bitmap blocks it
    assert c.claim(*args) is None
    # whole-cache invalidation + refill: the new window starts past the
    # served high-water, so index 100 is gone for good under these keys
    c.invalidate()
    c.fill()
    assert c.claim(*args) is None
    assert c.claim(np.array([0]), np.array([SSRCS[0]]), np.array([101]),
                   np.array([64]), True) is not None
    # rekey resets the epoch: index 100 is claimable again, but the
    # serve log distinguishes it by key generation
    t.add_stream(0, *_keys(0x77))
    c.prime(np.array([0]), np.array([SSRCS[0]]), start=100)
    assert c.claim(*args) is not None
    log = set(c._serve_log)
    assert len(log) == len(c._serve_log)
    assert {(g, i) for g, _s, _v, i in log} == {(0, 100), (0, 101), (1, 100)}


def test_in_batch_duplicates_alias_only():
    t = _gcm_table()
    c = t.enable_keystream_cache(window=32, debug=True)
    c.prime(np.array([0]), np.array([SSRCS[0]]), start=200)
    two = np.array([0, 0])
    ssrc = np.array([SSRCS[0]] * 2)
    # exact aliases (size-class padding cycles real rows): one serve,
    # one consumption, one log entry
    got = c.claim(two, ssrc, np.array([200, 200]), np.array([64, 64]), True)
    assert got is not None
    assert np.asarray(got[2])[0] == np.asarray(got[2])[1]
    assert len(c._serve_log) == 1
    assert c.claim(np.array([0]), np.array([SSRCS[0]]), np.array([200]),
                   np.array([64]), True) is None
    # non-alias duplicate (same index, different length) would pair one
    # keystream with two plaintexts: whole batch misses, nothing is
    # consumed, and the index stays claimable
    got = c.claim(two, ssrc, np.array([201, 201]), np.array([64, 48]), True)
    assert got is None
    assert c.claim(np.array([0]), np.array([SSRCS[0]]), np.array([201]),
                   np.array([64]), True) is not None


# --------------------------------------------------------- property


def test_never_serve_twice_under_chaos():
    """Loss / reorder / retransmit / rekey chaos through real tables:
    both direction's serve logs stay duplicate-free."""
    rng = np.random.default_rng(7)
    tx = _gcm_table()
    rx = _gcm_table()
    ctx = tx.enable_keystream_cache(window=64, debug=True)
    crx = rx.enable_keystream_cache(window=64, debug=True)
    all_s = np.arange(len(SSRCS))
    all_v = np.asarray(SSRCS)
    ctx.prime(all_s, all_v, start=SEQ0)
    crx.prime(all_s, all_v, start=SEQ0)
    queue = []                      # delayed wire rows (reorder)
    for tick in range(28):
        wire = tx.protect_rtp(_batch(tick))
        for i in range(wire.batch_size):
            u = rng.random()
            if u < 0.15:
                continue            # lost
            row = (wire.to_bytes(i), int(wire.stream[i]))
            queue.append(row)
            if u < 0.30:
                queue.append(row)   # retransmit
        rng.shuffle(queue)
        feed, queue = queue[:4], queue[4:]
        if feed:
            cap = max(len(b) for b, _ in feed)
            data = np.zeros((len(feed), cap), np.uint8)
            for i, (b, _) in enumerate(feed):
                data[i, :len(b)] = np.frombuffer(b, np.uint8)
            pb = PacketBatch(data,
                             np.asarray([len(b) for b, _ in feed],
                                        dtype=np.int32),
                             np.asarray([s for _, s in feed],
                                        dtype=np.int32))
            rx.unprotect_rtp(pb)
        if tick == 13:              # mid-run rekey of stream 1
            tx.add_stream(1, *_keys(0xA0))
            rx.add_stream(1, *_keys(0xA0))
        ctx.fill()
        crx.fill()
    for cache in (ctx, crx):
        assert cache.hits > 0
        log = cache._serve_log
        assert len(set(log)) == len(log), "a keystream slot served twice"


# ----------------------------------------------------- bit-exactness


def _faulted_wire(n_ticks=24, seed=99):
    """(tick -> list of (bytes, stream)) — ~15% loss, ~10% corruption,
    generated offline so both universes see identical bytes."""
    rng = np.random.default_rng(seed)
    prot = _gcm_table()
    wire = {t: [] for t in range(n_ticks)}
    for t in range(n_ticks):
        pb = prot.protect_rtp(_batch(t))
        for i in range(pb.batch_size):
            raw = bytearray(pb.to_bytes(i))
            u = rng.random()
            pos = int(rng.integers(0, len(raw)))
            if u < 0.15:
                continue
            if u < 0.25:
                raw[pos] ^= 0xFF
            wire[t].append((bytes(raw), int(pb.stream[i])))
    return wire


def _wire_batch(rows):
    cap = max(len(b) for b, _ in rows)
    data = np.zeros((len(rows), cap), np.uint8)
    for i, (b, _) in enumerate(rows):
        data[i, :len(b)] = np.frombuffer(b, np.uint8)
    return PacketBatch(data,
                       np.asarray([len(b) for b, _ in rows], np.int32),
                       np.asarray([s for _, s in rows], np.int32))


def _run_rx(cached: bool, wire, n_ticks=24):
    rx = _gcm_table()
    cache = None
    if cached:
        cache = rx.enable_keystream_cache(window=64)
        cache.prime(np.arange(len(SSRCS)), np.asarray(SSRCS), start=SEQ0)
    accepted = {}
    for t in range(n_ticks):
        if not wire[t]:
            continue
        dec, ok = rx.unprotect_rtp(_wire_batch(wire[t]))
        for i in np.nonzero(ok)[0]:
            i = int(i)
            accepted[(int(dec.stream[i]), t)] = dec.to_bytes(i)
        if cache is not None:
            cache.fill()
    return accepted, cache


def test_cached_unprotect_bit_exact_across_roc_wrap():
    wire = _faulted_wire()
    stock, _ = _run_rx(False, wire)
    cached, cache = _run_rx(True, wire)
    assert cache.hits > 0, "cached run never hit — vacuous comparison"
    assert cached == stock
    # the wire really crossed the wrap (else the ROC half of the claim
    # index was never exercised)
    assert any(t >= 65536 - SEQ0 for _, t in stock)


def test_cached_protect_bit_exact_across_roc_wrap():
    stock_tx = _gcm_table()
    cached_tx = _gcm_table()
    cache = cached_tx.enable_keystream_cache(window=64)
    cache.prime(np.arange(len(SSRCS)), np.asarray(SSRCS), start=SEQ0)
    for t in range(20):
        b = _batch(t)
        a = stock_tx.protect_rtp(b)
        c = cached_tx.protect_rtp(b)
        for i in range(a.batch_size):
            assert c.to_bytes(i) == a.to_bytes(i), (t, i)
        cache.fill()
    assert cache.hits > 0
