"""Bridge-to-bridge cascade trunk (mesh/cascade.py).

Unit tier for the trunk leg itself: wire-format roundtrip under the
trunk's own SRTP layer, typed admission with jittered retry hints,
heartbeat liveness with down detection and backlog flush on recovery,
speaker/roster control-plane propagation with the echo-loop guard and
failover ownership claim, and the loss-recovery span across the hop —
NACK/RTX under Gilbert–Elliott loss with a residual-loss assertion,
XOR-FEC single-loss repair, and the deadline discipline (an expired
loss is conceded to PLC and never re-NACKed).

All trunk pairs here exchange datagrams through an in-memory channel
(monkeypatched `_send`) so loss is injected deterministically; the
socket path is covered by the churn_soak `--cascade` scenario and
tests/test_chaos_recovery.py.
"""

import json

import numpy as np
import pytest

from libjitsi_tpu.mesh.cascade import (CascadeTrunk, MAGIC_CONTROL,
                                       KIND_NACK, TRUNK_SSRC,
                                       TrunkConfig, TrunkRelay)
from libjitsi_tpu.mesh.placement import ConferencePlacer
from libjitsi_tpu.utils.metrics import MetricsRegistry
from libjitsi_tpu.utils.slo import SlicedSloSpec, SloEngine

KEY_AB = (b"\xa0" * 16, b"\xa1" * 14)
KEY_BA = (b"\xb0" * 16, b"\xb1" * 14)


def _relay_pair(cfg=None):
    a = TrunkRelay(KEY_AB, KEY_BA, cfg)
    b = TrunkRelay(KEY_BA, KEY_AB, cfg)
    return a, b


def _inner(tag: int, n: int = 90) -> bytes:
    return bytes([0x80, 96]) + bytes([tag]) * n


# ------------------------------------------------------------ wire format

def test_trunk_frame_roundtrip():
    a, b = _relay_pair()
    seq, wire = a.frame_media(7, _inner(1), now=0.0)
    got = b.open_media(wire, now=0.0)
    assert got is not None
    rseq, conf, inner = got
    assert rseq == seq and conf == 7 and inner == _inner(1)


def test_trunk_layer_authenticates_independently():
    """A peer holding the WRONG trunk key opens nothing, even though
    the inner packet is in the clear relative to the trunk layer."""
    a, _ = _relay_pair()
    mallory = TrunkRelay(KEY_BA, (b"\xee" * 16, b"\xef" * 14))
    _seq, wire = a.frame_media(7, _inner(2), now=0.0)
    assert mallory.open_media(wire, now=0.0) is None


def test_trunk_seq_wraps_mod16():
    a, b = _relay_pair()
    a.tx_seq = 0xFFFF
    s1, w1 = a.frame_media(7, _inner(3), now=0.0)
    s2, w2 = a.frame_media(7, _inner(4), now=0.0)
    assert (s1, s2) == (0xFFFF, 0)
    assert b.open_media(w1, now=0.0) is not None
    assert b.open_media(w2, now=0.0) is not None


def test_oversize_inner_refused():
    a, _ = _relay_pair()
    assert a.frame_media(7, b"\x80" * 1500, now=0.0) is None


# ------------------------------------------------------- typed admission

def test_admit_reason_and_jittered_retry_hint():
    tr = CascadeTrunk(KEY_AB, KEY_BA, TrunkConfig(), seed=3)
    try:
        assert tr.admit_reason() == "trunk_down"      # never connected
        tr.connect("127.0.0.1", 1, now=0.0)
        assert tr.admit_reason() is None
        tr._tx_queue.extend([b"x"] * tr.cfg.backlog_bound)
        assert tr.admit_reason() == "trunk_backlog"
        assert not tr.relay_media(7, _inner(5), now=0.0)
        assert tr.refusals_total == 1
        # hint escalates with reconnect attempts, jitter bounded +25%
        base = tr.cfg.retry_base_s
        for attempts in (0, 3, 9):
            tr.attempts = attempts
            lo = base * (2 ** min(attempts, 6))
            for _ in range(8):
                assert lo <= tr.retry_after() <= lo * 1.25
    finally:
        tr.close()


# ---------------------------------------------- liveness + control plane

class _Channel:
    """Deterministic in-memory wire between two trunks.  `drop(data)`
    decides per-datagram loss; control frames are also visible for
    protocol assertions (NACK discipline)."""

    def __init__(self):
        self.ends = {}
        self.queues = {"a": [], "b": []}
        self.nack_log = []                  # (now, [seqs]) B -> A
        self.dropped = 0
        self.drop = lambda data: False
        self.now = 0.0

    def wire(self, ta, tb):
        self.ends = {"a": ta, "b": tb}
        ta._send = lambda data: self._push("b", data)
        tb._send = lambda data: self._push("a", data)

    def _push(self, dst, data):
        if data[0] == MAGIC_CONTROL and data[1] == KIND_NACK:
            self.nack_log.append(
                (self.now, json.loads(data[2:].decode())["seqs"]))
        if data[0] != MAGIC_CONTROL and self.drop(data):
            self.dropped += 1
            return
        self.queues[dst].append(data)

    def deliver(self, now):
        self.now = now
        for name, tr in self.ends.items():
            q, self.queues[name] = self.queues[name], []
            for data in q:
                tr.on_datagram(data, now)


def _trunk_pair(cfg=None, seed=0):
    cfg = cfg or TrunkConfig()
    ta = CascadeTrunk(KEY_AB, KEY_BA, cfg, seed=seed)
    tb = CascadeTrunk(KEY_BA, KEY_AB, cfg, seed=seed + 1)
    ch = _Channel()
    ch.wire(ta, tb)
    ta.connect("127.0.0.1", 1, now=0.0)
    tb.connect("127.0.0.1", 1, now=0.0)
    return ta, tb, ch


def _run(ta, tb, ch, now, steps, dt=0.01, pump_b=True):
    for _ in range(steps):
        now += dt
        ta.pump(now)
        if pump_b:
            tb.pump(now)
        ch.deliver(now)
    return now


def test_heartbeat_down_detection_and_backlog_flush():
    ta, tb, ch = _trunk_pair()
    downs, ups = [], []
    ta.on_down = downs.append
    ta.on_up = ups.append
    delivered = []
    tb.deliver = lambda conf, inner: delivered.append(inner)
    ta.cascade_conference(7)
    now = _run(ta, tb, ch, 0.0, 20)
    assert ta.state == tb.state == "up"
    assert 0.0 < ta.rtt <= 0.02
    # partition: B stops answering — A flips down after the miss streak
    ch.drop = lambda data: True
    orig = tb._send
    tb._send = lambda data: None
    for _ in range(200):
        now += 0.01
        ta.pump(now)
        ch.deliver(now)
        if ta.state == "down":
            break
    assert ta.state == "down" and downs
    # media while down rides the bounded backlog, not the floor
    assert ta.relay_media(7, _inner(6), now=now)
    assert len(ta._tx_queue) == 1
    # heal: the next answered heartbeat flips up and flushes the queue
    ch.drop = lambda data: False
    tb._send = orig
    for _ in range(400):
        now += 0.01
        ta.pump(now)
        tb.pump(now)
        ch.deliver(now)
        if ta.state == "up" and delivered:
            break
    assert ta.state == "up" and ups
    assert delivered == [_inner(6)]


def test_speakers_roster_echo_guard_and_claim():
    ta, tb, ch = _trunk_pair()
    flips, rosters = [], []
    tb.on_speakers = lambda conf, ssrcs: flips.append((conf, ssrcs))
    tb.on_roster = rosters.append
    ta.cascade_conference(7)
    tb.cascade_conference(7)
    now = _run(ta, tb, ch, 0.0, 3)
    # top-K flip propagates: both ends restrict the same legs
    ta.set_speakers(7, [0x111, 0x222], now=now)
    now = _run(ta, tb, ch, now, 2)
    assert tb._confs[7] == {0x111, 0x222}
    assert flips and flips[-1][0] == 7
    assert ta.wants(7, 0x111) and not ta.wants(7, 0x333)
    # roster sync: B learns A's members and marks them peer-homed
    ta.set_roster({7: [{"ssrc": 0x111, "rx": ["aa", "bb"],
                        "tx": ["cc", "dd"]}]})
    now = _run(ta, tb, ch, now, 2)
    assert rosters and 7 in tb.remote_roster
    assert 0x111 in tb._remote_ssrcs
    # echo-loop guard: the peer-homed member is never relayed BACK
    tb.set_speakers(7, [0x111], now=now)
    assert not tb.wants(7, 0x111)
    # failover adoption commits -> ownership transfer lifts the guard
    tb.claim_member(7, 0x111)
    assert 0x111 not in tb._remote_ssrcs
    assert tb.wants(7, 0x111)
    assert 7 not in tb.remote_roster


# ------------------------------------------------- loss recovery span

def test_nack_rtx_recovers_gilbert_elliott_loss():
    """E2E across the hop: media under bursty GE loss, the receive side
    NACKs trunk seqs, the send side serves RTX from its cache, and the
    residual loss after the recovery window is ZERO."""
    cfg = TrunkConfig(fec_k=0)             # isolate the NACK/RTX path
    ta, tb, ch = _trunk_pair(cfg)
    delivered = []
    tb.deliver = lambda conf, inner: delivered.append(inner)
    ta.cascade_conference(7)

    rng = np.random.default_rng(11)
    state = {"bad": False}

    def ge_drop(_data):
        # Gilbert–Elliott: p(good->bad)=.12, p(bad->good)=.4,
        # loss .75 in bad, .02 in good
        if state["bad"]:
            if rng.random() < 0.4:
                state["bad"] = False
        elif rng.random() < 0.12:
            state["bad"] = True
        return rng.random() < (0.75 if state["bad"] else 0.02)

    now = _run(ta, tb, ch, 0.0, 5)
    ch.drop = ge_drop
    sent = []
    for k in range(120):
        inner = _inner(k % 251)
        sent.append(inner)
        assert ta.relay_media(7, inner, now=now)
        now = _run(ta, tb, ch, now, 1)
    ch.drop = lambda data: False           # tail: only recovery traffic
    now = _run(ta, tb, ch, now, 30)
    assert ch.dropped > 0, "GE channel never dropped — test is vacuous"
    assert tb.nacks_sent_total > 0
    assert ta.rtx_served_total > 0
    residual = {bytes(s) for s in sent} - {bytes(d) for d in delivered}
    assert not residual, f"unrecovered after NACK/RTX: {len(residual)}"


def test_fec_recovers_single_loss_without_roundtrip():
    ta, tb, ch = _trunk_pair(TrunkConfig(fec_k=4))
    delivered = []
    tb.deliver = lambda conf, inner: delivered.append(inner)
    ta.cascade_conference(7)
    now = _run(ta, tb, ch, 0.0, 3)
    # drop exactly the second media frame of the 4-frame FEC group
    seen = {"n": 0}

    def drop_second(_data):
        seen["n"] += 1
        return seen["n"] == 2

    ch.drop = drop_second
    for k in range(4):
        ta.relay_media(7, _inner(0x30 + k), now=now)
    now = _run(ta, tb, ch, now, 2)
    assert tb.fec_recovered_total == 1
    assert _inner(0x31) in delivered       # the dropped frame, repaired


def test_deadline_expired_loss_is_plc_not_renack():
    """A trunk seq lost past `deadline_budget_s` is conceded to PLC
    accounting and never re-NACKed — concealment on the destination
    bridge, not a retransmission storm across the trunk."""
    cfg = TrunkConfig(fec_k=0, deadline_budget_s=0.06)
    ta, tb, ch = _trunk_pair(cfg)
    ta.cascade_conference(7)
    now = _run(ta, tb, ch, 0.0, 3)
    # permanently drop the SECOND media frame (the first must arrive to
    # seed the loss tracker) — original AND every RTX of it
    doomed = {"seq": None, "n": 0}

    def drop_doomed(data):
        seq = int.from_bytes(data[2:4], "big")
        if doomed["seq"] is None:
            doomed["n"] += 1
            if doomed["n"] == 2:
                doomed["seq"] = seq
                return True
            return False
        return seq == doomed["seq"]

    ch.drop = drop_doomed
    for k in range(4):
        ta.relay_media(7, _inner(0x50 + k), now=now)
        now = _run(ta, tb, ch, now, 1)
    # run far past the deadline: the loss must expire, not re-NACK
    now = _run(ta, tb, ch, now, 40)
    assert tb.plc_fallthrough_total >= 1
    expiry_nacks = [t for t, seqs in ch.nack_log
                    if doomed["seq"] in seqs]
    assert expiry_nacks, "the loss was never NACKed at all"
    # every NACK naming the doomed seq predates the deadline
    assert max(expiry_nacks) <= expiry_nacks[0] + cfg.deadline_budget_s
    post = [seqs for t, seqs in ch.nack_log
            if t > expiry_nacks[0] + cfg.deadline_budget_s]
    assert all(doomed["seq"] not in seqs for seqs in post)


# ------------------------------------------------- failover adjuncts

def test_placer_bridge_axis_evacuate_and_adopt():
    p = ConferencePlacer(n_shards=2)
    p.enable_bridges(2)
    assert p.place_bridge(1, 4) == 0       # least loaded
    assert p.place_bridge(2, 4) == 1
    assert p.place_bridge(1, 4) == 0       # sticky re-placement
    # bridge 1 dies: its conferences are un-homed, then the failover
    # plane adopts each explicitly as its adoption commits
    orphans = p.evacuate_bridge(1)
    assert orphans == [2] and p.bridge_of(2) is None
    p.adopt_bridge(2, 0, 4)
    assert p.bridge_of(2) == 0
    # new placements avoid a dead peer when asked
    assert p.place_bridge(3, 4, avoid=(1,)) == 0


def test_sliced_slo_bridge_label_axis():
    reg = MetricsRegistry()
    slo = SloEngine(reg)
    slo.register_metrics(reg)
    good = {"0": 1000.0, "1": 1000.0}
    bad = {"0": 0.0, "1": 0.0}
    slo.add_sliced(SlicedSloSpec(
        name="bridge_media", objective=0.999, label="bridge",
        reader=lambda: ((k, good[k], bad[k]) for k in good)))
    for _ in range(3):
        good["0"] += 100.0
        good["1"] += 100.0
        slo.on_tick()
    scrape = reg.render()
    assert 'bridge="0"' in scrape and 'bridge="1"' in scrape
    # bridge 1 starts burning its media budget: only ITS slice alerts
    for _ in range(60):
        good["0"] += 100.0
        bad["1"] += 50.0
        slo.on_tick()
    assert slo.slice_state("bridge_media", "0") == "ok"
    assert slo.slice_state("bridge_media", "1") != "ok"
