"""Bridge-to-bridge cascade trunk (mesh/cascade.py).

Unit tier for the trunk leg itself: wire-format roundtrip under the
trunk's own SRTP layer, typed admission with jittered retry hints,
heartbeat liveness with down detection and backlog flush on recovery,
speaker/roster control-plane propagation with the echo-loop guard and
failover ownership claim, and the loss-recovery span across the hop —
NACK/RTX under Gilbert–Elliott loss with a residual-loss assertion,
XOR-FEC single-loss repair, and the deadline discipline (an expired
loss is conceded to PLC and never re-NACKed).

All trunk pairs here exchange datagrams through an in-memory channel
(monkeypatched `_send`) so loss is injected deterministically; the
socket path is covered by the churn_soak `--cascade` scenario and
tests/test_chaos_recovery.py.
"""

import json

import numpy as np
import pytest

from libjitsi_tpu.mesh.cascade import (CascadeTrunk, MAGIC_CONTROL,
                                       KIND_NACK, TRACE_WIRE_LEN,
                                       TRUNK_SSRC, TrunkConfig,
                                       TrunkRelay, TrunkTrace)
from libjitsi_tpu.mesh.placement import ConferencePlacer
from libjitsi_tpu.utils.metrics import MetricsRegistry
from libjitsi_tpu.utils.slo import SlicedSloSpec, SloEngine

KEY_AB = (b"\xa0" * 16, b"\xa1" * 14)
KEY_BA = (b"\xb0" * 16, b"\xb1" * 14)


def _relay_pair(cfg=None):
    a = TrunkRelay(KEY_AB, KEY_BA, cfg)
    b = TrunkRelay(KEY_BA, KEY_AB, cfg)
    return a, b


def _inner(tag: int, n: int = 90) -> bytes:
    return bytes([0x80, 96]) + bytes([tag]) * n


# ------------------------------------------------------------ wire format

def test_trunk_frame_roundtrip():
    a, b = _relay_pair()
    seq, wire = a.frame_media(7, _inner(1), now=0.0)
    got = b.open_media(wire, now=0.0)
    assert got is not None
    rseq, conf, inner, trace = got
    assert rseq == seq and conf == 7 and inner == _inner(1)
    assert trace is None                   # legacy frame carries none


def test_trace_extension_roundtrip_and_legacy_interop():
    """The journey trace rides an RTP header extension on the trunk
    frame: a traced frame opens to the same (conf, inner) BIT-EXACT as
    an untraced one (the extension lives in the header, outside the
    payload slice an old peer takes), and an untraced frame opens on a
    new peer with `trace=None` — interop both directions."""
    a, b = _relay_pair()
    _s, plain = a.frame_media(7, _inner(1), now=0.0)
    tr = TrunkTrace(bridge_id=2, hop=1, trace_id=0xDEADBEEF, t0=12.5)
    _s2, traced = a.frame_media(7, _inner(1), now=0.0, trace=tr)
    assert len(traced) == len(plain) + TRACE_WIRE_LEN
    got = b.open_media(plain, now=0.0)
    assert got is not None and got[3] is None
    got_t = b.open_media(traced, now=0.0)
    assert got_t is not None
    _rseq, conf, inner, rtr = got_t
    assert (conf, inner) == (7, _inner(1))     # inner bit-exact
    assert rtr == tr                           # µs stamp roundtrips


def test_trunk_seq_wraps_with_trace_extension():
    a, b = _relay_pair()
    tr = TrunkTrace(bridge_id=0, hop=0, trace_id=1, t0=0.0)
    a.tx_seq = 0xFFFF
    s1, w1 = a.frame_media(7, _inner(3), now=0.0, trace=tr)
    s2, w2 = a.frame_media(7, _inner(4), now=0.0, trace=tr)
    assert (s1, s2) == (0xFFFF, 0)
    g1 = b.open_media(w1, now=0.0)
    g2 = b.open_media(w2, now=0.0)
    assert g1 is not None and g1[3] == tr
    assert g2 is not None and g2[2] == _inner(4)


def test_trunk_layer_authenticates_independently():
    """A peer holding the WRONG trunk key opens nothing, even though
    the inner packet is in the clear relative to the trunk layer."""
    a, _ = _relay_pair()
    mallory = TrunkRelay(KEY_BA, (b"\xee" * 16, b"\xef" * 14))
    _seq, wire = a.frame_media(7, _inner(2), now=0.0)
    assert mallory.open_media(wire, now=0.0) is None


def test_trunk_seq_wraps_mod16():
    a, b = _relay_pair()
    a.tx_seq = 0xFFFF
    s1, w1 = a.frame_media(7, _inner(3), now=0.0)
    s2, w2 = a.frame_media(7, _inner(4), now=0.0)
    assert (s1, s2) == (0xFFFF, 0)
    assert b.open_media(w1, now=0.0) is not None
    assert b.open_media(w2, now=0.0) is not None


def test_oversize_inner_refused():
    a, _ = _relay_pair()
    assert a.frame_media(7, b"\x80" * 1500, now=0.0) is None


# ------------------------------------------------------- typed admission

def test_admit_reason_and_jittered_retry_hint():
    tr = CascadeTrunk(KEY_AB, KEY_BA, TrunkConfig(), seed=3)
    try:
        assert tr.admit_reason() == "trunk_down"      # never connected
        tr.connect("127.0.0.1", 1, now=0.0)
        assert tr.admit_reason() is None
        tr._tx_queue.extend([b"x"] * tr.cfg.backlog_bound)
        assert tr.admit_reason() == "trunk_backlog"
        assert not tr.relay_media(7, _inner(5), now=0.0)
        assert tr.refusals_total == 1
        # hint escalates with reconnect attempts, jitter bounded +25%
        base = tr.cfg.retry_base_s
        for attempts in (0, 3, 9):
            tr.attempts = attempts
            lo = base * (2 ** min(attempts, 6))
            for _ in range(8):
                assert lo <= tr.retry_after() <= lo * 1.25
    finally:
        tr.close()


# ---------------------------------------------- liveness + control plane

class _Channel:
    """Deterministic in-memory wire between two trunks.  `drop(data)`
    decides per-datagram loss; control frames are also visible for
    protocol assertions (NACK discipline)."""

    def __init__(self):
        self.ends = {}
        self.queues = {"a": [], "b": []}
        self.nack_log = []                  # (now, [seqs]) B -> A
        self.dropped = 0
        self.drop = lambda data: False
        self.now = 0.0

    def wire(self, ta, tb):
        self.ends = {"a": ta, "b": tb}
        ta._send = lambda data: self._push("b", data)
        tb._send = lambda data: self._push("a", data)

    def _push(self, dst, data):
        if data[0] == MAGIC_CONTROL and data[1] == KIND_NACK:
            self.nack_log.append(
                (self.now, json.loads(data[2:].decode())["seqs"]))
        if data[0] != MAGIC_CONTROL and self.drop(data):
            self.dropped += 1
            return
        self.queues[dst].append(data)

    def deliver(self, now):
        self.now = now
        for name, tr in self.ends.items():
            q, self.queues[name] = self.queues[name], []
            for data in q:
                tr.on_datagram(data, now)


def _trunk_pair(cfg=None, seed=0):
    cfg = cfg or TrunkConfig()
    ta = CascadeTrunk(KEY_AB, KEY_BA, cfg, seed=seed)
    tb = CascadeTrunk(KEY_BA, KEY_AB, cfg, seed=seed + 1)
    ch = _Channel()
    ch.wire(ta, tb)
    ta.connect("127.0.0.1", 1, now=0.0)
    tb.connect("127.0.0.1", 1, now=0.0)
    return ta, tb, ch


def _run(ta, tb, ch, now, steps, dt=0.01, pump_b=True):
    for _ in range(steps):
        now += dt
        ta.pump(now)
        if pump_b:
            tb.pump(now)
        ch.deliver(now)
    return now


def test_heartbeat_down_detection_and_backlog_flush():
    ta, tb, ch = _trunk_pair()
    downs, ups = [], []
    ta.on_down = downs.append
    ta.on_up = ups.append
    delivered = []
    tb.deliver = lambda conf, inner, trace=None: delivered.append(inner)
    ta.cascade_conference(7)
    now = _run(ta, tb, ch, 0.0, 20)
    assert ta.state == tb.state == "up"
    assert 0.0 < ta.rtt <= 0.02
    # partition: B stops answering — A flips down after the miss streak
    ch.drop = lambda data: True
    orig = tb._send
    tb._send = lambda data: None
    for _ in range(200):
        now += 0.01
        ta.pump(now)
        ch.deliver(now)
        if ta.state == "down":
            break
    assert ta.state == "down" and downs
    # media while down rides the bounded backlog, not the floor
    assert ta.relay_media(7, _inner(6), now=now)
    assert len(ta._tx_queue) == 1
    # heal: the next answered heartbeat flips up and flushes the queue
    ch.drop = lambda data: False
    tb._send = orig
    for _ in range(400):
        now += 0.01
        ta.pump(now)
        tb.pump(now)
        ch.deliver(now)
        if ta.state == "up" and delivered:
            break
    assert ta.state == "up" and ups
    assert delivered == [_inner(6)]


def test_speakers_roster_echo_guard_and_claim():
    ta, tb, ch = _trunk_pair()
    flips, rosters = [], []
    tb.on_speakers = lambda conf, ssrcs: flips.append((conf, ssrcs))
    tb.on_roster = rosters.append
    ta.cascade_conference(7)
    tb.cascade_conference(7)
    now = _run(ta, tb, ch, 0.0, 3)
    # top-K flip propagates: both ends restrict the same legs
    ta.set_speakers(7, [0x111, 0x222], now=now)
    now = _run(ta, tb, ch, now, 2)
    assert tb._confs[7] == {0x111, 0x222}
    assert flips and flips[-1][0] == 7
    assert ta.wants(7, 0x111) and not ta.wants(7, 0x333)
    # roster sync: B learns A's members and marks them peer-homed
    ta.set_roster({7: [{"ssrc": 0x111, "rx": ["aa", "bb"],
                        "tx": ["cc", "dd"]}]})
    now = _run(ta, tb, ch, now, 2)
    assert rosters and 7 in tb.remote_roster
    assert 0x111 in tb._remote_ssrcs
    # echo-loop guard: the peer-homed member is never relayed BACK
    tb.set_speakers(7, [0x111], now=now)
    assert not tb.wants(7, 0x111)
    # failover adoption commits -> ownership transfer lifts the guard
    tb.claim_member(7, 0x111)
    assert 0x111 not in tb._remote_ssrcs
    assert tb.wants(7, 0x111)
    assert 7 not in tb.remote_roster


# ------------------------------------------------- loss recovery span

def test_nack_rtx_recovers_gilbert_elliott_loss():
    """E2E across the hop: media under bursty GE loss, the receive side
    NACKs trunk seqs, the send side serves RTX from its cache, and the
    residual loss after the recovery window is ZERO."""
    cfg = TrunkConfig(fec_k=0)             # isolate the NACK/RTX path
    ta, tb, ch = _trunk_pair(cfg)
    delivered = []
    tb.deliver = lambda conf, inner, trace=None: delivered.append(inner)
    ta.cascade_conference(7)

    rng = np.random.default_rng(11)
    state = {"bad": False}

    def ge_drop(_data):
        # Gilbert–Elliott: p(good->bad)=.12, p(bad->good)=.4,
        # loss .75 in bad, .02 in good
        if state["bad"]:
            if rng.random() < 0.4:
                state["bad"] = False
        elif rng.random() < 0.12:
            state["bad"] = True
        return rng.random() < (0.75 if state["bad"] else 0.02)

    now = _run(ta, tb, ch, 0.0, 5)
    ch.drop = ge_drop
    sent = []
    for k in range(120):
        inner = _inner(k % 251)
        sent.append(inner)
        assert ta.relay_media(7, inner, now=now)
        now = _run(ta, tb, ch, now, 1)
    ch.drop = lambda data: False           # tail: only recovery traffic
    now = _run(ta, tb, ch, now, 30)
    assert ch.dropped > 0, "GE channel never dropped — test is vacuous"
    assert tb.nacks_sent_total > 0
    assert ta.rtx_served_total > 0
    residual = {bytes(s) for s in sent} - {bytes(d) for d in delivered}
    assert not residual, f"unrecovered after NACK/RTX: {len(residual)}"


def test_fec_recovers_single_loss_without_roundtrip():
    ta, tb, ch = _trunk_pair(TrunkConfig(fec_k=4))
    delivered = []
    tb.deliver = lambda conf, inner, trace=None: delivered.append(inner)
    ta.cascade_conference(7)
    now = _run(ta, tb, ch, 0.0, 3)
    # drop exactly the second media frame of the 4-frame FEC group
    seen = {"n": 0}

    def drop_second(_data):
        seen["n"] += 1
        return seen["n"] == 2

    ch.drop = drop_second
    for k in range(4):
        ta.relay_media(7, _inner(0x30 + k), now=now)
    now = _run(ta, tb, ch, now, 2)
    assert tb.fec_recovered_total == 1
    assert _inner(0x31) in delivered       # the dropped frame, repaired


def test_deadline_expired_loss_is_plc_not_renack():
    """A trunk seq lost past `deadline_budget_s` is conceded to PLC
    accounting and never re-NACKed — concealment on the destination
    bridge, not a retransmission storm across the trunk."""
    cfg = TrunkConfig(fec_k=0, deadline_budget_s=0.06)
    ta, tb, ch = _trunk_pair(cfg)
    ta.cascade_conference(7)
    now = _run(ta, tb, ch, 0.0, 3)
    # permanently drop the SECOND media frame (the first must arrive to
    # seed the loss tracker) — original AND every RTX of it
    doomed = {"seq": None, "n": 0}

    def drop_doomed(data):
        seq = int.from_bytes(data[2:4], "big")
        if doomed["seq"] is None:
            doomed["n"] += 1
            if doomed["n"] == 2:
                doomed["seq"] = seq
                return True
            return False
        return seq == doomed["seq"]

    ch.drop = drop_doomed
    for k in range(4):
        ta.relay_media(7, _inner(0x50 + k), now=now)
        now = _run(ta, tb, ch, now, 1)
    # run far past the deadline: the loss must expire, not re-NACK
    now = _run(ta, tb, ch, now, 40)
    assert tb.plc_fallthrough_total >= 1
    expiry_nacks = [t for t, seqs in ch.nack_log
                    if doomed["seq"] in seqs]
    assert expiry_nacks, "the loss was never NACKed at all"
    # every NACK naming the doomed seq predates the deadline
    assert max(expiry_nacks) <= expiry_nacks[0] + cfg.deadline_budget_s
    post = [seqs for t, seqs in ch.nack_log
            if t > expiry_nacks[0] + cfg.deadline_budget_s]
    assert all(doomed["seq"] not in seqs for seqs in post)


# ------------------------------------------------- failover adjuncts

def test_placer_bridge_axis_evacuate_and_adopt():
    p = ConferencePlacer(n_shards=2)
    p.enable_bridges(2)
    assert p.place_bridge(1, 4) == 0       # least loaded
    assert p.place_bridge(2, 4) == 1
    assert p.place_bridge(1, 4) == 0       # sticky re-placement
    # bridge 1 dies: its conferences are un-homed, then the failover
    # plane adopts each explicitly as its adoption commits
    orphans = p.evacuate_bridge(1)
    assert orphans == [2] and p.bridge_of(2) is None
    p.adopt_bridge(2, 0, 4)
    assert p.bridge_of(2) == 0
    # new placements avoid a dead peer when asked
    assert p.place_bridge(3, 4, avoid=(1,)) == 0


def test_sliced_slo_bridge_label_axis():
    reg = MetricsRegistry()
    slo = SloEngine(reg)
    slo.register_metrics(reg)
    good = {"0": 1000.0, "1": 1000.0}
    bad = {"0": 0.0, "1": 0.0}
    slo.add_sliced(SlicedSloSpec(
        name="bridge_media", objective=0.999, label="bridge",
        reader=lambda: ((k, good[k], bad[k]) for k in good)))
    for _ in range(3):
        good["0"] += 100.0
        good["1"] += 100.0
        slo.on_tick()
    scrape = reg.render()
    assert 'bridge="0"' in scrape and 'bridge="1"' in scrape
    # bridge 1 starts burning its media budget: only ITS slice alerts
    for _ in range(60):
        good["0"] += 100.0
        bad["1"] += 50.0
        slo.on_tick()
    assert slo.slice_state("bridge_media", "0") == "ok"
    assert slo.slice_state("bridge_media", "1") != "ok"


# ------------------------------------------- journey tracing plumbing

def test_trunk_stamps_and_delivers_trace():
    """The trunk's origin hook (latched from the loop on attach)
    stamps hop-0 traces on every relayed frame; the receiving trunk
    hands the decoded trace to `deliver` alongside the inner bytes."""
    ta, tb, ch = _trunk_pair()
    ta.bridge_id = 3
    ta._journey_origin = lambda: (0xABC, 123.0)
    got = []
    tb.deliver = lambda conf, inner, trace: got.append(
        (conf, inner, trace))
    ta.cascade_conference(7)
    now = _run(ta, tb, ch, 0.0, 3)
    assert ta.relay_media(7, _inner(9), now=now)
    now = _run(ta, tb, ch, now, 2)
    conf, inner, trace = got[-1]
    assert (conf, inner) == (7, _inner(9))
    assert trace is not None
    assert trace.bridge_id == 3 and trace.hop == 0
    assert trace.trace_id == 0xABC and trace.t0 == 123.0


class _StubLoop:
    """Just enough loop for BridgeSupervisor: a registry with a
    capacity, plus the journey-origin surface `_journey_inflight`
    reads (trace id + pipelined dispatch origins)."""

    def __init__(self):
        self.registry = type("_R", (), {"capacity": 4})()
        self.trace_id = 40
        self._inflight = [(None, None, (41, 0.0), 0)]
        self._rx_inflight = [{"origin": (42, 0.0)}]


class _StubBridge:
    def __init__(self):
        self.loop = _StubLoop()
        self.port = 0
        self._bcast_speakers = {}
        self._trunks = {}

    def _sid_of_ssrc(self, ssrc):
        return None

    def attach_trunk(self, trunk, conf, speakers=None):
        self._trunks[int(conf)] = trunk


def _stub_cascade_sup(slo=None):
    from libjitsi_tpu.service.supervisor import (CascadeSupervisor,
                                                 SupervisorConfig)
    tr = CascadeTrunk(KEY_AB, KEY_BA, TrunkConfig(), seed=5)
    tr._send = lambda data: None           # no socket, no peer
    sup = CascadeSupervisor(_StubBridge(), tr,
                            SupervisorConfig(deadline_ms=1000.0),
                            bridge_id=1, peer_bridge_id=0, slo=slo)
    return sup, tr


def test_trunk_down_conviction_captures_failover_postmortem():
    """Trunk-down conviction writes a `trunk_failover` post-mortem —
    {trigger, event, dump} like quarantine/shed/recover — whose event
    names the in-flight journey set at the moment of failure."""
    sup, tr = _stub_cascade_sup()
    try:
        tr.connect("127.0.0.1", 1, now=0.0)
        now = 0.0
        for _ in range(400):
            now += 0.05
            tr.pump(now)                   # heartbeats never answered
            if tr.state == "down":
                break
        assert tr.state == "down"
        pms = [p for p in sup.postmortems
               if p["trigger"] == "trunk_failover"]
        assert len(pms) == 1
        pm = pms[0]
        assert pm["event"]["kind"] == "trunk_failover"
        assert pm["event"]["peer"] == 0
        # the loop's live trace + both pipelined dispatch origins
        assert pm["event"]["inflight"] == [40, 41, 42]
        assert pm["dump"]
        assert tr.heartbeat_misses_total > 0
    finally:
        tr.close()


def test_adoption_commit_captures_failover_postmortem():
    """The second half of the failover story: every orphan adoption
    COMMIT appends its own `trunk_failover` post-mortem carrying the
    `orphan_adopted` event and the adopted stream's flight dump."""
    sup, tr = _stub_cascade_sup()
    try:
        tr.cascade_conference(7)
        sup._conf_outstanding[7] = 1
        sup._adopt_done({"conf": 7, "m": {"ssrc": 0x111}, "n": 1,
                         "attempts": 0, "promote": True}, sid=3)
        pms = [p for p in sup.postmortems
               if p["trigger"] == "trunk_failover"]
        assert len(pms) == 1
        assert pms[0]["sid"] == 3
        assert pms[0]["event"]["kind"] == "orphan_adopted"
        assert pms[0]["event"]["ssrc"] == 0x111
        assert sup.orphans_adopted == 1
    finally:
        tr.close()


def test_hop_slo_burn_gates_admission():
    """`SlicedSloSpec(label="hop")` over the hop-labeled journey
    children: a hop whose tail blows the trunk deadline budget drives
    its slice to fast_burn, and `admission_decision` refuses joins
    with the typed `hop_burn` — per-hop, like shard_burn."""
    reg = MetricsRegistry()
    slo = SloEngine(reg)
    sup, tr = _stub_cascade_sup(slo=slo)
    try:
        assert any(s.name == "hop_journey" and s.label == "hop"
                   for s in slo.sliced)
        from libjitsi_tpu.io.loop import JOURNEY_BUCKETS
        vec = reg.histogram_vec("packet_journey_seconds",
                                JOURNEY_BUCKETS, "hop", exemplars=True)
        sup._journey_vec = vec
        assert sup.admission_decision() == (True, "ok")
        h = vec.labels("b0-b1")
        for _ in range(80):                # every journey past budget
            h.observe(1.0)
            slo.on_tick()
        assert "b0-b1" in slo.burning_slices("hop_journey")
        assert sup.admission_decision() == (False, "hop_burn")
    finally:
        tr.close()


def test_trunk_metrics_follow_replaced_trunk_instance():
    """Failover-recovery regression (the stale-trunk twin of the
    stale-array bug): metrics registered with `owner=` must resolve
    through the owner's CURRENT `.trunk` at scrape time — recovery
    constructs a fresh trunk (sockets don't survive a crash) and the
    scrape has to follow it, not stay frozen on the dead instance."""
    import types

    reg = MetricsRegistry()
    t1 = CascadeTrunk(KEY_AB, KEY_BA, TrunkConfig(), seed=11)
    t2 = CascadeTrunk(KEY_AB, KEY_BA, TrunkConfig(), seed=12)
    try:
        owner = types.SimpleNamespace(trunk=t1)
        t1.register_metrics(reg, owner=owner)
        t1.heartbeats_total = 5
        t1.state = "up"
        text = reg.render()
        assert "libjitsi_tpu_trunk_heartbeats_total 5" in text
        # recovery: a whole new trunk object under the same owner
        t2.heartbeats_total = 9
        t2.state = "down"
        owner.trunk = t2
        text = reg.render()
        assert "libjitsi_tpu_trunk_heartbeats_total 9" in text, \
            "scrape kept reading the dead pre-failover trunk"
        assert "libjitsi_tpu_trunk_heartbeats_total 5" not in text
    finally:
        t1.close()
        t2.close()
