"""AudioMediaStream / VideoMediaStream typed API facades."""

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.rtp import ext as rtp_ext
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.core.packet import PacketBatch


@pytest.fixture()
def svc():
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    return libjitsi_tpu.media_service()


def make_audio_pair(svc):
    a = svc.create_media_stream("audio", local_ssrc=0xA1)
    b = svc.create_media_stream("audio", local_ssrc=0xB1)
    ans = b.sdes.create_answer(a.sdes.create_offer())
    a.sdes.accept_answer(ans)
    a.set_remote_ssrc(b.local_ssrc)
    b.set_remote_ssrc(a.local_ssrc)
    a.start()
    b.start()
    return a, b


def test_audio_stream_dtmf_roundtrip(svc):
    a, b = make_audio_pair(svc)
    events = []
    b.add_dtmf_listener(lambda sid, ev: events.append(ev))
    a.start_sending_dtmf("7")
    wire = a.send([b"audio-while-tone"])
    a.stop_sending_dtmf()
    dec, ok = b.receive(wire)
    # the event packet is consumed by the DTMF engine (not media)
    assert not ok.any()
    assert events and events[0].event == 7


@pytest.mark.slow
def test_audio_stream_levels(svc):
    a, b = make_audio_pair(svc)
    levels = np.full(1024, 127, np.uint8)
    levels[a.sid] = 33
    a.set_level_source(lambda sids: levels[sids])
    heard = []
    b.add_audio_level_listener(lambda sids, lv: heard.append(lv))
    dec, ok = b.receive(a.send([b"frame"]))
    assert ok.all()
    assert b.last_received_level == 33
    assert heard and heard[0][0] == 33


def test_video_stream_keyframe_and_layers(svc):
    v = svc.create_media_stream("video", local_ssrc=0x7)
    v.set_remote_ssrc(0x9)
    pli = rtcp.parse_compound(v.request_keyframe())[0]
    assert isinstance(pli, rtcp.Pli)
    assert pli.media_ssrc == 0x9
    fir = rtcp.parse_compound(v.request_keyframe(use_fir=True))[0]
    assert isinstance(fir, rtcp.Fir)
    assert fir.entries[0][0] == 0x9
    fir2 = rtcp.parse_compound(v.request_keyframe(use_fir=True))[0]
    assert fir2.entries[0][1] == fir.entries[0][1] + 1  # seq advances
    v.set_simulcast_layers([0x10, 0x20, 0x30])
    assert v.simulcast.layer_of[0x20] == 1
