"""Observability plane: Histogram bucket semantics, the exposition
validator, TimingRing reentrancy, label escaping, callable array
sources (stale-array regression), PipelineTracer ledgers, the flight
recorder, and the HTTP server — plus a slow soak twin of
scripts/obs_smoke.py.
"""

import json
import types
import urllib.request

import numpy as np
import pytest

from libjitsi_tpu.service.obs_server import ObservabilityServer
from libjitsi_tpu.service.supervisor import (BridgeSupervisor,
                                             SupervisorConfig)
from libjitsi_tpu.utils.flight import FlightRecorder
from libjitsi_tpu.utils.metrics import (Histogram, MetricsRegistry,
                                        TimingRing, count_exemplars,
                                        escape_label_value,
                                        exponential_buckets,
                                        validate_exposition)
from libjitsi_tpu.utils.tracing import PipelineTracer


# ------------------------------------------------------------ histogram

def test_histogram_bucket_boundaries_are_inclusive():
    h = Histogram((1.0, 2.0, 5.0))
    for v in (0.5, 1.0, 1.5, 2.0, 5.0, 99.0):
        h.observe(v)
    # le semantics: 1.0 lands in the le="1" bucket, 5.0 in le="5"
    assert h.bucket_counts.tolist() == [2, 2, 1, 1]
    assert h.cumulative().tolist() == [2, 4, 5, 6]
    assert h.count == 6
    assert h.sum == pytest.approx(0.5 + 1.0 + 1.5 + 2.0 + 5.0 + 99.0)


def test_histogram_vectorized_fill_matches_scalar_loop():
    rng = np.random.default_rng(7)
    vals = rng.exponential(0.05, size=2000)
    buckets = exponential_buckets(0.001, 2.0, 10)
    ha, hb = Histogram(buckets), Histogram(buckets)
    ha.observe_array(vals)
    for v in vals:
        hb.observe(float(v))
    assert ha.bucket_counts.tolist() == hb.bucket_counts.tolist()
    assert ha.count == hb.count == 2000
    assert ha.sum == pytest.approx(hb.sum)


def test_histogram_rejects_empty_and_infinite_buckets():
    with pytest.raises(ValueError):
        Histogram(())
    with pytest.raises(ValueError):
        Histogram((1.0, float("inf")))


def test_histogram_render_is_cumulative_with_inf_bucket():
    m = MetricsRegistry()
    h = m.histogram("pkt_bytes", (100, 200), help_="sizes")
    h.observe_array(np.array([50.0, 150.0, 150.0, 999.0]))
    text = m.render()
    assert "# TYPE libjitsi_tpu_pkt_bytes histogram" in text
    assert 'libjitsi_tpu_pkt_bytes_bucket{le="100"} 1' in text
    assert 'libjitsi_tpu_pkt_bytes_bucket{le="200"} 3' in text
    assert 'libjitsi_tpu_pkt_bytes_bucket{le="+Inf"} 4' in text
    assert "libjitsi_tpu_pkt_bytes_count 4" in text
    assert validate_exposition(text) == []


def test_registry_histogram_factory_is_create_or_get():
    m = MetricsRegistry()
    a = m.histogram("x", (1, 2))
    b = m.histogram("x", (5, 6))          # existing wins; buckets kept
    assert a is b
    assert a.uppers.tolist() == [1.0, 2.0]


# ------------------------------------------------------------ exemplars

def test_histogram_exemplar_slots_last_wins_and_tail_signal():
    h = Histogram((0.01, 0.1), exemplars=True)
    assert h.observe(0.005, exemplar={"trace_id": "1"}) is False
    assert h.observe(0.007, exemplar={"trace_id": "2"}) is False
    assert h.observe(5.0, exemplar={"trace_id": "3"}) is True  # +Inf
    assert h.exemplars[0][0] == {"trace_id": "2"}   # last wins
    assert h.exemplars[0][1] == pytest.approx(0.007)
    assert h.exemplars[-1][0] == {"trace_id": "3"}
    assert h.exemplars[1] is None                   # untouched slot
    # observe_same spreads n observations, one exemplar
    assert h.observe_same(0.05, 4, exemplar={"trace_id": "4"}) is False
    assert h.exemplars[1][0] == {"trace_id": "4"}


def test_exemplars_render_only_on_openmetrics():
    m = MetricsRegistry()
    h = m.histogram("journey_seconds", (0.01, 0.1), exemplars=True)
    h.observe(0.005, exemplar={"trace_id": "42"})
    plain = m.render()
    om = m.render(openmetrics=True)
    assert count_exemplars(plain) == 0
    assert count_exemplars(om) == 1
    assert '# {trace_id="42"} 0.005' in om
    assert om.rstrip().endswith("# EOF")
    assert validate_exposition(plain) == []
    assert validate_exposition(om, openmetrics=True) == []


@pytest.mark.parametrize("breakage,needle", [
    # exemplar allowed on _bucket lines only
    ('# TYPE h histogram\nh_bucket{le="1"} 1\nh_bucket{le="+Inf"} 1\n'
     'h_sum 1\nh_count 1 # {t="1"} 0.5\n# EOF\n', "bucket"),
    # exemplar label set over the 128-rune OpenMetrics cap
    ('# TYPE h histogram\nh_bucket{le="1"} 1 # {t="' + "x" * 140
     + '"} 0.5\nh_bucket{le="+Inf"} 1\nh_sum 1\nh_count 1\n# EOF\n',
     "128"),
    # exemplar value must be numeric
    ('# TYPE h histogram\nh_bucket{le="1"} 1 # {t="1"} oops\n'
     'h_bucket{le="+Inf"} 1\nh_sum 1\nh_count 1\n# EOF\n', "numeric"),
    # OpenMetrics requires the EOF terminator, last
    ('# TYPE g gauge\ng 1\n', "# EOF"),
])
def test_openmetrics_validator_rejects_seeded_breakage(breakage, needle):
    errors = validate_exposition(breakage, openmetrics=True)
    assert errors and any(needle in e for e in errors), errors


def test_exemplar_in_plain_exposition_is_a_violation():
    text = ('# TYPE h histogram\nh_bucket{le="1"} 1 # {t="1"} 0.5\n'
            'h_bucket{le="+Inf"} 1\nh_sum 1\nh_count 1\n')
    errors = validate_exposition(text)     # 0.0.4 format: no exemplars
    assert errors and any("exemplar" in e.lower() for e in errors)


# ------------------------------------------------------------ validator

def test_validator_accepts_full_registry_render():
    m = MetricsRegistry()
    m.register_array("rx", np.array([1, 2, 3]), help_="per stream",
                     kind="counter")
    m.register_scalar("up", lambda: 1)
    m.histogram("sizes", (10, 100)).observe_array(
        np.array([5.0, 50.0, 500.0]))
    ring = m.timing("stage_ingress")
    for v in (0.001, 0.002, 0.003):
        ring.record(v)
    assert validate_exposition(m.render()) == []


@pytest.mark.parametrize("text,needle", [
    # buckets must be cumulative
    ('# TYPE h histogram\nh_bucket{le="1"} 5\nh_bucket{le="2"} 3\n'
     'h_bucket{le="+Inf"} 5\nh_sum 1\nh_count 5\n', "cumulative"),
    # +Inf bucket required
    ('# TYPE h histogram\nh_bucket{le="1"} 2\nh_sum 1\nh_count 2\n',
     '+Inf'),
    # +Inf must equal _count
    ('# TYPE h histogram\nh_bucket{le="1"} 1\nh_bucket{le="+Inf"} 2\n'
     'h_sum 1\nh_count 3\n', "_count"),
    # _sum required
    ('# TYPE h histogram\nh_bucket{le="1"} 1\nh_bucket{le="+Inf"} 1\n'
     'h_count 1\n', "_sum"),
    # every family typed exactly once
    ('# TYPE g gauge\n# TYPE g gauge\ng 1\n', "duplicate"),
    # samples without a TYPE line
    ('untyped_metric 4\n', "no # TYPE"),
    # summary quantiles must be numeric in [0, 1]
    ('# TYPE s summary\ns{quantile="p99"} 1\ns_sum 1\ns_count 1\n',
     "quantile"),
])
def test_validator_rejects_seeded_breakage(text, needle):
    errors = validate_exposition(text)
    assert errors and any(needle in e for e in errors), errors


# ------------------------------------------------- timing-ring reentrancy

def test_timing_ring_nested_with_blocks_record_both():
    ring = TimingRing()
    with ring:
        with ring:                       # reentrant: inner must not
            pass                         # clobber the outer's t0
    assert ring.count == 2
    durations = ring._buf[:2]
    assert durations[1] >= durations[0]  # outer (recorded 2nd) >= inner


def test_timing_ring_overlapping_span_tokens():
    ring = TimingRing()
    a = ring.span()
    b = ring.span()                      # overlapping, non-LIFO
    a.stop()
    b.stop()
    assert ring.count == 2
    assert a.stop() == a.seconds         # idempotent stop


# -------------------------------------------------------------- escaping

def test_hostile_label_values_are_escaped():
    hostile = 'pwn" } 1\nfake_metric{x="y'
    esc = escape_label_value(hostile)
    assert "\n" not in esc and '"' not in esc.replace('\\"', "")
    m = MetricsRegistry()
    m.register_array("rx", np.array([7]), by="stream")
    m.set_stream_name(0, hostile)
    text = m.render()
    assert hostile not in text
    assert validate_exposition(text) == []
    # the escaped value round-trips through the parser
    from libjitsi_tpu.utils.metrics import parse_exposition
    _types, samples, errors = parse_exposition(text)
    assert not errors
    byname = {n: lab for n, lab, _v in samples}
    assert byname["libjitsi_tpu_rx"]["name"] == hostile


def test_hostile_help_text_is_escaped():
    m = MetricsRegistry()
    m.register_scalar("up", lambda: 1,
                      help_="line1\nline2 \\ backslash")
    text = m.render()
    assert "# HELP libjitsi_tpu_up line1\\nline2 \\\\ backslash" in text
    assert validate_exposition(text) == []


# ------------------------------------- callable sources (stale arrays)

CAP = 8


class _DummyLoop:
    def __init__(self):
        self.registry = types.SimpleNamespace(capacity=CAP)
        self.recv_window_ms = 1
        self.inbound_drop = np.zeros(CAP, dtype=bool)
        self.inbound_dropped = np.zeros(CAP, dtype=np.int64)
        self.inbound_dropped_total = 0


class _DummyBridge:
    def __init__(self):
        self.loop = _DummyLoop()
        self.degraded = False
        self._ssrc_of = {}
        self.rx_table = types.SimpleNamespace(
            auth_fail=np.zeros(CAP, dtype=np.int64),
            replay_reject=np.zeros(CAP, dtype=np.int64))
        self.speaker = types.SimpleNamespace(dominant=0)

    def tick(self, now=None):
        return {"rx": 0}


def test_register_array_accepts_callable_source():
    m = MetricsRegistry()
    holder = {"arr": np.array([1, 2])}
    m.register_array("live", lambda: holder["arr"], kind="counter")
    assert 'libjitsi_tpu_live{stream="0"} 1' in m.render()
    holder["arr"] = np.array([9, 9])     # rebind, not mutate
    assert 'libjitsi_tpu_live{stream="0"} 9' in m.render()


def test_supervisor_scrape_survives_table_rebind():
    """Chaos-style kill/restore regression: the exporter must follow
    the supervisor's CURRENT bridge objects, not the arrays captured at
    registration time (the stale-array bug)."""
    reg = MetricsRegistry()
    bridge = _DummyBridge()
    sup = BridgeSupervisor(bridge, SupervisorConfig(deadline_ms=1000.0),
                           metrics=reg)
    bridge.rx_table.auth_fail[3] = 2
    assert 'libjitsi_tpu_srtp_auth_fail{stream="3"} 2' in reg.render()
    # "restore": a whole new table object, as recover() produces
    bridge.rx_table = types.SimpleNamespace(
        auth_fail=np.zeros(CAP, dtype=np.int64),
        replay_reject=np.zeros(CAP, dtype=np.int64))
    bridge.rx_table.auth_fail[3] = 41
    text = reg.render()
    assert 'libjitsi_tpu_srtp_auth_fail{stream="3"} 41' in text, \
        "exporter kept reading the pre-restore array"
    assert sup is not None


# --------------------------------------------------------------- tracer

def test_tracer_feeds_rings_and_ledger():
    m = MetricsRegistry()
    tr = PipelineTracer(m, annotate=False)
    with tr.span("ingress"):
        with tr.span("recovery"):        # nested spans both record
            pass
    assert m.timings["stage_ingress"].count == 1
    assert m.timings["stage_recovery"].count == 1
    led = tr.take_ledger()
    assert set(led) == {"ingress", "recovery"}
    assert led["ingress"] >= led["recovery"] >= 0.0
    assert tr.ledger() == {}             # drained
    assert tr.last_ledger == led
    stage, secs = PipelineTracer.dominant(led)
    assert stage == "ingress" and secs == led["ingress"]
    assert PipelineTracer.dominant({}) == (None, 0.0)


# ------------------------------------------------------ flight recorder

def test_flight_recorder_rings_are_bounded_and_ordered():
    fr = FlightRecorder(per_stream=4, global_events=3)
    for i in range(10):
        fr.record("x", sid=1, tick=i)
        fr.record("g", tick=i)
    d = fr.dump(1)
    assert len(d["events"]) == 4
    assert [e["tick"] for e in d["events"]] == [6, 7, 8, 9]
    assert len(d["global"]) == 3
    seqs = [e["seq"] for e in d["events"]]
    assert seqs == sorted(seqs)          # merged-timeline ordering
    assert fr.events_recorded == 20
    assert fr.streams() == [1]
    fr.clear(1)
    assert fr.dump(1)["events"] == []


def test_flight_recorder_header_sampling_is_capped_spread():
    """Default sampling is a deterministic stride reservoir: capped at
    max_headers rows, spread over the burst, ALWAYS including the last
    row (the old first-N sampling was blind to burst tails)."""
    fr = FlightRecorder(max_headers=3)
    sids = [5] * 10 + [6]
    seqs = list(range(100, 110)) + [777]
    lens = [60] * 11
    fr.record_headers(sids, seqs, lens, tick=2, trace=9)
    ev5 = fr.dump(5)["events"][0]
    assert ev5["kind"] == "hdr" and ev5["n"] == 3
    assert ev5["total"] == 10 and ev5["mode"] == "spread"
    assert ev5["trace"] == 9
    assert ev5["headers"][0] == [100, 60]     # first row kept
    assert ev5["headers"][-1] == [109, 60]    # last row ALWAYS kept
    assert fr.dump(6)["events"][0]["headers"] == [[777, 60]]


def test_flight_recorder_burst_tail_regression():
    """A 1k-packet burst must leave at least one header from the burst
    TAIL on record — both in spread mode (stride reservoir includes the
    final row) and, for a priority-marked stream, the full tail."""
    fr = FlightRecorder(max_headers=16)
    n = 1000
    sids = [3] * n
    seqs = list(range(n))
    lens = [60] * n
    fr.record_headers(sids, seqs, lens, tick=0)
    ev = fr.dump(3)["events"][-1]
    tail_seqs = set(range(n - 16, n))
    assert any(h[0] in tail_seqs for h in ev["headers"]), \
        "spread sample kept nothing from the burst tail"
    assert ev["headers"][-1][0] == n - 1

    # priority mark (set by a journey-tail overflow or a NACK/RTX/FEC
    # event) biases the NEXT sample to the whole tail, then clears
    fr.mark_priority(3)
    fr.record_headers(sids, seqs, lens, tick=1)
    ev = fr.dump(3)["events"][-1]
    assert ev["mode"] == "tail"
    assert [h[0] for h in ev["headers"]] == list(range(n - 16, n))
    fr.record_headers(sids, seqs, lens, tick=2)   # mark consumed
    assert fr.dump(3)["events"][-1]["mode"] == "spread"


def test_flight_recorder_priority_kinds_mark_stream():
    """NACK/RTX/FEC events auto-mark their stream: the next header
    sample keeps the burst tail the event is about."""
    fr = FlightRecorder(max_headers=2)
    fr.record("rtx_served", sid=7, tick=0, seq=55)
    fr.record_headers([7] * 5, [10, 11, 12, 13, 14], [60] * 5, tick=1)
    ev = fr.dump(7)["events"][-1]
    assert ev["mode"] == "tail"
    assert [h[0] for h in ev["headers"]] == [13, 14]


def test_flight_dump_is_json_serializable():
    fr = FlightRecorder()
    fr.record("q", sid=np.int64(3), tick=np.int32(1),
              n=np.int64(5))
    json.dumps(fr.dump(3))               # plain dicts by construction


# ------------------------------------------------------------ http server

def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode("utf-8")


def test_obs_server_serves_metrics_health_and_debug():
    m = MetricsRegistry()
    m.register_scalar("up", lambda: 1)
    fr = FlightRecorder()
    fr.record("hdr", sid=4, tick=0, n=1, headers=[[10, 60]])
    sup = types.SimpleNamespace(
        health=lambda: {"state": "healthy"}, flight=fr, postmortems=[])
    with ObservabilityServer(metrics=m, supervisor=sup) as srv:
        code, text = _get(srv.port, "/metrics")
        assert code == 200 and "libjitsi_tpu_up 1" in text
        assert validate_exposition(text) == []
        code, body = _get(srv.port, "/healthz")
        assert code == 200 and json.loads(body)["ok"]
        code, body = _get(srv.port, "/debug/streams")
        assert json.loads(body)["streams"] == [4]
        code, body = _get(srv.port, "/debug/streams/4")
        assert code == 200
        assert json.loads(body)["events"][0]["kind"] == "hdr"
        code, body = _get(srv.port, "/debug/postmortems")
        assert code == 200 and json.loads(body) == []


def test_obs_server_negotiates_openmetrics_and_serves_slo():
    from libjitsi_tpu.utils.slo import SloEngine, SloSpec

    m = MetricsRegistry()
    h = m.histogram("journey_seconds", (0.01, 0.1), exemplars=True)
    h.observe(0.005, exemplar={"trace_id": "7"})
    state = {"bad": 1.0, "total": 100.0}
    m.register_scalar("bad_things", lambda: state["bad"],
                      kind="counter")
    m.register_scalar("all_things", lambda: state["total"],
                      kind="counter")
    slo = SloEngine(m, [SloSpec("r", objective=0.99,
                                bad_metric="bad_things",
                                total_metric="all_things")])
    slo.on_tick()
    sup = types.SimpleNamespace(
        health=lambda: {"state": "healthy"}, flight=None,
        postmortems=[])
    with ObservabilityServer(metrics=m, supervisor=sup,
                             slo=slo) as srv:
        # plain scrape: 0.0.4 content type, no exemplars
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/metrics")
        with urllib.request.urlopen(req, timeout=5) as r:
            plain, ctype = r.read().decode("utf-8"), \
                r.headers.get("Content-Type", "")
        assert "text/plain" in ctype
        assert count_exemplars(plain) == 0
        # Accept negotiation flips to OpenMetrics: exemplars + # EOF
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/metrics",
            headers={"Accept":
                     "application/openmetrics-text; version=1.0.0"})
        with urllib.request.urlopen(req, timeout=5) as r:
            om, ctype = r.read().decode("utf-8"), \
                r.headers.get("Content-Type", "")
        assert "application/openmetrics-text" in ctype
        assert validate_exposition(om, openmetrics=True) == []
        assert count_exemplars(om) == 1 and 'trace_id="7"' in om
        # /debug/slo mirrors SloEngine.status()
        code, body = _get(srv.port, "/debug/slo")
        doc = json.loads(body)
        assert code == 200 and doc["ticks"] == 1
        assert doc["slos"][0]["name"] == "r"


def test_obs_server_debug_device_and_slo_attribution():
    """/debug/device serves per-device memory stats; /debug/slo picks
    up the supervisor's host/device attribution when it offers one."""
    from libjitsi_tpu.utils.slo import SloEngine, SloSpec

    m = MetricsRegistry()
    slo = SloEngine(m, [SloSpec("r", objective=0.99,
                                bad_metric="bad_things",
                                total_metric="all_things")])
    m.register_scalar("bad_things", lambda: 0)
    m.register_scalar("all_things", lambda: 1)
    slo.on_tick()
    phases = {"host_python": 0.02, "device_compute": 0.001}
    sup = types.SimpleNamespace(
        health=lambda: {"state": "healthy"}, flight=None,
        postmortems=[],
        phase_attribution=lambda: {
            "bound": "host", "phase": "host_python",
            "phase_share": 0.95, "phases": phases})
    with ObservabilityServer(metrics=m, supervisor=sup,
                             slo=slo) as srv:
        code, body = _get(srv.port, "/debug/device")
        doc = json.loads(body)
        assert code == 200 and doc["devices"]
        assert "device" in doc["devices"][0]
        assert "bytes_in_use" in doc["devices"][0]
        code, body = _get(srv.port, "/debug/slo")
        attr = json.loads(body)["attribution"]
        assert code == 200 and attr["bound"] == "host"
        assert attr["phases"]["host_python"] == 0.02


def test_obs_server_slo_404_when_absent():
    sup = types.SimpleNamespace(
        health=lambda: {"state": "healthy"}, flight=None,
        postmortems=[])
    with ObservabilityServer(supervisor=sup) as srv:
        try:
            code, body = _get(srv.port, "/debug/slo")
        except urllib.error.HTTPError as e:
            code, body = e.code, e.read().decode("utf-8")
        assert code == 404 and "no slo engine" in body


def test_obs_server_healthz_503_when_stalled_and_404s():
    sup = types.SimpleNamespace(
        health=lambda: {"state": "stalled"}, flight=None,
        postmortems=[])
    with ObservabilityServer(supervisor=sup) as srv:
        try:
            code, body = _get(srv.port, "/healthz")
        except urllib.error.HTTPError as e:
            code, body = e.code, e.read().decode("utf-8")
        assert code == 503 and not json.loads(body)["ok"]
        try:
            code, _ = _get(srv.port, "/debug/streams/abc")
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 404
        try:
            code, _ = _get(srv.port, "/nope")
        except urllib.error.HTTPError as e:
            code = e.code
        assert code == 404


# --------------------------------------------------- process families

def test_process_families_render_and_validator_bounds():
    """The un-namespaced process families every /metrics response
    carries: a well-formed pair validates; a zero/negative start time
    (the classic uninitialized-clock bug Prometheus restart detection
    would silently swallow) and a negative scrape duration are format
    violations."""
    from libjitsi_tpu.utils.metrics import process_families_text

    good = process_families_text(0.002)
    assert validate_exposition(good) == []
    assert "# TYPE process_start_time_seconds gauge" in good
    assert "# TYPE scrape_duration_seconds gauge" in good
    # default start stamp is this process's import time: a real epoch
    line = [ln for ln in good.splitlines()
            if ln.startswith("process_start_time_seconds ")][0]
    assert float(line.split()[1]) > 1e9

    bad_start = process_families_text(0.002, start_time_s=0.0)
    errors = validate_exposition(bad_start)
    assert any("positive unix time" in e for e in errors)

    bad_dur = process_families_text(-0.5)
    errors = validate_exposition(bad_dur)
    assert any("scrape_duration_seconds" in e and ">= 0" in e
               for e in errors)


# ------------------------------------------------- histogram vec render

def test_histogram_vec_zero_observation_child_renders_valid():
    """A labeled child created but never observed (a hop that carried
    no traffic yet) must still render a complete, validator-clean
    bucket/sum/count triple of zeros under the family's single # TYPE
    line — not a half-family the scraper chokes on."""
    m = MetricsRegistry()
    vec = m.histogram_vec("hop_seconds", (0.01, 0.1), "hop")
    vec.labels("local").observe(0.005)
    vec.labels("b1-b2")                  # created, zero observations
    text = m.render()
    assert validate_exposition(text) == []
    assert text.count("# TYPE libjitsi_tpu_hop_seconds histogram") == 1
    assert ('libjitsi_tpu_hop_seconds_bucket{hop="b1-b2",le="+Inf"} 0'
            in text)
    assert 'libjitsi_tpu_hop_seconds_count{hop="b1-b2"} 0' in text
    assert 'libjitsi_tpu_hop_seconds_count{hop="local"} 1' in text
    # OpenMetrics rendering of the empty child is also clean
    assert validate_exposition(m.render(openmetrics=True),
                               openmetrics=True) == []


# -------------------------------------------------- offline fleet merge

def test_trace_report_merges_saved_bridge_scrapes(tmp_path):
    """scripts/trace_report.py --merge-bridges over SAVED exposition
    files (the offline twin of /debug/fleet): a trace id whose journey
    exemplars appear on two bridges' scrapes is stitched; a bridge-local
    id is not."""
    import sys
    sys.path.insert(0, "scripts")
    import trace_report

    def scrape(hop, observes):
        m = MetricsRegistry()
        vec = m.histogram_vec("packet_journey_seconds", (0.01, 0.1),
                              "hop", exemplars=True)
        for tid, seconds in observes:
            vec.labels(hop).observe(seconds,
                                    exemplar={"trace_id": tid})
        return m.render(openmetrics=True)

    a, b = tmp_path / "a.om", tmp_path / "b.om"
    # distinct buckets: exemplar slots are per-bucket, last wins
    a.write_text(scrape("local", [("77", 0.004), ("88", 0.05)]))
    b.write_text(scrape("b1-b2", [("77", 0.004)]))
    doc = trace_report.merge_bridges([str(a), str(b)])
    assert doc["errors"] == {}
    assert set(doc["bridges"]) == {"a.om", "b.om"}
    assert doc["bridges"]["a.om"]["exemplars"] == 2
    assert doc["stitched_trace_ids"] == ["77"]
    by_id = {j["trace_id"]: j for j in doc["journeys"]}
    assert by_id["77"]["stitched"]
    assert {s["hop"] for s in by_id["77"]["spans"]} \
        == {"local", "b1-b2"}
    assert not by_id["88"]["stitched"]
    text = trace_report.format_fleet(doc)
    assert "stitched journeys (seen on >1 bridge): 1" in text
    # the CLI exit contract: merged scrapes with no errors -> 0
    assert trace_report.main(["--merge-bridges", str(a), str(b)]) == 0


# ------------------------------------------------------------ dashboards

def test_checked_in_dashboards_are_fresh():
    """Round-trip: regenerating the recording rules + dashboard from
    the live registry must reproduce the checked-in files byte-for-byte
    (a metrics change that shifts the scrape surface fails here until
    scripts/gen_dashboards.py is re-run)."""
    import os
    import sys
    sys.path.insert(0, "scripts")
    import gen_dashboards

    texts = gen_dashboards.generate()
    assert set(texts) == set(gen_dashboards.FILES)
    for name, text in texts.items():
        path = os.path.join(gen_dashboards.OUT_DIR, name)
        assert os.path.exists(path), f"dashboards/{name} not checked in"
        with open(path) as fh:
            on_disk = fh.read()
        assert on_disk == text, \
            (f"dashboards/{name} is stale — "
             "re-run scripts/gen_dashboards.py")
    # every PromQL family referenced exists in the registry the
    # generator saw: burn-rate rules name each stock SLO
    rules = texts["recording_rules.yaml"]
    for slo_name in ("journey_p99", "residual_loss", "auth_fail"):
        assert f"slo: {slo_name}" in rules
    dash = json.loads(texts["bridge_dashboard.json"])
    assert dash["panels"], "dashboard generated with no panels"
    # alertmanager routing: per-SLO fast-burn routes page, slow-burn
    # routes ticket, and fast inhibits slow on the same slo label
    am = texts["alertmanager.yaml"]
    for slo_name in ("journey_p99", "residual_loss", "auth_fail"):
        assert f'- slo = "{slo_name}"' in am
    assert am.count("receiver: rtc-oncall-pager") == 3
    assert "alertname = SloFastBurn" in am
    assert "inhibit_rules:" in am and "equal: [slo]" in am


# ------------------------------------------------------------- soak twin

@pytest.mark.slow
def test_obs_smoke_soak():
    """The tier-1 smoke with 5x the ticks: histograms keep their
    invariants and the validator stays clean under sustained load."""
    import sys
    sys.path.insert(0, "scripts")
    import obs_smoke

    obs_smoke.run(ticks=200)
