import hashlib
import hmac as hmac_mod

import numpy as np

from libjitsi_tpu.kernels import sha1 as K


def _batchify(msgs, width=None):
    width = width or max((len(m) for m in msgs), default=1) or 1
    data = np.zeros((len(msgs), width), dtype=np.uint8)
    lengths = np.zeros((len(msgs),), dtype=np.int32)
    for i, m in enumerate(msgs):
        data[i, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        lengths[i] = len(m)
    return data, lengths


def test_sha1_fips_vectors():
    msgs = [b"abc", b"", b"abcdbcdecdefdefgefghfghighijhijkijkljklmklmnlmnomnopnopq"]
    data, lengths = _batchify(msgs, 64)
    out = np.asarray(K.sha1(data, lengths))
    for i, m in enumerate(msgs):
        assert bytes(out[i]) == hashlib.sha1(m).digest(), f"vector {i}"


def test_sha1_block_boundaries():
    # 55/56/57/63/64/65 bytes hit the padding-block split cases
    msgs = [b"a" * n for n in (0, 1, 55, 56, 57, 63, 64, 65, 119, 120, 128, 200)]
    data, lengths = _batchify(msgs, 256)
    out = np.asarray(K.sha1(data, lengths))
    for i, m in enumerate(msgs):
        assert bytes(out[i]) == hashlib.sha1(m).digest(), f"len {len(m)}"


def test_sha1_random_differential():
    rng = np.random.default_rng(7)
    msgs = [
        bytes(rng.integers(0, 256, size=int(rng.integers(0, 1500)), dtype=np.uint8))
        for _ in range(64)
    ]
    data, lengths = _batchify(msgs, 1504)
    out = np.asarray(K.sha1(data, lengths))
    for i, m in enumerate(msgs):
        assert bytes(out[i]) == hashlib.sha1(m).digest()


def test_hmac_rfc2202_vectors():
    # RFC 2202 test cases 1-7 for HMAC-SHA1
    cases = [
        (b"\x0b" * 20, b"Hi There"),
        (b"Jefe", b"what do ya want for nothing?"),
        (b"\xaa" * 20, b"\xdd" * 50),
        (bytes(range(1, 26)), b"\xcd" * 50),
        (b"\x0c" * 20, b"Test With Truncation"),
        (b"\xaa" * 80, b"Test Using Larger Than Block-Size Key - Hash Key First"),
        (
            b"\xaa" * 80,
            b"Test Using Larger Than Block-Size Key and Larger Than One Block-Size Data",
        ),
    ]
    mids = np.stack([K.hmac_precompute(k) for k, _ in cases])
    data, lengths = _batchify([m for _, m in cases], 128)
    out = np.asarray(K.hmac_sha1(mids, data, lengths))
    for i, (k, m) in enumerate(cases):
        expect = hmac_mod.new(k, m, hashlib.sha1).digest()
        assert bytes(out[i]) == expect, f"RFC2202 case {i + 1}"


def test_hmac_per_row_keys_random():
    rng = np.random.default_rng(11)
    keys = [bytes(rng.integers(0, 256, size=20, dtype=np.uint8)) for _ in range(32)]
    msgs = [
        bytes(rng.integers(0, 256, size=int(rng.integers(1, 1400)), dtype=np.uint8))
        for _ in range(32)
    ]
    mids = np.stack([K.hmac_precompute(k) for k in keys])
    data, lengths = _batchify(msgs, 1504)
    out = np.asarray(K.hmac_sha1(mids, data, lengths))
    for i in range(32):
        assert bytes(out[i]) == hmac_mod.new(keys[i], msgs[i], hashlib.sha1).digest()
