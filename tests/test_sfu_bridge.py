"""SfuBridge e2e: decrypt-once fan-out over real loopback UDP + NACK
retransmission from the per-leg cache."""

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.service.sfu_bridge import SfuBridge
from libjitsi_tpu.transform.header_ext import AbsSendTimeEngine
from libjitsi_tpu.transform.srtp import SrtpStreamTable


class _Endpoint:
    def __init__(self, ssrc, bridge_port):
        self.ssrc = ssrc
        self.rx_key = (bytes([ssrc & 0xFF]) * 16,
                       bytes([(ssrc + 1) & 0xFF]) * 14)
        self.tx_key = (bytes([(ssrc + 2) & 0xFF]) * 16,
                       bytes([(ssrc + 3) & 0xFF]) * 14)
        self.protect = SrtpStreamTable(capacity=1)
        self.protect.add_stream(0, *self.rx_key)
        # one rx context PER SENDER SSRC (RFC 3711: contexts are
        # per-SSRC; all legs share this receiver's session keys)
        self.open = SrtpStreamTable(capacity=4)
        self.row_of = {}
        self.engine = UdpEngine(port=0, max_batch=64)
        self.bridge_port = bridge_port
        self.seq = 500
        self.got = {}                     # seq -> payload

    def send_media(self, n=4):
        pls = [b"m-%08x-%d" % (self.ssrc, self.seq + i)
               for i in range(n)]
        b = rtp_header.build(pls, [self.seq + i for i in range(n)],
                             [0] * n, [self.ssrc] * n, [96] * n,
                             stream=[0] * n)
        self.seq += n
        self.engine.send_batch(self.protect.protect_rtp(b),
                               "127.0.0.1", self.bridge_port)

    def expect_sender(self, ssrc):
        row = len(self.row_of)
        self.row_of[ssrc] = row
        self.open.add_stream(row, *self.tx_key)

    def drain(self):
        back, _, _ = self.engine.recv_batch(timeout_ms=2)
        if back.batch_size:
            hdr0 = rtp_header.parse(back)
            back.stream[:] = [self.row_of.get(int(s), -1)
                              for s in hdr0.ssrc]
            dec, ok = self.open.unprotect_rtp(back)
            hdr = rtp_header.parse(dec)
            for i in np.nonzero(ok)[0]:
                i = int(i)
                self.got[(int(hdr.ssrc[i]), int(hdr.seq[i]))] = \
                    dec.to_bytes(i)[int(hdr.payload_off[i]):]

    def send_nack(self, media_ssrc, media_seqs):
        """SRTCP-protected NACK (the bridge drops plaintext control)."""
        blob = rtcp.build_compound([rtcp.build_nack(rtcp.Nack(
            sender_ssrc=self.ssrc, media_ssrc=media_ssrc,
            lost_seqs=list(media_seqs)))])
        from libjitsi_tpu.core.packet import PacketBatch

        b = PacketBatch.from_payloads([blob], stream=[0])
        wire = self.protect.protect_rtcp(b)
        self.engine.send_batch(wire, "127.0.0.1", self.bridge_port)


@pytest.mark.slow
def test_sfu_fanout_and_nack_over_udp():
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=8, recv_window_ms=0)
    eps = [_Endpoint(0x100 + 7 * k, sfu.port) for k in range(3)]
    sids = [sfu.add_endpoint(e.ssrc, e.rx_key, e.tx_key) for e in eps]
    for e in eps:
        for other in eps:
            if other is not e:
                e.expect_sender(other.ssrc)

    # every endpoint sends; everyone must receive the other two's media
    for rnd in range(4):
        for e in eps:
            e.send_media()
        for _ in range(20):
            sfu.tick(now=50.0 + rnd * 0.02)
        for e in eps:
            for _ in range(4):
                e.drain()
    assert sfu.forwarded > 0
    for e in eps:
        payloads = b"".join(e.got.values())
        for other in eps:
            if other is e:
                continue
            assert b"m-%08x" % other.ssrc in payloads, \
                f"{e.ssrc:#x} missing media from {other.ssrc:#x}"
        assert b"m-%08x" % e.ssrc not in payloads, "echoed own media"

    # NACK service: receiver drops a seq, asks again, gets the cached
    # per-leg copy (protected with ITS leg key)
    victim = eps[0]
    missing_seq = 501
    victim.got.clear()
    # fresh contexts for the re-delivery (replay windows already saw
    # these seqs in the live pass)
    for ssrc, row in victim.row_of.items():
        victim.open.add_stream(row, *victim.tx_key)
    victim.send_nack(eps[1].ssrc, [missing_seq])
    for _ in range(20):
        sfu.tick(now=50.5)   # within the cache's 1 s max age
    for _ in range(4):
        victim.drain()
    assert sfu.retransmitted > 0
    assert any(seq == missing_seq for _, seq in victim.got)
    # only the NACKed sender's copy was re-delivered (cache keys carry
    # the sender ssrc)
    assert all(ssrc == eps[1].ssrc for ssrc, _ in victim.got)
    # feedback drain: aggregated NACK/RR toward senders, SRTCP-protected
    sfu.emit_feedback(now=50.6)
    sfu.close()


class _BweSender(_Endpoint):
    """Endpoint whose media carries abs-send-time stamps from a
    controllable clock (lets the test shape queue delay: arrival is the
    bridge tick's `now`, send time is `ast_now`)."""

    def __init__(self, ssrc, bridge_port, ext_id=3):
        super().__init__(ssrc, bridge_port)
        self.ast_now = 0.0
        self._ast = AbsSendTimeEngine(ext_id, clock=lambda: self.ast_now)

    def send_media(self, n=4):
        pls = [b"m-%08x-%d" % (self.ssrc, self.seq + i)
               for i in range(n)]
        b = rtp_header.build(pls, [self.seq + i for i in range(n)],
                             [0] * n, [self.ssrc] * n, [96] * n,
                             stream=[0] * n)
        self.seq += n
        b, _ = self._ast.rtp_transformer.transform(b)
        self.engine.send_batch(self.protect.protect_rtp(b),
                               "127.0.0.1", self.bridge_port)

    def drain_rembs(self):
        """Unprotect bridge SRTCP feedback; return REMB bitrates."""
        out = []
        back, _, _ = self.engine.recv_batch(timeout_ms=2)
        for i in range(back.batch_size):
            back.stream[i] = 0
        if back.batch_size:
            dec, ok = self.srtcp_rx.unprotect_rtcp(back)
            for i in np.nonzero(np.asarray(ok))[0]:
                for p in rtcp.parse_compound(dec.to_bytes(int(i))):
                    if isinstance(p, rtcp.Remb):
                        out.append(p.bitrate_bps)
        return out


@pytest.mark.slow
def test_sfu_bwe_congestion_drives_remb_down_and_back_up():
    """VERDICT r2 #2: the bridge's OWN receive-side estimate (abs-send-
    time GCC over the sender->bridge leg) governs the REMB it advertises:
    a growing-queue trace cuts it, recovery raises it again."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=8, recv_window_ms=0)
    sender = _BweSender(0x700, sfu.port)
    recv = _Endpoint(0x701, sfu.port)
    sid_s = sfu.add_endpoint(sender.ssrc, sender.rx_key, sender.tx_key)
    sfu.add_endpoint(recv.ssrc, recv.rx_key, recv.tx_key)
    recv.expect_sender(sender.ssrc)
    # receiver must latch an address on the bridge (any packet does)
    recv.send_media(1)
    # sender-side SRTCP context for the bridge's feedback (protected
    # with the sender leg's tx key)
    sender.srtcp_rx = SrtpStreamTable(capacity=1)
    sender.srtcp_rx.add_stream(0, *sender.tx_key)

    rembs = []

    def run_phase(rounds, queue_of):
        for r in range(rounds):
            t = run_phase.t0 + r * 0.02
            sender.ast_now = t - queue_of(r)
            sender.send_media(4)
            for _ in range(10):
                sfu.tick(now=t)
            sfu.emit_feedback(now=t)
            got = sender.drain_rembs()
            if got:
                rembs.append(got[-1])
            recv.drain()
        run_phase.t0 += rounds * 0.02

    run_phase.t0 = 50.0
    run_phase(10, lambda r: 0.0)                  # clean network
    assert rembs, "no REMB reached the sender"
    baseline = rembs[-1]
    assert sfu.own_estimate_bps(sid_s) is not None
    run_phase(30, lambda r: r * 0.003)            # queue grows 3 ms/tick
    congested = rembs[-1]
    assert congested < baseline * 0.7, \
        f"REMB did not drop under congestion: {baseline} -> {congested}"
    run_phase(60, lambda r: 0.090)                # constant queue: drained
    recovered = rembs[-1]
    assert recovered > congested * 1.1, \
        f"REMB did not recover: {congested} -> {recovered}"
    sfu.close()


@pytest.mark.slow
def test_sfu_dtls_keyed_endpoint_e2e():
    """VERDICT r2 #5: a sender joins the SfuBridge keyed by DTLS-SRTP
    over the real UDP port (loop first-byte demux -> on_dtls), media
    sent the instant the client completes flows to a static-keyed
    receiver — any packets racing the install are queued and replayed."""
    from libjitsi_tpu.control.dtls import DtlsSrtpEndpoint
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.transform.srtp import SrtpProfile

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=8, recv_window_ms=0)
    recv = _Endpoint(0x901, sfu.port)
    sfu.add_endpoint(recv.ssrc, recv.rx_key, recv.tx_key)
    recv.send_media(1)                     # latch receiver address

    ssrc = 0x900
    sid, bridge_ep = sfu.add_endpoint_dtls(ssrc, role="server")
    cli = DtlsSrtpEndpoint(
        "client", remote_fingerprint=bridge_ep.local_fingerprint)
    eng = UdpEngine(port=0, max_batch=16)

    def pump_client(datagrams):
        if datagrams:
            eng.send_batch(PacketBatch.from_payloads(list(datagrams)),
                           "127.0.0.1", sfu.port)
        sfu.tick(now=80.0)
        back, _, _ = eng.recv_batch(timeout_ms=5)
        return [back.to_bytes(i) for i in range(back.batch_size)]

    out = cli.handshake_packets()
    for _ in range(40):
        if cli.complete:
            break
        replies = pump_client(out)
        out = []
        for r in replies:
            out.extend(cli.feed(r))
    assert cli.complete, "client handshake did not complete"

    profile, tk, tsalt, rk, rsalt = cli.srtp_keys()
    assert profile == SrtpProfile.AES_CM_128_HMAC_SHA1_80
    tx = SrtpStreamTable(capacity=1, profile=profile)
    tx.add_stream(0, tk, tsalt)
    # receiver must open the DTLS sender's legs with the BRIDGE leg key
    # it was added with (fan-out re-encrypts per leg as usual)
    recv.expect_sender(ssrc)

    b = rtp_header.build([b"dtls-media-%d" % i for i in range(4)],
                         [700 + i for i in range(4)], [0] * 4,
                         [ssrc] * 4, [96] * 4, stream=[0] * 4)
    eng.send_batch(tx.protect_rtp(b), "127.0.0.1", sfu.port)
    for _ in range(20):
        sfu.tick(now=80.1)
    for _ in range(4):
        recv.drain()
    got = b"".join(recv.got.values())
    assert b"dtls-media-0" in got and b"dtls-media-3" in got
    sfu.close()
    eng.close()


@pytest.mark.slow
def test_sfu_video_simulcast_layer_switch_and_rtx():
    """VERDICT r2 #4: the assembled video SFU.  A 3-layer VP8 simulcast
    sender (real libvpx bitstreams) feeds the bridge over loopback UDP;
    the receiver's REMB drives keyframe-gated layer selection (PLI goes
    upstream until the target layer's keyframe lands), a NACKed packet
    returns as proper RFC 4588 RTX, and the projected stream stays
    decodable across the switch."""
    from libjitsi_tpu.codecs import vp8 as vp8_mod
    from libjitsi_tpu.codecs.vpx import VpxDecoder, VpxEncoder, \
        vpx_available
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.sfu import rtx as rtx_mod

    if not vpx_available():
        pytest.skip("libvpx not present")
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=32, recv_window_ms=0)
    send = _Endpoint(0xA0, sfu.port)
    recv = _Endpoint(0xA4, sfu.port)
    sid_s = sfu.add_endpoint(send.ssrc, send.rx_key, send.tx_key)
    sid_r = sfu.add_endpoint(recv.ssrc, recv.rx_key, recv.tx_key)
    recv.send_media(1)                         # latch receiver address
    layer_ssrcs = [0xB00, 0xB01, 0xB02]
    track = sfu.add_video_track(
        sid_s, layer_ssrcs, layer_bps=[100e3, 500e3, 2e6], rtx_pt=97)

    # ---- sender: one SRTP row + encoder per layer
    dims = [(160, 96), (320, 192), (640, 384)]
    tx = SrtpStreamTable(capacity=4)
    for k in range(3):
        tx.add_stream(k, *send.rx_key)
    enc = [VpxEncoder(w, h) for w, h in dims]
    seqs, pids = [1000, 2000, 3000], [10, 20, 30]
    # sender-side SRTCP context for bridge feedback (PLI drain)
    fb = SrtpStreamTable(capacity=1)
    fb.add_stream(0, *send.tx_key)

    def frame_planes(k, t):
        w, h = dims[k]
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
        y = (128 + 60 * np.sin(xx / 17 + t * 0.7)
             + 40 * np.cos(yy / 11 + t)).clip(0, 255).astype(np.uint8)
        c = np.full(((h + 1) // 2, (w + 1) // 2), 128, np.uint8)
        return y, c, c

    def send_video(t):
        for k in range(3):
            for data, _key in enc[k].encode(*frame_planes(k, t)):
                pls = vp8_mod.packetize(data, picture_id=pids[k],
                                        max_payload=1100)
                pids[k] = (pids[k] + 1) & 0x7FFF
                n = len(pls)
                b = rtp_header.build(
                    pls, [(seqs[k] + i) & 0xFFFF for i in range(n)],
                    [t * 3000] * n, [layer_ssrcs[k]] * n, [96] * n,
                    marker=[0] * (n - 1) + [1],
                    stream=[k] * n)
                seqs[k] = (seqs[k] + n) & 0xFFFF
                send.engine.send_batch(tx.protect_rtp(b), "127.0.0.1",
                                       sfu.port)

    def sender_drain_plis():
        back, _, _ = send.engine.recv_batch(timeout_ms=5)
        got = []
        if back.batch_size:
            back.stream[:] = 0
            dec, ok = fb.unprotect_rtcp(back)
            for i in np.nonzero(np.asarray(ok))[0]:
                try:
                    for p in rtcp.parse_compound(dec.to_bytes(int(i))):
                        if isinstance(p, rtcp.Pli):
                            got.append(p.media_ssrc)
                except ValueError:
                    pass
        return got

    # ---- receiver: unprotect rows for the projected stream + RTX
    out_ssrc = send.ssrc
    rxt = SrtpStreamTable(capacity=4)
    rxt.add_stream(0, *recv.tx_key)            # projected video stream
    rxt.add_stream(1, *recv.tx_key)            # RTX stream
    fa = vp8_mod.FrameAssembler()
    seen_seqs = []
    rtx_got = []

    def recv_drain():
        back, _, _ = recv.engine.recv_batch(timeout_ms=2)
        if not back.batch_size:
            return
        hdr0 = rtp_header.parse(back)
        rowmap = {out_ssrc: 0, track.rtx_ssrc: 1}
        back.stream[:] = [rowmap.get(int(s), -1) for s in hdr0.ssrc]
        keep = np.nonzero(np.asarray(back.stream) >= 0)[0]
        if len(keep) == 0:
            return
        sub = PacketBatch(back.data[keep],
                          np.asarray(back.length)[keep],
                          back.stream[keep])
        dec, ok = rxt.unprotect_rtp(sub)
        hdr = rtp_header.parse(dec)
        vid = np.nonzero(ok & (np.asarray(dec.stream) == 0))[0]
        if len(vid):
            vb = PacketBatch(dec.data[vid],
                             np.asarray(dec.length)[vid],
                             dec.stream[vid])
            fa.push_batch(vb)
            seen_seqs.extend(int(s) for s in rtp_header.parse(vb).seq)
        for i in np.nonzero(ok & (np.asarray(dec.stream) == 1))[0]:
            one = PacketBatch(dec.data[i:i+1],
                              np.asarray(dec.length)[i:i+1],
                              dec.stream[i:i+1])
            restored, osn = rtx_mod.decapsulate_batch(one, out_ssrc, 96)
            rtx_got.append(int(osn[0]))

    def run(ticks, t0, remb=None):
        for t in range(ticks):
            send_video(t0 + t)
            if remb is not None:
                blob = rtcp.build_compound([rtcp.build_remb(rtcp.Remb(
                    recv.ssrc, int(remb), [out_ssrc]))])
                b = PacketBatch.from_payloads([blob], stream=[0])
                recv.engine.send_batch(recv.protect.protect_rtcp(b),
                                       "127.0.0.1", sfu.port)
            # 0.1 s rounds: a lost PLI datagram re-fires within the
            # phase (RtcpTermination's PLI limiter is 0.5 s)
            for _ in range(12):
                sfu.tick(now=90.0 + (t0 + t) * 0.1)
            sfu.emit_feedback(now=90.0 + (t0 + t) * 0.1)
            for ssrc in sender_drain_plis():
                if ssrc in layer_ssrcs:        # keyframe request: new
                    k = layer_ssrcs.index(ssrc)  # encoder => keyframe
                    enc[k].close()
                    enc[k] = VpxEncoder(*dims[k])
            recv_drain()

    fwd = track.fwd[sid_r]
    run(10, 0, remb=3_000_000)                 # plenty of bandwidth
    assert fwd.current_layer == 2, \
        f"no upswitch: layer={fwd.current_layer}"
    switches_before = fwd.switches
    run(12, 10, remb=600_000)   # starved to one mid layer (500 kbps)
    assert fwd.current_layer == 1, \
        f"no downswitch: layer={fwd.current_layer}"
    assert fwd.switches > switches_before

    # the projected stream reassembles into decodable VP8 across the
    # switch (keyframe-gated: the decoder survives the resolution jump)
    frames = fa.pop_frames()
    assert len(frames) >= 6
    dec = VpxDecoder()
    decoded = 0
    for _ts, _pid, _key, data in frames:
        try:
            decoded += len(dec.decode(data))
        except RuntimeError:
            pass
    assert decoded >= len(frames) - 2, \
        f"only {decoded}/{len(frames)} frames decodable"

    # NACK -> RTX: ask for a seq we saw; it must come back encapsulated
    assert seen_seqs
    want = seen_seqs[-1]
    recv.send_nack(out_ssrc, [want])
    for _ in range(12):
        sfu.tick(now=90.0 + 22 * 0.1 + 0.05)   # within cache max age
    recv_drain()
    assert want in rtx_got, f"seq {want} not re-delivered as RTX"
    sfu.close()


@pytest.mark.slow
def test_sfu_pipelined_fanout_delivers_everything():
    """Pipelined SfuBridge: the fan-out launch dispatched in tick N
    ships at tick N+1 (overlapping the recv window); every endpoint
    still hears every other endpoint's media, and NACK service still
    works against the flushed cache."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=8, recv_window_ms=0, pipelined=True)
    eps = [_Endpoint(0x300 + 5 * k, sfu.port) for k in range(3)]
    for e in eps:
        sfu.add_endpoint(e.ssrc, e.rx_key, e.tx_key)
        for other in eps:
            if other is not e:
                e.expect_sender(other.ssrc)

    for rnd in range(4):
        for e in eps:
            e.send_media()
        for _ in range(24):       # extra ticks: flush rides tick N+1
            sfu.tick(now=70.0 + rnd * 0.02)
        for e in eps:
            for _ in range(4):
                e.drain()
    assert sfu.forwarded > 0
    assert not sfu._pending_fanout, "pending fan-out never flushed"
    for e in eps:
        payloads = b"".join(e.got.values())
        for other in eps:
            if other is e:
                continue
            assert b"m-%08x" % other.ssrc in payloads, \
                f"{e.ssrc:#x} missing media from {other.ssrc:#x}"
        assert b"m-%08x" % e.ssrc not in payloads, "echoed own media"

    # NACK service against the FLUSHED cache: the per-leg copies were
    # inserted at flush time, not dispatch time
    victim = eps[0]
    victim.got.clear()
    for ssrc, row in victim.row_of.items():
        victim.open.add_stream(row, *victim.tx_key)
    victim.send_nack(eps[1].ssrc, [500])
    for _ in range(20):
        sfu.tick(now=70.2)
    for _ in range(4):
        victim.drain()
    assert sfu.retransmitted > 0
    assert any(seq == 500 for _, seq in victim.got)
    sfu.close()


@pytest.mark.slow
def test_sfu_svc_track_projection_e2e():
    """VP9 SVC through the assembled bridge: one SSRC carries two
    spatial layers; the receiver's REMB drives the projection (raise
    gated on a keyframe via PLI, downswitch at a picture boundary), the
    receiver sees a gapless renumbered stream, and a NACKed projected
    seq returns as RTX."""
    from libjitsi_tpu.codecs import vp9
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.sfu import rtx as rtx_mod

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=16, recv_window_ms=0)
    sender = _Endpoint(0xD0, sfu.port)
    recv = _Endpoint(0xD1, sfu.port)
    sid_s = sfu.add_endpoint(sender.ssrc, sender.rx_key, sender.tx_key)
    sid_r = sfu.add_endpoint(recv.ssrc, recv.rx_key, recv.tx_key)
    recv.send_media(1)
    svc_ssrc = 0xD00
    track = sfu.add_svc_track(sid_s, svc_ssrc,
                              layer_bps=[100e3, 1e6])
    fwd = track.fwd[sid_r]

    tx = SrtpStreamTable(capacity=1)
    tx.add_stream(0, *sender.rx_key)
    fb = SrtpStreamTable(capacity=1)
    fb.add_stream(0, *sender.tx_key)
    rxt = SrtpStreamTable(capacity=2)
    rxt.add_stream(0, *recv.tx_key)            # projected stream
    rxt.add_stream(1, *recv.tx_key)            # RTX stream
    state = {"seq": 100, "pic": 300}

    def send_pic(key=False):
        # every call is a NEW picture (the forwarder's switch logic
        # lands at picture boundaries, keyed by picture id)
        p = state["pic"]
        state["pic"] += 1
        pkts = []
        for s in range(2):
            desc = vp9.build_descriptor(
                begin=True, end=True, picture_id=p & 0x7FFF,
                tid=0, sid=s, tl0picidx=p & 0xFF,
                inter_predicted=not (key and s == 0))
            pkts.append(desc + bytes([0x90 + s]) * 40)
        b = rtp_header.build(pkts, [state["seq"], state["seq"] + 1],
                             [p * 3000] * 2, [svc_ssrc] * 2, [98] * 2,
                             marker=[0, 1], stream=[0, 0])
        state["seq"] += 2
        sender.engine.send_batch(tx.protect_rtp(b), "127.0.0.1",
                                 sfu.port)

    got_seqs, got_sids, rtx_osn = [], [], []

    def drain():
        back, _, _ = recv.engine.recv_batch(timeout_ms=2)
        if not back.batch_size:
            return
        hdr0 = rtp_header.parse(back)
        rowmap = {svc_ssrc: 0, track.rtx_ssrc: 1}
        back.stream[:] = [rowmap.get(int(s), -1) for s in hdr0.ssrc]
        keep = np.nonzero(np.asarray(back.stream) >= 0)[0]
        if len(keep) == 0:
            return
        sub = PacketBatch(back.data[keep],
                          np.asarray(back.length)[keep],
                          back.stream[keep])
        dec, ok = rxt.unprotect_rtp(sub)
        hdr = rtp_header.parse(dec)
        vid = np.nonzero(ok & (np.asarray(dec.stream) == 0))[0]
        if len(vid):
            vb = PacketBatch(dec.data[vid],
                             np.asarray(dec.length)[vid],
                             dec.stream[vid])
            d = vp9.parse_descriptors(vb)
            got_seqs.extend(int(s) for s in rtp_header.parse(vb).seq)
            got_sids.extend(int(s) for s in np.asarray(d.sid))
        for i in np.nonzero(ok & (np.asarray(dec.stream) == 1))[0]:
            one = PacketBatch(dec.data[i:i + 1],
                              np.asarray(dec.length)[i:i + 1],
                              dec.stream[i:i + 1])
            _res, osn = rtx_mod.decapsulate_batch(one, svc_ssrc, 98)
            rtx_osn.append(int(osn[0]))

    def sender_handle_feedback():
        back, _, _ = sender.engine.recv_batch(timeout_ms=3)
        if not back.batch_size:
            return False
        back.stream[:] = 0
        dec, ok = fb.unprotect_rtcp(back)
        saw = False
        for i in np.nonzero(np.asarray(ok))[0]:
            try:
                pkts = rtcp.parse_compound(dec.to_bytes(int(i)))
            except ValueError:
                continue
            saw |= any(isinstance(p, rtcp.Pli)
                       and p.media_ssrc == svc_ssrc for p in pkts)
        return saw

    def run(rounds, t0, remb, key_on_pli=False):
        for t in range(rounds):
            send_pic()
            blob = rtcp.build_compound([rtcp.build_remb(rtcp.Remb(
                recv.ssrc, int(remb), [svc_ssrc]))])
            b = PacketBatch.from_payloads([blob], stream=[0])
            recv.engine.send_batch(recv.protect.protect_rtcp(b),
                                   "127.0.0.1", sfu.port)
            for _ in range(10):
                sfu.tick(now=60.0 + (t0 + t) * 0.1)
            sfu.emit_feedback(now=60.0 + (t0 + t) * 0.1)
            if sender_handle_feedback() and key_on_pli:
                send_pic(key=True)
                for _ in range(10):
                    sfu.tick(now=60.0 + (t0 + t) * 0.1)
            drain()

    run(4, 0, remb=150_000)                 # base layer only
    assert fwd.current_sid == 0
    assert got_sids and max(got_sids) == 0
    run(8, 4, remb=1_500_000, key_on_pli=True)   # raise: needs keyframe
    assert fwd.current_sid == 1, "SVC raise never landed"
    assert 1 in got_sids
    run(4, 12, remb=150_000)                # starve: boundary downswitch
    assert fwd.current_sid == 0
    # gapless output seq space across every projection change
    assert got_seqs == list(range(got_seqs[0],
                                  got_seqs[0] + len(got_seqs)))
    # NACK on a projected seq comes back as RTX with that OSN
    want = got_seqs[-1]
    recv.send_nack(svc_ssrc, [want])
    for _ in range(10):
        sfu.tick(now=60.0 + 16 * 0.1 + 0.05)
    drain()
    assert want in rtx_osn, f"seq {want} not re-delivered as RTX"
    sfu.close()


def test_sfu_video_simulcast_forward_and_switch_core():
    """Core-gate video SFU (VERDICT r3 #4): tiny-shape simulcast
    forward + REMB-driven layer switch with SYNTHETIC VP8 frames (every
    frame a keyframe, so switches land without a PLI round trip) — no
    libvpx, few packets, seconds not minutes.  The per-change gate now
    fails if SfuBridge video forwarding breaks."""
    from libjitsi_tpu.codecs import vp8 as vp8_mod
    from libjitsi_tpu.core.packet import PacketBatch

    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=16, recv_window_ms=0)
    send = _Endpoint(0xE0, sfu.port)
    recv = _Endpoint(0xE4, sfu.port)
    sid_s = sfu.add_endpoint(send.ssrc, send.rx_key, send.tx_key)
    sid_r = sfu.add_endpoint(recv.ssrc, recv.rx_key, recv.tx_key)
    recv.send_media(1)                         # latch receiver address
    layer_ssrcs = [0xE00, 0xE01]
    track = sfu.add_video_track(sid_s, layer_ssrcs,
                                layer_bps=[100e3, 1e6], rtx_pt=97)
    fwd = track.fwd[sid_r]

    tx = SrtpStreamTable(capacity=2)
    for k in range(2):
        tx.add_stream(k, *send.rx_key)
    rxt = SrtpStreamTable(capacity=1)
    rxt.add_stream(0, *recv.tx_key)            # projected stream
    seqs, pids = [1000, 2000], [10, 20]
    got_layers, got_seqs = [], []

    def send_video(t):
        # synthetic VP8: frame tag LSB 0 => keyframe; payload byte
        # encodes the layer so the projection is attributable
        for k in range(2):
            frame = bytes([0x00, 0xE0 + k]) * 20
            pls = vp8_mod.packetize(frame, picture_id=pids[k])
            pids[k] = (pids[k] + 1) & 0x7FFF
            n = len(pls)
            b = rtp_header.build(
                pls, [(seqs[k] + i) & 0xFFFF for i in range(n)],
                [t * 3000] * n, [layer_ssrcs[k]] * n, [96] * n,
                marker=[0] * (n - 1) + [1], stream=[k] * n)
            seqs[k] = (seqs[k] + n) & 0xFFFF
            send.engine.send_batch(tx.protect_rtp(b), "127.0.0.1",
                                   sfu.port)

    def drain():
        back, _, _ = recv.engine.recv_batch(timeout_ms=2)
        if not back.batch_size:
            return
        hdr0 = rtp_header.parse(back)
        back.stream[:] = [0 if int(s) == send.ssrc else -1
                          for s in hdr0.ssrc]
        keep = np.nonzero(np.asarray(back.stream) >= 0)[0]
        if len(keep) == 0:
            return
        sub = PacketBatch(back.data[keep],
                          np.asarray(back.length)[keep],
                          back.stream[keep])
        dec, ok = rxt.unprotect_rtp(sub)
        hdr = rtp_header.parse(dec)
        for i in np.nonzero(ok)[0]:
            i = int(i)
            payload = dec.to_bytes(i)[int(hdr.payload_off[i]):]
            got_layers.append(payload[-1] - 0xE0)
            got_seqs.append(int(hdr.seq[i]))

    def run(rounds, t0, remb):
        for t in range(rounds):
            blob = rtcp.build_compound([rtcp.build_remb(rtcp.Remb(
                recv.ssrc, int(remb), [track.out_ssrc]))])
            b = PacketBatch.from_payloads([blob], stream=[0])
            recv.engine.send_batch(recv.protect.protect_rtcp(b),
                                   "127.0.0.1", sfu.port)
            for _ in range(3):
                sfu.tick(now=95.0 + (t0 + t) * 0.1)
            sfu.emit_feedback(now=95.0 + (t0 + t) * 0.1)
            send_video(t0 + t)
            for _ in range(6):
                sfu.tick(now=95.0 + (t0 + t) * 0.1 + 0.05)
            drain()

    run(3, 0, remb=2_000_000)        # bandwidth for the high layer
    assert fwd.current_layer == 1, f"no upswitch: {fwd.current_layer}"
    assert 1 in got_layers, "high-layer media never projected"
    run(3, 3, remb=150_000)          # starved to the base layer
    assert fwd.current_layer == 0, f"no downswitch: {fwd.current_layer}"
    assert got_layers[-1] == 0, "post-downswitch media not base layer"
    # the projection renumbers into one gapless seq space across the
    # switches
    assert got_seqs == list(range(got_seqs[0],
                                  got_seqs[0] + len(got_seqs)))
    assert sfu.forwarded > 0
    sfu.close()


@pytest.mark.slow
def test_sfu_bridge_snapshot_resume_mid_conference():
    """SURVEY §5 at assembly level: snapshot a live conference, tear
    the bridge down, restore on a NEW port — endpoints keep their SRTP
    counters running and media keeps flowing (replay windows moved with
    the snapshot, so the old packets are rejected and new ones pass)."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=8, recv_window_ms=0)
    eps = [_Endpoint(0x500 + 3 * k, sfu.port) for k in range(3)]
    for e in eps:
        sfu.add_endpoint(e.ssrc, e.rx_key, e.tx_key)
        for other in eps:
            if other is not e:
                e.expect_sender(other.ssrc)
    for rnd in range(2):
        for e in eps:
            e.send_media()
        for _ in range(16):
            sfu.tick(now=40.0 + rnd * 0.02)
        for e in eps:
            e.drain()
    assert sfu.forwarded > 0

    snap = sfu.snapshot()
    sfu.close()

    sfu2 = SfuBridge.restore(libjitsi_tpu.configuration_service(),
                             snap, port=0, recv_window_ms=0)
    assert sfu2.port != 0
    for e in eps:
        e.bridge_port = sfu2.port       # "signaling" moves endpoints
        e.got.clear()
    before = sfu2.forwarded
    for rnd in range(3):
        for e in eps:
            e.send_media()              # SRTP counters CONTINUE
        for _ in range(16):
            sfu2.tick(now=41.0 + rnd * 0.02)
        for e in eps:
            for _ in range(3):
                e.drain()
    assert sfu2.forwarded > before
    for e in eps:
        payloads = b"".join(e.got.values())
        for other in eps:
            if other is e:
                continue
            assert b"m-%08x" % other.ssrc in payloads, \
                f"{e.ssrc:#x} missing post-restore media from " \
                f"{other.ssrc:#x}"
    # replayed pre-snapshot wire must NOT re-enter (windows resumed)
    rx_before = sfu2.forwarded
    replay = rtp_header.build([b"replay"], [500], [0],
                              [eps[0].ssrc], [96], stream=[0])
    old_tab = SrtpStreamTable(capacity=1)
    old_tab.add_stream(0, *eps[0].rx_key)
    eps[0].engine.send_batch(old_tab.protect_rtp(replay), "127.0.0.1",
                             sfu2.port)
    for _ in range(10):
        sfu2.tick(now=41.2)
    assert sfu2.forwarded == rx_before, "replayed old seq re-forwarded"
    sfu2.close()
