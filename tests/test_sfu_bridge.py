"""SfuBridge e2e: decrypt-once fan-out over real loopback UDP + NACK
retransmission from the per-leg cache."""

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.service.sfu_bridge import SfuBridge
from libjitsi_tpu.transform.srtp import SrtpStreamTable


class _Endpoint:
    def __init__(self, ssrc, bridge_port):
        self.ssrc = ssrc
        self.rx_key = (bytes([ssrc & 0xFF]) * 16,
                       bytes([(ssrc + 1) & 0xFF]) * 14)
        self.tx_key = (bytes([(ssrc + 2) & 0xFF]) * 16,
                       bytes([(ssrc + 3) & 0xFF]) * 14)
        self.protect = SrtpStreamTable(capacity=1)
        self.protect.add_stream(0, *self.rx_key)
        # one rx context PER SENDER SSRC (RFC 3711: contexts are
        # per-SSRC; all legs share this receiver's session keys)
        self.open = SrtpStreamTable(capacity=4)
        self.row_of = {}
        self.engine = UdpEngine(port=0, max_batch=64)
        self.bridge_port = bridge_port
        self.seq = 500
        self.got = {}                     # seq -> payload

    def send_media(self, n=4):
        pls = [b"m-%08x-%d" % (self.ssrc, self.seq + i)
               for i in range(n)]
        b = rtp_header.build(pls, [self.seq + i for i in range(n)],
                             [0] * n, [self.ssrc] * n, [96] * n,
                             stream=[0] * n)
        self.seq += n
        self.engine.send_batch(self.protect.protect_rtp(b),
                               "127.0.0.1", self.bridge_port)

    def expect_sender(self, ssrc):
        row = len(self.row_of)
        self.row_of[ssrc] = row
        self.open.add_stream(row, *self.tx_key)

    def drain(self):
        back, _, _ = self.engine.recv_batch(timeout_ms=2)
        if back.batch_size:
            hdr0 = rtp_header.parse(back)
            back.stream[:] = [self.row_of.get(int(s), -1)
                              for s in hdr0.ssrc]
            dec, ok = self.open.unprotect_rtp(back)
            hdr = rtp_header.parse(dec)
            for i in np.nonzero(ok)[0]:
                i = int(i)
                self.got[(int(hdr.ssrc[i]), int(hdr.seq[i]))] = \
                    dec.to_bytes(i)[int(hdr.payload_off[i]):]

    def send_nack(self, media_ssrc, media_seqs):
        """SRTCP-protected NACK (the bridge drops plaintext control)."""
        blob = rtcp.build_compound([rtcp.build_nack(rtcp.Nack(
            sender_ssrc=self.ssrc, media_ssrc=media_ssrc,
            lost_seqs=list(media_seqs)))])
        from libjitsi_tpu.core.packet import PacketBatch

        b = PacketBatch.from_payloads([blob], stream=[0])
        wire = self.protect.protect_rtcp(b)
        self.engine.send_batch(wire, "127.0.0.1", self.bridge_port)


@pytest.mark.slow
def test_sfu_fanout_and_nack_over_udp():
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=8, recv_window_ms=0)
    eps = [_Endpoint(0x100 + 7 * k, sfu.port) for k in range(3)]
    sids = [sfu.add_endpoint(e.ssrc, e.rx_key, e.tx_key) for e in eps]
    for e in eps:
        for other in eps:
            if other is not e:
                e.expect_sender(other.ssrc)

    # every endpoint sends; everyone must receive the other two's media
    for rnd in range(4):
        for e in eps:
            e.send_media()
        for _ in range(20):
            sfu.tick(now=50.0 + rnd * 0.02)
        for e in eps:
            for _ in range(4):
                e.drain()
    assert sfu.forwarded > 0
    for e in eps:
        payloads = b"".join(e.got.values())
        for other in eps:
            if other is e:
                continue
            assert b"m-%08x" % other.ssrc in payloads, \
                f"{e.ssrc:#x} missing media from {other.ssrc:#x}"
        assert b"m-%08x" % e.ssrc not in payloads, "echoed own media"

    # NACK service: receiver drops a seq, asks again, gets the cached
    # per-leg copy (protected with ITS leg key)
    victim = eps[0]
    missing_seq = 501
    victim.got.clear()
    # fresh contexts for the re-delivery (replay windows already saw
    # these seqs in the live pass)
    for ssrc, row in victim.row_of.items():
        victim.open.add_stream(row, *victim.tx_key)
    victim.send_nack(eps[1].ssrc, [missing_seq])
    for _ in range(20):
        sfu.tick(now=50.5)   # within the cache's 1 s max age
    for _ in range(4):
        victim.drain()
    assert sfu.retransmitted > 0
    assert any(seq == missing_seq for _, seq in victim.got)
    # only the NACKed sender's copy was re-delivered (cache keys carry
    # the sender ssrc)
    assert all(ssrc == eps[1].ssrc for ssrc, _ in victim.got)
    # feedback drain: aggregated NACK/RR toward senders, SRTCP-protected
    sfu.emit_feedback(now=50.6)
    sfu.close()
