"""Full conference-bridge integration: the BASELINE config #3 shape.

N participants send Opus-encoded, SRTP-protected RTP to the bridge; the
bridge decrypts (batched), decodes, runs the mix-minus kernel + levels +
dominant-speaker detection, re-encodes each participant's personalized
mix and SRTP-protects it back out.  Byte paths, crypto state, and the
mixer math are all the production code paths (reference call stack:
SURVEY §3.3).
"""

import numpy as np
import pytest

from libjitsi_tpu.codecs import OpusDecoder, OpusEncoder, opus_available
from libjitsi_tpu.conference import AudioMixer
from libjitsi_tpu.conference.speaker import DominantSpeakerIdentification
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpStreamTable

pytestmark = pytest.mark.slow   # cold-compile-heavy e2e tier

N = 4
FRAME = 960  # 20 ms @ 48 kHz


def _tone(freq, amp, n=FRAME, phase=0):
    t = (np.arange(n) + phase) / 48000.0
    return (np.sin(2 * np.pi * freq * t) * amp).astype(np.int16)


@pytest.mark.skipif(not opus_available(), reason="libopus not present")
def test_conference_bridge_tick():
    # --- setup: per-participant keys, codecs, bridge state
    keys = [(bytes([i] * 16), bytes([i + 50] * 14)) for i in range(N)]
    # participant-side tables (tx toward bridge, rx from bridge)
    p_tx = []
    p_rx = []
    # bridge-side tables (rx from participants, tx toward participants)
    b_rx = SrtpStreamTable(capacity=N)
    b_tx = SrtpStreamTable(capacity=N)
    for i, (mk, ms) in enumerate(keys):
        t = SrtpStreamTable(capacity=1)
        t.add_stream(0, mk, ms)
        p_tx.append(t)
        b_rx.add_stream(i, mk, ms)
        # downstream leg uses a distinct key per participant
        mk2, ms2 = bytes([i + 100] * 16), bytes([i + 150] * 14)
        b_tx.add_stream(i, mk2, ms2)
        r = SrtpStreamTable(capacity=1)
        r.add_stream(0, mk2, ms2)
        p_rx.append(r)

    enc = [OpusEncoder() for _ in range(N)]
    dec = [OpusDecoder() for _ in range(N)]
    down_dec = [OpusDecoder() for _ in range(N)]
    mixer = AudioMixer(capacity=N, frame_samples=FRAME)
    dsi = DominantSpeakerIdentification(capacity=N)
    for i in range(N):
        mixer.add_participant(i)
        dsi.add_participant(i)

    # participant 2 talks loudly; 0 quietly; 1 and 3 silent
    amps = [600, 0, 16000, 0]
    down_enc = [OpusEncoder() for _ in range(N)]

    last_mix = None
    for tick in range(25):
        # --- uplink: each participant encodes + protects one frame
        wires = []
        for i in range(N):
            pcm = _tone(300 + 200 * i, amps[i], phase=tick * FRAME)
            payload = enc[i].encode(pcm)
            b = rtp_header.build([payload], [tick], [tick * FRAME],
                                 [0x100 + i], [111], stream=[0])
            wires.append(p_tx[i].protect_rtp(b).to_bytes(0))

        # --- bridge: one batched decrypt for all participants
        batch = PacketBatch.from_payloads(wires, stream=list(range(N)))
        plain, ok = b_rx.unprotect_rtp(batch)
        assert ok.all()
        hdr = rtp_header.parse(plain)
        for i in range(N):
            payload = plain.to_bytes(i)[int(hdr.payload_off[i]):]
            mixer.push(i, dec[i].decode(payload, FRAME))

        # --- mix + levels + dominant speaker (device kernel)
        out_pcm, levels = mixer.mix()
        dsi.levels(levels)
        last_mix = (out_pcm, levels)

        # --- downlink: encode each personalized mix, batched protect
        payloads = [down_enc[i].encode(out_pcm[i]) for i in range(N)]
        down = rtp_header.build(payloads, [tick] * N, [tick * FRAME] * N,
                                [0x200 + i for i in range(N)], [111],
                                stream=list(range(N)))
        wire_down = b_tx.protect_rtp(down)

        # --- participants decrypt their mix
        for i in range(N):
            sub = PacketBatch.from_payloads([wire_down.to_bytes(i)],
                                            stream=[0])
            d, ok_i = p_rx[i].unprotect_rtp(sub)
            assert ok_i.all()

    out_pcm, levels = last_mix
    # the loud participant is dominant
    assert dsi.dominant == 2
    # levels: participant 2 loud; "silent" senders decode to codec
    # comfort noise, so near-silence (>100 dBov down), not exactly 127
    assert levels[2] < 40 and levels[1] > 100 and levels[3] > 100
    # mix-minus: participant 2's mix excludes itself -> much quieter
    # than participant 1's mix (which contains the loud 2)
    e1 = np.std(out_pcm[1].astype(float))
    e2 = np.std(out_pcm[2].astype(float))
    assert e2 < e1 * 0.25
    # crypto state advanced consistently on every leg
    assert b_rx.rx_max.tolist()[:N] == [24] * N
    assert b_tx.tx_ext.tolist()[:N] == [24] * N
