"""BridgeSupervisor unit tests: watchdog state machine, the overload
escalation ladder (recv window -> degraded -> shedding) and its
recovery, sliding-window quarantine with exponential-backoff
re-admission, checkpoint file versioning, and the health primitives.

All against a dummy bridge — no sockets, no device; the e2e proofs
live in tests/test_chaos_recovery.py.
"""

import types

import numpy as np
import pytest

from libjitsi_tpu.service.supervisor import (BridgeSupervisor, CKPT_MAGIC,
                                             CKPT_VERSION, SupervisorConfig)
from libjitsi_tpu.utils.health import (ExponentialBackoff, HEALTHY,
                                       OVERLOADED, STALLED,
                                       SlidingWindowCounter, Watchdog,
                                       retrying)
from libjitsi_tpu.utils.metrics import MetricsRegistry

CAP = 8


class DummyLoop:
    def __init__(self):
        self.registry = types.SimpleNamespace(capacity=CAP)
        self.recv_window_ms = 1
        self.inbound_drop = np.zeros(CAP, dtype=bool)
        self.inbound_dropped = np.zeros(CAP, dtype=np.int64)
        self.inbound_dropped_total = 0


class DummyBridge:
    def __init__(self):
        self.loop = DummyLoop()
        self.degraded = False
        self._ssrc_of = {0: 100, 1: 101, 2: 102, 3: 103}
        self.rx_table = types.SimpleNamespace(
            auth_fail=np.zeros(CAP, dtype=np.int64),
            replay_reject=np.zeros(CAP, dtype=np.int64))
        self.speaker = types.SimpleNamespace(dominant=0)
        self.ticked = 0

    def tick(self, now=None):
        self.ticked += 1
        return {"rx": 0}


class FakeClock:
    """Scripted tick durations: each supervisor tick reads the clock
    twice (t0/t1); the second read advances by the next duration."""

    def __init__(self, durations):
        self.durations = list(durations)
        self.t = 0.0
        self.half = False

    def __call__(self):
        if self.half:
            self.t += self.durations.pop(0) if self.durations else 0.0
        self.half = not self.half
        return self.t


def _sup(durations, **cfg_kwargs):
    cfg = SupervisorConfig(deadline_ms=10.0, **cfg_kwargs)
    bridge = DummyBridge()
    return BridgeSupervisor(bridge, cfg,
                            clock=FakeClock(durations)), bridge


# ------------------------------------------------------------- watchdog

def test_watchdog_states_and_counters():
    wd = Watchdog(0.010, overload_after=3, stall_after=5)
    assert wd.state == HEALTHY
    for _ in range(2):
        assert wd.observe(0.020)
    assert wd.state == HEALTHY and wd.consecutive == 2
    wd.observe(0.020)
    assert wd.state == OVERLOADED
    for _ in range(2):
        wd.observe(0.020)
    assert wd.state == STALLED and wd.overruns == 5
    assert not wd.observe(0.001)          # one good tick clears the run
    assert wd.state == HEALTHY and wd.consecutive == 0
    assert wd.max_consecutive == 5 and wd.worst_s == 0.020


def test_supervisor_passes_result_through_and_counts():
    sup, bridge = _sup([0.001] * 3)
    assert sup.tick() == {"rx": 0}
    sup.tick(now=1.0)
    assert bridge.ticked == 2 and sup.ticks == 2
    assert sup.health()["state"] == HEALTHY


# -------------------------------------------------------------- ladder

def test_escalation_ladder_then_full_recovery():
    # 12 overrun ticks (escalate every 2), then 30 good ones
    sup, bridge = _sup([0.05] * 12 + [0.001] * 30,
                       overload_after=2, overload_exit=3, shed_step=2)
    for _ in range(12):
        sup.tick()
    # rung 1: batching window zeroed; rung 2: degraded; rung 3+: shed
    assert bridge.loop.recv_window_ms == 0
    assert bridge.degraded
    assert sup.level >= 3 and len(sup._shed) > 0
    assert bridge.loop.inbound_drop[sorted(sup._shed_set)].all()
    # dominant speaker (sid 0) is never shed
    assert 0 not in sup._shed_set
    # every shed produced a retrievable post-mortem naming its trigger
    pms = [p for p in sup.postmortems if p["trigger"] == "overload_shed"]
    assert {p["sid"] for p in pms} == set(sup._shed_set)
    for p in pms:
        assert p["event"]["kind"] == "shed"
        assert any(e["kind"] == "shed" and e["sid"] == p["sid"]
                   for e in p["dump"]["events"])
        # the global ring in the dump shows the ladder walking up
        assert any(e["kind"] == "ladder_escalate"
                   for e in p["dump"]["global"])
    for _ in range(30):
        sup.tick()
    assert sup.level == 0
    assert not bridge.degraded
    assert bridge.loop.recv_window_ms == 1          # restored
    assert not sup._shed and not bridge.loop.inbound_drop.any()
    # recovery left its own trail: de-escalations + per-sid restores
    glob = {e["kind"] for e in sup.flight.dump_all()["global"]}
    assert "ladder_deescalate" in glob
    for sid in {p["sid"] for p in pms}:
        kinds = [e["kind"] for e in sup.flight.dump(sid)["events"]]
        assert "shed_restore" in kinds


def _stage_sup(ledger, durations, with_recovery=True, slo=None,
               phases=None, **cfg_kwargs):
    """Supervisor over a DummyBridge with a seeded stage ledger (the
    tracer stub returns the same per-stage seconds every tick) and an
    optional recovery stub that records shed_fec/throttle_rtx calls.
    `phases` seeds a phase ledger (host/device split) the same way."""
    cfg = SupervisorConfig(deadline_ms=10.0, overload_after=1,
                           **cfg_kwargs)
    bridge = DummyBridge()
    bridge.loop.tracer = types.SimpleNamespace(
        take_ledger=lambda: dict(ledger))
    if phases is not None:
        bridge.loop.tracer.take_phase_ledger = \
            lambda: dict(phases)
    calls = []
    if with_recovery:
        bridge.recovery = types.SimpleNamespace(
            shed_fec=lambda on: calls.append(("shed_fec", on)),
            throttle_rtx=lambda on: calls.append(("throttle_rtx", on)))
    sup = BridgeSupervisor(bridge, cfg, clock=FakeClock(durations),
                           slo=slo)
    return sup, bridge, calls


def _escalations(sup):
    return [e for e in sup.flight.dump_all()["global"]
            if e["kind"] == "ladder_escalate"]


def test_stage_skew_forward_chain_sheds_fec_before_recv_window():
    """forward_chain owning the tick budget must pick shed_fec FIRST —
    not the wall-time ladder's recv_window rung."""
    ledger = {"ingress": 0.0004, "forward_chain": 0.009,
              "egress": 0.0006}
    sup, bridge, calls = _stage_sup(ledger, [0.05])
    sup.tick()
    (ev,) = _escalations(sup)
    assert ev["rung"] == "shed_fec"
    assert ev["stage"] == "forward_chain"
    assert ev["stage_share"] == pytest.approx(0.9, abs=0.01)
    assert ev["slo_state"] == "none"
    assert calls == [("shed_fec", True)]
    # the wall-ladder rungs stayed untouched
    assert bridge.loop.recv_window_ms == 1 and not bridge.degraded


def test_stage_skew_ingress_shrinks_recv_window_and_unwinds_lifo():
    ledger = {"ingress": 0.008, "forward_chain": 0.001,
              "egress": 0.001}
    sup, bridge, calls = _stage_sup(
        ledger, [0.05, 0.05] + [0.001] * 10, overload_exit=2)
    sup.tick()
    (ev, ) = _escalations(sup)
    assert ev["rung"] == "recv_window" and ev["stage"] == "ingress"
    assert bridge.loop.recv_window_ms == 0
    # second escalation: ingress rung already held -> wall ladder next
    sup.tick()
    assert _escalations(sup)[-1]["rung"] == "degrade"
    assert bridge.degraded and not calls
    # recovery unwinds LIFO: degrade first, then the window restores
    for _ in range(2):
        sup.tick()
    assert not bridge.degraded and bridge.loop.recv_window_ms == 0
    for _ in range(2):
        sup.tick()
    assert bridge.loop.recv_window_ms == 1
    assert sup.level == 0


def test_stage_skew_below_threshold_falls_back_to_wall_ladder():
    """A balanced ledger (no stage >= stage_share_threshold) must walk
    the PR-2 wall-time order even when forward_chain is nominally the
    dominant stage."""
    ledger = {"ingress": 0.003, "forward_chain": 0.004,
              "egress": 0.003}
    sup, bridge, calls = _stage_sup(ledger, [0.05, 0.05])
    sup.tick()
    sup.tick()
    rungs = [e["rung"] for e in _escalations(sup)]
    assert rungs == ["recv_window", "degrade"]
    assert not calls


def test_stage_skew_without_recovery_skips_fec_rung():
    ledger = {"forward_chain": 0.009, "ingress": 0.001}
    sup, bridge, calls = _stage_sup(ledger, [0.05],
                                    with_recovery=False)
    sup.tick()
    (ev,) = _escalations(sup)
    assert ev["rung"] == "recv_window"       # no controller to act on
    assert not calls


def test_escalation_event_carries_live_slo_state():
    slo = types.SimpleNamespace(state=lambda *a: "fast_burn",
                                on_tick=lambda: None)
    ledger = {"forward_chain": 0.009, "ingress": 0.001}
    sup, _bridge, _calls = _stage_sup(ledger, [0.05], slo=slo)
    sup.tick()
    (ev,) = _escalations(sup)
    assert ev["slo_state"] == "fast_burn"
    assert sup.health()["slo_state"] == "fast_burn"


def test_escalation_names_host_phase_when_host_bound():
    """A host-dominant phase split must reach the ladder_escalate
    event: the page says "host-bound, host_python owns the tick", not
    just which pipeline stage overran."""
    ledger = {"ingress": 0.008, "forward_chain": 0.001}
    phases = {"host_python": 0.016, "dispatch": 0.002,
              "device_compute": 0.001, "idle": 0.001}
    sup, _bridge, _calls = _stage_sup(ledger, [0.05], phases=phases)
    sup.tick()
    (ev,) = _escalations(sup)
    assert ev["phase"] == "host_python"
    assert ev["bound"] == "host"
    assert ev["phase_share"] == pytest.approx(0.8, abs=0.01)
    attr = sup.phase_attribution()
    assert attr["bound"] == "host"
    assert attr["phase"] == "host_python"
    assert attr["phases"] == phases
    assert sup.health()["bound"] == "host"


def test_escalation_names_device_phase_when_device_bound():
    ledger = {"forward_chain": 0.009, "ingress": 0.001}
    phases = {"host_python": 0.001, "dispatch": 0.001,
              "device_compute": 0.015, "d2h_transfer": 0.002}
    sup, _bridge, _calls = _stage_sup(ledger, [0.05], phases=phases)
    sup.tick()
    (ev,) = _escalations(sup)
    assert ev["phase"] == "device_compute"
    assert ev["bound"] == "device"


def test_escalation_without_phase_ledger_reports_unknown():
    """Tracer stubs (and pre-profiler loops) have no phase ledger at
    all — attribution degrades to unknown, never crashes."""
    ledger = {"forward_chain": 0.009, "ingress": 0.001}
    sup, _bridge, _calls = _stage_sup(ledger, [0.05])
    sup.tick()
    (ev,) = _escalations(sup)
    assert ev["phase"] == "unknown"
    assert ev["bound"] == "unknown"
    assert sup.phase_attribution()["phases"] == {}


def test_phase_ledger_keeps_last_sampled_split_across_empty_drains():
    """Supervisor ticks outpace sampled profiler ticks: an empty drain
    must NOT wipe the last real split."""
    ledger = {"forward_chain": 0.009, "ingress": 0.001}
    drains = [{"host_python": 0.01, "device_compute": 0.002}, {}, {}]
    sup, _bridge, _calls = _stage_sup(ledger, [0.05] * 3)
    sup.tracer.take_phase_ledger = lambda: drains.pop(0) if drains \
        else {}
    for _ in range(3):
        sup.tick()
    assert sup.last_phases == {"host_python": 0.01,
                               "device_compute": 0.002}
    assert _escalations(sup)[-1]["bound"] == "host"


def test_shed_is_deterministic_and_priority_ordered():
    cfg = SupervisorConfig(deadline_ms=10.0, overload_after=1,
                           shed_step=2)
    bridge = DummyBridge()
    sup = BridgeSupervisor(bridge, cfg, priorities={1: 5, 2: 0, 3: 0},
                           clock=FakeClock([0.05] * 3))
    sup.tick()          # level 1
    sup.tick()          # level 2
    sup.tick()          # level 3: shed 2
    # lowest priority first, then highest sid: 3 then 2 (1 has prio 5,
    # 0 is the dominant speaker)
    assert sup._shed == [3, 2]


# ---------------------------------------------------------- quarantine

def test_quarantine_convicts_releases_and_backs_off():
    cfg = SupervisorConfig(deadline_ms=1000.0, quarantine_window=5,
                           quarantine_auth_threshold=10,
                           quarantine_backoff_ticks=4,
                           quarantine_backoff_cap=8)
    bridge = DummyBridge()
    sup = BridgeSupervisor(bridge, cfg)
    for _ in range(3):
        bridge.rx_table.auth_fail[2] += 4
        sup.tick(now=0.0)
    assert 2 in sup._quarantined and bridge.loop.inbound_drop[2]
    assert sup.quarantine_total == 1
    # the conviction dumped a post-mortem whose ring shows the storm
    pm = next(p for p in sup.postmortems if p["trigger"] == "quarantine")
    assert pm["sid"] == 2 and pm["event"]["reason"] == "auth_storm"
    assert any(e["kind"] == "srtp_auth_fail"
               for e in pm["dump"]["events"])
    first_release = sup._quarantined[2]
    assert first_release - sup.ticks <= 4
    # other streams untouched
    assert not bridge.loop.inbound_drop[[0, 1, 3]].any()
    while sup.ticks < first_release:
        sup.tick(now=0.0)
    assert 2 not in sup._quarantined and not bridge.loop.inbound_drop[2]
    assert any(e["kind"] == "quarantine_release"
               for e in sup.flight.dump(2)["events"])
    # relapse: second conviction's ban is exponentially longer
    for _ in range(3):
        bridge.rx_table.auth_fail[2] += 4
        sup.tick(now=0.0)
    assert 2 in sup._quarantined
    assert sup._quarantined[2] - sup.ticks >= 7      # 4 * 2 (minus 1 tick)
    assert sup.quarantine_total == 2


def test_quarantine_threshold_is_windowed_not_lifetime():
    cfg = SupervisorConfig(deadline_ms=1000.0, quarantine_window=3,
                           quarantine_auth_threshold=10)
    bridge = DummyBridge()
    sup = BridgeSupervisor(bridge, cfg)
    # 2 failures/tick forever: lifetime total crosses 10 but any
    # 3-tick window holds only 6 — never quarantined
    for _ in range(20):
        bridge.rx_table.auth_fail[1] += 2
        sup.tick(now=0.0)
    assert 1 not in sup._quarantined


# ------------------------------------------------------------- metrics

def test_supervisor_metrics_render():
    reg = MetricsRegistry()
    cfg = SupervisorConfig(deadline_ms=10.0, overload_after=1,
                           quarantine_window=5,
                           quarantine_auth_threshold=5)
    bridge = DummyBridge()
    sup = BridgeSupervisor(bridge, cfg, metrics=reg,
                           clock=FakeClock([0.05] * 4))
    bridge.rx_table.auth_fail[3] += 6
    for _ in range(4):
        sup.tick()
    txt = reg.render()
    assert "# TYPE libjitsi_tpu_supervisor_ticks_overrun counter" in txt
    assert "libjitsi_tpu_supervisor_ticks_overrun 4" in txt
    assert "libjitsi_tpu_supervisor_watchdog_state 1" in txt
    assert "libjitsi_tpu_supervisor_streams_quarantined 1" in txt
    assert "libjitsi_tpu_supervisor_quarantine_total 1" in txt
    assert 'libjitsi_tpu_srtp_auth_fail{stream="3"} 6' in txt
    assert "# TYPE libjitsi_tpu_srtp_auth_fail counter" in txt


# ----------------------------------------------------------- checkpoint

def test_checkpoint_rejects_garbage_and_wrong_version(tmp_path):
    bad = tmp_path / "bad.ckpt"
    bad.write_bytes(b"not a checkpoint")
    with pytest.raises(Exception):
        BridgeSupervisor.load_checkpoint(str(bad))

    import pickle
    wrong = tmp_path / "wrong.ckpt"
    wrong.write_bytes(pickle.dumps({"magic": "other", "version": 1}))
    with pytest.raises(ValueError, match="not a libjitsi_tpu"):
        BridgeSupervisor.load_checkpoint(str(wrong))
    futur = tmp_path / "future.ckpt"
    futur.write_bytes(pickle.dumps(
        {"magic": CKPT_MAGIC, "version": CKPT_VERSION + 1}))
    with pytest.raises(ValueError, match="version"):
        BridgeSupervisor.load_checkpoint(str(futur))


def test_periodic_checkpoint_cadence(tmp_path):
    path = str(tmp_path / "bridge.ckpt")

    class SnapBridge(DummyBridge):
        def snapshot(self):
            return {"hello": 1}

    cfg = SupervisorConfig(deadline_ms=1000.0, checkpoint_every=3,
                           checkpoint_path=path)
    sup = BridgeSupervisor(SnapBridge(), cfg)
    for _ in range(7):
        sup.tick(now=0.0)
    assert sup.checkpoints_written == 2
    blob = BridgeSupervisor.load_checkpoint(path)
    assert blob["snap"] == {"hello": 1}
    assert blob["ticks"] == 6 and blob["bridge"] == "SnapBridge"


# ------------------------------------------------------ health helpers

def test_sliding_window_counter_expires_old_ticks():
    win = SlidingWindowCounter(4, window=3)
    win.push(np.array([5, 0, 0, 0]))
    win.push(np.array([0, 2, 0, 0]))
    assert list(win.sums()) == [5, 2, 0, 0]
    win.push(np.zeros(4, dtype=np.int64))
    win.push(np.zeros(4, dtype=np.int64))     # row with the 5 rotates out
    assert list(win.sums()) == [0, 2, 0, 0]
    win.reset_rows([1])
    assert list(win.sums()) == [0, 0, 0, 0]


def test_exponential_backoff_caps():
    bo = ExponentialBackoff(4, factor=2.0, cap=10)
    assert [bo.delay(a) for a in range(4)] == [4, 8, 10, 10]


def test_retrying_bounded_and_sleeps_backoff():
    slept = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(98, "in use")
        return "bound"

    assert retrying(flaky, retries=5, backoff_s=0.01,
                    sleep=slept.append) == "bound"
    assert calls["n"] == 3 and slept == [0.01, 0.02]

    def always():
        raise OSError(98, "in use")

    with pytest.raises(OSError):
        retrying(always, retries=3, backoff_s=0.01, sleep=slept.append)
