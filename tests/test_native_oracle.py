"""The C++ OpenSSL oracle (SURVEY §2.6-1's native fallback) vs the
device kernels: the same libcrypto.so.3 the reference's JNI provider
wraps, reached through a C++ shim instead of the `cryptography` Python
binding.  Agreement here pins the TPU kernels to OpenSSL itself."""

import numpy as np
import jax.numpy as jnp

from libjitsi_tpu.kernels import sha1 as K
from libjitsi_tpu.kernels.aes import ctr_crypt_uniform, expand_keys_batch
from libjitsi_tpu.native import oracle


def test_cpp_oracle_aes_ctr_matches_kernel():
    rng = np.random.default_rng(3)
    n, width = 4, 96
    keys = rng.integers(0, 256, (n, 16), dtype=np.uint8)
    ivs = rng.integers(0, 256, (n, 16), dtype=np.uint8)
    data = rng.integers(0, 256, (n, width), dtype=np.uint8)
    lengths = np.full(n, width, np.int32)
    rks = expand_keys_batch(keys)
    out = np.asarray(ctr_crypt_uniform(jnp.asarray(rks),
                                       jnp.asarray(ivs),
                                       jnp.asarray(data), 0,
                                       jnp.asarray(lengths)))
    for i in range(n):
        want = oracle.aes_ctr(keys[i].tobytes(), ivs[i].tobytes(),
                              data[i].tobytes())
        assert out[i].tobytes() == want, i


def test_cpp_oracle_hmac_matches_kernel():
    rng = np.random.default_rng(4)
    keys = [rng.integers(0, 256, int(k), dtype=np.uint8).tobytes()
            for k in (16, 20, 64)]
    msgs = [rng.integers(0, 256, int(m), dtype=np.uint8).tobytes()
            for m in (5, 56, 200)]
    width = 256
    data = np.zeros((3, width), np.uint8)
    lengths = np.zeros(3, np.int32)
    for i, m in enumerate(msgs):
        data[i, :len(m)] = np.frombuffer(m, np.uint8)
        lengths[i] = len(m)
    mids = np.stack([K.hmac_precompute(k) for k in keys])
    out = np.asarray(K.hmac_sha1(jnp.asarray(mids), jnp.asarray(data),
                                 jnp.asarray(lengths)))
    for i, (k, m) in enumerate(zip(keys, msgs)):
        assert out[i].tobytes() == oracle.hmac_sha1(k, m), i


def test_cpp_oracle_gcm_matches_kernel():
    from libjitsi_tpu.kernels import gcm as G
    from libjitsi_tpu.kernels.ghash import ghash_matrix
    from libjitsi_tpu.kernels.aes import aes_encrypt_np, expand_key

    rng = np.random.default_rng(5)
    key = rng.integers(0, 256, 16, dtype=np.uint8).tobytes()
    iv12 = rng.integers(0, 256, 12, dtype=np.uint8).tobytes()
    aad = rng.integers(0, 256, 12, dtype=np.uint8).tobytes()
    pt = rng.integers(0, 256, 64, dtype=np.uint8).tobytes()
    rk = expand_key(key)
    h = aes_encrypt_np(rk, np.zeros((1, 16), np.uint8))[0].tobytes()
    gm = ghash_matrix(h).astype(np.int8)
    width = 96
    data = np.zeros((1, width), np.uint8)
    blob = aad + pt
    data[0, :len(blob)] = np.frombuffer(blob, np.uint8)
    out, outlen = G.gcm_protect(
        jnp.asarray(data), jnp.asarray([len(blob)], jnp.int32),
        jnp.asarray([len(aad)], jnp.int32),
        jnp.asarray(rk[None].astype(np.uint8)),
        jnp.asarray(gm[None]), jnp.asarray(
            np.frombuffer(iv12, np.uint8)[None]))
    out = np.asarray(out)[0]
    ct, tag = oracle.gcm_seal(key, iv12, aad, pt)
    assert out[len(aad):len(blob)].tobytes() == ct
    assert out[len(blob):len(blob) + 16].tobytes() == tag
