"""AES-f8 SRTP cipher mode (RFC 3711 §4.1.2; reference: SRTPCipherF8).

The batched JAX path is differential-tested against an independently
written scalar oracle (`f8_keystream_np`, OpenSSL AES-ECB via the
`cryptography` package) plus a from-scratch scalar SRTP-f8 protect here.
"""

import hashlib
import hmac as pyhmac

import numpy as np

from libjitsi_tpu.kernels.aes import (expand_key, f8_keystream,
                                      f8_keystream_np, f8_m)
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.transform.srtp import SrtpStreamTable
from libjitsi_tpu.transform.srtp.kdf import derive_session_keys
from libjitsi_tpu.transform.srtp.policy import SrtpProfile
import pytest

KEY = bytes(range(16))
SALT = bytes(range(100, 114))


def test_f8_keystream_matches_scalar_oracle():
    rng = np.random.default_rng(1)
    keys = rng.integers(0, 256, (4, 16), dtype=np.uint8)
    salts = rng.integers(0, 256, (4, 14), dtype=np.uint8)
    ivs = rng.integers(0, 256, (4, 16), dtype=np.uint8)
    rk = np.stack([expand_key(k.tobytes()) for k in keys])
    rkf = np.stack([
        expand_key(bytes(a ^ b for a, b in zip(
            k.tobytes(), f8_m(k.tobytes(), s.tobytes()))))
        for k, s in zip(keys, salts)])
    dev = np.asarray(f8_keystream(rk, rkf, ivs, 9))
    for i in range(4):
        want = f8_keystream_np(keys[i].tobytes(), salts[i].tobytes(),
                               ivs[i].tobytes(), 9 * 16)
        assert dev[i].tobytes() == want


def _scalar_f8_protect(mk: bytes, ms: bytes, pkt: bytes, roc: int) -> bytes:
    """Scalar RFC 3711 f8 SRTP protect written independently of the
    batched path (kdf is shared — it is CM/F8-agnostic §4.3)."""
    ks = derive_session_keys(mk, ms, enc_key_len=16, auth_key_len=20,
                             salt_len=14)
    m, pt = pkt[1] >> 7, pkt[1] & 0x7F
    seq = int.from_bytes(pkt[2:4], "big")
    ts = int.from_bytes(pkt[4:8], "big")
    ssrc = int.from_bytes(pkt[8:12], "big")
    iv = bytes([0, (m << 7) | pt]) + seq.to_bytes(2, "big") + \
        ts.to_bytes(4, "big") + ssrc.to_bytes(4, "big") + roc.to_bytes(4, "big")
    stream = f8_keystream_np(ks.rtp_enc, ks.rtp_salt, iv, len(pkt) - 12)
    ct = pkt[:12] + bytes(a ^ b for a, b in zip(pkt[12:], stream))
    tag = pyhmac.new(ks.rtp_auth, ct + roc.to_bytes(4, "big"),
                     hashlib.sha1).digest()[:10]
    return ct + tag


@pytest.mark.slow
def test_f8_protect_matches_scalar_oracle():
    tx = SrtpStreamTable(capacity=1, profile=SrtpProfile.F8_128_HMAC_SHA1_80)
    tx.add_stream(0, KEY, SALT)
    pkt = rtp_header.build([b"f8-oracle" * 9], [444], [12345], [0xABCD],
                           [111], marker=[1], stream=[0])
    prot = tx.protect_rtp(pkt)
    want = _scalar_f8_protect(KEY, SALT, pkt.to_bytes(0), 0)
    assert prot.to_bytes(0) == want


@pytest.mark.slow
def test_f8_rtp_roundtrip_and_tamper():
    tx = SrtpStreamTable(capacity=2, profile=SrtpProfile.F8_128_HMAC_SHA1_80)
    rx = SrtpStreamTable(capacity=2, profile=SrtpProfile.F8_128_HMAC_SHA1_80)
    for sid in (0, 1):
        tx.add_stream(sid, KEY, SALT)
        rx.add_stream(sid, KEY, SALT)
    pkt = rtp_header.build([bytes([i]) * 120 for i in range(6)],
                           list(range(50, 56)), [160 * i for i in range(6)],
                           [7, 8] * 3, [96] * 6, stream=[0, 1] * 3)
    prot = tx.protect_rtp(pkt)
    # ciphertext actually differs from plaintext
    assert prot.to_bytes(0)[12:20] != pkt.to_bytes(0)[12:20]
    dec, ok = rx.unprotect_rtp(prot)
    assert ok.all()
    for i in range(6):
        assert dec.to_bytes(i) == pkt.to_bytes(i)
    # tampered ciphertext fails auth
    bad = prot.copy()
    bad.data[2, 20] ^= 0xFF
    _, ok2 = rx.unprotect_rtp(bad)
    assert not ok2[2] and ok2[[0, 1, 3, 4, 5]].sum() == 0  # replayed too


@pytest.mark.slow
def test_f8_rtcp_roundtrip():
    tx = SrtpStreamTable(capacity=1, profile=SrtpProfile.F8_128_HMAC_SHA1_80)
    rx = SrtpStreamTable(capacity=1, profile=SrtpProfile.F8_128_HMAC_SHA1_80)
    tx.add_stream(0, KEY, SALT)
    rx.add_stream(0, KEY, SALT)
    from libjitsi_tpu.core.packet import PacketBatch
    # minimal SR: V=2, PT=200, length=6 words, SSRC + sender info
    sr = bytes([0x80, 200, 0, 6]) + (0x1234).to_bytes(4, "big") + bytes(24)
    batch = PacketBatch.from_payloads([sr], capacity=128)
    batch.stream[:] = 0
    prot = tx.protect_rtcp(batch)
    assert prot.to_bytes(0)[8:16] != sr[8:16]      # payload encrypted
    assert prot.to_bytes(0)[:8] == sr[:8]          # header+SSRC clear
    dec, ok = rx.unprotect_rtcp(prot)
    assert ok.all() and dec.to_bytes(0) == sr


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_f8_snapshot_restore_preserves_schedules():
    tx = SrtpStreamTable(capacity=1, profile=SrtpProfile.F8_128_HMAC_SHA1_80)
    tx.add_stream(0, KEY, SALT)
    pkt = rtp_header.build([b"snap" * 30], [10], [0], [5], [96], stream=[0])
    first = tx.protect_rtp(pkt)
    tx2 = SrtpStreamTable.restore(tx.snapshot())
    pkt2 = rtp_header.build([b"snap" * 30], [11], [0], [5], [96], stream=[0])
    a = tx.protect_rtp(pkt2)
    b = tx2.protect_rtp(pkt2)
    assert a.to_bytes(0) == b.to_bytes(0) != first.to_bytes(0)


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_f8_srtcp_protect_matches_scalar_oracle():
    """Independent scalar SRTCP-f8 protect (RFC 3711 §3.4 + §4.1.2.4)
    written from the RFC, compared byte-for-byte with the batched path."""
    ks = derive_session_keys(KEY, SALT, enc_key_len=16, auth_key_len=20,
                             salt_len=14)
    sr = bytes([0x80, 200, 0, 6]) + (0x7777).to_bytes(4, "big") + \
        bytes(range(24))
    index = 0
    word = (1 << 31) | index                      # E set: encrypting
    iv = bytes(4) + word.to_bytes(4, "big") + sr[:8]
    stream = f8_keystream_np(ks.rtcp_enc, ks.rtcp_salt, iv, len(sr) - 8)
    ct = sr[:8] + bytes(a ^ b for a, b in zip(sr[8:], stream))
    mac_input = ct + word.to_bytes(4, "big")
    tag = pyhmac.new(ks.rtcp_auth, mac_input, hashlib.sha1).digest()[:10]
    want = ct + word.to_bytes(4, "big") + tag

    from libjitsi_tpu.core.packet import PacketBatch
    tx = SrtpStreamTable(capacity=1, profile=SrtpProfile.F8_128_HMAC_SHA1_80)
    tx.add_stream(0, KEY, SALT)
    batch = PacketBatch.from_payloads([sr], capacity=128)
    batch.stream[:] = 0
    prot = tx.protect_rtcp(batch)
    assert prot.to_bytes(0) == want
