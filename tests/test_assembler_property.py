"""Property test: FrameAssembler vs oracle under random loss/reorder/dup.

SURVEY §4's test-strategy analog (same family as the replay-window
property test): drive the assembler with randomized network behavior
and check its output against a straightforward oracle over the ground
truth — delivered frames must be (a) byte-identical to sent frames,
(b) a subset ordered by send time, and (c) complete whenever every
fragment of a frame arrived before any later frame completed.
"""

import numpy as np
import pytest

from libjitsi_tpu.codecs import vp8
from libjitsi_tpu.rtp import header as rtp_header


def _mk_frame(rng, i):
    body = rng.integers(0, 256, int(rng.integers(300, 4000)),
                        dtype=np.uint8).tobytes()
    lead = body[0] & 0xFE if i == 0 else body[0] | 0x01
    return bytes([lead]) + body[1:]


@pytest.mark.parametrize("seed", range(8))
def test_assembler_random_network(seed):
    rng = np.random.default_rng(seed)
    n_frames = 25
    frames = [_mk_frame(rng, i) for i in range(n_frames)]
    # packetize with per-frame ts (wrap-adjacent base to stress unwrap)
    base_ts = 0xFFFFD000 if seed % 2 else int(rng.integers(0, 2**31))
    rows = []                    # (payload, seq, ts, marker, frame_idx)
    seq = int(rng.integers(0, 60000))
    for i, f in enumerate(frames):
        pls = vp8.packetize(f, picture_id=0x4000 | i, max_payload=500)
        for k, p in enumerate(pls):
            rows.append((p, seq & 0xFFFF, (base_ts + i * 3000) & 0xFFFFFFFF,
                         int(k == len(pls) - 1), i))
            seq += 1

    # random network: drop 10%, duplicate 10%, shuffle within a window
    kept = [r for r in rows if rng.random() > 0.10]
    dups = [r for r in kept if rng.random() < 0.10]
    wire = kept + dups
    # windowed reorder: swap neighbors within +-4
    for _ in range(len(wire) // 2):
        a = int(rng.integers(0, len(wire)))
        b = min(len(wire) - 1, a + int(rng.integers(0, 5)))
        wire[a], wire[b] = wire[b], wire[a]

    fa = vp8.FrameAssembler(max_pending=64)
    delivered = []
    for chunk_start in range(0, len(wire), 7):
        chunk = wire[chunk_start:chunk_start + 7]
        if not chunk:
            continue
        pls, seqs, tss, mks, _idx = zip(*chunk)
        fa.push_batch(rtp_header.build(
            list(pls), list(seqs), list(tss), [5] * len(pls),
            [96] * len(pls), marker=list(mks)))
        delivered += fa.pop_frames()

    # oracle: which frames had every fragment survive the drop?
    frags_sent = {}
    for _p, _s, _t, _m, i in rows:
        frags_sent[i] = frags_sent.get(i, 0) + 1
    frags_kept = {}
    for _p, _s, _t, _m, i in kept:
        frags_kept[i] = frags_kept.get(i, 0) + 1
    complete = {i for i in frags_sent
                if frags_kept.get(i, 0) == frags_sent[i]}

    sent_map = {f: i for i, f in enumerate(frames)}
    got_idx = []
    for _ts, _pid, _key, data in delivered:
        assert data in sent_map, "delivered frame is not a sent frame"
        got_idx.append(sent_map[data])
    # (b) strictly increasing send order — never out of order, no dups
    assert got_idx == sorted(set(got_idx))
    # (a+c) everything delivered was complete; and completeness mostly
    # converts to delivery (late completions may be dropped by design,
    # but a frame can only be missing if it was incomplete OR a newer
    # frame completed first — verify delivered ⊆ complete)
    assert set(got_idx) <= complete
    # sanity: the harness isn't vacuous — most complete frames deliver
    if len(complete) >= 5:
        assert len(got_idx) >= len(complete) // 2


def test_assembler_drops_corrupt_seq_span():
    """A forged S-bit/marker pair spanning >MAX_FRAGMENTS seqs must be
    dropped as corrupt, not walked fragment-by-fragment."""
    rng = np.random.default_rng(0)
    frame = _mk_frame(rng, 0)
    pls = vp8.packetize(frame, picture_id=0x4001, max_payload=200)
    assert len(pls) >= 2
    fa = vp8.FrameAssembler()
    # start fragment at seq 100, marker fragment at seq 100+5000: the
    # implied span (5001) is unsatisfiable and hostile
    fa.push_batch(rtp_header.build(
        [pls[0], pls[-1]], [100, (100 + 5000) & 0xFFFF], [7000, 7000],
        [5, 5], [96, 96], marker=[0, 1]))
    assert fa.dropped_corrupt == 1
    assert fa.pop_frames() == []
    assert 7000 not in getattr(fa, "_pending")

    # a sane frame right after still assembles
    seqs = list(range(200, 200 + len(pls)))
    mks = [0] * (len(pls) - 1) + [1]
    fa.push_batch(rtp_header.build(
        pls, seqs, [10000] * len(pls), [5] * len(pls), [96] * len(pls),
        marker=mks))
    got = fa.pop_frames()
    assert len(got) == 1 and got[0][3] == frame


def test_assembler_drops_single_ts_fragment_flood():
    """Unique-seq fragments on one ts with no S/marker pair must be
    bounded by MAX_FRAGMENTS, not accumulate 64k entries."""
    rng = np.random.default_rng(1)
    frame = _mk_frame(rng, 1)
    pls = vp8.packetize(frame, picture_id=0x4002, max_payload=200)
    mid = pls[1] if len(pls) > 2 else pls[0]   # no S-bit, no marker
    fa = vp8.FrameAssembler()
    cap = vp8.FrameAssembler.MAX_FRAGMENTS
    n = cap + 8
    fa.push_batch(rtp_header.build(
        [mid] * n, list(range(n)), [5000] * n, [5] * n, [96] * n,
        marker=[0] * n))
    assert fa.dropped_corrupt >= 1
    assert all(len(s) <= cap for s in fa._pending.values())
