"""G.722 sub-band ADPCM: round-trip quality, batching, embedded modes."""

import numpy as np
import pytest

from libjitsi_tpu.codecs import g722


def _tone(n, freq=1000.0, amp=8000.0, sr=16000):
    return np.round(
        amp * np.sin(2 * np.pi * freq * np.arange(n) / sr)).astype(np.int16)


def _best_snr_db(ref, got, max_lag=40):
    """SNR at the best alignment.  The decoder output is *delayed* by the
    QMF analysis+synthesis group delay (22 samples), so we advance `got`
    and search a few lags to stay robust to off-by-one conventions."""
    best = -np.inf
    ref = ref.astype(np.float64)
    got = got.astype(np.float64)
    for lag in range(max_lag):
        n = min(len(got) - lag, len(ref))
        a, b = ref[:n], got[lag:lag + n]
        a, b = a[800:], b[800:]           # skip adaptation transient
        err = np.mean((a - b) ** 2)
        sig = np.mean(a ** 2)
        if err == 0:
            return np.inf
        best = max(best, 10 * np.log10(sig / err))
    return best


def test_roundtrip_tone_64k():
    pcm = _tone(4000)
    dec = g722.decode(g722.encode(pcm))
    assert len(dec) == len(pcm)
    assert _best_snr_db(pcm, dec) > 20.0


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_roundtrip_speechlike_modes():
    # sum of low tones (speech band) — all three modes intelligible,
    # quality ordered 64k >= 56k >= 48k
    rng = np.random.default_rng(3)
    t = np.arange(6000) / 16000.0
    sig = sum(a * np.sin(2 * np.pi * f * t + p) for f, a, p in
              [(350, 4000, 0.3), (800, 3000, 1.1), (1700, 1500, 2.0)])
    pcm = np.round(sig + rng.normal(0, 30, len(t))).astype(np.int16)
    code = g722.encode(pcm)
    snrs = [_best_snr_db(pcm, g722.decode(code, bits_per_sample=b))
            for b in (8, 7, 6)]
    assert snrs[0] > 18.0 and snrs[1] > 14.0 and snrs[2] > 10.0
    assert snrs[0] >= snrs[1] - 1.0 and snrs[1] >= snrs[2] - 1.0


def test_silence_stays_quiet():
    # ADPCM idle-channel noise is a few LSBs (the quantizer has no
    # zero output level); assert it stays at that floor
    dec = g722.decode(g722.encode(np.zeros(1600, dtype=np.int16)))
    assert np.abs(dec.astype(np.int32)).max() <= 4


def test_batched_matches_single():
    rng = np.random.default_rng(11)
    sigs = [(_tone(640, f)) for f in (440.0, 1000.0, 2500.0)]
    sigs.append(rng.integers(-3000, 3000, 640).astype(np.int16))
    batch = np.stack(sigs)
    enc = g722.G722Encoder(batch=4).encode(batch)
    for i, s in enumerate(sigs):
        assert np.array_equal(enc[i], np.frombuffer(g722.encode(s),
                                                    dtype=np.uint8))
    dec = g722.G722Decoder(batch=4).decode(enc)
    for i in range(4):
        assert np.array_equal(dec[i], g722.decode(enc[i].tobytes()))


def test_streaming_equals_oneshot():
    pcm = _tone(1920, 700.0)
    enc = g722.G722Encoder(1)
    chunks = [enc.encode(pcm[None, i:i + 320]) for i in range(0, 1920, 320)]
    assert np.array_equal(np.concatenate(chunks, axis=1)[0],
                          np.frombuffer(g722.encode(pcm), dtype=np.uint8))
    dec = g722.G722Decoder(1)
    code = np.frombuffer(g722.encode(pcm), dtype=np.uint8).reshape(1, -1)
    parts = [dec.decode(code[:, i:i + 80]) for i in range(0, 960, 80)]
    assert np.array_equal(np.concatenate(parts, axis=1)[0],
                          g722.decode(code[0].tobytes()))


def test_rate_is_one_byte_per_two_samples():
    pcm = _tone(320)
    assert len(g722.encode(pcm)) == 160
