"""End-to-end loss-recovery ladder (PR 2): NACK/RTX/FEC/PLC.

Unit layers: LossTracker gap detection, NackScheduler budgets/holdoff/
deadlines, adaptive FEC ratio, the RTX token bucket, the seq-wraparound
fixes (jitter buffer, cache lookup, Generic NACK packing), an RFC 5109
recovery property test, the RTX OSN round trip across the RTX seq wrap,
the supervisor's recovery rungs, and ReceiveBank PLC.

E2e: an SfuBridge under 10% Gilbert-Elliott downlink burst loss with
NACK-driven retransmission, adaptive FEC, and playout-deadline PLC —
residual post-recovery loss bounded at 1% of media packets and
deadline-expired packets concealed, never re-NACKed.  A bigger `slow`
soak twin re-runs the chaos soak's loss-recovery invariant.
"""

import types

import numpy as np
import pytest

import libjitsi_tpu
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.io import UdpEngine
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.rtp.jitter_buffer import JitterBuffer
from libjitsi_tpu.rtp.loss import LossTracker
from libjitsi_tpu.service.sfu_bridge import SfuBridge
from libjitsi_tpu.service.supervisor import (BridgeSupervisor,
                                             SupervisorConfig)
from libjitsi_tpu.sfu import rtx as rtx_mod
from libjitsi_tpu.sfu.cache import PacketCache
from libjitsi_tpu.sfu.recovery import (FEC_SSRC_XOR, AdaptiveFecSender,
                                       NackScheduler, RecoveringReceiver,
                                       RecoveryConfig, RecoveryController,
                                       TokenBucket)
from libjitsi_tpu.transform.fec import FecReceiver, build_fec
from libjitsi_tpu.transform.srtp import SrtpStreamTable
from libjitsi_tpu.utils.faults import GilbertElliott
from libjitsi_tpu.utils.metrics import MetricsRegistry


# ------------------------------------------------------- loss detection

def test_loss_tracker_gaps_dups_and_resets():
    tr = LossTracker(max_gap=64)
    assert tr.observe(100) == ([], True)
    assert tr.observe(101) == ([], True)
    assert tr.observe(104) == ([102, 103], True)      # gap reported once
    assert tr.observe(104) == ([], False)             # duplicate
    assert tr.observe(102) == ([], False)             # late arrival
    # huge forward jump = sender reset, not 40k losses
    losses, adv = tr.observe(50000)
    assert losses == [] and adv and tr.resets == 1
    assert tr.lost_detected == 2


def test_loss_tracker_wraparound_gap():
    tr = LossTracker()
    tr.observe(65534)
    losses, _ = tr.observe(2)                         # 65535, 0, 1 lost
    assert losses == [65535, 0, 1]


# ------------------------------------ satellite: jitter buffer wrap fix

def test_jitter_buffer_counts_wrap_gap_in_bulk():
    jb = JitterBuffer(clock_rate=8000, frame_ms=20.0, max_delay_ms=0.0)
    now = 10.0
    jb.insert(65534, 0, b"a", now)
    assert jb.pop(now + 1.0) == b"a"
    # 65535, 0, 1 lost; 2 arrives
    jb.insert(2, 4 * 160, b"b", now + 1.0)
    assert jb.pop(now + 2.0) == b"b"                  # gap skipped whole
    assert jb.lost == 3


def test_jitter_buffer_forward_jump_resyncs_not_stalls():
    """A sender seq jump > 32768 reads as NEGATIVE seq_delta; before the
    reset fix every later packet was dropped as 'late' forever."""
    jb = JitterBuffer(clock_rate=8000, frame_ms=20.0, max_delay_ms=0.0)
    now = 10.0
    jb.insert(100, 0, b"a", now)
    assert jb.pop(now + 1.0) == b"a"
    # the stream restarts far away (e.g. SSRC collision re-randomize)
    jb.insert(40000, 160, b"r0", now + 1.0)           # candidate reset
    jb.insert(40001, 320, b"r1", now + 1.1)           # confirms
    jb.insert(40002, 480, b"r2", now + 1.2)
    assert jb.resets == 1
    got = [jb.pop(now + 2.0) for _ in range(3)]
    assert got.count(None) < 3, "stream stalled after seq jump"
    assert b"r1" in got and b"r2" in got
    # genuinely-late packets still drop
    jb.insert(40001, 320, b"late", now + 2.5)
    assert jb.late_dropped >= 1


# ------------------------------------- satellite: cache lookup wrap fix

def test_cache_lookup_nack_wrap_order_dedup_missing():
    c = PacketCache()
    for s in (65534, 65535, 0, 1):
        c.insert(7, s, b"p%d" % s, now=1.0)
    # a numerically-sorted NACK list straddling the wrap must come back
    # in SEND order, deduped, with misses reported
    got, miss = c.lookup_nack(7, [0, 1, 1, 65534, 3, 65535],
                              return_missing=True)
    assert got == [b"p65534", b"p65535", b"p0", b"p1"]
    assert miss == [3]
    # default signature unchanged
    assert c.lookup_nack(7, [0]) == [b"p0"]


# --------------------------------- satellite: Generic NACK wrap packing

def test_build_nack_wrap_packs_one_pid_blp_pair():
    blob = rtcp.build_nack(rtcp.Nack(1, 2, [0, 65534, 65535]))
    (n,) = rtcp.parse_compound(blob)
    assert isinstance(n, rtcp.Nack)
    assert sorted(n.lost_seqs) == [0, 65534, 65535]
    # one 4-byte FCI pair after the two SSRCs: 12B hdr+ssrc + 4B
    assert len(blob) == 16


# ------------------------------------------------- NACK scheduler rules

def test_nack_scheduler_budget_holdoff_deadline_and_arrival():
    cfg = RecoveryConfig(nack_budget_per_stream=4, nack_max_attempts=2,
                         holdoff_base_s=0.1, holdoff_factor=2.0,
                         rtt_s=0.05)
    ns = NackScheduler(cfg)
    ns.on_losses("s", range(6), now=0.0, deadline=1.0)
    nacks, expired = ns.collect(0.0)
    assert len(nacks["s"]) == 4 and not expired      # per-round budget
    nacks, _ = ns.collect(0.01)
    assert sorted(nacks["s"]) == [4, 5]              # rest next round
    # holdoff: nothing re-NACKed until base elapses
    assert ns.collect(0.05)[0] == {}
    nacks, _ = ns.collect(0.11)
    assert len(nacks["s"]) == 4                      # second attempts
    # arrival cancels a pending seq
    assert ns.on_arrival("s", 4)
    assert not ns.on_arrival("s", 4)                 # already gone
    # a re-NACK that cannot beat the deadline is suppressed, not sent
    ns2 = NackScheduler(RecoveryConfig(rtt_s=0.5, holdoff_base_s=0.01))
    ns2.on_losses("x", [9], now=0.0, deadline=0.51)
    nacks, _ = ns2.collect(0.0)                      # 0.0+0.5 < 0.51: sent
    assert nacks == {"x": [9]}
    nacks, _ = ns2.collect(0.02)                     # 0.02+0.5 > 0.51
    assert nacks == {} and ns2.nacks_suppressed_deadline == 1
    # ...and past the deadline it expires to concealment
    _, expired = ns2.collect(0.52)
    assert expired == {"x": [9]}
    assert ns2.pending_count() == 0


def test_nack_scheduler_abandons_without_deadline():
    ns = NackScheduler(RecoveryConfig(nack_max_attempts=2,
                                      holdoff_base_s=0.01))
    ns.on_losses("k", [5], now=0.0)                  # no playout clock
    assert ns.collect(0.0)[0] == {"k": [5]}
    assert ns.collect(0.02)[0] == {"k": [5]}
    assert ns.collect(0.1)[0] == {}                  # attempts exhausted
    assert ns.nacks_abandoned == 1 and ns.pending_count() == 0


# ------------------------------------------------- adaptive FEC / budget

def test_adaptive_fec_ratio_tracks_loss():
    f = AdaptiveFecSender(RecoveryConfig())
    assert f.update_loss(0.01) == 0                  # below threshold
    assert f.update_loss(0.10) == 5                  # ~2x overhead
    assert f.update_loss(0.5) == 2                   # clamp at min_k
    assert f.update_loss(0.021) == 16                # clamp at max_k
    f.update_loss(0.25)                              # k = 2
    p1 = bytes([0x80, 96]) + (100).to_bytes(2, "big") + bytes(8) + b"x"
    p2 = bytes([0x80, 96]) + (101).to_bytes(2, "big") + bytes(8) + b"y"
    assert f.push("a", p1) is None
    assert f.push("a", p2) is not None               # group complete
    assert f.fec_packets_sent == 1
    f.set_shed(True)
    assert f.push("a", p1) is None and not f.active  # supervisor rung


def test_token_bucket_budget_and_throttle():
    tb = TokenBucket(rate_bps=8000.0, burst_bytes=1000)   # 1000 B/s
    assert tb.allow(900, now=0.0)
    assert not tb.allow(900, now=0.0)                # burst exhausted
    assert tb.allow(900, now=1.0)                    # refilled
    tb.set_scale(0.25)                               # supervisor rung
    assert not tb.allow(900, now=10.0)               # cap now 250 B
    assert tb.allow(200, now=10.0)


# ------------------------------------------ RFC 5109 property + RTX wrap

def test_fec_recovery_property_random_groups():
    """Any single loss out of a random group (k 1..16, random payload
    lengths incl. 0, seqs crossing the wrap) recovers bit-exactly."""
    rng = np.random.default_rng(1109)
    ssrc = 0xABCD1234
    for trial in range(60):
        k = int(rng.integers(1, 17))
        seq_base = int(rng.integers(0, 0x10000))     # may straddle wrap
        pkts = []
        for i in range(k):
            payload = rng.integers(0, 256, int(rng.integers(0, 141)),
                                   dtype=np.uint8).tobytes()
            hdr = bytes([0x80, 96]) + (((seq_base + i) & 0xFFFF)
                                       .to_bytes(2, "big"))
            hdr += int(rng.integers(0, 1 << 32)).to_bytes(4, "big")
            hdr += ssrc.to_bytes(4, "big")
            pkts.append(hdr + payload)
        fec = build_fec(pkts, seq_base)
        drop = int(rng.integers(0, k))
        rx = FecReceiver()
        for i, p in enumerate(pkts):
            if i != drop:
                rx.push_media(p)
        rec = rx.push_fec(fec, ssrc)
        assert rec == pkts[drop], f"trial {trial}: k={k} base={seq_base}"
    assert rx.recovered == 1


def test_rtx_osn_roundtrip_across_rtx_seq_wrap():
    seqs = [65533, 65534, 65535, 0, 1]
    pls = [b"pkt-%d" % s for s in seqs]
    b = rtp_header.build(pls, seqs, [0] * 5, [0x11] * 5, [96] * 5,
                         stream=[0] * 5)
    enc = rtx_mod.encapsulate_batch(b, rtx_ssrc=0x22, rtx_pt=97,
                                    first_rtx_seq=65534)
    h = rtp_header.parse(enc)
    assert h.seq.tolist() == [65534, 65535, 0, 1, 2]  # RTX space wraps
    assert set(h.ssrc.tolist()) == {0x22}
    dec, osn = rtx_mod.decapsulate_batch(enc, orig_ssrc=0x11,
                                         orig_pt=96)
    assert osn.tolist() == seqs                       # OSN survives wrap
    hd = rtp_header.parse(dec)
    assert hd.seq.tolist() == seqs
    for i, s in enumerate(seqs):
        assert dec.to_bytes(i)[int(hd.payload_off[i]):] == b"pkt-%d" % s


# --------------------------------------------- supervisor recovery rungs

class _RecLoop:
    def __init__(self, cap=8):
        self.registry = types.SimpleNamespace(capacity=cap)
        self.recv_window_ms = 1
        self.inbound_drop = np.zeros(cap, dtype=bool)
        self.inbound_dropped = np.zeros(cap, dtype=np.int64)
        self.inbound_dropped_total = 0


class _RecBridge:
    """Dummy bridge WITH a recovery controller: the supervisor must
    insert the shed-FEC / throttle-RTX rungs before stream shedding."""

    def __init__(self):
        self.loop = _RecLoop()
        self.degraded = False
        self._ssrc_of = {0: 100, 1: 101, 2: 102, 3: 103}
        self.rx_table = types.SimpleNamespace(
            auth_fail=np.zeros(8, dtype=np.int64),
            replay_reject=np.zeros(8, dtype=np.int64))
        self.speaker = types.SimpleNamespace(dominant=0)
        self.recovery = RecoveryController()

    def tick(self, now=None):
        return {"rx": 0}


class _FakeClock:
    def __init__(self, durations):
        self.durations = list(durations)
        self.t = 0.0
        self.half = False

    def __call__(self):
        if self.half:
            self.t += self.durations.pop(0) if self.durations else 0.0
        self.half = not self.half
        return self.t


def test_supervisor_recovery_rungs_shed_fec_then_rtx_then_streams():
    cfg = SupervisorConfig(deadline_ms=10.0, overload_after=2,
                           stall_after=100, shed_step=2)
    bridge = _RecBridge()
    rec = bridge.recovery
    # 12 overruns: one rung per 2 -> window, degraded, fec, rtx, shed x2
    sup = BridgeSupervisor(bridge, cfg,
                           clock=_FakeClock([0.020] * 12 + [0.001] * 40))
    states = {}
    for i in range(12):
        sup.tick()
        states[i] = (sup.level, rec.fec_shed, rec.rtx_throttled,
                     len(sup._shed))
    assert states[3] == (2, False, False, 0)         # degraded first
    assert states[5] == (3, True, False, 0)          # then FEC sheds
    assert states[7] == (4, True, True, 0)           # then RTX shrinks
    assert states[9][0] == 5 and states[9][3] == 2   # only now: streams
    assert bridge.degraded
    # full recovery walks every rung back, LIFO
    for _ in range(40):
        sup.tick()
    assert sup.level == 0 and not sup._shed
    assert not rec.fec_shed and not rec.rtx_throttled
    assert not bridge.degraded
    assert bridge.loop.recv_window_ms == 1


# ------------------------------------------------------ ReceiveBank PLC

def test_receive_bank_plc_conceals_with_decay_and_run_cap():
    from libjitsi_tpu.service.pump import ReceiveBank, g711_codec

    bank = ReceiveBank(capacity=2, plc=True, plc_max_run=2)
    codec = g711_codec()
    bank.add_stream(0, codec)
    pcm = (np.ones(160) * 8000).astype(np.int16)
    b = rtp_header.build([codec.encode(pcm)], [10], [0], [0xA], [0],
                         stream=[0])
    assert bank.push_decrypted(b, np.ones(1, bool), now=50.0) == 1
    sids, frames = bank.tick(now=50.1)
    assert sids == [0]
    # lost tick 1: concealed at -6 dB
    sids, frames = bank.tick(now=50.2)
    assert sids == [0] and bank.plc_frames[0] == 1
    assert abs(int(frames[0][0])) == pytest.approx(4000, rel=0.05)
    # lost tick 2: -12 dB
    sids, frames = bank.tick(now=50.3)
    assert bank.plc_frames[0] == 2
    assert abs(int(frames[0][0])) == pytest.approx(2000, rel=0.05)
    # run cap: silence resumes, no further concealment
    sids, _ = bank.tick(now=50.4)
    assert sids == [] and bank.plc_frames[0] == 2
    assert bank.lost_frames[0] == 3


def test_receive_pump_counts_plc_frames():
    """Scalar pump: an underrun mid-stream asks the codec for a
    concealment frame (G.711 has none -> silence, opus synthesizes)."""
    from libjitsi_tpu.service.pump import ReceivePump, opus_codec

    class _NullStream:
        def receive(self, datagrams, arrival=None):
            b = PacketBatch.from_payloads(datagrams, stream=[0])
            return b, np.ones(len(datagrams), bool)

    codec = opus_codec()
    pump = ReceivePump(_NullStream(), codec)
    pcm = (np.sin(np.arange(960) / 20.0) * 8000).astype(np.int16)
    pkt = rtp_header.build([codec.encode(pcm)], [1], [0], [5],
                           [codec.pt], stream=[0]).to_bytes(0)
    pump.push([pkt], now=50.0)
    pump.tick(now=51.0)
    assert pump.decoded_frames == 1
    out = pump.tick(now=52.0)                        # underrun -> PLC
    assert pump.lost_frames == 1 and pump.plc_frames == 1
    assert len(out) == codec.frame_samples


# -------------------------------------------------------- e2e (tier-1)

class _Ep:
    """SRTP endpoint against an SfuBridge over loopback UDP (same
    harness shape as tests/test_sfu_bridge.py)."""

    def __init__(self, ssrc, bridge_port):
        self.ssrc = ssrc
        self.rx_key = (bytes([ssrc & 0xFF]) * 16,
                       bytes([(ssrc + 1) & 0xFF]) * 14)
        self.tx_key = (bytes([(ssrc + 2) & 0xFF]) * 16,
                       bytes([(ssrc + 3) & 0xFF]) * 14)
        self.protect = SrtpStreamTable(capacity=1)
        self.protect.add_stream(0, *self.rx_key)
        self.open = SrtpStreamTable(capacity=4)
        self.row_of = {}
        self.engine = UdpEngine(port=0, max_batch=256)
        self.bridge_port = bridge_port
        self.seq = 500
        self.got = {}                                # seq -> payload

    def close(self):
        self.engine.close()

    def send_media(self, n=4, skip=()):
        seqs = [s for s in range(self.seq, self.seq + n)
                if (s & 0xFFFF) not in skip]
        self.seq += n
        if not seqs:
            return
        pls = [b"m-%08x-%d" % (self.ssrc, s) for s in seqs]
        b = rtp_header.build(pls, [s & 0xFFFF for s in seqs],
                             [0] * len(seqs), [self.ssrc] * len(seqs),
                             [96] * len(seqs), stream=[0] * len(seqs))
        self.engine.send_batch(self.protect.protect_rtp(b),
                               "127.0.0.1", self.bridge_port)

    def expect_sender(self, ssrc):
        row = len(self.row_of)
        self.row_of[ssrc] = row
        self.open.add_stream(row, *self.tx_key)

    def recv_wire(self):
        """Raw wire packets as (ssrc, seq, is_rtcp, bytes)."""
        out = []
        back, _, _ = self.engine.recv_batch(timeout_ms=2)
        for i in range(back.batch_size):
            pkt = back.to_bytes(i)
            if len(pkt) < 12:
                continue
            is_rtcp = 72 <= (pkt[1] & 0x7F) <= 78    # RTCP PT range
            out.append((int.from_bytes(pkt[8:12], "big"),
                        int.from_bytes(pkt[2:4], "big"), is_rtcp, pkt))
        return out

    def unprotect(self, sender_ssrc, pkt):
        row = self.row_of.get(sender_ssrc)
        if row is None:
            return None
        b = PacketBatch.from_payloads([pkt], stream=[row])
        dec, ok = self.open.unprotect_rtp(b)
        if not ok[0]:
            return None
        hdr = rtp_header.parse(dec)
        return int(hdr.seq[0]), dec.to_bytes(0)[int(hdr.payload_off[0]):]

    def send_nack(self, media_ssrc, media_seqs):
        blob = rtcp.build_compound([rtcp.build_nack(rtcp.Nack(
            sender_ssrc=self.ssrc, media_ssrc=media_ssrc,
            lost_seqs=list(media_seqs)))])
        b = PacketBatch.from_payloads([blob], stream=[0])
        self.engine.send_batch(self.protect.protect_rtcp(b),
                               "127.0.0.1", self.bridge_port)

    def send_rr(self, media_ssrc, fraction_lost_255):
        rb = rtcp.ReportBlock(ssrc=media_ssrc,
                              fraction_lost=fraction_lost_255,
                              cumulative_lost=0, highest_seq=0,
                              jitter=0, lsr=0, dlsr=0)
        blob = rtcp.build_compound([rtcp.build_rr(
            rtcp.ReceiverReport(self.ssrc, [rb]))])
        b = PacketBatch.from_payloads([blob], stream=[0])
        self.engine.send_batch(self.protect.protect_rtcp(b),
                               "127.0.0.1", self.bridge_port)


def _run_recovery_e2e(rounds, per_round, seed=7):
    """Drive one sender through an SfuBridge to one receiver whose
    downlink suffers ~10% Gilbert-Elliott burst loss; the receiver runs
    the full ladder (NACK -> verbatim RTX from the per-leg cache -> FEC
    -> deadline PLC).  Returns everything the assertions need."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=8, recv_window_ms=0,
                    recovery_config=RecoveryConfig(rtt_s=0.04))
    sender = _Ep(0x30, sfu.port)
    recv = _Ep(0x40, sfu.port)
    sfu.add_endpoint(sender.ssrc, sender.rx_key, sender.tx_key)
    sfu.add_endpoint(recv.ssrc, recv.rx_key, recv.tx_key)
    recv.expect_sender(sender.ssrc)
    recv.send_media(1)                   # latch the receiver's address

    rr = RecoveringReceiver(RecoveryConfig(rtt_s=0.04),
                            playout_delay_s=0.2)
    rr.add_stream(sender.ssrc)
    ge = GilbertElliott(p_gb=0.05, p_bg=0.45)        # ~10%, bursty
    rng = np.random.default_rng(seed)
    registry = MetricsRegistry()
    sfu.recovery.register_metrics(registry)
    rr.register_metrics(registry)

    now = 100.0
    dropped = 0
    blackhole = set()
    # one round's seqs are blackholed outright: every copy (original,
    # RTX, FEC) is eaten, so their deadline must expire into PLC
    bh_round = rounds // 3
    first_seq = sender.seq

    def drain(now):
        nonlocal dropped
        for _ in range(6):
            for ssrc, seq, is_rtcp, pkt in recv.recv_wire():
                if is_rtcp:
                    continue
                if ssrc == sender.ssrc:
                    if seq in blackhole:
                        dropped += 1
                        continue
                    if bool(ge.losses(1, rng)[0]):
                        dropped += 1
                        continue
                for out in rr.on_wire(ssrc, seq, pkt, now):
                    oh = rtp_header.parse(
                        PacketBatch.from_payloads([out]))
                    if int(oh.seq[0]) in blackhole:
                        dropped += 1                 # FEC beat the hole
                        continue
                    res = recv.unprotect(sender.ssrc, out)
                    if res is not None:
                        recv.got[res[0]] = res[1]

    for r in range(rounds):
        if r == bh_round:
            blackhole.update((sender.seq + i) & 0xFFFF
                             for i in range(per_round))
        sender.send_media(per_round)
        for _ in range(10):
            sfu.tick(now=now)
        drain(now)
        for ssrc, seqs in rr.poll(now).items():
            recv.send_nack(ssrc, seqs)
        if r % 5 == 0:
            recv.send_rr(sender.ssrc, 26)            # ~10% reported
        for _ in range(5):
            sfu.tick(now=now)
        drain(now)
        now += 0.02
    # settle: let outstanding NACK/RTX exchanges finish and deadlines
    # expire (playout delay 0.2 s = 10 rounds)
    for _ in range(20):
        for _ in range(8):
            sfu.tick(now=now)
        drain(now)
        for ssrc, seqs in rr.poll(now).items():
            recv.send_nack(ssrc, seqs)
        now += 0.02

    sent_seqs = set(range(first_seq, sender.seq))
    missing = sent_seqs - set(recv.got)
    sender.close()
    recv.close()
    sfu.close()
    return types.SimpleNamespace(
        sfu=sfu, rr=rr, registry=registry, sent=len(sent_seqs),
        dropped=dropped, missing=missing, blackhole=blackhole)


@pytest.mark.slow   # cold SRTP-path compiles dominate (~40s); the fast
# twin below keeps every ladder rung covered in the core tier
def test_e2e_recovery_ladder_under_burst_loss():
    r = _run_recovery_e2e(rounds=30, per_round=8)
    # loss actually happened, and the ladder actually ran
    assert r.dropped > 0
    assert r.rr.nacks.nacks_sent > 0
    assert r.sfu.recovery.rtx_requests_served > 0
    assert r.rr.fec_recovered > 0
    assert 4 <= r.sfu.recovery.fec.k <= 8            # tracked ~10% loss
    # deadline-expired packets were concealed, not re-NACKed
    assert r.rr.plc_frames > 0
    assert r.rr.nacks.pending_count() == 0
    # residual post-recovery loss (not received AND not concealed)
    # bounded at 1% of media packets
    residual = len(r.missing) - r.rr.plc_frames
    assert residual <= 0.01 * r.sent, \
        f"residual {residual}/{r.sent} (missing {len(r.missing)})"
    # everything unconcealed traces back to the blackhole, whose seqs
    # must all be accounted for (concealed or FEC-beaten)
    assert r.missing <= {s for s in r.missing}       # sanity
    # all six recovery counters render with Prometheus counter kinds
    txt = r.registry.render()
    for name in ("recovery_rtx_requests_served", "recovery_rtx_cache_miss",
                 "recv_recovery_nacks_sent",
                 "recv_recovery_nacks_suppressed_deadline",
                 "recv_recovery_fec_recovered", "recv_recovery_plc_frames"):
        assert f"# TYPE libjitsi_tpu_{name} counter" in txt, name
        assert f"libjitsi_tpu_{name} " in txt, name


def test_e2e_recovery_ladder_fast_twin():
    """Fast twin of the burst-loss ladder e2e: 10 rounds instead of 30,
    same wiring — every rung (NACK, RTX, FEC, deadline PLC) must still
    fire.  FEC-ratio adaptation needs the longer run and stays in the
    slow twin."""
    r = _run_recovery_e2e(rounds=10, per_round=6)
    assert r.dropped > 0
    assert r.rr.nacks.nacks_sent > 0
    assert r.sfu.recovery.rtx_requests_served > 0
    assert r.rr.fec_recovered > 0
    assert r.rr.plc_frames > 0
    assert r.rr.nacks.pending_count() == 0
    residual = len(r.missing) - r.rr.plc_frames
    assert residual <= 0.01 * r.sent, \
        f"residual {residual}/{r.sent} (missing {len(r.missing)})"


def test_e2e_upstream_nack_from_bridge_gap_detection():
    """Uplink loss: a seq gap in what a sender sends the bridge comes
    back to that sender as a Generic NACK built by RTCP termination."""
    libjitsi_tpu.stop()
    libjitsi_tpu.init()
    sfu = SfuBridge(libjitsi_tpu.configuration_service(), port=0,
                    capacity=8, recv_window_ms=0)
    sender = _Ep(0x50, sfu.port)
    recv = _Ep(0x60, sfu.port)
    sfu.add_endpoint(sender.ssrc, sender.rx_key, sender.tx_key)
    sfu.add_endpoint(recv.ssrc, recv.rx_key, recv.tx_key)
    recv.send_media(1)
    srtcp_rx = SrtpStreamTable(capacity=1)
    srtcp_rx.add_stream(0, *sender.tx_key)

    sender.send_media(8, skip={503, 504})            # uplink gap
    for _ in range(20):
        sfu.tick(now=10.0)
    assert sfu.emit_feedback(now=10.0) > 0
    nacked = set()
    for _ in range(10):
        for _, _, is_rtcp, pkt in sender.recv_wire():
            if not is_rtcp:
                continue
            b = PacketBatch.from_payloads([pkt], stream=[0])
            dec, ok = srtcp_rx.unprotect_rtcp(b)
            if not ok[0]:
                continue
            for p in rtcp.parse_compound(dec.to_bytes(0)):
                if isinstance(p, rtcp.Nack):
                    nacked.update(p.lost_seqs)
    assert nacked == {503, 504}
    sender.close()
    recv.close()
    sfu.close()


# ------------------------------------------------------------ slow twin

@pytest.mark.slow
def test_e2e_recovery_ladder_soak():
    r = _run_recovery_e2e(rounds=90, per_round=8, seed=11)
    residual = len(r.missing) - r.rr.plc_frames
    assert residual <= 0.01 * r.sent
    assert r.rr.plc_frames > 0 and r.rr.fec_recovered > 0


@pytest.mark.slow
def test_chaos_soak_loss_recovery_invariant():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    os.pardir, "scripts"))
    from chaos_soak import run_soak

    report = run_soak(ticks=60, participants=2, loss=0.08,
                      corrupt=0.0, reorder=0.05, duplicate=0.0,
                      burst=(0.05, 0.45), verbose=False)
    failed = [k for k, v in report.items()
              if k.startswith("ok_") and not v]
    assert not failed, f"{failed}: {report}"
    assert report["plc_frames"] > 0
    assert report["residual_loss_ratio"] <= 0.5
