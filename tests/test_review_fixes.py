"""Regression tests for the round-1 code-review findings."""

import numpy as np
import pytest

from libjitsi_tpu.bwe import SendSideBandwidthEstimation
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import ext as rtp_ext
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.rtp import rtcp
from libjitsi_tpu.rtp.stats import StreamStatsTable
from libjitsi_tpu.transform.dtmf import DtmfTransformEngine
from libjitsi_tpu.transform.header_ext import TransportCCEngine


def test_tcc_lookup_survives_16bit_wrap():
    """Feedback carries 16-bit seqs; lookup must unwrap past 65535."""
    eng = TransportCCEngine(ext_id=5, clock=lambda: 3.0)
    eng.next_seq_ext = 70_000  # counter already past one wrap
    b = rtp_header.build([b"x"], [1], [0], [9], [96], stream=[0])
    eng.rtp_transformer.transform(b)  # sends ext seq 70000
    assert eng.lookup_send_time(70_000 & 0xFFFF) == 3.0
    assert eng.lookup_send_time(123) is None


def test_rtcp_malformed_bodies_skipped():
    # well-framed SR with empty body (length_words=0)
    bad_sr = bytes([0x80, 200, 0, 0])
    # short PLI (body 4B where 8 are required)
    bad_pli = bytes([0x81, 206, 0, 1]) + b"\x00\x00\x00\x07"
    # short NACK
    bad_nack = bytes([0x81, 205, 0, 1]) + b"\x00\x00\x00\x01"
    good = rtcp.build_pli(rtcp.Pli(1, 2))
    got = rtcp.parse_compound(bad_sr + bad_pli + bad_nack + good)
    # no crash, malformed bodies skipped, the good packet recovered
    assert got == [rtcp.Pli(1, 2)]


def test_stats_reset_on_release():
    t = StreamStatsTable(capacity=2)
    t.on_received(np.zeros(3, np.int64), np.array([5, 6, 9]),
                  np.zeros(3), np.full(3, 100), arrival=np.zeros(3))
    t.on_sent(np.zeros(2, np.int64), np.full(2, 50))
    assert t.cumulative_lost(0) == 2
    t.reset(0)
    assert t.rx_packets[0] == 0 and t.tx_packets[0] == 0
    assert t.expected(0) == 0 and t.cumulative_lost(0) == 0
    rb = t.make_report_block(0, remote_ssrc=1, now=0.0)
    assert rb.cumulative_lost == 0 and rb.fraction_lost == 0


def test_dtmf_stop_before_any_send_is_noop():
    eng = DtmfTransformEngine(dtmf_pt=101)
    eng.start_tone(0, "1")
    eng.stop_tone(0)  # no packet sent while the tone was active
    b = rtp_header.build([b"audio"], [1], [0], [9], [96], stream=[0])
    out, ok = eng.rtp_transformer.transform(b)  # must not raise
    assert ok.all()
    assert rtp_header.parse(out).pt[0] == 96  # plain audio, no event


def test_send_side_internal_bitrate_floored():
    ss = SendSideBandwidthEstimation(min_bitrate_bps=30_000,
                                     start_bitrate_bps=100_000)
    for i in range(50):  # sustained heavy loss
        ss.on_receiver_report(200, now_ms=1000 + i * 400)
    assert ss.bitrate >= 30_000
    # prompt recovery: a few clean seconds get back above min quickly
    b = 0
    for i in range(5):
        b = ss.on_receiver_report(0, now_ms=30_000 + i * 1000)
    assert b > 30_000 * 1.2


def test_ext_same_id_different_length_replaces_not_shadows():
    b = rtp_header.build([b"payload"], [1], [0], [9], [96], stream=[0])
    hdr = rtp_header.parse(b)
    out = rtp_ext.set_one_byte_ext(b, hdr, 4,
                                   np.full((1, 3), 0xAA, np.uint8))
    h2 = rtp_header.parse(out)
    # restamp id 4 with a DIFFERENT length
    out2 = rtp_ext.set_one_byte_ext(out, h2, 4,
                                    np.full((1, 2), 0xBB, np.uint8))
    h3 = rtp_header.parse(out2)
    off, ln, found = rtp_ext.find_one_byte_ext(out2, h3, 4)
    assert found.all() and ln[0] == 2
    np.testing.assert_array_equal(out2.data[0, off[0]:off[0] + 2],
                                  [0xBB, 0xBB])
    assert out2.to_bytes(0).endswith(b"payload")


@pytest.mark.slow   # compile-heavy; sibling tests keep core coverage
def test_unprotect_forged_oversize_ext_header_dropped():
    """A packet whose ext_words field claims a header beyond the buffer
    must be dropped by auth, not crash the uniform-offset fast path
    (single-packet batches are trivially offset-uniform)."""
    import numpy as np
    from libjitsi_tpu.core.packet import PacketBatch
    from libjitsi_tpu.transform.srtp import SrtpStreamTable

    rx = SrtpStreamTable(capacity=1)
    rx.add_stream(0, bytes(16), bytes(14))
    raw = bytearray(40)
    raw[0] = 0x90                      # V=2, X=1
    raw[1] = 96
    raw[12:16] = b"\xbe\xde\xff\x00"   # ext_words = 0xff00 -> off >> width
    batch = PacketBatch.from_payloads([bytes(raw)], capacity=64)
    batch.stream[:] = 0
    dec, ok = rx.unprotect_rtp(batch)
    assert not np.asarray(ok).any()


def test_bench_emit_final_line_is_compact_and_parseable(tmp_path):
    """BENCH emit protocol (VERDICT r4 #1): the LAST stdout line must be
    a compact JSON headline that survives a driver tail window, with
    the full record on disk/penultimate line — and emit() must never
    die even when serialization of the live dict races."""
    import json
    import subprocess
    import sys

    code = (
        "import bench, json\n"
        "bench.RESULT['value'] = 2.0e9\n"
        "bench.EXTRA['estimators_pps'] = {'pipelined_median': 2.0e9}\n"
        "bench.RESULT['value'] = round(bench._roofline("
        "'headline', 2.0e9, 632.0, 'model'), 1)\n"
        "bench._aes_consistency_check({'xla_table': 4.0e9})\n"
        "bench.emit()\n")
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               LIBJITSI_TPU_BENCH_DETAIL=str(tmp_path / "detail.json"))
    res = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120,
                         cwd=repo, env=env)
    assert res.returncode == 0, res.stderr[-500:]
    lines = [l for l in res.stdout.splitlines() if l.strip()]
    final = json.loads(lines[-1])            # last line parses
    assert len(lines[-1]) < 2000             # sized for a tail window
    assert final["metric"] == "srtp_protect_pps_at_10k_streams"
    # roofline capped the impossible 2.0B to <= the HBM ceiling, then
    # the AES-core cross-check bounded it further
    assert final["value"] <= 819e9 / 632.0 + 1
    assert final["extra"]["headline_roofline"]["roofline_capped"]
    assert final["extra"]["consistency_vs_aes_core"]["ok"] is False
    # full record parses too (penultimate line)
    json.loads(lines[-2])
