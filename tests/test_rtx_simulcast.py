"""RTX retransmission format (RFC 4588) and simulcast layer forwarding."""

import numpy as np

from libjitsi_tpu.codecs import vp8
from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header
from libjitsi_tpu.sfu import (PacketCache, RtxReceiver, RtxSender,
                              SimulcastForwarder, decapsulate_batch,
                              encapsulate_batch)


def _media_batch(seqs, ssrc=0x1111, pt=96, payloads=None):
    payloads = payloads or [b"payload-%04d" % s for s in seqs]
    return rtp_header.build(payloads, seqs, [s * 90 for s in seqs],
                            [ssrc] * len(seqs), [pt] * len(seqs))


def test_rtx_encapsulate_decapsulate_roundtrip():
    seqs = [100, 101, 65535, 7]
    batch = _media_batch(seqs)
    originals = [batch.to_bytes(i) for i in range(4)]
    rtx = encapsulate_batch(batch, rtx_ssrc=0x2222, rtx_pt=97,
                            first_rtx_seq=500)
    hdr = rtp_header.parse(rtx)
    assert list(hdr.seq) == [500, 501, 502, 503]
    assert all(s == 0x2222 for s in hdr.ssrc)
    assert all(p == 97 for p in hdr.pt)
    assert all(rtx.length[i] == len(originals[i]) + 2 for i in range(4))
    # OSN is the first two payload bytes
    assert rtx.to_bytes(0)[12:14] == bytes([100 >> 8, 100 & 0xFF])

    back, osn = decapsulate_batch(rtx, orig_ssrc=0x1111, orig_pt=96)
    assert list(osn) == seqs
    for i in range(4):
        assert back.to_bytes(i) == originals[i]


def test_rtx_sender_receiver_over_cache():
    cache = PacketCache()
    sent = {}
    for s in (10, 11, 12, 13):
        b = _media_batch([s])
        sent[s] = b.to_bytes(0)
        cache.insert(0x1111, s, sent[s])
    tx = RtxSender(cache, media_ssrc=0x1111, rtx_ssrc=0x2222, rtx_pt=97)
    rtx_batch = tx.on_nack([11, 13, 99])     # 99 is a cache miss
    assert rtx_batch.batch_size == 2 and tx.served == 2

    rx = RtxReceiver()
    rx.add_association(0x2222, 0x1111, 96)
    restored = rx.restore(rtx_batch)
    assert [(s, p) for s, p in restored] == [(11, sent[11]), (13, sent[13])]
    # unknown rtx ssrc ignored
    other = encapsulate_batch(_media_batch([5]), 0x9999, 98, 0)
    assert rx.restore(other) == []
    assert tx.on_nack([99]) is None


def _layer_packet(ssrc, seq, ts, pid, key, fragment=b"x" * 40, start=True,
                  marker=True):
    body = (b"\x00" if key else b"\x01") + fragment
    desc = vp8.build_descriptor(start=start, picture_id=pid | 0x4000)
    # pid | 0x4000 forces 15-bit encoding so rewrite keeps field width
    return rtp_header.build([desc + body], [seq], [ts], [ssrc], [100],
                            marker=[1 if marker else 0])


def test_simulcast_forward_single_layer_continuous():
    fwd = SimulcastForwarder([0xA0, 0xA1, 0xA2], out_ssrc=0xBEEF,
                             initial_layer=0)
    outs = []
    for i in range(4):
        outs += fwd.forward(_layer_packet(0xA0, 100 + i, 3000 * i,
                                          pid=50 + i, key=(i == 0)))
        # other layers' packets are dropped
        assert fwd.forward(_layer_packet(0xA1, 200 + i, 3000 * i,
                                         pid=50 + i, key=(i == 0))) == []
    got = PacketBatch.from_payloads(outs)
    hdr = rtp_header.parse(got)
    assert list(hdr.seq) == [0, 1, 2, 3]             # continuous out space
    assert all(s == 0xBEEF for s in hdr.ssrc)
    desc = vp8.parse_descriptors(got)
    pids = list(desc.picture_id)
    assert pids == [(pids[0] + k) & 0x7FFF for k in range(4)]


def test_simulcast_switch_waits_for_keyframe():
    fwd = SimulcastForwarder([0xA0, 0xA1, 0xA2], out_ssrc=0xBEEF)
    fwd.forward(_layer_packet(0xA0, 100, 0, pid=10, key=True))
    fwd.forward(_layer_packet(0xA0, 101, 3000, pid=11, key=False))
    assert fwd.request_layer(2) is True              # needs upstream PLI
    # delta frames on the target do NOT switch; current layer still flows
    assert fwd.forward(_layer_packet(0xA2, 300, 6000, pid=7,
                                     key=False)) == []
    assert len(fwd.forward(_layer_packet(0xA0, 102, 6000, pid=12,
                                         key=False))) == 1
    assert fwd.awaiting_keyframe
    # keyframe on the target completes the switch
    out = fwd.forward(_layer_packet(0xA2, 301, 9000, pid=8, key=True))
    assert len(out) == 1 and not fwd.awaiting_keyframe
    assert fwd.current_layer == 2 and fwd.switches == 1
    # old layer now dropped; output stays seq- and pid-continuous
    assert fwd.forward(_layer_packet(0xA0, 103, 9000, pid=13,
                                     key=False)) == []
    out2 = fwd.forward(_layer_packet(0xA2, 302, 12000, pid=9, key=False))
    both = PacketBatch.from_payloads(out + out2)
    hdr = rtp_header.parse(both)
    desc = vp8.parse_descriptors(both)
    assert list(hdr.seq)[1] == (list(hdr.seq)[0] + 1) & 0xFFFF
    assert int(desc.picture_id[1]) == (int(desc.picture_id[0]) + 1) & 0x7FFF


def test_simulcast_ts_continuity_across_random_bases():
    """Each layer has its own random RFC 3550 ts base; the output ts
    must stay monotonic across a switch (no arbitrary jump)."""
    fwd = SimulcastForwarder([0xA0, 0xA1], out_ssrc=0xBEEF,
                             ts_switch_step=3000)
    base0, base1 = 0xF0000000, 0x12345678       # wildly different bases
    o1 = fwd.forward(_layer_packet(0xA0, 1, base0, pid=1, key=True))
    o2 = fwd.forward(_layer_packet(0xA0, 2, base0 + 3000, pid=2, key=False))
    fwd.request_layer(1)
    o3 = fwd.forward(_layer_packet(0xA1, 50, base1, pid=9, key=True))
    o4 = fwd.forward(_layer_packet(0xA1, 51, base1 + 3000, pid=10,
                                   key=False))
    got = PacketBatch.from_payloads(o1 + o2 + o3 + o4)
    ts = list(rtp_header.parse(got).ts.astype(np.int64))
    # in-layer spacing preserved exactly; switch gap = ts_switch_step
    assert ts[1] - ts[0] == 3000
    assert ts[2] - ts[1] == 3000
    assert ts[3] - ts[2] == 3000


def test_simulcast_seq_rewrite_preserves_relative_order():
    """Upstream reordering/duplication must survive the rewrite (a
    per-arrival counter would renumber dups as new packets)."""
    fwd = SimulcastForwarder([0xA0], out_ssrc=0xBEEF)
    pkts = {s: _layer_packet(0xA0, s, 0, pid=5, key=True, start=(s == 100),
                             marker=(s == 102))
            for s in (100, 101, 102)}
    outs = []
    for s in (100, 102, 101, 101):               # reorder + duplicate
        outs += fwd.forward(pkts[s])
    hdr = rtp_header.parse(PacketBatch.from_payloads(outs))
    seqs = list(hdr.seq)
    assert seqs[0] == 0 and seqs[1] == 2 and seqs[2] == 1 and seqs[3] == 1


def test_simulcast_rejects_bad_layer():
    import pytest

    fwd = SimulcastForwarder([0xA0, 0xA1, 0xA2], out_ssrc=1)
    with pytest.raises(IndexError):
        fwd.request_layer(3)
    with pytest.raises(IndexError):
        SimulcastForwarder([0xA0], out_ssrc=1, initial_layer=5)


def test_simulcast_rewrite_preserves_frame_content():
    fwd = SimulcastForwarder([0xA0, 0xA1], out_ssrc=0x1234)
    frag = bytes(range(60))
    out = fwd.forward(_layer_packet(0xA0, 7, 0, pid=99, key=True,
                                    fragment=frag))
    got = PacketBatch.from_payloads(out)
    desc = vp8.parse_descriptors(got)
    hdr = rtp_header.parse(got)
    payload = got.to_bytes(0)[int(hdr.payload_off[0] + desc.desc_len[0]):]
    assert payload == b"\x00" + frag                 # content untouched
