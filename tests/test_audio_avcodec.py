"""G.729 / iLBC / G.723.1 decode via the system libavcodec — the rows
recorded as lib-blocked in rounds 1-2 close (decode half) through the
validated avcodec ctypes binding."""

import numpy as np
import pytest

from libjitsi_tpu.codecs.audio_avcodec import (AvAudioDecoder,
                                               audio_decoder_available)

def _need(name):
    if not audio_decoder_available(name):
        pytest.skip(f"libavcodec without the {name} decoder")


@pytest.mark.parametrize("name,frame_bytes,samples", [
    ("g729", 10, 80),        # 10 ms @ 8 kHz
    ("ilbc", 38, 160),       # 20 ms mode
    ("g723_1", 24, 240),     # 6.3 kbit/s 30 ms frames
])
def test_frame_geometry(name, frame_bytes, samples):
    _need(name)
    d = AvAudioDecoder(name)
    rng = np.random.default_rng(7)
    for _ in range(4):
        frame = rng.integers(0, 256, frame_bytes,
                             dtype=np.uint8).tobytes()
        if name == "g723_1":
            # frame type rides the low 2 bits of byte 0: force 6.3k
            frame = bytes([frame[0] & ~0x03]) + frame[1:]
        pcm = d.decode(frame)
        assert pcm.dtype == np.int16 and len(pcm) == samples
    assert d.sample_rate == 8000
    d.close()


def test_deterministic_and_stateful():
    """Same input stream -> same output; the decoder carries state
    across frames (predictors), so a replayed stream matches exactly."""
    _need("g729")
    frames = [bytes([i] * 10) for i in range(6)]
    a, b = AvAudioDecoder("g729"), AvAudioDecoder("g729")
    out_a = np.concatenate([a.decode(f) for f in frames])
    out_b = np.concatenate([b.decode(f) for f in frames])
    assert np.array_equal(out_a, out_b)
    assert np.abs(out_a.astype(np.int64)).max() > 0
    a.close()
    b.close()


def test_bad_frame_is_an_error_not_corruption():
    _need("g729")
    d = AvAudioDecoder("g729")
    with pytest.raises(ValueError):
        d.decode(b"\x01\x02\x03")      # not a whole G.729 frame
    # decoder still usable afterwards
    assert len(d.decode(bytes(10))) == 80
    d.close()


def test_g729_sid_frames_are_silence_not_errors():
    """RFC 3551 Annex B comfort-noise frames (2 bytes) appear in any
    VAD-enabled G.729 stream: they yield empty PCM, not a crash."""
    _need("g729")
    d = AvAudioDecoder("g729")
    assert len(d.decode(bytes(10))) == 80
    assert len(d.decode(b"\x12\x34")) == 0     # SID -> DTX gap
    assert len(d.decode(bytes(10))) == 80      # stream continues
    d.close()


def test_g729_multiframe_rtp_payload():
    """RFC 3551: a 20 ms G.729 RTP payload is two 10-byte frames (plus
    an optional SID tail) -> 160 samples."""
    _need("g729")
    d = AvAudioDecoder("g729")
    assert len(d.decode_payload(bytes(20))) == 160
    assert len(d.decode_payload(bytes(20) + b"\x11\x22")) == 160
    d.close()


def test_ilbc_30ms_mode_refused_not_misdecoded():
    _need("ilbc")
    with pytest.raises(RuntimeError):
        AvAudioDecoder("ilbc", ilbc_mode_ms=30)


def test_g729_receive_only_leg_through_receive_bank():
    """The decode-only codecs plug into the dense receive plane: a
    G.729 stream lands in a ReceiveBank row, decodes per tick, and
    deposits into the mixer; the encode direction refuses loudly."""
    _need("g729")
    from libjitsi_tpu.conference.mixer import AudioMixer
    from libjitsi_tpu.rtp import header as rtp_header
    from libjitsi_tpu.service.pump import ReceiveBank, g729_rx_codec

    codec = g729_rx_codec()
    with pytest.raises(RuntimeError):
        codec.encode(np.zeros(160, np.int16))

    mixer = AudioMixer(capacity=4, frame_samples=160)
    bank = ReceiveBank(4, mixer=mixer, mixer_rate=8000)
    bank.add_stream(1, codec)
    mixer.add_participant(1)
    now = 30.0
    for k in range(4):
        b = rtp_header.build([bytes(20)], [600 + k], [160 * k],
                             [0xAA] * 1, [18], stream=[1])
        bank.push_decrypted(b, np.ones(1, bool), now=now + k * 0.02)
    sids, pcms = bank.tick(now=now + 0.081)
    assert 1 in sids
    assert len(pcms[sids.index(1)]) == 160
    assert bank.decoded_frames[1] >= 1
