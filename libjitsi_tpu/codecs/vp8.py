"""VP8 RTP payload descriptor handling (RFC 7741) — vectorized.

Rebuilds `org.jitsi.impl.neomedia.codec.video.vp8.DePacketizer`'s header
logic (the part BASELINE config #4 needs — simulcast layer bookkeeping):
payload-descriptor parse (X/N/S/PID, PictureID, TL0PICIDX, TID/KEYIDX),
keyframe detection from the VP8 payload header P bit, and frame-start
accounting, all as batched array ops over a PacketBatch.  Actual VP8
bitstream decode stays on libvpx (host, verification only).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from libjitsi_tpu.core.packet import PacketBatch
from libjitsi_tpu.rtp import header as rtp_header


@dataclasses.dataclass
class Vp8Descriptors:
    """Parsed per-row VP8 payload descriptor fields (-1 where absent)."""

    desc_len: np.ndarray      # descriptor size in bytes
    start_of_partition: np.ndarray  # S bit
    partition_id: np.ndarray  # PID
    picture_id: np.ndarray    # 7/15-bit, -1 if no I
    tl0picidx: np.ndarray     # -1 if no L
    tid: np.ndarray           # temporal layer, -1 if no T
    keyidx: np.ndarray        # -1 if no K
    is_keyframe: np.ndarray   # bool: S, PID 0 and payload P bit == 0
    valid: np.ndarray


def parse_descriptors(batch: PacketBatch, hdr=None) -> Vp8Descriptors:
    """Vectorized RFC 7741 §4.2 parse over the batch's RTP payloads.

    Pass pre-parsed RTP headers via `hdr` to avoid re-parsing on paths
    that already have them (the SFU forwarder parses once per batch).
    """
    if hdr is None:
        hdr = rtp_header.parse(batch)
    d = batch.data
    n, cap = d.shape
    ln = np.asarray(batch.length, dtype=np.int64)
    off = hdr.payload_off.astype(np.int64)

    def byte_at(pos):
        return rtp_header.byte_at(d, pos)

    b0 = byte_at(off)
    x = (b0 >> 7) & 1
    s = (b0 >> 4) & 1
    pid = b0 & 0x07
    cur = off + 1

    xb = np.where(x == 1, byte_at(cur), 0)
    cur = cur + x  # X byte present
    i_bit = (xb >> 7) & 1
    l_bit = (xb >> 6) & 1
    t_bit = (xb >> 5) & 1
    k_bit = (xb >> 4) & 1

    pic_b0 = byte_at(cur)
    m = (pic_b0 >> 7) & 1  # 15-bit picture id
    pic7 = pic_b0 & 0x7F
    pic15 = ((pic_b0 & 0x7F) << 8) | byte_at(cur + 1)
    picture_id = np.where(i_bit == 1, np.where(m == 1, pic15, pic7), -1)
    cur = cur + np.where(i_bit == 1, 1 + m, 0)

    tl0 = np.where(l_bit == 1, byte_at(cur), -1)
    cur = cur + l_bit

    tk = byte_at(cur)
    has_tk = ((t_bit == 1) | (k_bit == 1)).astype(np.int64)
    tid = np.where((t_bit == 1) & (has_tk == 1), (tk >> 6) & 0x03, -1)
    keyidx = np.where((k_bit == 1) & (has_tk == 1), tk & 0x1F, -1)
    cur = cur + has_tk

    desc_len = cur - off
    # VP8 payload header P bit (inverse keyframe flag), RFC 7741 §4.3
    p_bit = byte_at(cur) & 0x01
    is_key = (s == 1) & (pid == 0) & (p_bit == 0)
    valid = (ln > off) & (off + desc_len < ln) & (hdr.valid)

    return Vp8Descriptors(
        desc_len=desc_len.astype(np.int32),
        start_of_partition=s.astype(np.int32),
        partition_id=pid.astype(np.int32),
        picture_id=picture_id.astype(np.int64),
        tl0picidx=tl0.astype(np.int64),
        tid=tid.astype(np.int32),
        keyidx=keyidx.astype(np.int32),
        is_keyframe=(is_key & valid),
        valid=valid,
    )


def build_descriptor(start: bool, picture_id: int = -1, tl0picidx: int = -1,
                     tid: int = -1, keyidx: int = -1) -> bytes:
    """Packetizer counterpart (reference: vp8.Packetizer) — one-byte
    required part + optional extensions."""
    need_x = picture_id >= 0 or tl0picidx >= 0 or tid >= 0 or keyidx >= 0
    b0 = (0x10 if start else 0) | (0x80 if need_x else 0)
    out = bytearray([b0])
    if need_x:
        xb = ((0x80 if picture_id >= 0 else 0)
              | (0x40 if tl0picidx >= 0 else 0)
              | (0x20 if tid >= 0 else 0)
              | (0x10 if keyidx >= 0 else 0))
        out.append(xb)
        if picture_id >= 0:
            if picture_id > 0x7F:
                out += bytes([0x80 | (picture_id >> 8), picture_id & 0xFF])
            else:
                out.append(picture_id)
        if tl0picidx >= 0:
            out.append(tl0picidx & 0xFF)
        if tid >= 0 or keyidx >= 0:
            out.append(((tid & 0x03) << 6 if tid >= 0 else 0)
                       | (keyidx & 0x1F if keyidx >= 0 else 0))
    return bytes(out)


class SimulcastReceiver:
    """Per-(ssrc-layer) frame bookkeeping for 3-layer VP8 simulcast
    (reference: MediaStreamTrackDesc/RTPEncodingDesc/FrameDesc).

    Tracks, per spatial layer: latest picture id, TL0PICIDX continuity,
    keyframe seen, and frame starts — what the SFU's layer-selection
    logic needs before forwarding."""

    def __init__(self, layer_ssrcs):
        self.layer_of = {int(s) & 0xFFFFFFFF: i
                         for i, s in enumerate(layer_ssrcs)}
        n = len(layer_ssrcs)
        self.last_picture_id = np.full(n, -1, dtype=np.int64)
        self.last_tl0 = np.full(n, -1, dtype=np.int64)
        self.keyframe_seen = np.zeros(n, dtype=bool)
        self.frames = np.zeros(n, dtype=np.int64)

    def ingest(self, batch: PacketBatch, hdr=None,
               desc: "Vp8Descriptors" = None) -> Vp8Descriptors:
        if hdr is None:
            hdr = rtp_header.parse(batch)
        if desc is None:
            desc = parse_descriptors(batch, hdr=hdr)
        for i in range(batch.batch_size):
            if not desc.valid[i]:
                continue
            layer = self.layer_of.get(int(hdr.ssrc[i]))
            if layer is None:
                continue
            if desc.start_of_partition[i] == 1 and desc.partition_id[i] == 0:
                self.frames[layer] += 1
                if desc.picture_id[i] >= 0:
                    self.last_picture_id[layer] = desc.picture_id[i]
                if desc.tl0picidx[i] >= 0:
                    self.last_tl0[layer] = desc.tl0picidx[i]
                if desc.is_keyframe[i]:
                    self.keyframe_seen[layer] = True
        return desc

    def select_layer(self, target_bps: float, layer_rates) -> int:
        """Highest layer whose rate fits the target and has a keyframe."""
        best = 0
        for i, r in enumerate(layer_rates):
            if r <= target_bps and self.keyframe_seen[i]:
                best = i
        return best


def packetize(frame: bytes, picture_id: int = -1,
              max_payload: int = 1200, tl0picidx: int = -1,
              tid: int = -1) -> list:
    """Split one VP8 frame into RTP payloads (descriptor + fragment).

    Reference: `...codec.video.vp8.Packetizer` — S bit set on the first
    fragment only; every fragment of a frame carries the same extension
    fields; the RTP marker (set by the sender on the last fragment) ends
    the frame.
    """
    if not frame:
        raise ValueError("empty frame")
    # descriptor length is the same for every fragment (the S bit does
    # not change the size), so budget it out of max_payload up front —
    # emitted payloads must not exceed the caller's MTU allowance
    desc_len = len(build_descriptor(start=True, picture_id=picture_id,
                                    tl0picidx=tl0picidx, tid=tid))
    chunk = max_payload - desc_len
    if chunk <= 0:
        raise ValueError(f"max_payload {max_payload} cannot fit the "
                         f"{desc_len}-byte descriptor")
    out = []
    for pos in range(0, len(frame), chunk):
        desc = build_descriptor(start=(pos == 0), picture_id=picture_id,
                                tl0picidx=tl0picidx, tid=tid)
        out.append(desc + frame[pos:pos + chunk])
    return out


class FrameAssembler:
    """Reassemble complete VP8 frames from depacketized RTP.

    Reference: the DePacketizer's frame-reassembly half — fragments
    share an RTP timestamp; the S-bit fragment starts the frame, the
    marker-bit fragment ends it, and the frame is complete when every
    sequence number in between has arrived (out-of-order tolerant).
    `push_batch` ingests a decrypted PacketBatch; `pop_frames` yields
    (rtp_ts, picture_id, is_keyframe, frame_bytes) in timestamp order.
    """

    # A 16 KiB-fragment frame at 512 fragments is ~8 MB — far beyond any
    # real VP8 frame.  Larger start→end seq spans can only come from
    # corrupt/hostile S-bit/marker packets; without the bound a forged
    # span of up to 65536 makes every _is_complete call walk the span
    # (quadratic across calls) before eviction engages.
    MAX_FRAGMENTS = 512

    def __init__(self, max_pending: int = 32):
        self.max_pending = max_pending
        # keys are UNWRAPPED timestamps (the 32-bit RTP ts starts at a
        # random value and wraps within hours — minutes under loss —
        # so min()/sorted() over raw values would misorder across the
        # wrap and evict the wrong frames)
        self._pending: dict = {}      # uts -> {seq: payload}
        self._meta: dict = {}         # uts -> [start_seq, end_seq, pid, key]
        self._ts_high: int = 0        # unwrap epoch (multiples of 2^32)
        self._ts_last: int = -1       # last wire ts seen
        self._delivered_ts: int = -1  # newest uts handed to the caller
        self.dropped_incomplete = 0   # evicted waiting on lost packets
        self.dropped_backlog = 0      # complete but never popped (4x cap)
        self.dropped_late = 0         # completed after a newer delivery
        self.dropped_corrupt = 0      # start→end span > MAX_FRAGMENTS

    def _unwrap_ts(self, ts: int) -> int:
        if self._ts_last >= 0:
            delta = (ts - self._ts_last) & 0xFFFFFFFF
            if delta < 0x80000000:            # forward move
                if ts < self._ts_last:        # wrapped past zero
                    self._ts_high += 1 << 32
            elif ts > self._ts_last:          # backward move across wrap
                return self._ts_high - (1 << 32) + ts
        self._ts_last = ts
        return self._ts_high + ts

    def push_batch(self, batch: PacketBatch) -> None:
        hdr = rtp_header.parse(batch)
        desc = parse_descriptors(batch)
        for i in range(batch.batch_size):
            if not desc.valid[i]:
                continue
            ts = self._unwrap_ts(int(hdr.ts[i]))
            seq = int(hdr.seq[i])
            frag = batch.to_bytes(i)[int(hdr.payload_off[i]
                                         + desc.desc_len[i]):]
            slot = self._pending.setdefault(ts, {})
            meta = self._meta.setdefault(ts, [None, None, -1, False])
            slot[seq] = frag
            if len(slot) > self.MAX_FRAGMENTS:
                # fragment flood on one ts (unique seqs, no S/marker pair
                # to trip the span check): a real frame never has this
                # many fragments, so drop the whole entry
                del self._pending[ts]
                del self._meta[ts]
                self.dropped_corrupt += 1
                continue
            if desc.start_of_partition[i] == 1 and desc.partition_id[i] == 0:
                meta[0] = seq
                meta[2] = int(desc.picture_id[i])
                meta[3] = bool(desc.is_keyframe[i])
            if hdr.marker[i]:
                meta[1] = seq
            if (meta[0] is not None and meta[1] is not None
                    and ((meta[1] - meta[0]) & 0xFFFF) + 1
                    > self.MAX_FRAGMENTS):
                del self._pending[ts]
                del self._meta[ts]
                self.dropped_corrupt += 1
                continue
        # bound memory two-tier: INCOMPLETE frames older than the newest
        # entry (stalled gaps) evict oldest-first at max_pending — the
        # newest frame is still arriving and is never a victim below the
        # cap; COMPLETE frames, which a burst can accumulate faster than
        # the caller pops, only give way at a 4x hard cap (counted
        # separately: that is caller backlog, not packet loss).
        if len(self._pending) > self.max_pending:
            ordered = sorted(self._pending)
            for t in ordered[:-1]:
                if len(self._pending) <= self.max_pending:
                    break
                if not self._is_complete(t):
                    del self._pending[t]
                    del self._meta[t]
                    self.dropped_incomplete += 1
            while len(self._pending) > 4 * self.max_pending:
                t = min(self._pending)
                del self._pending[t]
                del self._meta[t]
                self.dropped_backlog += 1

    def _is_complete(self, ts: int) -> bool:
        start, end, _pid, _key = self._meta[ts]
        if start is None or end is None:
            return False
        n = ((end - start) & 0xFFFF) + 1
        if n > self.MAX_FRAGMENTS:    # corrupt span; never completes
            return False
        slot = self._pending[ts]
        return all(((start + k) & 0xFFFF) in slot for k in range(n))

    def pop_frames(self) -> list:
        done = []
        for ts in sorted(self._pending):
            if not self._is_complete(ts):
                continue
            start, end, pid, key = self._meta[ts]
            slot = self._pending[ts]
            del self._pending[ts]
            del self._meta[ts]
            if ts <= self._delivered_ts:
                # completed only after a newer frame was already handed
                # out — delivering it now would feed the decoder frames
                # backwards; drop it (the decoder PLCs the gap)
                self.dropped_late += 1
                continue
            n = ((end - start) & 0xFFFF) + 1
            done.append((ts, pid, key,
                         b"".join(slot[(start + k) & 0xFFFF]
                                  for k in range(n))))
            self._delivered_ts = ts
        return done
