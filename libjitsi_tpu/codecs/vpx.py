"""VP8/VP9 bitstream encode/decode via ctypes on the system libvpx.

Rebuilds the JNI surface of the reference's
`org.jitsi.impl.neomedia.codec.video.VPX` (+ `src/native/vpx`): codec
context init, frame encode to compressed packets, packet decode to
I420 planes.  Per SURVEY §2.6 item 4 this is the host-side libvpx
binding (video bitstream coding has no TPU analog in scope); it exists
to author/verify real VP8 media for the RTP/SFU path (BASELINE config
#4) and for the recording sink.

ABI note: libvpx's init entry points take an ABI version constant that
changes across releases.  Rather than hard-code one, `_probe_abi`
tries versions until init succeeds — the same role as the reference's
configure-time version check, done at runtime because we bind whatever
libvpx.so the image ships.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Iterator, List, Optional, Tuple

import numpy as np

_lib = None

VPX_CODEC_OK = 0
_VPX_IMG_FMT_PLANAR = 0x100
VPX_IMG_FMT_I420 = _VPX_IMG_FMT_PLANAR | 2
_VPX_DL_REALTIME = 1
_VPX_CODEC_CX_FRAME_PKT = 0
VPX_FRAME_IS_KEY = 0x1
_CTX_SIZE = 256          # opaque vpx_codec_ctx_t (real one is ~56 bytes)
_CFG_SIZE = 4096         # opaque vpx_codec_enc_cfg_t (~1 KiB with layers)


class _VpxImage(ctypes.Structure):
    """vpx_image_t prefix (vpx/vpx_image.h; stable across 1.x)."""

    _fields_ = [
        ("fmt", ctypes.c_int),
        ("cs", ctypes.c_int),
        ("range", ctypes.c_int),
        ("w", ctypes.c_uint),
        ("h", ctypes.c_uint),
        ("bit_depth", ctypes.c_uint),
        ("d_w", ctypes.c_uint),
        ("d_h", ctypes.c_uint),
        ("r_w", ctypes.c_uint),
        ("r_h", ctypes.c_uint),
        ("x_chroma_shift", ctypes.c_uint),
        ("y_chroma_shift", ctypes.c_uint),
        ("planes", ctypes.c_void_p * 4),
        ("stride", ctypes.c_int * 4),
        ("bps", ctypes.c_int),
        ("user_priv", ctypes.c_void_p),
        ("img_data", ctypes.c_void_p),
        ("img_data_owner", ctypes.c_int),
        ("self_allocd", ctypes.c_int),
        ("fb_priv", ctypes.c_void_p),
    ]


class _CxPkt(ctypes.Structure):
    """vpx_codec_cx_pkt_t frame variant prefix.

    The union after `kind` starts at pointer alignment, so the pad
    between them is pointer-size dependent — computed, not hard-coded
    (on ILP32 there is no pad at all).
    """

    _fields_ = ([("kind", ctypes.c_int)]
                + ([("_pad", ctypes.c_int)]
                   if ctypes.sizeof(ctypes.c_void_p) == 8 else [])
                + [
        ("buf", ctypes.c_void_p),
        ("sz", ctypes.c_size_t),
        ("pts", ctypes.c_int64),
        ("duration", ctypes.c_ulong),
        ("flags", ctypes.c_uint),
        ("partition_id", ctypes.c_int),
    ])


def _load():
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("vpx") or "libvpx.so.7"
    lib = ctypes.CDLL(name)
    for f in ("vpx_codec_vp8_cx", "vpx_codec_vp8_dx",
              "vpx_codec_vp9_cx", "vpx_codec_vp9_dx"):
        getattr(lib, f).restype = ctypes.c_void_p
    lib.vpx_codec_enc_config_default.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint]
    lib.vpx_codec_enc_init_ver.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long,
        ctypes.c_int]
    lib.vpx_codec_dec_init_ver.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_long,
        ctypes.c_int]
    lib.vpx_codec_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_ulong,
        ctypes.c_long, ctypes.c_ulong]
    lib.vpx_codec_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint, ctypes.c_void_p,
        ctypes.c_long]
    lib.vpx_codec_get_cx_data.restype = ctypes.POINTER(_CxPkt)
    lib.vpx_codec_get_cx_data.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_void_p)]
    lib.vpx_codec_get_frame.restype = ctypes.POINTER(_VpxImage)
    lib.vpx_codec_get_frame.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_void_p)]
    lib.vpx_img_alloc.restype = ctypes.POINTER(_VpxImage)
    lib.vpx_img_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                  ctypes.c_uint, ctypes.c_uint,
                                  ctypes.c_uint]
    lib.vpx_img_free.argtypes = [ctypes.POINTER(_VpxImage)]
    lib.vpx_codec_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def vpx_available() -> bool:
    try:
        _load()
        return True
    except (OSError, AttributeError):
        # AttributeError: lib present but built without vp8/vp9 symbols
        return False


def _probe_abi(init, *args) -> Tuple[int, bytearray]:
    """Find the installed lib's ABI version constant by trial init."""
    for ver in range(6, 40):
        ctx = ctypes.create_string_buffer(_CTX_SIZE)
        if init(ctx, *args, ver) == VPX_CODEC_OK:
            return ver, ctx
    raise RuntimeError("no libvpx ABI version in 6..39 accepted init")


class VpxDecoder:
    """Decode VP8/VP9 packets to I420 planes (the verification path)."""

    def __init__(self, codec: str = "vp8"):
        lib = _load()
        iface = {"vp8": lib.vpx_codec_vp8_dx,
                 "vp9": lib.vpx_codec_vp9_dx}[codec]()
        _, self._ctx = _probe_abi(
            lambda c, v: lib.vpx_codec_dec_init_ver(c, iface, None, 0, v))

    def decode(self, packet: bytes) -> List[Tuple[np.ndarray, np.ndarray,
                                                  np.ndarray]]:
        """Returns decoded frames as (y, u, v) uint8 arrays."""
        lib = _load()
        if lib.vpx_codec_decode(self._ctx, packet, len(packet),
                                None, 0) != VPX_CODEC_OK:
            raise RuntimeError("vpx_codec_decode failed")
        out = []
        it = ctypes.c_void_p(None)
        while True:
            img = lib.vpx_codec_get_frame(self._ctx, ctypes.byref(it))
            if not img:
                break
            out.append(_image_to_planes(img.contents))
        return out

    def close(self) -> None:
        _load().vpx_codec_destroy(self._ctx)


def _image_to_planes(im: _VpxImage):
    def plane(idx, w, h):
        stride = im.stride[idx]
        buf = (ctypes.c_ubyte * (stride * h)).from_address(im.planes[idx])
        return np.ctypeslib.as_array(buf).reshape(h, stride)[:, :w].copy()

    w, h = im.d_w, im.d_h
    cw = (w + (1 << im.x_chroma_shift) - 1) >> im.x_chroma_shift
    ch = (h + (1 << im.y_chroma_shift) - 1) >> im.y_chroma_shift
    return plane(0, w, h), plane(1, cw, ch), plane(2, cw, ch)


def _drain_packets(lib, ctx) -> List[Tuple[bytes, bool]]:
    out: List[Tuple[bytes, bool]] = []
    it = ctypes.c_void_p(None)
    while True:
        pkt = lib.vpx_codec_get_cx_data(ctx, ctypes.byref(it))
        if not pkt:
            return out
        p = pkt.contents
        if p.kind == _VPX_CODEC_CX_FRAME_PKT:
            out.append((ctypes.string_at(p.buf, p.sz),
                        bool(p.flags & VPX_FRAME_IS_KEY)))


# vpx_codec_enc_cfg_t field offsets (vpx/vpx_encoder.h, stable in 1.x)
_CFG_G_W = 12
_CFG_G_H = 16
_CFG_G_TIMEBASE_NUM = 28
_CFG_G_TIMEBASE_DEN = 32


class VpxEncoder:
    """Encode I420 frames to VP8/VP9 packets (fixture authoring path)."""

    def __init__(self, width: int, height: int, codec: str = "vp8",
                 fps: int = 30):
        lib = _load()
        self._iface = {"vp8": lib.vpx_codec_vp8_cx,
                       "vp9": lib.vpx_codec_vp9_cx}[codec]()
        self.width, self.height = width, height
        cfg = ctypes.create_string_buffer(_CFG_SIZE)
        if lib.vpx_codec_enc_config_default(self._iface, cfg, 0) \
                != VPX_CODEC_OK:
            raise RuntimeError("vpx enc_config_default failed")
        # The offsets below are patched blind, so validate the layout
        # first: libvpx 1.x's defaults at those offsets are g_w=320,
        # g_h=240, g_timebase=1/30.  A build whose cfg prefix differs
        # must fail loudly here, not encode at silently wrong
        # dimensions/timebase.
        def _peek(off: int) -> int:
            return ctypes.c_uint.from_buffer_copy(cfg, off).value
        got = (_peek(_CFG_G_W), _peek(_CFG_G_H),
               _peek(_CFG_G_TIMEBASE_NUM), _peek(_CFG_G_TIMEBASE_DEN))
        if got != (320, 240, 1, 30):
            raise RuntimeError(
                f"vpx_codec_enc_cfg_t layout mismatch: defaults at "
                f"g_w/g_h/g_timebase offsets read {got}, want "
                "(320, 240, 1, 30); refusing to patch raw offsets")
        for off, val in ((_CFG_G_W, width), (_CFG_G_H, height),
                         (_CFG_G_TIMEBASE_NUM, 1),
                         (_CFG_G_TIMEBASE_DEN, fps)):
            ctypes.memmove(ctypes.addressof(cfg) + off,
                           bytes(ctypes.c_uint(val)), 4)
        _, self._ctx = _probe_abi(
            lambda c, v: lib.vpx_codec_enc_init_ver(c, self._iface, cfg,
                                                    0, v))
        self._pts = 0

    def encode(self, y: np.ndarray, u: np.ndarray, v: np.ndarray
               ) -> List[Tuple[bytes, bool]]:
        """Encode one I420 frame; returns [(packet, is_keyframe)]."""
        lib = _load()
        img = lib.vpx_img_alloc(None, VPX_IMG_FMT_I420, self.width,
                                self.height, 1)
        if not img:
            raise RuntimeError("vpx_img_alloc failed")
        try:
            im = img.contents
            cw = (self.width + 1) >> 1
            ch = (self.height + 1) >> 1
            expect = {0: (self.height, self.width), 1: (ch, cw),
                      2: (ch, cw)}
            for idx, plane in ((0, y), (1, u), (2, v)):
                p = np.asarray(plane, dtype=np.uint8)
                if p.shape != expect[idx]:
                    # writing past the plane allocation would corrupt
                    # the heap silently — fail as a Python error instead
                    raise ValueError(
                        f"plane {idx} shape {p.shape} != {expect[idx]}")
                h, w = p.shape
                stride = im.stride[idx]
                dst = (ctypes.c_ubyte * (stride * h)).from_address(
                    im.planes[idx])
                arr = np.ctypeslib.as_array(dst).reshape(h, stride)
                arr[:, :w] = p
            if lib.vpx_codec_encode(self._ctx, img, self._pts, 1, 0,
                                    _VPX_DL_REALTIME) != VPX_CODEC_OK:
                raise RuntimeError("vpx_codec_encode failed")
            self._pts += 1
        finally:
            lib.vpx_img_free(img)
        return _drain_packets(lib, self._ctx)

    def flush(self) -> List[Tuple[bytes, bool]]:
        """Drain lookahead-lagged packets (VP9 defaults to a multi-frame
        lag; VP8's default lag is 0 so this is usually empty there)."""
        lib = _load()
        out: List[Tuple[bytes, bool]] = []
        while True:
            if lib.vpx_codec_encode(self._ctx, None, self._pts, 1, 0,
                                    _VPX_DL_REALTIME) != VPX_CODEC_OK:
                raise RuntimeError("vpx_codec_encode(flush) failed")
            got = _drain_packets(lib, self._ctx)
            if not got:
                return out
            out += got

    def close(self) -> None:
        _load().vpx_codec_destroy(self._ctx)
