"""H.264 bitstream codec via libavcodec (ctypes; no pybind11 in image).

Parity target: the reference's `...codec.video.h264.{JNIEncoder,
JNIDecoder}` over `src/native/ffmpeg` (SURVEY §2.5) — here a ctypes
binding to the system libavcodec 59 (FFmpeg 5.x): encode through
libx264, decode through the native h264 decoder.  RFC 6184
packetization lives in `codecs.h264`; this module is the bitstream
half the round-1 review flagged as missing.

ABI strategy (same doctrine as `codecs.vpx`): every struct field this
module pokes is validated at runtime before use —

- AVCodecContext is configured ONLY through the AVOptions API
  (`av_opt_set_image_size` / `_pixel_fmt` / `_q` / `av_opt_set`), which
  is name-based and version-stable; no context offsets at all.
- AVFrame/AVPacket use the FFmpeg 5.x prefix layout (data[8], then
  linesize[8], extended_data, width, height, nb_samples, format;
  packet: buf, pts, dts, data, size).  A freshly allocated AVFrame must
  read width=0, height=0, format=-1 at those offsets and a probe
  av_new_packet must read back its size — otherwise the module refuses
  to run rather than corrupt memory.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Tuple

import numpy as np

_AV_PIX_FMT_YUV420P = 0
_AVERROR_EAGAIN = -11      # AVERROR(EAGAIN) on Linux
_AVERROR_EOF = -0x20464F45  # FFERRTAG('E','O','F',' ') as AVERROR

# FFmpeg 5.x AVFrame prefix offsets
_F_DATA, _F_LINESIZE = 0, 64
_F_W, _F_H, _F_FMT = 104, 108, 116
# FFmpeg 5.x AVPacket prefix offsets
_P_DATA, _P_SIZE = 24, 32


class _Q(ctypes.Structure):
    _fields_ = [("num", ctypes.c_int), ("den", ctypes.c_int)]


_libs: Optional[Tuple[ctypes.CDLL, ctypes.CDLL]] = None


def _load() -> Tuple[ctypes.CDLL, ctypes.CDLL]:
    global _libs
    if _libs is None:
        av = ctypes.CDLL("libavcodec.so.59")
        u = ctypes.CDLL("libavutil.so.57")
        for f in ("avcodec_find_encoder_by_name",
                  "avcodec_find_decoder_by_name",
                  "avcodec_alloc_context3"):
            getattr(av, f).restype = ctypes.c_void_p
        av.avcodec_find_encoder_by_name.argtypes = [ctypes.c_char_p]
        av.avcodec_find_decoder_by_name.argtypes = [ctypes.c_char_p]
        av.avcodec_alloc_context3.argtypes = [ctypes.c_void_p]
        av.avcodec_open2.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                     ctypes.c_void_p]
        for f in ("avcodec_send_frame", "avcodec_receive_packet",
                  "avcodec_send_packet", "avcodec_receive_frame"):
            getattr(av, f).argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        av.av_packet_alloc.restype = ctypes.c_void_p
        av.av_new_packet.argtypes = [ctypes.c_void_p, ctypes.c_int]
        av.av_packet_unref.argtypes = [ctypes.c_void_p]
        u.av_frame_alloc.restype = ctypes.c_void_p
        u.av_frame_get_buffer.argtypes = [ctypes.c_void_p, ctypes.c_int]
        u.av_frame_unref.argtypes = [ctypes.c_void_p]
        u.av_frame_free.argtypes = [ctypes.c_void_p]
        av.av_packet_free.argtypes = [ctypes.c_void_p]
        av.avcodec_free_context.argtypes = [ctypes.c_void_p]
        u.av_opt_set_image_size.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
            ctypes.c_int]
        u.av_opt_set_pixel_fmt.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        u.av_opt_set_q.argtypes = [ctypes.c_void_p, ctypes.c_char_p, _Q,
                                   ctypes.c_int]
        u.av_opt_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_char_p, ctypes.c_int]
        _probe_abi(av, u)
        _libs = (av, u)
    return _libs


def _geti(p: int, off: int) -> int:
    return ctypes.c_int.from_buffer_copy(ctypes.string_at(p + off, 4)).value


def _getp(p: int, off: int) -> int:
    return ctypes.c_void_p.from_buffer_copy(
        ctypes.string_at(p + off, 8)).value or 0


def _seti(p: int, off: int, v: int) -> None:
    ctypes.memmove(p + off, bytes(ctypes.c_int(v)), 4)


def _probe_abi(av, u) -> None:
    """Refuse to run on a layout that fails the known-value probes."""
    fr = u.av_frame_alloc()
    if (_geti(fr, _F_W), _geti(fr, _F_H), _geti(fr, _F_FMT)) \
            != (0, 0, -1):
        raise RuntimeError(
            "AVFrame prefix layout mismatch (fresh frame should read "
            "width=0, height=0, format=-1); refusing raw offsets")
    u.av_frame_free(ctypes.byref(ctypes.c_void_p(fr)))
    pkt = av.av_packet_alloc()
    if av.av_new_packet(pkt, 48) != 0 or _geti(pkt, _P_SIZE) != 48 \
            or not _getp(pkt, _P_DATA):
        raise RuntimeError("AVPacket prefix layout mismatch")
    av.av_packet_free(ctypes.byref(ctypes.c_void_p(pkt)))


class _AvHandle:
    """Shared lifecycle for (codec context, packet, frame) triples —
    one teardown implementation for every codec class in this binding."""

    _ctx = 0
    _pkt = 0
    _fr = 0

    def close(self) -> None:
        if getattr(self, "_ctx", 0):
            self._av.avcodec_free_context(
                ctypes.byref(ctypes.c_void_p(self._ctx)))
            self._ctx = 0
        if getattr(self, "_pkt", 0):
            self._av.av_packet_free(
                ctypes.byref(ctypes.c_void_p(self._pkt)))
            self._pkt = 0
        if getattr(self, "_fr", 0):
            self._u.av_frame_free(
                ctypes.byref(ctypes.c_void_p(self._fr)))
            self._fr = 0

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def h264_available() -> bool:
    try:
        av, _ = _load()
    except (OSError, RuntimeError):
        return False
    return bool(av.avcodec_find_encoder_by_name(b"libx264")
                and av.avcodec_find_decoder_by_name(b"h264"))


def _drain_packets(av, ctx, pkt) -> List[bytes]:
    out = []
    while True:
        r = av.avcodec_receive_packet(ctx, pkt)
        if r != 0:
            if r in (_AVERROR_EAGAIN, _AVERROR_EOF):
                return out
            raise RuntimeError(f"avcodec_receive_packet: {r}")
        size = _geti(pkt, _P_SIZE)
        out.append(ctypes.string_at(_getp(pkt, _P_DATA), size))
        av.av_packet_unref(pkt)


class H264Encoder(_AvHandle):
    """Encode I420 frames to H.264 Annex-B access units (libx264)."""

    def __init__(self, width: int, height: int, fps: int = 30,
                 bitrate: int = 500_000, keyint: int = 30):
        av, u = _load()
        codec = av.avcodec_find_encoder_by_name(b"libx264")
        if not codec:
            raise RuntimeError("libx264 encoder not present in libavcodec")
        self._av, self._u = av, u
        self.width, self.height = width, height
        ctx = av.avcodec_alloc_context3(codec)
        u.av_opt_set_image_size(ctx, b"video_size", width, height, 0)
        u.av_opt_set_pixel_fmt(ctx, b"pixel_format", _AV_PIX_FMT_YUV420P,
                               0)
        u.av_opt_set_q(ctx, b"time_base", _Q(1, fps), 0)
        u.av_opt_set(ctx, b"preset", b"ultrafast", 1)
        u.av_opt_set(ctx, b"tune", b"zerolatency", 1)  # no B-frame delay
        u.av_opt_set(ctx, b"b", str(bitrate).encode(), 1)
        u.av_opt_set(ctx, b"g", str(keyint).encode(), 1)
        if av.avcodec_open2(ctx, codec, None) != 0:
            raise RuntimeError("avcodec_open2(libx264) failed")
        self._ctx = ctx
        # one reusable frame + packet per instance (unref'd after each
        # use; freed in close() — av_*_unref alone releases buffers but
        # leaks the struct)
        self._pkt = av.av_packet_alloc()
        self._fr = u.av_frame_alloc()

    def encode(self, y: np.ndarray, u_: np.ndarray, v: np.ndarray
               ) -> List[bytes]:
        """One I420 frame -> zero or more Annex-B access units."""
        av, u = self._av, self._u
        w, h = self.width, self.height
        fr = self._fr
        try:
            _seti(fr, _F_W, w)
            _seti(fr, _F_H, h)
            _seti(fr, _F_FMT, _AV_PIX_FMT_YUV420P)
            if u.av_frame_get_buffer(fr, 0) != 0:
                raise RuntimeError("av_frame_get_buffer failed")
            planes = [(np.asarray(y, np.uint8), h, w),
                      (np.asarray(u_, np.uint8), (h + 1) // 2,
                       (w + 1) // 2),
                      (np.asarray(v, np.uint8), (h + 1) // 2,
                       (w + 1) // 2)]
            for i, (arr, ph, pw) in enumerate(planes):
                if arr.shape != (ph, pw):
                    raise ValueError(
                        f"plane {i} must be {(ph, pw)}, got {arr.shape}")
                ls = _geti(fr, _F_LINESIZE + 4 * i)
                ptr = _getp(fr, _F_DATA + 8 * i)
                buf = np.ascontiguousarray(arr)
                for row in range(ph):
                    ctypes.memmove(ptr + row * ls,
                                   buf[row].ctypes.data, pw)
            # pts is deliberately left to libx264's own counter: frames
            # arrive in order and zerolatency keeps decode order equal
            # to presentation order.
            if av.avcodec_send_frame(self._ctx, fr) != 0:
                raise RuntimeError("avcodec_send_frame failed")
            return _drain_packets(av, self._ctx, self._pkt)
        finally:
            u.av_frame_unref(fr)

    def flush(self) -> List[bytes]:
        av = self._av
        av.avcodec_send_frame(self._ctx, None)
        return _drain_packets(av, self._ctx, self._pkt)


class H264Decoder(_AvHandle):
    """Decode H.264 Annex-B access units to I420 frames."""

    def __init__(self):
        av, u = _load()
        codec = av.avcodec_find_decoder_by_name(b"h264")
        if not codec:
            raise RuntimeError("h264 decoder not present in libavcodec")
        self._av, self._u = av, u
        ctx = av.avcodec_alloc_context3(codec)
        if av.avcodec_open2(ctx, codec, None) != 0:
            raise RuntimeError("avcodec_open2(h264) failed")
        self._ctx = ctx
        self._pkt = av.av_packet_alloc()
        self._fr = u.av_frame_alloc()

    def decode(self, au: bytes
               ) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """One access unit -> zero or more (y, u, v) I420 frames."""
        av = self._av
        pkt = self._pkt
        if av.av_new_packet(pkt, len(au)) != 0:
            raise RuntimeError("av_new_packet failed")
        ctypes.memmove(_getp(pkt, _P_DATA), au, len(au))
        out: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        for attempt in range(2):
            r = av.avcodec_send_packet(self._ctx, pkt)
            if r == _AVERROR_EAGAIN:
                # output queue full: the packet was NOT consumed —
                # drain, then resend (dropping it would break the
                # decoder's reference chain silently)
                out += self._drain()
                continue
            av.av_packet_unref(pkt)
            if r != 0:
                raise RuntimeError(f"avcodec_send_packet: {r}")
            return out + self._drain()
        av.av_packet_unref(pkt)
        raise RuntimeError("avcodec_send_packet: EAGAIN after drain")

    def flush(self) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        self._av.avcodec_send_packet(self._ctx, None)
        return self._drain()

    def _drain(self):
        av, u = self._av, self._u
        out = []
        fr = self._fr
        while True:
            r = av.avcodec_receive_frame(self._ctx, fr)
            if r != 0:
                if r in (_AVERROR_EAGAIN, _AVERROR_EOF):
                    return out
                raise RuntimeError(f"avcodec_receive_frame: {r}")
            w, h = _geti(fr, _F_W), _geti(fr, _F_H)
            planes = []
            for i, (ph, pw) in enumerate(((h, w),
                                          ((h + 1) // 2, (w + 1) // 2),
                                          ((h + 1) // 2, (w + 1) // 2))):
                ls = _geti(fr, _F_LINESIZE + 4 * i)
                ptr = _getp(fr, _F_DATA + 8 * i)
                rows = np.frombuffer(
                    ctypes.string_at(ptr, ls * ph), np.uint8
                ).reshape(ph, ls)[:, :pw]
                planes.append(rows.copy())
            out.append(tuple(planes))
            u.av_frame_unref(fr)
