"""G.722 wideband audio codec (ITU-T G.722 sub-band ADPCM), batched.

Parity target: the reference's G.722 codec
(`org.jitsi.impl.neomedia.codec.audio.g722.{JNIEncoder,JNIDecoder}` with
`src/native/g722`, SURVEY §2.5) — 7 kHz audio in 64/56/48 kbit/s.

Algorithm (from the ITU-T G.722 specification; constants are the
standard's published tables, not code from the reference mount):

- a 24-tap QMF analysis bank splits 16 kHz PCM into two 8 kHz sub-bands;
- the lower band (0–4 kHz) is coded with embedded 6/5/4-bit ADPCM
  (modes 1/2/3 drop LSBs — the decoder picks how many bits to trust);
- the higher band (4–8 kHz) is coded with 2-bit ADPCM;
- each byte is ``(ihigh << 6) | ilow``, one byte per two input samples.

Design note (TPU-first framework placement): ADPCM is a per-sample
recurrence — the *time* axis is inherently sequential and does not
belong on the MXU.  Like Opus/GSM/Speex here, G.722 is a host-side
codec; the parallel axis is the *stream* axis, so this implementation
is vectorized with NumPy across a batch of independent channels
(state arrays are ``[B, ...]``; the sample loop does vector ops over
all B streams at once), which is how a conference bridge actually
encounters it: many calls, one codec tick.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# --- ITU-T G.722 quantizer / adaptation tables (spec constants) -----------

_Q6 = np.array([
    0, 35, 72, 110, 150, 190, 233, 276, 323, 370, 422, 473, 530, 587,
    650, 714, 786, 858, 940, 1023, 1121, 1219, 1339, 1458, 1612, 1765,
    1980, 2195, 2557, 2919], dtype=np.int32)          # decision levels (30)
_ILN = np.array([
    0, 63, 62, 31, 30, 29, 28, 27, 26, 25, 24, 23, 22, 21, 20, 19, 18,
    17, 16, 15, 14, 13, 12, 11, 10, 9, 8, 7, 6, 5, 4, 0], dtype=np.int32)
_ILP = np.array([
    0, 61, 60, 59, 58, 57, 56, 55, 54, 53, 52, 51, 50, 49, 48, 47, 46,
    45, 44, 43, 42, 41, 40, 39, 38, 37, 36, 35, 34, 33, 32, 0],
    dtype=np.int32)
_WL = np.array([-60, -30, 58, 172, 334, 538, 1198, 3042], dtype=np.int32)
_RL42 = np.array([0, 7, 6, 5, 4, 3, 2, 1, 7, 6, 5, 4, 3, 2, 1, 0],
                 dtype=np.int32)
_ILB = np.array([
    2048, 2093, 2139, 2186, 2233, 2282, 2332, 2383, 2435, 2489, 2543,
    2599, 2656, 2714, 2774, 2834, 2896, 2960, 3025, 3091, 3158, 3228,
    3298, 3371, 3444, 3520, 3597, 3676, 3756, 3838, 3922, 4008],
    dtype=np.int32)
_QM2 = np.array([-7408, -1616, 7408, 1616], dtype=np.int32)
_QM4 = np.array([
    0, -20456, -12896, -8968, -6288, -4240, -2584, -1200,
    20456, 12896, 8968, 6288, 4240, 2584, 1200, 0], dtype=np.int32)
_QM5 = np.array([
    -280, -280, -23352, -17560, -14120, -11664, -9752, -8184,
    -6864, -5712, -4696, -3784, -2960, -2208, -1520, -880,
    23352, 17560, 14120, 11664, 9752, 8184, 6864, 5712,
    4696, 3784, 2960, 2208, 1520, 880, 280, -280], dtype=np.int32)
_QM6 = np.array([
    -136, -136, -136, -136, -24808, -21904, -19008, -16704,
    -14984, -13512, -12280, -11192, -10232, -9360, -8576, -7856,
    -7192, -6576, -6000, -5456, -4944, -4464, -4008, -3576,
    -3168, -2776, -2400, -2032, -1688, -1360, -1040, -728,
    24808, 21904, 19008, 16704, 14984, 13512, 12280, 11192,
    10232, 9360, 8576, 7856, 7192, 6576, 6000, 5456,
    4944, 4464, 4008, 3576, 3168, 2776, 2400, 2032,
    1688, 1360, 1040, 728, 432, 136, -432, -136], dtype=np.int32)
_WH = np.array([0, -214, 798], dtype=np.int32)
_RH2 = np.array([2, 1, 2, 1], dtype=np.int32)
_IHN = np.array([0, 1, 0], dtype=np.int32)
_IHP = np.array([0, 3, 2], dtype=np.int32)
_QMF = np.array([3, -11, 12, 32, -210, 951, 3876, -805, 362, -156, 53,
                 -11], dtype=np.int64)               # 24-tap half filter


def _sat16(x: np.ndarray) -> np.ndarray:
    return np.clip(x, -32768, 32767)


class _BandState:
    """Per-band predictor state for a batch of B channels (int32 [B,...])."""

    def __init__(self, batch: int, det0: int):
        self.s = np.zeros(batch, dtype=np.int32)     # predictor output
        self.sp = np.zeros(batch, dtype=np.int32)    # pole section
        self.sz = np.zeros(batch, dtype=np.int32)    # zero section
        self.r = np.zeros((batch, 3), dtype=np.int32)   # reconstructed
        self.a = np.zeros((batch, 3), dtype=np.int32)   # pole coeffs
        self.ap = np.zeros((batch, 3), dtype=np.int32)
        self.p = np.zeros((batch, 3), dtype=np.int32)   # partial recons
        self.d = np.zeros((batch, 7), dtype=np.int32)   # quantized diffs
        self.b = np.zeros((batch, 7), dtype=np.int32)   # zero coeffs
        self.bp = np.zeros((batch, 7), dtype=np.int32)
        self.nb = np.zeros(batch, dtype=np.int32)    # log scale factor
        self.det = np.full(batch, det0, dtype=np.int32)  # quantizer step


def _block4(st: _BandState, d: np.ndarray) -> None:
    """Predictor adaptation + reconstruction (spec blocks 3/4), batched."""
    st.d[:, 0] = d
    st.r[:, 0] = _sat16(st.s + d)
    st.p[:, 0] = _sat16(st.sz + d)

    # UPPOL2: second pole coefficient
    sg = st.p >> 15                                  # sign bits [B, 3]
    wd1 = _sat16(st.a[:, 1].astype(np.int64) << 2).astype(np.int32)
    wd2 = np.where(sg[:, 0] == sg[:, 1], -wd1, wd1)
    wd2 = np.minimum(wd2, 32767)
    wd3 = (wd2 >> 7) + np.where(sg[:, 0] == sg[:, 2], 128, -128)
    wd3 = wd3 + ((st.a[:, 2].astype(np.int64) * 32512) >> 15).astype(
        np.int32)
    st.ap[:, 2] = np.clip(wd3, -12288, 12288)

    # UPPOL1: first pole coefficient
    wd1 = np.where(sg[:, 0] == sg[:, 1], 192, -192)
    wd2 = ((st.a[:, 1].astype(np.int64) * 32640) >> 15).astype(np.int32)
    ap1 = _sat16(wd1 + wd2)
    wd3 = _sat16(15360 - st.ap[:, 2])
    st.ap[:, 1] = np.clip(ap1, -wd3, wd3)

    # UPZERO: the six zero coefficients
    wd1 = np.where(d == 0, 0, 128)[:, None]          # [B, 1]
    sgd = (st.d >> 15)                               # [B, 7]
    wd2 = np.where(sgd[:, 1:] == sgd[:, :1], wd1, -wd1)
    wd3 = ((st.b[:, 1:].astype(np.int64) * 32640) >> 15).astype(np.int32)
    st.bp[:, 1:] = _sat16(wd2 + wd3)

    # DELAY + coefficient commit
    st.d[:, 1:] = st.d[:, :-1]
    st.b[:, 1:] = st.bp[:, 1:]
    st.r[:, 1:] = st.r[:, :-1]
    st.p[:, 1:] = st.p[:, :-1]
    st.a[:, 1:] = st.ap[:, 1:]

    # FILTEP: pole section output
    wd1 = _sat16(st.r[:, 1].astype(np.int64) * 2)
    wd1 = (st.a[:, 1].astype(np.int64) * wd1) >> 15
    wd2 = _sat16(st.r[:, 2].astype(np.int64) * 2)
    wd2 = (st.a[:, 2].astype(np.int64) * wd2) >> 15
    st.sp = _sat16(wd1 + wd2).astype(np.int32)

    # FILTEZ: zero section output
    dd = _sat16(st.d[:, 1:].astype(np.int64) * 2)
    sz = ((st.b[:, 1:].astype(np.int64) * dd) >> 15).sum(axis=1)
    st.sz = _sat16(sz).astype(np.int32)

    st.s = _sat16(st.sp + st.sz).astype(np.int32)


def _scale(nb: np.ndarray, shift_base: int) -> np.ndarray:
    """Log-to-linear scale factor (spec block SCALEL/SCALEH)."""
    wd1 = _ILB[(nb >> 6) & 31].astype(np.int64)
    wd2 = shift_base - (nb >> 11)
    wd3 = np.where(wd2 < 0, wd1 << np.minimum(-wd2, 16),
                   wd1 >> np.minimum(wd2, 30))
    return (wd3 << 2).astype(np.int32)


class G722Encoder:
    """Batched G.722 encoder: int16 [B, 2n] @16 kHz -> uint8 [B, n]."""

    def __init__(self, batch: int = 1):
        self.batch = batch
        self.low = _BandState(batch, 32)
        self.high = _BandState(batch, 8)
        self._x = np.zeros((batch, 24), dtype=np.int64)  # QMF history

    def encode(self, pcm: np.ndarray) -> np.ndarray:
        pcm = np.atleast_2d(np.asarray(pcm, dtype=np.int64))
        if pcm.shape[0] != self.batch or pcm.shape[1] % 2:
            raise ValueError(f"want [B={self.batch}, even] PCM, "
                             f"got {pcm.shape}")
        n = pcm.shape[1] // 2
        out = np.zeros((self.batch, n), dtype=np.uint8)
        for j in range(n):
            # QMF analysis over the last 24 samples
            self._x[:, :22] = self._x[:, 2:]
            self._x[:, 22] = pcm[:, 2 * j]
            self._x[:, 23] = pcm[:, 2 * j + 1]
            sumodd = (self._x[:, 0::2] * _QMF).sum(axis=1)
            sumeven = (self._x[:, 1::2] * _QMF[::-1]).sum(axis=1)
            xlow = ((sumeven + sumodd) >> 14).astype(np.int32)
            xhigh = ((sumeven - sumodd) >> 14).astype(np.int32)

            # ---- lower band: 6-bit embedded ADPCM
            el = _sat16(xlow - self.low.s).astype(np.int32)
            wd = np.where(el >= 0, el, -(el + 1))
            decision = (_Q6[None, 1:30].astype(np.int64)
                        * self.low.det[:, None]) >> 12
            mil = 1 + (wd[:, None] >= decision).sum(axis=1)
            ilow = np.where(el < 0, _ILN[mil], _ILP[mil]).astype(np.int32)
            # local decode (4-bit core) feeds the predictor
            ril = ilow >> 2
            dlow = ((self.low.det.astype(np.int64) * _QM4[ril]) >> 15) \
                .astype(np.int32)
            il4 = _RL42[ril]
            nb = ((self.low.nb.astype(np.int64) * 127) >> 7).astype(
                np.int32) + _WL[il4]
            self.low.nb = np.clip(nb, 0, 18432)
            self.low.det = _scale(self.low.nb, 8)
            _block4(self.low, dlow)

            # ---- higher band: 2-bit ADPCM
            eh = _sat16(xhigh - self.high.s).astype(np.int32)
            wd = np.where(eh >= 0, eh, -(eh + 1))
            wd1 = (564 * self.high.det.astype(np.int64)) >> 12
            mih = np.where(wd >= wd1, 2, 1)
            ihigh = np.where(eh < 0, _IHN[mih], _IHP[mih]).astype(np.int32)
            dhigh = ((self.high.det.astype(np.int64) * _QM2[ihigh]) >> 15) \
                .astype(np.int32)
            ih2 = _RH2[ihigh]
            nb = ((self.high.nb.astype(np.int64) * 127) >> 7).astype(
                np.int32) + _WH[ih2]
            self.high.nb = np.clip(nb, 0, 22528)
            self.high.det = _scale(self.high.nb, 10)
            _block4(self.high, dhigh)

            out[:, j] = ((ihigh << 6) | ilow).astype(np.uint8)
        return out


class G722Decoder:
    """Batched G.722 decoder: uint8 [B, n] -> int16 [B, 2n] @16 kHz.

    bits_per_sample: 8 (mode 1, 64 kbit/s), 7 (mode 2, 56k) or 6
    (mode 3, 48k) — the embedded property: lower-band LSBs are dropped.
    """

    def __init__(self, batch: int = 1, bits_per_sample: int = 8):
        if bits_per_sample not in (6, 7, 8):
            raise ValueError("bits_per_sample must be 6, 7 or 8")
        self.batch = batch
        self.bits = bits_per_sample
        self.low = _BandState(batch, 32)
        self.high = _BandState(batch, 8)
        self._x = np.zeros((batch, 24), dtype=np.int64)  # QMF history

    def decode(self, code: np.ndarray) -> np.ndarray:
        code = np.atleast_2d(np.asarray(code, dtype=np.int32))
        if code.shape[0] != self.batch:
            raise ValueError(f"want [B={self.batch}, n] codes, "
                             f"got {code.shape}")
        n = code.shape[1]
        out = np.zeros((self.batch, 2 * n), dtype=np.int16)
        for j in range(n):
            byte = code[:, j]
            ilow = byte & 0x3F
            ihigh = (byte >> 6) & 0x03

            # ---- lower band reconstruction at the mode's precision
            det = self.low.det.astype(np.int64)
            if self.bits == 8:
                wd2 = _QM6[ilow]
            elif self.bits == 7:
                wd2 = _QM5[ilow >> 1]
            else:
                wd2 = _QM4[ilow >> 2]
            dlowt = ((det * wd2) >> 15).astype(np.int32)
            rlow = np.clip(self.low.s + dlowt, -16384, 16383)
            # adaptation always runs on the 4-bit core (embedded coding)
            ril = ilow >> 2
            dlow = ((det * _QM4[ril]) >> 15).astype(np.int32)
            il4 = _RL42[ril]
            nb = ((self.low.nb.astype(np.int64) * 127) >> 7).astype(
                np.int32) + _WL[il4]
            self.low.nb = np.clip(nb, 0, 18432)
            self.low.det = _scale(self.low.nb, 8)
            _block4(self.low, dlow)

            # ---- higher band
            dhigh = ((self.high.det.astype(np.int64) * _QM2[ihigh]) >> 15) \
                .astype(np.int32)
            rhigh = np.clip(self.high.s + dhigh, -16384, 16383)
            ih2 = _RH2[ihigh]
            nb = ((self.high.nb.astype(np.int64) * 127) >> 7).astype(
                np.int32) + _WH[ih2]
            self.high.nb = np.clip(nb, 0, 22528)
            self.high.det = _scale(self.high.nb, 10)
            _block4(self.high, dhigh)

            # ---- QMF synthesis: two output samples
            self._x[:, :22] = self._x[:, 2:]
            self._x[:, 22] = rlow + rhigh
            self._x[:, 23] = rlow - rhigh
            xout2 = (self._x[:, 0::2] * _QMF).sum(axis=1)
            xout1 = (self._x[:, 1::2] * _QMF[::-1]).sum(axis=1)
            out[:, 2 * j] = _sat16(xout1 >> 11)
            out[:, 2 * j + 1] = _sat16(xout2 >> 11)
        return out


def encode(pcm: np.ndarray) -> bytes:
    """One-shot single-channel helper: int16 PCM @16 kHz -> G.722 bytes."""
    return G722Encoder(1).encode(np.asarray(pcm).reshape(1, -1))[0].tobytes()


def decode(data: bytes, bits_per_sample: int = 8) -> np.ndarray:
    """One-shot single-channel helper: G.722 bytes -> int16 PCM @16 kHz."""
    code = np.frombuffer(data, dtype=np.uint8).reshape(1, -1)
    return G722Decoder(1, bits_per_sample).decode(code)[0]
