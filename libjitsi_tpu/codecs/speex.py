"""Speex codec via ctypes to libspeex.

Completes the reference's Speex support (`org.jitsi.impl.neomedia.codec.
audio.speex.*` + `src/native/speex`): the RESAMPLER is already a device
kernel (`kernels/resample.py`, the part SURVEY §2.5 flags as mattering
for the mixer); this module adds the bitstream codec itself as a host
ctypes binding (our ctypes = the reference's JNI).

Modes: narrowband (8 kHz, 160-sample frames), wideband (16 kHz, 320),
ultra-wideband (32 kHz, 640).
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional

import numpy as np

MODE_NB, MODE_WB, MODE_UWB = 0, 1, 2
_RATES = {MODE_NB: 8000, MODE_WB: 16000, MODE_UWB: 32000}

_SPEEX_GET_FRAME_SIZE = 3
_SPEEX_SET_QUALITY = 4

_lib = None


class _SpeexBits(ctypes.Structure):
    # public ABI of SpeexBits (speex/speex_bits.h)
    _fields_ = [("chars", ctypes.c_char_p),
                ("nbBits", ctypes.c_int),
                ("charPtr", ctypes.c_int),
                ("bitPtr", ctypes.c_int),
                ("owner", ctypes.c_int),
                ("overflow", ctypes.c_int),
                ("buf_size", ctypes.c_int),
                ("reserved1", ctypes.c_int),
                ("reserved2", ctypes.c_void_p)]


def _load():
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("speex") or "libspeex.so.1"
    lib = ctypes.CDLL(name)
    lib.speex_lib_get_mode.restype = ctypes.c_void_p
    lib.speex_lib_get_mode.argtypes = [ctypes.c_int]
    lib.speex_encoder_init.restype = ctypes.c_void_p
    lib.speex_encoder_init.argtypes = [ctypes.c_void_p]
    lib.speex_decoder_init.restype = ctypes.c_void_p
    lib.speex_decoder_init.argtypes = [ctypes.c_void_p]
    for f in (lib.speex_encoder_destroy, lib.speex_decoder_destroy):
        f.argtypes = [ctypes.c_void_p]
    for f in (lib.speex_encoder_ctl, lib.speex_decoder_ctl):
        f.restype = ctypes.c_int
        f.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]
    lib.speex_bits_init.argtypes = [ctypes.POINTER(_SpeexBits)]
    lib.speex_bits_reset.argtypes = [ctypes.POINTER(_SpeexBits)]
    lib.speex_bits_destroy.argtypes = [ctypes.POINTER(_SpeexBits)]
    lib.speex_bits_write.restype = ctypes.c_int
    lib.speex_bits_write.argtypes = [ctypes.POINTER(_SpeexBits),
                                     ctypes.c_char_p, ctypes.c_int]
    lib.speex_bits_read_from.argtypes = [ctypes.POINTER(_SpeexBits),
                                         ctypes.c_char_p, ctypes.c_int]
    lib.speex_encode_int.restype = ctypes.c_int
    lib.speex_encode_int.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_short),
                                     ctypes.POINTER(_SpeexBits)]
    lib.speex_decode_int.restype = ctypes.c_int
    lib.speex_decode_int.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(_SpeexBits),
                                     ctypes.POINTER(ctypes.c_short)]
    _lib = lib
    return lib


def speex_available() -> bool:
    try:
        _load()
        return True
    except OSError:
        return False


class SpeexEncoder:
    def __init__(self, mode: int = MODE_NB, quality: int = 8):
        if mode not in _RATES:
            raise ValueError(f"mode must be one of {sorted(_RATES)}")
        lib = _load()
        self._lib = lib
        self._st = lib.speex_encoder_init(lib.speex_lib_get_mode(mode))
        if not self._st:
            raise RuntimeError("speex_encoder_init failed")
        q = ctypes.c_int(quality)
        lib.speex_encoder_ctl(self._st, _SPEEX_SET_QUALITY,
                              ctypes.byref(q))
        fs = ctypes.c_int(0)
        lib.speex_encoder_ctl(self._st, _SPEEX_GET_FRAME_SIZE,
                              ctypes.byref(fs))
        self.frame_size = fs.value
        self.sample_rate = _RATES[mode]
        self._bits = _SpeexBits()
        lib.speex_bits_init(ctypes.byref(self._bits))

    def encode(self, pcm: np.ndarray) -> bytes:
        """int16 [frame_size] -> one encoded Speex frame."""
        # private copy: speex_encode_int may overwrite its input frame
        # (fixed-point builds), and callers may pass read-only views
        pcm = np.array(pcm, dtype=np.int16, copy=True)
        if pcm.size != self.frame_size:
            raise ValueError(
                f"frame must be {self.frame_size} samples, got {pcm.size}")
        self._lib.speex_bits_reset(ctypes.byref(self._bits))
        self._lib.speex_encode_int(
            self._st, pcm.ctypes.data_as(ctypes.POINTER(ctypes.c_short)),
            ctypes.byref(self._bits))
        buf = ctypes.create_string_buffer(2048)
        n = self._lib.speex_bits_write(ctypes.byref(self._bits), buf, 2048)
        return buf.raw[:n]

    def close(self) -> None:
        if self._st:
            self._lib.speex_encoder_destroy(self._st)
            self._lib.speex_bits_destroy(ctypes.byref(self._bits))
            self._st = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class SpeexDecoder:
    def __init__(self, mode: int = MODE_NB):
        if mode not in _RATES:
            raise ValueError(f"mode must be one of {sorted(_RATES)}")
        lib = _load()
        self._lib = lib
        self._st = lib.speex_decoder_init(lib.speex_lib_get_mode(mode))
        if not self._st:
            raise RuntimeError("speex_decoder_init failed")
        fs = ctypes.c_int(0)
        lib.speex_decoder_ctl(self._st, _SPEEX_GET_FRAME_SIZE,
                              ctypes.byref(fs))
        self.frame_size = fs.value
        self.sample_rate = _RATES[mode]
        self._bits = _SpeexBits()
        lib.speex_bits_init(ctypes.byref(self._bits))

    def decode(self, frame: Optional[bytes]) -> np.ndarray:
        """One Speex frame -> int16 [frame_size].  None = packet loss
        (concealment, like the reference decoder's FEC/PLC path)."""
        out = np.zeros(self.frame_size, dtype=np.int16)
        optr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_short))
        if frame is None:
            self._lib.speex_decode_int(self._st, None, optr)
            return out
        self._lib.speex_bits_read_from(ctypes.byref(self._bits), frame,
                                       len(frame))
        rc = self._lib.speex_decode_int(self._st,
                                        ctypes.byref(self._bits), optr)
        if rc < 0:
            raise ValueError("speex_decode_int failed")
        return out

    def close(self) -> None:
        if self._st:
            self._lib.speex_decoder_destroy(self._st)
            self._lib.speex_bits_destroy(ctypes.byref(self._bits))
            self._st = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
