"""GSM 06.10 full-rate codec via ctypes to libgsm.

Rebuilds the reference's GSM codec (`org.jitsi.impl.neomedia.codec.audio.
gsm.*`, SURVEY §2.5 telephony codecs) the same way the Opus module wraps
libopus: the host-side bitstream codec binds the system library (our
ctypes = the reference's JNI), while PCM post-processing (mixing,
resampling, levels) rides the device kernels.

Frame geometry: 160 int16 samples at 8 kHz (20 ms) <-> 33-byte frame
(13 kbit/s).
"""

from __future__ import annotations

import ctypes
import ctypes.util

import numpy as np

FRAME_SAMPLES = 160
FRAME_BYTES = 33
SAMPLE_RATE = 8000

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("gsm") or "libgsm.so.1"
    lib = ctypes.CDLL(name)
    lib.gsm_create.restype = ctypes.c_void_p
    lib.gsm_destroy.argtypes = [ctypes.c_void_p]
    lib.gsm_encode.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_short),
                               ctypes.POINTER(ctypes.c_ubyte)]
    lib.gsm_decode.restype = ctypes.c_int
    lib.gsm_decode.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_ubyte),
                               ctypes.POINTER(ctypes.c_short)]
    _lib = lib
    return lib


def gsm_available() -> bool:
    try:
        _load()
        return True
    except OSError:
        return False


class GsmCodec:
    """One GSM 06.10 en/decoder instance (stateful, like the reference's
    per-stream codec plugins)."""

    def __init__(self):
        lib = _load()
        self._lib = lib
        self._enc = lib.gsm_create()
        self._dec = lib.gsm_create()
        if not self._enc or not self._dec:
            raise RuntimeError("gsm_create failed")

    def encode(self, pcm: np.ndarray) -> bytes:
        """int16 [160] (or a multiple) at 8 kHz -> 33 bytes per frame."""
        pcm = np.ascontiguousarray(pcm, dtype=np.int16)
        if pcm.size % FRAME_SAMPLES:
            raise ValueError(f"PCM length must be a multiple of "
                             f"{FRAME_SAMPLES}, got {pcm.size}")
        out = bytearray()
        frame = (ctypes.c_ubyte * FRAME_BYTES)()
        for k in range(pcm.size // FRAME_SAMPLES):
            chunk = pcm[k * FRAME_SAMPLES:(k + 1) * FRAME_SAMPLES]
            sig = chunk.ctypes.data_as(ctypes.POINTER(ctypes.c_short))
            self._lib.gsm_encode(self._enc, sig, frame)
            out += bytes(frame)
        return bytes(out)

    def decode(self, data: bytes) -> np.ndarray:
        """33-byte frames -> int16 [160 * nframes]."""
        if len(data) % FRAME_BYTES:
            raise ValueError(f"GSM payload must be a multiple of "
                             f"{FRAME_BYTES}B, got {len(data)}")
        n = len(data) // FRAME_BYTES
        out = np.zeros(n * FRAME_SAMPLES, dtype=np.int16)
        buf = (ctypes.c_ubyte * FRAME_BYTES)()
        for k in range(n):
            buf[:] = data[k * FRAME_BYTES:(k + 1) * FRAME_BYTES]
            sig = out[k * FRAME_SAMPLES:(k + 1) * FRAME_SAMPLES]
            rc = self._lib.gsm_decode(
                self._dec, buf, sig.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_short)))
            if rc != 0:
                raise ValueError(f"gsm_decode failed on frame {k}")
        return out

    def close(self) -> None:
        if self._enc:
            self._lib.gsm_destroy(self._enc)
            self._enc = None
        if self._dec:
            self._lib.gsm_destroy(self._dec)
            self._dec = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
