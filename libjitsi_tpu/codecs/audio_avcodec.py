"""G.729 / iLBC / G.723.1 decode via the system libavcodec.

Parity target: the reference's `...codec.audio.{g729,ilbc}.*` (SURVEY
§2.5).  Those rows were recorded as lib-blocked in rounds 1-2 (no
libbcg729/libilbc in the image) — but the system libavcodec 59 ships
NATIVE decoders for g729, ilbc and g723_1, so the decode half closes
through the same validated ctypes binding `codecs.avcodec` built for
H.264 (AVOptions-only context config, probed AVFrame/AVPacket prefix
offsets).  FFmpeg has no native encoders for these codecs, so the
encode half remains honestly unavailable until a system encoder lib
appears; conference legs that must SEND these codecs keep using G.711
(the gateway posture the reference's SILK row takes vs Opus).

Frame sizes:
  g729    10 B / frame -> 80 samples  (10 ms @ 8 kHz); 2 B SID = DTX
  ilbc    38 B -> 160 samples (RFC 3952 mode=20; the 30 ms mode needs
          block_align, which has no AVOptions surface — refused at
          construction rather than silently misdecoded)
  g723_1  24 B -> 240 samples (30 ms @ 8 kHz; 6.3 kbit/s frames)
"""

from __future__ import annotations

import ctypes
from typing import List

import numpy as np

from libjitsi_tpu.codecs.avcodec import (_AVERROR_EAGAIN, _AVERROR_EOF,
                                         _AvHandle, _F_DATA, _F_FMT,
                                         _P_DATA, _geti, _getp, _load)

_F_NB_SAMPLES = 112          # FFmpeg 5.x AVFrame prefix (after w/h)
_MAX_SAMPLES = 48_000        # refuse implausible counts (offset guard)
_SAMPLE_FMT_S16, _SAMPLE_FMT_S16P = 1, 6

_DECODERS = {"g729": 8000, "ilbc": 8000, "g723_1": 8000}

_nb_samples_probed = False


def _probe_nb_samples(u) -> None:
    """Once per process: a fresh AVFrame must read nb_samples == 0 at
    the poked offset (the binding's refuse-to-run doctrine; the
    per-decode _MAX_SAMPLES bound guards the live values)."""
    global _nb_samples_probed
    if _nb_samples_probed:
        return
    fr = u.av_frame_alloc()
    nb0 = _geti(fr, _F_NB_SAMPLES)
    u.av_frame_free(ctypes.byref(ctypes.c_void_p(fr)))
    if nb0 != 0:
        raise RuntimeError(
            "AVFrame nb_samples offset mismatch (fresh frame read "
            f"{nb0}); refusing raw offsets")
    _nb_samples_probed = True


def audio_decoder_available(name: str) -> bool:
    try:
        av, _ = _load()
    except Exception:
        return False
    return bool(av.avcodec_find_decoder_by_name(name.encode()))


class AvAudioDecoder(_AvHandle):
    """Mono S16 frame decoder over libavcodec (g729/ilbc/g723_1)."""

    def __init__(self, codec_name: str, ilbc_mode_ms: int = 20):
        if codec_name not in _DECODERS:
            raise ValueError(f"unsupported codec {codec_name!r}")
        if codec_name == "ilbc" and ilbc_mode_ms != 20:
            # the 30 ms mode needs block_align on the codec context,
            # which has no AVOptions surface; poking a raw context
            # offset would break the binding's validated-ABI doctrine
            raise RuntimeError(
                "iLBC 30 ms mode unsupported (no AVOptions path to "
                "block_align); RFC 3952 mode=20 only")
        av, u = _load()
        _probe_nb_samples(u)
        codec = av.avcodec_find_decoder_by_name(codec_name.encode())
        if not codec:
            raise RuntimeError(
                f"{codec_name} decoder not present in libavcodec")
        self._av, self._u = av, u
        self.codec_name = codec_name
        self.sample_rate = _DECODERS[codec_name]
        self.ilbc_mode_ms = ilbc_mode_ms
        # assign the context BEFORE open so _AvHandle.close() frees it
        # on the open-failure path too
        self._ctx = av.avcodec_alloc_context3(codec)
        # AVOptions only (name-based, version-stable): sample rate +
        # mono; the decoders refuse to open without a channel count
        u.av_opt_set_int.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64, ctypes.c_int]
        u.av_opt_set_int(self._ctx, b"ar", self.sample_rate, 0)
        u.av_opt_set_int(self._ctx, b"ac", 1, 0)
        if av.avcodec_open2(self._ctx, codec, None) != 0:
            raise RuntimeError(f"avcodec_open2({codec_name}) failed")
        self._pkt = av.av_packet_alloc()
        self._fr = u.av_frame_alloc()

    def decode(self, frame: bytes) -> np.ndarray:
        """One codec frame -> int16 PCM [samples] (mono).

        G.729 Annex-B SID (comfort-noise) frames — exactly 2 bytes,
        standard with VAD — return empty PCM rather than erroring:
        callers fill silence, same as a DTX gap.  (0/1-byte fragments
        stay errors: malformed input must not pass silently.)"""
        if self.codec_name == "g729" and len(frame) == 2:
            return np.zeros(0, dtype=np.int16)
        av = self._av
        pkt = self._pkt
        if av.av_new_packet(pkt, len(frame)) != 0:
            raise RuntimeError("av_new_packet failed")
        ctypes.memmove(_getp(pkt, _P_DATA), frame, len(frame))
        r = av.avcodec_send_packet(self._ctx, pkt)
        av.av_packet_unref(pkt)
        if r != 0:
            raise ValueError(
                f"{self.codec_name} rejected a {len(frame)}-byte frame "
                f"({r})")
        out = self._drain()
        if not out:
            return np.zeros(0, dtype=np.int16)
        return np.concatenate(out)

    def _drain(self) -> List[np.ndarray]:
        av, u = self._av, self._u
        fr = self._fr
        out: List[np.ndarray] = []
        while True:
            r = av.avcodec_receive_frame(self._ctx, fr)
            if r != 0:
                if r in (_AVERROR_EAGAIN, _AVERROR_EOF):
                    return out
                raise RuntimeError(f"avcodec_receive_frame: {r}")
            fmt = _geti(fr, _F_FMT)
            if fmt not in (_SAMPLE_FMT_S16, _SAMPLE_FMT_S16P):
                u.av_frame_unref(fr)
                raise RuntimeError(
                    f"unexpected sample format {fmt} from "
                    f"{self.codec_name} (want S16/S16P)")
            n = _geti(fr, _F_NB_SAMPLES)
            if not 0 < n <= _MAX_SAMPLES:
                u.av_frame_unref(fr)
                raise RuntimeError(
                    f"implausible nb_samples {n} (layout drift?)")
            ptr = _getp(fr, _F_DATA)       # mono: plane 0 either way
            pcm = np.frombuffer(ctypes.string_at(ptr, n * 2),
                                dtype=np.int16).copy()
            out.append(pcm)
            u.av_frame_unref(fr)

    # close()/__del__ inherited from _AvHandle

    def decode_payload(self, payload: bytes) -> np.ndarray:
        """One RTP payload -> PCM.

        RFC 3551: a G.729 payload is N back-to-back 10-byte frames with
        an optional trailing 2-byte SID; iLBC (RFC 3952, mode=20) and
        G.723.1 payloads may also stack whole frames.  Splits on the
        codec's frame size and decodes in order (G.723.1 frame size
        follows the 2-bit rate field of each frame's first byte:
        24/20/4/1 bytes)."""
        out: List[np.ndarray] = []
        pos = 0
        while pos < len(payload):
            if self.codec_name == "g729":
                size = 2 if len(payload) - pos == 2 else 10
            elif self.codec_name == "ilbc":
                size = 38                   # mode=20 (enforced at init)
            else:                           # g723_1: per-frame rate bits
                size = {0: 24, 1: 20, 2: 4, 3: 1}[payload[pos] & 3]
            chunk = payload[pos:pos + size]
            if len(chunk) < size:
                raise ValueError(
                    f"truncated {self.codec_name} payload at {pos}")
            if self.codec_name == "g723_1" and size <= 4:
                pcm = np.zeros(0, dtype=np.int16)   # SID: DTX gap
            else:
                pcm = self.decode(chunk)
            if len(pcm):
                out.append(pcm)
            pos += size
        if not out:
            return np.zeros(0, dtype=np.int16)
        return np.concatenate(out)


def g729_decoder() -> AvAudioDecoder:
    return AvAudioDecoder("g729")


def ilbc_decoder() -> AvAudioDecoder:
    return AvAudioDecoder("ilbc")


def g723_1_decoder() -> AvAudioDecoder:
    return AvAudioDecoder("g723_1")
