"""G.729 / iLBC / G.723.1 decode via the system libavcodec.

Parity target: the reference's `...codec.audio.{g729,ilbc}.*` (SURVEY
§2.5).  Those rows were recorded as lib-blocked in rounds 1-2 (no
libbcg729/libilbc in the image) — but the system libavcodec 59 ships
NATIVE decoders for g729, ilbc and g723_1, so the decode half closes
through the same validated ctypes binding `codecs.avcodec` built for
H.264 (AVOptions-only context config, probed AVFrame/AVPacket prefix
offsets).  FFmpeg has no native encoders for these codecs, so the
encode half remains honestly unavailable until a system encoder lib
appears; conference legs that must SEND these codecs keep using G.711
(the gateway posture the reference's SILK row takes vs Opus).

Frame sizes (detected by the decoders from packet length):
  g729    10 B / frame -> 80 samples  (10 ms @ 8 kHz)
  ilbc    38 B -> 160 samples (20 ms) or 50 B -> 240 samples (30 ms)
  g723_1  24 B -> 240 samples (30 ms @ 8 kHz; 6.3 kbit/s frames)
"""

from __future__ import annotations

import ctypes
from typing import List

import numpy as np

from libjitsi_tpu.codecs.avcodec import (_AVERROR_EAGAIN, _AVERROR_EOF,
                                         _AvHandle, _F_DATA, _F_FMT,
                                         _geti, _getp, _load)

_F_NB_SAMPLES = 112          # FFmpeg 5.x AVFrame prefix (after w/h)
_MAX_SAMPLES = 48_000        # refuse implausible counts (offset guard)
_P_DATA, _P_SIZE = 24, 32
_SAMPLE_FMT_S16, _SAMPLE_FMT_S16P = 1, 6

_DECODERS = {"g729": 8000, "ilbc": 8000, "g723_1": 8000}


def audio_decoder_available(name: str) -> bool:
    try:
        av, _ = _load()
    except Exception:
        return False
    return bool(av.avcodec_find_decoder_by_name(name.encode()))


class AvAudioDecoder(_AvHandle):
    """Mono S16 frame decoder over libavcodec (g729/ilbc/g723_1)."""

    def __init__(self, codec_name: str):
        if codec_name not in _DECODERS:
            raise ValueError(f"unsupported codec {codec_name!r}")
        av, u = _load()
        # probe the one offset the video binding doesn't: a fresh
        # AVFrame must read nb_samples == 0 (the binding's refuse-to-
        # run-on-layout-mismatch doctrine; _MAX_SAMPLES bounds the
        # count again after every decode)
        fr = u.av_frame_alloc()
        nb0 = _geti(fr, _F_NB_SAMPLES)
        u.av_frame_free(ctypes.byref(ctypes.c_void_p(fr)))
        if nb0 != 0:
            raise RuntimeError(
                "AVFrame nb_samples offset mismatch (fresh frame read "
                f"{nb0}); refusing raw offsets")
        codec = av.avcodec_find_decoder_by_name(codec_name.encode())
        if not codec:
            raise RuntimeError(
                f"{codec_name} decoder not present in libavcodec")
        self._av, self._u = av, u
        self.codec_name = codec_name
        self.sample_rate = _DECODERS[codec_name]
        ctx = av.avcodec_alloc_context3(codec)
        # AVOptions only (name-based, version-stable): sample rate +
        # mono; the decoders refuse to open without a channel count
        u.av_opt_set_int.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64, ctypes.c_int]
        u.av_opt_set_int(ctx, b"ar", self.sample_rate, 0)
        u.av_opt_set_int(ctx, b"ac", 1, 0)
        if av.avcodec_open2(ctx, codec, None) != 0:
            raise RuntimeError(f"avcodec_open2({codec_name}) failed")
        self._ctx = ctx
        self._pkt = av.av_packet_alloc()
        self._fr = u.av_frame_alloc()

    def decode(self, frame: bytes) -> np.ndarray:
        """One codec frame -> int16 PCM [samples] (mono).

        G.729 Annex-B SID (comfort-noise) frames — 2 bytes, standard
        with VAD — return empty PCM rather than erroring: callers fill
        silence, same as a DTX gap."""
        if self.codec_name == "g729" and len(frame) <= 2:
            return np.zeros(0, dtype=np.int16)
        av = self._av
        pkt = self._pkt
        if av.av_new_packet(pkt, len(frame)) != 0:
            raise RuntimeError("av_new_packet failed")
        ctypes.memmove(_getp(pkt, _P_DATA), frame, len(frame))
        r = av.avcodec_send_packet(self._ctx, pkt)
        av.av_packet_unref(pkt)
        if r != 0:
            raise ValueError(
                f"{self.codec_name} rejected a {len(frame)}-byte frame "
                f"({r})")
        out = self._drain()
        if not out:
            return np.zeros(0, dtype=np.int16)
        return np.concatenate(out)

    def _drain(self) -> List[np.ndarray]:
        av, u = self._av, self._u
        fr = self._fr
        out: List[np.ndarray] = []
        while True:
            r = av.avcodec_receive_frame(self._ctx, fr)
            if r != 0:
                if r in (_AVERROR_EAGAIN, _AVERROR_EOF):
                    return out
                raise RuntimeError(f"avcodec_receive_frame: {r}")
            fmt = _geti(fr, _F_FMT)
            if fmt not in (_SAMPLE_FMT_S16, _SAMPLE_FMT_S16P):
                u.av_frame_unref(fr)
                raise RuntimeError(
                    f"unexpected sample format {fmt} from "
                    f"{self.codec_name} (want S16/S16P)")
            n = _geti(fr, _F_NB_SAMPLES)
            if not 0 < n <= _MAX_SAMPLES:
                u.av_frame_unref(fr)
                raise RuntimeError(
                    f"implausible nb_samples {n} (layout drift?)")
            ptr = _getp(fr, _F_DATA)       # mono: plane 0 either way
            pcm = np.frombuffer(ctypes.string_at(ptr, n * 2),
                                dtype=np.int16).copy()
            out.append(pcm)
            u.av_frame_unref(fr)

    # close()/__del__ inherited from _AvHandle


def g729_decoder() -> AvAudioDecoder:
    return AvAudioDecoder("g729")


def ilbc_decoder() -> AvAudioDecoder:
    return AvAudioDecoder("ilbc")


def g723_1_decoder() -> AvAudioDecoder:
    return AvAudioDecoder("g723_1")
