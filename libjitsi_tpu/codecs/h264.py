"""H.264 RTP packetization/depacketization (RFC 6184).

Rebuilds the header logic of the reference's
`org.jitsi.impl.neomedia.codec.video.h264.{Packetizer,DePacketizer}`:
single NAL unit mode, STAP-A aggregation, and FU-A fragmentation, plus
keyframe (IDR/SPS) detection for layer switching.  The bitstream codec
half (the reference's JNIEncoder/JNIDecoder over ffmpeg) is
`codecs.avcodec` (libavcodec via ctypes); `split_annexb` bridges its
Annex-B access units to the NAL lists this module packetizes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

NAL_STAP_A = 24
NAL_FU_A = 28
NAL_IDR = 5
NAL_SPS = 7
NAL_PPS = 8


def split_annexb(au: bytes) -> List[bytes]:
    """Split an Annex-B access unit (00 00 [00] 01 start codes) into
    bare NAL units (the packetizer's input format)."""
    nals: List[bytes] = []
    i = 0
    n = len(au)
    start = -1
    while i + 2 < n:
        if au[i] == 0 and au[i + 1] == 0 and \
                (au[i + 2] == 1
                 or (i + 3 < n and au[i + 2] == 0 and au[i + 3] == 1)):
            sc = 3 if au[i + 2] == 1 else 4
            if start >= 0:
                nal = au[start:i]
                if nal:
                    nals.append(nal)
            i += sc
            start = i
        else:
            i += 1
    if start >= 0 and start < n:
        nals.append(au[start:])
    return nals


def packetize(nals: List[bytes], mtu: int = 1200) -> List[bytes]:
    """NAL units (one access unit) -> RTP payloads (RFC 6184).

    Small NALs aggregate into STAP-A; oversized NALs fragment into
    FU-A.  Reference: h264.Packetizer.
    """
    out: List[bytes] = []
    agg: List[bytes] = []
    agg_size = 1  # STAP-A indicator byte

    def flush_agg():
        nonlocal agg, agg_size
        if not agg:
            return
        if len(agg) == 1:
            out.append(agg[0])  # single NAL unit packet
        else:
            nri = max((n[0] >> 5) & 3 for n in agg)
            blob = bytes([(nri << 5) | NAL_STAP_A])
            for n in agg:
                blob += len(n).to_bytes(2, "big") + n
            out.append(blob)
        agg = []
        agg_size = 1

    for nal in nals:
        if not nal:
            continue
        if len(nal) + 2 + agg_size > mtu:
            flush_agg()
        if len(nal) <= mtu:
            agg.append(nal)
            agg_size += 2 + len(nal)
            continue
        # FU-A fragmentation
        flush_agg()
        hdr = nal[0]
        fu_ind = (hdr & 0xE0) | NAL_FU_A
        typ = hdr & 0x1F
        payload = nal[1:]
        pos = 0
        chunk = mtu - 2
        while pos < len(payload):
            piece = payload[pos:pos + chunk]
            s = 0x80 if pos == 0 else 0
            e = 0x40 if pos + chunk >= len(payload) else 0
            out.append(bytes([fu_ind, s | e | typ]) + piece)
            pos += len(piece)
    flush_agg()
    return out


@dataclasses.dataclass
class H264Depacketizer:
    """Reassemble NAL units from RTP payloads (reference: DePacketizer).

    Feed payloads in seq order (post jitter buffer); `push` returns the
    completed NAL units from that payload (possibly several for STAP-A,
    one after the final FU-A fragment, none mid-fragment).
    """

    _fu: Optional[bytearray] = None
    keyframe_seen: bool = False

    def push(self, payload: bytes) -> List[bytes]:
        if not payload:
            return []
        typ = payload[0] & 0x1F
        if typ == NAL_STAP_A:
            nals = []
            off = 1
            while off + 2 <= len(payload):
                ln = int.from_bytes(payload[off:off + 2], "big")
                nal = payload[off + 2:off + 2 + ln]
                if len(nal) == ln:
                    nals.append(nal)
                off += 2 + ln
            for n in nals:
                self._note(n)
            return nals
        if typ == NAL_FU_A:
            if len(payload) < 2:
                return []
            ind, fu = payload[0], payload[1]
            start, end = fu & 0x80, fu & 0x40
            if start:
                hdr = (ind & 0xE0) | (fu & 0x1F)
                self._fu = bytearray([hdr]) + payload[2:]
            elif self._fu is not None:
                self._fu += payload[2:]
            if end and self._fu is not None:
                nal = bytes(self._fu)
                self._fu = None
                self._note(nal)
                return [nal]
            return []
        # single NAL unit packet
        self._note(payload)
        return [payload]

    def _note(self, nal: bytes) -> None:
        if nal and (nal[0] & 0x1F) in (NAL_IDR, NAL_SPS):
            self.keyframe_seen = True


def is_keyframe_payload(payload: bytes) -> bool:
    """Does this RTP payload start/contain an IDR or SPS NAL?
    (reference: DePacketizer.isKeyFrame)"""
    if not payload:
        return False
    typ = payload[0] & 0x1F
    if typ in (NAL_IDR, NAL_SPS):
        return True
    if typ == NAL_STAP_A and len(payload) >= 4:
        off = 1
        while off + 2 < len(payload):
            ln = int.from_bytes(payload[off:off + 2], "big")
            if off + 2 < len(payload) and \
                    (payload[off + 2] & 0x1F) in (NAL_IDR, NAL_SPS):
                return True
            off += 2 + ln
    if typ == NAL_FU_A and len(payload) >= 2:
        return bool(payload[1] & 0x80) and \
            (payload[1] & 0x1F) in (NAL_IDR, NAL_SPS)
    return False
